"""Replica fleet: N supervised engines behind one admission layer —
the serve side's fault-tolerance story.

One engine is a single point of failure twice over: a crashed bucket
dispatch fails every coalesced request with it, and a wedged device fetch
silently holds its waiters forever (the serve twin of the collective hang
PR 14 closed on the training side). `FleetService` replicates the engine
N ways behind the EXISTING admission controller and keeps the
`ServeService` surface (`handle(row)`, `shutdown()`, `.metrics`,
`.admission`, `.engine`), so every front door — `cli/serve.py`,
`bench.py --mode serve`, loadgen, the tests — runs unchanged on a fleet.

What each piece does:

* **Routing** — every admitted request goes to the healthy replica with
  the fewest requests in flight, tie-broken by the replica's OWN rolling
  `SLOWindow` p99 (a straggling replica keeps taking SOME traffic — its
  window must keep refreshing to prove recovery — but never the bulk).
* **Supervision** — a loop-side watchdog task ages every replica's
  dispatched-but-unanswered flushes (the batcher's in-flight journal,
  `MicroBatcher.oldest_inflight_age`) exactly like the PR 14 collective
  watchdog ages open journal entries. A flush older than
  `wedge_timeout_s` declares the replica WEDGED: its waiters are released
  with `ReplicaWedged` (loop-side future completion — the wedged reply
  thread's eventual late scatter finds the journal entry gone and
  delivers nothing twice), the reply thread is abandoned (daemon, never
  joined — joining would block on exactly the hang being escaped), and
  the replica restarts off-loop.
* **Failover** — a replica-scoped failure raising out of `submit`
  (engine crash, wedge release) quarantines the replica and RETRIES the
  request on a survivor under `retry_budget` additional attempts: an
  accepted request is only lost when the budget exhausts or no healthy
  replica appears within the bounded wait. Client errors (a malformed
  row's `ValueError`) never count against the replica and never retry.
* **Restart** — a quarantined replica rebuilds its engine (full AOT
  bucket ladder) in the executor, off the event loop, from the fleet's
  CURRENT params generation — so a replica crashing during a hot reload
  comes back already serving the new weights — and rejoins routing.

Hot reload (`serve/reload.py`) drives `apply_reload`: new-generation
engines are staged off-loop FIRST (full ladders compiled, capacity never
dips for a compile), then each replica is swapped behind its own drain —
routing skips it, its outstanding futures resolve on the OLD engine, and
only then does the new engine take the slot, so no request ever spans a
swap. Each swap records the machine-checkable invariant
(`outstanding_at_swap == 0`) into the telemetry trace as a
`reload_event` point; `scripts/check_telemetry.py` validates it.

Every state transition publishes: `serve.fleet.*` registry metrics
(healthy/replicas gauges the `/healthz` endpoint folds into its verdict,
crash/wedge/restart/retry counters the bench artifact stamps),
`fleet_event` telemetry points, and flight-recorder entries for
post-mortems. Runs identically under JAX_PLATFORMS=cpu — the chaos smoke
and tier-1 tests exercise every path without hardware.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, List, Optional

from ..telemetry import flight
from ..telemetry.events import get_tracer
from .admission import AdmissionController, Rejected
from .batcher import MicroBatcher
from .metrics import ServeMetrics, SLOWindow
from .tracing import ServeTracer

# Replica lifecycle: HEALTHY takes traffic; DRAINING is a reload swap in
# progress (router skips it, outstanding work completes on the old
# engine); RESTARTING is quarantined with an off-loop rebuild running;
# DEAD is a restart that itself failed — terminal until shutdown.
HEALTHY, DRAINING, RESTARTING, DEAD = "healthy", "draining", "restarting", "dead"


class ReplicaFailure(RuntimeError):
    """A replica-scoped serve failure: the request was fine, the replica
    was not — the fleet's retry path catches exactly this family (plus
    unclassified engine errors) and never a client error."""


class ReplicaCrashed(ReplicaFailure):
    """The replica's engine raised mid-dispatch (or its waiters were
    released after a sibling request crashed it)."""


class ReplicaWedged(ReplicaFailure):
    """The supervisor aged an in-flight flush past the wedge timeout and
    released its waiters."""


class FleetUnavailable(ReplicaFailure):
    """No healthy replica appeared within the bounded wait — the one way
    an accepted request is lost besides retry-budget exhaustion."""


class Replica:
    """One engine + its private batcher + its own rolling SLO window.

    The per-replica `SLOWindow` is the routing signal: the shared
    `ServeMetrics` aggregates the fleet, but routing needs to know which
    REPLICA is slow. `inflight` counts admitted-to-this-replica,
    unanswered requests — the router's load measure (queue depth alone
    misses dispatched-but-unfetched work)."""

    __slots__ = ("idx", "engine", "batcher", "slo", "state", "inflight",
                 "generation", "restarts")

    def __init__(self, idx: int, engine, batcher):
        self.idx = idx
        self.engine = engine
        self.batcher = batcher
        self.slo = SLOWindow()
        self.state = HEALTHY
        self.inflight = 0
        self.generation = 0
        self.restarts = 0

    def snapshot(self) -> dict:
        return {
            "idx": self.idx,
            "state": self.state,
            "inflight": self.inflight,
            "generation": self.generation,
            "restarts": self.restarts,
            "rolling_p99_ms": round(self.slo.percentile(0.99) * 1e3, 3),
            "window_n": self.slo.n,
        }


class FleetService:
    """N replicated engines behind one admission layer, drop-in for
    `ServeService` (docs/SERVING.md §Replica fleet & hot reload).

    `build_engine(params)` constructs ONE engine (full AOT ladder) from a
    params pytree; the fleet calls it N times at construction, per
    restart, and per reload generation — always in the executor except at
    construction, so the event loop never hosts a compile. `params` is
    the initial generation; `serving_step` labels it (the reload watcher
    advances both).
    """

    def __init__(self, build_engine: Callable, params, *,
                 n_replicas: int = 2, max_batch=None,
                 max_delay_ms: float = 2.0, max_depth: int = 256,
                 retry_after_s: float = 0.05, clock=None, registry=None,
                 admit_mode: str = "depth", slo_p99_s=None, fast=None,
                 wedge_timeout_s: float = 0.25, retry_budget: int = 2,
                 no_replica_wait_s: Optional[float] = None,
                 serving_step: int = -1):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1; got {n_replicas}")
        if retry_budget < 0:
            raise ValueError(f"retry_budget must be >= 0; got {retry_budget}")
        if wedge_timeout_s <= 0:
            raise ValueError(
                f"wedge_timeout_s must be > 0; got {wedge_timeout_s}")
        clock = clock or time.monotonic
        self.clock = clock
        self._build_engine = build_engine
        self._params = params
        self.serving_step = int(serving_step)
        self.wedge_timeout_s = float(wedge_timeout_s)
        self.retry_budget = int(retry_budget)
        # how long an admitted request waits for SOME replica to come
        # back before it is lost: long enough to ride out one restart
        # (ladder recompile), short enough that a dead fleet fails loudly
        self.no_replica_wait_s = (float(no_replica_wait_s)
                                  if no_replica_wait_s is not None
                                  else max(10 * self.wedge_timeout_s, 5.0))
        self._batcher_kw = dict(max_batch=max_batch,
                                max_delay_ms=max_delay_ms, fast=fast)
        self.metrics = ServeMetrics(depth_fn=lambda: self.admission.depth,
                                    clock=clock, registry=registry)
        self.admission = AdmissionController(
            max_depth, retry_after_s=retry_after_s, mode=admit_mode,
            slo_p99_s=slo_p99_s,
            predictor=(self.metrics.predicted_p99
                       if admit_mode == "predicted_p99" else None))
        self.tracer = ServeTracer(clock=clock, metrics=self.metrics)
        self.replicas: List[Replica] = [
            Replica(i, self._make_engine(i, params), None)
            for i in range(n_replicas)]
        for rep in self.replicas:
            rep.batcher = self._new_batcher(rep.engine)
        self._generation = 0
        # -- serve.fleet.* observability --------------------------------
        reg = self.metrics.registry
        self._retried = reg.counter("serve.fleet.retried_requests")
        self._retry_exhausted = reg.counter("serve.fleet.retry_exhausted")
        self._crashes = reg.counter("serve.fleet.crashes")
        self._wedges = reg.counter("serve.fleet.wedges")
        self._restarts = reg.counter("serve.fleet.restarts")
        self._failovers = reg.counter("serve.fleet.failed_over_requests")
        reg.gauge("serve.fleet.replicas").set(n_replicas)
        reg.gauge("serve.fleet.healthy").set_fn(
            lambda: sum(1 for r in self.replicas if r.state == HEALTHY))
        reg.gauge("serve.fleet.generation").set_fn(lambda: self._generation)
        reg.gauge("serve.fleet.serving_step").set_fn(
            lambda: self.serving_step)
        # supervisor/restart task plumbing: the watchdog spawns lazily on
        # the first handled request (it needs the running loop), restart
        # tasks are tracked so shutdown can wait for or cancel them
        self._supervisor: Optional[asyncio.Task] = None
        self._tasks: "set[asyncio.Task]" = set()
        self._healthy_event: Optional[asyncio.Event] = None
        self._closed = False

    # -- construction helpers ---------------------------------------------

    def _make_engine(self, idx: int, params):
        engine = self._build_engine(params)
        try:
            engine.replica = idx   # fault-point + forensics label
        except AttributeError:
            pass                   # duck-typed test engines without slots
        return engine

    def _new_batcher(self, engine) -> MicroBatcher:
        return MicroBatcher(engine, metrics=self.metrics, clock=self.clock,
                            tracer=self.tracer, **self._batcher_kw)

    @staticmethod
    def _close_engine(engine) -> None:
        """Best-effort engine retirement (duck-typed test engines have no
        pool to drain; a dead engine's own teardown failure is noise)."""
        close = getattr(engine, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001 — teardown only
                pass

    # -- routing ------------------------------------------------------------

    def _healthy(self) -> List[Replica]:
        return [r for r in self.replicas if r.state == HEALTHY]

    @property
    def engine(self):
        """A representative engine (loadgen reads `input_dtype`, bench
        warms buckets): the first non-dead replica's — every replica
        serves the same params, so any one speaks for the fleet."""
        for rep in self.replicas:
            if rep.state != DEAD:
                return rep.engine
        return self.replicas[0].engine

    @property
    def batcher(self):
        """Compat shim for front doors that read `service.batcher`
        attributes (fast_path, flush counters): the first replica's."""
        return self.replicas[0].batcher

    def _pick_now(self) -> Optional[Replica]:
        healthy = self._healthy()
        if not healthy:
            return None
        return min(healthy, key=lambda r: (r.inflight,
                                           r.slo.percentile(0.99), r.idx))

    async def _pick(self) -> Replica:
        """The healthy replica with the least load, waiting (bounded) for
        one to appear when the whole fleet is quarantined — a restart in
        progress should cost latency, not accepted requests."""
        rep = self._pick_now()
        if rep is not None:
            return rep
        deadline = time.monotonic() + self.no_replica_wait_s
        while True:
            if self._closed:
                raise FleetUnavailable("fleet is shutting down")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise FleetUnavailable(
                    f"no healthy replica within {self.no_replica_wait_s:.1f}s "
                    f"(states: {[r.state for r in self.replicas]})")
            self._healthy_event = self._healthy_event or asyncio.Event()
            self._healthy_event.clear()
            try:
                await asyncio.wait_for(self._healthy_event.wait(),
                                       timeout=min(remaining,
                                                   self.wedge_timeout_s))
            except asyncio.TimeoutError:
                pass
            rep = self._pick_now()
            if rep is not None:
                return rep

    def _wake_routers(self) -> None:
        if self._healthy_event is not None:
            self._healthy_event.set()

    # -- the request path ---------------------------------------------------

    async def handle(self, row) -> int:
        """Serve one request row -> predicted class: admit once, then
        route/submit with replica failover under the retry budget.
        Raises `Rejected` under backpressure/drain; client errors
        propagate unretried; a replica failure surfaces only after the
        budget exhausts."""
        self._ensure_supervisor()
        rctx = self.tracer.begin()
        self.metrics.record_arrival()
        try:
            self.admission.admit()
        except Rejected:
            self.metrics.record_reject()
            raise
        self.tracer.admitted(rctx)
        t0 = self.clock()
        try:
            pred = await self._submit_with_failover(row, rctx)
        except Exception:
            self.metrics.record_failure()
            self.tracer.finish(rctx, ok=False)
            self.admission.release()
            raise
        self.admission.release()
        self.metrics.record_done(self.clock() - t0)
        self.tracer.finish(rctx, ok=True)
        return pred

    async def _submit_with_failover(self, row, rctx) -> int:
        attempts = 0
        t0 = self.clock()
        while True:
            rep = await self._pick()
            rep.inflight += 1
            try:
                pred = await rep.batcher.submit(row, rctx)
            except (ValueError, TypeError):
                raise         # client error: not the replica's fault
            except Rejected:
                raise
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # replica-scoped: quarantine (idempotent — fail_all
                # storms arrive one exception per waiter) and retry on a
                # survivor under the budget
                self._quarantine(
                    rep, kind=("wedge" if isinstance(e, ReplicaWedged)
                               else "crash"),
                    cause=e)
                if attempts >= self.retry_budget:
                    self._retry_exhausted.inc()
                    get_tracer().point(
                        "fleet_event", event="retry_exhausted",
                        replica=rep.idx, request=rctx.request_id,
                        attempts=attempts + 1)
                    raise
                attempts += 1
                self._retried.inc()
                get_tracer().point("fleet_event", event="retry",
                                   replica=rep.idx,
                                   request=rctx.request_id,
                                   attempt=attempts,
                                   error=str(e)[:200])
                continue
            finally:
                rep.inflight -= 1
            done = self.clock()
            rep.slo.record(done - t0, done)
            return pred

    # -- supervision --------------------------------------------------------

    def _ensure_supervisor(self) -> None:
        if (self._supervisor is None or self._supervisor.done()) \
                and not self._closed:
            loop = asyncio.get_running_loop()
            self._healthy_event = self._healthy_event or asyncio.Event()
            self._supervisor = loop.create_task(self._supervise())

    async def _supervise(self) -> None:
        """The batch watchdog (the PR 14 collective-watchdog pattern on
        the serve side): periodically age every healthy replica's oldest
        in-flight flush; past the wedge timeout, declare the replica
        wedged and fail it over. Loop-side by construction — future
        completion and journal reads stay on the loop."""
        interval = max(self.wedge_timeout_s / 4.0, 0.01)
        while not self._closed:
            await asyncio.sleep(interval)
            now = self.clock()
            for rep in self.replicas:
                if rep.state != HEALTHY:
                    continue
                age = rep.batcher.oldest_inflight_age(now)
                if age > self.wedge_timeout_s:
                    self._quarantine(rep, kind="wedge", cause=RuntimeError(
                        f"oldest in-flight batch aged {age * 1e3:.0f} ms "
                        f"> wedge timeout "
                        f"{self.wedge_timeout_s * 1e3:.0f} ms"))

    def _quarantine(self, rep: Replica, *, kind: str,
                    cause: BaseException) -> None:
        """Loop-side replica takedown, idempotent: flip the state so the
        router skips it, release every waiter it still owes (they retry
        via `handle`'s failover loop), abandon its reply thread, and
        schedule the off-loop restart."""
        if rep.state != HEALTHY or self._closed:
            return
        rep.state = RESTARTING
        (self._wedges if kind == "wedge" else self._crashes).inc()
        detail = f"{type(cause).__name__}: {cause}"[:300]
        flight.record("fleet_event", event="quarantine", replica=rep.idx,
                      cause=kind, error=detail)
        get_tracer().point("fleet_event", event="quarantine",
                           replica=rep.idx, cause=kind, error=detail)
        exc_cls = ReplicaWedged if kind == "wedge" else ReplicaCrashed
        released = rep.batcher.fail_all(exc_cls(
            f"replica {rep.idx} {kind}: {detail}"))
        if released:
            self._failovers.inc(released)
        # never join: on a wedge the reply thread is blocked inside the
        # very fetch being escaped (daemon — it cannot hold the process)
        rep.batcher.close(wait=False)
        loop = asyncio.get_running_loop()
        task = loop.create_task(self._restart(rep))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _restart(self, rep: Replica) -> None:
        """Rebuild a quarantined replica's engine off-loop and rejoin it
        to routing on the fleet's CURRENT generation (re-staged if a
        reload lands mid-rebuild — a restarted replica must never serve
        stale weights next to new-generation siblings)."""
        loop = asyncio.get_running_loop()
        t0 = time.monotonic()
        old_engine = rep.engine
        while True:
            gen, params = self._generation, self._params
            try:
                engine = await loop.run_in_executor(
                    None, self._make_engine, rep.idx, params)
            except Exception as e:  # noqa: BLE001 — a failed rebuild is
                # terminal for the replica, never for the fleet
                rep.state = DEAD
                detail = f"{type(e).__name__}: {e}"[:300]
                flight.record("fleet_event", event="dead", replica=rep.idx,
                              error=detail)
                get_tracer().point("fleet_event", event="dead",
                                   replica=rep.idx, error=detail)
                return
            if gen == self._generation:
                break
            self._close_engine(engine)  # reload landed mid-rebuild: re-stage
        # retire the old engine off-loop too: block_until_ready on its
        # abandoned in-flight work must not stall request routing
        await loop.run_in_executor(None, self._close_engine, old_engine)
        rep.engine = engine
        rep.batcher = self._new_batcher(engine)
        rep.generation = gen
        rep.restarts += 1
        rep.state = HEALTHY
        self._restarts.inc()
        dur = time.monotonic() - t0
        flight.record("fleet_event", event="restart", replica=rep.idx,
                      generation=gen, dur_s=round(dur, 4))
        get_tracer().point("fleet_event", event="restart", replica=rep.idx,
                           generation=gen, dur_s=round(dur, 4))
        self._wake_routers()

    # -- hot reload (driven by serve/reload.py) -----------------------------

    async def apply_reload(self, params, step: int) -> int:
        """Swap every replica to `params` with zero downtime: stage ALL
        new-generation engines off-loop first (capacity never dips for a
        compile), then swap replica-by-replica behind a drain — routing
        skips the draining replica, its outstanding futures resolve on
        the OLD engine, and only then does the new engine take the slot.
        No request spans a swap; each swap's `reload_event` point records
        `outstanding_at_swap` (always 0 — the machine-checkable
        invariant). Returns the number of replicas swapped; replicas
        mid-restart rejoin on the new generation via `_restart`'s
        re-stage loop."""
        loop = asyncio.get_running_loop()
        self._generation += 1
        gen = self._generation
        self._params = params
        self.serving_step = int(step)
        staged = {}
        for rep in self.replicas:
            if rep.state in (HEALTHY, DRAINING):
                staged[rep.idx] = await loop.run_in_executor(
                    None, self._make_engine, rep.idx, params)
        swapped = 0
        for rep in self.replicas:
            engine = staged.get(rep.idx)
            if engine is None:
                continue
            if rep.state != HEALTHY or self._closed:
                self._close_engine(engine)  # quarantined mid-reload:
                continue         # _restart re-stages the new generation
            rep.state = DRAINING
            await rep.batcher.drain()
            outstanding = len(rep.batcher._outstanding)
            rep.batcher.close()          # drained: the join is instant
            old = rep.engine
            rep.engine = engine
            rep.batcher = self._new_batcher(engine)
            rep.generation = gen
            rep.state = HEALTHY
            self._wake_routers()
            swapped += 1
            get_tracer().point("reload_event", event="swapped",
                               replica=rep.idx, step=int(step),
                               generation=gen,
                               outstanding_at_swap=outstanding)
            flight.record("reload_event", event="swapped", replica=rep.idx,
                          step=int(step), outstanding_at_swap=outstanding)
            await loop.run_in_executor(None, self._close_engine, old)
        return swapped

    # -- observability ------------------------------------------------------

    def fleet_snapshot(self) -> dict:
        """The live fleet view the `{"op": "health"}` front door and the
        bench artifact share: per-replica state + the failure/retry
        counters, one JSON-able dict."""
        healthy = len(self._healthy())
        return {
            "replicas": len(self.replicas),
            "healthy": healthy,
            "degraded": healthy < len(self.replicas),
            "generation": self._generation,
            "serving_step": self.serving_step,
            "retried_requests": self._retried.value,
            "retry_exhausted": self._retry_exhausted.value,
            "failed_over_requests": self._failovers.value,
            "crashes": self._crashes.value,
            "wedges": self._wedges.value,
            "restarts": self._restarts.value,
            "per_replica": [r.snapshot() for r in self.replicas],
        }

    # -- teardown -----------------------------------------------------------

    async def shutdown(self) -> None:
        """Graceful fleet drain: refuse new work, let every healthy
        replica serve what it accepted, settle restart tasks, then close
        every batcher/engine. Mirrors `ServeService.shutdown` so
        `run_until_drained` works unchanged."""
        self._closed = True
        self.admission.begin_drain()
        for rep in self.replicas:
            if rep.state in (HEALTHY, DRAINING):
                await rep.batcher.drain()
        await self.admission.drained()
        if self._supervisor is not None:
            self._supervisor.cancel()
            try:
                await self._supervisor
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        for task in list(self._tasks):
            task.cancel()
        if self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)
        for rep in self.replicas:
            rep.batcher.close(wait=rep.state in (HEALTHY, DRAINING))
            self._close_engine(rep.engine)
        self.tracer.flush_exemplars()
