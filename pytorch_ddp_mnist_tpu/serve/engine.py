"""Inference engine: a training checkpoint turned into a warm, bucketed
forward pass that can never cold-compile mid-request.

Serving on TPU is won or lost at the batching/compile-cache layer, not the
model (PAPERS.md: the Gemma-on-TPU serving comparison): a request that
arrives with a batch shape XLA has not seen pays a full compile — seconds of
p99 latency on a path whose steady state is microseconds. The engine
therefore AOT-compiles a fixed bucket ladder of batch shapes (powers of two
up to `max_batch`) at startup via `jax.jit(...).lower(...).compile()` and
serves every request from those executables. A compiled executable rejects
any other shape by construction, so "no cold compile after warmup" is a
structural guarantee, not a convention — `compile_count` instruments it for
tests.

Data-parallel replication is the same mesh story as training: pass a
`parallel.mesh` Mesh and params replicate over it while each bucket's rows
shard across `DATA_AXIS` (buckets are then multiples of the device count, so
every replica always gets equal full rows). Single-device serving (the
default, and the CPU/simulator path tier-1 exercises) skips the mesh
entirely.

Inputs are float32 rows already normalized by the client, or raw uint8
pixels normalized on device with the training path's exact op chain
(`train.scan.device_normalize`) — chosen once at construction
(`input_dtype`), because each choice is its own compiled program.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..models.mlp import MLP_DIMS, init_mlp, mlp_apply
from ..parallel.mesh import DATA_AXIS
from ..train.checkpoint import load_checkpoint
from ..train.scan import device_normalize

IN_DIM = MLP_DIMS[0]


def bucket_ladder(max_batch: int, multiple_of: int = 1) -> "tuple[int, ...]":
    """Ascending power-of-two batch buckets up to `max_batch`, each a
    multiple of `multiple_of` (the mesh device count — every replica must
    receive equal full rows). `max_batch` itself is always the top rung so
    the ladder covers the batcher's largest flush even when the cap is not
    a power of two."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1; got {max_batch}")
    if max_batch % multiple_of != 0:
        raise ValueError(
            f"max_batch {max_batch} must be a multiple of the mesh device "
            f"count {multiple_of} (each bucket shards equal rows per "
            f"replica)")
    ladder = []
    b = 1
    while b < max_batch:
        if b % multiple_of == 0:
            ladder.append(b)
        b *= 2
    ladder.append(max_batch)
    return tuple(ladder)


class InferenceEngine:
    """Warm bucketed forward pass over a params pytree.

    `predict(x)` / `forward(x)` pad the batch to the smallest bucket that
    holds it and run the bucket's AOT-compiled executable; results come back
    trimmed to the real rows. Two requests for the same rows are bitwise
    identical whether they arrive alone or coalesced into a larger flush of
    the SAME bucket — and the batcher pads exactly like `_run_bucket`, so
    the served path reproduces a direct `forward` call bit-for-bit.
    """

    def __init__(self, params, *, max_batch: int = 128, mesh=None,
                 input_dtype: str = "float32", donate: Optional[bool] = None,
                 buckets: Optional[Sequence[int]] = None):
        if input_dtype not in ("float32", "uint8"):
            raise ValueError(f"input_dtype must be 'float32' or 'uint8'; "
                             f"got {input_dtype!r}")
        self.max_batch = int(max_batch)
        self.input_dtype = input_dtype
        self._np_dtype = (np.uint8 if input_dtype == "uint8"
                          else np.float32)
        self.mesh = mesh
        n_dev = 1 if mesh is None else int(mesh.devices.size)
        self.buckets = (tuple(sorted(set(int(b) for b in buckets)))
                        if buckets is not None
                        else bucket_ladder(self.max_batch, n_dev))
        for b in self.buckets:
            if b < 1 or b % n_dev != 0:
                raise ValueError(f"bucket {b} must be a positive multiple "
                                 f"of the {n_dev}-device mesh")
        if mesh is None:
            self._x_sharding = None
            self._params = jax.device_put(params)
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P
            self._x_sharding = NamedSharding(mesh, P(DATA_AXIS))
            self._params = jax.device_put(params, NamedSharding(mesh, P()))
        # Donating the padded input buffer saves one HBM alloc per request
        # batch on accelerators; CPU has no donation support and would warn
        # per call, so default it off there.
        if donate is None:
            donate = jax.default_backend() not in ("cpu",)
        self._donate = bool(donate)
        # bucket -> AOT executable; populated ONLY here at warmup. Serving
        # looks executables up and never falls back to jit, so a missing
        # shape is a loud KeyError, not a silent multi-second compile.
        # Each rung compiles under its forensics label, so the
        # jax.monitoring listener attributes the warmup's compile time to
        # `serve.bucket<N>` (telemetry/costs.py compile_attribution).
        from ..telemetry.runtime import label_compiles
        self._compiled = {}
        self.compile_count = 0
        for b in self.buckets:
            with label_compiles(f"serve.bucket{b}"):
                self._compiled[b] = self._compile(b)
            self.compile_count += 1
        # Register the ladder's memory story in the program table the OOM
        # forensics dump names (peak/arg/temp bytes per bucket). Reading
        # the analyses off already-compiled executables is warmup-cheap;
        # any failure (older jaxlib without memory_analysis, a backend
        # that refuses the query) must never break serving.
        try:
            from ..telemetry.costs import harvest_engine
            harvest_engine(self)
        except (AttributeError, RuntimeError, ValueError, TypeError,
                NotImplementedError, OSError):
            pass  # forensics are advisory; the engine serves without them

    @classmethod
    def from_checkpoint(cls, path: str, **kw) -> "InferenceEngine":
        """Load params via the training checkpoint layer (msgpack or the
        reference's torch `.pt` — both formats serve identically)."""
        template = init_mlp(jax.random.key(0))
        return cls(load_checkpoint(path, template), **kw)

    # -- compilation ------------------------------------------------------

    def _fn(self, params, x):
        if x.dtype == jnp.uint8:
            x = device_normalize(x)
        logits = mlp_apply(params, x.astype(jnp.float32), train=False)
        return logits, jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _compile(self, bucket: int):
        dt = jnp.uint8 if self.input_dtype == "uint8" else jnp.float32
        x_spec = jax.ShapeDtypeStruct((bucket, IN_DIM), dt,
                                      sharding=self._x_sharding)
        jitted = (jax.jit(self._fn, donate_argnums=(1,)) if self._donate
                  else jax.jit(self._fn))
        return jitted.lower(self._params, x_spec).compile()

    # -- serving ----------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        """Smallest precompiled bucket holding `n` rows."""
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(f"batch of {n} rows exceeds the largest bucket "
                         f"{self.buckets[-1]} (max_batch {self.max_batch})")

    def _run_bucket(self, x: np.ndarray, bctx=None):
        """Pad `x` to its bucket and run the compiled executable. Returns
        (logits, preds) for the REAL rows only. `bctx` (a
        `serve.tracing.BatchCtx`) receives the pad/H2D and compute stage
        stamps — plain clock reads, no extra device sync: the `np.asarray`
        fetch below already blocks on the executable, so the compute stamp
        lands when the results are truly on the host."""
        n = x.shape[0]
        bucket = self.bucket_for(n)
        if n != bucket:
            pad = np.zeros((bucket - n, IN_DIM), x.dtype)
            x = np.concatenate([x, pad], axis=0)
        xd = (jax.device_put(x, self._x_sharding)
              if self._x_sharding is not None else jnp.asarray(x))
        if bctx is not None:
            bctx.mark_h2d(bucket)
        try:
            logits, preds = self._compiled[bucket](self._params, xd)
            out = np.asarray(logits)[:n], np.asarray(preds)[:n], bucket
        except RuntimeError as e:
            # an allocation failure dies naming the program and the HBM
            # budget it blew (telemetry/costs.py; no-op for non-OOM
            # errors) — the exception itself propagates unchanged
            from ..telemetry.costs import record_oom_forensics
            record_oom_forensics(e, program=f"serve.bucket{bucket}")
            raise
        if bctx is not None:
            bctx.mark_computed()
        return out

    def compiled_programs(self) -> dict:
        """bucket -> the AOT-compiled executable: the forensics surface
        (`telemetry.costs.harvest_engine` reads cost/memory analyses off
        these; a copy, so callers cannot un-warm the ladder)."""
        return dict(self._compiled)

    def _as_rows(self, x) -> np.ndarray:
        x = np.asarray(x, self._np_dtype)
        if x.ndim == 1:
            x = x[None, :]
        if x.ndim != 2 or x.shape[1] != IN_DIM:
            raise ValueError(f"expected (n, {IN_DIM}) rows; got {x.shape}")
        return np.ascontiguousarray(x)

    def forward(self, x) -> np.ndarray:
        """Logits (n, 10) float32 for `x` (n, 784); chunks batches larger
        than max_batch so direct callers never hit the bucket cap."""
        x = self._as_rows(x)
        outs = [self._run_bucket(x[i:i + self.max_batch])[0]
                for i in range(0, len(x), self.max_batch)]
        return np.concatenate(outs, axis=0)

    def predict(self, x) -> np.ndarray:
        """Argmax classes (n,) int32 for `x` (n, 784)."""
        x = self._as_rows(x)
        outs = [self._run_bucket(x[i:i + self.max_batch])[1]
                for i in range(0, len(x), self.max_batch)]
        return np.concatenate(outs, axis=0)
