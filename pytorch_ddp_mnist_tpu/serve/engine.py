"""Inference engine: a training checkpoint turned into a warm, bucketed
forward pass that can never cold-compile mid-request.

Serving on TPU is won or lost at the batching/compile-cache layer, not the
model (PAPERS.md: the Gemma-on-TPU serving comparison): a request that
arrives with a batch shape XLA has not seen pays a full compile — seconds of
p99 latency on a path whose steady state is microseconds. The engine
therefore AOT-compiles a fixed bucket ladder of batch shapes (powers of two
up to `max_batch`) at startup via `jax.jit(...).lower(...).compile()` and
serves every request from those executables. A compiled executable rejects
any other shape by construction, so "no cold compile after warmup" is a
structural guarantee, not a convention — `compile_count` instruments it for
tests.

Data-parallel replication is the same mesh story as training: pass a
`parallel.mesh` Mesh and params replicate over it while each bucket's rows
shard across `DATA_AXIS` (buckets are then multiples of the device count, so
every replica always gets equal full rows). Single-device serving (the
default, and the CPU/simulator path tier-1 exercises) skips the mesh
entirely.

Inputs are float32 rows already normalized by the client, or raw uint8
pixels normalized on device with the training path's exact op chain
(`train.scan.device_normalize`) — chosen once at construction
(`input_dtype`), because each choice is its own compiled program.

The serve fast path (docs/SERVING.md §Fast path) adds persistent host
staging: the engine owns a small pool of top-rung-shaped slabs, every
ladder rung's staging array is a leading-rows view of one, and the
micro-batcher writes request rows straight into the active slab at
enqueue time. `dispatch_staged` then pays only the pad-tail memset and
the H2D dispatch per flush — no stack, no concatenate, no fresh host
allocation — and swaps slabs so the next flush accumulates while this
one is in flight (double-buffered H2D). On accelerators the input
donation (`donate_argnums`) closes the device half of the story: each
flush's H2D allocation is donated into the executable, so the same
per-rung HBM size class round-trips through the allocator instead of
growing the footprint per flush.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..models.mlp import MLP_DIMS, init_mlp, mlp_apply
from ..parallel.mesh import DATA_AXIS
from ..train.checkpoint import load_checkpoint
from ..train.scan import device_normalize
from ..utils import faultpoints

IN_DIM = MLP_DIMS[0]

# Host staging slabs the engine keeps warm for the serve fast path: two is
# the double buffer (flush N+1 accumulates and dispatches its H2D while
# flush N's compute is still in flight); the pool grows past it only when
# replies lag more than one flush behind, and growth is counted
# (`staging_grown`), never silent.
STAGING_SLOTS = 2


class InflightBatch:
    """One dispatched bucket call whose results have not been fetched yet:
    the device output arrays (futures under JAX async dispatch), the real
    row count to trim back to, and — for staged dispatches — the host slab
    the input rows rode in on, returned to the engine's staging pool at
    fetch/teardown time."""

    __slots__ = ("logits_d", "preds_d", "n", "bucket", "slab",
                 "wedged_until")

    def __init__(self, logits_d, preds_d, n: int, bucket: int, slab=None):
        self.logits_d = logits_d
        self.preds_d = preds_d
        self.n = n
        self.bucket = bucket
        self.slab = slab
        # injected-wedge deadline (utils/faultpoints `engine_wedge`):
        # until this monotonic instant the batch reports not-ready and
        # its fetch blocks — a device that stopped answering, in handle
        # form. 0.0 (never) outside chaos runs.
        self.wedged_until = 0.0

    def ready(self) -> bool:
        """Non-blocking: True when both outputs are on-device complete,
        so a fetch would return without waiting. The batcher uses this
        for its opportunistic inline reply (fetch on the loop ONLY when
        it cannot block it)."""
        if self.wedged_until and time.monotonic() < self.wedged_until:
            return False
        try:
            return bool(self.logits_d.is_ready()
                        and self.preds_d.is_ready())
        except AttributeError:   # a jax without is_ready: never inline
            return False

    @property
    def inline_ok(self) -> bool:
        """False while an injected wedge holds this batch: the reply
        router must never take a wedged fetch inline — blocking the loop
        would blind the very watchdog the wedge exists to test."""
        return not (self.wedged_until
                    and time.monotonic() < self.wedged_until)


def bucket_ladder(max_batch: int, multiple_of: int = 1) -> "tuple[int, ...]":
    """Ascending power-of-two batch buckets up to `max_batch`, each a
    multiple of `multiple_of` (the mesh device count — every replica must
    receive equal full rows). `max_batch` itself is always the top rung so
    the ladder covers the batcher's largest flush even when the cap is not
    a power of two."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1; got {max_batch}")
    if max_batch % multiple_of != 0:
        raise ValueError(
            f"max_batch {max_batch} must be a multiple of the mesh device "
            f"count {multiple_of} (each bucket shards equal rows per "
            f"replica)")
    ladder = []
    b = 1
    while b < max_batch:
        if b % multiple_of == 0:
            ladder.append(b)
        b *= 2
    ladder.append(max_batch)
    return tuple(ladder)


class InferenceEngine:
    """Warm bucketed forward pass over a params pytree.

    `predict(x)` / `forward(x)` pad the batch to the smallest bucket that
    holds it and run the bucket's AOT-compiled executable; results come back
    trimmed to the real rows. Two requests for the same rows are bitwise
    identical whether they arrive alone or coalesced into a larger flush of
    the SAME bucket — and the batcher pads exactly like `_run_bucket`, so
    the served path reproduces a direct `forward` call bit-for-bit.
    """

    def __init__(self, params, *, max_batch: int = 128, mesh=None,
                 input_dtype: str = "float32", donate: Optional[bool] = None,
                 buckets: Optional[Sequence[int]] = None):
        if input_dtype not in ("float32", "uint8"):
            raise ValueError(f"input_dtype must be 'float32' or 'uint8'; "
                             f"got {input_dtype!r}")
        self.max_batch = int(max_batch)
        self.input_dtype = input_dtype
        self._np_dtype = (np.uint8 if input_dtype == "uint8"
                          else np.float32)
        self.mesh = mesh
        n_dev = 1 if mesh is None else int(mesh.devices.size)
        self.buckets = (tuple(sorted(set(int(b) for b in buckets)))
                        if buckets is not None
                        else bucket_ladder(self.max_batch, n_dev))
        for b in self.buckets:
            if b < 1 or b % n_dev != 0:
                raise ValueError(f"bucket {b} must be a positive multiple "
                                 f"of the {n_dev}-device mesh")
        if mesh is None:
            self._x_sharding = None
            self._params = jax.device_put(params)
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P
            self._x_sharding = NamedSharding(mesh, P(DATA_AXIS))
            self._params = jax.device_put(params, NamedSharding(mesh, P()))
        # Donating the padded input buffer saves one HBM alloc per request
        # batch on accelerators; CPU has no donation support and would warn
        # per call, so default it off there.
        if donate is None:
            donate = jax.default_backend() not in ("cpu",)
        self._donate = bool(donate)
        # bucket -> AOT executable; populated ONLY here at warmup. Serving
        # looks executables up and never falls back to jit, so a missing
        # shape is a loud KeyError, not a silent multi-second compile.
        # Each rung compiles under its forensics label, so the
        # jax.monitoring listener attributes the warmup's compile time to
        # `serve.bucket<N>` (telemetry/costs.py compile_attribution).
        from ..telemetry.runtime import label_compiles
        self._compiled = {}
        self.compile_count = 0
        for b in self.buckets:
            with label_compiles(f"serve.bucket{b}"):
                self._compiled[b] = self._compile(b)
            self.compile_count += 1
        # Register the ladder's memory story in the program table the OOM
        # forensics dump names (peak/arg/temp bytes per bucket). Reading
        # the analyses off already-compiled executables is warmup-cheap;
        # any failure (older jaxlib without memory_analysis, a backend
        # that refuses the query) must never break serving.
        try:
            from ..telemetry.costs import harvest_engine
            harvest_engine(self)
        except (AttributeError, RuntimeError, ValueError, TypeError,
                NotImplementedError, OSError):
            pass  # forensics are advisory; the engine serves without them
        # -- serve fast path: persistent staging + in-flight tracking -----
        # Host slabs of the top-rung shape, allocated ONCE here; each
        # rung's staging array is a leading-rows view of a slab, so one
        # allocation serves the whole ladder and the batcher writes
        # request rows straight into the active slab at enqueue time
        # (zero-copy batch forming — no np.stack/np.concatenate per
        # flush). A slab cycles active -> dispatched (H2D may read it
        # until the flush's compute completes; on CPU jax.device_put can
        # alias host memory outright) -> back to the pool at fetch. The
        # lock guards the pool handoff between the event loop
        # (dispatch_staged) and the reply thread (fetch_staged/close).
        self._staging_lock = threading.Lock()
        self._staging_pool = [self._new_slab()
                              for _ in range(STAGING_SLOTS - 1)]
        self._active_slab = self._new_slab()
        self._inflight: dict = {}
        self.staging_grown = 0
        # whoever is currently FILLING the active slab (a MicroBatcher
        # passes itself): two concurrent writers would silently corrupt
        # each other's batches, so the second one fails loudly instead
        self._staging_writer = None
        # -- fleet plumbing: which replica slot this engine fills (None
        # outside a fleet) and a per-call ordinal, so the serve fault
        # points (`engine_crash:after=N:replica=R`, `engine_wedge`) can
        # target one engine at a deterministic point in a burst
        self.replica: Optional[int] = None
        self._serve_calls = 0

    @classmethod
    def from_checkpoint(cls, path: str, **kw) -> "InferenceEngine":
        """Load params via the training checkpoint layer (msgpack or the
        reference's torch `.pt` — both formats serve identically)."""
        template = init_mlp(jax.random.key(0))
        return cls(load_checkpoint(path, template), **kw)

    # -- compilation ------------------------------------------------------

    def _fn(self, params, x):
        if x.dtype == jnp.uint8:
            x = device_normalize(x)
        logits = mlp_apply(params, x.astype(jnp.float32), train=False)
        return logits, jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _compile(self, bucket: int):
        dt = jnp.uint8 if self.input_dtype == "uint8" else jnp.float32
        x_spec = jax.ShapeDtypeStruct((bucket, IN_DIM), dt,
                                      sharding=self._x_sharding)
        jitted = (jax.jit(self._fn, donate_argnums=(1,)) if self._donate
                  else jax.jit(self._fn))
        return jitted.lower(self._params, x_spec).compile()

    # -- serving ----------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        """Smallest precompiled bucket holding `n` rows — a `bisect` over
        the precomputed ascending ladder, because this runs once per
        request on the serve hot path (the linear scan it replaces was
        O(rungs) per offered request)."""
        i = bisect_left(self.buckets, n)
        if i == len(self.buckets):
            raise ValueError(f"batch of {n} rows exceeds the largest "
                             f"bucket {self.buckets[-1]} "
                             f"(max_batch {self.max_batch})")
        return self.buckets[i]

    def _oom_forensics(self, e: BaseException, bucket: int) -> None:
        """An allocation failure dies naming the program and the HBM
        budget it blew (telemetry/costs.py; no-op for non-OOM errors) —
        the exception itself propagates unchanged. Under JAX async
        dispatch the failure can surface at the DISPATCH or at the
        FETCH, so both sites report through here."""
        from ..telemetry.costs import record_oom_forensics
        record_oom_forensics(e, program=f"serve.bucket{bucket}")

    def _fault_ctx(self) -> dict:
        ctx = {"after": self._serve_calls}
        if self.replica is not None:
            ctx["replica"] = self.replica
        return ctx

    def _execute(self, bucket: int, xd):
        """Dispatch the bucket's AOT executable (async under JAX dispatch;
        the returned arrays are futures until fetched). The `serve_engine`
        fault point fires per call with the engine's call ordinal and
        fleet replica index, so `engine_crash:after=N:replica=R` kills
        exactly one replica at a deterministic point in a burst."""
        self._serve_calls += 1
        faultpoints.fire("serve_engine", **self._fault_ctx())
        try:
            return self._compiled[bucket](self._params, xd)
        except RuntimeError as e:
            self._oom_forensics(e, bucket)
            raise

    def _dispatch(self, x: np.ndarray, bctx=None) -> InflightBatch:
        """Pad `x` to its bucket and DISPATCH the compiled executable
        without fetching: the returned handle's arrays resolve under
        JAX's async dispatch while the caller issues more work (the
        multi-chunk `forward`/`predict` overlap, and the legacy
        engine-wrapper path's first half)."""
        n = x.shape[0]
        bucket = self.bucket_for(n)
        if n != bucket:
            pad = np.zeros((bucket - n, IN_DIM), x.dtype)
            x = np.concatenate([x, pad], axis=0)
        xd = (jax.device_put(x, self._x_sharding)
              if self._x_sharding is not None else jnp.asarray(x))
        if bctx is not None:
            bctx.mark_h2d(bucket)
        logits, preds = self._execute(bucket, xd)
        return InflightBatch(logits, preds, n, bucket)

    def _run_bucket(self, x: np.ndarray, bctx=None):
        """Pad `x` to its bucket, run the compiled executable, and FETCH.
        Returns (logits, preds, bucket) for the REAL rows only. `bctx` (a
        `serve.tracing.BatchCtx`) receives the pad/H2D and compute stage
        stamps — plain clock reads, no extra device sync: the `np.asarray`
        fetch below already blocks on the executable, so the compute stamp
        lands when the results are truly on the host."""
        h = self._dispatch(x, bctx)
        try:
            out = (np.asarray(h.logits_d)[:h.n],
                   np.asarray(h.preds_d)[:h.n], h.bucket)
        except RuntimeError as e:   # async-dispatch failures surface at
            self._oom_forensics(e, h.bucket)    # the fetch, not the call
            raise
        if bctx is not None:
            bctx.mark_computed()
        return out

    # -- the serve fast path: persistent staging ---------------------------

    def _new_slab(self) -> np.ndarray:
        return np.zeros((self.max_batch, IN_DIM), self._np_dtype)

    def staging(self, owner=None) -> np.ndarray:
        """The host slab the NEXT staged flush dispatches from. The
        batcher writes request row i into `staging()[i]` at enqueue time;
        every ladder rung's staging array is a leading-rows view of this
        one persistent allocation.

        `owner` (the batcher, when writing rows) claims the active slab
        until the next `dispatch_staged`: the slab is engine-global
        state, so a SECOND concurrent filler would silently overwrite
        the first's rows and serve wrong predictions — that misuse
        raises here instead. Sequential services over one shared engine
        (each drains before the next serves) stay fine: every dispatch
        releases the claim."""
        if owner is not None:
            if self._staging_writer is None:
                self._staging_writer = owner
            elif self._staging_writer is not owner:
                raise RuntimeError(
                    "engine staging slab is already being filled by "
                    "another batcher — one engine serves ONE batcher at "
                    "a time (the fast path's staging is engine-global "
                    "state)")
        return self._active_slab

    def dispatch_staged(self, n: int, bctx=None) -> InflightBatch:
        """Dispatch rows 0..n of the active staging slab: zero the pad
        tail (padding stays inert whatever the slab carried last flush),
        issue the H2D + the bucket executable WITHOUT fetching, and swap
        the active slab so the caller accumulates the next flush while
        this one is in flight (the double buffer). Returns the in-flight
        handle; `fetch_staged` (any thread) completes it."""
        if not 1 <= n <= self.max_batch:
            raise ValueError(f"staged flush of {n} rows outside "
                             f"[1, {self.max_batch}]")
        bucket = self.bucket_for(n)
        self._staging_writer = None    # the claim ends with the flush
        slab = self._active_slab
        if n != bucket:
            slab[n:bucket] = 0
        xd = (jax.device_put(slab[:bucket], self._x_sharding)
              if self._x_sharding is not None
              else jnp.asarray(slab[:bucket]))
        if bctx is not None:
            bctx.mark_h2d(bucket)
        logits, preds = self._execute(bucket, xd)
        handle = InflightBatch(logits, preds, n, bucket, slab)
        # injected wedge (`engine_wedge:delay_s=S:replica=R`): the batch
        # reports not-ready and its fetch blocks until the deadline — the
        # reply thread hangs off-loop exactly as on a dead device, and
        # the fleet watchdog's in-flight aging is what must notice
        spec = faultpoints.claim("serve_wedge", **self._fault_ctx())
        if spec is not None:
            handle.wedged_until = time.monotonic() + spec.delay_s
        with self._staging_lock:
            self._inflight[id(handle)] = handle
            if self._staging_pool:
                self._active_slab = self._staging_pool.pop()
            else:
                # replies are lagging more than a full flush behind: grow
                # the pool rather than overwrite a slab the device may
                # still be reading — counted, so the steady-state
                # zero-allocation pin can see any growth
                self._active_slab = self._new_slab()
                self.staging_grown += 1
        return handle

    def fetch_staged(self, handle: InflightBatch):
        """Block until `handle`'s results are on the host (exactly two
        device->host fetches: logits + preds — the sanitizer-pinned
        per-flush budget) and return them trimmed to the real rows. The
        slab rides back into the staging pool EVEN when the fetch raises
        (a failed flush's device work is over either way — leaking the
        slab per failure would bleed the pool on a long-running server);
        an allocation failure surfacing here still gets its OOM
        forensics entry. A wedged handle (injected `engine_wedge`)
        blocks HERE until its deadline — this runs on the reply thread
        (the router never inlines a wedged batch), hanging exactly as it
        would on a device that never answers; the fleet watchdog's
        in-flight aging is what notices."""
        if handle.wedged_until:
            time.sleep(max(0.0, handle.wedged_until - time.monotonic()))
        try:
            logits = np.asarray(handle.logits_d)[:handle.n]
            preds = np.asarray(handle.preds_d)[:handle.n]
        except RuntimeError as e:
            self._oom_forensics(e, handle.bucket)
            raise
        finally:
            self._release(handle)
        return logits, preds

    def _release(self, handle: InflightBatch) -> None:
        with self._staging_lock:
            if self._inflight.pop(id(handle), None) is not None \
                    and handle.slab is not None:
                self._staging_pool.append(handle.slab)

    @property
    def inflight_count(self) -> int:
        with self._staging_lock:
            return len(self._inflight)

    def close(self) -> None:
        """Drain every staged dispatch still in flight (deterministic
        teardown, the pipeline/prefetch contract: by the time close
        returns the device owes nothing and every slab is back in the
        pool). Idempotent, and the engine stays serveable afterwards —
        close quiesces, it does not poison."""
        self._staging_writer = None   # an aborted filler's claim dies too
        with self._staging_lock:
            pending = list(self._inflight.values())
            self._inflight.clear()
        for h in pending:
            try:
                jax.block_until_ready((h.logits_d, h.preds_d))
            except Exception:  # noqa: BLE001 — teardown drain only: an
                pass           # abandoned transfer's own failure has no
                               # waiter left to deliver to
            if h.slab is not None:
                with self._staging_lock:
                    self._staging_pool.append(h.slab)

    def compiled_programs(self) -> dict:
        """bucket -> the AOT-compiled executable: the forensics surface
        (`telemetry.costs.harvest_engine` reads cost/memory analyses off
        these; a copy, so callers cannot un-warm the ladder)."""
        return dict(self._compiled)

    def _as_rows(self, x) -> np.ndarray:
        x = np.asarray(x, self._np_dtype)
        if x.ndim == 1:
            x = x[None, :]
        if x.ndim != 2 or x.shape[1] != IN_DIM:
            raise ValueError(f"expected (n, {IN_DIM}) rows; got {x.shape}")
        return np.ascontiguousarray(x)

    def _dispatch_chunks(self, x) -> "list[InflightBatch]":
        """Dispatch EVERY max_batch chunk before anything is fetched, so
        chunk k+1's H2D and compute overlap chunk k's execution under
        JAX's async dispatch — the old loop fetched synchronously per
        chunk, serializing the whole multi-chunk batch."""
        return [self._dispatch(x[i:i + self.max_batch])
                for i in range(0, len(x), self.max_batch)]

    def _fetch_chunks(self, handles, which: str) -> np.ndarray:
        """Fetch one output (`logits_d` / `preds_d`) per dispatched chunk.
        If a fetch fails, the remaining in-flight chunks are drained
        before the error propagates (the pipeline/prefetch teardown
        contract: the device owes nothing once the caller sees the
        exception)."""
        outs = []
        for i, h in enumerate(handles):
            try:
                outs.append(np.asarray(getattr(h, which))[:h.n])
            except BaseException as e:
                if isinstance(e, RuntimeError):   # OOM surfaces at fetch
                    self._oom_forensics(e, h.bucket)
                for later in handles[i + 1:]:
                    try:
                        jax.block_until_ready((later.logits_d,
                                               later.preds_d))
                    except Exception:  # noqa: BLE001 — teardown drain:
                        pass           # the primary fetch error is the
                                       # one the caller must see
                raise
        return np.concatenate(outs, axis=0)

    def forward(self, x) -> np.ndarray:
        """Logits (n, 10) float32 for `x` (n, 784); chunks batches larger
        than max_batch so direct callers never hit the bucket cap, with
        all chunks dispatched before the first fetch (they overlap)."""
        return self._fetch_chunks(self._dispatch_chunks(self._as_rows(x)),
                                  "logits_d")

    def predict(self, x) -> np.ndarray:
        """Argmax classes (n,) int32 for `x` (n, 784); same overlapped
        chunking as `forward`."""
        return self._fetch_chunks(self._dispatch_chunks(self._as_rows(x)),
                                  "preds_d")
