"""serve/ — micro-batching TPU inference: checkpoint in, request service out.

The training side of this framework ends at a params checkpoint; this
package is the other half of the north star ("serves heavy traffic"): an
engine that pre-compiles a bucketed ladder of batch shapes so no request
ever pays a cold XLA compile (`engine.py`), an asyncio micro-batcher that
coalesces requests up to a size/deadline knob (`batcher.py`), bounded-queue
admission control with backpressure and graceful drain (`admission.py`),
latency-percentile metrics (`metrics.py`), and an open-loop Poisson load
generator (`loadgen.py`). `ServeService` wires them into the one request
path every front door (cli/serve.py TCP server, bench.py --mode serve,
tests) shares.

Everything runs identically under JAX_PLATFORMS=cpu — the full request path
is exercised by tier-1 tests without hardware.
"""

from __future__ import annotations

import asyncio

from .admission import AdmissionController, Rejected  # noqa: F401
from .batcher import MicroBatcher  # noqa: F401
from .engine import InferenceEngine, bucket_ladder  # noqa: F401
from .metrics import LatencyHistogram, ServeMetrics, SLOWindow  # noqa: F401


class ServeService:
    """admission -> batcher -> engine, with per-request latency metrics.

    `handle(row)` is the whole request path: admit (or raise `Rejected`),
    coalesce, run, scatter, record. Construction wires the metrics' queue-
    depth gauge to the controller and the batcher's occupancy recorder to
    the same metrics object, so a snapshot is always internally consistent.
    """

    def __init__(self, engine: InferenceEngine, *, max_batch=None,
                 max_delay_ms: float = 2.0, max_depth: int = 256,
                 retry_after_s: float = 0.05, clock=None, registry=None):
        import time
        clock = clock or time.monotonic
        self.engine = engine
        self.admission = AdmissionController(max_depth,
                                             retry_after_s=retry_after_s)
        # registry=None keeps the service hermetic (its own private
        # registry); the CLI/bench front doors pass telemetry.get_registry()
        # so serve.* metrics publish into the process-wide snapshot.
        self.metrics = ServeMetrics(depth_fn=lambda: self.admission.depth,
                                    clock=clock, registry=registry)
        self.batcher = MicroBatcher(engine, max_batch=max_batch,
                                    max_delay_ms=max_delay_ms,
                                    metrics=self.metrics, clock=clock)
        self.clock = clock

    async def handle(self, row) -> int:
        """Serve one request row -> predicted class. Raises `Rejected`
        under backpressure or drain (metrics count it either way)."""
        self.metrics.record_arrival()
        try:
            self.admission.admit()
        except Rejected:
            self.metrics.record_reject()
            raise
        t0 = self.clock()
        try:
            pred = await self.batcher.submit(row)
        except Exception:
            # admitted but errored (bad payload, engine failure): counted —
            # a fault storm must not read as a healthy low-traffic interval
            self.metrics.record_failure()
            raise
        finally:
            self.admission.release()
        self.metrics.record_done(self.clock() - t0)
        return pred

    async def shutdown(self) -> None:
        """Graceful drain: refuse new work, serve everything admitted."""
        self.admission.begin_drain()
        await self.batcher.drain()
        await self.admission.drained()


def run_until_drained(service: ServeService, coro):
    """Run `coro` on a fresh event loop, then drain the service — the
    synchronous front doors' (bench, CLI selftest) shared harness."""
    async def _main():
        try:
            return await coro
        finally:
            await service.shutdown()
    return asyncio.run(_main())
