"""serve/ — micro-batching TPU inference: checkpoint in, request service out.

The training side of this framework ends at a params checkpoint; this
package is the other half of the north star ("serves heavy traffic"): an
engine that pre-compiles a bucketed ladder of batch shapes so no request
ever pays a cold XLA compile (`engine.py`), an asyncio micro-batcher that
coalesces requests up to a size/deadline knob (`batcher.py`), bounded-queue
admission control with backpressure, graceful drain and an optional
predicted-p99 SLO boundary (`admission.py`), latency-percentile metrics
with per-stage attribution (`metrics.py`), request-scoped stage tracing —
request_id at the front door, a telescoped admission/queue/batch_form/
pad_h2d/compute/reply breakdown at the back (`tracing.py`) — and an
open-loop load generator with poisson/ramp/spike arrival shapes
(`loadgen.py`). `ServeService` wires them into the one request path every
front door (cli/serve.py TCP server, bench.py --mode serve, tests) shares;
`FleetService` (fleet.py) replicates the engine N ways behind the same
admission layer with SLO-aware routing, a wedge watchdog, bounded request
failover, and supervised restarts, and `ReloadWatcher` (reload.py) hot-swaps
the fleet to newly committed checkpoints behind per-replica drains.

Everything runs identically under JAX_PLATFORMS=cpu — the full request path
is exercised by tier-1 tests without hardware.
"""

from __future__ import annotations

import asyncio

from .admission import ADMIT_MODES, AdmissionController, Rejected  # noqa: F401
from .batcher import MicroBatcher  # noqa: F401
from .engine import InferenceEngine, bucket_ladder  # noqa: F401
from .fleet import (FleetService, FleetUnavailable, ReplicaCrashed,  # noqa: F401
                    ReplicaFailure, ReplicaWedged)
from .metrics import LatencyHistogram, ServeMetrics, SLOWindow  # noqa: F401
from .reload import ReloadWatcher  # noqa: F401
from .tracing import ServeTracer  # noqa: F401


class ServeService:
    """admission -> batcher -> engine, with per-request latency metrics
    and request-scoped stage tracing.

    `handle(row)` is the whole request path: admit (or raise `Rejected`),
    coalesce, run, scatter, record. Construction wires the metrics' queue-
    depth gauge to the controller, the batcher's occupancy recorder to the
    same metrics object, and one `ServeTracer` (serve/tracing.py) through
    all three — every request gets a request_id at the front door and a
    per-stage latency breakdown at the back, so a snapshot is always
    internally consistent AND decomposable.

    `admit_mode="predicted_p99"` (+ `slo_p99_s`) switches admission from
    the raw depth budget to the SLO boundary: reject when the metrics'
    predicted p99 (rolling p99 + queue-drain time) would bust the SLO —
    see serve/admission.py.
    """

    def __init__(self, engine: InferenceEngine, *, max_batch=None,
                 max_delay_ms: float = 2.0, max_depth: int = 256,
                 retry_after_s: float = 0.05, clock=None, registry=None,
                 admit_mode: str = "depth", slo_p99_s=None, fast=None):
        import time
        clock = clock or time.monotonic
        self.engine = engine
        # registry=None keeps the service hermetic (its own private
        # registry); the CLI/bench front doors pass telemetry.get_registry()
        # so serve.* metrics publish into the process-wide snapshot.
        self.metrics = ServeMetrics(depth_fn=lambda: self.admission.depth,
                                    clock=clock, registry=registry)
        self.admission = AdmissionController(
            max_depth, retry_after_s=retry_after_s, mode=admit_mode,
            slo_p99_s=slo_p99_s,
            predictor=(self.metrics.predicted_p99
                       if admit_mode == "predicted_p99" else None))
        self.tracer = ServeTracer(clock=clock, metrics=self.metrics)
        # fast=None auto-selects the staged fast path when the engine has
        # the staging surface (docs/SERVING.md §Fast path); fast=False is
        # the A/B knob (bench.py --no_fast) that forces the legacy
        # stack-at-flush path
        self.batcher = MicroBatcher(engine, max_batch=max_batch,
                                    max_delay_ms=max_delay_ms,
                                    metrics=self.metrics, clock=clock,
                                    tracer=self.tracer, fast=fast)
        self.clock = clock

    async def handle(self, row) -> int:
        """Serve one request row -> predicted class. Raises `Rejected`
        under backpressure or drain (metrics count it either way)."""
        rctx = self.tracer.begin()      # request_id + arrival stamp, even
        self.metrics.record_arrival()   # for requests admission refuses
        try:
            self.admission.admit()
        except Rejected:
            self.metrics.record_reject()
            raise
        self.tracer.admitted(rctx)
        t0 = self.clock()
        try:
            pred = await self.batcher.submit(row, rctx)
        except Exception:
            # admitted but errored (bad payload, engine failure): counted —
            # a fault storm must not read as a healthy low-traffic interval
            self.metrics.record_failure()
            self.tracer.finish(rctx, ok=False)
            raise
        finally:
            self.admission.release()
        self.metrics.record_done(self.clock() - t0)
        self.tracer.finish(rctx, ok=True)
        return pred

    async def shutdown(self) -> None:
        """Graceful drain: refuse new work, serve everything admitted
        (on the fast path that includes awaiting the reply thread's
        outstanding futures), stop the reply thread, drain any in-flight
        device transfers (engine.close — a no-op after a clean drain,
        load-bearing on an aborted one), then leave the slowest-request
        exemplar trees in the flight ring (the post-mortem the drain-time
        dump carries)."""
        self.admission.begin_drain()
        await self.batcher.drain()
        await self.admission.drained()
        self.batcher.close()
        close = getattr(self.engine, "close", None)
        if close is not None:   # duck-typed wrapper engines have no pool
            close()
        self.tracer.flush_exemplars()


def run_until_drained(service: ServeService, coro):
    """Run `coro` on a fresh event loop, then drain the service — the
    synchronous front doors' (bench, CLI selftest) shared harness."""
    async def _main():
        try:
            return await coro
        finally:
            await service.shutdown()
    return asyncio.run(_main())
