"""Admission control: a bounded request queue with backpressure and a
graceful drain.

An open-loop client population does not slow down because the server is
busy — at overload the only choices are unbounded queue growth (every
request eventually served, none within its deadline) or early rejection.
The controller bounds in-flight depth at `max_depth`: past it, requests are
refused IMMEDIATELY with a retry-after hint, keeping the latency of the
admitted population flat while the reject rate absorbs the overload (the
standard TPU-serving admission pattern — the queue protects the batcher,
the batcher protects the MXU).

Shutdown is a drain, not a drop: `begin_drain()` closes the door (new
arrivals rejected as draining) while everything already admitted runs to
completion; `await drained()` returns once in-flight work hits zero.

Two admission modes (cli/serve.py `--admit`, docs/SERVING.md):

  * `depth` (default): reject when in-flight depth hits `max_depth` — the
    original bounded queue. Simple, but it only reacts AFTER the queue is
    long: every request admitted on the way there still eats the full
    backlog's latency.
  * `predicted_p99`: reject when the PREDICTED p99 — the rolling observed
    p99 plus this request's expected queue-drain time (depth / observed
    service rate, both from the serve metrics' SLO window) — exceeds
    `slo_p99_s`. This turns the SLO itself into the admission boundary:
    under overload the controller starts refusing while the queue is
    still short, keeping the ADMITTED population inside its latency
    budget instead of uniformly degrading everyone (ROADMAP item 4's
    SLO-aware admission). `max_depth` stays as the memory backstop, the
    mode degrades to it until the predictor has observations, and an
    EMPTY server (depth 0) always admits — the probe that refreshes a
    stale window, without which a transient overload would reject forever.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Optional

from ..telemetry import flight

ADMIT_MODES = ("depth", "predicted_p99")


class Rejected(Exception):
    """Request refused by admission control; `retry_after_s` is the hint a
    transport should surface (HTTP Retry-After analog)."""

    def __init__(self, reason: str, retry_after_s: float):
        super().__init__(reason)
        self.reason = reason
        self.retry_after_s = retry_after_s


class AdmissionController:
    def __init__(self, max_depth: int = 256, *, retry_after_s: float = 0.05,
                 mode: str = "depth", slo_p99_s: Optional[float] = None,
                 predictor: Optional[Callable[[], Optional[float]]] = None):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1; got {max_depth}")
        if mode not in ADMIT_MODES:
            raise ValueError(f"mode must be one of {ADMIT_MODES}; "
                             f"got {mode!r}")
        if mode == "predicted_p99":
            if slo_p99_s is None or slo_p99_s <= 0:
                raise ValueError(f"predicted_p99 mode needs slo_p99_s > 0; "
                                 f"got {slo_p99_s!r}")
            if predictor is None:
                raise ValueError("predicted_p99 mode needs a predictor "
                                 "(ServeMetrics.predicted_p99 — ServeService "
                                 "wires it)")
        self.max_depth = int(max_depth)
        self.retry_after_s = float(retry_after_s)
        self.mode = mode
        self.slo_p99_s = float(slo_p99_s) if slo_p99_s is not None else None
        # zero-arg callable -> predicted p99 seconds (None until the SLO
        # window has observations — the mode degrades to the depth
        # backstop until then, never rejects on a guess)
        self.predictor = predictor
        self.depth = 0          # admitted and not yet released
        self.admitted = 0
        self.rejected = 0
        self.rejected_predicted = 0  # rejects owed to the SLO boundary
        self.draining = False
        self._empty: Optional[asyncio.Event] = None

    def admit(self) -> None:
        """Take one slot or raise Rejected. Pair with `release()`.

        Both reject branches feed the flight recorder (bounded ring, no
        I/O): a drained or overloaded server that later dies leaves WHICH
        requests it was refusing, and why, in the post-mortem dump —
        aggregate reject counts live in the metrics registry, the recorder
        keeps the most recent individual refusals."""
        if self.draining:
            self.rejected += 1
            flight.record("serve_reject", reason="draining",
                          depth=self.depth, rejected_total=self.rejected)
            raise Rejected("draining: server is shutting down",
                           self.retry_after_s)
        if self.depth >= self.max_depth:
            self.rejected += 1
            flight.record("serve_reject", reason="queue_full",
                          depth=self.depth, max_depth=self.max_depth,
                          rejected_total=self.rejected)
            raise Rejected(
                f"queue depth {self.depth} at budget {self.max_depth}",
                self.retry_after_s)
        # An EMPTY server always admits (depth 0 skips the SLO boundary):
        # the queue-drain term is zero, and the admitted request is the
        # probe that refreshes the rolling window. Without it a transient
        # overload livelocks — the window only updates on completions, so
        # a stale past-SLO p99 would reject 100% of traffic forever on an
        # otherwise idle server.
        if self.mode == "predicted_p99" and self.depth > 0:
            predicted = self.predictor()
            if predicted is not None and predicted > self.slo_p99_s:
                self.rejected += 1
                self.rejected_predicted += 1
                flight.record("serve_reject", reason="predicted_p99",
                              predicted_p99_s=round(float(predicted), 6),
                              slo_p99_s=self.slo_p99_s, depth=self.depth,
                              rejected_total=self.rejected)
                raise Rejected(
                    f"predicted p99 {predicted * 1e3:.1f}ms past SLO "
                    f"{self.slo_p99_s * 1e3:.1f}ms (depth {self.depth})",
                    self.retry_after_s)
        self.depth += 1
        self.admitted += 1

    def release(self) -> None:
        assert self.depth > 0, "release() without a matching admit()"
        self.depth -= 1
        if self.depth == 0 and self._empty is not None:
            self._empty.set()

    def begin_drain(self) -> None:
        """Stop admitting; already-admitted requests run to completion."""
        self.draining = True

    async def drained(self) -> None:
        """Resolve once draining AND no request is in flight."""
        self.begin_drain()
        if self.depth == 0:
            return
        if self._empty is None:
            self._empty = asyncio.Event()
        while self.depth > 0:
            self._empty.clear()
            await self._empty.wait()
