"""Admission control: a bounded request queue with backpressure and a
graceful drain.

An open-loop client population does not slow down because the server is
busy — at overload the only choices are unbounded queue growth (every
request eventually served, none within its deadline) or early rejection.
The controller bounds in-flight depth at `max_depth`: past it, requests are
refused IMMEDIATELY with a retry-after hint, keeping the latency of the
admitted population flat while the reject rate absorbs the overload (the
standard TPU-serving admission pattern — the queue protects the batcher,
the batcher protects the MXU).

Shutdown is a drain, not a drop: `begin_drain()` closes the door (new
arrivals rejected as draining) while everything already admitted runs to
completion; `await drained()` returns once in-flight work hits zero.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..telemetry import flight


class Rejected(Exception):
    """Request refused by admission control; `retry_after_s` is the hint a
    transport should surface (HTTP Retry-After analog)."""

    def __init__(self, reason: str, retry_after_s: float):
        super().__init__(reason)
        self.reason = reason
        self.retry_after_s = retry_after_s


class AdmissionController:
    def __init__(self, max_depth: int = 256, *, retry_after_s: float = 0.05):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1; got {max_depth}")
        self.max_depth = int(max_depth)
        self.retry_after_s = float(retry_after_s)
        self.depth = 0          # admitted and not yet released
        self.admitted = 0
        self.rejected = 0
        self.draining = False
        self._empty: Optional[asyncio.Event] = None

    def admit(self) -> None:
        """Take one slot or raise Rejected. Pair with `release()`.

        Both reject branches feed the flight recorder (bounded ring, no
        I/O): a drained or overloaded server that later dies leaves WHICH
        requests it was refusing, and why, in the post-mortem dump —
        aggregate reject counts live in the metrics registry, the recorder
        keeps the most recent individual refusals."""
        if self.draining:
            self.rejected += 1
            flight.record("serve_reject", reason="draining",
                          depth=self.depth, rejected_total=self.rejected)
            raise Rejected("draining: server is shutting down",
                           self.retry_after_s)
        if self.depth >= self.max_depth:
            self.rejected += 1
            flight.record("serve_reject", reason="queue_full",
                          depth=self.depth, max_depth=self.max_depth,
                          rejected_total=self.rejected)
            raise Rejected(
                f"queue depth {self.depth} at budget {self.max_depth}",
                self.retry_after_s)
        self.depth += 1
        self.admitted += 1

    def release(self) -> None:
        assert self.depth > 0, "release() without a matching admit()"
        self.depth -= 1
        if self.depth == 0 and self._empty is not None:
            self._empty.set()

    def begin_drain(self) -> None:
        """Stop admitting; already-admitted requests run to completion."""
        self.draining = True

    async def drained(self) -> None:
        """Resolve once draining AND no request is in flight."""
        self.begin_drain()
        if self.depth == 0:
            return
        if self._empty is None:
            self._empty = asyncio.Event()
        while self.depth > 0:
            self._empty.clear()
            await self._empty.wait()
