"""Async micro-batching queue: coalesce single-row requests into bucketed
engine calls.

The latency/throughput knob of every TPU serving stack: one request per
forward pass wastes the MXU (a (1, 784) matmul is pure dispatch overhead),
while unbounded coalescing holds early arrivals hostage to late ones. The
batcher bounds both sides — a flush fires when `max_batch` rows are pending
(throughput side) or when the OLDEST pending request has waited
`max_delay_ms` (latency side), whichever comes first. Flushed rows are
stacked, padded to the engine's nearest bucket, run as one executable call,
and scattered back to each request's future.

The deadline clock is injectable (`clock=`) and the flush decision is a pure
function of (now, pending) — `flush_due(now)` — so tests drive coalescing
deterministically under a fake clock instead of racing real timers.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from .engine import IN_DIM


class MicroBatcher:
    """Coalesces `submit`ted rows into engine calls.

    Not thread-safe: like any asyncio building block it lives on one event
    loop. The engine call itself is synchronous (JAX blocks until the
    executable returns) — at MNIST-MLP scale a bucket forward is far cheaper
    than a loop tick, so handing it to a thread pool would only add latency.
    """

    def __init__(self, engine, *, max_batch: Optional[int] = None,
                 max_delay_ms: float = 2.0, metrics=None,
                 clock: Callable[[], float] = time.monotonic, tracer=None):
        self.engine = engine
        self.max_batch = int(max_batch or engine.max_batch)
        if not 1 <= self.max_batch <= engine.max_batch:
            raise ValueError(
                f"max_batch {self.max_batch} outside [1, {engine.max_batch}]"
                f" (the engine's largest precompiled bucket)")
        if max_delay_ms < 0:
            raise ValueError(f"max_delay_ms must be >= 0; got {max_delay_ms}")
        self.max_delay_s = max_delay_ms / 1000.0
        self.metrics = metrics
        self.clock = clock
        # serve.tracing.ServeTracer (or None for the standalone/legacy
        # construction): stamps the per-flush BatchCtx and links every
        # member request's ctx to it
        self.tracer = tracer
        self.engine_in_dim = IN_DIM
        # (row, future, t_enqueue, rctx) tuples awaiting a flush; rctx is
        # the request's tracing context (None from bare submit() callers)
        self._pending: List[Tuple[np.ndarray, asyncio.Future, float,
                                  object]] = []
        self._timer: Optional[asyncio.TimerHandle] = None
        self.flushes = 0

    @property
    def depth(self) -> int:
        return len(self._pending)

    def flush_due(self, now: float) -> bool:
        """True when the pending set must flush at time `now`: full batch,
        or the oldest request's deadline has arrived."""
        if not self._pending:
            return False
        if len(self._pending) >= self.max_batch:
            return True
        return now - self._pending[0][2] >= self.max_delay_s

    async def submit(self, row, rctx=None) -> int:
        """Enqueue one request row; resolves to its predicted class.
        `rctx` (a `serve.tracing.RequestCtx`) gets the enqueue stamp and,
        at flush time, a link to the batch that carried the request.

        A malformed row raises HERE, synchronously to its own caller — it
        must never reach the flush, where one bad row would poison the
        whole coalesced batch (np.stack of ragged rows raises after the
        pending set was already swapped out, hanging every other waiter
        and leaking their admission slots)."""
        row = np.asarray(row).reshape(-1)   # (1, 784) and (784,) both fine
        if row.shape != (self.engine_in_dim,):
            raise ValueError(f"request row must have {self.engine_in_dim} "
                             f"pixels; got shape {np.asarray(row).shape}")
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        t_enq = self.clock()
        if rctx is not None and self.tracer is not None:
            # one stamp serves both the flush-deadline bookkeeping and the
            # queue stage — they must never disagree about when waiting
            # started
            self.tracer.enqueued(rctx, t_enq)
        self._pending.append((row, fut, t_enq, rctx))
        if len(self._pending) >= self.max_batch:
            self.flush(reason="size")
        elif self._timer is None:
            # one timer per oldest-pending request: it fires at that
            # request's deadline and flush() re-arms for the next batch
            self._timer = loop.call_later(self.max_delay_s, self._on_timer)
        return await fut

    def _on_timer(self) -> None:
        self._timer = None
        if self.flush_due(self.clock()):
            self.flush(reason="deadline")
        elif self._pending:
            # injected-clock drift (tests): re-arm for the remainder
            remain = self.max_delay_s - (self.clock() - self._pending[0][2])
            self._timer = asyncio.get_event_loop().call_later(
                max(remain, 0.0), self._on_timer)

    def flush(self, reason: str = "manual") -> int:
        """Run every pending row through the engine now; returns the number
        of rows flushed. Fills each request's future (result or the
        engine's exception). `reason` records WHY the batch formed (size /
        deadline / drain / manual) on its tracing context — the coalescing
        knob's observable output."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        batch, self._pending = self._pending, []
        if not batch:
            return 0
        bctx = (self.tracer.batch_begin(reason)
                if self.tracer is not None else None)
        try:
            rows = np.stack([r for r, _, _, _ in batch])
            x = self.engine._as_rows(rows)
            if bctx is not None:
                bctx.mark_formed()
            # the bctx arg only when tracing is wired: duck-typed engine
            # wrappers with the original one-arg _run_bucket keep working
            _, preds, bucket = (self.engine._run_bucket(x, bctx)
                                if bctx is not None
                                else self.engine._run_bucket(x))
        except Exception as e:  # scatter the failure — a waiter must never
            for _, fut, _, _ in batch:                    # hang on a crash
                if not fut.done():
                    fut.set_exception(e)
            return len(batch)
        self.flushes += 1
        if self.metrics is not None:
            self.metrics.record_batch(len(batch), bucket)
        if bctx is not None:
            self.tracer.batch_end(bctx, n_real=len(batch))
        for (_, fut, _, rctx), pred in zip(batch, preds):
            if rctx is not None:
                rctx.batch = bctx
            if not fut.done():
                fut.set_result(int(pred))
        return len(batch)

    async def drain(self) -> None:
        """Flush whatever is pending and return once it is served."""
        self.flush(reason="drain")
