"""Async micro-batching queue: coalesce single-row requests into bucketed
engine calls.

The latency/throughput knob of every TPU serving stack: one request per
forward pass wastes the MXU (a (1, 784) matmul is pure dispatch overhead),
while unbounded coalescing holds early arrivals hostage to late ones. The
batcher bounds both sides — a flush fires when `max_batch` rows are pending
(throughput side) or when the OLDEST pending request has waited
`max_delay_ms` (latency side), whichever comes first.

Two flush paths share that policy:

* **fast path** (a real `InferenceEngine`, the default): `submit` writes
  each request's row straight into the engine's persistent staging slab at
  enqueue time, so `batch_form` collapses to index bookkeeping — no
  np.stack, no fresh allocation per flush. The flush DISPATCHES the bucket
  executable (`engine.dispatch_staged`, async under JAX dispatch) and
  returns to the loop immediately. The reply is then ROUTED one loop pass
  later, cheapest-first: results already device-complete are fetched
  INLINE (a no-wait asarray, free of cross-thread handoff); fetches whose
  recent cost (EWMA) sits under the inline budget (~one coalescing
  deadline) are taken inline too; genuinely in-flight work goes to a
  dedicated **reply thread** that blocks on the device->host fetch
  off-loop and re-enters the loop via `call_soon_threadsafe` to scatter —
  the `reply` stage is where event-loop starvation lives (PR 9's stage
  catalog), and with long fetches off-loop the loop keeps
  admitting/coalescing while the device computes.
* **legacy path** (duck-typed engine wrappers without the staging API, or
  `fast=False`): rows accumulate as tuples, the flush stacks/pads/runs/
  scatters synchronously — the original PR 1 shape, kept so instrumented
  test engines and embedded callers run unchanged.

The deadline clock is injectable (`clock=`) and the flush decision is a pure
function of (now, pending) — `flush_due(now)` — so tests drive coalescing
deterministically under a fake clock instead of racing real timers.

Threading contract (docs/SERVING.md §Fast path): `submit`/`flush`/`drain`
stay event-loop-only; the reply thread (`_reply_worker`, registered in the
statics thread-entry map by its `threading.Thread(target=...)` spawn) only
fetches and enqueues the loop-side `_scatter` callback — futures, tracer
spans, and metrics are touched exclusively on the loop.
"""

from __future__ import annotations

import asyncio
import queue
import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from .engine import IN_DIM

# Staging-capable engines expose exactly this surface; anything less (the
# tests' recording wrappers, embedded duck-typed engines) gets the legacy
# stack-at-flush path.
_FAST_API = ("staging", "dispatch_staged", "fetch_staged")

# Floor of the reply router's inline-fetch budget (seconds): a flush
# whose recent fetches ran under max(budget, max_delay_ms) is fetched ON
# the loop — blocking it for at most about one coalescing deadline, which
# is time the oldest request would have waited anyway — instead of paying
# a cross-thread handoff (one GIL switch interval each way on a
# contended host). Fetches past the budget (real accelerator compute) go
# to the reply thread, where blocking belongs.
INLINE_FETCH_BUDGET_S = 2e-3


class MicroBatcher:
    """Coalesces `submit`ted rows into engine calls.

    Not thread-safe: like any asyncio building block it lives on one event
    loop. On the fast path the engine call is DISPATCHED from the loop but
    fetched on the reply thread, so the loop never blocks on device
    execution; on the legacy path the call is synchronous (at MNIST-MLP
    scale a bucket forward is far cheaper than a loop tick).
    """

    def __init__(self, engine, *, max_batch: Optional[int] = None,
                 max_delay_ms: float = 2.0, metrics=None,
                 clock: Callable[[], float] = time.monotonic, tracer=None,
                 fast: Optional[bool] = None):
        self.engine = engine
        self.max_batch = int(max_batch or engine.max_batch)
        if not 1 <= self.max_batch <= engine.max_batch:
            raise ValueError(
                f"max_batch {self.max_batch} outside [1, {engine.max_batch}]"
                f" (the engine's largest precompiled bucket)")
        if max_delay_ms < 0:
            raise ValueError(f"max_delay_ms must be >= 0; got {max_delay_ms}")
        self.max_delay_s = max_delay_ms / 1000.0
        self.metrics = metrics
        self.clock = clock
        # serve.tracing.ServeTracer (or None for the standalone/legacy
        # construction): stamps the per-flush BatchCtx and links every
        # member request's ctx to it
        self.tracer = tracer
        self.engine_in_dim = IN_DIM
        # fast path only when the engine actually has the staging surface;
        # fast=False forces legacy (the A/B knob bench.py --no_fast rides)
        has_api = all(hasattr(engine, m) for m in _FAST_API)
        self.fast_path = has_api if fast is None else bool(fast) and has_api
        # (row, future, t_enqueue, rctx) tuples awaiting a flush; on the
        # fast path `row` is None — the row already lives in the engine's
        # staging slab at its enqueue index. rctx is the request's tracing
        # context (None from bare submit() callers).
        self._pending: List[Tuple[Optional[np.ndarray], asyncio.Future,
                                  float, object]] = []
        self._timer: Optional[asyncio.TimerHandle] = None
        self.flushes = 0
        # flushes completed inline on the loop — results already
        # device-complete when the router ran, or fetched within the
        # inline budget — vs handed to the reply thread (the routing's
        # observable)
        self.inline_replies = 0
        # PER-BUCKET EWMAs of recent fetch_staged wall times, the
        # router's cost model: a bucket with no history never blocks the
        # loop on a guess — and small-bucket history never vouches for a
        # top-bucket flush whose compute is proportionally longer (the
        # mispredict would stall the loop for the whole bucket compute).
        # Written from whichever context fetched last (loop or reply
        # thread) — a benign last-writer-wins float heuristic, never a
        # correctness input.
        self._fetch_ewma: "dict[int, float]" = {}
        self._inline_budget_s = max(self.max_delay_s,
                                    INLINE_FETCH_BUDGET_S)
        # fast path plumbing: the loop captured at submit time (the one
        # the reply thread re-enters), futures not yet resolved (drain
        # awaits them), and the fetch work queue feeding the reply thread
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._outstanding: "set[asyncio.Future]" = set()
        self._reply_q: "queue.Queue" = queue.Queue()
        self._reply_thread: Optional[threading.Thread] = None
        # In-flight journal for the fleet supervisor (serve/fleet.py):
        # id(batch) -> (t_dispatch, batch) for every fast-path flush
        # dispatched but not yet scattered. Loop-side only (opened in
        # _flush_fast, closed in _scatter) — the supervisor ages the
        # oldest entry exactly like the PR 14 collective watchdog ages
        # open journal entries, and `fail_all` is the failover that
        # releases the waiters of a replica declared dead or wedged.
        self._inflight_meta: "dict[int, tuple]" = {}
        if self.fast_path:
            # spawn eagerly: thread startup is construction-time cost,
            # never first-request latency (close() stops it; a later
            # flush would respawn)
            self._ensure_reply_thread()

    @property
    def depth(self) -> int:
        return len(self._pending)

    def flush_due(self, now: float) -> bool:
        """True when the pending set must flush at time `now`: full batch,
        or the oldest request's deadline has arrived."""
        if not self._pending:
            return False
        if len(self._pending) >= self.max_batch:
            return True
        return now - self._pending[0][2] >= self.max_delay_s

    async def submit(self, row, rctx=None) -> int:
        """Enqueue one request row; resolves to its predicted class.
        `rctx` (a `serve.tracing.RequestCtx`) gets the enqueue stamp and,
        at flush time, a link to the batch that carried the request.

        A malformed row raises HERE, synchronously to its own caller — it
        must never reach the flush (and on the fast path must never touch
        the staging slab), where one bad row would poison the whole
        coalesced batch."""
        row = np.asarray(row).reshape(-1)   # (1, 784) and (784,) both fine
        if row.shape != (self.engine_in_dim,):
            raise ValueError(f"request row must have {self.engine_in_dim} "
                             f"pixels; got shape {np.asarray(row).shape}")
        loop = asyncio.get_running_loop()
        self._loop = loop
        fut: asyncio.Future = loop.create_future()
        t_enq = self.clock()
        if rctx is not None and self.tracer is not None:
            # one stamp serves both the flush-deadline bookkeeping and the
            # queue stage — they must never disagree about when waiting
            # started
            self.tracer.enqueued(rctx, t_enq)
        if self.fast_path:
            # zero-copy batch forming: the row lands at its final batch
            # index in the persistent staging slab NOW; the flush is left
            # with index bookkeeping only (the assignment casts to the
            # engine dtype exactly like _as_rows did). Passing ourselves
            # claims the slab — a second batcher filling the same engine
            # concurrently fails loudly instead of corrupting silently.
            self.engine.staging(self)[len(self._pending)] = row
            self._pending.append((None, fut, t_enq, rctx))
        else:
            self._pending.append((row, fut, t_enq, rctx))
        if len(self._pending) >= self.max_batch:
            self.flush(reason="size")
        elif self._timer is None:
            # one timer per oldest-pending request: it fires at that
            # request's deadline and flush() re-arms for the next batch
            self._timer = loop.call_later(self.max_delay_s, self._on_timer)
        return await fut

    def _on_timer(self) -> None:
        self._timer = None
        if self.flush_due(self.clock()):
            self.flush(reason="deadline")
        elif self._pending:
            # injected-clock drift (tests): re-arm for the remainder
            remain = self.max_delay_s - (self.clock() - self._pending[0][2])
            self._timer = asyncio.get_event_loop().call_later(
                max(remain, 0.0), self._on_timer)

    def flush(self, reason: str = "manual") -> int:
        """Flush every pending row through the engine; returns the number
        of rows flushed. On the fast path the engine call is DISPATCHED
        and the reply thread fills the futures once results land on the
        host; on the legacy path everything completes synchronously here.
        `reason` records WHY the batch formed (size / deadline / drain /
        manual) on its tracing context — the coalescing knob's observable
        output."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        batch, self._pending = self._pending, []
        if not batch:
            return 0
        bctx = (self.tracer.batch_begin(reason)
                if self.tracer is not None else None)
        if self.fast_path:
            return self._flush_fast(batch, bctx)
        try:
            rows = np.stack([r for r, _, _, _ in batch])
            x = self.engine._as_rows(rows)
            if bctx is not None:
                bctx.mark_formed()
            # the bctx arg only when tracing is wired: duck-typed engine
            # wrappers with the original one-arg _run_bucket keep working
            _, preds, bucket = (self.engine._run_bucket(x, bctx)
                                if bctx is not None
                                else self.engine._run_bucket(x))
        except Exception as e:  # scatter the failure — a waiter must never
            for _, fut, _, _ in batch:                    # hang on a crash
                if not fut.done():
                    fut.set_exception(e)
            return len(batch)
        self.flushes += 1
        if self.metrics is not None:
            self.metrics.record_batch(len(batch), bucket)
        if bctx is not None:
            self.tracer.batch_end(bctx, n_real=len(batch))
        for (_, fut, _, rctx), pred in zip(batch, preds):
            if rctx is not None:
                rctx.batch = bctx
            if not fut.done():
                fut.set_result(int(pred))
        return len(batch)

    # -- fast path: dispatch on the loop, fetch on the reply thread --------

    def _flush_fast(self, batch, bctx) -> int:
        """The staged flush: rows are ALREADY in the engine's staging slab
        (written at enqueue), so forming the batch is this clock stamp —
        then dispatch H2D + compute and hand the in-flight handle to the
        reply thread. The loop is free again in microseconds."""
        if bctx is not None:
            bctx.mark_formed()
        try:
            handle = self.engine.dispatch_staged(len(batch), bctx)
        except Exception as e:  # dispatch failed (OOM forensics already
            for _, fut, _, _ in batch:          # recorded): scatter it —
                if not fut.done():              # a waiter must never hang
                    fut.set_exception(e)
            return len(batch)
        self.flushes += 1
        self._inflight_meta[id(batch)] = (self.clock(), batch)
        for _, fut, _, _ in batch:
            self._outstanding.add(fut)
            fut.add_done_callback(self._outstanding.discard)
        # Defer the reply decision ONE loop pass (lets a short
        # executable finish while other callbacks run), then route it.
        self._loop.call_soon(self._route_reply, (handle, batch, bctx))
        return len(batch)

    def _route_reply(self, item) -> None:
        """Loop-side reply routing, cheapest-first:

        1. results already device-complete -> fetch inline (a no-wait
           asarray; zero cross-thread handoff — which costs one GIL
           switch interval each way on a contended host);
        2. recent fetches OF THIS BUCKET ran under the inline budget
           (~one coalescing deadline) -> fetch inline anyway: blocking
           the loop for less than the deadline the oldest request
           already tolerated beats paying the handoff twice per flush;
        3. else (accelerator-scale compute, or no history for this
           bucket yet) -> the reply thread blocks on the fetch OFF the
           loop.
        """
        handle, batch, bctx = item
        ewma = self._fetch_ewma.get(handle.bucket)
        # inline_ok is False only for a deliberately wedged handle
        # (fault injection): EWMA history must never vouch a hung fetch
        # onto the loop — it would blind the fleet watchdog under test
        if handle.ready() or (ewma is not None
                              and ewma <= self._inline_budget_s
                              and getattr(handle, "inline_ok", True)):
            self.inline_replies += 1
            self._scatter(self._fetch_payload(handle, batch, bctx))
        else:
            self._ensure_reply_thread()
            self._reply_q.put((handle, batch, bctx, self._loop))

    def _fetch_payload(self, handle, batch, bctx):
        """Fetch one flush's results into a scatter payload (result or
        the fetch's own exception). Runs on the reply thread for
        in-flight work, on the loop for the inline cases — either way
        the engine's exactly-two-fetches-per-flush budget holds.

        The router's cost model only learns from fetches that actually
        WAITED (not device-complete when the fetch started): a no-wait
        fetch measures pure copy cost, and letting it drag the EWMA down
        would license an inline fetch of a not-yet-ready flush at the
        next quiet-to-busy transition — blocking the loop for a full
        bucket compute, the exact stall the budget bounds."""
        waited = not handle.ready()
        t0 = time.monotonic()
        try:
            _, preds = self.engine.fetch_staged(handle)
            if bctx is not None:
                bctx.mark_computed()
            payload = (batch, bctx, handle.bucket, preds, None)
        except Exception as e:  # noqa: BLE001 — fetch fault barrier:
            # the error is delivered to every waiter via the scatter
            # (re-raised at each await site); swallowing only a narrow
            # set would strand waiters on an unforeseen one
            payload = (batch, bctx, handle.bucket, None, e)
        if waited:
            dur = time.monotonic() - t0
            prev = self._fetch_ewma.get(handle.bucket)
            self._fetch_ewma[handle.bucket] = (
                dur if prev is None else 0.5 * prev + 0.5 * dur)
        return payload

    def _ensure_reply_thread(self) -> None:
        if self._reply_thread is None or not self._reply_thread.is_alive():
            self._reply_thread = threading.Thread(
                target=self._reply_worker, name="serve-reply", daemon=True)
            self._reply_thread.start()

    def _reply_worker(self) -> None:
        """The dedicated reply thread (statics thread-entry map: spawned
        by `_ensure_reply_thread`): block on each flush's device->host
        fetch OFF the event loop, then re-enter the loop via
        `call_soon_threadsafe` to scatter. Touches no future, tracer, or
        metrics state itself — that is `_scatter`'s, on the loop."""
        while True:
            item = self._reply_q.get()
            if item is None:
                return
            handle, batch, bctx, loop = item
            payload = self._fetch_payload(handle, batch, bctx)
            try:
                loop.call_soon_threadsafe(self._scatter, payload)
            except RuntimeError:
                # loop already closed (abandoned service, no drain): the
                # futures' awaiters are gone with it; nothing to deliver
                return

    def _scatter(self, payload) -> None:
        """Loop-side completion of one fast-path flush: metrics, the
        batch-end span, and the per-request future fill (exactly what the
        legacy flush tail does, minus the fetch that already happened
        off-loop)."""
        batch, bctx, bucket, preds, err = payload
        # a journal entry missing here means `fail_all` already failed
        # this flush over (a quarantined replica's late fetch finally
        # landing): end the batch span honestly — the device DID finish —
        # but record no batch and fill no future; the requests were
        # retried elsewhere and a retry batch accounts for them
        abandoned = (self._inflight_meta.pop(id(batch), None) is None
                     and self.fast_path)
        if err is not None:
            for _, fut, _, _ in batch:
                if not fut.done():
                    fut.set_exception(err)
            return
        if abandoned:
            if bctx is not None:
                self.tracer.batch_end(bctx, n_real=len(batch))
            return
        if self.metrics is not None:
            self.metrics.record_batch(len(batch), bucket)
        if bctx is not None:
            self.tracer.batch_end(bctx, n_real=len(batch))
        for (_, fut, _, rctx), pred in zip(batch, preds):
            if rctx is not None:
                rctx.batch = bctx
            if not fut.done():
                fut.set_result(int(pred))

    # -- fleet supervision surface (serve/fleet.py) -------------------------

    def oldest_inflight_age(self, now: float) -> float:
        """Age (seconds) of the oldest dispatched-but-unscattered flush at
        `now`, 0.0 when nothing is in flight — what the fleet supervisor
        compares against its wedge timeout. Loop-side, like everything
        else touching the journal."""
        if not self._inflight_meta:
            return 0.0
        return now - min(t for t, _ in self._inflight_meta.values())

    def fail_all(self, exc: BaseException) -> int:
        """Failover: deliver `exc` to every in-flight AND pending request
        of this batcher and forget them; returns how many waiters were
        released. The fleet supervisor calls this on a replica declared
        dead or wedged so its accepted-but-unanswered requests re-raise at
        their `submit` await sites and can retry on a survivor — the
        futures are completed loop-side (this must run on the loop), and
        a wedged flush's eventual late `_scatter` finds its journal entry
        gone and delivers nothing twice."""
        n = 0
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        for _, batch in list(self._inflight_meta.values()):
            for _, fut, _, _ in batch:
                if not fut.done():
                    fut.set_exception(exc)
                    n += 1
        self._inflight_meta.clear()
        pending, self._pending = self._pending, []
        for _, fut, _, _ in pending:
            if not fut.done():
                fut.set_exception(exc)
                n += 1
        return n

    async def drain(self) -> None:
        """Flush whatever is pending and return once it is served — on
        the fast path that means awaiting every outstanding future the
        reply thread still owes (the legacy path resolves them inside
        flush)."""
        self.flush(reason="drain")
        if self._outstanding:
            await asyncio.gather(*list(self._outstanding),
                                 return_exceptions=True)

    def close(self, wait: bool = True) -> None:
        """Stop the reply thread (sentinel + join). Call after `drain` —
        anything still queued is fetched and delivered first because the
        sentinel lands behind it. Idempotent; the next fast-path flush
        would simply spawn a fresh thread.

        `wait=False` abandons instead of joining: the sentinel is queued
        so a LIVE thread exits once it finishes what it is on, but a
        thread blocked inside a wedged fetch is left behind (daemon — it
        cannot hold the process). That is the fleet's retirement path for
        a wedged replica, where joining would block the supervisor for
        exactly the hang being escaped."""
        if self._reply_thread is not None and self._reply_thread.is_alive():
            self._reply_q.put(None)
            if wait:
                self._reply_thread.join(timeout=10.0)
        self._reply_thread = None
