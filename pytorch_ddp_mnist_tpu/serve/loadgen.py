"""Open-loop Poisson load generator for the serve path.

Closed-loop benchmarks (N workers, each waiting for its response before the
next request — bench.py's other modes) cannot see queueing collapse: the
client slows down exactly when the server does, hiding the latency the real
open-loop world (millions of independent users) would experience. This
generator schedules arrivals on an ABSOLUTE Poisson timeline — exponential
inter-arrival gaps at `offered_rps`, drawn from a seeded numpy Generator —
and fires each request at its scheduled instant whether or not earlier ones
have returned. Latency percentiles therefore include queueing delay, and
offered vs achieved throughput (+ reject rate) exposes saturation honestly.

Each request also stamps its CLIENT-side send time: `client_latency_ms`
is the latency the caller perceived (send -> response), while the server's
own `latency_ms` starts at admission. The percentile-level delta between
them (`front_door_overhead_ms`) is the front-door cost — event-loop
scheduling before the handler runs, and over a real transport the network
+ framing — the piece of the user's experience no server-side histogram
can see. Both sides use the same exact nearest-rank convention AND the
same population (the last min(n, 512) completions by completion time —
the SLO window's own selection rule), so the delta measures the front
door even on runs longer than the window; log-bucketed histogram
quantization would bury the signal. `bench.py --mode serve` stamps it
into the artifact line.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

import numpy as np

from . import Rejected, ServeService

from .metrics import nearest_rank

IN_DIM = 784

# Arrival shapes for `arrival_times` (cli/bench `--shape`). All three
# offer the SAME total load (n requests, n/offered_rps nominal seconds);
# they differ only in how that mass lands on the timeline.
SHAPES = ("poisson", "ramp", "spike")


def arrival_times(n: int, offered_rps: float, *, shape: str = "poisson",
                  seed: int = 0) -> np.ndarray:
    """Absolute arrival instants (seconds from start) for `n` requests at
    nominal `offered_rps`, under one of three offered-load shapes:

    - ``poisson``: homogeneous Poisson — exponential inter-arrival gaps.
      This branch is bitwise-identical to the generator's original
      timeline (same seed -> same floats), so every existing artifact
      and pinned test keeps its exact arrivals.
    - ``ramp``: linear rate ramp from 0.2x to 1.8x the nominal rate over
      the run — the warm-up curve that exposes whether admission tuned
      at steady state also holds while load is still climbing.
    - ``spike``: 0.5x baseline with a 3x burst through the middle fifth
      of the run — the flash-crowd shape that stresses failover + drain
      (the chaos smoke kills a replica inside the burst).

    The inhomogeneous shapes are exact thinning-free draws: simulate a
    unit-rate Poisson process (cumsum of Exp(1)) and time-warp it through
    the inverse cumulative intensity Lambda^-1 — for ramp a closed-form
    quadratic root, for spike a piecewise-linear inversion whose tail
    continues at the final segment's rate (random mass can overshoot the
    nominal window; arrivals must stay monotone, never clip)."""
    if shape not in SHAPES:
        raise ValueError(f"unknown arrival shape {shape!r}; "
                         f"choose from {SHAPES}")
    rng = np.random.default_rng(seed)
    if shape == "poisson":
        return np.cumsum(rng.exponential(1.0 / offered_rps, size=n))
    u = np.cumsum(rng.exponential(1.0, size=n))  # unit-rate arrivals
    T = n / offered_rps                          # nominal duration
    if shape == "ramp":
        # lambda(t) = r*(0.2 + 1.6*t/T)  =>  Lambda(t) = r*(0.2t + 0.8t²/T)
        # (integrates to exactly n over [0, T]); solve Lambda(t) = u
        v = u / offered_rps
        return (T / 1.6) * (np.sqrt(0.04 + 3.2 * v / T) - 0.2)
    # spike: (fraction-of-T, rate-multiplier) segments; multipliers are
    # mass-balanced (0.4*0.5 + 0.2*3.0 + 0.4*0.5 = 1.0) so nominal total
    # stays n
    segs = ((0.4, 0.5), (0.2, 3.0), (0.4, 0.5))
    durs = np.array([f * T for f, _ in segs])
    rates = np.array([m * offered_rps for _, m in segs])
    mass_edges = np.concatenate([[0.0], np.cumsum(rates * durs)])
    time_edges = np.concatenate([[0.0], np.cumsum(durs)])
    seg = np.minimum(np.searchsorted(mass_edges[1:], u, side="left"),
                     len(segs) - 1)
    return time_edges[seg] + (u - mass_edges[seg]) / rates[seg]


def request_rows(n: int, dtype: str = "float32",
                 seed: int = 0) -> np.ndarray:
    """Deterministic synthetic request payloads: (n, 784) pixel rows in the
    engine's input dtype (uint8 raw pixels or pre-normalized float32)."""
    rng = np.random.default_rng(seed)
    raw = rng.integers(0, 256, size=(n, IN_DIM), dtype=np.uint8)
    if dtype == "uint8":
        return raw
    from ..data.mnist import normalize_images
    return normalize_images(raw.reshape(n, 28, 28)).astype(np.float32)


async def run_open_loop(service: ServeService, *, offered_rps: float,
                        n_requests: int, seed: int = 0,
                        rows: Optional[np.ndarray] = None,
                        shape: str = "poisson") -> dict:
    """Drive `n_requests` through the service at `offered_rps` under the
    given arrival `shape` (see `arrival_times`); returns {offered_rps,
    duration_s, predictions, snapshot...}.

    Arrival times are precomputed and each request fires as its own task
    at its absolute slot — a slow response never delays later arrivals
    (open loop). Rejects count in the metrics and leave a None
    prediction."""
    if offered_rps <= 0:
        raise ValueError(f"offered_rps must be > 0; got {offered_rps}")
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1; got {n_requests}")
    arrivals = arrival_times(n_requests, offered_rps, shape=shape,
                             seed=seed)
    if rows is None:
        rows = request_rows(n_requests, service.engine.input_dtype,
                            seed=seed + 1)
    elif len(rows) < n_requests:
        rows = rows[np.arange(n_requests) % len(rows)]

    preds: "list[Optional[int]]" = [None] * n_requests
    # client-perceived latency per COMPLETED request: send stamp taken
    # before the handler coroutine even gets scheduled, so event-loop
    # queueing ahead of admission (the front door) is on the clock.
    # Completion time rides along so the front-door delta below can
    # select the SAME population the server's SLO window holds.
    client_lat: "list[Optional[float]]" = [None] * n_requests
    client_done_t: "list[Optional[float]]" = [None] * n_requests

    async def one(i: int) -> None:
        t_send = time.monotonic()
        try:
            preds[i] = await service.handle(rows[i])
            client_done_t[i] = time.monotonic()
            client_lat[i] = client_done_t[i] - t_send
        except Rejected:
            pass  # counted by service.metrics

    t0 = time.monotonic()
    tasks = []
    for i in range(n_requests):
        delay = arrivals[i] - (time.monotonic() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        else:
            # behind schedule: fire immediately, but still YIELD once per
            # arrival. Real open-loop clients live across a transport, so
            # the server's loop interleaves accepts with its own
            # completion callbacks; an in-process spawn loop that never
            # yields would instead starve every completion behind the
            # whole late burst — a harness artifact that reads as a
            # reject storm the real deployment would not have.
            await asyncio.sleep(0)
        tasks.append(asyncio.ensure_future(one(i)))
    await asyncio.gather(*tasks)
    duration = time.monotonic() - t0
    snap = service.metrics.snapshot()
    done = sorted(v for v in client_lat if v is not None)
    client_ms = {
        "p50": round(nearest_rank(done, 0.50) * 1e3, 3),
        "p95": round(nearest_rank(done, 0.95) * 1e3, 3),
        "p99": round(nearest_rank(done, 0.99) * 1e3, 3),
        "mean": round(sum(done) / len(done) * 1e3, 3) if done else 0.0,
        "max": round(done[-1] * 1e3, 3) if done else 0.0,
    }
    # percentile-level delta vs the server's own e2e. Both sides of the
    # subtraction use the SAME exact nearest-rank convention AND the same
    # population-selection rule: the server side is the SLO window (its
    # last `window` completions, in completion order — NOT the snapshot's
    # log-bucketed histogram, whose ~21%-wide buckets would swamp the
    # sub-ms overhead being measured), so the client side restricts
    # itself to its own last min(n, window) completions by completion
    # time. Past the window span the two sides are then still the same
    # requests — an all-run client percentile minus a window server
    # percentile would measure distribution drift across the run, not the
    # front door. (May still be noisy-negative at sub-ms scale: the two
    # clocks rank the shared population independently.)
    slo = service.metrics.slo
    tail = sorted(lat for _t, lat in
                  sorted((t, lat) for t, lat in
                         zip(client_done_t, client_lat)
                         if lat is not None)[-slo.window:])
    front_door = {name: round(nearest_rank(tail, q) * 1e3
                              - slo.percentile(q) * 1e3, 3)
                  for name, q in (("p50", 0.50), ("p95", 0.95),
                                  ("p99", 0.99))}
    return {
        "offered_rps": round(float(offered_rps), 2),
        "shape": shape,
        "n_requests": int(n_requests),
        "duration_s": round(duration, 4),
        "predictions": preds,
        "client_latency_ms": client_ms,
        "front_door_overhead_ms": front_door,
        **snap,
    }


def run_loadgen(service: ServeService, *, offered_rps: float,
                n_requests: int, seed: int = 0,
                shape: str = "poisson") -> dict:
    """Synchronous wrapper: open-loop run + graceful drain on one fresh
    event loop (the bench / CLI-selftest entry)."""
    from . import run_until_drained
    return run_until_drained(
        service, run_open_loop(service, offered_rps=offered_rps,
                               n_requests=n_requests, seed=seed,
                               shape=shape))
