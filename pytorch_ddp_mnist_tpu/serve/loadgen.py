"""Open-loop Poisson load generator for the serve path.

Closed-loop benchmarks (N workers, each waiting for its response before the
next request — bench.py's other modes) cannot see queueing collapse: the
client slows down exactly when the server does, hiding the latency the real
open-loop world (millions of independent users) would experience. This
generator schedules arrivals on an ABSOLUTE Poisson timeline — exponential
inter-arrival gaps at `offered_rps`, drawn from a seeded numpy Generator —
and fires each request at its scheduled instant whether or not earlier ones
have returned. Latency percentiles therefore include queueing delay, and
offered vs achieved throughput (+ reject rate) exposes saturation honestly.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

import numpy as np

from . import Rejected, ServeService

IN_DIM = 784


def request_rows(n: int, dtype: str = "float32",
                 seed: int = 0) -> np.ndarray:
    """Deterministic synthetic request payloads: (n, 784) pixel rows in the
    engine's input dtype (uint8 raw pixels or pre-normalized float32)."""
    rng = np.random.default_rng(seed)
    raw = rng.integers(0, 256, size=(n, IN_DIM), dtype=np.uint8)
    if dtype == "uint8":
        return raw
    from ..data.mnist import normalize_images
    return normalize_images(raw.reshape(n, 28, 28)).astype(np.float32)


async def run_open_loop(service: ServeService, *, offered_rps: float,
                        n_requests: int, seed: int = 0,
                        rows: Optional[np.ndarray] = None) -> dict:
    """Drive `n_requests` through the service at Poisson-`offered_rps`;
    returns {offered_rps, duration_s, predictions, snapshot...}.

    Arrival times are precomputed (t_i = cumsum of Exp(1/rate) draws) and
    each request fires as its own task at its absolute slot — a slow
    response never delays later arrivals (open loop). Rejects count in the
    metrics and leave a None prediction."""
    if offered_rps <= 0:
        raise ValueError(f"offered_rps must be > 0; got {offered_rps}")
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1; got {n_requests}")
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / offered_rps, size=n_requests)
    arrivals = np.cumsum(gaps)
    if rows is None:
        rows = request_rows(n_requests, service.engine.input_dtype,
                            seed=seed + 1)
    elif len(rows) < n_requests:
        rows = rows[np.arange(n_requests) % len(rows)]

    preds: "list[Optional[int]]" = [None] * n_requests

    async def one(i: int) -> None:
        try:
            preds[i] = await service.handle(rows[i])
        except Rejected:
            pass  # counted by service.metrics

    t0 = time.monotonic()
    tasks = []
    for i in range(n_requests):
        delay = arrivals[i] - (time.monotonic() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        tasks.append(asyncio.ensure_future(one(i)))
    await asyncio.gather(*tasks)
    duration = time.monotonic() - t0
    return {
        "offered_rps": round(float(offered_rps), 2),
        "n_requests": int(n_requests),
        "duration_s": round(duration, 4),
        "predictions": preds,
        **service.metrics.snapshot(),
    }


def run_loadgen(service: ServeService, *, offered_rps: float,
                n_requests: int, seed: int = 0) -> dict:
    """Synchronous wrapper: open-loop run + graceful drain on one fresh
    event loop (the bench / CLI-selftest entry)."""
    from . import run_until_drained
    return run_until_drained(
        service, run_open_loop(service, offered_rps=offered_rps,
                               n_requests=n_requests, seed=seed))
