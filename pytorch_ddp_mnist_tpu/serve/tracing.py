"""Request-scoped tracing: every served request carries a decomposable
latency story.

`serve.rolling_p99_s` (PR 6) says the tail moved; it cannot say WHERE the
time went. Before predicted-p99 admission or least-loaded routing can exist
(ROADMAP item 4), each request needs its end-to-end latency attributed to
the pipeline stages that produced it — the measure-attribute-optimize habit
the MULTICHIP characterization work established for training. This module
is that attribution layer for the serve path:

  * `ServeTracer.begin()` stamps a `request_id` at the front door
    (`ServeService.handle`), before admission — rejected requests already
    leave flight-recorder entries; admitted ones now leave a stage story.
  * A `RequestCtx` rides the request through admission -> batcher pending
    -> flush -> engine -> reply, collecting monotonic stamps at every
    stage boundary. Stage durations (the catalog below) telescope: they
    sum to the request's e2e up to the few instructions between adjacent
    stamps — `trace report --serve` pins the coverage.
  * A `BatchCtx` is stamped per flush (batch_id, bucket, occupancy,
    coalesce reason: size vs deadline vs drain) and every member request
    records its batch_id — N request spans resolve to the ONE batch that
    carried them instead of each pretending it ran alone.
  * Stage durations land in `serve.stage.*_s` registry histograms ALWAYS
    (plain clock reads, the same cost class as the existing per-request
    `record_done`) — the live `{"op": "stats"}` attribution section and
    the Prometheus endpoint need no JSONL trace. Schema-v1 span RECORDS
    are emitted only when `telemetry.enable()` has swapped in a real
    EventTrace; the NullTracer default keeps the disabled path at zero
    extra host syncs and zero span records, pinned the same way as
    training's zero-sync invariant.
  * The slowest-`EXEMPLAR_K` requests (full stage trees) are kept in a
    bounded heap and flushed to the flight recorder at drain — a killed
    or misbehaving server leaves its worst tails in the post-mortem, not
    just the aggregate histogram.

Stage catalog (docs/OBSERVABILITY.md §Request tracing):

    admission    front door -> admission decision
    queue        batcher enqueue -> the flush that took the request
                 (coalescing wait: the max_delay_ms story)
    batch_form   flush start -> rows stacked/validated
    pad_h2d      stacked -> padded to bucket + device_put issued
    compute      dispatch -> logits/preds FETCHED (device execution and
                 the D2H copy are one blocking unit under JAX's async
                 dispatch — splitting them would need an extra
                 block_until_ready on the hot path, so they are reported
                 as one honest stage)
    reply        fetch complete -> the request coroutine resumed with its
                 prediction (future scatter + event-loop wake: loop
                 starvation shows up here, nowhere else)

All stamps use the service's injectable clock, so tests drive attribution
deterministically under a fake clock; at span-emission time durations are
shifted into the perf_counter/time.time frames the schema requires.

Fast-path threading contract (docs/SERVING.md §Fast path): a `BatchCtx`
is stamped from two execution contexts — `t0`/`mark_formed`/`mark_h2d`
on the event loop at flush time, `mark_computed` on the batcher's reply
thread when the fetch lands — but every cross-thread hop is sequenced
(queue put/get, then `call_soon_threadsafe`), so the stamps are monotone
in pipeline order and `batch_end` / `ServeTracer.finish` (span emission,
exemplar heap, stage histograms) still run EXCLUSIVELY on the loop: the
EventTrace writer's single-thread contract is preserved.
"""

from __future__ import annotations

import heapq
import os
import time
from typing import Callable, List, Optional, Tuple

from ..telemetry import events, flight

# The stage catalog — the ONE naming truth shared by the JSONL span attrs,
# the serve.stage.*_s registry histograms, the {"op": "stats"} attribution
# section, and the `trace report --serve` table (they must never disagree).
STAGES = ("admission", "queue", "batch_form", "pad_h2d", "compute", "reply")
# Why a flush fired: full batch (size), oldest request's deadline
# (deadline), graceful drain (drain), or a direct flush() call (manual —
# tests and embedded callers).
COALESCE_REASONS = ("size", "deadline", "drain", "manual")
# Slowest-request exemplars kept for the flight recorder: enough to see a
# pattern in the tail, bounded so a soak never grows it.
EXEMPLAR_K = 8

REQUEST_SPAN = "serve.request"
BATCH_SPAN = "serve.batch"
# the `<stage>_s` attribute spellings, precomputed ONCE: the per-request
# hot path must not pay six f-string formats per completion
STAGE_KEYS = tuple(f"{s}_s" for s in STAGES)
# batch child stage spans, in pipeline order (the checker validates their
# start stamps are monotone in this order within one batch)
BATCH_STAGE_SPANS = ("serve.batch_form", "serve.pad_h2d", "serve.compute")


class BatchCtx:
    """Stage stamps for one batcher flush. Shared by every request the
    flush carried; the engine marks the H2D and compute boundaries."""

    __slots__ = ("batch_id", "coalesce", "clock", "t0", "t_formed",
                 "t_h2d", "t_computed", "bucket", "n_real")

    def __init__(self, batch_id: str, coalesce: str,
                 clock: Callable[[], float]):
        self.batch_id = batch_id
        self.coalesce = coalesce
        self.clock = clock
        self.t0 = clock()
        self.t_formed: Optional[float] = None
        self.t_h2d: Optional[float] = None
        self.t_computed: Optional[float] = None
        self.bucket: Optional[int] = None
        self.n_real: Optional[int] = None

    def mark_formed(self) -> None:
        """Rows stacked + validated (end of batch_form)."""
        self.t_formed = self.clock()

    def mark_h2d(self, bucket: int) -> None:
        """Padded to `bucket` and device transfer issued (end of
        pad_h2d)."""
        self.bucket = int(bucket)
        self.t_h2d = self.clock()

    def mark_computed(self) -> None:
        """Logits/preds fetched back to host (end of compute)."""
        self.t_computed = self.clock()

    @property
    def complete(self) -> bool:
        return (self.t_formed is not None and self.t_h2d is not None
                and self.t_computed is not None)

    def occupancy(self) -> Optional[float]:
        if not self.bucket or self.n_real is None:
            return None
        return self.n_real / self.bucket


class RequestCtx:
    """One request's stamps, front door to reply. `batch` is filled by the
    flush that carried it (None for requests that failed before one)."""

    __slots__ = ("request_id", "t_arrival", "t_admit", "t_enqueue",
                 "batch", "t_done", "ok")

    def __init__(self, request_id: str, t_arrival: float):
        self.request_id = request_id
        self.t_arrival = t_arrival
        self.t_admit: Optional[float] = None
        self.t_enqueue: Optional[float] = None
        self.batch: Optional[BatchCtx] = None
        self.t_done: Optional[float] = None
        self.ok: Optional[bool] = None

    def stage_values(self) -> "Optional[Tuple[float, ...]]":
        """The telescoped per-stage breakdown as a bare tuple in STAGES
        order (None for a request without a fully stamped batch — a
        failed request has no honest decomposition). The hot path
        records from THIS: no dict, no per-request key formatting."""
        b = self.batch
        if (self.t_admit is None or self.t_enqueue is None
                or self.t_done is None or b is None or not b.complete):
            return None
        return (self.t_admit - self.t_arrival,
                b.t0 - self.t_enqueue,
                b.t_formed - b.t0,
                b.t_h2d - b.t_formed,
                b.t_computed - b.t_h2d,
                self.t_done - b.t_computed)

    def stage_durations(self) -> dict:
        """`stage_values` under its `<stage>_s` key spellings (the span
        attrs / exemplar-tree shape); {} when incomplete."""
        vals = self.stage_values()
        return {} if vals is None else dict(zip(STAGE_KEYS, vals))

    def e2e_s(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return self.t_done - self.t_arrival


class ServeTracer:
    """The request/batch stage clock for one ServeService.

    Always active as a STAGE CLOCK (metrics + exemplars are plain host
    arithmetic); emits schema-v1 span records only while the process-wide
    telemetry tracer is a real EventTrace. One instance per service, used
    from the service's single event loop — same threading contract as
    EventTrace itself."""

    def __init__(self, *, clock: Callable[[], float] = time.monotonic,
                 metrics=None, exemplar_k: int = EXEMPLAR_K):
        self.clock = clock
        self.metrics = metrics
        self.exemplar_k = int(exemplar_k)
        self._req_seq = 0
        self._batch_seq = 0
        self._fin_seq = 0
        self._prefix = f"{os.getpid():x}"
        # min-heap of (e2e_s, finish-seq, tree): the K SLOWEST requests
        # ever seen. The finish counter is the tie-breaker — it is unique
        # PER HEAP ENTRY, so equal e2e values (coarse or injected clocks)
        # never fall through to comparing the tree dicts (TypeError)
        self._exemplars: List[Tuple[float, int, dict]] = []

    # -- request lifecycle -------------------------------------------------

    def begin(self) -> RequestCtx:
        """Front door: assign the request_id and stamp arrival."""
        self._req_seq += 1
        return RequestCtx(f"{self._prefix}-{self._req_seq}", self.clock())

    def admitted(self, rctx: RequestCtx) -> None:
        rctx.t_admit = self.clock()

    def enqueued(self, rctx: RequestCtx, t: Optional[float] = None) -> None:
        """Entered the batcher's pending set; `t` lets the batcher reuse
        its own deadline stamp so queue_s and flush_due never disagree."""
        rctx.t_enqueue = self.clock() if t is None else t

    def batch_begin(self, coalesce: str) -> BatchCtx:
        self._batch_seq += 1
        return BatchCtx(f"{self._prefix}-b{self._batch_seq}", coalesce,
                        self.clock)

    def batch_end(self, bctx: BatchCtx, n_real: int) -> None:
        """Flush finished its engine call: record the batch shape and emit
        the batch span (+ stage children) when tracing is enabled."""
        bctx.n_real = int(n_real)
        tracer = events.get_tracer()
        if not tracer.enabled or not bctx.complete:
            return
        off_mono = time.perf_counter() - self.clock()
        off_wall = time.time() - self.clock()
        occ = bctx.occupancy()
        parent = tracer.emit_span(
            BATCH_SPAN,
            t0_mono=bctx.t0 + off_mono, t0_wall=bctx.t0 + off_wall,
            dur_s=bctx.t_computed - bctx.t0,
            attrs={"batch_id": bctx.batch_id, "bucket": bctx.bucket,
                   "n_real": bctx.n_real,
                   "occupancy": round(occ, 4) if occ is not None else None,
                   "coalesce": bctx.coalesce})
        for name, (t0, t1) in zip(BATCH_STAGE_SPANS, (
                (bctx.t0, bctx.t_formed),
                (bctx.t_formed, bctx.t_h2d),
                (bctx.t_h2d, bctx.t_computed))):
            tracer.emit_span(name, t0_mono=t0 + off_mono,
                             t0_wall=t0 + off_wall, dur_s=t1 - t0,
                             parent=parent,
                             attrs={"batch_id": bctx.batch_id})

    def finish(self, rctx: RequestCtx, *, ok: bool) -> None:
        """Reply delivered (or the request failed): stamp completion, feed
        the stage histograms, emit the request span, keep the exemplar.

        This runs once per completed request at peak service rate, so
        the common path (tracing disabled, exemplar heap full) touches
        no dicts and formats no strings: the stage breakdown rides a
        bare tuple into the histograms, and the keyed spellings are only
        built for an admitted exemplar or an enabled span."""
        rctx.t_done = self.clock()
        rctx.ok = ok
        vals = rctx.stage_values() if ok else None
        if vals is not None and self.metrics is not None:
            self.metrics.record_stage_values(vals)
        e2e = rctx.e2e_s()
        if vals is not None and e2e is not None:
            # heap admission FIRST: at high rps most requests cannot
            # displace the minimum, and must not pay tree construction
            full = len(self._exemplars) >= self.exemplar_k
            if not full or e2e > self._exemplars[0][0]:
                self._fin_seq += 1
                tree = {"request_id": rctx.request_id,
                        "e2e_s": round(e2e, 6),
                        "stages": {k: round(v, 6)
                                   for k, v in zip(STAGE_KEYS, vals)},
                        "batch_id": rctx.batch.batch_id,
                        "bucket": rctx.batch.bucket,
                        "coalesce": rctx.batch.coalesce}
                item = (e2e, self._fin_seq, tree)
                if full:
                    heapq.heapreplace(self._exemplars, item)
                else:
                    heapq.heappush(self._exemplars, item)
        tracer = events.get_tracer()
        if not tracer.enabled or e2e is None:
            return
        off_mono = time.perf_counter() - self.clock()
        off_wall = time.time() - self.clock()
        attrs = {"request_id": rctx.request_id, "ok": ok}
        if rctx.batch is not None:
            attrs["batch"] = rctx.batch.batch_id
        if vals is not None:
            attrs.update((k, round(v, 9))
                         for k, v in zip(STAGE_KEYS, vals))
        tracer.emit_span(REQUEST_SPAN,
                         t0_mono=rctx.t_arrival + off_mono,
                         t0_wall=rctx.t_arrival + off_wall,
                         dur_s=e2e, attrs=attrs)

    # -- exemplars ---------------------------------------------------------

    def exemplars(self) -> List[dict]:
        """Slowest-K request trees, slowest first."""
        return [t for _, _, t in sorted(self._exemplars,
                                        key=lambda it: -it[0])]

    def flush_exemplars(self) -> int:
        """Record the slowest-K request trees into the flight-recorder ring
        (drain-time post-mortem evidence; the ring is bounded and writes no
        I/O) and reset the heap. Returns how many were recorded."""
        trees = self.exemplars()
        for rank, tree in enumerate(trees):
            flight.record("serve_exemplar", rank=rank, **tree)
        self._exemplars.clear()
        return len(trees)
