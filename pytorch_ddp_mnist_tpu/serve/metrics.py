"""Serving metrics: latency percentiles, queue depth, batch occupancy,
request counters — one JSON-able snapshot, backed by the shared telemetry
registry.

Since the telemetry/ PR this module owns no metric TYPES: latencies land in
a `telemetry.registry.Histogram` (the log-spaced 2us-floor, 12-bucket/decade
design first built here — constant memory at any request rate, percentile
error bounded by the ~21% bucket ratio, always pessimistic), and the
counters/gauge are registry `Counter`/`Gauge` objects under `serve.*` names.
A `ServeMetrics` constructed with the process-wide registry (what
`cli/serve.py` and `bench.py --mode serve` do) is therefore visible in the
unified `{"op": "stats"}` / artifact snapshot alongside compile counts and
memory gauges; the default is a PRIVATE registry so tests and embedded
services stay hermetic. `snapshot()` keeps its original shape — the serving
dashboard in one dict — unchanged.
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Callable, Optional

from ..telemetry.registry import Histogram, MetricsRegistry
from .tracing import STAGES

# Rolling SLO window length: big enough for a stable p99 (>=100 samples
# past the 99th percentile boundary), small enough that the monitor
# reflects the CURRENT regime, not the whole run — which is the point:
# the cumulative histogram answers "how was the run", this answers "how
# is the service RIGHT NOW".
SLO_WINDOW = 512


def nearest_rank(sorted_vals, q: float) -> float:
    """Exact nearest-rank q-quantile of an ALREADY-SORTED sequence; 0.0
    when empty. The one percentile convention the serve side shares
    (SLOWindow, the loadgen's client-side clock) — two copies of the
    rounding rule would let client-vs-server deltas compare values ranked
    under different conventions. Deliberately the SAME ceil(q*n) formula
    as `telemetry.analysis._percentile` (which must stay framework-free
    and so cannot import this module): `trace report --serve` and the
    live SLO window must never disagree on identical samples."""
    if not sorted_vals:
        return 0.0
    rank = max(1, math.ceil(q * len(sorted_vals)))
    return sorted_vals[min(rank, len(sorted_vals)) - 1]


class SLOWindow:
    """Rolling latency/throughput monitor over the most recent
    completions: exact p99 over a bounded window, and the observed
    service rate (completions per second across the window's wall span).

    This is the live half the admission layer needs next (ROADMAP item 4:
    reject on PREDICTED p99 = queue depth x observed service rate instead
    of raw queue length): the cumulative `serve.latency_s` histogram
    cannot answer it — a morning of fast traffic forever dilutes an
    afternoon collapse. Constant memory (two bounded deques plus one
    cached sorted copy); the sort is paid at most once per COMPLETION
    (the cache invalidates on `record`), never per read — predicted-p99
    admission reads a percentile on every arrival, and re-sorting 512
    floats per offered request would make the admission check inflate the
    very queue delay it predicts."""

    def __init__(self, window: int = SLO_WINDOW):
        if window < 2:
            raise ValueError(f"window must be >= 2; got {window}")
        self.window = int(window)
        self._lat: "collections.deque[float]" = collections.deque(
            maxlen=self.window)
        self._done_t: "collections.deque[float]" = collections.deque(
            maxlen=self.window)
        self._sorted: "Optional[list]" = None
        # The window is WRITTEN by the serve event loop (record() per
        # completion) and READ by the Prometheus scrape thread through the
        # serve.rolling_p99_s / serve.service_rate_rps gauge callables —
        # and percentile()'s "read" also WRITES the sorted cache, so a
        # scrape thread mutates state the loop is concurrently
        # invalidating (the LOCK001 class; prom.py's lock-light-scrape
        # contract assumes reads are READ-only). Under the GIL the
        # observable failure is a stale/over-written cache, not a crash —
        # still a data race by contract, and a real one on free-threaded
        # builds. One lock makes each method atomic; the sort-at-most-
        # once-per-completion cost story is unchanged, and no caller
        # holds this across an await.
        self._lock = threading.Lock()

    def record(self, latency_s: float, t_done: float) -> None:
        with self._lock:
            self._lat.append(float(latency_s))
            self._done_t.append(float(t_done))
            self._sorted = None

    @property
    def n(self) -> int:
        return len(self._lat)

    def percentile(self, q: float) -> float:
        """Exact q-quantile over the window (nearest-rank); 0.0 empty."""
        with self._lock:
            if self._sorted is None:
                self._sorted = sorted(self._lat)
            return nearest_rank(self._sorted, q)

    def service_rate(self) -> Optional[float]:
        """Completions/sec over the window's first..last completion wall
        span; None until two completions exist or when the span is zero
        (injected clocks)."""
        with self._lock:
            if len(self._done_t) < 2:
                return None
            span = self._done_t[-1] - self._done_t[0]
            if span <= 0:
                return None
            return (len(self._done_t) - 1) / span

    def snapshot(self) -> dict:
        rate = self.service_rate()
        return {
            "window_n": self.n,
            "rolling_p50_ms": round(self.percentile(0.50) * 1e3, 3),
            "rolling_p99_ms": round(self.percentile(0.99) * 1e3, 3),
            "service_rate_rps": round(rate, 2) if rate is not None else None,
        }


class LatencyHistogram(Histogram):
    """DEPRECATED thin alias of `telemetry.registry.Histogram` — import
    that instead. Kept so existing callers (and their tests) run unchanged;
    the seconds-unit property spellings survive here."""

    @property
    def total_s(self) -> float:
        return self.total

    @property
    def max_s(self) -> float:
        return self.max

    @property
    def mean_s(self) -> float:
        return self.mean


class ServeMetrics:
    """Aggregated serving counters + latency histogram.

    `depth_fn` (optional) reads the live queue depth at snapshot time, so
    the gauge reflects the instant, not an average. The requests/sec
    counter is completed requests over the first-arrival..last-completion
    wall span — the achieved (not offered) rate. `registry` (optional)
    selects where the `serve.*` metrics live; pass
    `telemetry.get_registry()` to publish into the process-wide snapshot.
    """

    def __init__(self, depth_fn: Optional[Callable[[], int]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        # the deprecated subclass keeps .latency's *_s spellings working
        # for external readers of the old private type; a SECOND metrics
        # instance on the same registry adopts the live histogram instead
        # (get-or-adopt — the same merge semantics the counters below get
        # from the registry's get-or-create)
        try:
            self.latency = LatencyHistogram("serve.latency_s")
            self.registry.register("serve.latency_s", self.latency)
        except ValueError:
            adopted = self.registry.histogram("serve.latency_s")
            if not isinstance(adopted, LatencyHistogram):
                # property-only subclass, no extra state: reclassing keeps
                # the *_s compat spellings working regardless of which
                # owner created the live histogram first
                adopted.__class__ = LatencyHistogram
            self.latency = adopted
        self._completed = self.registry.counter("serve.completed")
        self._rejected = self.registry.counter("serve.rejected")
        self._failed = self.registry.counter("serve.failed")
        self._batches = self.registry.counter("serve.batches")
        self._batched_rows = self.registry.counter("serve.batched_rows")
        self._bucket_rows = self.registry.counter("serve.bucket_rows")
        self.depth_fn = depth_fn
        if depth_fn is not None:
            self.registry.gauge("serve.queue_depth").set_fn(depth_fn)
        self.clock = clock
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        # Rolling SLO monitor (live p99 + observed service rate): always
        # on — two bounded deques cost nothing — and published as registry
        # gauges so the Prometheus endpoint and {"op": "stats"}/"health"
        # read the same live numbers. set_fn (not set) so a scrape reads
        # the instant; a second ServeMetrics on the same registry rebinds
        # the gauges to its own window, same get-or-adopt story as above.
        self.slo = SLOWindow()
        self.registry.gauge("serve.rolling_p99_s").set_fn(
            lambda: self.slo.percentile(0.99) if self.slo.n else None)
        self.registry.gauge("serve.service_rate_rps").set_fn(
            self.slo.service_rate)
        # Request-scoped attribution (serve/tracing.py): one histogram per
        # pipeline stage, fed by ServeTracer.finish on every completed
        # request — the same stage names the JSONL spans and the
        # `trace report --serve` table use. Per-stage observed service
        # rate (completions / stage-busy-seconds = 1 / mean stage time)
        # rides as a derived gauge: the capacity number a fleet router
        # needs per stage, not just end-to-end. `serve.predicted_p99_s`
        # is the admission predictor — rolling p99 + depth / service rate
        # (what a request arriving NOW should expect its tail to be).
        self._stage_hists = {}
        for stage in STAGES:
            h = self.registry.histogram(f"serve.stage.{stage}_s")
            self._stage_hists[stage] = h
            self.registry.gauge(f"serve.stage.{stage}_rate_rps").set_fn(
                (lambda hist: lambda: (hist.n / hist.total
                                       if hist.total > 0 else None))(h))
        # the histograms in STAGES order, for the tuple-shaped hot-path
        # recorder (record_stage_values: one zip, no key lookups)
        self._stage_hist_list = [self._stage_hists[s] for s in STAGES]
        self.registry.gauge("serve.predicted_p99_s").set_fn(
            self.predicted_p99)

    # counter values under their historical attribute names
    @property
    def completed(self) -> int:
        return self._completed.value

    @property
    def rejected(self) -> int:
        return self._rejected.value

    @property
    def failed(self) -> int:
        return self._failed.value

    @property
    def batches(self) -> int:
        return self._batches.value

    @property
    def batched_rows(self) -> int:
        return self._batched_rows.value

    @property
    def bucket_rows(self) -> int:
        return self._bucket_rows.value

    # -- recording hooks --------------------------------------------------

    def record_arrival(self) -> None:
        if self._t_first is None:
            self._t_first = self.clock()

    def record_done(self, latency_s: float) -> None:
        self.latency.record(latency_s)
        self._completed.inc()
        self._t_last = self.clock()
        self.slo.record(latency_s, self._t_last)

    def record_reject(self) -> None:
        self._rejected.inc()
        if self._t_first is None:
            self._t_first = self.clock()
        self._t_last = self.clock()

    def record_failure(self) -> None:
        """A request that was admitted but errored (bad payload, engine
        exception) — neither completed nor rejected, but it DID arrive:
        dropping it from the counters would make a fault storm read as a
        healthy low-traffic interval."""
        self._failed.inc()
        self._t_last = self.clock()

    def record_batch(self, real_rows: int, bucket: int) -> None:
        """One batcher flush: `real_rows` requests padded into `bucket`."""
        self._batches.inc()
        self._batched_rows.inc(real_rows)
        self._bucket_rows.inc(bucket)

    def record_stages(self, stages: dict) -> None:
        """One completed request's per-stage durations (`<stage>_s` keys,
        serve/tracing.py's telescoped breakdown) into the stage
        histograms — the dict-shaped spelling for external feeders; the
        tracer's per-completion hot path uses `record_stage_values`."""
        for stage, hist in self._stage_hists.items():
            v = stages.get(f"{stage}_s")
            if isinstance(v, (int, float)) and v >= 0:
                hist.record(v)

    def record_stage_values(self, values) -> None:
        """One completed request's telescoped stage durations as a bare
        tuple in STAGES order (`tracing.RequestCtx.stage_values`): the
        allocation-light recorder the tracer calls once per completion
        at peak service rate — no dict, no key formatting."""
        for hist, v in zip(self._stage_hist_list, values):
            if v >= 0:
                hist.record(v)

    def predicted_p99(self) -> Optional[float]:
        """The admission predictor (seconds): rolling observed p99 plus
        the time the CURRENT queue takes to drain at the observed service
        rate — what a request arriving this instant should expect its
        tail to be. None until the SLO window has both a percentile and a
        rate (predicting from nothing would reject on a guess)."""
        if not self.slo.n:
            return None
        rate = self.slo.service_rate()
        if rate is None or rate <= 0:
            return None
        depth = self.depth_fn() if self.depth_fn is not None else 0
        return self.slo.percentile(0.99) + depth / rate

    # -- snapshot ---------------------------------------------------------

    def attribution(self) -> dict:
        """The live per-stage latency attribution — stage p50/p99 (ms),
        in pipeline order, plus each stage's SHARE of the telescoped
        per-request time (stage total / sum of stage totals: the stages
        decompose e2e, so the shares sum to 100%) and the current
        predicted p99 — under EXACTLY the stage names the JSONL trace
        uses (serve/tracing.py STAGES): the `{"op": "stats"}` dashboard,
        the bench artifact's `stage_attribution` stamp, and `trace
        report --serve` must never disagree on naming."""
        pred = self.predicted_p99()
        denom = sum(h.total for h in self._stage_hists.values())
        return {
            "stages": {
                stage: {"n": h.n,
                        "p50_ms": round(h.percentile(0.50) * 1e3, 3),
                        "p99_ms": round(h.percentile(0.99) * 1e3, 3),
                        "share_pct": (round(100.0 * h.total / denom, 2)
                                      if denom > 0 else None)}
                for stage, h in self._stage_hists.items() if h.n
            },
            "predicted_p99_ms": (round(pred * 1e3, 3)
                                 if pred is not None else None),
        }

    def snapshot(self) -> dict:
        """JSON-able state: the serving dashboard in one dict."""
        arrived = self.completed + self.rejected + self.failed
        span = ((self._t_last - self._t_first)
                if self._t_first is not None and self._t_last is not None
                else 0.0)
        lat = self.latency
        return {
            "requests": arrived,
            "completed": self.completed,
            "rejected": self.rejected,
            "failed": self.failed,
            "reject_rate": round(self.rejected / arrived, 4) if arrived
                           else 0.0,
            "achieved_rps": round(self.completed / span, 2) if span > 0
                            else None,
            "latency_ms": {
                "p50": round(lat.percentile(0.50) * 1e3, 3),
                "p95": round(lat.percentile(0.95) * 1e3, 3),
                "p99": round(lat.percentile(0.99) * 1e3, 3),
                "mean": round(lat.mean * 1e3, 3),
                "max": round(lat.max * 1e3, 3),
            },
            "batches": self.batches,
            # real rows per flush / bucket rows actually computed: 1.0 means
            # every padded slot carried a request (perfect coalescing)
            "batch_occupancy": round(self.batched_rows / self.bucket_rows, 4)
                               if self.bucket_rows else None,
            "mean_batch_size": round(self.batched_rows / self.batches, 2)
                               if self.batches else None,
            "queue_depth": self.depth_fn() if self.depth_fn else None,
            # the rolling SLO view (recent window), beside the cumulative
            # percentiles above — "right now" vs "the whole run"
            "slo": self.slo.snapshot(),
            # request-scoped tail attribution: per-stage p50/p99 + the
            # predicted p99 admission signal (docs/OBSERVABILITY.md
            # §Request tracing)
            "attribution": self.attribution(),
        }
