"""Serving metrics: latency percentiles, queue depth, batch occupancy,
request counters — one JSON-able snapshot.

Latencies land in a log-spaced histogram (2 us .. ~90 s, 12 buckets/decade)
rather than an unbounded sample list: constant memory at any request rate,
and percentile error bounded by the bucket ratio (~21% of the value —
narrower than the run-to-run noise of any real latency tail). A percentile
reports the winning bucket's UPPER edge, clamped to the recorded max —
deliberately pessimistic, never flattering. Counters follow the reference
framework's conventions (utils/logging: machine-parseable one-line records,
process-0 gating left to the caller).
"""

from __future__ import annotations

import math
import time
from typing import Callable, Optional

# 12 buckets per decade: ratio 10^(1/12) ~ 1.21 between edges.
_BUCKETS_PER_DECADE = 12
_FLOOR_S = 2e-6


class LatencyHistogram:
    """Log-bucketed latency recorder with percentile estimation."""

    def __init__(self):
        self.counts: "dict[int, int]" = {}
        self.n = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def _index(self, seconds: float) -> int:
        if seconds <= _FLOOR_S:
            return 0
        return 1 + int(_BUCKETS_PER_DECADE
                       * math.log10(seconds / _FLOOR_S))

    def _edge(self, index: int) -> float:
        # upper edge of bucket `index` (bucket 0 = [0, _FLOOR_S])
        return _FLOOR_S * 10 ** (index / _BUCKETS_PER_DECADE)

    def record(self, seconds: float) -> None:
        i = self._index(seconds)
        self.counts[i] = self.counts.get(i, 0) + 1
        self.n += 1
        self.total_s += seconds
        self.max_s = max(self.max_s, seconds)

    def percentile(self, q: float) -> float:
        """Estimated q-quantile (q in [0, 1]) in seconds; 0.0 when empty.

        Clamped to the recorded max so a sparse tail bucket cannot report a
        latency larger than any request actually experienced."""
        if self.n == 0:
            return 0.0
        rank = q * self.n
        seen = 0
        for i in sorted(self.counts):
            seen += self.counts[i]
            if seen >= rank:
                return min(self._edge(i), self.max_s)
        return self.max_s

    @property
    def mean_s(self) -> float:
        return self.total_s / self.n if self.n else 0.0


class ServeMetrics:
    """Aggregated serving counters + latency histogram.

    `depth_fn` (optional) reads the live queue depth at snapshot time, so
    the gauge reflects the instant, not an average. The requests/sec
    counter is completed requests over the first-arrival..last-completion
    wall span — the achieved (not offered) rate.
    """

    def __init__(self, depth_fn: Optional[Callable[[], int]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.latency = LatencyHistogram()
        self.depth_fn = depth_fn
        self.clock = clock
        self.completed = 0
        self.rejected = 0
        self.failed = 0
        self.batches = 0
        self.batched_rows = 0
        self.bucket_rows = 0
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None

    # -- recording hooks --------------------------------------------------

    def record_arrival(self) -> None:
        if self._t_first is None:
            self._t_first = self.clock()

    def record_done(self, latency_s: float) -> None:
        self.latency.record(latency_s)
        self.completed += 1
        self._t_last = self.clock()

    def record_reject(self) -> None:
        self.rejected += 1
        if self._t_first is None:
            self._t_first = self.clock()
        self._t_last = self.clock()

    def record_failure(self) -> None:
        """A request that was admitted but errored (bad payload, engine
        exception) — neither completed nor rejected, but it DID arrive:
        dropping it from the counters would make a fault storm read as a
        healthy low-traffic interval."""
        self.failed += 1
        self._t_last = self.clock()

    def record_batch(self, real_rows: int, bucket: int) -> None:
        """One batcher flush: `real_rows` requests padded into `bucket`."""
        self.batches += 1
        self.batched_rows += real_rows
        self.bucket_rows += bucket

    # -- snapshot ---------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able state: the serving dashboard in one dict."""
        arrived = self.completed + self.rejected + self.failed
        span = ((self._t_last - self._t_first)
                if self._t_first is not None and self._t_last is not None
                else 0.0)
        lat = self.latency
        return {
            "requests": arrived,
            "completed": self.completed,
            "rejected": self.rejected,
            "failed": self.failed,
            "reject_rate": round(self.rejected / arrived, 4) if arrived
                           else 0.0,
            "achieved_rps": round(self.completed / span, 2) if span > 0
                            else None,
            "latency_ms": {
                "p50": round(lat.percentile(0.50) * 1e3, 3),
                "p95": round(lat.percentile(0.95) * 1e3, 3),
                "p99": round(lat.percentile(0.99) * 1e3, 3),
                "mean": round(lat.mean_s * 1e3, 3),
                "max": round(lat.max_s * 1e3, 3),
            },
            "batches": self.batches,
            # real rows per flush / bucket rows actually computed: 1.0 means
            # every padded slot carried a request (perfect coalescing)
            "batch_occupancy": round(self.batched_rows / self.bucket_rows, 4)
                               if self.bucket_rows else None,
            "mean_batch_size": round(self.batched_rows / self.batches, 2)
                               if self.batches else None,
            "queue_depth": self.depth_fn() if self.depth_fn else None,
        }
