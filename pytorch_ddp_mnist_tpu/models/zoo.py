"""Model zoo — the workload-scaling knob behind one interface.

The 118k-param reference MLP is too small to be communication-bound in any
interesting way (ROADMAP item 2: at that size every comm saving drowns in
fixed costs, which is exactly how bf16 "compression" measured SLOWEST in
MULTICHIP_r06). This module parameterizes the model family so the perf
work has something to bite on, WITHOUT forking the training stack: every
model is the same (init, apply) functional pair the trainers already
consume, same 784-feature input, same 10-class head, dropout 0.2 after the
first layer (the reference's one dropout site).

    resolve_model("mlp", 1)        -> literally (init_mlp, mlp_apply): the
                                      reference model, bit-for-bit — every
                                      existing parity pin stays anchored
    resolve_model("mlp", N)        -> hidden widths scaled N× (784-128N-
                                      128N-10), same 3-layer topology
    resolve_model("deep_mlp", N)   -> DEEP_MLP_LAYERS hidden layers of
                                      width 128N (out layer bias-free like
                                      the reference head)

`param_scale` multiplies hidden WIDTH, so params grow ~quadratically: the
knob reaches genuinely comm-bound sizes fast (mlp@8 ≈ 1.9M params ≈ 7.4 MB
of f32 gradient on the wire per step under pmean; deep_mlp@8 ≈ 4.0M).
`cli/train.py --model/--param_scale`, `bench.py --mode ddp`, and
`scripts/bench_matrix.py`'s model-size axis all funnel through
`resolve_model`; docs/PERF.md carries the measured strategy × model-size
crossover table.

The Pallas kernels hard-code the reference MLP's dims (VMEM block shapes
are compile-time constants there), so non-default models run the XLA
kernel — callers reject other kernels by name.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .mlp import (DROPOUT_RATE, MLP_DIMS, _torch_linear_init, init_mlp,
                  mlp_apply)

MODELS = ("mlp", "deep_mlp")
DEEP_MLP_LAYERS = 4          # hidden layers of the deep_mlp family
HIDDEN_BASE = MLP_DIMS[1]    # 128 — param_scale multiplies this


class ModelSpec(NamedTuple):
    """One resolved model: everything a trainer needs. `init(key)` builds
    the params pytree; `apply(params, x, train=, dropout_key=,
    dropout_mask=)` has exactly `mlp_apply`'s signature so the step
    builders are model-agnostic."""
    name: str
    param_scale: int
    init: Callable[..., Any]
    apply: Callable[..., jax.Array]
    dims: Tuple[int, ...]


def validate_model(model: str, param_scale: int) -> None:
    """Reject unknown families / non-positive scales by name — the single
    source of truth the CLI, bench, and step builders funnel through."""
    if model not in MODELS:
        raise ValueError(f"unknown model {model!r}; choose one of {MODELS}")
    if not isinstance(param_scale, int) or param_scale < 1:
        raise ValueError(f"param_scale must be an int >= 1 (multiplies the "
                         f"{HIDDEN_BASE}-unit hidden width); got "
                         f"{param_scale!r}")


def is_default_model(model: str, param_scale: int) -> bool:
    return model == "mlp" and param_scale == 1


def init_deep_mlp(key: jax.Array, width: int, depth: int,
                  dtype=jnp.float32) -> dict:
    """784 -> depth × width hidden (ReLU; dropout after the first, like
    the reference) -> 10 bias-free head, torch Linear init bounds
    throughout (the same `_torch_linear_init` as the reference MLP)."""
    keys = jax.random.split(key, depth + 1)
    params = {}
    fan_in = MLP_DIMS[0]
    for i in range(depth):
        params[f"h{i}"] = _torch_linear_init(keys[i], fan_in, width,
                                             bias=True, dtype=dtype)
        fan_in = width
    params["out"] = _torch_linear_init(keys[depth], fan_in, MLP_DIMS[3],
                                       bias=False, dtype=dtype)
    return params


def deep_mlp_apply(params: dict, x: jax.Array, *, train: bool = False,
                   dropout_key: jax.Array | None = None,
                   dropout_mask: jax.Array | None = None) -> jax.Array:
    """Forward pass of the deep family — mlp_apply's exact contract
    (compute dtype follows x, dropout only after the first hidden layer,
    exactly one of key/mask in train mode)."""
    dt = x.dtype
    depth = sum(1 for k in params if k.startswith("h"))
    h = x
    for i in range(depth):
        layer = params[f"h{i}"]
        h = h @ layer["w"].astype(dt) + layer["b"].astype(dt)
        h = jax.nn.relu(h)
        if i == 0 and train:
            keep = 1.0 - DROPOUT_RATE
            if (dropout_key is None) == (dropout_mask is None):
                raise ValueError("train=True requires exactly one of "
                                 "dropout_key / dropout_mask")
            if dropout_mask is not None:
                h = h * (dropout_mask.astype(dt)
                         * jnp.asarray(1.0 / keep, dt))
            else:
                mask = jax.random.bernoulli(dropout_key, keep, h.shape)
                h = jnp.where(mask, h / jnp.asarray(keep, dt),
                              jnp.zeros((), dt))
    return h @ params["out"]["w"].astype(dt)


def resolve_model(model: str = "mlp", param_scale: int = 1) -> ModelSpec:
    """(init, apply) for the named family at the given width scale.

    The default resolves to the UNTOUCHED reference pair (same function
    objects, not wrappers), so every bitwise pin built on init_mlp /
    mlp_apply keeps holding by construction."""
    validate_model(model, param_scale)
    if model == "mlp":
        if param_scale == 1:
            return ModelSpec("mlp", 1, init_mlp, mlp_apply, MLP_DIMS)
        dims = (MLP_DIMS[0], HIDDEN_BASE * param_scale,
                HIDDEN_BASE * param_scale, MLP_DIMS[3])
        return ModelSpec("mlp", param_scale,
                         partial(init_mlp, dims=dims), mlp_apply, dims)
    width = HIDDEN_BASE * param_scale
    dims = (MLP_DIMS[0],) + (width,) * DEEP_MLP_LAYERS + (MLP_DIMS[3],)
    return ModelSpec("deep_mlp", param_scale,
                     partial(init_deep_mlp, width=width,
                             depth=DEEP_MLP_LAYERS),
                     deep_mlp_apply, dims)
