from .mlp import MLP_DIMS, init_mlp, mlp_apply, param_count

__all__ = ["MLP_DIMS", "init_mlp", "mlp_apply", "param_count"]
