from .mlp import MLP_DIMS, init_mlp, mlp_apply, param_count
from .zoo import MODELS, ModelSpec, is_default_model, resolve_model, \
    validate_model

__all__ = ["MLP_DIMS", "init_mlp", "mlp_apply", "param_count",
           "MODELS", "ModelSpec", "is_default_model", "resolve_model",
           "validate_model"]
