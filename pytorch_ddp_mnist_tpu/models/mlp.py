"""The MNIST MLP, as a functional JAX model.

Capability parity with the reference model (see /root/reference
ddp_tutorial_cpu.py:43-53, identical copies at ddp_tutorial_multi_gpu.py:52-62,
mnist_cpu_mp.py:344-354, mnist_pnetcdf_cpu.py:66-76,
mnist_pnetcdf_cpu_mp.py:412-422):

    Linear(784, 128) -> ReLU -> Dropout(0.2) -> Linear(128, 128) -> ReLU
        -> Linear(128, 10, bias=False)

Parity points the implementation preserves:
  * dropout ONLY after the first layer, rate 0.2, active only in train mode;
  * NO bias on the final (output) layer;
  * torch's default Linear initialization semantics: weight and bias both
    drawn from U(-1/sqrt(fan_in), +1/sqrt(fan_in)) (kaiming_uniform with
    a=sqrt(5) reduces to that bound for the weight).

The model is a params pytree + pure apply function, the idiomatic JAX shape:
everything jits, vmaps, and shards without a module system in the way. Params
are stored in float32; `mlp_apply` computes in the dtype of `x` so a bfloat16
compute path (MXU-friendly) is a cast at the call site, not a model change.
"""

from __future__ import annotations

import math
from typing import Any, Dict

import jax
import jax.numpy as jnp

# (in_features, hidden, hidden, classes) — reference ddp_tutorial_cpu.py:45-51.
MLP_DIMS = (784, 128, 128, 10)
DROPOUT_RATE = 0.2

Params = Dict[str, Dict[str, Any]]


def _torch_linear_init(key: jax.Array, fan_in: int, fan_out: int, *, bias: bool,
                       dtype=jnp.float32) -> Dict[str, jax.Array]:
    """U(-1/sqrt(fan_in), +1/sqrt(fan_in)) for weight (and bias if present).

    Matches torch.nn.Linear.reset_parameters semantics (kaiming_uniform with
    a=sqrt(5) => bound sqrt(6/(6*fan_in)) = 1/sqrt(fan_in)).
    Weight is stored as (fan_in, fan_out) so the forward pass is x @ w — the
    natural MXU layout — rather than torch's (out, in) + transpose.
    """
    bound = 1.0 / math.sqrt(fan_in)
    wkey, bkey = jax.random.split(key)
    layer = {
        "w": jax.random.uniform(wkey, (fan_in, fan_out), dtype, -bound, bound)
    }
    if bias:
        layer["b"] = jax.random.uniform(bkey, (fan_out,), dtype, -bound, bound)
    return layer


def init_mlp(key: jax.Array, dtype=jnp.float32, dims=MLP_DIMS) -> Params:
    """Initialize the 784-128-128-10 MLP params pytree. `dims` widens the
    two hidden layers for the scaled model family (models/zoo.py
    `--param_scale`); the default is bit-for-bit the reference init."""
    d0, d1, d2, d3 = dims
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "fc1": _torch_linear_init(k1, d0, d1, bias=True, dtype=dtype),
        "fc2": _torch_linear_init(k2, d1, d2, bias=True, dtype=dtype),
        # Output layer has bias=False in the reference (ddp_tutorial_cpu.py:51).
        "fc3": _torch_linear_init(k3, d2, d3, bias=False, dtype=dtype),
    }


def mlp_apply(params: Params, x: jax.Array, *, train: bool = False,
              dropout_key: jax.Array | None = None,
              dropout_mask: jax.Array | None = None) -> jax.Array:
    """Forward pass. `x` is (batch, 784) (callers flatten, matching the
    reference's x.view(B, -1) at ddp_tutorial_multi_gpu.py:90).

    In train mode a dropout mask is drawn from `dropout_key`; each data-parallel
    replica must pass a distinct key (DDP ranks draw independent masks — see
    SURVEY.md §7 parity item 4). Alternatively `dropout_mask` streams a
    pre-drawn {0,1} mask of `h`'s shape (the `--dropout_rng torch` path:
    masks drawn host-side from torch's bitwise CPU bernoulli stream,
    parallel/torch_rng.py); exactly one of the two must be given in train
    mode. Compute dtype follows x; params are cast to it.
    """
    dt = x.dtype
    h = x @ params["fc1"]["w"].astype(dt) + params["fc1"]["b"].astype(dt)
    h = jax.nn.relu(h)
    if train:
        keep = 1.0 - DROPOUT_RATE
        if (dropout_key is None) == (dropout_mask is None):
            raise ValueError("train=True requires exactly one of "
                             "dropout_key / dropout_mask")
        if dropout_mask is not None:
            # torch applies input * mask * (1/keep); mask∈{0,1} and 1/0.8
            # is exactly representable, so the product order is bit-inert.
            h = h * (dropout_mask.astype(dt) * jnp.asarray(1.0 / keep, dt))
        else:
            mask = jax.random.bernoulli(dropout_key, keep, h.shape)
            # Inverted dropout, same as torch.nn.Dropout: scale kept units
            # by 1/keep.
            h = jnp.where(mask, h / jnp.asarray(keep, dt), jnp.zeros((), dt))
    h = h @ params["fc2"]["w"].astype(dt) + params["fc2"]["b"].astype(dt)
    h = jax.nn.relu(h)
    return h @ params["fc3"]["w"].astype(dt)


def param_count(params: Params) -> int:
    return sum(int(p.size) for p in jax.tree_util.tree_leaves(params))
