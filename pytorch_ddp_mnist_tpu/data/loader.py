"""Batch loaders: in-memory, disk-sharded NetCDF, and device prefetch.

Reference data plane: `DataLoader(dataset, batch_size, sampler)` feeding the
train loop with `(x, y)` batches, `.to(device, non_blocking=True)` per batch
(ddp_tutorial_multi_gpu.py:33-36,87-88); the PnetCDF variant reads each
sample independently from the shared .nc file inside `__getitem__`
(mnist_pnetcdf_cpu_mp.py:39-49).

XLA-native reshaping:
  * STATIC batch shapes — torch tolerates a short final batch; XLA would
    recompile for it. The final partial batch is padded by wrapping to the
    shard's head (the same repetition trick DistributedSampler itself uses to
    pad the epoch, SURVEY.md §7 item 3), keeping one compiled program.
  * labels are cast uint8 -> int32 at batch assembly (SURVEY.md §7 item 9:
    the PnetCDF path yields uint8 0-d labels; CE targets need integers).
  * `device_prefetch` overlaps the NEXT batch's host->device transfer with
    the current step — the MpDeviceLoader / non_blocking=True analog: XLA
    device_put is async, so putting batch k+1 before blocking on step k
    double-buffers HBM transfers.
  * `NetCDFShardLoader` gathers each batch's rows straight from the .nc file
    (independent-I/O analog) through the native C++ core when available.
"""

from __future__ import annotations

import math
from typing import Callable, Iterator, Optional, Tuple

import numpy as np

from .mnist import normalize_images


def _batched_indices(sampler, batch_size: int) -> Iterator[np.ndarray]:
    """Split this rank's shard into fixed-size index batches, wrap-padding
    the final one so every batch has the same (compiled-once) shape."""
    shard = np.asarray(sampler.indices())
    for start in range(0, shard.size, batch_size):
        b = shard[start:start + batch_size]
        if b.size < batch_size:
            b = np.concatenate([b, np.resize(shard, batch_size - b.size)])
        yield b


class BatchLoader:
    """In-memory loader: yields (x, y) batches for `sampler`'s shard.

    `images` is the pre-normalized (n, 784) float32 array; `labels` any
    integer array, cast to int32 per batch.
    """

    def __init__(self, images: np.ndarray, labels: np.ndarray, sampler,
                 batch_size: int):
        self.images = np.ascontiguousarray(images)
        self.labels = np.asarray(labels)
        self.sampler = sampler
        self.batch_size = int(batch_size)

    def __len__(self) -> int:
        return math.ceil(len(self.sampler) / self.batch_size)

    def read_batch(self, b: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """One index batch -> (x, y) — the pipeline-capable load half
        (pipeline/reader.py): stateless per batch, safe from worker
        threads (numpy gathers share no cursor)."""
        return self.images[b], self.labels[b].astype(np.int32)

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        return self.iter_from(0)

    def iter_from(self, start: int) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Iterate this epoch's batches from batch index `start` — the
        mid-epoch resume path (train.loop.fit start_offset): skipped
        batches' index rows are simply dropped, never gathered."""
        from ..utils import faultpoints
        for i, b in enumerate(_batched_indices(self.sampler, self.batch_size)):
            if i < start:
                continue
            # chaos hook: PDMT_FAULT=loader_stall:batch=K:delay_s=S stalls
            # this batch — the injected I/O hiccup the data_wait telemetry
            # phase exists to expose (no-op when no faults are installed)
            faultpoints.fire("loader_next", batch=i)
            yield self.read_batch(b)


class NetCDFShardLoader:
    """Disk-sharded loader: each batch is a row gather from the shared .nc
    file for THIS rank's sampler indices only — the PnetCDF independent-I/O
    analog (mnist_pnetcdf_cpu_mp.py:32,46), minus MPI: plain sharded preads
    via the native C++ core (pure-Python fallback when no toolchain).

    Batches are bit-identical to BatchLoader over the same sampler state:
    gather -> normalize is elementwise, so normalize(all)[idx] ==
    normalize(gather(idx)).

    `sampler` may be None at construction (so `num_samples` can be read to
    size the sampler first) but must be assigned before iterating.

    `num_workers > 0` enables readahead: that many threads gather+normalize
    upcoming batches into bounded queues while the consumer trains — the
    capability of the reference's persistent DataLoader workers
    (mnist_pnetcdf_cpu.py:58-60), as threads instead of forked processes
    (the reference itself must force num_workers=0 in its DDP variant
    because MPI handles can't fork, mnist_pnetcdf_cpu_mp.py:396-401; threads
    sidestep that entirely). Batch order is identical to the synchronous
    path: worker w produces batches w, w+N, ... and the consumer round-
    robins the queues.

    Labels are cached whole at construction (one coalesced pread of n bytes
    — the serial reference's collective label read, mnist_pnetcdf_cpu.py:47);
    per-batch disk work is the image gather only.
    """

    def __init__(self, path: str, sampler=None, *, batch_size: int,
                 num_workers: int = 0):
        self.path = path
        self.sampler = sampler
        self.batch_size = int(batch_size)
        self.num_workers = int(num_workers)
        from .native import NativeReader, native_available
        if native_available():
            self._reader = NativeReader(path)
            self._read = self._reader.read
        else:
            from .netcdf import NetCDFReader
            self._reader = NetCDFReader(path)
            self._read = self._reader.read
        shape = (self._reader.variables["images"][0]
                 if isinstance(self._reader.variables["images"], tuple)
                 else self._reader.variables["images"].shape)
        self.num_samples = int(shape[0])
        self._labels = self._read("labels")  # whole-variable coalesced read

    def __len__(self) -> int:
        return math.ceil(len(self.sampler) / self.batch_size)

    def _load(self, b: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        images = self._read("images", b)
        return normalize_images(images), self._labels[b].astype(np.int32)

    def read_batch(self, b: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """One index batch -> (x, y) — the pipeline-capable load half
        (pipeline/reader.py). Safe from worker threads: both the native
        core and the pure-Python reader gather by POSITIONAL preads
        (no shared file cursor), the same property the in-loader
        readahead threads below already rely on."""
        return self._load(b)

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        yield from self.iter_from(0)

    def iter_from(self, start: int) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Iterate from batch index `start` (mid-epoch resume): skipped
        batches are dropped from the index list BEFORE any disk gather —
        neither this thread nor the readahead workers ever read them."""
        from ..utils import faultpoints
        batches = list(_batched_indices(self.sampler, self.batch_size))[start:]
        if self.num_workers <= 0 or len(batches) <= 1:
            for i, b in enumerate(batches, start=start):
                # same loader_stall chaos hook as BatchLoader — fired at
                # the CONSUMER so the stall lands in data_wait either way
                faultpoints.fire("loader_next", batch=i)
                yield self._load(b)
            return
        yield from self._iter_readahead(batches)

    def _iter_readahead(self, batches):
        """N worker threads, bounded queues, strict batch order."""
        import queue
        import threading

        nw = min(self.num_workers, len(batches))
        qs = [queue.Queue(maxsize=2) for _ in range(nw)]
        stop = threading.Event()

        def work(w: int) -> None:
            try:
                for i in range(w, len(batches), nw):
                    item = self._load(batches[i])
                    while not stop.is_set():
                        try:
                            qs[w].put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:  # propagate into the consumer
                qs[w].put(e)

        threads = [threading.Thread(target=work, args=(w,), daemon=True)
                   for w in range(nw)]
        for t in threads:
            t.start()
        try:
            from ..utils import faultpoints
            for i in range(len(batches)):
                faultpoints.fire("loader_next", batch=i)
                item = qs[i % nw].get()
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            for q in qs:  # unblock any worker parked in put()
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass
            for t in threads:
                t.join(timeout=5)


def device_prefetch(loader, sharding=None,
                    put: Optional[Callable] = None):
    """Iterate a loader with one batch of transfer lookahead.

    `put` places a host batch on device(s) (e.g. the DP global-batch
    assembler); `sharding` is a shorthand for jax.device_put with that
    sharding; default is plain device_put. Dispatching batch k+1's transfer
    before batch k's step is consumed lets XLA overlap PCIe/HBM copies with
    compute — the reference gets the same overlap from
    `non_blocking=True` + CUDA streams (ddp_tutorial_multi_gpu.py:87-88).

    Thin alias over `pipeline.prefetch(depth=1)` — the generalized depth-K
    stage, which also fixed this function's old teardown: a producer
    exception mid-iteration now drains the pending transfer (so its own
    async failure can't be silently dropped with the abandoned array) and
    re-raises the ORIGINAL error deterministically.
    """
    from ..pipeline.prefetch import prefetch
    return prefetch(loader, depth=1, sharding=sharding, put=put)
