"""L2 data pipeline (SURVEY.md §1): IDX + NetCDF parsing, MNIST loading with
synthetic fallback, sharded batch loaders, device prefetch, and the native
C++ reader core — the capabilities of the reference's torchvision path
(ddp_tutorial_cpu.py:12-49) and PnetCDF/MPI-IO path
(mnist_pnetcdf_cpu[_mp].py), re-designed for TPU hosts."""

from .idx import read_idx, write_idx
from .mnist import (MNIST_MEAN, MNIST_STD, Split, get_mnist, load_mnist,
                    normalize_images, synthetic_mnist)
from .loader import BatchLoader, NetCDFShardLoader, device_prefetch
from .download import DownloadError, download_file, download_mnist

__all__ = [
    "read_idx", "write_idx",
    "MNIST_MEAN", "MNIST_STD", "Split", "get_mnist", "load_mnist",
    "normalize_images", "synthetic_mnist",
    "BatchLoader", "NetCDFShardLoader", "device_prefetch",
    "DownloadError", "download_file", "download_mnist",
]
