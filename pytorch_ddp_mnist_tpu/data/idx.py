"""IDX (MNIST ubyte) format reader/writer.

The reference parses raw IDX files in its converter notebook
(mnist_to_netcdf.ipynb cell-2, `MnistDataloader.read_images_labels`):
big-endian headers via struct.unpack('>II'/'>IIII') and explicit magic checks
(2049 for labels, 2051 for images) — the only asserts in the whole reference
(SURVEY.md §4 item 3). This module implements the full IDX grammar, both
directions, so the framework can read torchvision-style cached MNIST and
round-trip its own files without torch.

IDX layout: 2 zero bytes, 1 dtype code byte, 1 ndims byte, then ndims
big-endian uint32 dimension sizes, then the array data big-endian.
"""

from __future__ import annotations

import gzip

import numpy as np

# dtype code byte -> numpy dtype (big-endian on disk)
_DTYPE_OF_CODE = {
    0x08: "u1", 0x09: "i1", 0x0B: ">i2", 0x0C: ">i4",
    0x0D: ">f4", 0x0E: ">f8",
}
_CODE_OF_KIND = {
    "uint8": 0x08, "int8": 0x09, "int16": 0x0B, "int32": 0x0C,
    "float32": 0x0D, "float64": 0x0E,
}


def _open_maybe_gz(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    f = open(path, "rb")
    head = f.read(2)
    f.seek(0)
    if head == b"\x1f\x8b":  # gzip payload without the extension
        f.close()
        return gzip.open(path, "rb")
    return f


def read_idx(path: str) -> np.ndarray:
    """Parse one IDX file (optionally gzipped) into a native-endian array.

    Raises ValueError on a bad magic, like the notebook's
    `raise ValueError('Magic number mismatch...')`.
    """
    with _open_maybe_gz(path) as f:
        header = f.read(4)
        if len(header) < 4 or header[0] != 0 or header[1] != 0 \
                or header[2] not in _DTYPE_OF_CODE:
            raise ValueError(f"{path}: bad IDX magic {header[:4]!r}")
        dtype = np.dtype(_DTYPE_OF_CODE[header[2]])
        ndims = header[3]
        if ndims == 0:
            raise ValueError(f"{path}: bad IDX magic (zero dimensions)")
        shape = tuple(
            int.from_bytes(f.read(4), "big") for _ in range(ndims))
        count = int(np.prod(shape, dtype=np.int64))
        raw = f.read(count * dtype.itemsize)
        if len(raw) != count * dtype.itemsize:
            raise ValueError(f"{path}: truncated IDX data")
        arr = np.frombuffer(raw, dtype).reshape(shape)
        return arr.astype(dtype.newbyteorder("="), copy=True)


def write_idx(path: str, arr: np.ndarray) -> None:
    """Write an array as an IDX file (magic 2051 for 3-d uint8 images,
    2049 for 1-d uint8 labels, per the notebook's checks)."""
    arr = np.asarray(arr)
    code = _CODE_OF_KIND.get(arr.dtype.name)
    if code is None:
        raise ValueError(f"IDX cannot store dtype {arr.dtype}")
    with open(path, "wb") as f:
        f.write(bytes([0, 0, code, arr.ndim]))
        for d in arr.shape:
            f.write(int(d).to_bytes(4, "big"))
        f.write(arr.astype(arr.dtype.newbyteorder(">")).tobytes())
