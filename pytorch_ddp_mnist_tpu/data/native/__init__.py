"""ctypes binding for the native C++ reader core (reader.cc).

Builds `_reader.so` on demand with the system C++ toolchain (g++ by default,
$CXX to override; `make` in this directory does the same build) and falls
back cleanly when no toolchain is present: `native_available()` gates every
use, `native_build_error()` reports why it is off, and the pure-Python
parsers in data/netcdf.py + data/idx.py remain the behavioral source of
truth (tests/test_native.py asserts byte equality between the two).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "reader.cc")
_SO = os.path.join(_HERE, "_reader.so")

_lock = threading.Lock()
_lib = None
_build_error: Optional[str] = None
_tried = False

# nc_type -> native numpy dtype (the C core already swapped to host order)
_NP_OF_NC = {1: "i1", 2: "S1", 3: "i2", 4: "i4", 5: "f4", 6: "f8",
             7: "u1", 8: "u2", 9: "u4", 10: "i8", 11: "u8"}


def _compile() -> None:
    cxx = os.environ.get("CXX", "g++")
    tmp = _SO + f".tmp.{os.getpid()}"
    cmd = [cxx, "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", tmp]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"{' '.join(cmd)} failed:\n{proc.stderr.strip()}")
    os.replace(tmp, _SO)  # atomic under concurrent builders


def _load():
    global _lib, _build_error, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _compile()
            lib = ctypes.CDLL(_SO)
            lib.nr_open.restype = ctypes.c_void_p
            lib.nr_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                    ctypes.c_int]
            lib.nr_close.argtypes = [ctypes.c_void_p]
            lib.nr_nvars.restype = ctypes.c_int
            lib.nr_nvars.argtypes = [ctypes.c_void_p]
            lib.nr_var_info.restype = ctypes.c_int
            lib.nr_var_info.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
                ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
            lib.nr_read_rows.restype = ctypes.c_int
            lib.nr_read_rows.argtypes = [
                ctypes.c_void_p, ctypes.c_int,
                ctypes.POINTER(ctypes.c_longlong), ctypes.c_longlong,
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
            _lib = lib
        except Exception as e:  # toolchain missing, compile error, bad .so
            _build_error = str(e)
            _lib = None
    return _lib


def native_available() -> bool:
    return _load() is not None


def native_build_error() -> Optional[str]:
    _load()
    return _build_error


class NativeReader:
    """One open file (IDX or NetCDF). Context manager; thread-safe reads
    (the core uses pread on a shared fd).

    `variables` maps name -> (shape tuple, nc_type). `read(name, idx)`
    gathers leading-dim rows host-endian; `read(name)` reads the whole
    variable (a single coalesced pread).
    """

    def __init__(self, path: str):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native reader unavailable: {_build_error}")
        self._lib = lib
        err = ctypes.create_string_buffer(1024)
        self._h = lib.nr_open(os.fsencode(path), err, len(err))
        if not self._h:
            raise ValueError(err.value.decode(errors="replace"))
        self.path = path
        self.variables: Dict[str, Tuple[Tuple[int, ...], int]] = {}
        self._index: Dict[str, int] = {}
        shape = (ctypes.c_longlong * 16)()
        ndims = ctypes.c_int()
        nc_type = ctypes.c_int()
        name = ctypes.create_string_buffer(256)
        for i in range(lib.nr_nvars(self._h)):
            if lib.nr_var_info(self._h, i, name, len(name), shape, 16,
                               ctypes.byref(ndims), ctypes.byref(nc_type)):
                raise RuntimeError(f"{path}: nr_var_info({i}) failed")
            nm = name.value.decode()
            self.variables[nm] = (
                tuple(int(shape[d]) for d in range(ndims.value)),
                int(nc_type.value))
            self._index[nm] = i

    def close(self) -> None:
        if self._h:
            self._lib.nr_close(self._h)
            self._h = None

    def __enter__(self) -> "NativeReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def read(self, name: str,
             indices: Optional[Sequence[int]] = None) -> np.ndarray:
        if self._h is None:
            raise ValueError(f"{self.path}: reader is closed")
        shape, nc_type = self.variables[name]  # KeyError on unknown name
        if indices is None:
            idx = np.arange(shape[0] if shape else 1, dtype=np.int64)
            out_shape = shape
        else:
            idx = np.ascontiguousarray(indices, np.int64)
            if not shape:
                raise IndexError(f"variable {name!r} is a scalar")
            if idx.size and (idx.min() < 0 or idx.max() >= shape[0]):
                raise IndexError(
                    f"indices out of range [0, {shape[0]}) for {name!r}")
            out_shape = (idx.size,) + shape[1:]
        out = np.empty(out_shape, dtype=_NP_OF_NC[nc_type])
        if out.size == 0:
            return out
        err = ctypes.create_string_buffer(1024)
        rc = self._lib.nr_read_rows(
            self._h, self._index[name],
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
            len(idx), out.ctypes.data_as(ctypes.c_void_p), err, len(err))
        if rc != 0:
            raise IOError(
                f"{self.path}: {err.value.decode(errors='replace')}")
        return out
