// Native reader core: IDX + NetCDF classic/CDF-5 header parse and sharded
// row gathers. This is the framework's analog of the reference's native
// PnetCDF C library (SURVEY.md §2.12): where each reference rank issues
// independent MPI-IO reads for its sampler's indices
// (mnist_pnetcdf_cpu_mp.py:32,46), here each host process gathers its rows
// with plain pread(2) — contiguous index runs are coalesced into single
// reads, large gathers fan out over a thread pool, and multi-byte types are
// byte-swapped from the format's big-endian to host order.
//
// The grammar matches data/netcdf.py (the format source of truth, tested
// against it): CDF-5 widens every NON_NEG size field to INT64; offsets are
// 32-bit only in CDF-1. Record (unlimited) dimensions are not supported.
//
// C ABI only (consumed via ctypes): nr_open / nr_close / nr_nvars /
// nr_var_info / nr_read_rows.

#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr int kMaxHeader = 1 << 20;  // converter headers are ~100s of bytes

struct Var {
  std::string name;
  std::vector<long long> shape;
  int nc_type = 0;      // netcdf type ids; IDX dtypes are mapped onto them
  int itemsize = 0;
  long long begin = 0;
  long long row_bytes = 0;  // itemsize * prod(shape[1:])
};

struct File {
  int fd = -1;
  std::vector<Var> vars;
  ~File() {
    if (fd >= 0) close(fd);
  }
};

void set_err(char* err, int cap, const std::string& msg) {
  if (err && cap > 0) {
    std::snprintf(err, static_cast<size_t>(cap), "%s", msg.c_str());
  }
}

int nc_itemsize(int nc_type) {
  switch (nc_type) {
    case 1: case 2: case 7: return 1;   // byte, char, ubyte
    case 3: case 8: return 2;           // short, ushort
    case 4: case 5: case 9: return 4;   // int, float, uint
    case 6: case 10: case 11: return 8; // double, int64, uint64
    default: return 0;
  }
}

// Big-endian cursor over the in-memory header buffer.
struct Cur {
  const unsigned char* p;
  size_t len, pos = 0;
  bool ok = true;

  uint64_t be(int n) {
    if (!ok || pos + n > len) {
      ok = false;
      return 0;
    }
    uint64_t v = 0;
    for (int i = 0; i < n; i++) v = (v << 8) | p[pos + i];
    pos += n;
    return v;
  }

  std::string name(int W) {
    uint64_t n = be(W);
    if (!ok || pos + n > len) {
      ok = false;
      return "";
    }
    std::string s(reinterpret_cast<const char*>(p) + pos, n);
    pos += (n + 3) & ~3ULL;  // namestring is padded to a 4-byte boundary
    if (pos > len) ok = false;
    return s;
  }

  void skip_atts(int W) {
    uint64_t tag = be(4), n = be(W);
    if (tag == 0 && n == 0) return;
    if (tag != 0x0C) {
      ok = false;
      return;
    }
    for (uint64_t i = 0; i < n && ok; i++) {
      name(W);
      uint64_t t = be(4), ne = be(W);
      uint64_t bytes = ne * static_cast<uint64_t>(nc_itemsize(t));
      pos += (bytes + 3) & ~3ULL;
      if (pos > len) ok = false;
    }
  }
};

long long prod_tail(const std::vector<long long>& shape) {
  long long p = 1;
  for (size_t i = 1; i < shape.size(); i++) p *= shape[i];
  return p;
}

bool parse_netcdf(File* f, const unsigned char* h, size_t hlen, int ver,
                  std::string* err) {
  const int W = ver == 5 ? 8 : 4;
  const int OFF = ver == 1 ? 4 : 8;
  Cur c{h, hlen, 4};
  c.be(W);  // numrecs (no record vars in the converter schema)

  std::vector<long long> dimlen;
  uint64_t tag = c.be(4), n = c.be(W);
  if (tag == 0x0A) {
    for (uint64_t i = 0; i < n && c.ok; i++) {
      c.name(W);
      dimlen.push_back(static_cast<long long>(c.be(W)));
    }
  } else if (tag != 0 || n != 0) {
    *err = "header: bad dim_list tag";
    return false;
  }
  c.skip_atts(W);

  tag = c.be(4);
  n = c.be(W);
  if (tag == 0x0B) {
    for (uint64_t i = 0; i < n && c.ok; i++) {
      Var v;
      v.name = c.name(W);
      uint64_t nd = c.be(W);
      for (uint64_t d = 0; d < nd && c.ok; d++) {
        uint64_t id = c.be(W);
        if (id >= dimlen.size()) {
          *err = "header: dimid out of range";
          return false;
        }
        v.shape.push_back(dimlen[id]);
      }
      c.skip_atts(W);
      v.nc_type = static_cast<int>(c.be(4));
      v.itemsize = nc_itemsize(v.nc_type);
      c.be(W);  // vsize (recomputed from the shape)
      v.begin = static_cast<long long>(c.be(OFF));
      if (v.itemsize == 0) {
        *err = "header: unsupported nc_type";
        return false;
      }
      v.row_bytes = v.itemsize * (v.shape.empty() ? 1 : prod_tail(v.shape));
      f->vars.push_back(std::move(v));
    }
  } else if (tag != 0 || n != 0) {
    *err = "header: bad var_list tag";
    return false;
  }
  if (!c.ok) {
    *err = "header: truncated or malformed";
    return false;
  }
  return true;
}

bool parse_idx(File* f, const unsigned char* h, size_t hlen,
               std::string* err) {
  // IDX dtype code -> (nc_type, itemsize)
  int nc_type;
  switch (h[2]) {
    case 0x08: nc_type = 7; break;   // ubyte
    case 0x09: nc_type = 1; break;   // byte
    case 0x0B: nc_type = 3; break;   // short
    case 0x0C: nc_type = 4; break;   // int
    case 0x0D: nc_type = 5; break;   // float
    case 0x0E: nc_type = 6; break;   // double
    default:
      *err = "magic: bad IDX dtype code";
      return false;
  }
  int nd = h[3];
  if (nd == 0 || hlen < 4 + 4 * static_cast<size_t>(nd)) {
    *err = "magic: bad IDX dimension count";
    return false;
  }
  Var v;
  v.name = nd >= 2 ? "images" : "labels";
  v.nc_type = nc_type;
  v.itemsize = nc_itemsize(nc_type);
  for (int d = 0; d < nd; d++) {
    uint64_t s = 0;
    for (int b = 0; b < 4; b++) s = (s << 8) | h[4 + 4 * d + b];
    v.shape.push_back(static_cast<long long>(s));
  }
  v.begin = 4 + 4 * nd;
  v.row_bytes = v.itemsize * prod_tail(v.shape);
  f->vars.push_back(std::move(v));
  return true;
}

bool pread_full(int fd, char* dst, long long bytes, long long off) {
  while (bytes > 0) {
    ssize_t r = pread(fd, dst, static_cast<size_t>(bytes), off);
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    if (r == 0) return false;  // unexpected EOF
    dst += r;
    off += r;
    bytes -= r;
  }
  return true;
}

void bswap_buf(unsigned char* p, long long bytes, int itemsize) {
  long long n = bytes / itemsize;
  switch (itemsize) {
    case 2: {
      uint16_t* q = reinterpret_cast<uint16_t*>(p);
      for (long long i = 0; i < n; i++) q[i] = __builtin_bswap16(q[i]);
      break;
    }
    case 4: {
      uint32_t* q = reinterpret_cast<uint32_t*>(p);
      for (long long i = 0; i < n; i++) q[i] = __builtin_bswap32(q[i]);
      break;
    }
    case 8: {
      uint64_t* q = reinterpret_cast<uint64_t*>(p);
      for (long long i = 0; i < n; i++) q[i] = __builtin_bswap64(q[i]);
      break;
    }
    default: break;
  }
}

struct Run {
  long long file_off, out_off, bytes;
};

// Persistent worker pool for gather fan-out. The old per-call
// std::thread spawn cost ~50us/thread, which swamped training-shaped
// gathers (~100 KB) and forced a 4 MiB threshold that real batches never
// reached (VERDICT r1 weak #1); reusing parked workers makes threading
// profitable at batch scale. Lazily constructed on first threaded gather;
// workers park on a condition variable between jobs.
class Pool {
 public:
  static Pool& get() {
    // Deliberately leaked: Python daemon readahead threads can still be
    // inside run() at interpreter exit; destroying the mutex/cv under them
    // is UB. A process-lifetime pool never dies.
    static Pool* p = new Pool;
    return *p;
  }

  // Run fn(0..n-1) across the pool (the calling thread helps); returns when
  // all jobs finished. Serialized across callers: Python readahead worker
  // threads may issue concurrent gathers (the GIL is released during the
  // ctypes call), and the job slots are single-generation.
  void run(size_t n, const std::function<void(size_t)>& fn) {
    std::lock_guard<std::mutex> serialize(run_mu_);
    std::unique_lock<std::mutex> l(mu_);
    job_ = &fn;
    njobs_ = n;
    next_ = 0;
    done_ = 0;
    ++gen_;
    cv_work_.notify_all();
    while (next_ < njobs_) {
      size_t i = next_++;
      l.unlock();
      fn(i);
      l.lock();
      ++done_;
    }
    cv_done_.wait(l, [&] { return done_ == njobs_; });
    job_ = nullptr;
  }

  size_t size() const { return workers_.size(); }

 private:
  Pool() {
    unsigned hw = std::thread::hardware_concurrency();
    size_t nt = std::min<size_t>(hw ? hw : 4, 16);
    for (size_t t = 0; t < nt; t++) {
      workers_.emplace_back([this] { Work(); });
    }
  }

  ~Pool() {
    {
      std::lock_guard<std::mutex> l(mu_);
      stop_ = true;
    }
    cv_work_.notify_all();
    for (auto& t : workers_) t.join();
  }

  void Work() {
    std::unique_lock<std::mutex> l(mu_);
    uint64_t seen = 0;
    for (;;) {
      cv_work_.wait(l, [&] {
        return stop_ || (gen_ != seen && next_ < njobs_);
      });
      if (stop_) return;
      seen = gen_;
      while (next_ < njobs_) {
        size_t i = next_++;
        const std::function<void(size_t)>* fn = job_;
        l.unlock();
        (*fn)(i);
        l.lock();
        if (++done_ == njobs_) cv_done_.notify_all();
      }
    }
  }

  std::vector<std::thread> workers_;
  std::mutex run_mu_;
  std::mutex mu_;
  std::condition_variable cv_work_, cv_done_;
  const std::function<void(size_t)>* job_ = nullptr;
  size_t njobs_ = 0, next_ = 0, done_ = 0;
  uint64_t gen_ = 0;
  bool stop_ = false;
};

}  // namespace

extern "C" {

void* nr_open(const char* path, char* err, int err_cap) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) {
    set_err(err, err_cap,
            std::string("open: ") + path + ": " + std::strerror(errno));
    return nullptr;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 4) {
    close(fd);
    set_err(err, err_cap, std::string("magic: ") + path + ": too short");
    return nullptr;
  }
  size_t hlen = static_cast<size_t>(
      st.st_size < kMaxHeader ? st.st_size : kMaxHeader);
  std::vector<unsigned char> head(hlen);
  if (!pread_full(fd, reinterpret_cast<char*>(head.data()),
                  static_cast<long long>(hlen), 0)) {
    close(fd);
    set_err(err, err_cap, std::string("open: ") + path + ": header read failed");
    return nullptr;
  }

  auto* f = new File;
  f->fd = fd;
  std::string msg;
  bool ok;
  if (hlen >= 4 && head[0] == 'C' && head[1] == 'D' && head[2] == 'F' &&
      (head[3] == 1 || head[3] == 2 || head[3] == 5)) {
    ok = parse_netcdf(f, head.data(), hlen, head[3], &msg);
  } else if (head[0] == 0 && head[1] == 0) {
    ok = parse_idx(f, head.data(), hlen, &msg);
  } else {
    ok = false;
    msg = "magic: not a NetCDF classic or IDX file";
  }
  if (!ok) {
    set_err(err, err_cap, std::string(path) + ": " + msg);
    delete f;
    return nullptr;
  }
  return f;
}

void nr_close(void* h) { delete static_cast<File*>(h); }

int nr_nvars(void* h) {
  return static_cast<int>(static_cast<File*>(h)->vars.size());
}

// Fills name (NUL-terminated, truncated to name_cap), shape (up to
// shape_cap dims), *ndims, *nc_type for variable i. Returns 0 on success.
int nr_var_info(void* h, int i, char* name, int name_cap, long long* shape,
                int shape_cap, int* ndims, int* nc_type) {
  File* f = static_cast<File*>(h);
  if (i < 0 || i >= static_cast<int>(f->vars.size())) return -1;
  const Var& v = f->vars[i];
  std::snprintf(name, static_cast<size_t>(name_cap), "%s", v.name.c_str());
  *ndims = static_cast<int>(v.shape.size());
  *nc_type = v.nc_type;
  for (int d = 0; d < *ndims && d < shape_cap; d++) shape[d] = v.shape[d];
  return 0;
}

// Gather `n` leading-dim rows of variable `vi` (indices pre-validated by the
// caller) into `out`, host-endian. Returns 0 on success, -1 with `err` set.
int nr_read_rows(void* h, int vi, const long long* idx, long long n,
                 void* out, char* err, int err_cap) {
  File* f = static_cast<File*>(h);
  if (vi < 0 || vi >= static_cast<int>(f->vars.size())) {
    set_err(err, err_cap, "read: bad variable index");
    return -1;
  }
  const Var& v = f->vars[vi];

  // Coalesce consecutive indices into single contiguous preads (a shuffled
  // epoch still yields many short runs; a whole-variable read becomes one).
  std::vector<Run> runs;
  long long k = 0;
  while (k < n) {
    long long j = k + 1;
    while (j < n && idx[j] == idx[j - 1] + 1) j++;
    runs.push_back({v.begin + idx[k] * v.row_bytes, k * v.row_bytes,
                    (j - k) * v.row_bytes});
    k = j;
  }

  char* dst = static_cast<char*>(out);
  const long long total = n * v.row_bytes;
  std::atomic<bool> failed{false};

  auto do_range = [&](size_t a, size_t b) {
    for (size_t r = a; r < b; r++) {
      if (!pread_full(f->fd, dst + runs[r].out_off, runs[r].bytes,
                      runs[r].file_off)) {
        failed.store(true, std::memory_order_relaxed);
        return;
      }
    }
  };

  // Fan out over the persistent pool once the gather is big enough that
  // parallel preads beat one thread issuing them serially. A shuffled
  // 128-row training batch (~128 runs, ~100 KB) qualifies — the point of
  // the persistent pool; tiny gathers (a handful of runs, e.g. labels or
  // sequential eval reads that coalesce to one run) stay inline.
  constexpr size_t kMinRunsForPool = 16;
  constexpr long long kMinBytesForPool = 32 << 10;  // 32 KiB
  if (runs.size() >= kMinRunsForPool && total >= kMinBytesForPool) {
    Pool& pool = Pool::get();
    // Chunk runs so each pool job handles a contiguous span: fewer handoffs
    // than one-job-per-run, still enough chunks to load every worker.
    size_t nchunks = std::min(runs.size(), pool.size() * 4);
    size_t per = (runs.size() + nchunks - 1) / nchunks;
    pool.run(nchunks, [&](size_t c) {
      size_t a = c * per, b = std::min(runs.size(), a + per);
      if (a < b) do_range(a, b);
    });
  } else {
    do_range(0, runs.size());
  }
  if (failed.load(std::memory_order_relaxed)) {
    set_err(err, err_cap, "read: pread failed or short");
    return -1;
  }
  if (v.itemsize > 1) {
    bswap_buf(static_cast<unsigned char*>(out), total, v.itemsize);
  }
  return 0;
}

}  // extern "C"
