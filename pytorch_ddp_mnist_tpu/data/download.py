"""MNIST acquisition: mirrored IDX download with checksum verification.

The reference's first-line data capability is `datasets.MNIST(download=True)`
(ddp_tutorial_cpu.py:20,31): torchvision fetches the four gzipped IDX files
from a mirror list and verifies checksums before use. This module restores
that capability without torch: stdlib urllib against the same public mirrors,
MD5 allowlist (the canonical published digests torchvision itself pins),
atomic writes, and an IDX magic-check on the downloaded payload so a
corrupted or HTML-error body can never be mistaken for data.

Offline behavior: every mirror failing (the zero-egress case) raises
DownloadError; callers fall back per policy (cli.train probes disk ->
optional download -> synthetic, data/mnist.py:get_mnist).
"""

from __future__ import annotations

import gzip
import hashlib
import os
import tempfile
import urllib.error
import urllib.request

# Same mirror order torchvision uses: the S3 mirror first (yann.lecun.com
# has throttled/403'd anonymous clients for years), then the origin.
MIRRORS = (
    "https://ossci-datasets.s3.amazonaws.com/mnist/",
    "https://storage.googleapis.com/cvdf-datasets/mnist/",
    "http://yann.lecun.com/exdb/mnist/",
)

# filename -> canonical MD5 of the .gz payload (the digests torchvision pins
# for these exact artifacts; the files have been byte-stable since 1998).
FILES = {
    "train-images-idx3-ubyte.gz": "f68b3c2dcbeaaa9fbdd348bbdeb94873",
    "train-labels-idx1-ubyte.gz": "d53e105ee54ea40749a09fcbcd1e9432",
    "t10k-images-idx3-ubyte.gz": "9fb629c4189551a2d022fa330f9573f3",
    "t10k-labels-idx1-ubyte.gz": "ec29112dd5afa0611ce80d1b7f02629c",
}


class DownloadError(RuntimeError):
    """All mirrors failed (or produced bad payloads) for a file."""


def _md5(path: str) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _looks_like_idx_gz(path: str) -> bool:
    """Cheap structural check: gunzips the first 4 bytes and validates the
    IDX magic (00 00 <dtype> <ndims>) — rejects HTML error pages that a
    misbehaving mirror serves with HTTP 200."""
    try:
        with gzip.open(path, "rb") as f:
            head = f.read(4)
    except OSError:
        return False
    return (len(head) == 4 and head[0] == 0 and head[1] == 0
            and head[2] in (0x08, 0x09, 0x0B, 0x0C, 0x0D, 0x0E)
            and head[3] > 0)


def download_file(filename: str, dest_dir: str, *,
                  mirrors=None, md5: str | None = None,
                  timeout: float = 30.0, quiet: bool = False) -> str:
    """Fetch one artifact into `dest_dir`, trying each mirror in order.

    The payload lands in a temp file, is checksum- and structure-verified,
    then atomically renamed into place — a crashed or corrupt download can
    never leave a half-written file where the loader probes. Returns the
    final path. An existing file with a matching checksum short-circuits
    (the reference's `download=True` is likewise a no-op on a warm cache).
    """
    mirrors = MIRRORS if mirrors is None else mirrors  # late-bound: tests
    os.makedirs(dest_dir, exist_ok=True)               # repoint the module's
    dest = os.path.join(dest_dir, filename)            # MIRRORS/FILES
    want = md5 if md5 is not None else FILES.get(filename)
    if os.path.exists(dest) and (want is None or _md5(dest) == want):
        return dest
    errors = []
    for mirror in mirrors:
        url = mirror.rstrip("/") + "/" + filename
        fd, tmp = tempfile.mkstemp(dir=dest_dir, suffix=".part")
        try:
            with urllib.request.urlopen(url, timeout=timeout) as r, \
                    os.fdopen(fd, "wb") as out:
                fd = None
                while True:
                    chunk = r.read(1 << 20)
                    if not chunk:
                        break
                    out.write(chunk)
            if want is not None and _md5(tmp) != want:
                raise DownloadError(f"{url}: checksum mismatch "
                                    f"(got {_md5(tmp)}, want {want})")
            if not _looks_like_idx_gz(tmp):
                raise DownloadError(f"{url}: payload is not a gzipped IDX file")
            os.replace(tmp, dest)
            if not quiet:
                print(f"[data] downloaded {filename} from {mirror}")
            return dest
        except (urllib.error.URLError, OSError, DownloadError) as e:
            errors.append(f"  {url}: {e}")
        finally:
            if fd is not None:
                os.close(fd)
            if os.path.exists(tmp):
                os.unlink(tmp)
    raise DownloadError(
        f"could not download {filename} from any mirror:\n" + "\n".join(errors))


def download_mnist(root: str, *, mirrors=None, files=None,
                   quiet: bool = False) -> str:
    """Fetch all four MNIST IDX artifacts into `root` (idempotent; verified).

    The capability analog of `datasets.MNIST(root, download=True)`
    (ddp_tutorial_cpu.py:19-33). Files are stored gzipped at `root`'s top
    level, where data/mnist.py's loader probes (`read_idx` gunzips
    transparently). `files` overrides the {filename: md5} manifest (tests
    point it at fixture artifacts). Returns `root`.
    """
    files = FILES if files is None else files
    for filename, md5 in files.items():
        download_file(filename, root, mirrors=mirrors, md5=md5, quiet=quiet)
    return root


def main(argv=None) -> int:
    """CLI: python -m pytorch_ddp_mnist_tpu.data.download [--root data/]"""
    import argparse
    p = argparse.ArgumentParser(
        description="Download the MNIST IDX files (checksum-verified), the "
                    "datasets.MNIST(download=True) analog")
    p.add_argument("--root", default="data/", help="destination directory")
    a = p.parse_args(argv)
    download_mnist(a.root)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
