"""MNIST dataset: IDX loading, normalization, synthetic fallback.

Reference data plane (SURVEY.md §2.1/§2.6): torchvision's
`datasets.MNIST(download=True)` with transform
`ToTensor() -> Normalize((0.1307,), (0.3081,))` (ddp_tutorial_cpu.py:13-33).
Here the same bytes come from the IDX files directly (the torchvision cache
layout `<root>/MNIST/raw/*-ubyte[.gz]` is probed too, so an existing cache is
reused), and normalization reproduces the transform exactly: /255 then
(x - 0.1307) / 0.3081, flattened to 784 like the train loop's
`x.view(B, -1)` (ddp_tutorial_multi_gpu.py:90).

Zero-egress environments get `synthetic_mnist`: a deterministic, learnable
stand-in (10 fixed class templates + per-sample noise) so every config can
run end-to-end without downloads.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

from .idx import read_idx

MNIST_MEAN = 0.1307
MNIST_STD = 0.3081


@dataclass
class Split:
    """One dataset split: uint8 images (n, H, W) + uint8 labels (n,)."""
    images: np.ndarray
    labels: np.ndarray

    def __len__(self) -> int:
        return len(self.images)


def normalize_images(images: np.ndarray) -> np.ndarray:
    """uint8 (n, H, W) -> float32 (n, H*W), the reference transform + flatten.

    Computed in place on one float32 buffer — bit-identical to the naive
    `((x/255) - mean)/std` temporary chain (same ops, same order) but
    without materializing three n*784*4-byte temporaries, which dominated
    the streaming data path's CPU profile at 60k-row scale.
    """
    x = np.asarray(images, np.float32)
    if np.shares_memory(x, images):  # never mutate the caller's buffer
        x = x.copy()
    x /= 255.0
    x -= MNIST_MEAN
    x /= MNIST_STD
    return x.reshape(x.shape[0], -1)


def _find_idx(root: str, stem: str) -> str | None:
    for d in (root, os.path.join(root, "MNIST", "raw")):
        for name in (stem, stem + ".gz"):
            p = os.path.join(d, name)
            if os.path.exists(p):
                return p
    return None


def load_mnist(root: str, train: bool = True) -> Split | None:
    """Load one split from IDX files under `root` (torchvision layouts
    included). Returns None when the files are absent."""
    prefix = "train" if train else "t10k"
    ipath = _find_idx(root, f"{prefix}-images-idx3-ubyte")
    lpath = _find_idx(root, f"{prefix}-labels-idx1-ubyte")
    if ipath is None or lpath is None:
        return None
    images = read_idx(ipath)
    labels = read_idx(lpath)
    if len(images) != len(labels):
        raise ValueError(
            f"{root}: {len(images)} images but {len(labels)} labels")
    return Split(images, labels)


def synthetic_mnist(n: int, seed: int = 0) -> Split:
    """Deterministic learnable MNIST stand-in.

    Class structure comes from 10 FIXED 7x7 templates (independent of `seed`,
    so a train split at seed=0 and a test split at seed=1 share classes and a
    model can generalize between them); `seed` drives the per-sample label
    draw and pixel noise.
    """
    tmpl_rng = np.random.default_rng(0xC0FFEE)
    coarse = tmpl_rng.integers(30, 226, (10, 7, 7)).astype(np.float32)
    templates = np.kron(coarse, np.ones((4, 4), np.float32))  # (10, 28, 28)
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n).astype(np.uint8)
    noise = rng.normal(0.0, 20.0, (n, 28, 28)).astype(np.float32)
    images = np.clip(templates[labels] + noise, 0, 255).astype(np.uint8)
    return Split(images, labels)


def get_mnist(root: str, train: bool = True, *, synthetic_n: int | None = None,
              quiet: bool = False, download: bool = False) -> Split:
    """Load a split from disk, optionally downloading, falling back to
    synthetic data.

    Probe order mirrors the reference's acquisition chain
    (datasets.MNIST(download=True), ddp_tutorial_cpu.py:22): files on disk
    win; `download=True` then fetches the real IDX artifacts from the public
    mirrors (data/download.py, checksum-verified); zero-egress environments
    land on the generated stand-in of the canonical split size (60k/10k,
    `synthetic_n` overrides) so every config still runs end-to-end.
    """
    split = load_mnist(root, train)
    if split is not None:
        return split
    if download:
        from .download import DownloadError, download_mnist
        try:
            download_mnist(root, quiet=quiet)
            split = load_mnist(root, train)
            if split is not None:
                return split
        except DownloadError as e:
            if not quiet:
                print(f"[data] MNIST download failed ({e}); "
                      f"falling back to synthetic data")
    n = synthetic_n if synthetic_n is not None else (60000 if train else 10000)
    if not quiet:
        hint = "" if download else " (pass --download to fetch real MNIST)"
        print(f"[data] no MNIST IDX files under {root!r}; using synthetic "
              f"{'train' if train else 'test'} split of {n} samples{hint}")
    return synthetic_mnist(n, seed=0 if train else 1)
