"""Dependency-free NetCDF classic reader/writer (CDF-1 / CDF-2 / CDF-5).

The reference's parallel-I/O data path stores MNIST in NetCDF written by
PnetCDF in `64BIT_DATA` (CDF-5) format — mnist_to_netcdf.ipynb cell-2:
dims Y=28/X=28/idx=N, vars `images` NC_UBYTE (idx,Y,X) and `labels`
NC_UBYTE (idx,) — and reads it back over MPI-IO, collectively
(mnist_pnetcdf_cpu.py:33,47) or independently per rank
(mnist_pnetcdf_cpu_mp.py:31-46). TPU hosts have no MPI; this module
implements the on-disk grammar itself (the netcdf-c classic format spec plus
the PnetCDF CDF-5 widening: every NON_NEG size field becomes INT64) so each
process opens the shared file and gathers exactly its own sampler's rows —
the independent-I/O analog, with no native library dependency. The C++ core
in `data/native/` parses the same grammar for the hot path; this file is the
format source of truth it is tested against.

Grammar implemented (header, big-endian):
  magic('C''D''F' ver) numrecs dim_list gatt_list var_list
  dim_list  = ABSENT | tag(0x0A) NELEMS [name length]...
  gatt_list = ABSENT | tag(0x0C) NELEMS [name nc_type NELEMS values pad4]...
  var_list  = ABSENT | tag(0x0B) NELEMS
              [name ndims dimid... vatt_list nc_type vsize begin]...
  NON_NEG   = u32 (CDF-1/2) | u64 (CDF-5);  begin = u32 (CDF-1) | u64 (2/5)
Record (unlimited) dimensions are not produced by the converter and are not
supported; attributes are parsed and skipped.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

NC_BYTE, NC_CHAR, NC_SHORT, NC_INT, NC_FLOAT, NC_DOUBLE = 1, 2, 3, 4, 5, 6
NC_UBYTE, NC_USHORT, NC_UINT, NC_INT64, NC_UINT64 = 7, 8, 9, 10, 11

_TAG_DIM, _TAG_VAR, _TAG_ATT = 0x0A, 0x0B, 0x0C

# nc_type -> big-endian on-disk numpy dtype
_NP_OF_NC = {
    NC_BYTE: "i1", NC_CHAR: "S1", NC_SHORT: ">i2", NC_INT: ">i4",
    NC_FLOAT: ">f4", NC_DOUBLE: ">f8", NC_UBYTE: "u1", NC_USHORT: ">u2",
    NC_UINT: ">u4", NC_INT64: ">i8", NC_UINT64: ">u8",
}
_NC_OF_NP = {
    "int8": NC_BYTE, "uint8": NC_UBYTE, "int16": NC_SHORT,
    "uint16": NC_USHORT, "int32": NC_INT, "uint32": NC_UINT,
    "int64": NC_INT64, "uint64": NC_UINT64, "float32": NC_FLOAT,
    "float64": NC_DOUBLE,
}


def _pad4(n: int) -> int:
    return (n + 3) & ~3


class Variable:
    """Header entry for one variable (fixed-size; no record vars)."""

    def __init__(self, name: str, dims: Tuple[str, ...],
                 shape: Tuple[int, ...], nc_type: int, begin: int):
        self.name = name
        self.dims = dims
        self.shape = shape
        self.nc_type = nc_type
        self.begin = begin
        self.disk_dtype = np.dtype(_NP_OF_NC[nc_type])
        self.row_bytes = int(np.prod(shape[1:], dtype=np.int64)) \
            * self.disk_dtype.itemsize if shape else self.disk_dtype.itemsize

    def __repr__(self):
        return (f"Variable({self.name!r}, shape={self.shape}, "
                f"nc_type={self.nc_type}, begin={self.begin})")


# ---------------------------------------------------------------- writer ---

class _HeaderWriter:
    def __init__(self, version: int):
        if version not in (1, 2, 5):
            raise ValueError(f"unsupported NetCDF version {version}")
        self.version = version
        self.W = 8 if version == 5 else 4       # NON_NEG width
        self.OFF = 4 if version == 1 else 8     # begin-offset width

    def nonneg(self, x: int) -> bytes:
        return int(x).to_bytes(self.W, "big")

    def u32(self, x: int) -> bytes:
        return int(x).to_bytes(4, "big")

    def offset(self, x: int) -> bytes:
        return int(x).to_bytes(self.OFF, "big")

    def name(self, s: str) -> bytes:
        b = s.encode("utf-8")
        return self.nonneg(len(b)) + b + b"\x00" * (_pad4(len(b)) - len(b))


def write_netcdf(path: str,
                 dims: Dict[str, int],
                 variables: Dict[str, Tuple[Sequence[str], np.ndarray]],
                 version: int = 5) -> None:
    """Write fixed-size dims + variables as one classic-format file.

    `variables` maps name -> (dim-name tuple, array); array shapes must match
    the named dims. version=5 produces the `64BIT_DATA` files the reference
    converter emits (CDF\\x05 magic).
    """
    w = _HeaderWriter(version)
    dim_names = list(dims)
    dim_ids = {n: i for i, n in enumerate(dim_names)}

    entries = []  # (name, dim_ids, nc_type, disk_array, vsize)
    for name, (vdims, arr) in variables.items():
        arr = np.asarray(arr)
        vdims = tuple(vdims)
        want = tuple(int(dims[d]) for d in vdims)
        if tuple(arr.shape) != want:
            raise ValueError(
                f"variable {name!r}: shape {arr.shape} != dims {vdims}={want}")
        nc_type = _NC_OF_NP.get(arr.dtype.name)
        if nc_type is None:
            raise ValueError(f"variable {name!r}: unsupported dtype {arr.dtype}")
        disk = arr.astype(_NP_OF_NC[nc_type])
        vsize = _pad4(disk.nbytes)
        entries.append((name, [dim_ids[d] for d in vdims], nc_type, disk, vsize))

    absent = w.u32(0) + w.nonneg(0)

    def header_bytes(begins: List[int]) -> bytes:
        out = [b"CDF", bytes([version]), w.nonneg(0)]           # magic, numrecs
        out += [w.u32(_TAG_DIM), w.nonneg(len(dim_names))]
        for n in dim_names:
            out += [w.name(n), w.nonneg(dims[n])]
        out.append(absent)                                      # gatt_list
        if entries:
            out += [w.u32(_TAG_VAR), w.nonneg(len(entries))]
            for (name, ids, nc_type, _disk, vsize), begin in zip(entries, begins):
                out += [w.name(name), w.nonneg(len(ids))]
                out += [w.nonneg(i) for i in ids]
                out.append(absent)                              # vatt_list
                out += [w.u32(nc_type), w.nonneg(vsize), w.offset(begin)]
        else:
            out.append(absent)
        return b"".join(out)

    # Header size is begin-independent (fixed-width offsets): measure with
    # placeholder begins, then lay variables out back to back, 4-aligned.
    hsize = len(header_bytes([0] * len(entries)))
    begins, cur = [], _pad4(hsize)
    for *_rest, vsize in entries:
        begins.append(cur)
        cur += vsize

    with open(path, "wb") as f:
        head = header_bytes(begins)
        f.write(head)
        f.write(b"\x00" * (_pad4(hsize) - hsize))
        for (_n, _ids, _t, disk, vsize) in entries:
            raw = disk.tobytes()
            f.write(raw)
            f.write(b"\x00" * (vsize - len(raw)))


def write_mnist_netcdf(path: str, images: np.ndarray,
                       labels: np.ndarray) -> None:
    """Write the reference converter's exact schema (mnist_to_netcdf.ipynb
    cell-2 / SURVEY.md §3.4): CDF-5, dims Y/X/idx, NC_UBYTE images (idx,Y,X)
    then labels (idx,)."""
    images = np.asarray(images, np.uint8)
    labels = np.asarray(labels, np.uint8)
    n, h, wdt = images.shape
    if labels.shape != (n,):
        raise ValueError(f"labels shape {labels.shape} != ({n},)")
    write_netcdf(path, {"Y": h, "X": wdt, "idx": n},
                 {"images": (("idx", "Y", "X"), images),
                  "labels": (("idx",), labels)},
                 version=5)


# ---------------------------------------------------------------- reader ---

class _HeaderCursor:
    """Big-endian cursor that pulls header bytes from the file on demand."""

    def __init__(self, f):
        self.f = f
        self.buf = b""
        self.pos = 0

    def take(self, n: int) -> bytes:
        while len(self.buf) - self.pos < n:
            chunk = self.f.read(1 << 16)
            if not chunk:
                raise ValueError("truncated NetCDF header")
            self.buf += chunk
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def u32(self) -> int:
        return int.from_bytes(self.take(4), "big")

    def be(self, width: int) -> int:
        return int.from_bytes(self.take(width), "big")


class NetCDFReader:
    """Parse a classic-format header; read variables whole or by row gather."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            magic = f.read(4)
            if magic[:3] != b"CDF" or len(magic) < 4 or magic[3] not in (1, 2, 5):
                raise ValueError(f"{path}: bad NetCDF magic {magic!r}")
            self.version = magic[3]
            W = 8 if self.version == 5 else 4
            OFF = 4 if self.version == 1 else 8
            c = _HeaderCursor(f)
            self.numrecs = c.be(W)
            dim_list: List[Tuple[str, int]] = []
            tag, n = c.u32(), c.be(W)
            if tag == _TAG_DIM:
                for _ in range(n):
                    dim_list.append((self._name(c, W), c.be(W)))
            elif tag or n:
                raise ValueError(f"{path}: bad dim_list tag {tag:#x}")
            self._skip_attrs(c, W, path)                    # global atts
            self.dimensions = dict(dim_list)
            self.variables: Dict[str, Variable] = {}
            tag, n = c.u32(), c.be(W)
            if tag == _TAG_VAR:
                for _ in range(n):
                    name = self._name(c, W)
                    ndims = c.be(W)
                    ids = [c.be(W) for _ in range(ndims)]
                    self._skip_attrs(c, W, path)
                    nc_type = c.u32()
                    c.be(W)                                 # vsize (recomputed)
                    begin = c.be(OFF)
                    vdims = tuple(dim_list[i][0] for i in ids)
                    shape = tuple(dim_list[i][1] for i in ids)
                    if nc_type not in _NP_OF_NC:
                        raise ValueError(
                            f"{path}: variable {name!r} has unsupported "
                            f"nc_type {nc_type}")
                    self.variables[name] = Variable(
                        name, vdims, shape, nc_type, begin)
            elif tag or n:
                raise ValueError(f"{path}: bad var_list tag {tag:#x}")

    @staticmethod
    def _name(c: _HeaderCursor, W: int) -> str:
        n = c.be(W)
        s = c.take(_pad4(n))[:n]
        return s.decode("utf-8")

    @staticmethod
    def _skip_attrs(c: _HeaderCursor, W: int, path: str) -> None:
        tag, n = c.u32(), c.be(W)
        if tag == 0 and n == 0:
            return
        if tag != _TAG_ATT:
            raise ValueError(f"{path}: bad attribute list tag {tag:#x}")
        for _ in range(n):
            NetCDFReader._name(c, W)
            nc_type = c.u32()
            nelems = c.be(W)
            item = np.dtype(_NP_OF_NC.get(nc_type, "u1")).itemsize
            c.take(_pad4(nelems * item))

    def read(self, name: str,
             indices: Optional[Sequence[int]] = None) -> np.ndarray:
        """Read a variable, whole or as a leading-dim row gather (the access
        pattern of mnist_pnetcdf_cpu_mp.py:43-46: each rank fetches only its
        own sampler's indices). Returns a native-endian array."""
        v = self.variables[name]
        disk = v.disk_dtype
        native = disk.newbyteorder("=")
        if indices is None:
            count = int(np.prod(v.shape, dtype=np.int64))
            with open(self.path, "rb") as f:
                f.seek(v.begin)
                raw = f.read(count * disk.itemsize)
            if len(raw) != count * disk.itemsize:
                raise ValueError(f"{self.path}: truncated variable {name!r}")
            return np.frombuffer(raw, disk).reshape(v.shape).astype(
                native, copy=True)
        idx = np.asarray(indices, np.int64)
        if not v.shape:
            raise IndexError(f"variable {name!r} is a scalar")
        if idx.size and (idx.min() < 0 or idx.max() >= v.shape[0]):
            raise IndexError(
                f"indices out of range [0, {v.shape[0]}) for {name!r}")
        out = np.empty((idx.size,) + v.shape[1:], disk)
        flat = out.reshape(idx.size, -1).view(np.uint8) if idx.size else out
        with open(self.path, "rb") as f:
            for k, i in enumerate(idx):
                f.seek(v.begin + int(i) * v.row_bytes)
                raw = f.read(v.row_bytes)
                if len(raw) != v.row_bytes:
                    raise ValueError(
                        f"{self.path}: truncated row {int(i)} of {name!r}")
                flat[k] = np.frombuffer(raw, np.uint8)
        return out.astype(native, copy=True)


def read_mnist_netcdf(path: str,
                      indices: Optional[Sequence[int]] = None
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """(images, labels) from one converter-schema file, whole or row-gathered."""
    r = NetCDFReader(path)
    return r.read("images", indices), r.read("labels", indices)
