"""IDX -> NetCDF converter CLI — the mnist_to_netcdf.ipynb replacement.

The reference converts raw MNIST IDX files to two CDF-5 NetCDF files with a
notebook (SURVEY.md §2.8/§3.4: parse IDX with magic checks, write
mnist_{train,test}_images.nc via PnetCDF `64BIT_DATA`). This is the same
capability as a proper CLI, with a `--synthetic N:M` mode that materializes
a generated dataset for zero-egress environments.

Usage:
  python -m pytorch_ddp_mnist_tpu.data.convert --idx_dir data/ --out_dir data/
  python -m pytorch_ddp_mnist_tpu.data.convert --out_dir data/ --synthetic 60000:10000
"""

from __future__ import annotations

import argparse
import os
from typing import List, Optional

from .mnist import load_mnist, synthetic_mnist
from .netcdf import write_mnist_netcdf

OUT_NAMES = ("mnist_train_images.nc", "mnist_test_images.nc")


def convert(idx_dir: str, out_dir: str,
            synthetic: Optional[str] = None) -> List[str]:
    """Convert both splits; returns [train_path, test_path].

    `synthetic="N:M"` generates N train / M test samples instead of reading
    IDX files. Raises FileNotFoundError when IDX files are absent and no
    synthetic spec is given.
    """
    os.makedirs(out_dir, exist_ok=True)
    if synthetic:
        n_train, n_test = (int(p) for p in synthetic.split(":"))
        splits = [synthetic_mnist(n_train, seed=0),
                  synthetic_mnist(n_test, seed=1)]
    else:
        splits = []
        for train in (True, False):
            split = load_mnist(idx_dir, train=train)
            if split is None:
                prefix = "train" if train else "t10k"
                raise FileNotFoundError(
                    f"no IDX files for the {prefix!r} split under {idx_dir!r}"
                    " (expected <prefix>-images-idx3-ubyte[.gz] + labels)")
            splits.append(split)
    out = []
    for split, name in zip(splits, OUT_NAMES):
        path = os.path.join(out_dir, name)
        write_mnist_netcdf(path, split.images, split.labels)
        out.append(path)
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--idx_dir", default="data/",
                   help="directory holding the raw IDX files")
    p.add_argument("--out_dir", default="data/",
                   help="where to write mnist_{train,test}_images.nc")
    p.add_argument("--synthetic", default=None, metavar="N:M",
                   help="generate N train / M test synthetic samples instead "
                        "of reading IDX files")
    a = p.parse_args(argv)
    for path in convert(a.idx_dir, a.out_dir, synthetic=a.synthetic):
        print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
