"""Benchmark: MNIST training images/sec through the flagship data-parallel
path on real hardware. Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

Workload = the flagship DDP config (SURVEY.md §6): the 118,272-param MLP,
per-chip batch 128, SGD lr=0.01, dropout active, gradient allreduce-mean
across the mesh every step — the work one training step of
ddp_tutorial_multi_gpu.py does per rank, with full DDP semantics
(epoch-reshuffled DistributedSampler indices included).

Measured path = the framework's epoch-scanned trainer (train/scan.py) with
MULTIPLE epochs fused into one device program: the dataset lives in HBM,
batch gathers/dropout/fwd/bwd/allreduce/SGD all run under a nested lax.scan.
Default variant on a single TPU chip = the WHOLE-EPOCH Pallas kernel
(weights VMEM-resident across the epoch, uint8 input streaming) + the rbg
(hardware) PRNG dropout stream — the fastest semantics-preserving
configuration of every hardware variant matrix to date (docs/PERF.md;
36.9-37.1M img/s/chip). Multi-chip meshes default to the fused per-step
Pallas kernel; --kernel/--impl select the others.
Fusing epochs removes host<->device round-trips from the measurement — on a
tunneled/remote TPU a per-epoch sync costs ~70ms of RTT that says nothing
about the hardware. Timing = full fetch of the loss curve (a guaranteed
sync), best of 5 windows.
"""

import argparse
import functools
import json
import os
import sys

import numpy as np
import jax

NOMINAL_BASELINE_IMGS_PER_SEC = 1_000_000.0
# Eval/stream modes get DISTINCT nominals (same magnitude, different
# meaning): their vs_baseline fields normalize an inference-pass rate and a
# disk-loader rate respectively, so neither is comparable to train rows
# even though all three share the field name. Keeping the constants
# separate means retuning one can't silently reshape another's ratio
# (ADVICE r3).
NOMINAL_BASELINE_EVAL_IMGS_PER_SEC = 1_000_000.0
NOMINAL_BASELINE_STREAM_IMGS_PER_SEC = 1_000_000.0
# Serve mode normalizes an OPEN-LOOP request rate (single-row requests
# through admission + micro-batching), not an image rate — three orders of
# magnitude below the closed-loop eval number by construction (per-request
# latency budget vs fused throughput), hence its own nominal.
NOMINAL_BASELINE_SERVE_RPS = 1_000.0
# DDP mode normalizes the PER-CHIP train rate of the N-device mesh — same
# magnitude as the train nominal but a different program (per-step XLA
# collective in the scan, vs the single-chip epoch kernel), hence its own
# constant (same retuning-isolation rule as the others).
NOMINAL_BASELINE_DDP_IMGS_PER_SEC = 1_000_000.0

# Roofline context for every throughput line (VERDICT r4 #8: a reader of a
# BENCH_r0X.json should see how close the chip is to its ceiling without
# opening docs/PERF.md). The model cost is exact — 118,016 fwd MACs/image
# (784*128 + 128*128 + 128*10), backward ~2x forward — and the ceiling is
# the v5e chip's 197 TFLOP/s bf16 peak (f32 programs face the same MXU, so
# quoting one fixed ceiling keeps MFU comparable across dtype variants;
# docs/PERF.md derives the same roofline). scripts/bench_matrix.py uses
# these constants for its per-row tflops/mfu columns.
MACS_FWD_PER_IMG = 784 * 128 + 128 * 128 + 128 * 10      # 118,016
V5E_PEAK_FLOPS_BF16 = 197e12


def perf_fields(per_chip_imgs_per_sec: float, *, fwd_only: bool = False):
    """{tflops, mfu_pct_vs_bf16_peak} for a measured per-chip image rate.

    `fwd_only` for inference rates (eval mode): 2 FLOPs/MAC, no backward."""
    flops_per_img = (2 if fwd_only else 6) * MACS_FWD_PER_IMG
    tf = per_chip_imgs_per_sec * flops_per_img / 1e12
    return {"tflops": round(tf, 2),
            "mfu_pct_vs_bf16_peak": round(100 * tf * 1e12
                                          / V5E_PEAK_FLOPS_BF16, 2)}
# Window length: each timing window carries a fixed ~30 ms of program
# dispatch + sync RTT over the TPU tunnel (measured: 50/100/200/400-epoch
# windows report 15.5/16.7/17.3/18.1M img/s — a 1/x approach to the ~18.5M
# steady state). 400 epochs (~24M images, ~1.3 s/window) amortizes that to
# <3% while keeping the whole bench under ~a minute.
FUSED_EPOCHS = 400
# --mode accuracy trains real epochs (not timing windows); the north-star
# acceptance names 10 (BASELINE.json / ddp_tutorial_multi_gpu.py:127).
ACCURACY_EPOCHS = 10
# --mode ddp fuses this many epochs per timing window (default): the DDP
# scan program is measured per STRATEGY plus a 1-device baseline, so the
# whole mode stays a few windows even on CPU fake devices.
DDP_EPOCHS = 10
# --mode input trains this many REAL streaming epochs per variant (legacy
# + piped): enough epochs for a p95 over per-epoch data_wait shares while
# the synthetic read latency keeps each epoch sub-second.
INPUT_EPOCHS = 4

from pytorch_ddp_mnist_tpu.train.scan import resolve_kernel  # noqa: E402
from pytorch_ddp_mnist_tpu.ops.pallas_step import (  # noqa: E402
    EPOCH_KERNEL_MAX_BATCH)


CALIBRATION_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "bench_calibration.json")


@functools.lru_cache(maxsize=1)
def statics_stamp() -> dict:
    """{lint_findings, concurrency_findings, audit_ok[, error]} — computed
    once per process (lru_cache) and stamped on every artifact line, so a
    MULTICHIP/BENCH JSON records whether the measured build also honored
    the static contracts (docs/STATIC_ANALYSIS.md). `lint_findings` counts
    the PR 8 source-lint rules, `concurrency_findings` the ASYNC/LOCK
    auditor's (both post-baseline); the audit covers the 8 comm x overlap
    step programs (the form every measured strategy runs). The stamp NEVER
    kills a finished measurement: a named contract violation reads as
    audit_ok=false, and an unexpected stamp failure (a scratch file under
    scripts/ that doesn't parse, a malformed baseline, a backendless
    process) degrades to null fields plus an `error` string instead of an
    exception."""
    from pytorch_ddp_mnist_tpu.statics import jaxpr_audit, lint
    from pytorch_ddp_mnist_tpu.statics.rules import CONCURRENCY_RULES
    out = {"lint_findings": None, "concurrency_findings": None,
           "audit_ok": None}
    try:
        findings, _ = lint.lint_paths(lint.default_targets())
        new, _, _ = lint.apply_baseline(
            findings, lint.load_baseline(lint.default_baseline_path()))
        n_conc = sum(1 for f in new if f.rule in CONCURRENCY_RULES)
        out["lint_findings"] = len(new) - n_conc
        out["concurrency_findings"] = n_conc
    except (OSError, SyntaxError, UnicodeDecodeError, ValueError) as e:
        out["error"] = f"lint: {e}"[:300]
    try:
        jaxpr_audit.audit_matrix(forms=("step",))
        out["audit_ok"] = True
    except jaxpr_audit.AuditViolation:
        out["audit_ok"] = False
    except (RuntimeError, ValueError, OSError) as e:
        # tracing needs a live backend for the example arrays; a dead one
        # must not cost the artifact (the _backend_info degradation rule)
        out["error"] = (out.get("error", "") + f" audit: {e}"[:300]).strip()
    return out


def statics_stamp_fields() -> "dict | None":
    """The env-gated form every stamper shares: the statics_stamp() dict,
    or None when PDMT_STATICS_STAMP=0 disabled it (the test harness's
    fast path — the stamp costs a few seconds of lint+audit per process;
    matrix drivers disable it per cell and stamp once at the artifact
    level instead)."""
    if os.environ.get("PDMT_STATICS_STAMP", "1").strip().lower() \
            in ("0", "false", "no", "off"):
        return None
    return dict(statics_stamp())


def ledger_stamp_fields() -> dict:
    """The performance-ledger stamp every artifact line carries from
    schema v2 on (telemetry/ledger.py ingests these directly instead of
    re-deriving them): the schema generation, and the run ordinal the
    row's series sorts under — the driver's round number via PDMT_RUN_ORD
    when set, else the wall-clock second (monotone across rounds, which
    is all an ordinal needs to be)."""
    import time

    from pytorch_ddp_mnist_tpu.telemetry.ledger import SCHEMA_VERSION
    try:
        run_ord = int(os.environ.get("PDMT_RUN_ORD", ""))
    except ValueError:
        run_ord = int(time.time())
    return {"schema_version": SCHEMA_VERSION, "run_ord": run_ord}


def registry_stamp(registry=None) -> dict:
    """Compile-count and memory fields for a bench JSON line, read from the
    telemetry registry (main() arms the jax.monitoring compile listener
    before any jit, so `xla_compiles` covers the whole process). A reader
    of a BENCH_r0X.json sees recompilation storms and memory pressure
    without re-running the bench."""
    from pytorch_ddp_mnist_tpu import telemetry
    reg = registry or telemetry.get_registry()
    telemetry.collect_memory(reg)
    snap = reg.snapshot()
    out = {"xla_compiles": snap["counters"].get("xla.compiles")}
    rss = snap["gauges"].get("host.rss_bytes")
    out["host_rss_mb"] = round(rss / 2**20, 1) if rss else None
    dev = snap["gauges"].get("device.peak_bytes_in_use")
    if dev is not None:  # absent off-accelerator (CPU has no memory_stats)
        out["device_peak_bytes"] = dev
    # the program-forensics pair (docs/OBSERVABILITY.md §Program
    # forensics): the HBM watermark gauge (None off-accelerator, same
    # degrade as device_peak_bytes) and the process's total compile-time
    # bill from the xla.compile_s histogram the monitoring listener feeds
    out["peak_hbm_bytes"] = snap["gauges"].get("mem.device_peak_bytes")
    ch = snap["histograms"].get("xla.compile_s")
    out["compile_s_total"] = (round(ch["total"], 3)
                              if isinstance(ch, dict)
                              and isinstance(ch.get("total"), (int, float))
                              else None)
    # What degraded, not just that something did: detector fire counts +
    # worst severity from any watchdog that observed this process (the
    # device-mode bench runs one over its measured loss curves). A round
    # that died mid-measure still stamps the signals seen up to the death.
    out["health_summary"] = telemetry.health_summary(reg)
    statics = statics_stamp_fields()
    if statics is not None:
        out["statics"] = statics
    out.update(ledger_stamp_fields())
    return out


def _load_calibration(calibration_path: str = None) -> dict:
    """The committed calibration as a dict; {} for absent/invalid/non-object
    files (the documented fall-back-to-defaults contract)."""
    try:
        with open(calibration_path or CALIBRATION_PATH) as f:
            cal = json.load(f)
        return cal if isinstance(cal, dict) else {}
    except (OSError, ValueError):
        return {}


def resolve_bench_config(dtype: str, superstep: int, kernel: str,
                         calibration_path: str = None,
                         n_chips: int = 1) -> tuple:
    """Resolve bench's `--dtype auto` / `--superstep 0` defaults JOINTLY
    through the committed hardware calibration -> (dtype, superstep).

    The calibration (bench_calibration.json) is written ONLY by
    scripts/promote_epoch_dtype.py when one of the single-chip epoch-kernel
    candidate matrix rows — bf16-matmul at K in {1, 8}, f32 superstep K in
    {2, 4, 8} (promote_epoch_dtype.CANDIDATES) — beats the
    f32/K1 baseline in the SAME sweep (bf16 winners additionally pass a
    10-epoch accuracy-parity run; superstep alone is bitwise-equal math).
    That gate validates a single (dtype, K) PAIR, so the auto fields adopt
    the calibrated values only when every EXPLICITLY-set field matches the
    pair: e.g. an explicit `--superstep 1` against a {bf16, K8}
    calibration resolves dtype to float32, NOT bf16 — bf16/K1 was never
    validated and may even have lost the sweep. Auto therefore means "the
    fastest hardware-verified configuration", never a chimera of it.
    Absent/invalid calibrations, non-epoch kernels, and multi-chip meshes
    (the DP ring rejects K>1, and the gate's evidence is single-chip)
    always resolve to the plain defaults (float32, 1)."""
    out_d = dtype if dtype != "auto" else "float32"
    out_k = superstep if superstep != 0 else 1
    if kernel != "pallas_epoch" or n_chips != 1:
        return out_d, out_k
    cal = _load_calibration(calibration_path)
    cd = cal.get("epoch_kernel_dtype")
    ck = cal.get("epoch_kernel_superstep")
    if cd not in ("float32", "bfloat16") or ck not in (1, 2, 4, 8):
        return out_d, out_k
    if dtype != "auto" and dtype != cd:
        return out_d, out_k
    if superstep != 0 and superstep != ck:
        return out_d, out_k
    return cd, ck


def resolve_bench_kernel(kernel: str, dtype: str, on_tpu: bool,
                         n_chips: int, batch: int = 128,
                         unroll: int = 1) -> str:
    """bench's `--kernel auto`: the shared CLI policy, plus the single-chip
    promotion to the whole-epoch kernel — a 1-device mesh's DP semantics
    reduce to exactly it (the per-step pmean is the identity), and it is the
    fastest measured variant (docs/PERF.md). Multi-chip keeps the per-step
    kernel with the real allreduce; so do batches the epoch kernel can't
    take (not 8-aligned, or past its one-VMEM-block budget) and --unroll
    experiments (an epoch-kernel has no step scan to unroll)."""
    if kernel != "auto":
        return kernel
    kernel = resolve_kernel(dtype, on_tpu)
    if (kernel == "pallas" and n_chips == 1 and unroll == 1
            and batch % 8 == 0 and batch <= EPOCH_KERNEL_MAX_BATCH):
        kernel = "pallas_epoch"
    return kernel


def _stream_bench(a) -> None:
    """NetCDF streaming-loader throughput: gather + normalize of a full
    shuffled 60k-row epoch from disk (the mnist_pnetcdf_cpu_mp.py data
    plane), no device work — isolates the I/O path bench'd in docs/PERF.md."""
    import os
    import tempfile

    from pytorch_ddp_mnist_tpu.data.convert import main as convert_main
    from pytorch_ddp_mnist_tpu.data.loader import NetCDFShardLoader
    from pytorch_ddp_mnist_tpu.parallel import ShardedSampler
    from pytorch_ddp_mnist_tpu.utils import Timer

    with tempfile.TemporaryDirectory() as td:
        convert_main(["--synthetic", "60000:16", "--out_dir", td])
        ldr = NetCDFShardLoader(os.path.join(td, "mnist_train_images.nc"),
                                batch_size=128, num_workers=a.num_workers)
        ldr.sampler = ShardedSampler(60000, num_replicas=1, rank=0,
                                     shuffle=True, seed=42)
        best, n = float("inf"), 0
        for trial in range(4):  # trial 0 warms the page cache
            ldr.sampler.set_epoch(trial)
            with Timer("epoch") as t:
                n = sum(len(x) for x, _ in ldr)
            if trial:
                best = min(best, t.seconds)
        # no tflops/mfu: the stream mode measures the DISK loader, not
        # device compute — a roofline fraction would be meaningless here
        print(json.dumps({
            "metric": "mnist_netcdf_stream_images_per_sec",
            "value": round(n / best, 1),
            "unit": "images/sec",
            "vs_baseline": round(
                (n / best) / NOMINAL_BASELINE_STREAM_IMGS_PER_SEC, 4),
            **ledger_stamp_fields(),
        }))


def make_eval_program(reps: int):
    """Jitted program of `reps` reference eval passes (full test set,
    dropout off — ddp_tutorial_multi_gpu.py:101-114) under one lax.scan.

    `x` may be raw uint8 pixels: each pass then replays the reference
    loader's ToTensor+Normalize on device (the reference normalizes at eval
    time too), and the pass's HBM input stream is 4x smaller — the same
    uint8-residency design as the training path; XLA fuses the normalize
    into the first matmul's operand read.

    Each repetition's bias carries a +1e-30 perturbation from the previous
    pass's mean loss: numerically lost in f32 rounding (b1 is ~1e-2 scale),
    but it makes every pass data-depend on the one before, so XLA cannot
    hoist the loop-invariant forward out of the scan and evaluate it once
    (pinned by tests/test_bench.py::test_eval_bench_scan_does_not_collapse).
    """
    import jax.numpy as jnp

    from pytorch_ddp_mnist_tpu.train.loop import _eval_math
    from pytorch_ddp_mnist_tpu.train.scan import device_normalize

    @jax.jit
    def prog(params, x, y):
        def body(p, _):
            xf = device_normalize(x) if x.dtype == jnp.uint8 else x
            per_sample, correct = _eval_math(p, xf, y)
            m = per_sample.mean()
            p = dict(p, fc1=dict(p["fc1"], b=p["fc1"]["b"] + 1e-30 * m))
            return p, (m, correct.mean())
        _, outs = jax.lax.scan(body, params, None, length=reps)
        return outs

    return prog


def _eval_bench(a) -> None:
    """Inference throughput (`--mode eval`): `--epochs` fused repetitions of
    make_eval_program's pass per timing window, best of 5 — the measurement
    is the forward itself rather than per-pass dispatch RTT."""
    from pytorch_ddp_mnist_tpu.data import synthetic_mnist
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.train.scan import resident_images
    from pytorch_ddp_mnist_tpu.utils import Timer

    split = synthetic_mnist(10000, seed=1)
    # uint8-resident test set, normalized in-program per pass (the
    # reference's loader normalizes at eval time too)
    x = jax.device_put(resident_images(split.images))
    y = jax.device_put(split.labels.astype(np.int32))
    params = jax.device_put(init_mlp(jax.random.key(0)))
    prog = make_eval_program(a.epochs)  # same knob: fused reps per window

    losses, accs = prog(params, x, y)           # compile + warm
    assert np.isfinite(np.asarray(losses)).all()
    best = float("inf")
    for _ in range(5):
        with Timer("window") as t:
            out = prog(params, x, y)
            t.sync(out[0])
        best = min(best, t.seconds)
    # The eval program runs on ONE device (no mesh/sharding) — its
    # throughput IS the per-chip number; dividing by device_count() would
    # underreport by the idle chips on a multi-device host.
    per_chip = x.shape[0] * a.epochs / best
    print(json.dumps({
        "metric": "mnist_eval_images_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / NOMINAL_BASELINE_EVAL_IMGS_PER_SEC, 4),
        **perf_fields(per_chip, fwd_only=True),
        **registry_stamp(),
    }))


def _serve_bench(a) -> None:
    """`--mode serve`: latency-percentile serving bench — the open-loop
    Poisson load generator (serve/loadgen.py) drives `--requests`
    single-row requests at `--offered_rps` through the FULL request path
    (admission -> micro-batcher -> bucketed AOT engine) and the one JSON
    line reports achieved rate, p50/p95/p99 latency, batch occupancy and
    reject rate. Offered vs achieved (+ rejects) is the saturation story a
    closed-loop sweep cannot tell. Runs identically on CPU/simulator: the
    engine precompiles its bucket ladder on whatever backend is up."""
    from pytorch_ddp_mnist_tpu import telemetry
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.serve import (FleetService, InferenceEngine,
                                             ServeService)
    from pytorch_ddp_mnist_tpu.serve.loadgen import run_loadgen

    # A fresh registry per bench (not the process-wide one): the artifact
    # must report THIS run's serve counters, not whatever else the process
    # accumulated.
    reg = telemetry.MetricsRegistry()
    params = init_mlp(jax.random.key(0))
    if a.replicas > 1:
        service = FleetService(
            lambda p_: InferenceEngine(p_, max_batch=a.max_batch), params,
            n_replicas=a.replicas, max_batch=a.max_batch,
            max_delay_ms=a.max_delay_ms, max_depth=a.queue_depth,
            registry=reg, fast=a.serve_fast)
        engine = service.engine
    else:
        engine = InferenceEngine(params, max_batch=a.max_batch)
        service = ServeService(engine, max_delay_ms=a.max_delay_ms,
                               max_depth=a.queue_depth, registry=reg,
                               fast=a.serve_fast)
    # Bucket executables compiled at construction; one dispatch per bucket
    # seats runtime first-call overhead outside the measured percentiles.
    for b in engine.buckets:
        engine.predict(np.zeros((b, 784), np.float32))
    telemetry.record_engine_compiles(reg, engine.compile_count)
    out = run_loadgen(service, offered_rps=a.offered_rps,
                      n_requests=a.requests, seed=0, shape=a.shape)
    lat = out["latency_ms"]
    rps = out["achieved_rps"]
    counters = reg.snapshot()["counters"]
    # the per-stage tail story rides the artifact: p50/p99 + each stage's
    # share of the telescoped per-request time, under the serve/tracing.py
    # stage names — the before/after evidence SERVE_r01.json commits
    # (docs/SERVING.md §Fast path)
    stages = service.metrics.attribution()["stages"]
    print(json.dumps({
        "metric": "mnist_serve_requests_per_sec",
        "value": rps,
        "unit": "requests/sec",
        "vs_baseline": (round(rps / NOMINAL_BASELINE_SERVE_RPS, 4)
                        if rps else None),
        "offered_rps": out["offered_rps"],
        "shape": out["shape"],
        "p50_ms": lat["p50"], "p95_ms": lat["p95"], "p99_ms": lat["p99"],
        # robustness stamps (always present so the ledger trends them
        # across single-engine AND fleet rounds): availability is the
        # fraction of ADMITTED requests answered — rejects are honest
        # backpressure, failures are broken promises; retried_requests
        # counts fleet failovers (0 without --replicas); reloads counts
        # hot swaps (0 in a bench — the chaos smoke drives those)
        "availability": (round(out["completed"]
                               / (out["completed"] + out["failed"]), 6)
                         if out["completed"] + out["failed"] else None),
        "replicas": a.replicas,
        "retried_requests": counters.get("serve.fleet.retried_requests", 0),
        "reloads": counters.get("serve.reload.reloads", 0),
        # client-perceived minus server-side e2e at matched percentiles:
        # the front-door (event-loop scheduling / transport) overhead the
        # server histogram cannot see (serve/loadgen.py)
        "front_door_overhead_ms": out["front_door_overhead_ms"],
        "reject_rate": out["reject_rate"],
        # the absolute queue-rejection count (reject_rate alone cannot
        # distinguish 1/10 from 100/1000): overload behavior is auditable
        # from the artifact alone
        "rejected": counters["serve.rejected"],
        "batch_occupancy": out["batch_occupancy"],
        # structural no-cold-compile evidence: the bucket ladder's warmup
        # compiles are the ONLY compiles the engine can ever perform
        "compile_count": counters["serve.engine_compiles"],
        # which flush path served (the --no_fast A/B knob), whether the
        # staging pool ever grew past its double buffer (0 in steady
        # state — the zero-allocation-per-flush pin's observable), and
        # the per-stage attribution under the tracing stage names
        "fast_path": service.batcher.fast_path,
        "staging_grown": getattr(engine, "staging_grown", None),
        "stage_attribution": stages,
        **registry_stamp(),  # global registry: xla.compiles + memory
    }))


def _input_bench(a) -> None:
    """`--mode input`: the input-pipeline story's read side — the SAME
    streaming `fit` over the SAME synthetic source, once through the
    legacy synchronous path (workers=0, depth=1: bare reads + the one-slot
    double buffer) and once through the staged pipeline
    (--input_workers decode threads + --input_depth device prefetch), one
    JSON artifact line reporting batches/sec and the data_wait share of
    epoch time for both (telemetry/analysis.data_report over each run's
    own trace — the numbers `trace report --data` would print).

    The synthetic source (pipeline/synthetic.py) charges
    --input_latency_ms of read latency PER BATCH, sized so the legacy
    path is INPUT-BOUND (docs/PERF.md states the committed geometry) —
    the regime the pipeline exists for; the measured claim is the
    data_wait share collapsing, not a lucky compute overlap. Both
    variants run under `statics.sanitize.no_host_sync` with the PR 10
    epoch-granular fetch budget (<= 6 fetches/epoch): the pipeline may
    add worker threads but ZERO consumer-side host syncs, and the
    artifact stamps the observed counts as evidence."""
    import shutil
    import tempfile
    import time

    from pytorch_ddp_mnist_tpu import telemetry
    from pytorch_ddp_mnist_tpu.data import normalize_images, synthetic_mnist
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.pipeline import SyntheticSource
    from pytorch_ddp_mnist_tpu.statics import sanitize
    from pytorch_ddp_mnist_tpu.telemetry import analysis
    from pytorch_ddp_mnist_tpu.train import TrainState, fit

    test = synthetic_mnist(256, seed=1)
    x_test = normalize_images(test.images)
    y_test = test.labels.astype(np.int32)
    latency_s = a.input_latency_ms / 1e3

    # warm the jit caches (train step + eval) OUTSIDE both measured runs:
    # whichever variant ran first would otherwise pay every compile and
    # the comparison would measure compile order, not the pipeline
    warm = SyntheticSource(2, a.batch_size, seed=0)
    fit(TrainState(init_mlp(jax.random.key(0)), jax.random.key(1)),
        warm, x_test, y_test, epochs=1, batch_size=a.batch_size, lr=0.01,
        log=lambda _m: None)

    def run(tag, workers, depth):
        out_dir = tempfile.mkdtemp(prefix=f"pdmt_input_{tag}_")
        try:
            telemetry.enable(out_dir, process_index=0)
            src = SyntheticSource(a.input_batches, a.batch_size,
                                  latency_s=latency_s, seed=0)
            state = TrainState(init_mlp(jax.random.key(0)),
                               jax.random.key(1))
            t0 = time.perf_counter()
            with sanitize.no_host_sync(max_fetches=a.epochs * 6) as sync:
                fit(state, src, x_test, y_test, epochs=a.epochs,
                    batch_size=a.batch_size, lr=0.01, log=lambda _m: None,
                    input_workers=workers, prefetch_depth=depth)
            wall = time.perf_counter() - t0
            rep = analysis.data_report(analysis.trace_files(out_dir))
        finally:
            # a failed variant (e.g. the fetch budget firing — the exact
            # regression this mode exists to catch) must not leave the
            # process-global tracer armed or the scratch dir behind
            telemetry.disable()
            shutil.rmtree(out_dir, ignore_errors=True)
        return {
            "workers": workers, "prefetch_depth": depth,
            "batches_per_sec": round(a.epochs * a.input_batches / wall, 1),
            "images_per_sec": round(
                a.epochs * a.input_batches * a.batch_size / wall, 1),
            "data_wait_share_p50": round(rep["share"]["p50"], 4),
            "data_wait_share_p95": round(rep["share"]["p95"], 4),
            "data_wait_p95_s": round(rep["data_wait"]["p95_s"], 6),
            # the PR 10 fetch-budget sanitizer's observed counts: the
            # artifact carries its own zero-new-host-sync evidence
            "fetches": sync.fetches,
            "fetch_budget": a.epochs * 6,
            "block_until_ready": sync.block_until_ready_calls,
        }

    legacy = run("legacy", 0, 1)
    piped = run("piped", a.input_workers, a.input_depth)
    print(json.dumps({
        "metric": "mnist_input_pipeline_batches_per_sec",
        "value": piped["batches_per_sec"],
        "unit": "batches/sec",
        # the legacy synchronous loader IS this mode's baseline: >1 means
        # the pipeline hid that much of the read latency
        "vs_baseline": (round(piped["batches_per_sec"]
                              / legacy["batches_per_sec"], 4)
                        if legacy["batches_per_sec"] else None),
        "epochs": a.epochs,
        "batch_size": a.batch_size,
        "batches_per_epoch": a.input_batches,
        "read_latency_ms_per_batch": a.input_latency_ms,
        "legacy": legacy,
        "pipeline": piped,
        **registry_stamp(),
    }))


def ddp_strategy_rows(*, per_chip_batch: int = 128, epochs: int = DDP_EPOCHS,
                      n_rows: int = 8192, strategies=None,
                      parity_steps: int = 3, parity_lr: float = 0.05,
                      n_devices: int = None, model: str = "mlp",
                      param_scale: int = 1,
                      overlap_variants=(False,)) -> list:
    """Measure the DDP scan program once per (gradient-communication
    strategy, overlap) combination on the full-device mesh, plus a
    1-device baseline, and return one row dict per combination:

        {strategy, overlap, model, param_scale, n_params, n_devices,
         per_chip_batch, images_per_sec, per_chip_images_per_sec,
         scaling_efficiency_vs_1dev, analytic_efficiency,
         bytes_on_wire_per_step_per_device, collective_s_p50,
         collectives_per_step, journal_overhead_share,
         parity_max_rel_diff_vs_pmean, parity_max_abs_diff_vs_pmean}

    `collectives_per_step` is the strategy's static collective schedule
    length (parallel.collectives.collective_schedule — the per-rank
    journal's per-step record count) and `journal_overhead_share` the
    MEASURED host cost of journaling one such step as a share of the
    row's measured step time (telemetry/cluster.py: the zero-overhead
    claim lands in the artifact, not just in a test).

    `scaling_efficiency_vs_1dev` = (N-device per-chip rate) / (1-device
    rate of the same per-chip batch) — 1.0 is perfect linear scaling.
    `parity_max_rel_diff_vs_pmean` re-runs `parity_steps` streaming DP
    steps per strategy from one init and reports the worst relative
    parameter divergence vs the pmean baseline (0.0 for pmean itself — the
    bitwise pin); `parity_lr` governs ONLY that probe (deliberately larger
    than the measured program's fixed lr=0.01 so drift has signal).
    `model`/`param_scale` pick the workload (models/zoo.py) — the
    model-size axis that shows where compressed/overlapped collectives
    cross over pmean. Shared by `bench.py --mode ddp` and
    `scripts/multichip_smoke.py` so the two artifacts can never measure
    different programs."""
    from pytorch_ddp_mnist_tpu.data import synthetic_mnist
    from pytorch_ddp_mnist_tpu.models import param_count, resolve_model
    from pytorch_ddp_mnist_tpu.parallel import ShardedSampler, collectives
    from pytorch_ddp_mnist_tpu.parallel import data_parallel_mesh
    from pytorch_ddp_mnist_tpu.parallel.ddp import (batch_sharding,
                                                    make_dp_train_step,
                                                    replicated)
    from pytorch_ddp_mnist_tpu.parallel.mesh import DATA_AXIS, make_mesh
    from pytorch_ddp_mnist_tpu.train.scan import (epoch_batch_indices,
                                                  make_dp_run_fn,
                                                  resident_images)
    from pytorch_ddp_mnist_tpu.utils import Timer
    from jax.sharding import NamedSharding, PartitionSpec as P

    strategies = list(strategies or collectives.STRATEGIES)
    spec = resolve_model(model, param_scale)
    # n_devices caps the mesh (e.g. multichip_smoke's pool holds a +1
    # spare device for the dry run's simulator that must NOT join the
    # measured mesh); default = every device, the bench-mode contract.
    mesh = (data_parallel_mesh() if n_devices is None
            else make_mesh([n_devices], [DATA_AXIS],
                           jax.devices()[:n_devices]))
    n = int(mesh.devices.size)
    n_rows = max(n_rows, per_chip_batch * n)  # at least one step per epoch

    split = synthetic_mnist(n_rows, seed=0)
    x_host = resident_images(split.images)
    y_host = split.labels.astype(np.int32)
    params_host = jax.tree_util.tree_map(np.asarray,
                                         spec.init(jax.random.key(0)))
    n_params = param_count(params_host)
    key_host = np.asarray(jax.random.key_data(jax.random.key(1)))

    def measure(mesh_m, comm, overlap=False):
        nm = int(mesh_m.devices.size)
        batch = per_chip_batch * nm
        rep = replicated(mesh_m)
        x_all = jax.device_put(x_host, rep)
        y_all = jax.device_put(y_host, rep)
        sampler = ShardedSampler(n_rows, num_replicas=1, rank=0, seed=42)
        idxs = []
        for e in range(epochs):
            sampler.set_epoch(e)
            idxs.append(epoch_batch_indices(sampler, batch))
        idxs = jax.device_put(np.stack(idxs),
                              NamedSharding(mesh_m, P(None, None, DATA_AXIS)))
        run = make_dp_run_fn(mesh_m, lr=0.01, kernel="xla", comm=comm,
                             overlap=overlap, model=model,
                             param_scale=param_scale)

        def fresh():
            # everything a window consumes is placed HERE, outside the
            # Timer — including the int8 residual (O(n_params) host alloc
            # + device transfer), so no strategy pays input prep on the
            # clock that the others don't
            args = [jax.device_put(params_host, rep),
                    jax.random.wrap_key_data(jax.device_put(key_host, rep))]
            if run.comm_state:
                args.append(collectives.place_comm_state(
                    mesh_m, params_host))
            return args

        def go(args):
            return run(args[0], args[1], x_all, y_all, idxs, *args[2:])

        losses = np.asarray(go(fresh())[2])            # compile + sync
        assert np.isfinite(losses).all()
        best = float("inf")
        for _ in range(3):
            args = fresh()
            with Timer("window") as t:
                out = go(args)
                t.sync(out[2])
            best = min(best, t.seconds)
        return idxs.size / best

    def parity_params(comm, overlap=False):
        """`parity_steps` streaming DP steps on the full mesh — the
        make_dp_train_step program the acceptance pins."""
        step = make_dp_train_step(mesh, lr=parity_lr, comm=comm,
                                  overlap=overlap, model=model,
                                  param_scale=param_scale)
        p = jax.device_put(params_host, replicated(mesh))
        k = jax.random.wrap_key_data(
            jax.device_put(key_host, replicated(mesh)))
        resid = step.place_comm_state(None, p) if step.comm_state else None
        bs = batch_sharding(mesh)
        b = per_chip_batch * n
        for s in range(parity_steps):
            rows = np.arange(s * b, (s + 1) * b) % n_rows
            x = jax.device_put(
                (x_host[rows].astype(np.float32) / 255.0), bs)
            y = jax.device_put(y_host[rows], bs)
            if step.comm_state:
                p, k, _, resid = step(p, k, x, y, resid)
            else:
                p, k, _ = step(p, k, x, y)
        return jax.tree_util.tree_map(np.asarray, p)

    def dispatch_probe(comm, overlap=False):
        """One streaming make_dp_train_step per strategy, decomposed by
        telemetry.dispatch.measure_dispatch_phases — the host-side half
        of the roofline: named phases for the O the analytic bound leaves
        unexplained (`trace report --overhead` reads the stamps back)."""
        from pytorch_ddp_mnist_tpu.telemetry.dispatch import (
            measure_dispatch_phases)
        step = make_dp_train_step(mesh, lr=0.01, comm=comm,
                                  overlap=overlap, model=model,
                                  param_scale=param_scale)
        rep = replicated(mesh)
        state = [jax.device_put(params_host, rep),
                 jax.random.wrap_key_data(jax.device_put(key_host, rep))]
        if step.comm_state:
            state.append(step.place_comm_state(None, state[0]))
        bs = batch_sharding(mesh)
        b = per_chip_batch * n
        x = jax.device_put(x_host[:b].astype(np.float32) / 255.0, bs)
        y = jax.device_put(y_host[:b], bs)

        def step_once():
            out = step(state[0], state[1], x, y, *state[2:])
            state[0], state[1] = out[0], out[1]
            if step.comm_state:
                state[2] = out[3]
            return out
        return measure_dispatch_phases(step_once, steps=8)

    def overhead_stamps(phases, step_s, bound_s):
        """The row stamps `trace report --overhead` consumes
        (telemetry/analysis.py overhead_from_artifact): O's share of the
        measured step, the probe's per-step phase seconds, how much of O
        the HOST phases (python_prestep + dispatch) explain, and the
        worst host phase. sync_wait is excluded from coverage and from
        `worst` — in the probe it is mostly the device computing, not
        overhead."""
        o_s = max(step_s - bound_s, 0.0)
        host_s = phases["python_prestep"] + phases["dispatch"]
        window = host_s + phases["sync_wait"]
        worst = max(("python_prestep", "dispatch"),
                    key=lambda p: phases[p])
        return {
            "overhead_share": (round(o_s / step_s, 4) if step_s > 0
                               else 0.0),
            "overhead_phases": {p: round(phases[p], 6)
                                for p in ("python_prestep", "dispatch",
                                          "device_idle", "sync_wait")},
            # clamped at 1.0: the streaming probe's host cost upper-bounds
            # the fused scan program's O (docs/PERF.md)
            "overhead_coverage": (round(min(host_s / o_s, 1.0), 4)
                                  if o_s > 0 else 1.0),
            "overhead_worst_phase": worst,
            "overhead_worst_share": (round(phases[worst] / window, 4)
                                     if window > 0 else 0.0),
            "overhead_probe_steps": int(phases["steps"]),
        }

    one_dev_rate = measure(make_mesh([1], [DATA_AXIS], jax.devices()[:1]),
                           "pmean")
    # The pmean row below re-runs this probe from a FRESH build and diffs
    # against it — a deliberate determinism pin (a nondeterministic
    # collective would surface as a nonzero pmean-vs-pmean diff in the
    # artifact), not a redundant measurement.
    p_ref = parity_params("pmean")
    ref_leaves = jax.tree_util.tree_leaves(p_ref)

    rows = []
    # analytic compute time per step (the roofline's C): strategy-
    # independent — the 1-device rate of the same per-chip batch
    compute_s = per_chip_batch / one_dev_rate
    for comm in strategies:
        # The isolated comm probe is overlap-AGNOSTIC (overlap is step-
        # program scheduling, not a different collective program), so it
        # is measured ONCE per strategy and stamped on every overlap row
        # — two probe runs of the same jitted program would publish
        # run-to-run variance as a fake overlap effect.
        probe = collectives.make_comm_probe(mesh, comm)
        secs = collectives.measure_collective_seconds(
            probe, jax.device_put(params_host, replicated(mesh)))
        coll_p50 = round(sorted(secs)[len(secs) // 2], 6)
        for overlap in overlap_variants:
            if overlap and comm in ("sharded", "int8"):
                # overlap composes as the IDENTITY for bucket-structured
                # strategies (apply_gradients never reads the flag): the
                # step program is the same, so the overlap row reuses the
                # base measurement — re-running a byte-identical program
                # would publish run-to-run variance as a fake overlap
                # effect (the same argument the probe comment makes)
                base = next((r for r in rows if r["strategy"] == comm
                             and not r["overlap"]), None)
                if base is not None:
                    # measurements copy (byte-identical program), but the
                    # analytic bound follows the row's overlap flag —
                    # max(C, M), the attribution convention (telemetry/
                    # costs.py) — so the stamp and `trace report --cost`
                    # can never disagree on the same row. The overhead
                    # stamps recompute too: O = T - bound shrinks with
                    # the tighter bound even though the probe's phase
                    # seconds (same program) copy over.
                    ov_bound = max(compute_s, coll_p50)
                    ov_step_s = ((per_chip_batch * n)
                                 / base["images_per_sec"])
                    rows.append({**base, "overlap": True,
                                 "analytic_efficiency": round(
                                     compute_s / ov_bound, 4),
                                 **overhead_stamps(
                                     {**base["overhead_phases"],
                                      "steps":
                                      base["overhead_probe_steps"]},
                                     ov_step_s, ov_bound)})
                    continue
            rate = measure(mesh, comm, overlap)
            leaves = jax.tree_util.tree_leaves(parity_params(comm, overlap))
            # rel over near-zero params overstates drift; the abs number is
            # the complementary view (both land in the artifact)
            rel = max(float(np.max(np.abs(a - b) / (np.abs(b) + 1e-12)))
                      for a, b in zip(leaves, ref_leaves))
            absd = max(float(np.max(np.abs(a - b)))
                       for a, b in zip(leaves, ref_leaves))
            # the roofline decomposition's analytic efficiency (telemetry/
            # costs.py): 1-device compute time C vs the isolated wire
            # probe M — the efficiency this strategy WOULD reach were the
            # step only compute + wire (measured efficiency below it is
            # overhead, the trace report --cost story)
            bound_s = (max(compute_s, coll_p50) if overlap
                       else compute_s + coll_p50)
            # the zero-overhead claim of the collective journal, MEASURED
            # in-artifact (telemetry/cluster.py): host seconds one
            # journaled step of this schedule costs, as a share of this
            # row's measured step time — the claim the docs pin lives in
            # the artifact, not just in a test
            from pytorch_ddp_mnist_tpu.telemetry import cluster
            schedule = collectives.collective_schedule(params_host, n,
                                                       comm,
                                                       overlap=overlap)
            journal_step_s = cluster.measure_journal_overhead(schedule)
            step_s = (per_chip_batch * n) / rate
            rows.append({
                "strategy": comm,
                "overlap": bool(overlap),
                "collectives_per_step": len(schedule),
                "journal_overhead_share": round(journal_step_s / step_s,
                                                6),
                "model": model,
                "param_scale": param_scale,
                "n_params": n_params,
                "n_devices": n,
                "per_chip_batch": per_chip_batch,
                "images_per_sec": round(rate, 1),
                "per_chip_images_per_sec": round(rate / n, 1),
                "scaling_efficiency_vs_1dev": round((rate / n)
                                                    / one_dev_rate, 4),
                "analytic_efficiency": round(compute_s / bound_s, 4),
                "bytes_on_wire_per_step_per_device":
                    collectives.bytes_on_wire(params_host, n, comm),
                "collective_s_p50": coll_p50,
                "parity_max_rel_diff_vs_pmean": rel,
                "parity_max_abs_diff_vs_pmean": absd,
                **overhead_stamps(dispatch_probe(comm, overlap),
                                  step_s, bound_s),
            })
    return rows


def _ddp_bench(a) -> None:
    """`--mode ddp`: the multichip story's read side — one artifact line
    per gradient-communication strategy (pmean / sharded / bf16, or the
    one picked by --ddp_comm) on the full-device mesh: images/sec,
    scaling efficiency vs a 1-device run, analytic wire bytes, isolated
    collective time, and parity drift vs the pmean baseline. Runs on real
    chips or `--xla_force_host_platform_device_count` fake devices alike
    (the artifact stamps compile/memory state; the caller's env names the
    backend)."""
    from pytorch_ddp_mnist_tpu.parallel import COMM_STRATEGIES
    strategies = (COMM_STRATEGIES if a.ddp_comm == "all" else (a.ddp_comm,))
    rows = ddp_strategy_rows(per_chip_batch=a.batch_size, epochs=a.epochs,
                             strategies=strategies, model=a.model,
                             param_scale=a.param_scale,
                             overlap_variants=(a.overlap,))
    stamp = registry_stamp()
    for r in rows:
        print(json.dumps({
            "metric": "mnist_ddp_train_images_per_sec_per_chip",
            "value": r["per_chip_images_per_sec"],
            "unit": "images/sec/chip",
            "vs_baseline": round(r["per_chip_images_per_sec"]
                                 / NOMINAL_BASELINE_DDP_IMGS_PER_SEC, 4),
            **{k: v for k, v in r.items()
               if k != "per_chip_images_per_sec"},
            **perf_fields(r["per_chip_images_per_sec"]),
            **stamp,
        }))


def measure_train_accuracy(kernel: str, dtype: str, superstep: int,
                           impl: str, epochs: int,
                           interpret: bool = False) -> "tuple[float, float]":
    """(final test accuracy, mean val loss) of an `epochs`-epoch training
    run of the given variant on the bench workload (synthetic MNIST, batch
    128, SGD 0.01, sampler seed 42).

    The ONE accuracy-measurement helper: both `--mode accuracy` (the
    north-star parity line) and the promotion gate's accuracy-parity runs
    (scripts/promote_epoch_dtype.py) call this, so the two can never
    silently measure different workloads."""
    from pytorch_ddp_mnist_tpu.data import normalize_images, synthetic_mnist
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.parallel import ShardedSampler
    from pytorch_ddp_mnist_tpu.train.loop import evaluate, make_eval_step
    from pytorch_ddp_mnist_tpu.train.scan import (epoch_batch_indices,
                                                  make_run_fn,
                                                  resident_images)

    train = synthetic_mnist(60000, seed=0)
    test = synthetic_mnist(10000, seed=1)
    x_all = jax.device_put(resident_images(train.images))
    y_all = jax.device_put(train.labels.astype(np.int32))
    sampler = ShardedSampler(60000, num_replicas=1, rank=0, seed=42)
    idxs = []
    for e in range(epochs):
        sampler.set_epoch(e)
        idxs.append(epoch_batch_indices(sampler, 128))
    run = make_run_fn(0.01, dtype=dtype, kernel=kernel, superstep=superstep,
                      interpret=interpret)
    params, _, losses = run(init_mlp(jax.random.key(0)),
                            jax.random.key(1, impl=impl),
                            x_all, y_all, jax.device_put(np.stack(idxs)))
    assert np.isfinite(np.asarray(losses)).all()
    # evaluate returns the (val_loss_ref_unit, mean_loss, accuracy) triple
    _, mean_loss, acc = evaluate(
        make_eval_step(), params,
        jax.numpy.asarray(normalize_images(test.images)),
        jax.numpy.asarray(test.labels.astype(np.int32)), 128)
    return float(acc), float(mean_loss)


def _accuracy_bench(a, on_tpu: bool) -> None:
    """`--mode accuracy`: the north-star SEMANTICS check (BASELINE.json:
    "identical 10-epoch test accuracy") as one machine-readable line.

    Trains the RESOLVED flagless configuration (auto kernel/dtype/superstep
    through the calibration, the requested --impl) AND the
    reference-semantics configuration (xla / f32 / threefry — the
    ddp_tutorial script restated) for --epochs epochs each, then reports
    the flagless config's final test accuracy with vs_baseline = ratio to
    the reference config's: 1.0 ± noise means every perf variant stack-up
    preserved the training outcome."""
    interpret = a.kernel == "pallas" and not on_tpu
    acc_auto, loss_auto = measure_train_accuracy(
        a.kernel, a.dtype, a.superstep, a.impl, a.epochs, interpret)
    acc_ref, loss_ref = measure_train_accuracy(
        "xla", "float32", 1, "threefry2x32", a.epochs)
    print(json.dumps({
        "metric": f"mnist_{a.epochs}epoch_test_accuracy",
        "value": round(acc_auto, 4),
        "unit": "fraction",
        "vs_baseline": round(acc_auto / acc_ref, 4) if acc_ref else None,
        # accuracy saturates on the synthetic stand-in; the continuous val
        # loss is the sensitive semantics signal (close ratios mean the
        # perf variant stack preserved the training outcome)
        "mean_val_loss": round(loss_auto, 6),
        "ref_mean_val_loss": round(loss_ref, 6),
        **ledger_stamp_fields(),
    }))


def _emit_backend_error(e: Exception, tag: str = "backend_unavailable") -> None:
    """One machine-readable JSON line for a backend that never came up —
    the driver records it instead of a traceback (VERDICT r2 #1). `tag`
    distinguishes a hard outage from a wedged-client state (where the
    backend is healthy and a plain rerun would succeed).

    The line also stamps `flight_recorder`: the path of the flight-recorder
    dump (wireup's probe/retry loop records every probe outcome into the
    bounded ring) — a failed hardware round is diagnosable from the JSON
    alone instead of the opaque tails of BENCH_r01-r05. Null when nothing
    was recorded (the failure predates the first probe) or the dump could
    not be written."""
    from pytorch_ddp_mnist_tpu.telemetry import flight, health_summary
    dump_path = flight.dump(reason=f"bench {tag}: {str(e)[:200]}")
    print(json.dumps({
        "metric": "mnist_train_images_per_sec_per_chip",
        "value": None,
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "error": f"{tag}: {e}",
        "flight_recorder": dump_path,
        # a failed round names what the watchdog saw degrade before the
        # death (empty when nothing fired / no watchdog ran) — the
        # BENCH_r02-r05 tails were opaque precisely for lack of this
        "health_summary": health_summary(),
        **ledger_stamp_fields(),
    }))


def main(argv=None) -> None:
    # Variant flags. The driver's flagless run resolves to the fastest
    # measured variant (Pallas + rbg on TPU — docs/PERF.md matrix); explicit
    # flags select the others, e.g. the reference-RNG-semantics
    # --kernel xla --impl threefry2x32.
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--kernel",
                   choices=("auto", "xla", "pallas", "pallas_rng",
                            "pallas_epoch"),
                   default="auto",
                   help="auto (default): on a single TPU chip the "
                        "whole-epoch kernel (pallas_epoch: weights "
                        "VMEM-resident across all steps, in-kernel SGD + "
                        "PRNG dropout; needs batch%%8==0 and batch<="
                        f"{EPOCH_KERNEL_MAX_BATCH}), on multi-chip meshes "
                        "the fused per-step Pallas kernel (real per-step "
                        "allreduce), off-TPU XLA autodiff. pallas_rng draws "
                        "dropout inside the per-step kernel (real TPU only)")
    p.add_argument("--dtype", choices=("auto", "float32", "bfloat16"),
                   default="auto",
                   help="auto (default): float32, unless the committed "
                        "hardware calibration (bench_calibration.json, "
                        "written by scripts/promote_epoch_dtype.py's "
                        "win+accuracy-parity gate) promotes the single-chip "
                        "epoch kernel to bf16 matmuls")
    p.add_argument("--impl", choices=("threefry2x32", "rbg"), default="rbg",
                   help="PRNG engine carried by the train key (dropout "
                        "stream); rbg (default) uses the TPU hardware "
                        "generator — measured 1.7x the whole-step rate vs "
                        "threefry key-derivation on the per-step kernels. "
                        "With --kernel pallas_epoch, threefry2x32 draws the "
                        "REFERENCE RNG stream in-kernel (VPU cipher, "
                        "bitwise models/mlp.py masks; docs/PERF.md round 4)")
    p.add_argument("--epochs", type=int, default=None,
                   help=f"fused epochs per timing window (default "
                        f"{FUSED_EPOCHS}); --mode accuracy trains this many "
                        f"REAL epochs (default {ACCURACY_EPOCHS} there — "
                        f"explicit values are always honored); never read "
                        f"by --mode stream")
    p.add_argument("--batch_size", type=int, default=128,
                   help="PER-CHIP batch (the reference flagship is 128; "
                        "larger values measure throughput scaling — the "
                        "gridded Pallas kernel handles any size)")
    p.add_argument("--superstep", type=int, default=0,
                   choices=(0, 1, 2, 4, 8),
                   help="whole-epoch kernel only: K SGD sub-steps per grid "
                        "iteration (identical math; amortizes per-iteration "
                        "cost). 0 (default) = auto: 1 unless the committed "
                        "hardware calibration promotes the single-chip "
                        "epoch kernel to a larger K (same win-gated "
                        "mechanism as --dtype auto). Rejected by name on "
                        "per-step kernels")
    p.add_argument("--ring", choices=("auto", "allgather", "reduce_scatter"),
                   default="auto",
                   help="DP epoch kernel only: in-kernel allreduce strategy "
                        "(auto: all-gather ring to 8 replicas, "
                        "reduce-scatter ring beyond). Rejected by name "
                        "elsewhere")
    p.add_argument("--unroll", type=int, default=1,
                   help="unroll factor for the per-step scan; measured "
                        "SLOWER than 1 at 2/4/8 (docs/PERF.md) — kept for "
                        "reproducing that negative result")
    p.add_argument("--mode", choices=("train", "stream", "eval", "accuracy",
                                      "serve", "ddp", "input"),
                   default="train",
                   help="train: the flagship device-train metric (driver "
                        "default); stream: NetCDF disk-streaming loader "
                        "throughput (the PnetCDF-path data plane); eval: "
                        "inference throughput of the reference eval pass "
                        "(full test set, dropout off, --epochs fused "
                        "repetitions per window); accuracy: the north-star "
                        "SEMANTICS check — final test accuracy of an "
                        "--epochs-epoch run (default 10 there) of the "
                        "resolved flagless config, vs_baseline = ratio to "
                        "the reference-semantics config (xla/f32/threefry) "
                        "trained identically; serve: open-loop Poisson "
                        "latency-percentile bench of the serve/ request "
                        "path (admission + micro-batching + bucketed AOT "
                        "engine); ddp: per-strategy DDP comms bench — one "
                        "JSON line per gradient-communication strategy on "
                        "the full-device mesh (images/sec, scaling "
                        "efficiency vs 1 device, wire bytes, parity drift "
                        "vs pmean; real chips or "
                        "--xla_force_host_platform_device_count fakes); "
                        "input: legacy loader vs the staged input pipeline "
                        "(pipeline/) on an input-bound synthetic source — "
                        "batches/sec + data_wait share of epoch time per "
                        "variant, under the no_host_sync fetch budget "
                        "(docs/DATA.md)")
    p.add_argument("--ddp_comm", choices=("all", "pmean", "sharded", "bf16",
                                          "int8"),
                   default="all",
                   help="ddp mode: which gradient-communication "
                        "strategy(ies) to measure (parallel/collectives.py; "
                        "default all four — scripts/bench_matrix.py "
                        "selects one per row)")
    p.add_argument("--overlap", action="store_true",
                   help="ddp mode: measure the bucket-pipelined variant "
                        "(one collective per gradient bucket launched off "
                        "its own backward slice; arXiv:1711.00705) of the "
                        "selected strategies instead of the whole-tree-"
                        "barrier form")
    p.add_argument("--model", choices=("mlp", "deep_mlp"), default="mlp",
                   help="ddp mode: model family for the measured workload "
                        "(models/zoo.py)")
    p.add_argument("--param_scale", type=int, default=1,
                   help="ddp mode: hidden-width multiplier (128*N units; "
                        "the model-size axis of the strategy crossover "
                        "table in docs/PERF.md)")
    p.add_argument("--num_workers", type=int, default=0,
                   help="stream mode: readahead threads")
    p.add_argument("--input_latency_ms", type=float, default=5.0,
                   help="input mode: synthetic per-batch read latency — "
                        "sized so the LEGACY path is input-bound (the "
                        "committed geometry in docs/PERF.md)")
    p.add_argument("--input_batches", type=int, default=48,
                   help="input mode: batches per epoch of the synthetic "
                        "source")
    p.add_argument("--input_workers", type=int, default=4,
                   help="input mode: background decode workers for the "
                        "piped variant (the legacy variant is always 0)")
    p.add_argument("--input_depth", type=int, default=2,
                   help="input mode: device-prefetch depth for the piped "
                        "variant (the legacy variant is always 1)")
    p.add_argument("--offered_rps", type=float, default=500.0,
                   help="serve mode: open-loop Poisson arrival rate")
    p.add_argument("--requests", type=int, default=1000,
                   help="serve mode: number of requests to drive")
    p.add_argument("--max_batch", type=int, default=64,
                   help="serve mode: largest coalesced batch / top compile "
                        "bucket")
    p.add_argument("--max_delay_ms", type=float, default=2.0,
                   help="serve mode: micro-batcher coalescing deadline")
    p.add_argument("--queue_depth", type=int, default=256,
                   help="serve mode: admission budget (requests beyond it "
                        "are rejected with retry-after)")
    p.add_argument("--no_fast", dest="serve_fast", action="store_false",
                   help="serve mode: force the LEGACY stack-at-flush path "
                        "instead of the staged fast path (persistent "
                        "staging + off-loop reply) — the A/B knob the "
                        "SERVE_r01 before/after artifact rides "
                        "(docs/SERVING.md §Fast path)")
    p.add_argument("--replicas", type=int, default=1,
                   help="serve mode: engine replicas behind the shared "
                        "admission layer (>1 = FleetService with SLO-aware "
                        "routing + failover — docs/SERVING.md §Replica "
                        "fleet & hot reload)")
    p.add_argument("--shape", choices=("poisson", "ramp", "spike"),
                   default="poisson",
                   help="serve mode: offered-load arrival shape — "
                        "homogeneous poisson, a 0.2x->1.8x linear ramp, or "
                        "a 3x mid-run burst (serve/loadgen.py)")
    from pytorch_ddp_mnist_tpu.parallel.wireup import backend_wait_env
    p.add_argument("--backend_wait", type=float,
                   default=backend_wait_env(3600.0),
                   help="seconds to keep polling for the accelerator backend "
                        "before giving up (the tunneled TPU is known to drop "
                        "for HOURS and recover — round-3's bench gave up at "
                        "300s mid-outage; on a healthy backend the first "
                        "probe answers immediately so a long budget costs "
                        "nothing. 0 = single immediate probe; "
                        "PDMT_BACKEND_WAIT sets the default)")
    a = p.parse_args(argv)
    if a.mode in ("stream", "serve") and a.epochs is not None:
        p.error(f"--epochs is never read by --mode {a.mode}")
    if a.mode == "serve":
        if a.offered_rps <= 0:
            p.error("--offered_rps must be > 0")
        if a.requests < 1:
            p.error("--requests must be >= 1")
        if a.max_batch < 1:
            p.error("--max_batch must be >= 1")
        if a.max_delay_ms < 0:
            p.error("--max_delay_ms must be >= 0")
        if a.queue_depth < 1:
            p.error("--queue_depth must be >= 1")
        if a.replicas < 1:
            p.error("--replicas must be >= 1")
    else:
        # serve-mode knobs rejected by name elsewhere (same mislabeled-
        # measurement rule as the train knobs below)
        for dest in ("offered_rps", "requests", "max_batch",
                     "max_delay_ms", "queue_depth", "serve_fast",
                     "replicas", "shape"):
            if getattr(a, dest) != p.get_default(dest):
                flag = "no_fast" if dest == "serve_fast" else dest
                p.error(f"--{flag} is a serve-mode "
                        f"knob; --mode {a.mode} never reads it")
    if a.mode != "input":
        # input-mode knobs rejected by name elsewhere (the same
        # mislabeled-measurement rule as the serve/ddp knobs)
        for dest in ("input_latency_ms", "input_batches", "input_workers",
                     "input_depth"):
            if getattr(a, dest) != p.get_default(dest):
                p.error(f"--{dest} {getattr(a, dest)} is an input-mode "
                        f"knob; --mode {a.mode} never reads it")
    else:
        if a.input_latency_ms < 0:
            p.error("--input_latency_ms must be >= 0")
        if a.input_batches < 1:
            p.error("--input_batches must be >= 1")
        if a.input_workers < 1:
            p.error("--input_workers must be >= 1 (the legacy variant "
                    "already measures 0)")
        if a.input_depth < 1:
            p.error("--input_depth must be >= 1")
    if a.mode != "ddp":
        for dest in ("ddp_comm", "overlap", "model", "param_scale"):
            if getattr(a, dest) != p.get_default(dest):
                p.error(f"--{dest} {getattr(a, dest)} is a ddp-mode knob; "
                        f"--mode {a.mode} never reads it")
    else:
        from pytorch_ddp_mnist_tpu.models import validate_model
        try:
            validate_model(a.model, a.param_scale)
        except ValueError as e:
            p.error(str(e))
    if a.epochs is None:   # per-mode default, a sentinel rather than a
        # value compare so an EXPLICIT --epochs 400 in accuracy mode is
        # honored instead of silently remapped
        a.epochs = (ACCURACY_EPOCHS if a.mode == "accuracy"
                    else DDP_EPOCHS if a.mode == "ddp"
                    else INPUT_EPOCHS if a.mode == "input"
                    else FUSED_EPOCHS)
    if a.epochs < 1:
        p.error("--epochs must be >= 1")
    if a.batch_size < 1:
        p.error("--batch_size must be >= 1")
    # Mode/knob compatibility, rejected by name — a variant flag that the
    # selected mode never reads would otherwise silently label a
    # measurement with a configuration it didn't run (the unroll lesson).
    # Defaults come from the parser itself, not literals, so a future
    # default change can't desynchronize this check (ADVICE r3).
    if a.mode != "train":
        # accuracy mode READS the variant config (it trains the resolved
        # flagless variant); it still rejects the knobs it never consults.
        # ddp mode reads batch_size (per-chip) + epochs + ddp_comm and
        # fixes the rest (xla kernel, f32 — the strategy is the variant);
        # input mode likewise reads batch_size + epochs and fixes the
        # step variant (the PIPELINE is the variant under measure).
        blocked = (("unroll", "ring", "batch_size") if a.mode == "accuracy"
                   else ("kernel", "dtype", "impl", "superstep", "unroll",
                         "ring") if a.mode in ("ddp", "input")
                   else ("kernel", "dtype", "impl", "superstep", "unroll",
                         "ring", "batch_size"))
        for dest in blocked:
            flag, val, default = f"--{dest}", getattr(a, dest), \
                p.get_default(dest)
            if val != default:
                p.error(f"{flag} {val} is a train-mode variant knob; "
                        f"--mode {a.mode} never reads it")
    if a.mode != "stream" and a.num_workers != 0:
        p.error(f"--num_workers is a stream-mode knob; --mode {a.mode} "
                f"never reads it")

    if a.mode == "stream":
        return _stream_bench(a)

    # An explicit JAX_PLATFORMS in the env wins over any backend the site
    # startup pre-registered (e.g. run the bench on CPU while the TPU tunnel
    # is down): same policy as the trainer CLI.
    from pytorch_ddp_mnist_tpu.parallel.wireup import (
        BackendUnavailableError, BackendWedgedError, _honor_platform_env,
        wait_for_backend)
    _honor_platform_env()

    # Compile accounting armed before ANY jit (pure jax.monitoring plumbing,
    # no backend touch): every device mode's artifact line carries the
    # process's true compile count via registry_stamp().
    from pytorch_ddp_mnist_tpu import telemetry
    telemetry.install_compile_listener()

    # Bounded backend retry: the tunneled TPU drops and recovers (BENCH_r02
    # died on a single un-retried probe); poll before the first real backend
    # query so a transient outage inside the window doesn't kill the bench.
    # Final failure = ONE named JSON line (machine-readable), not a traceback.
    # The default budget (1 h) deliberately exceeds any plausible caller
    # timeout: if the caller times out first and SIGTERMs us mid-poll, the
    # handler below still emits the honest error line — the artifact records
    # "polled Ns through an outage" instead of nothing at all.
    import signal
    import time as _time
    _wait_t0 = _time.monotonic()

    def _term_while_waiting(signum, frame):
        _emit_backend_error(RuntimeError(
            f"caller sent SIGTERM after {_time.monotonic() - _wait_t0:.0f}s "
            f"of backend polling (budget {a.backend_wait:.0f}s); backend "
            f"never came up"))
        sys.stdout.flush()
        sys.exit(1)

    try:
        prev_term = signal.signal(signal.SIGTERM, _term_while_waiting)
    except ValueError:       # non-main thread (programmatic caller): skip
        prev_term = None
    try:
        wait_for_backend(max_wait_s=a.backend_wait)
    except BackendWedgedError as e:
        # The tunnel recovered but THIS interpreter's jax client is stuck
        # behind a hung init (lock held by an abandoned probe thread). No
        # measurement has started yet, so a fresh process loses nothing:
        # re-exec once (env marker breaks loops, and lets tests opt out).
        # CLI path (argv is None) ONLY: a programmatic bench.main([...])
        # caller must get the error line back, not have its whole host
        # process replaced by a bench run.
        if argv is None and os.environ.get("PDMT_NO_REEXEC") != "1":
            os.environ["PDMT_NO_REEXEC"] = "1"
            print("bench: backend recovered but in-process client is wedged;"
                  " re-exec'ing a fresh interpreter",
                  file=sys.stderr, flush=True)
            os.execv(sys.executable,
                     [sys.executable, os.path.abspath(__file__)]
                     + sys.argv[1:])
        _emit_backend_error(e, tag="backend_wedged")
        sys.exit(1)
    except BackendUnavailableError as e:
        _emit_backend_error(e)
        sys.exit(1)
    finally:
        if prev_term is not None:   # backend up: a later SIGTERM is not a
            signal.signal(signal.SIGTERM, prev_term)  # backend-wait failure

    if a.mode == "eval":
        return _eval_bench(a)
    if a.mode == "serve":
        return _serve_bench(a)
    if a.mode == "ddp":
        return _ddp_bench(a)
    if a.mode == "input":
        return _input_bench(a)

    from pytorch_ddp_mnist_tpu.data import synthetic_mnist
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.parallel import ShardedSampler, data_parallel_mesh
    from pytorch_ddp_mnist_tpu.parallel.ddp import replicated
    from pytorch_ddp_mnist_tpu.train.scan import (epoch_batch_indices,
                                                  make_dp_run_fn,
                                                  resident_images)
    from pytorch_ddp_mnist_tpu.parallel.mesh import DATA_AXIS
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = data_parallel_mesh()
    n_chips = mesh.devices.size
    per_chip_batch = a.batch_size
    batch = per_chip_batch * n_chips

    split = synthetic_mnist(60000, seed=0)
    # uint8-resident dataset: 47 MB in HBM instead of 188 MB, 4x less HBM
    # read per batch gather; the scan body normalizes on device
    # (train/scan.py _gathered_x — same math as the host normalize).
    x_all = jax.device_put(resident_images(split.images), replicated(mesh))
    y_all = jax.device_put(split.labels.astype(np.int32), replicated(mesh))

    sampler = ShardedSampler(60000, num_replicas=1, rank=0, seed=42)
    idxs = []
    for e in range(a.epochs):
        sampler.set_epoch(e)
        idxs.append(epoch_batch_indices(sampler, batch))
    idxs = jax.device_put(np.stack(idxs),
                          NamedSharding(mesh, P(None, None, DATA_AXIS)))

    # Pallas needs Mosaic (TPU); `auto` resolves to it exactly there, and an
    # explicit --kernel pallas elsewhere runs interpreted so every variant
    # runs everywhere (same fallback as the trainer CLI).
    from pytorch_ddp_mnist_tpu.parallel.wireup import on_tpu_backend
    on_tpu = on_tpu_backend()
    # dtype 'auto' is float32 for the purposes of kernel resolution (only
    # the resolved-pallas_epoch case can promote it, below) — breaking the
    # kernel<->dtype resolution cycle deterministically.
    a.kernel = resolve_bench_kernel(
        a.kernel, "float32" if a.dtype == "auto" else a.dtype, on_tpu,
        n_chips, batch=a.batch_size, unroll=a.unroll)
    a.dtype, a.superstep = resolve_bench_config(a.dtype, a.superstep,
                                                a.kernel, n_chips=n_chips)
    if a.kernel in ("pallas_rng", "pallas_epoch") and not on_tpu:
        p.error(f"--kernel {a.kernel} needs a real TPU (the core PRNG has "
                "no interpreter lowering)")
    if a.superstep != 1 and a.kernel != "pallas_epoch":
        p.error(f"--superstep {a.superstep} is a whole-epoch-kernel knob; "
                f"the resolved kernel is {a.kernel!r} (use --kernel "
                f"pallas_epoch, or drop --superstep)")
    if a.ring != "auto" and (a.kernel != "pallas_epoch" or n_chips == 1):
        p.error(f"--ring {a.ring} selects the DP epoch kernel's in-kernel "
                f"allreduce strategy; it needs --kernel pallas_epoch on a "
                f"multi-chip mesh (resolved kernel {a.kernel!r}, "
                f"{n_chips} chip(s))")
    if a.mode == "accuracy":
        # semantics, not throughput: runs on ONE device regardless of mesh
        # size (the training outcome is device-count-invariant by the DP ==
        # serial equivalence the test suite pins)
        return _accuracy_bench(a, on_tpu)
    interpret = a.kernel == "pallas" and not on_tpu
    if a.kernel == "pallas_epoch" and n_chips == 1:
        # Whole-epoch kernel on the 1-chip mesh: the serial program IS the
        # DP program there (pmean over one device is the identity), without
        # shard_map in the way. unroll is forwarded so the scan layer's
        # named rejection fires instead of silently measuring unroll=1.
        from pytorch_ddp_mnist_tpu.train.scan import make_run_fn
        run_fn = make_run_fn(lr=0.01, dtype=a.dtype, kernel=a.kernel,
                             unroll=a.unroll, superstep=a.superstep)
    else:
        if a.kernel == "pallas_epoch":
            print("[experimental] pallas_epoch on a multi-chip mesh: "
                  "per-step DDP mean-gradients via the IN-KERNEL ICI ring "
                  "allreduce — this path has not executed on real "
                  "multi-chip hardware yet; treat the number accordingly",
                  file=sys.stderr, flush=True)
        run_fn = make_dp_run_fn(mesh, lr=0.01, dtype=a.dtype,
                                kernel=a.kernel, interpret=interpret,
                                unroll=a.unroll, superstep=a.superstep,
                                ring=a.ring)
    params_host = jax.tree_util.tree_map(np.asarray, init_mlp(jax.random.key(0)))
    key_host = np.asarray(jax.random.key_data(
        jax.random.key(1, impl=a.impl)))
    rep = replicated(mesh)

    def fresh():
        return (jax.device_put(params_host, rep),
                jax.random.wrap_key_data(
                    jax.device_put(key_host, rep), impl=a.impl))

    p, k = fresh()
    losses = np.asarray(run_fn(p, k, x_all, y_all, idxs)[2])  # compile + sync
    # Health watchdog over the measured loss curves (warn policy — a bench
    # never aborts): NaN/spike/throughput signals land in the registry, so
    # every artifact line's health_summary stamp (and a failed round's
    # error line) says WHAT degraded. The hard assert stays the last line
    # of defense for the artifact's validity.
    from pytorch_ddp_mnist_tpu.telemetry import HealthConfig, Watchdog
    # loss-spike detection is off here: every window restarts from FRESH
    # params, so each curve's full fresh-training dynamic range (first-step
    # loss >> converged loss) is expected, not an anomaly — NaN and
    # throughput anomalies are what a bench round can actually degrade on
    wd = Watchdog(HealthConfig(policy="warn",
                               loss_spike_ratio=float("inf")))
    wd.observe(losses, epoch=0, step=losses.size)
    assert np.isfinite(losses).all()

    from pytorch_ddp_mnist_tpu.utils import Timer
    best = float("inf")
    # best-of-5: each window is one fused-run dispatch (~1.3s at the
    # 400-epoch default); the tunneled chip shows ~15% invocation-to-
    # invocation swing (docs/PERF.md), so extra windows buy a tighter
    # floor nearly for free.
    for w in range(5):
        p, k = fresh()
        with Timer("window") as t:
            out = run_fn(p, k, x_all, y_all, idxs)
            t.sync(out[2])        # timer exit blocks on the loss curve
        best = min(best, t.seconds)
        wd.observe(np.asarray(out[2]), epoch=w + 1,
                   step=(w + 2) * out[2].size,
                   dt_s=t.seconds, imgs=idxs.size)

    imgs = idxs.size  # FUSED_EPOCHS * nbatches * batch
    imgs_per_sec = imgs / best
    per_chip = imgs_per_sec / n_chips
    print(json.dumps({
        "metric": "mnist_train_images_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(imgs_per_sec / NOMINAL_BASELINE_IMGS_PER_SEC, 4),
        **perf_fields(per_chip),
        **registry_stamp(),
    }))


if __name__ == "__main__":
    main()
