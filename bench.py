"""Benchmark: MNIST images/sec through the full data-parallel train step on
real hardware. Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N}

Workload = the flagship DDP config (SURVEY.md §6): the 118,272-param MLP,
per-chip batch 128, SGD lr=0.01, dropout active — i.e. the work one training
step of ddp_tutorial_multi_gpu.py does per rank, on TPU via the SPMD step.

vs_baseline: the reference publishes no numbers (BASELINE.md). The
driver-set north star is "match 2xA100 NCCL images/sec"; we pin that at a
nominal 1,000,000 images/sec (an optimistic latency-bound estimate for this
tiny MLP on 2 GPUs) and report value/1e6 so the ratio is stable across rounds.
"""

import json
import time

import numpy as np
import jax

NOMINAL_BASELINE_IMGS_PER_SEC = 1_000_000.0


def main() -> None:
    from pytorch_ddp_mnist_tpu.parallel.ddp import (
        make_dp_train_step, batch_sharding, replicated)
    from pytorch_ddp_mnist_tpu.parallel.mesh import data_parallel_mesh
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.data import synthetic_mnist, normalize_images

    mesh = data_parallel_mesh()
    n_chips = mesh.devices.size
    per_chip_batch = 128
    batch = per_chip_batch * n_chips

    split = synthetic_mnist(batch * 64, seed=0)
    x_all = normalize_images(split.images)
    y_all = split.labels.astype(np.int32)

    step = make_dp_train_step(mesh, lr=0.01)
    params = jax.device_put(init_mlp(jax.random.key(0)), replicated(mesh))
    key = jax.device_put(jax.random.key(1), replicated(mesh))
    bs = batch_sharding(mesh)

    # Pre-stage batches on device: measures the compute/collective path the
    # way the reference's images/sec would be measured with a saturated loader.
    batches = [(jax.device_put(x_all[i * batch:(i + 1) * batch], bs),
                jax.device_put(y_all[i * batch:(i + 1) * batch], bs))
               for i in range(64)]

    for x, y in batches[:3]:  # warmup + compile
        params, key, loss = step(params, key, x, y)
    jax.block_until_ready(loss)

    iters = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < 5.0:
        for x, y in batches:
            params, key, loss = step(params, key, x, y)
        iters += len(batches)
        jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    imgs_per_sec = iters * batch / dt
    per_chip = imgs_per_sec / n_chips
    print(json.dumps({
        "metric": "mnist_train_images_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(imgs_per_sec / NOMINAL_BASELINE_IMGS_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
