"""Worker process for the multi-process trace-aggregation test
(tests/test_trace_analysis.py) — NOT collected by pytest (no test_ prefix).

The mp_worker.py launch pattern without the jax.distributed rendezvous:
trace aggregation is pure file math over per-rank `events*.jsonl` siblings,
so each worker just IS one process index — it enables telemetry with an
explicit rank (no backend query) and emits the train loop's span shape
(epoch spans with data_wait / step_compute / eval children) with REAL
elapsed time. Rank >= 1 sleeps an extra `stall_s` inside each epoch — the
injected straggler the parent asserts the merged report isolates.

    python tests/trace_worker.py OUT_DIR RANK EPOCHS STALL_S
"""

import sys
import time


def main() -> int:
    out_dir, rank = sys.argv[1], int(sys.argv[2])
    epochs, stall_s = int(sys.argv[3]), float(sys.argv[4])

    from pytorch_ddp_mnist_tpu import telemetry

    trace = telemetry.enable(out_dir, process_index=rank)
    for epoch in range(epochs):
        with trace.span("epoch", epoch=epoch):
            t0 = time.perf_counter()
            time.sleep(0.005)
            trace.complete_span("data_wait", time.perf_counter() - t0,
                                batches=2)
            t0 = time.perf_counter()
            time.sleep(0.01 + (stall_s if rank else 0.0))  # the straggler
            trace.complete_span("step_compute", time.perf_counter() - t0,
                                steps=2)
            t0 = time.perf_counter()
            time.sleep(0.002)
            trace.complete_span("eval", time.perf_counter() - t0)
    reg = telemetry.MetricsRegistry()
    reg.counter("worker.epochs").inc(epochs)
    trace.snapshot(reg)
    telemetry.disable()
    return 0


if __name__ == "__main__":
    sys.exit(main())
