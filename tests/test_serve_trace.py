"""Request-scoped tracing + tail-latency attribution on the serve path
(serve/tracing.py, telemetry/analysis.serve_report, predicted-p99
admission): the acceptance pins.

  * the telescoped stage breakdown sums to within 5% of measured e2e
    (attribution that does not cover the e2e story is decoration);
  * tracing disabled -> no span records AND no extra host syncs on the
    serve path (the NullTracer zero-overhead contract, pinned with the
    same block_until_ready + device-fetch-counter technique as PR 6's
    watchdog pin);
  * served == direct stays BITWISE with tracing enabled (attribution must
    observe the request path, never perturb it);
  * `--admit predicted_p99` rejects under synthetic overload while raw
    queue-depth admission would still be admitting;
  * the checker enforces the request/batch span contract (non-empty
    request_id, batch links resolving, pipeline-ordered batch stages).
"""

import asyncio
import json
import pathlib

import numpy as np
import pytest
import jax

from pytorch_ddp_mnist_tpu import telemetry
from pytorch_ddp_mnist_tpu.models import init_mlp
from pytorch_ddp_mnist_tpu.serve import (AdmissionController, InferenceEngine,
                                         Rejected, ServeMetrics, ServeService)
from pytorch_ddp_mnist_tpu.serve import tracing
from pytorch_ddp_mnist_tpu.serve.loadgen import request_rows, run_loadgen
from pytorch_ddp_mnist_tpu.telemetry import analysis, flight

import importlib.util

_spec = importlib.util.spec_from_file_location(
    "check_telemetry",
    pathlib.Path(__file__).resolve().parents[1] / "scripts"
    / "check_telemetry.py")
_checker = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_checker)
check_main = _checker.main


@pytest.fixture(scope="module")
def engine():
    return InferenceEngine(init_mlp(jax.random.key(0)), max_batch=16)


def _traced_run(engine, tmp_path, n=60, offered_rps=3000.0):
    """One loadgen burst with JSONL tracing enabled into tmp_path; returns
    (loadgen output, trace dir). Always restores the NullTracer."""
    out_dir = tmp_path / "obs"
    telemetry.enable(str(out_dir))
    try:
        svc = ServeService(engine, max_delay_ms=2.0, max_depth=256,
                           registry=telemetry.MetricsRegistry())
        out = run_loadgen(svc, offered_rps=offered_rps, n_requests=n,
                          seed=0)
        telemetry.get_tracer().snapshot(svc.metrics.registry)
    finally:
        telemetry.disable()
    return out, str(out_dir)


# ---------------------------------------------------------------------------
# the catalog is one truth
# ---------------------------------------------------------------------------

def test_stage_catalog_pinned_across_write_and_read_sides():
    """serve/tracing.py (writer) and telemetry/analysis.py (reader, kept
    as literals so the file-loading checker stays framework-free) must
    name the same stages, spans, and coalesce reasons — a drift here makes
    the report silently empty."""
    assert tracing.STAGES == analysis.SERVE_STAGES
    assert tracing.REQUEST_SPAN == analysis.SERVE_REQUEST_SPAN
    assert tracing.BATCH_SPAN == analysis.SERVE_BATCH_SPAN
    assert tracing.COALESCE_REASONS == analysis.SERVE_COALESCE_REASONS
    assert tracing.BATCH_STAGE_SPANS == analysis.SERVE_BATCH_STAGE_ORDER


# ---------------------------------------------------------------------------
# acceptance: stages sum to e2e
# ---------------------------------------------------------------------------

def test_attribution_sums_to_e2e_within_5pct(engine, tmp_path):
    """The ISSUE acceptance pin: per-request stage durations telescope, so
    summed over the run they must cover the measured e2e within 5% — and
    every completed request must carry a full breakdown."""
    out, out_dir = _traced_run(engine, tmp_path, n=80)
    report = analysis.serve_report(analysis.trace_files(out_dir))
    assert report["requests"] == out["completed"]
    assert report["attributed"] == report["requests"]
    assert report["span_errors"] == []
    cov = report["attribution_coverage"]
    assert cov is not None and 0.95 <= cov <= 1.0 + 1e-9, cov
    # every stage of the catalog observed, n == attributed requests
    assert set(report["stages"]) == set(analysis.SERVE_STAGES)
    for st in report["stages"].values():
        assert st["n"] == report["attributed"]
    # per-request, not just aggregate: each exemplar tree's own stages
    # sum to its own e2e within 5%
    assert report["slowest"]
    for tree in report["slowest"]:
        assert abs(sum(tree["stages"].values()) - tree["e2e_s"]) \
            <= 0.05 * tree["e2e_s"]


def test_batch_links_resolve_and_checker_passes(engine, tmp_path):
    """Every request span names the batch that carried it, batch spans
    carry occupancy/coalesce, and the full trace passes the schema +
    structure + serve-contract checker including the --require serve.
    registry gate."""
    _out, out_dir = _traced_run(engine, tmp_path, n=60)
    recs = [json.loads(line) for line
            in open(pathlib.Path(out_dir) / "events.jsonl")]
    reqs = [r for r in recs if r.get("name") == "serve.request"]
    batches = [r for r in recs if r.get("name") == "serve.batch"]
    assert reqs and batches
    batch_ids = {b["attrs"]["batch_id"] for b in batches}
    for r in reqs:
        assert r["attrs"]["request_id"]
        assert r["attrs"]["batch"] in batch_ids
        assert r["attrs"]["ok"] is True
    for b in batches:
        assert 0 < b["attrs"]["occupancy"] <= 1.0
        assert b["attrs"]["coalesce"] in tracing.COALESCE_REASONS
        assert 1 <= b["attrs"]["n_real"] <= b["attrs"]["bucket"]
    # request ids are unique (the join key cannot be ambiguous)
    ids = [r["attrs"]["request_id"] for r in reqs]
    assert len(ids) == len(set(ids))
    assert check_main([out_dir]) == 0
    assert check_main(["--require", "serve.", out_dir]) == 0


def test_checker_rejects_serve_contract_violations(tmp_path):
    """The satellite's violation matrix: empty request_id, dangling batch
    link, unknown coalesce reason, occupancy > 1, and out-of-pipeline-order
    batch stages each fail the checker with a named message."""
    base = {"v": 1, "t_wall": 1.0, "t_mono": 1.0, "proc": 0}
    recs = [
        {**base, "kind": "meta", "name": "trace_start"},
        {**base, "kind": "span", "name": "serve.batch", "span": 1,
         "parent": None, "dur_s": 0.5,
         "attrs": {"batch_id": "b1", "bucket": 4, "n_real": 8,
                   "occupancy": 2.0, "coalesce": "vibes",
                   "t0_mono": 0.4, "t0_wall": 0.4}},
        {**base, "kind": "span", "name": "serve.pad_h2d", "span": 2,
         "parent": 1, "dur_s": 0.1,
         "attrs": {"batch_id": "b1", "t0_mono": 0.5, "t0_wall": 0.5}},
        {**base, "kind": "span", "name": "serve.batch_form", "span": 3,
         "parent": 1, "dur_s": 0.1,
         "attrs": {"batch_id": "b1", "t0_mono": 0.7, "t0_wall": 0.7}},
        {**base, "kind": "span", "name": "serve.request", "span": 4,
         "parent": None, "dur_s": 0.9,
         "attrs": {"request_id": "", "batch": "nope",
                   "t0_mono": 0.1, "t0_wall": 0.1}},
        # a batch span whose bucket/n_real fields went MISSING entirely —
        # the checker must flag the absence, not silently skip the check
        {**base, "kind": "span", "name": "serve.batch", "span": 5,
         "parent": None, "dur_s": 0.1,
         "attrs": {"batch_id": "b2", "coalesce": "size",
                   "t0_mono": 0.8, "t0_wall": 0.8}},
    ]
    p = tmp_path / "events.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    errors = analysis.serve_structure_errors(
        [dict(r, _line=i + 1) for i, r in enumerate(recs)])
    msgs = "\n".join(m for _, m in errors)
    assert "request_id" in msgs
    assert "no serve.batch span" in msgs
    assert "coalesce" in msgs
    assert "outside [1, bucket" in msgs
    assert "pipeline" in msgs
    assert "missing int bucket/n_real" in msgs
    assert check_main([str(tmp_path)]) == 1


# ---------------------------------------------------------------------------
# zero-overhead pin (tracing disabled) + bitwise pin (tracing enabled)
# ---------------------------------------------------------------------------

def test_tracing_disabled_no_spans_no_extra_syncs(engine):
    """The NullTracer contract, pinned like PR 6's watchdog: with
    telemetry DISABLED a full loadgen run forces zero block_until_ready
    calls, and the only device->host fetches are the engine's own
    logits/preds pair per flush — stage stamping adds clock reads, never
    syncs. And no span records exist anywhere: the tracer stays the
    NullTracer. The interception is the shared sanitizer
    (statics.sanitize.no_host_sync — this test's original monkeypatch
    idiom, promoted)."""
    from pytorch_ddp_mnist_tpu.statics import sanitize

    assert not telemetry.get_tracer().enabled
    svc = ServeService(engine, max_delay_ms=2.0, max_depth=256,
                       registry=telemetry.MetricsRegistry())
    with sanitize.no_host_sync() as sync:     # max_block_until_ready=0
        out = run_loadgen(svc, offered_rps=3000.0, n_requests=40, seed=0)
    assert out["completed"] == 40
    assert sync.armed and sync.block_until_ready_calls == 0
    # exactly 2 fetches (logits + preds) per flush — a tracing-induced
    # extra sync would break the equality
    assert sync.fetches == 2 * svc.batcher.flushes
    # the stage clock still fed the ALWAYS-ON attribution histograms
    assert svc.metrics.attribution()["stages"]["compute"]["n"] == 40


def test_served_equals_direct_bitwise_with_tracing_enabled(engine,
                                                           tmp_path):
    """Tracing observes, never perturbs: the coalescing path with full
    span emission stays bitwise-identical to a direct engine pass on the
    same rows."""
    rows = request_rows(6, seed=14)
    telemetry.enable(str(tmp_path / "obs"))
    try:
        svc = ServeService(engine, max_delay_ms=1000.0, max_depth=16,
                           registry=telemetry.MetricsRegistry())

        async def scenario():
            subs = [asyncio.ensure_future(svc.handle(r)) for r in rows]
            await asyncio.sleep(0)
            svc.batcher.flush()
            preds = await asyncio.gather(*subs)
            await svc.shutdown()
            return preds

        served = np.asarray(asyncio.run(scenario()), np.int32)
    finally:
        telemetry.disable()
    direct = engine.predict(rows)
    np.testing.assert_array_equal(served, direct)


# ---------------------------------------------------------------------------
# predicted-p99 admission
# ---------------------------------------------------------------------------

def test_predicted_p99_gauge_math():
    """predicted p99 = rolling p99 + depth / observed service rate; None
    until the window can answer both."""
    depth = {"v": 0}
    m = ServeMetrics(depth_fn=lambda: depth["v"])
    assert m.predicted_p99() is None          # no observations yet
    # 20 completions of 10ms, 1ms apart -> rate ~1000/s, p99 = 10ms
    for i in range(20):
        m.record_arrival()
        m.slo.record(0.010, t_done=i * 0.001)
    depth["v"] = 50
    pred = m.predicted_p99()
    rate = m.slo.service_rate()
    assert pred == pytest.approx(0.010 + 50 / rate)
    # published as a live gauge under the documented name
    assert m.registry.snapshot()["gauges"]["serve.predicted_p99_s"] == \
        pytest.approx(pred)


def test_predicted_p99_rejects_before_queue_depth_would():
    """THE acceptance pin: under synthetic overload (slow observed
    service, queue building) the predicted_p99 controller refuses while a
    raw depth controller with the same budget is still admitting — the
    SLO boundary fires first."""
    # observed regime: 50ms per request at ~20 rps -> a queue of 10 means
    # a new arrival's predicted tail is 0.05 + 10/20 = 0.55s
    class Pred:
        value = 0.55

        def __call__(self):
            return self.value

    depth_ctrl = AdmissionController(max_depth=64)
    slo_ctrl = AdmissionController(max_depth=64, mode="predicted_p99",
                                   slo_p99_s=0.100, predictor=Pred())
    for _ in range(10):
        depth_ctrl.admit()                # depth mode: happily admits 10
    slo_ctrl.admit()                      # depth 0 = the probe, admitted
    with pytest.raises(Rejected, match="predicted p99"):
        slo_ctrl.admit()                  # SLO mode: refuses at depth 1
    assert slo_ctrl.rejected_predicted == 1
    assert depth_ctrl.rejected == 0 and depth_ctrl.depth == 10 < 64


def test_predicted_p99_empty_server_probe_prevents_livelock():
    """Review-found livelock: the rolling window only updates on
    completions, so a stale past-SLO p99 with the queue drained to zero
    would otherwise reject 100%% of traffic forever. Depth 0 must always
    admit — the probe that refreshes the window."""
    ctrl = AdmissionController(max_depth=64, mode="predicted_p99",
                               slo_p99_s=0.010, predictor=lambda: 99.0)
    ctrl.admit()                          # empty server: probe admitted
    assert ctrl.depth == 1
    with pytest.raises(Rejected, match="predicted p99"):
        ctrl.admit()                      # in-flight work: boundary holds
    ctrl.release()                        # probe completes, queue empty
    ctrl.admit()                          # ...and the door reopens
    assert ctrl.rejected_predicted == 1 and ctrl.admitted == 2


def test_predicted_p99_degrades_to_depth_until_observed():
    """No observations -> predictor None -> the mode must NOT reject on a
    guess; the depth backstop still applies."""
    ctrl = AdmissionController(max_depth=2, mode="predicted_p99",
                               slo_p99_s=0.001, predictor=lambda: None)
    ctrl.admit()
    ctrl.admit()
    with pytest.raises(Rejected, match="queue depth"):
        ctrl.admit()


def test_predicted_p99_mode_rejects_under_real_overload(engine):
    """End-to-end: a service in predicted_p99 mode under a hot open loop
    starts refusing with the predicted-p99 reason while its queue is
    still far below max_depth (the raw-depth boundary never fires)."""
    svc = ServeService(engine, max_delay_ms=20.0, max_depth=10_000,
                       registry=telemetry.MetricsRegistry(),
                       admit_mode="predicted_p99", slo_p99_s=0.001)
    before = flight.get_flight_recorder().snapshot()
    out = run_loadgen(svc, offered_rps=5000.0, n_requests=300, seed=0)
    assert svc.admission.rejected_predicted > 0
    assert out["completed"] + out["rejected"] == 300
    # the depth backstop was never the binding constraint
    reasons = {e.get("reason") for e in
               flight.get_flight_recorder().snapshot()
               if e["kind"] == "serve_reject" and e not in before}
    assert "predicted_p99" in reasons and "queue_full" not in reasons


def test_admission_mode_validation():
    with pytest.raises(ValueError, match="mode"):
        AdmissionController(mode="vibes")
    with pytest.raises(ValueError, match="slo_p99_s"):
        AdmissionController(mode="predicted_p99", predictor=lambda: 1.0)
    with pytest.raises(ValueError, match="predictor"):
        AdmissionController(mode="predicted_p99", slo_p99_s=0.05)


# ---------------------------------------------------------------------------
# live dashboard + exemplars + export
# ---------------------------------------------------------------------------

def test_stats_op_attribution_matches_trace_naming(engine):
    """{"op": "stats"} answers an attribution section under EXACTLY the
    stage names the JSONL spans use — the dashboard and the trace must
    never disagree."""
    from pytorch_ddp_mnist_tpu.cli.serve import handle_request

    svc = ServeService(engine, max_delay_ms=2.0, max_depth=64,
                       registry=telemetry.MetricsRegistry())
    run_loadgen(svc, offered_rps=2000.0, n_requests=30, seed=0)
    resp = asyncio.run(handle_request(svc, {"op": "stats"}))
    attr = resp["serve"]["attribution"]
    assert set(attr) == {"stages", "predicted_p99_ms"}
    assert set(attr["stages"]) == set(tracing.STAGES)
    assert attr["predicted_p99_ms"] is not None
    # the stage histograms are in the unified registry snapshot too
    hists = resp["registry"]["histograms"]
    for stage in tracing.STAGES:
        assert f"serve.stage.{stage}_s" in hists
    # and the health op carries the same predicted number
    health = asyncio.run(handle_request(svc, {"op": "health"}))
    assert health["health"]["predicted_p99_ms"] == attr["predicted_p99_ms"]


def test_exemplar_heap_survives_equal_e2e_ties():
    """Review-found crash: under an injected constant clock (the
    documented deterministic-test mode) every request in a coalesced
    batch finishes with the SAME e2e — the heap tie-breaker must be
    unique per entry or heapq falls through to comparing the tree dicts
    (TypeError) on the success path of a served request."""
    tr = tracing.ServeTracer(clock=lambda: 0.0)
    b = tr.batch_begin("manual")
    b.mark_formed()
    b.mark_h2d(4)
    b.mark_computed()
    tr.batch_end(b, n_real=4)
    # the coalesced-batch shape: ALL requests begin before ANY finishes
    rs = []
    for _ in range(tracing.EXEMPLAR_K + 4):
        r = tr.begin()
        tr.admitted(r)
        tr.enqueued(r)
        r.batch = b
        rs.append(r)
    for r in rs:
        tr.finish(r, ok=True)      # equal e2e every time — must not raise
    assert len(tr.exemplars()) == tracing.EXEMPLAR_K


def test_drain_flushes_slowest_exemplars_to_flight(engine):
    """Shutdown leaves the slowest-K request trees in the flight ring —
    the post-mortem a killed server's dump carries."""
    rec = flight.get_flight_recorder()
    seq_before = rec.recorded
    svc = ServeService(engine, max_delay_ms=2.0, max_depth=64,
                       registry=telemetry.MetricsRegistry())
    run_loadgen(svc, offered_rps=2000.0, n_requests=40, seed=0)
    exemplars = [e for e in rec.snapshot()
                 if e["kind"] == "serve_exemplar"
                 and e["seq"] >= seq_before]
    assert 1 <= len(exemplars) <= tracing.EXEMPLAR_K
    worst = exemplars[0]
    assert worst["rank"] == 0 and worst["request_id"]
    assert set(worst["stages"]) == {f"{s}_s" for s in tracing.STAGES}
    # slowest-first ordering
    e2es = [e["e2e_s"] for e in exemplars]
    assert e2es == sorted(e2es, reverse=True)


def test_chrome_export_grows_request_batch_tracks_with_flows(engine,
                                                             tmp_path):
    """Perfetto export: request spans and the batch pipeline land on their
    own named threads, one flow arrow per request binds it to the batch
    that carried it."""
    from pytorch_ddp_mnist_tpu.telemetry.export import chrome_trace

    out, out_dir = _traced_run(engine, tmp_path, n=40)
    trace = chrome_trace(analysis.trace_files(out_dir))
    ev = trace["traceEvents"]
    names = {e["args"]["name"] for e in ev if e["ph"] == "M"
             and e["name"] == "thread_name"}
    assert {"serve requests", "serve batches"} <= names
    reqs = [e for e in ev if e["ph"] == "X"
            and e["name"] == "serve.request"]
    flows_s = [e for e in ev if e["ph"] == "s"]
    flows_f = [e for e in ev if e["ph"] == "f"]
    assert len(reqs) == out["completed"]
    assert len(flows_s) == len(flows_f) == len(reqs)
    assert {e["id"] for e in flows_s} == {e["id"] for e in flows_f}


def test_loadgen_reports_client_vs_server_latency(engine):
    """The client-side clock: client-perceived latency percentiles and the
    front-door delta ride the loadgen output (what bench --mode serve
    stamps)."""
    svc = ServeService(engine, max_delay_ms=2.0, max_depth=256,
                       registry=telemetry.MetricsRegistry())
    out = run_loadgen(svc, offered_rps=2000.0, n_requests=50, seed=0)
    cl = out["client_latency_ms"]
    assert set(cl) == {"p50", "p95", "p99", "mean", "max"}
    assert 0 < cl["p50"] <= cl["p95"] <= cl["p99"] <= cl["max"]
    fd = out["front_door_overhead_ms"]
    assert set(fd) == {"p50", "p95", "p99"}
    # the client awaited the server: its view can only be (noisily) slower.
    # Compare against the SLO window's EXACT p50 — the log-bucketed
    # histogram's pessimistic upper-edge p50 can read ~21% high, which on
    # a slow box dwarfs the sub-ms front-door delta (the same
    # quantization mismatch the front_door field itself avoids).
    assert cl["p50"] >= out["slo"]["rolling_p50_ms"] - 0.5


def test_front_door_delta_matches_window_population(engine):
    """Runs longer than the SLO window must compare MATCHED populations:
    the client side restricts itself to its last min(n, window)
    completions (the window's own selection rule), so the delta measures
    the front door, not distribution drift across the run. Pinned with a
    shrunken window so the tail path actually exercises."""
    from pytorch_ddp_mnist_tpu.serve.metrics import SLOWindow

    svc = ServeService(engine, max_delay_ms=2.0, max_depth=256,
                       registry=telemetry.MetricsRegistry())
    # the metrics gauges/deltas read svc.metrics.slo late-bound, so a
    # smaller window can be injected before traffic flows
    svc.metrics.slo = SLOWindow(window=8)
    out = run_loadgen(svc, offered_rps=2000.0, n_requests=50, seed=0)
    assert out["completed"] == 50
    assert out["slo"]["window_n"] == 8          # window saturated
    fd = out["front_door_overhead_ms"]
    # matched tails: the delta stays front-door-sized even though the
    # full-run client percentiles cover 50 completions vs the window's 8
    assert all(-1.0 < v < 50.0 for v in fd.values()), fd


def test_trace_report_serve_cli_round_trip(engine, tmp_path, capsys):
    """`trace report --serve` on a traced run: exit 0, the table names
    every stage, coverage is printed; --json round-trips; a non-serve
    trace dir exits 1."""
    from pytorch_ddp_mnist_tpu.cli.trace import main as trace_main

    _out, out_dir = _traced_run(engine, tmp_path, n=40)
    assert trace_main(["report", "--serve", out_dir]) == 0
    text = capsys.readouterr().out
    for stage in tracing.STAGES:
        assert stage in text
    assert "attribution coverage" in text
    assert trace_main(["report", "--serve", "--json", out_dir]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["report"] == "serve_trace_attribution"
    empty = tmp_path / "empty"
    empty.mkdir()
    assert trace_main(["report", "--serve", str(empty)]) == 1
