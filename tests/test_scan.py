"""Epoch-scanned training (train/scan.py): the cached/jitted epoch must
reproduce the streaming loop bit-for-bit (same sampler indices, same RNG
split chain, same losses), serially and over the 8-virtual-device DP mesh."""

import numpy as np
import jax
import jax.numpy as jnp

from pytorch_ddp_mnist_tpu.data import synthetic_mnist, normalize_images, BatchLoader
from pytorch_ddp_mnist_tpu.models import init_mlp
from pytorch_ddp_mnist_tpu.parallel import ShardedSampler, data_parallel_mesh
from pytorch_ddp_mnist_tpu.train import TrainState, fit
from pytorch_ddp_mnist_tpu.train.scan import (
    epoch_batch_indices, make_epoch_fn, make_dp_epoch_fn, fit_cached)


def _data(n_train=512, n_test=128):
    train = synthetic_mnist(n_train, seed=0)
    test = synthetic_mnist(n_test, seed=1)
    return (normalize_images(train.images), train.labels.astype(np.int32),
            normalize_images(test.images), test.labels.astype(np.int32))


def test_snapshot_eval_matches_per_epoch_eval():
    """make_snapshot_eval_step — ONE vmapped program replaying every
    epoch's eval (the fused trainer's path, killing E dispatch round-trips)
    — must reproduce make_eval_step + evaluate's per-epoch triples."""
    from pytorch_ddp_mnist_tpu.train.loop import (
        evaluate, make_eval_step, make_snapshot_eval_step, val_summary)
    _, _, xt, yt = _data()
    snaps = [init_mlp(jax.random.key(s)) for s in range(3)]
    p_snaps = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *snaps)
    ps_all, corr_all = make_snapshot_eval_step()(
        p_snaps, jnp.asarray(xt), jnp.asarray(yt))
    ps_all, corr_all = np.asarray(ps_all), np.asarray(corr_all)
    es = make_eval_step()
    for e, p in enumerate(snaps):
        ref = evaluate(es, p, xt, yt, batch_size=48)   # ragged last batch
        got = val_summary(ps_all[e], corr_all[e], batch_size=48)
        np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_epoch_batch_indices_match_loader():
    x, y, *_ = _data()
    s = ShardedSampler(512, num_replicas=2, rank=1)
    s.set_epoch(3)
    idx = epoch_batch_indices(s, 64)
    s2 = ShardedSampler(512, num_replicas=2, rank=1)
    s2.set_epoch(3)
    loader = BatchLoader(x, y, s2, batch_size=64)
    assert idx.shape == (len(loader), 64)
    for row, (bx, by) in zip(idx, loader):
        np.testing.assert_allclose(x[row], bx)
        np.testing.assert_array_equal(y[row], by)


def test_serial_scan_matches_streaming_fit():
    x, y, xt, yt = _data()
    s1 = ShardedSampler(512, num_replicas=1, rank=0)
    loader = BatchLoader(x, y, s1, batch_size=64)

    stream_lines = []
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(42))
    fit(state, loader, xt, yt, epochs=2, batch_size=64, lr=0.01,
        log=stream_lines.append)

    scan_lines = []
    s2 = ShardedSampler(512, num_replicas=1, rank=0)
    state2 = TrainState(init_mlp(jax.random.key(0)), jax.random.key(42))
    fit_cached(state2, x, y, s2, xt, yt, epochs=2, batch_size=64, lr=0.01,
               log=scan_lines.append)

    for a, b in zip(stream_lines, scan_lines):
        # identical up to the timing suffix: compare the loss fields
        assert a.split("[")[0].split("img")[0][:60] == b.split("[")[0][:60], \
            (a, b)


def test_dp_scan_epoch_runs_and_learns():
    mesh = data_parallel_mesh()
    n_dev = mesh.devices.size
    x, y, xt, yt = _data(n_train=1024)
    s = ShardedSampler(1024, num_replicas=1, rank=0)
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(42))
    lines = []
    state = fit_cached(state, x, y, s, xt, yt, epochs=2,
                       batch_size=16 * n_dev, lr=0.05, mesh=mesh,
                       log=lines.append)
    first = float(lines[0].split("mean_train=")[1].split(" ")[0])
    last = float(lines[-1].split("mean_train=")[1].split(" ")[0])
    assert last < first  # training progresses under the scanned DP epoch
    assert np.isfinite(last)


def test_dp_scan_matches_serial_scan_first_epoch_loss():
    """DP over 8 devices with the same global batch = serial, since grads are
    pmean'ed: the first-step loss (pre-update) must match exactly and the
    epoch trajectory closely (dropout masks differ per replica)."""
    mesh = data_parallel_mesh()
    n_dev = mesh.devices.size
    x, y, xt, yt = _data(n_train=512)
    B = 8 * n_dev

    s1 = ShardedSampler(512, num_replicas=1, rank=0)
    e_serial = make_epoch_fn(0.01)
    p = init_mlp(jax.random.key(0))
    _, _, losses_serial = e_serial(
        p, jax.random.key(42), x, y.astype(np.int32),
        epoch_batch_indices(s1, B))

    s2 = ShardedSampler(512, num_replicas=1, rank=0)
    e_dp = make_dp_epoch_fn(mesh, 0.01)
    p2 = init_mlp(jax.random.key(0))
    _, _, losses_dp = e_dp(
        p2, jax.random.key(42), x, y.astype(np.int32),
        epoch_batch_indices(s2, B))

    # step-0 forward happens before any update; dropout masks differ between
    # the serial draw and the per-replica folded draws, so compare loosely.
    np.testing.assert_allclose(np.asarray(losses_serial)[0],
                               np.asarray(losses_dp)[0], rtol=0.15)
    assert np.asarray(losses_dp).shape == np.asarray(losses_serial).shape


def test_dp_run_fn_matches_per_epoch_calls():
    """The E-epoch fused program must equal E sequential epoch programs."""
    import jax.numpy as jnp
    from pytorch_ddp_mnist_tpu.train.scan import make_dp_run_fn
    mesh = data_parallel_mesh()
    n_dev = mesh.devices.size
    x, y, *_ = _data(n_train=256)
    B = 8 * n_dev
    s = ShardedSampler(256, num_replicas=1, rank=0)
    idxs = []
    for e in range(3):
        s.set_epoch(e)
        idxs.append(epoch_batch_indices(s, B))
    idxs = np.stack(idxs)

    run = make_dp_run_fn(mesh, 0.01)
    p = init_mlp(jax.random.key(0))
    _, _, fused = run(p, jax.random.key(42), x, y, idxs)

    ep = make_dp_epoch_fn(mesh, 0.01)
    p2, k2 = init_mlp(jax.random.key(0)), jax.random.key(42)
    seq = []
    for e in range(3):
        p2, k2, losses = ep(p2, k2, x, y, idxs[e])
        seq.append(np.asarray(losses))
    np.testing.assert_allclose(np.asarray(fused), np.stack(seq), rtol=2e-5)


def test_fused_fit_cached_matches_per_epoch_fit_cached():
    """fit_cached(fused=True) — all epochs as one program + snapshot replay —
    must print the same loss fields as the per-epoch cached loop, serially
    and over the DP mesh, and fire the epoch hook per epoch."""
    mesh = data_parallel_mesh()
    n_dev = mesh.devices.size
    x, y, xt, yt = _data(n_train=512)

    def run(fused, use_mesh):
        s = ShardedSampler(512, num_replicas=1, rank=0)
        state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(42))
        lines, hooks, keys = [], [], []

        def hook(e, st):
            hooks.append(e)
            keys.append(np.asarray(jax.random.key_data(st.key)))

        fit_cached(state, x, y, s, xt, yt, epochs=3,
                   batch_size=(16 * n_dev if use_mesh else 64), lr=0.05,
                   mesh=mesh if use_mesh else None, fused=fused,
                   log=lines.append, epoch_hook=hook)
        assert hooks == [0, 1, 2]
        import re
        vals = []
        for ln in lines:
            m = re.match(r"Epoch=(\d+), train_loss=([\d.e-]+), "
                         r"val_loss=([\d.e-]+)", ln)
            vals.append((int(m.group(1)), float(m.group(2)),
                         float(m.group(3))))
        return vals, keys

    for use_mesh in (False, True):
        fused, f_keys = run(True, use_mesh)
        per_epoch, p_keys = run(False, use_mesh)
        for (ef, tf, vf), (ep, tp, vp) in zip(fused, per_epoch):
            assert ef == ep
            # train losses are computed inside the identical scan: exact.
            np.testing.assert_allclose(tf, tp, rtol=0, atol=0)
            # val goes through snapshot pmean vs carry pmean: the per-epoch
            # path re-rounds params between epochs ((x*N)/N != x), so allow
            # float-rounding-level drift.
            np.testing.assert_allclose(vf, vp, rtol=1e-6)
        # hooks must see each epoch's OWN RNG key (resume-faithful state),
        # identical to the per-epoch path's key chain.
        for fk, pk in zip(f_keys, p_keys):
            np.testing.assert_array_equal(fk, pk)


def test_uint8_resident_dataset_matches_f32():
    """The HBM-resident uint8 dataset (device-side normalize per gather)
    must reproduce the host-normalized f32 dataset to float-rounding level
    (same math; XLA may fuse the normalize chain differently) — serially and
    on the DP mesh."""
    from pytorch_ddp_mnist_tpu.train.scan import resident_images, make_dp_run_fn
    from pytorch_ddp_mnist_tpu.parallel.ddp import replicated
    from pytorch_ddp_mnist_tpu.parallel.mesh import make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    train = synthetic_mnist(256, seed=0)
    x_f32 = normalize_images(train.images)
    x_u8 = resident_images(train.images)
    assert x_u8.dtype == np.uint8 and x_u8.shape == (256, 784)
    y = train.labels.astype(np.int32)
    s = ShardedSampler(256, num_replicas=1, rank=0)
    s.set_epoch(0)
    idx = epoch_batch_indices(s, 64)

    fn = make_epoch_fn(0.05)
    out = {}
    for name, x_all in (("f32", x_f32), ("u8", x_u8)):
        p, k, losses = fn(init_mlp(jax.random.key(0)), jax.random.key(7),
                          jnp.asarray(x_all), jnp.asarray(y), idx)
        out[name] = (p, np.asarray(losses))
    np.testing.assert_allclose(out["f32"][1], out["u8"][1],
                               rtol=1e-6, atol=1e-7)
    for a, b in zip(jax.tree_util.tree_leaves(out["f32"][0]),
                    jax.tree_util.tree_leaves(out["u8"][0])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)

    mesh = make_mesh([4], ["dp"], jax.devices()[:4])
    rep = replicated(mesh)
    shard = NamedSharding(mesh, P(None, None, "dp"))
    dp = make_dp_run_fn(mesh, 0.05)
    dp_out = {}
    for name, x_all in (("f32", x_f32), ("u8", x_u8)):
        p, k, losses = dp(jax.device_put(init_mlp(jax.random.key(0)), rep),
                          jax.device_put(jax.random.key(7), rep),
                          jax.device_put(x_all, rep),
                          jax.device_put(y, rep),
                          jax.device_put(idx[None], shard))
        dp_out[name] = np.asarray(losses)
    np.testing.assert_allclose(dp_out["f32"], dp_out["u8"],
                               rtol=1e-6, atol=1e-7)


def test_scan_pallas_kernel_matches_xla_kernel():
    """The scanned Pallas body must reproduce the scanned XLA body exactly
    (same dropout stream, interpreter math) — serial and DP variants."""
    from pytorch_ddp_mnist_tpu.train.scan import make_epoch_fn, make_dp_run_fn
    from pytorch_ddp_mnist_tpu.parallel.ddp import replicated
    from pytorch_ddp_mnist_tpu.parallel.mesh import make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    n, bs = 256, 64
    rng = np.random.default_rng(5)
    x_all = jnp.asarray(rng.normal(size=(n, 784)).astype(np.float32))
    y_all = jnp.asarray(rng.integers(0, 10, n).astype(np.int32))
    idx = jnp.asarray(
        rng.integers(0, n, (4, bs)).astype(np.int32))

    def run(fn_maker, **kw):
        fn = fn_maker(0.05, **kw)
        params = init_mlp(jax.random.key(0))
        key = jax.random.key(1)
        return fn(params, key, x_all, y_all, idx)

    p_x, _, l_x = run(make_epoch_fn)
    p_p, _, l_p = run(make_epoch_fn, kernel="pallas", interpret=True)
    np.testing.assert_allclose(np.asarray(l_p), np.asarray(l_x),
                               rtol=1e-6, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(p_p),
                    jax.tree_util.tree_leaves(p_x)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)

    mesh = make_mesh([4], ["dp"], jax.devices()[:4])
    rep, shard = replicated(mesh), NamedSharding(mesh, P(None, None, "dp"))

    def run_dp(**kw):
        fn = make_dp_run_fn(mesh, 0.05, **kw)
        params = jax.device_put(init_mlp(jax.random.key(0)), rep)
        key = jax.device_put(jax.random.key(1), rep)
        idxs = jax.device_put(idx[None], shard)
        return fn(params, key, jax.device_put(x_all, rep),
                  jax.device_put(y_all, rep), idxs)

    pd_x, _, ld_x = run_dp()
    pd_p, _, ld_p = run_dp(kernel="pallas", interpret=True)
    np.testing.assert_allclose(np.asarray(ld_p), np.asarray(ld_x),
                               rtol=1e-6, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(pd_p),
                    jax.tree_util.tree_leaves(pd_x)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)
