"""Wireup env-derivation parity vs the reference `distributed` class branches
(mnist_cpu_mp.py:41-191). Single-process here; the true multi-process
rendezvous is exercised by tests/test_multiprocess.py."""

import pytest

from pytorch_ddp_mnist_tpu.parallel.wireup import (
    _derive, _first_host, detect_method, initialize_runtime)


def test_first_host_parsing():
    assert _first_host("nid[0012-0015,0020]") == "nid0012"
    assert _first_host("node1,node2") == "node1"
    assert _first_host("host07") == "host07"
    assert _first_host("gpu[3,5-9]") == "gpu3"


def test_slurm_derivation(monkeypatch):
    monkeypatch.setenv("SLURM_PROCID", "3")
    monkeypatch.setenv("SLURM_NTASKS", "8")
    monkeypatch.setenv("SLURM_LOCALID", "1")
    monkeypatch.setenv("SLURM_NODELIST", "nid[0040-0043]")
    monkeypatch.setenv("SLURM_JOBID", "12345")
    rank, size, local, coord = _derive("slurm")
    assert (rank, size, local) == (3, 8, 1)
    host, port = coord.rsplit(":", 1)
    assert host == "nid0040"
    assert 12000 <= int(port) < 32000
    assert detect_method() == "slurm"


def test_openmpi_derivation(monkeypatch):
    for k in ("SLURM_PROCID", "SLURM_NTASKS"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "2")
    monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "4")
    monkeypatch.setenv("OMPI_COMM_WORLD_LOCAL_RANK", "2")
    monkeypatch.setenv("MASTER_ADDR", "10.0.0.1")
    monkeypatch.setenv("MASTER_PORT", "23456")
    rank, size, local, coord = _derive("openmpi")
    assert (rank, size, local, coord) == (2, 4, 2, "10.0.0.1:23456")
    assert detect_method() == "openmpi"


def test_mpich_derivation(monkeypatch):
    monkeypatch.setenv("PMI_RANK", "1")
    monkeypatch.setenv("PMI_SIZE", "4")
    rank, size, local, coord = _derive("mpich")
    assert (rank, size) == (1, 4)


def test_env_fallback_and_single(monkeypatch):
    for k in ("SLURM_PROCID", "SLURM_NTASKS", "OMPI_COMM_WORLD_RANK",
              "PMI_RANK", "RANK", "WORLD_SIZE", "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(k, raising=False)
    assert detect_method() == "single"
    rt = initialize_runtime("auto")
    assert rt.size == 1 and rt.rank == 0 and not rt.initialized
    # single-process collectives degrade gracefully
    assert rt.reduce_max(3.5) == 3.5
    rt.barrier()
    rt.finalize()

    monkeypatch.setenv("RANK", "0")
    monkeypatch.setenv("WORLD_SIZE", "2")
    assert detect_method() == "env"
    rank, size, local, coord = _derive("env")
    assert (rank, size) == (0, 2)


def test_unknown_method_raises():
    with pytest.raises(ValueError):
        _derive("nccl")


def test_reference_alias_spellings(monkeypatch):
    """The reference's literal --wireup_method values resolve to our branches
    (mnist_cpu_mp.py:47-188, mnist_pnetcdf_cpu_mp.py:184-211)."""
    from pytorch_ddp_mnist_tpu.parallel.wireup import resolve_method
    assert resolve_method("nccl-slurm") == "slurm"
    assert resolve_method("nccl-openmpi") == "openmpi"
    assert resolve_method("nccl-mpich") == "mpich"
    assert resolve_method("gloo") == "env"
    assert resolve_method("mpich") == "mpich"
    assert resolve_method("auto") == "auto"

    # _derive accepts the aliases directly
    monkeypatch.setenv("SLURM_PROCID", "1")
    monkeypatch.setenv("SLURM_NTASKS", "4")
    monkeypatch.setenv("SLURM_NODELIST", "n[01-04]")
    rank, size, _, _ = _derive("nccl-slurm")
    assert (rank, size) == (1, 4)

    # and the config CLI accepts a reference launch line verbatim
    from pytorch_ddp_mnist_tpu.train.config import configure
    cfg = configure(["--parallel", "--wireup_method", "nccl-mpich"])
    assert cfg["trainer"]["wireup_method"] == "mpich"
    cfg = configure(["--parallel", "--wireup_method", "gloo"])
    assert cfg["trainer"]["wireup_method"] == "env"


def test_tpu_pod_detection(monkeypatch):
    """MULTI-worker Cloud TPU pod metadata detects as 'tpu'; a single-worker
    hostname list (every TPU VM exports one) does NOT; explicit scheduler
    env wins (a job srun'd onto TPU VMs follows the launcher)."""
    for k in ("SLURM_PROCID", "SLURM_NTASKS", "OMPI_COMM_WORLD_RANK",
              "PMI_RANK", "RANK", "WORLD_SIZE"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "w0")
    assert detect_method() == "single"
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "w0,w1")
    assert detect_method() == "tpu"
    monkeypatch.setenv("SLURM_PROCID", "0")
    monkeypatch.setenv("SLURM_NTASKS", "2")
    assert detect_method() == "slurm"
    # the CLI accepts the method name
    from pytorch_ddp_mnist_tpu.train.config import configure
    cfg = configure(["--parallel", "--wireup_method", "tpu"])
    assert cfg["trainer"]["wireup_method"] == "tpu"


def test_missing_env_named_errors(monkeypatch):
    """A missing launcher variable raises a named, actionable error (reference
    raises per-variable, mnist_cpu_mp.py:57-89) — not a bare KeyError."""
    for k in ("SLURM_PROCID", "SLURM_NTASKS", "OMPI_COMM_WORLD_RANK",
              "OMPI_COMM_WORLD_SIZE", "PMI_RANK", "PMI_SIZE"):
        monkeypatch.delenv(k, raising=False)
    with pytest.raises(RuntimeError, match="SLURM_PROCID"):
        _derive("slurm")
    with pytest.raises(RuntimeError, match="OMPI_COMM_WORLD_RANK"):
        _derive("openmpi")
    with pytest.raises(RuntimeError, match="PMI_RANK"):
        _derive("mpich")
    monkeypatch.setenv("PMI_RANK", "0")
    with pytest.raises(RuntimeError, match="PMI_SIZE"):
        _derive("mpich")


def test_backend_wait_env_parsing(monkeypatch, capsys):
    """PDMT_BACKEND_WAIT: tolerant parse shared by bench.py and the CLI —
    malformed/non-finite/negative fall back to the default with a stderr
    note, never a float() traceback."""
    from pytorch_ddp_mnist_tpu.parallel.wireup import backend_wait_env
    monkeypatch.delenv("PDMT_BACKEND_WAIT", raising=False)
    assert backend_wait_env(300.0) == 300.0
    monkeypatch.setenv("PDMT_BACKEND_WAIT", "45")
    assert backend_wait_env(300.0) == 45.0
    for bad in ("5m", "", "nan", "-3", "inf"):
        monkeypatch.setenv("PDMT_BACKEND_WAIT", bad)
        assert backend_wait_env(7.0) == 7.0, bad
    err = capsys.readouterr().err
    assert "PDMT_BACKEND_WAIT" in err


def test_backoff_schedule_deterministic_and_growing():
    """The elastic re-wire probe cadence: same seed -> same schedule,
    exponential growth under the jitter, never above 1.5x the cap."""
    from pytorch_ddp_mnist_tpu.parallel.wireup import backoff_schedule
    import itertools
    a = list(itertools.islice(backoff_schedule(0.5, 8.0, seed=3), 10))
    b = list(itertools.islice(backoff_schedule(0.5, 8.0, seed=3), 10))
    assert a == b
    c = list(itertools.islice(backoff_schedule(0.5, 8.0, seed=4), 10))
    assert a != c  # jitter is seed-dependent
    # every delay sits in [0.5, 1.5) x the capped exponential envelope
    for attempt, delay in enumerate(a):
        envelope = min(8.0, 0.5 * 2.0 ** attempt)
        assert 0.5 * envelope <= delay < 1.5 * envelope, (attempt, delay)
    # the tail is capped: late delays never exceed 1.5 x cap
    assert all(d < 1.5 * 8.0 for d in a[6:])


def test_backoff_schedule_rejects_bad_shapes():
    from pytorch_ddp_mnist_tpu.parallel.wireup import backoff_schedule
    for base, cap, factor in ((0.0, 1.0, 2.0), (-1.0, 1.0, 2.0),
                              (2.0, 1.0, 2.0), (0.5, 8.0, 1.0)):
        with pytest.raises(ValueError):
            next(backoff_schedule(base, cap, factor=factor))
