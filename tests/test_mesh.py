"""Mesh construction: topology-aware layout (SURVEY.md §7 step 5).

The reference has no mesh concept — flat ranks over NCCL (SURVEY.md §5.8).
Here the Mesh is the topology object; these tests pin down (a) the virtual
8-device CPU mesh used everywhere else, (b) the DCN-aware hybrid layout:
when devices span processes/slices, the dp axis must vary slowest across
granules so the inter-host hops ride DCN while per-host neighbors stay
contiguous for ICI rings.
"""

import numpy as np
import pytest

import jax

from pytorch_ddp_mnist_tpu.parallel.mesh import (
    DATA_AXIS, _topology_device_array, data_parallel_mesh, make_mesh)


def test_dp_mesh_covers_all_devices():
    m = data_parallel_mesh()
    assert m.axis_names == (DATA_AXIS,)
    assert m.shape[DATA_AXIS] == len(jax.devices())
    assert sorted(d.id for d in m.devices.flat) == sorted(
        d.id for d in jax.devices())


def test_make_mesh_2d_and_shape_errors():
    devs = jax.devices()
    m = make_mesh([2, len(devs) // 2], ["dp", "mp"], devs)
    assert m.shape == {"dp": 2, "mp": len(devs) // 2}
    with pytest.raises(ValueError, match="wants"):
        make_mesh([3], ["dp"], devs[:2])


class FakeDev:
    """Minimal device stand-in carrying the topology attributes mesh_utils
    reads (process_index / slice_index for granule grouping, id for identity).
    slice_index is only set when given, mirroring backends without slices."""

    def __init__(self, id, process_index, slice_index=None):
        self.id = id
        self.process_index = process_index
        if slice_index is not None:
            self.slice_index = slice_index
        self.platform = "cpu"
        self.device_kind = "cpu"
        self.client = None

    def __repr__(self):
        return (f"FakeDev(id={self.id}, proc={self.process_index}, "
                f"slice={getattr(self, 'slice_index', None)})")


def test_hybrid_layout_groups_process_granules():
    """4 fake processes x 2 devices: the dp axis orders all of process 0's
    devices before process 1's (contiguous granules), so a dp-sharded batch
    keeps each host's shard local and cross-host traffic is the slow stride."""
    devs = [FakeDev(id=p * 2 + k, process_index=p)
            for p in range(4) for k in range(2)]
    # shuffle so the test proves layout comes from topology, not input order
    rng = np.random.RandomState(0)
    shuffled = [devs[i] for i in rng.permutation(8)]
    arr = _topology_device_array([8], shuffled)
    assert arr is not None and arr.shape == (8,)
    procs = [d.process_index for d in arr.flat]
    assert procs == [0, 0, 1, 1, 2, 2, 3, 3], procs


def test_granule_mismatch_warns_before_fallback():
    """An axis-0 size not divisible by the DCN granule count must WARN when
    degrading to process-major order (VERDICT r2 weak #6: the silent branch
    next to the loudly-warning exception branch)."""
    import warnings
    devs = ([FakeDev(id=i, process_index=i // 2) for i in range(4)]
            + [FakeDev(id=4, process_index=2)])  # 3 processes, 5 devices
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        arr = _topology_device_array([5], devs)
    assert arr is None
    assert any("granule" in str(x.message) for x in w), [
        str(x.message) for x in w]


def test_single_slice_multihost_uses_ici_layout():
    """v4-32 north-star shape: 4 processes, ONE slice (all 16 chips on one
    ICI torus). The granule unit must be the slice, not the process — this
    must NOT take the hybrid path (which would fail its granule-count check
    and silently fall back before the fix)."""
    devs = [FakeDev(id=p * 4 + k, process_index=p, slice_index=0)
            for p in range(4) for k in range(4)]
    arr = _topology_device_array([16], devs)
    assert arr is not None and arr.shape == (16,)
    assert sorted(d.id for d in arr.flat) == list(range(16))


def test_multi_slice_groups_by_slice():
    """2 slices x 2 processes x 2 devices: granules are slices; the dp axis
    orders slice 0's devices before slice 1's."""
    devs = [FakeDev(id=s * 4 + p * 2 + k, process_index=s * 2 + p,
                    slice_index=s)
            for s in range(2) for p in range(2) for k in range(2)]
    arr = _topology_device_array([8], devs)
    assert arr is not None and arr.shape == (8,)
    slices = [d.slice_index for d in arr.flat]
    assert slices == [0, 0, 0, 0, 1, 1, 1, 1], slices


def test_topology_failure_warns_not_silent():
    """An unexpected mesh_utils failure surfaces as a RuntimeWarning, not a
    silent fallback (review finding: bare except hid a granule-count bug)."""
    # 3 slices cannot tile a dp axis of 8 -> intentional None, no warning
    devs = [FakeDev(id=i, process_index=i % 3, slice_index=i % 3)
            for i in range(8)]
    assert _topology_device_array([8], devs) is None
    # A failure inside mesh_utils itself warns: 2 slices of UNEQUAL size
    # (3+5) pass the divisibility pre-check (8 % 2 == 0) but cannot form
    # 4-device per-granule meshes.
    bad = [FakeDev(id=i, process_index=0, slice_index=0 if i < 3 else 1)
           for i in range(8)]
    with pytest.warns(RuntimeWarning, match="falling back"):
        assert _topology_device_array([8], bad) is None


def test_hybrid_layout_indivisible_falls_back():
    """dp axis not divisible by granule count -> fall back (None) rather
    than a bogus hybrid factorization."""
    devs = [FakeDev(id=i, process_index=i % 3) for i in range(8)]
    assert _topology_device_array([8], devs) is None
    # the public API still yields a valid full mesh
    m = make_mesh([8], ["dp"], devs)
    assert sorted(d.id for d in m.devices.flat) == list(range(8))
