"""Deterministic fault injection (utils/faultpoints.py): spec parsing,
matching semantics (first-crossing steps, rank gating, fire-once), each
action's behavior, flight-recorder evidence, and the instrumented fault
points in the loader and wireup barrier."""

import os
import signal
import time

import numpy as np
import pytest

from pytorch_ddp_mnist_tpu.telemetry.flight import get_flight_recorder
from pytorch_ddp_mnist_tpu.utils import faultpoints
from pytorch_ddp_mnist_tpu.utils.faultpoints import (FaultInjector,
                                                     FaultSpecError,
                                                     parse_faults)


@pytest.fixture(autouse=True)
def _clean_injector(monkeypatch):
    """Each test builds its own injector; none leaks into the next.

    The teardown must clear $PDMT_FAULT ITSELF before rebuilding: this
    fixture depends on monkeypatch, so it finalizes BEFORE monkeypatch
    restores the env — a test that setenv'd a fault spec would otherwise
    have it rebuilt into the process-wide injector here and fire in a
    LATER test file's first barrier/step (a real ordering-dependent leak
    this suite shipped for several rounds)."""
    monkeypatch.delenv(faultpoints.FAULT_ENV, raising=False)
    faultpoints.install()
    yield
    os.environ.pop(faultpoints.FAULT_ENV, None)
    faultpoints.install()


# -- parsing ----------------------------------------------------------------

def test_parse_empty_and_none():
    assert parse_faults(None) == []
    assert parse_faults("") == []
    assert parse_faults(" , ") == []


def test_parse_full_specs():
    specs = parse_faults("kill:rank=2:step=5,"
                         "loader_stall:batch=3:delay_s=0.25:times=2")
    assert [s.kind for s in specs] == ["kill", "loader_stall"]
    assert specs[0].point == "step"
    assert specs[0].where == {"rank": 2, "step": 5}
    assert specs[1].point == "loader_next"
    assert specs[1].delay_s == 0.25 and specs[1].times == 2


@pytest.mark.parametrize("bad, match", [
    ("explode:step=1", "unknown fault kind"),
    ("kill:when=5", "unknown fault constraint"),
    ("kill:step", "not key=value"),
    ("kill:step=soon", "not a number"),
])
def test_parse_rejects_by_name(bad, match):
    with pytest.raises(FaultSpecError, match=match):
        parse_faults(bad)


# -- matching ---------------------------------------------------------------

def test_step_is_first_crossing_and_fires_once():
    """step=K fires at the FIRST crossing >= K (the epoch-scanned trainer
    only surfaces chunk boundaries), then never again (times=1)."""
    inj = FaultInjector(parse_faults("ckpt_save_io:step=5"))
    inj.fire("ckpt_save", step=4)              # below: no fire
    with pytest.raises(OSError, match="ckpt_save_io"):
        inj.fire("ckpt_save", step=6)          # first crossing
    inj.fire("ckpt_save", step=7)              # already fired: no-op
    assert inj.specs[0].fired == 1


def test_times_budget():
    inj = FaultInjector(parse_faults("ckpt_save_io:times=2"))
    for _ in range(2):
        with pytest.raises(OSError):
            inj.fire("ckpt_save", step=0)
    inj.fire("ckpt_save", step=0)
    assert inj.specs[0].fired == 2


def test_rank_gating():
    spec = "collective_timeout:rank=2"
    FaultInjector(parse_faults(spec), rank=1).fire("barrier")  # wrong rank
    with pytest.raises(RuntimeError, match="DEADLINE_EXCEEDED"):
        FaultInjector(parse_faults(spec), rank=2).fire("barrier")


def test_wrong_point_never_matches():
    inj = FaultInjector(parse_faults("ckpt_save_io"))
    inj.fire("step", step=1)
    inj.fire("barrier")
    assert inj.specs[0].fired == 0


# -- actions ----------------------------------------------------------------

def test_collective_timeout_matches_backend_loss_triage():
    """The injected barrier failure must look EXACTLY like the failure
    class the outage machinery triages on."""
    from pytorch_ddp_mnist_tpu.parallel.wireup import looks_like_backend_loss
    inj = FaultInjector(parse_faults("collective_timeout"))
    with pytest.raises(RuntimeError) as ei:
        inj.fire("barrier")
    assert looks_like_backend_loss(ei.value)


def test_loader_stall_sleeps():
    inj = FaultInjector(parse_faults("loader_stall:batch=1:delay_s=0.2"))
    t0 = time.perf_counter()
    inj.fire("loader_next", batch=0)
    assert time.perf_counter() - t0 < 0.1      # wrong batch: no stall
    inj.fire("loader_next", batch=1)
    assert time.perf_counter() - t0 >= 0.2


def test_kill_dumps_flight_then_sigkills(tmp_path, monkeypatch):
    killed = {}
    monkeypatch.setattr(faultpoints.os, "kill",
                        lambda pid, sig: killed.update(pid=pid, sig=sig))
    rec = get_flight_recorder()
    monkeypatch.setattr(rec, "dump_dir", str(tmp_path))
    inj = FaultInjector(parse_faults("kill:step=3"))
    inj.fire("step", step=3, epoch=0)
    assert killed == {"pid": os.getpid(), "sig": signal.SIGKILL}
    # the dump landed BEFORE the (stubbed) SIGKILL, with the fault in it
    dumps = [p for p in os.listdir(tmp_path) if p.startswith("flight.")]
    assert len(dumps) == 1
    import json
    payload = json.loads((tmp_path / dumps[0]).read_text())
    assert "injected fault: kill:step=3" in payload["reason"]


def test_every_fired_fault_lands_in_flight_recorder():
    before = len(get_flight_recorder().snapshot())
    inj = FaultInjector(parse_faults("loader_stall:delay_s=0.0"), rank=3)
    inj.fire("loader_next", batch=7)
    tail = get_flight_recorder().snapshot()[before:]
    assert [e["kind"] for e in tail] == ["fault_injected"]
    assert tail[0]["fault"] == "loader_stall"
    assert tail[0]["rank"] == 3 and tail[0]["batch"] == 7


# -- the nan value fault (the health watchdog's chaos input) ---------------

def test_nan_parses_and_points_at_loss():
    (spec,) = parse_faults("nan:step=5")
    assert spec.kind == "nan" and spec.point == "loss"
    assert spec.where == {"step": 5}


def test_poison_scalar_first_crossing_fires_once():
    inj = FaultInjector(parse_faults("nan:step=5"))
    assert inj.poison("loss", 1.5, step=4) == 1.5          # below threshold
    out = inj.poison("loss", 1.5, step=7)                  # first crossing
    assert np.isnan(out)
    assert inj.poison("loss", 1.5, step=8) == 1.5          # fired once


def test_poison_records_flight_before_poisoning():
    before = len(get_flight_recorder().snapshot())
    inj = FaultInjector(parse_faults("nan:step=2"), rank=1)
    inj.poison("loss", 3.0, step=2, epoch=0)
    tail = get_flight_recorder().snapshot()[before:]
    assert [e["kind"] for e in tail] == ["fault_injected"]
    assert tail[0]["fault"] == "nan:step=2" and tail[0]["rank"] == 1


def test_poison_array_hits_the_crossing_index():
    inj = FaultInjector(parse_faults("nan:step=6"))
    # chunk covering steps 1..4: threshold not reached, array untouched
    a = np.ones(4)
    out = inj.poison_array("loss", a, first_step=1)
    assert np.isfinite(out).all() and inj.specs[0].fired == 0
    # chunk covering steps 5..8: step 6 is index 1
    b = np.ones(4)
    out = inj.poison_array("loss", b, first_step=5)
    assert np.isnan(out[1]) and np.isfinite(np.delete(out, 1)).all()
    assert np.isfinite(b).all()                 # caller's array untouched
    # spent: later chunks stay clean
    assert np.isfinite(inj.poison_array("loss", np.ones(4),
                                        first_step=9)).all()


def test_poison_array_threshold_already_passed_hits_first_index():
    # first-crossing >= K: a chunk starting past K poisons its first step
    inj = FaultInjector(parse_faults("nan:step=3"))
    out = inj.poison_array("loss", np.ones(4), first_step=7)
    assert np.isnan(out[0])


def test_poison_is_noop_without_config():
    assert faultpoints.poison("loss", 2.5, step=1) == 2.5
    arr = np.ones(3)
    assert faultpoints.poison_array("loss", arr, first_step=1) is arr


def test_fire_never_acts_on_nan_specs():
    # value faults only fire through poison(): fire() at the same point
    # must neither act nor consume the budget
    inj = FaultInjector(parse_faults("nan:step=1"))
    inj.fire("loss", step=5)
    assert inj.specs[0].fired == 0
    assert np.isnan(inj.poison("loss", 1.0, step=5))


# -- module-level switchboard ----------------------------------------------

def test_fire_is_noop_without_config():
    faultpoints.fire("step", step=1)           # nothing installed: no-op
    assert not faultpoints.active()


def test_env_driven_lazy_install(monkeypatch):
    monkeypatch.setenv(faultpoints.FAULT_ENV, "ckpt_save_io:step=1")
    faultpoints._INJECTOR = None               # simulate fresh process
    with pytest.raises(OSError, match="injected fault"):
        faultpoints.fire("ckpt_save", step=1)
    assert faultpoints.active()


def test_install_merges_env_and_cli(monkeypatch):
    monkeypatch.setenv(faultpoints.FAULT_ENV, "loader_stall")
    inj = faultpoints.install("collective_timeout", rank=2)
    assert [s.kind for s in inj.specs] == ["loader_stall",
                                           "collective_timeout"]
    assert inj.rank == 2
    faultpoints.set_rank(0)
    assert inj.rank == 0


# -- instrumented fault points ----------------------------------------------

def test_batch_loader_threads_loader_stall(monkeypatch):
    from pytorch_ddp_mnist_tpu.data.loader import BatchLoader
    from pytorch_ddp_mnist_tpu.parallel.sampler import ShardedSampler
    monkeypatch.setenv(faultpoints.FAULT_ENV,
                       "loader_stall:batch=1:delay_s=0.3")
    faultpoints.install()
    loader = BatchLoader(np.zeros((8, 4), np.float32),
                         np.zeros(8, np.uint8),
                         ShardedSampler(8, shuffle=False), batch_size=4)
    t0 = time.perf_counter()
    assert len(list(loader)) == 2
    assert time.perf_counter() - t0 >= 0.3


def test_runtime_barrier_threads_collective_timeout(monkeypatch):
    from pytorch_ddp_mnist_tpu.parallel.wireup import Runtime
    monkeypatch.setenv(faultpoints.FAULT_ENV, "collective_timeout")
    faultpoints.install()
    with pytest.raises(RuntimeError, match="DEADLINE_EXCEEDED"):
        Runtime(method="single").barrier()     # size=1: no real collective
