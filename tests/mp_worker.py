"""Worker process for the real multi-process integration test
(tests/test_multiprocess.py) — NOT collected by pytest (no test_ prefix).

Each worker is one jax.distributed process with ONE local CPU device. The
parent launches WORLD_SIZE of these with env-var wireup (RANK/WORLD_SIZE/
MASTER_ADDR/MASTER_PORT — the reference's fallback branch,
mnist_cpu_mp.py:147-185), and they jointly run SPMD data-parallel training:
rendezvous, per-process sampler shards, global-batch stitching, cross-process
gradient allreduce, plus the Runtime collectives (barrier, reduce_max).

Output: ONE JSON line on stdout with the loss curve, a params checksum, and
collective results, which the parent cross-checks between ranks and against
a single-process golden run of the same math.
"""

import json
import sys

# Single source of truth for the run config — the golden replay in
# test_multiprocess.py imports these, so worker and golden cannot drift.
# n must satisfy n/WORLD >= steps*local_batch so no step sees an empty
# shard slice (the worker asserts it).
HPARAMS = dict(n=1024, local_batch=32, steps=5, lr=0.05,
               data_seed=0, sampler_seed=42, param_seed=0, key_seed=1)


def main() -> int:
    import numpy as np
    import jax

    from pytorch_ddp_mnist_tpu.data import normalize_images, synthetic_mnist
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.parallel.ddp import (
        dp_mesh, global_batch_from_local, make_dp_train_step, replicate_state)
    from pytorch_ddp_mnist_tpu.parallel.sampler import ShardedSampler
    from pytorch_ddp_mnist_tpu.parallel.wireup import initialize_runtime

    n, local_batch, steps, lr = (HPARAMS["n"], HPARAMS["local_batch"],
                                 HPARAMS["steps"], HPARAMS["lr"])

    rt = initialize_runtime("env")
    assert jax.process_count() == rt.size, "rendezvous failed"
    mesh = dp_mesh()
    assert mesh.devices.size == rt.size  # one device per process

    split = synthetic_mnist(n, seed=HPARAMS["data_seed"])
    x_all = normalize_images(split.images)
    y_all = split.labels.astype(np.int32)
    sampler = ShardedSampler(n, num_replicas=rt.size, rank=rt.rank,
                             seed=HPARAMS["sampler_seed"])
    sampler.set_epoch(0)
    shard = sampler.indices()

    step = make_dp_train_step(mesh, lr=lr)
    params = replicate_state(mesh, init_mlp(jax.random.key(HPARAMS["param_seed"])))
    key = replicate_state(mesh, jax.random.key(HPARAMS["key_seed"]))

    losses = []
    for s in range(steps):
        rows = shard[s * local_batch:(s + 1) * local_batch]
        assert len(rows) == local_batch, \
            f"shard exhausted at step {s}: raise HPARAMS['n']"
        gx, gy = global_batch_from_local(mesh, (x_all[rows], y_all[rows]))
        params, key, loss = step(params, key, gx, gy)
        losses.append(float(loss))

    # Params are fully replicated -> every process can materialize them.
    checksum = float(sum(np.abs(np.asarray(leaf)).sum()
                         for leaf in jax.tree_util.tree_leaves(params)))
    rmax = rt.reduce_max(float(rt.rank))
    rt.barrier()
    print(json.dumps({"rank": rt.rank, "size": rt.size, "losses": losses,
                      "checksum": checksum, "reduce_max": rmax}))
    sys.stdout.flush()
    rt.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
