"""bench.py driver contract: ONE JSON line with the agreed schema, in both
modes. The driver parses exactly this output on real hardware after every
round (BENCH_r{N}.json), so the contract is load-bearing."""

import json
import os
import subprocess
import sys

# The bench must run on the host backend here: the suite's virtual-CPU
# setup (conftest) is in-process only, and a spawned bench would otherwise
# grab a possibly-absent TPU tunnel.
ENV = dict(os.environ, JAX_PLATFORMS="cpu",
           XLA_FLAGS="--xla_force_host_platform_device_count=1")


def _run(args):
    out = subprocess.run([sys.executable, "bench.py"] + args, env=ENV,
                         capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1, f"expected ONE JSON line, got: {out.stdout!r}"
    return json.loads(lines[0])


def test_train_mode_contract():
    rec = _run(["--epochs", "1"])
    assert rec["metric"] == "mnist_train_images_per_sec_per_chip"
    assert rec["unit"] == "images/sec/chip"
    assert rec["value"] > 0 and rec["vs_baseline"] > 0


def test_stream_mode_contract():
    rec = _run(["--mode", "stream"])
    assert rec["metric"] == "mnist_netcdf_stream_images_per_sec"
    assert rec["unit"] == "images/sec"
    assert rec["value"] > 0


def test_kernel_auto_composes_with_bfloat16():
    """`--kernel auto` (the default) must resolve to a kernel that accepts
    the requested dtype — bf16 + auto previously could pick the f32-only
    Pallas kernel and die in _check_kernel."""
    rec = _run(["--epochs", "1", "--dtype", "bfloat16"])
    assert rec["value"] > 0


def test_kernel_auto_resolution_table():
    """The auto-resolution rule itself, both backends (the subprocess test
    above can only exercise the CPU branch)."""
    import bench
    assert bench.resolve_kernel("float32", on_tpu=True) == "pallas"
    assert bench.resolve_kernel("bfloat16", on_tpu=True) == "xla"
    assert bench.resolve_kernel("float32", on_tpu=False) == "xla"
    assert bench.resolve_kernel("bfloat16", on_tpu=False) == "xla"


def test_bench_kernel_resolution_table():
    """bench's own auto policy incl. the single-chip whole-epoch promotion —
    the exact decision the driver's flagless TPU run takes."""
    import bench
    r = bench.resolve_bench_kernel
    assert r("auto", "float32", on_tpu=True, n_chips=1) == "pallas_epoch"
    assert r("auto", "float32", on_tpu=True, n_chips=8) == "pallas"
    assert r("auto", "bfloat16", on_tpu=True, n_chips=1) == "xla"
    assert r("auto", "float32", on_tpu=False, n_chips=1) == "xla"
    # batches the epoch kernel can't take, and unroll experiments, fall
    # back to the gridded per-step kernel instead of erroring
    assert r("auto", "float32", on_tpu=True, n_chips=1,
             batch=100) == "pallas"
    assert r("auto", "float32", on_tpu=True, n_chips=1,
             batch=2048) == "pallas"
    assert r("auto", "float32", on_tpu=True, n_chips=1,
             unroll=2) == "pallas"
    # explicit flags never get promoted/overridden
    assert r("pallas", "float32", on_tpu=True, n_chips=1) == "pallas"
    assert r("xla", "float32", on_tpu=True, n_chips=1) == "xla"


def test_backend_retry_then_success(monkeypatch):
    """wait_for_backend survives transient backend-init failures (the
    tunneled TPU's known outage mode) and returns once a probe succeeds."""
    import jax
    from pytorch_ddp_mnist_tpu.parallel.wireup import wait_for_backend

    calls = {"n": 0}
    real_devices = jax.devices

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("Unable to initialize backend 'axon': "
                               "UNAVAILABLE")
        return real_devices()

    monkeypatch.setattr(jax, "devices", flaky)
    devs = wait_for_backend(max_wait_s=30.0, poll_s=0.01)
    assert calls["n"] == 3 and len(devs) >= 1


def test_backend_retry_exhausted_raises_named_error(monkeypatch):
    import jax
    from pytorch_ddp_mnist_tpu.parallel.wireup import (
        BackendUnavailableError, wait_for_backend)

    def dead():
        raise RuntimeError("UNAVAILABLE: tunnel down")

    monkeypatch.setattr(jax, "devices", dead)
    import pytest
    with pytest.raises(BackendUnavailableError, match="tunnel down"):
        wait_for_backend(max_wait_s=0.05, poll_s=0.01)


def test_bench_emits_json_error_line_when_backend_unavailable():
    """A dead backend must produce ONE machine-readable JSON line (rc=1),
    never a bare traceback — the BENCH_r02 failure mode (VERDICT r2 #1)."""
    env = dict(ENV, PDMT_BACKEND_WAIT="0.05",
               JAX_PLATFORMS="fake_dead_platform")
    out = subprocess.run([sys.executable, "bench.py", "--epochs", "1"],
                         env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 1
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1, out.stdout
    rec = json.loads(lines[0])
    assert rec["value"] is None
    assert "backend_unavailable" in rec["error"]


def test_epochs_validation():
    out = subprocess.run([sys.executable, "bench.py", "--epochs", "0"],
                         env=ENV, capture_output=True, text=True, timeout=120)
    assert out.returncode != 0
    assert "--epochs" in out.stderr
