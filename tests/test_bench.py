"""bench.py driver contract: ONE JSON line with the agreed schema, in both
modes. The driver parses exactly this output on real hardware after every
round (BENCH_r{N}.json), so the contract is load-bearing."""

import json
import os
import subprocess
import sys

# The bench must run on the host backend here: the suite's virtual-CPU
# setup (conftest) is in-process only, and a spawned bench would otherwise
# grab a possibly-absent TPU tunnel. PDMT_STATICS_STAMP=0 keeps the many
# bench subprocesses below off the per-process lint+audit stamp cost; the
# stamp itself is pinned by test_bench_statics_stamp_in_artifact here and
# tests/test_statics.py in-process.
ENV = dict(os.environ, JAX_PLATFORMS="cpu",
           XLA_FLAGS="--xla_force_host_platform_device_count=1",
           PDMT_STATICS_STAMP="0")


def _run(args):
    out = subprocess.run([sys.executable, "bench.py"] + args, env=ENV,
                         capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1, f"expected ONE JSON line, got: {out.stdout!r}"
    return json.loads(lines[0])


def test_train_mode_contract():
    rec = _run(["--epochs", "1"])
    assert rec["metric"] == "mnist_train_images_per_sec_per_chip"
    assert rec["unit"] == "images/sec/chip"
    assert rec["value"] > 0 and rec["vs_baseline"] > 0
    # roofline context on every throughput line (VERDICT r4 #8): the
    # fields must be the exact rounded derivations of value — comparing
    # recomputed roundings (not `> 0`) keeps the contract host-speed
    # independent (a slow CI host legitimately rounds tiny MFUs to 0.0)
    import bench
    flops = rec["value"] * 6 * bench.MACS_FWD_PER_IMG
    assert rec["tflops"] == round(flops / 1e12, 2)
    assert rec["mfu_pct_vs_bf16_peak"] == round(
        100 * flops / bench.V5E_PEAK_FLOPS_BF16, 2)
    assert 0 <= rec["mfu_pct_vs_bf16_peak"] < 100


def test_stream_mode_contract():
    rec = _run(["--mode", "stream"])
    assert rec["metric"] == "mnist_netcdf_stream_images_per_sec"
    assert rec["unit"] == "images/sec"
    assert rec["value"] > 0


def test_input_mode_contract():
    """--mode input: ONE artifact line with legacy AND pipeline variant
    rows (batches/sec + data_wait share each), the sanitizer's observed
    fetch counts within budget, and vs_baseline = pipeline/legacy."""
    rec = _run(["--mode", "input", "--epochs", "2", "--input_batches", "12",
                "--batch_size", "32", "--input_latency_ms", "2",
                "--input_workers", "2"])
    assert rec["metric"] == "mnist_input_pipeline_batches_per_sec"
    assert rec["unit"] == "batches/sec"
    for row in (rec["legacy"], rec["pipeline"]):
        assert row["batches_per_sec"] > 0
        assert 0.0 <= row["data_wait_share_p95"] <= 1.0
        # the PR 10 fetch-budget sanitizer held (its evidence is stamped)
        assert row["block_until_ready"] == 0
        assert row["fetches"] <= row["fetch_budget"]
    assert rec["legacy"]["workers"] == 0
    assert rec["pipeline"]["workers"] == 2
    assert rec["vs_baseline"] == round(
        rec["pipeline"]["batches_per_sec"]
        / rec["legacy"]["batches_per_sec"], 4)


def test_input_mode_knob_hygiene():
    # input knobs rejected by name outside input mode...
    out = subprocess.run(
        [sys.executable, "bench.py", "--mode", "stream",
         "--input_latency_ms", "9"],
        env=ENV, capture_output=True, text=True, timeout=120)
    assert out.returncode != 0
    assert "input-mode knob" in out.stderr
    # ...and train variant knobs rejected inside it
    out = subprocess.run(
        [sys.executable, "bench.py", "--mode", "input", "--kernel", "xla"],
        env=ENV, capture_output=True, text=True, timeout=120)
    assert out.returncode != 0
    assert "never reads it" in out.stderr


def test_eval_mode_contract():
    """--mode eval: inference throughput of the reference eval pass, fused
    repetitions in one program. JSON contract only; the anti-hoisting
    dependence chain is sanity-checked by timing in
    test_eval_bench_scan_does_not_collapse."""
    rec = _run(["--mode", "eval", "--epochs", "2"])
    assert rec["metric"] == "mnist_eval_images_per_sec_per_chip"
    assert rec["unit"] == "images/sec/chip"
    assert rec["value"] > 0 and rec["vs_baseline"] > 0
    # eval is forward-only: 2 FLOPs/MAC in the roofline fields
    import bench
    flops = rec["value"] * 2 * bench.MACS_FWD_PER_IMG
    assert rec["tflops"] == round(flops / 1e12, 2)
    assert rec["mfu_pct_vs_bf16_peak"] == round(
        100 * flops / bench.V5E_PEAK_FLOPS_BF16, 2)


def test_serve_mode_contract():
    """--mode serve: open-loop latency-percentile bench of the serve/
    request path. One JSON line with percentiles, achieved rate, occupancy,
    reject rate, and the compile-count evidence that serving never
    compiled past the bucket-ladder warmup."""
    rec = _run(["--mode", "serve", "--requests", "200",
                "--offered_rps", "2000", "--max_batch", "16"])
    assert rec["metric"] == "mnist_serve_requests_per_sec"
    assert rec["unit"] == "requests/sec"
    assert rec["value"] > 0 and rec["vs_baseline"] > 0
    assert rec["offered_rps"] == 2000.0
    assert 0 < rec["p50_ms"] <= rec["p95_ms"] <= rec["p99_ms"]
    assert 0 <= rec["reject_rate"] <= 1
    # client-perceived minus server-side latency at matched percentiles
    # (front-door overhead): present at every gated percentile, and the
    # client can never be meaningfully FASTER than the server it awaited
    fd = rec["front_door_overhead_ms"]
    assert set(fd) == {"p50", "p95", "p99"}
    assert all(v > -1.0 for v in fd.values())
    assert 0 < rec["batch_occupancy"] <= 1
    # bucket ladder 1..16 -> exactly 5 warmup compiles, none at serve time
    assert rec["compile_count"] == 5
    # robustness stamps: default run is one replica on the poisson shape,
    # fully available, with no failovers and no reloads to report
    assert rec["shape"] == "poisson" and rec["replicas"] == 1
    assert rec["availability"] == 1.0
    assert rec["retried_requests"] == 0 and rec["reloads"] == 0


def test_ddp_mode_contract_8_fake_devices():
    """The PR acceptance as a test: `--mode ddp` on 8 fake CPU devices
    emits ONE artifact line per strategy (pmean, sharded, bf16, int8),
    each with non-null images_per_sec and scaling_efficiency_vs_1dev; the
    pmean row pins zero parity drift against itself, the sharded row stays
    within rtol 1e-6 of pmean, bf16/int8 within their bounded-drift
    envelopes."""
    env = dict(ENV, XLA_FLAGS="--xla_force_host_platform_device_count=8")
    out = subprocess.run(
        [sys.executable, "bench.py", "--mode", "ddp", "--epochs", "2",
         "--batch_size", "16"],
        env=env, capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, out.stderr[-2000:]
    recs = [json.loads(ln) for ln in out.stdout.splitlines()
            if ln.startswith("{")]
    assert [r["strategy"] for r in recs] == ["pmean", "sharded", "bf16",
                                            "int8"]
    by = {r["strategy"]: r for r in recs}
    for r in recs:
        assert r["metric"] == "mnist_ddp_train_images_per_sec_per_chip"
        assert r["n_devices"] == 8
        assert r["images_per_sec"] is not None and r["images_per_sec"] > 0
        assert r["scaling_efficiency_vs_1dev"] is not None
        assert 0 < r["scaling_efficiency_vs_1dev"] < 2
        assert r["bytes_on_wire_per_step_per_device"] > 0
        assert r["collective_s_p50"] > 0
        # the roofline stamp (telemetry/costs.py): predicted efficiency
        # were the step only compute + wire, and the batch the row was
        # measured at (the attribution reader's input)
        assert 0 < r["analytic_efficiency"] <= 1
        assert r["per_chip_batch"] == 16
        assert "peak_hbm_bytes" in r and "compile_s_total" in r
        # the collective-journal stamps (telemetry/cluster.py): the
        # static schedule length and the measured journaling cost share
        # — the in-artifact half of the zero-overhead claim
        assert r["collectives_per_step"] >= 1
        assert 0 <= r["journal_overhead_share"] < 0.5
        # the dispatch-forensics stamps (telemetry/dispatch.py probe):
        # the overhead decomposition next to analytic_efficiency, the
        # `trace report --overhead <artifact>` input
        assert 0 <= r["overhead_share"] < 1
        assert 0 <= r["overhead_coverage"] <= 1
        assert set(r["overhead_phases"]) == {"python_prestep", "dispatch",
                                             "device_idle", "sync_wait"}
        assert all(v >= 0 for v in r["overhead_phases"].values())
        # worst is an O constituent, never the probe's device-dominated
        # sync_wait
        assert r["overhead_worst_phase"] in ("python_prestep", "dispatch")
        assert 0 <= r["overhead_worst_share"] <= 1
        assert r["overhead_probe_steps"] >= 1
    assert by["pmean"]["parity_max_abs_diff_vs_pmean"] == 0.0
    assert by["sharded"]["parity_max_rel_diff_vs_pmean"] < 1e-6
    # the compressed wire is half the f32 wire, exactly
    assert (by["bf16"]["bytes_on_wire_per_step_per_device"] * 2
            == by["pmean"]["bytes_on_wire_per_step_per_device"])
    # int8: ~quarter of f32 (1 byte/elem + block scales + device*block pad)
    assert (by["int8"]["bytes_on_wire_per_step_per_device"]
            < 0.27 * by["pmean"]["bytes_on_wire_per_step_per_device"])
    assert 0 < by["int8"]["parity_max_abs_diff_vs_pmean"] < 1e-3
    assert not any(r["overlap"] for r in recs)


def test_bench_statics_stamp_in_artifact():
    """With the stamp enabled (the real-artifact default), every device-
    mode JSON line carries statics: {lint_findings, concurrency_findings,
    audit_ok} — the MULTICHIP/BENCH regression visibility the statics/
    subsystem adds."""
    env = dict(ENV, PDMT_STATICS_STAMP="1")
    out = subprocess.run(
        [sys.executable, "bench.py", "--mode", "eval", "--epochs", "2"],
        env=env, capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, out.stderr[-2000:]
    (line,) = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    rec = json.loads(line)
    assert rec["statics"] == {"lint_findings": 0,
                              "concurrency_findings": 0, "audit_ok": True}


def test_ddp_comm_knob_rejected_outside_ddp_mode():
    out = subprocess.run(
        [sys.executable, "bench.py", "--mode", "train", "--epochs", "1",
         "--ddp_comm", "sharded"],
        env=ENV, capture_output=True, text=True, timeout=120)
    assert out.returncode != 0 and "--ddp_comm" in out.stderr
    out = subprocess.run(
        [sys.executable, "bench.py", "--mode", "ddp", "--kernel", "xla"],
        env=ENV, capture_output=True, text=True, timeout=120)
    assert out.returncode != 0 and "--kernel" in out.stderr


def test_mode_knob_compat_rejected_by_name():
    """Variant knobs the selected mode never reads are rejected, not
    silently accepted as a mislabeled measurement."""
    out = subprocess.run(
        [sys.executable, "bench.py", "--mode", "eval", "--superstep", "4"],
        env=ENV, capture_output=True, text=True, timeout=120)
    assert out.returncode != 0 and "--superstep" in out.stderr
    out = subprocess.run(
        [sys.executable, "bench.py", "--mode", "train", "--epochs", "1",
         "--num_workers", "2"],
        env=ENV, capture_output=True, text=True, timeout=120)
    assert out.returncode != 0 and "--num_workers" in out.stderr
    # serve knobs are rejected outside serve mode, and vice versa
    out = subprocess.run(
        [sys.executable, "bench.py", "--mode", "train", "--epochs", "1",
         "--offered_rps", "100"],
        env=ENV, capture_output=True, text=True, timeout=120)
    assert out.returncode != 0 and "--offered_rps" in out.stderr
    out = subprocess.run(
        [sys.executable, "bench.py", "--mode", "serve", "--kernel", "xla"],
        env=ENV, capture_output=True, text=True, timeout=120)
    assert out.returncode != 0 and "--kernel" in out.stderr
    out = subprocess.run(
        [sys.executable, "bench.py", "--mode", "serve", "--epochs", "2"],
        env=ENV, capture_output=True, text=True, timeout=120)
    assert out.returncode != 0 and "--epochs" in out.stderr


def test_eval_program_uint8_matches_f32():
    """The eval program's in-pass device normalize of raw uint8 pixels must
    reproduce the host-normalized f32 pass (same op chain, float-rounding
    equality)."""
    import jax
    import numpy as np
    from bench import make_eval_program
    from pytorch_ddp_mnist_tpu.data import normalize_images, synthetic_mnist
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.train.scan import resident_images

    split = synthetic_mnist(512, seed=1)
    y = split.labels.astype(np.int32)
    params = init_mlp(jax.random.key(0))
    prog = make_eval_program(2)
    l_u8, a_u8 = prog(params, jax.numpy.asarray(
        resident_images(split.images)), y)
    l_f32, a_f32 = prog(params, jax.numpy.asarray(
        normalize_images(split.images)), y)
    np.testing.assert_allclose(np.asarray(l_u8), np.asarray(l_f32),
                               rtol=1e-5)
    # fusion can differ between the two compiled programs (the uint8 one
    # folds the normalize into the matmul read), so allow a near-tie
    # argmax flip or two out of 512 rather than exact equality
    np.testing.assert_allclose(np.asarray(a_u8), np.asarray(a_f32),
                               atol=2 / 512)


def test_eval_bench_scan_does_not_collapse():
    """The eval program's repetitions carry a bias dependence on the
    previous pass precisely so XLA cannot hoist the loop-invariant forward
    and evaluate it once. If that regressed (e.g. the perturbation constant
    folded away), R repetitions would cost the same as 1 and the reported
    throughput would be off by R. Pin it: 16 reps must cost clearly more
    than 1 (>=3x; a collapsed scan measures ~1x).

    CPU-backend only: on an accelerator the per-pass compute (~tens of µs)
    drowns in dispatch/sync RTT, so t16 ≈ t1 even with an intact chain and
    the ratio would fail spuriously (ADVICE r3)."""
    import time

    import jax
    import numpy as np
    import pytest

    if jax.default_backend() != "cpu":
        pytest.skip("wall-clock ratio needs compute to dominate dispatch "
                    "(CPU backend only)")
    from bench import make_eval_program as make
    from pytorch_ddp_mnist_tpu.data import normalize_images, synthetic_mnist
    from pytorch_ddp_mnist_tpu.models import init_mlp

    split = synthetic_mnist(10000, seed=1)
    x = jax.device_put(normalize_images(split.images))
    y = jax.device_put(split.labels.astype(np.int32))
    params = jax.device_put(init_mlp(jax.random.key(0)))

    def best_of(prog, n=5):
        prog(params, x, y)[0].block_until_ready()       # compile + warm
        best = float("inf")
        for _ in range(n):
            t0 = time.perf_counter()
            prog(params, x, y)[0].block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return best

    # 2.5x with best-of-5: a collapsed scan measures ~1x, so the margin
    # still discriminates sharply while tolerating a loaded CI host
    # inflating t1's fastest window (observed flaking at 3x/best-of-3)
    t1, t16 = best_of(make(1)), best_of(make(16))
    assert t16 >= 2.5 * t1, (t1, t16)


def test_kernel_auto_composes_with_bfloat16():
    """`--kernel auto` (the default) must resolve to a kernel that accepts
    the requested dtype — bf16 + auto previously could pick the f32-only
    Pallas kernel and die in _check_kernel."""
    rec = _run(["--epochs", "1", "--dtype", "bfloat16"])
    assert rec["value"] > 0


def test_kernel_auto_resolution_table():
    """The auto-resolution rule itself, both backends (the subprocess test
    above can only exercise the CPU branch)."""
    import bench
    assert bench.resolve_kernel("float32", on_tpu=True) == "pallas"
    assert bench.resolve_kernel("bfloat16", on_tpu=True) == "xla"
    assert bench.resolve_kernel("float32", on_tpu=False) == "xla"
    assert bench.resolve_kernel("bfloat16", on_tpu=False) == "xla"


def test_bench_kernel_resolution_table():
    """bench's own auto policy incl. the single-chip whole-epoch promotion —
    the exact decision the driver's flagless TPU run takes."""
    import bench
    r = bench.resolve_bench_kernel
    assert r("auto", "float32", on_tpu=True, n_chips=1) == "pallas_epoch"
    assert r("auto", "float32", on_tpu=True, n_chips=8) == "pallas"
    assert r("auto", "bfloat16", on_tpu=True, n_chips=1) == "xla"
    assert r("auto", "float32", on_tpu=False, n_chips=1) == "xla"
    # batches the epoch kernel can't take, and unroll experiments, fall
    # back to the gridded per-step kernel instead of erroring
    assert r("auto", "float32", on_tpu=True, n_chips=1,
             batch=100) == "pallas"
    assert r("auto", "float32", on_tpu=True, n_chips=1,
             batch=2048) == "pallas"
    assert r("auto", "float32", on_tpu=True, n_chips=1,
             unroll=2) == "pallas"
    # explicit flags never get promoted/overridden
    assert r("pallas", "float32", on_tpu=True, n_chips=1) == "pallas"
    assert r("xla", "float32", on_tpu=True, n_chips=1) == "xla"


def test_backend_retry_then_success(monkeypatch):
    """wait_for_backend survives transient backend-init failures (the
    tunneled TPU's known outage mode) and returns once a probe succeeds."""
    import jax
    from pytorch_ddp_mnist_tpu.parallel.wireup import wait_for_backend

    calls = {"n": 0}
    real_devices = jax.devices

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("Unable to initialize backend 'axon': "
                               "UNAVAILABLE")
        return real_devices()

    monkeypatch.setattr(jax, "devices", flaky)
    devs = wait_for_backend(max_wait_s=30.0, poll_s=0.01)
    assert calls["n"] == 3 and len(devs) >= 1


def test_backend_retry_exhausted_raises_named_error(monkeypatch):
    import jax
    from pytorch_ddp_mnist_tpu.parallel.wireup import (
        BackendUnavailableError, wait_for_backend)

    def dead():
        raise RuntimeError("UNAVAILABLE: tunnel down")

    monkeypatch.setattr(jax, "devices", dead)
    import pytest
    with pytest.raises(BackendUnavailableError, match="tunnel down"):
        wait_for_backend(max_wait_s=0.05, poll_s=0.01)


def test_probe_devices_bounded_three_outcomes(monkeypatch):
    """The probe distinguishes ok / error / HANG — the third is the round-3
    outage mode (query accepted, never answered: nothing to retry on)."""
    import time

    import jax
    from pytorch_ddp_mnist_tpu.parallel.wireup import _probe_devices_bounded

    status, devs = _probe_devices_bounded(30.0)
    assert status == "ok" and len(devs) >= 1

    monkeypatch.setattr(jax, "devices",
                        lambda: (_ for _ in ()).throw(
                            RuntimeError("UNAVAILABLE: down")))
    status, err = _probe_devices_bounded(30.0)
    assert status == "error" and "UNAVAILABLE" in str(err)

    monkeypatch.setattr(jax, "devices", lambda: time.sleep(5))
    status, payload = _probe_devices_bounded(0.05)
    assert status == "hang" and callable(payload)  # wait_fn for a slow init

    # non-RuntimeError = fatal: retrying can never clear a broken install
    monkeypatch.setattr(jax, "devices",
                        lambda: (_ for _ in ()).throw(
                            ImportError("jax is broken")))
    status, err = _probe_devices_bounded(30.0)
    assert status == "fatal" and isinstance(err, ImportError)


def test_backend_fatal_error_raises_immediately(monkeypatch):
    """A broken environment must not burn the whole retry budget: only
    RuntimeError (the backend-unavailable class) is retryable."""
    import time

    import jax
    import pytest
    from pytorch_ddp_mnist_tpu.parallel import wireup

    monkeypatch.setattr(jax, "devices",
                        lambda: (_ for _ in ()).throw(
                            ImportError("jax is broken")))
    t0 = time.monotonic()
    with pytest.raises(ImportError, match="broken"):
        wireup.wait_for_backend(max_wait_s=300.0, poll_s=0.01)
    assert time.monotonic() - t0 < 5.0


def test_backend_slow_init_is_not_misclassified_as_hang(monkeypatch):
    """An init that outlives hang_timeout_s but DOES land (cold tunnel /
    pod bring-up) must still return its devices, not kill the run: after
    the out-of-process probe reports healthy, the in-flight probe gets one
    more bounded join and its late result is used."""
    import time

    import jax
    from pytorch_ddp_mnist_tpu.parallel import wireup

    def slow_init():
        time.sleep(0.5)
        return ["late-device"]

    monkeypatch.setattr(jax, "devices", slow_init)
    monkeypatch.setattr(wireup, "_subprocess_backend_healthy",
                        lambda timeout_s: True)
    devs = wireup.wait_for_backend(max_wait_s=10.0, poll_s=0.01,
                                   hang_timeout_s=0.3)
    assert devs == ["late-device"]


def test_hang_timeout_env_override(monkeypatch):
    """PDMT_HANG_TIMEOUT feeds wait_for_backend's default hang bound (the
    knob for backends whose legitimate cold init is slower than 75 s)."""
    import time

    import jax
    import pytest
    from pytorch_ddp_mnist_tpu.parallel import wireup

    monkeypatch.setenv("PDMT_HANG_TIMEOUT", "0.05")
    monkeypatch.setattr(jax, "devices", lambda: time.sleep(5))
    monkeypatch.setattr(wireup, "_subprocess_backend_healthy",
                        lambda timeout_s: False)
    t0 = time.monotonic()
    with pytest.raises(wireup.BackendUnavailableError, match="hung"):
        wireup.wait_for_backend(max_wait_s=0.3, poll_s=0.01)  # no explicit
    assert time.monotonic() - t0 < 5.0  # 75s default would blow this bound


def test_backend_hang_then_recovery_raises_wedged(monkeypatch):
    """Hang + tunnel recovery = BackendWedgedError (the in-process client
    can never use the recovered backend: init lock held by the hung probe)."""
    import time

    import jax
    import pytest
    from pytorch_ddp_mnist_tpu.parallel import wireup

    monkeypatch.setattr(jax, "devices", lambda: time.sleep(5))
    monkeypatch.setattr(wireup, "_subprocess_backend_healthy",
                        lambda timeout_s: True)
    with pytest.raises(wireup.BackendWedgedError, match="wedged"):
        wireup.wait_for_backend(max_wait_s=2.0, poll_s=0.01,
                                hang_timeout_s=0.05)


def test_backend_hang_without_recovery_raises_unavailable(monkeypatch):
    """Hang + no recovery inside the budget = named BackendUnavailableError
    (bounded!) — never an indefinite stall of the caller."""
    import time

    import jax
    import pytest
    from pytorch_ddp_mnist_tpu.parallel import wireup

    monkeypatch.setattr(jax, "devices", lambda: time.sleep(5))
    monkeypatch.setattr(wireup, "_subprocess_backend_healthy",
                        lambda timeout_s: False)
    t0 = time.monotonic()
    with pytest.raises(wireup.BackendUnavailableError, match="hung"):
        wireup.wait_for_backend(max_wait_s=0.3, poll_s=0.01,
                                hang_timeout_s=0.05)
    assert time.monotonic() - t0 < 5.0


def test_bench_reexecs_once_on_wedged_backend(monkeypatch, capsys):
    """bench.py re-execs a fresh interpreter when the backend recovered but
    the in-process client is wedged — and only ONCE (marker env breaks the
    loop; second occurrence emits the named JSON error line instead)."""
    import pytest

    import bench
    from pytorch_ddp_mnist_tpu.parallel import wireup

    def wedged(max_wait_s):
        raise wireup.BackendWedgedError("client is wedged")

    monkeypatch.setattr(wireup, "wait_for_backend", wedged)
    execs = []
    monkeypatch.setattr(os, "execv",
                        lambda exe, argv: execs.append((exe, argv)) or (
                            _ for _ in ()).throw(SystemExit(99)))

    monkeypatch.delenv("PDMT_NO_REEXEC", raising=False)
    try:
        # a PROGRAMMATIC caller (explicit argv) must never have its host
        # process replaced: it gets the tagged JSON error line back
        with pytest.raises(SystemExit) as ei:
            bench.main(["--epochs", "1"])
        assert ei.value.code == 1 and len(execs) == 0
        out = capsys.readouterr().out
        rec = json.loads([ln for ln in out.splitlines()
                          if ln.startswith("{")][-1])
        # the wedged state gets its OWN tag: the backend is healthy and a
        # plain rerun would succeed — drivers must not treat it as an outage
        assert rec["value"] is None and "backend_wedged" in rec["error"]

        # the CLI path (argv=None) re-execs bench.py with sys.argv's flags
        monkeypatch.setattr(sys, "argv", ["bench.py", "--epochs", "1"])
        with pytest.raises(SystemExit) as ei:
            bench.main(None)
        assert ei.value.code == 99 and len(execs) == 1
        exe, argv = execs[0]
        assert argv[1].endswith("bench.py")
        assert argv[2:] == ["--epochs", "1"]
        assert os.environ.get("PDMT_NO_REEXEC") == "1"
        capsys.readouterr()

        # ... and only ONCE: the marker turns a second wedge into the error
        with pytest.raises(SystemExit) as ei:
            bench.main(None)
        assert ei.value.code == 1 and len(execs) == 1  # no second exec
        out = capsys.readouterr().out
        rec = json.loads([ln for ln in out.splitlines()
                          if ln.startswith("{")][-1])
        assert rec["value"] is None and "backend_wedged" in rec["error"]
    finally:
        # bench.main sets the marker directly; don't leak it into the
        # rest of the pytest session (re-exec would be silently disabled)
        os.environ.pop("PDMT_NO_REEXEC", None)


def test_bench_emits_error_json_on_sigterm_while_waiting():
    """A caller that times out and SIGTERMs the bench mid-poll (the driver's
    round-end budget < the bench's 1 h backend-wait default) still gets the
    machine-readable error line on stdout, not a silent death — the artifact
    then records how long the bench polled through the outage (VERDICT r3
    #2). JAX_PLATFORMS=rocm = a permanently-unavailable-but-retryable
    backend (RuntimeError on every probe, same class as a tunnel outage)."""
    import signal

    env = dict(os.environ, JAX_PLATFORMS="rocm")
    env.pop("PDMT_BACKEND_WAIT", None)
    proc = subprocess.Popen(
        [sys.executable, "bench.py", "--backend_wait", "120"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        # the first retry note on stderr marks "polling has started"
        line = proc.stderr.readline()
        assert "backend unavailable" in line, line
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
    finally:
        proc.kill()
    assert proc.returncode == 1
    rec = json.loads([ln for ln in out.splitlines()
                      if ln.startswith("{")][-1])
    assert rec["value"] is None and rec["vs_baseline"] is None
    assert "SIGTERM" in rec["error"]


def test_bench_matrix_retries_failed_rows(monkeypatch, tmp_path):
    """A variant that fails mid-sweep (tunnel outage) is re-measured by the
    retry pass instead of shipping a null row in the artifact."""
    import importlib.util
    import pathlib

    path = pathlib.Path(__file__).resolve().parent.parent / "scripts" \
        / "bench_matrix.py"
    spec = importlib.util.spec_from_file_location("bench_matrix", path)
    bm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bm)

    flaky = tuple(bm.VARIANTS[2][1])
    calls = []

    def fake_run(extra, epochs):
        calls.append(tuple(extra))
        if tuple(extra) == flaky and calls.count(flaky) == 1:
            return None, ["backend_unavailable: tunnel outage"]
        return {"value": 1e6, "unit": "images/sec/chip",
                "vs_baseline": 1.0}, None

    monkeypatch.setattr(bm, "run_variant", fake_run)
    monkeypatch.setattr(bm, "_backend_info",
                        lambda: {"backend": "cpu", "device_kind": "test",
                                 "jax_version": "0"})
    out = tmp_path / "matrix.json"
    rc = bm.main(["--quick", "--out", str(out), "--retries", "2"])
    assert rc == 0
    art = json.loads(out.read_text())
    assert len(art["variants"]) == len(bm.VARIANTS)
    assert all(r["value"] is not None for r in art["variants"])
    assert calls.count(flaky) == 2  # failed once, retried once, then clean


def test_bench_matrix_backend_probe_is_hang_bounded(monkeypatch, tmp_path):
    """The artifact's backend-identity probe must survive a hang-mode tunnel
    outage (a bare jax.devices() that never returns — no exception for a
    try/except to catch) and still write the artifact, recording the probe
    failure instead of stalling after a completed sweep."""
    import importlib.util
    import pathlib

    path = pathlib.Path(__file__).resolve().parent.parent / "scripts" \
        / "bench_matrix.py"
    spec = importlib.util.spec_from_file_location("bench_matrix2", path)
    bm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bm)

    import pytorch_ddp_mnist_tpu.parallel.wireup as wireup
    monkeypatch.setattr(wireup, "_probe_devices_bounded",
                        lambda t: ("hang", None))
    monkeypatch.setattr(
        bm, "run_variant",
        lambda extra, epochs: ({"value": 1e6, "unit": "images/sec/chip",
                                "vs_baseline": 1.0}, None))
    out = tmp_path / "matrix.json"
    rc = bm.main(["--quick", "--out", str(out), "--retries", "0"])
    assert rc == 0
    art = json.loads(out.read_text())
    assert art["backend"] is None
    assert art["backend_probe_error"].startswith("hang")
    # deterministic, readable — never the wait_fn closure's repr (the
    # artifact field is diffed across rounds)
    assert "0x" not in art["backend_probe_error"]
    assert len(art["variants"]) == len(bm.VARIANTS)


def test_hardware_mode_collection_survives_dead_backend():
    """PDMT_TPU_TESTS=1 with an unavailable accelerator backend must SKIP
    the Mosaic module at collection (bounded probe) rather than hang the
    first backend query — a collection-time hang burns the whole hardware
    window before any per-test watchdog arms."""
    env = dict(ENV, PDMT_TPU_TESTS="1", PDMT_HANG_TIMEOUT="20",
               JAX_PLATFORMS="fake_dead_platform")
    out = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_pallas_step.py",
         "--collect-only", "-q"],
        env=env, capture_output=True, text=True, timeout=240)
    # module-level SKIP, not a collection crash: pytest exits with
    # NO_TESTS_COLLECTED (5), never INTERNAL/USAGE/collection ERROR (2+)
    assert out.returncode == 5, (out.returncode, out.stdout[-1500:],
                                 out.stderr[-500:])
    assert "no tests collected" in out.stdout, out.stdout[-1500:]
    assert "error" not in out.stdout.lower(), out.stdout[-1500:]


def test_bench_emits_json_error_line_when_backend_unavailable():
    """A dead backend must produce ONE machine-readable JSON line (rc=1),
    never a bare traceback — the BENCH_r02 failure mode (VERDICT r2 #1)."""
    env = dict(ENV, PDMT_BACKEND_WAIT="0.05",
               JAX_PLATFORMS="fake_dead_platform")
    out = subprocess.run([sys.executable, "bench.py", "--epochs", "1"],
                         env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 1
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1, out.stdout
    rec = json.loads(lines[0])
    assert rec["value"] is None
    assert "backend_unavailable" in rec["error"]


def test_epochs_validation():
    out = subprocess.run([sys.executable, "bench.py", "--epochs", "0"],
                         env=ENV, capture_output=True, text=True, timeout=120)
    assert out.returncode != 0
    assert "--epochs" in out.stderr


def test_ring_rejected_off_the_dp_epoch_kernel():
    """--ring picks the DP epoch kernel's in-kernel allreduce strategy; on
    any other resolved configuration it must be rejected by name, not
    silently ignored (the unroll lesson, ADVICE r2)."""
    out = subprocess.run(
        [sys.executable, "bench.py", "--kernel", "xla", "--ring",
         "reduce_scatter", "--epochs", "1"],
        env=ENV, capture_output=True, text=True, timeout=300)
    assert out.returncode != 0
    assert "--ring" in out.stderr and "pallas_epoch" in out.stderr


def test_resolve_bench_config_calibration(tmp_path):
    """--dtype auto / --superstep 0 resolve JOINTLY through the committed
    calibration: the gate validates one (dtype, K) pair, so auto fields
    adopt it only when every explicit field matches the pair — no chimera
    configurations (e.g. bf16/K1 from a {bf16, K8} calibration, which was
    never validated and may have lost the sweep). Junk files, non-epoch
    kernels, and multi-chip meshes always fall back to (float32, 1)."""
    from bench import resolve_bench_config as r

    missing = str(tmp_path / "absent.json")
    # explicit values pass through untouched
    assert r("float32", 1, "pallas_epoch", missing) == ("float32", 1)
    assert r("bfloat16", 8, "xla", missing) == ("bfloat16", 8)
    # auto without calibration -> plain defaults
    assert r("auto", 0, "pallas_epoch", missing) == ("float32", 1)
    cal = tmp_path / "cal.json"
    cal.write_text('{"epoch_kernel_dtype": "bfloat16", '
                   '"epoch_kernel_superstep": 8}')
    # both auto: the validated pair applies as a unit
    assert r("auto", 0, "pallas_epoch", str(cal)) == ("bfloat16", 8)
    # an explicit field that CONTRADICTS the pair disables the promotion
    # entirely (bf16/K1 and f32/K8 were not what the gate validated)
    assert r("auto", 1, "pallas_epoch", str(cal)) == ("float32", 1)
    assert r("float32", 0, "pallas_epoch", str(cal)) == ("float32", 1)
    # an explicit field that MATCHES the pair keeps it
    assert r("auto", 8, "pallas_epoch", str(cal)) == ("bfloat16", 8)
    assert r("bfloat16", 0, "pallas_epoch", str(cal)) == ("bfloat16", 8)
    # only the single-chip epoch kernel is calibrated
    assert r("auto", 0, "pallas", str(cal)) == ("float32", 1)
    assert r("auto", 0, "xla", str(cal)) == ("float32", 1)
    assert r("auto", 0, "pallas_epoch", str(cal), n_chips=4) == \
        ("float32", 1)
    # a small-K f32 calibration — the shape measure_hw phase 5's merged
    # gate writes now that K=2/4 are candidates (superstep-only: no dtype
    # change, bitwise-equal math)
    cal4 = tmp_path / "cal4.json"
    cal4.write_text('{"epoch_kernel_dtype": "float32", '
                    '"epoch_kernel_superstep": 4}')
    assert r("auto", 0, "pallas_epoch", str(cal4)) == ("float32", 4)
    assert r("float32", 0, "pallas_epoch", str(cal4)) == ("float32", 4)
    assert r("auto", 4, "pallas_epoch", str(cal4)) == ("float32", 4)
    # an explicit K contradicting the validated pair passes through
    # unpromoted (K=2 was never validated by this calibration)
    assert r("auto", 2, "pallas_epoch", str(cal4)) == ("float32", 2)
    # junk calibrations never change behavior
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert r("auto", 0, "pallas_epoch", str(bad)) == ("float32", 1)
    notdict = tmp_path / "nd.json"
    notdict.write_text('["bfloat16"]')
    assert r("auto", 0, "pallas_epoch", str(notdict)) == ("float32", 1)
    weird = tmp_path / "weird.json"
    weird.write_text('{"epoch_kernel_dtype": "fp8", '
                     '"epoch_kernel_superstep": 3}')
    assert r("auto", 0, "pallas_epoch", str(weird)) == ("float32", 1)


def test_promote_epoch_config_gate_logic():
    """Every branch of the promotion gate (scripts/promote_epoch_dtype.py
    decide()): needs a measured f32/superstep-1 baseline, a WIN in the same
    matrix, an accuracy-parity run ONLY for bf16 winners (superstep alone
    is bitwise-equal math), and the best candidate — dtype x superstep —
    lands in the calibration."""
    import importlib.util
    import pathlib

    path = pathlib.Path(__file__).resolve().parent.parent / "scripts" \
        / "promote_epoch_dtype.py"
    spec = importlib.util.spec_from_file_location("promote_epoch_dtype", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    def row(label, value):
        return {"label": label, "value": value}

    acc_calls = []

    def acc(d, k, value=0.99):
        acc_calls.append((d, k))
        return value

    f32, bf16 = mod.F32_LABEL, mod.BF16_LABEL
    s2, s4 = mod.SUP2_F32_LABEL, mod.SUP4_F32_LABEL
    s8, s8b = mod.SUP_F32_LABEL, mod.SUP_BF16_LABEL

    # no baseline -> no promotion, no accuracy runs
    cal, why = mod.decide([row(bf16, 50e6)], 0.01, acc)
    assert cal is None and "baseline" in why and not acc_calls
    # unmeasured baseline row -> same
    cal, why = mod.decide([row(f32, None), row(bf16, 50e6)], 0.01, acc)
    assert cal is None and "baseline" in why and not acc_calls
    # baseline fastest -> no promotion, no accuracy runs
    cal, why = mod.decide([row(f32, 36e6), row(bf16, 30e6), row(s8, 35e6)],
                          0.01, acc)
    assert cal is None and "already fastest" in why and not acc_calls
    # unmeasured candidates are NAMED, not silently folded into "fastest"
    # (a flaky window must not read as a performance verdict)
    cal, why = mod.decide([row(f32, 36e6), row(bf16, None)], 0.01, acc)
    assert cal is None and "unmeasured" in why and not acc_calls

    # superstep-only winner: promoted WITHOUT any accuracy run; the two
    # never-measured candidates are recorded in evidence AND the reason
    cal, why = mod.decide([row(f32, 36e6), row(s8, 40e6)], 0.01, acc)
    assert cal == {"epoch_kernel_dtype": "float32",
                   "epoch_kernel_superstep": 8,
                   "evidence": {"winner": s8, "value": 40e6,
                                "baseline_value": 36e6,
                                "unmeasured_candidates": [bf16, s2, s4,
                                                          s8b]}}
    assert not acc_calls and "bitwise" in why and "unmeasured" in why

    # a small-K superstep winner promotes the same way (K=2/4 joined the
    # candidates when the r05 window left K=8 wedge-suspect)
    cal, why = mod.decide([row(f32, 36e6), row(s2, 37e6), row(s4, 39e6)],
                          0.01, acc)
    assert cal["epoch_kernel_dtype"] == "float32"
    assert cal["epoch_kernel_superstep"] == 4
    assert not acc_calls and "bitwise" in why

    # bf16 winner: accuracy gate runs, parity passes -> promoted
    cal, why = mod.decide([row(f32, 36e6), row(bf16, 50e6)], 0.01, acc)
    assert cal["epoch_kernel_dtype"] == "bfloat16"
    assert cal["epoch_kernel_superstep"] == 1
    assert cal["evidence"]["unmeasured_candidates"] == [s2, s4, s8, s8b]
    assert acc_calls == [("float32", 1), ("bfloat16", 1)]
    # bf16 x superstep-8 winner: the accuracy run uses the winning K
    acc_calls.clear()
    cal, why = mod.decide([row(f32, 36e6), row(bf16, 40e6), row(s8b, 55e6)],
                          0.01, acc)
    assert cal["epoch_kernel_dtype"] == "bfloat16"
    assert cal["epoch_kernel_superstep"] == 8
    assert acc_calls == [("float32", 1), ("bfloat16", 8)]
    # parity failure -> no promotion
    accs = iter([0.99, 0.90])
    cal, why = mod.decide([row(f32, 36e6), row(bf16, 50e6)], 0.01,
                          lambda d, k: next(accs))
    assert cal is None and "parity failed" in why


def test_promote_gate_labels_and_matrix_explicitness():
    """The gate's EXACT headline labels must exist in bench_matrix.VARIANTS
    (a rename there would silently break promotion), and every matrix row
    must carry an explicit --dtype — bench's `--dtype auto` default reads
    the committed calibration, which would otherwise turn the f32 rows into
    mislabeled bf16 runs after a promotion."""
    import importlib.util
    import pathlib

    scripts = pathlib.Path(__file__).resolve().parent.parent / "scripts"

    def load(name):
        spec = importlib.util.spec_from_file_location(name,
                                                      scripts / f"{name}.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    bm, gate = load("bench_matrix"), load("promote_epoch_dtype")
    labels = [label for label, _ in bm.VARIANTS]
    for lbl, _d, _k in gate.CANDIDATES:
        assert lbl in labels, lbl
    for label, argv in bm.VARIANTS:
        if "--mode" in argv and argv[argv.index("--mode") + 1] == "ddp":
            # ddp-mode rows never read --dtype (bench rejects it by name
            # there — the comm strategy IS the variant; f32/xla fixed), so
            # the calibration cannot relabel them
            assert "--ddp_comm" in argv, (label, argv)
            continue
        assert "--dtype" in argv, (label, argv)
        if "pallas_epoch" in argv:
            # --superstep 0 (auto) reads the calibration too: an epoch-
            # kernel row without an explicit K would silently change
            # configuration after a superstep promotion
            assert "--superstep" in argv, (label, argv)


def test_accuracy_mode_contract():
    """--mode accuracy: the north-star semantics check — final test
    accuracy of the resolved flagless config, vs_baseline = ratio to the
    reference-semantics config trained identically, plus the continuous
    val-loss pair (the sensitive signal once accuracy saturates)."""
    rec = _run(["--mode", "accuracy", "--epochs", "1"])
    assert rec["metric"] == "mnist_1epoch_test_accuracy"
    assert rec["unit"] == "fraction"
    assert 0 < rec["value"] <= 1.0
    assert rec["vs_baseline"] > 0.9      # parity with the reference config
    assert rec["mean_val_loss"] > 0 and rec["ref_mean_val_loss"] > 0
    # knobs accuracy mode never reads stay rejected by name
    out = subprocess.run(
        [sys.executable, "bench.py", "--mode", "accuracy", "--unroll", "2"],
        env=ENV, capture_output=True, text=True, timeout=120)
    assert out.returncode != 0 and "--unroll" in out.stderr
