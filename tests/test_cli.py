"""CLI surface tests — the unified trainer entry point
(pytorch_ddp_mnist_tpu/cli/train.py) run in-process on the virtual CPU mesh.

The reference's five entry scripts have no tests (SURVEY.md §4); this locks
our single config surface: serial end-to-end, checkpoint/resume, the NetCDF
data path behind the converter, and the CLI's guard rails (flag conflicts,
missing-file errors). The multi-process CLI path is covered by real spawned
processes in tests/test_multiprocess.py.
"""

import re

import pytest

from pytorch_ddp_mnist_tpu.cli.train import main
from pytorch_ddp_mnist_tpu.data.convert import main as convert_main


def _epoch_lines(capsys):
    out = capsys.readouterr().out
    return out, [ln for ln in out.splitlines() if ln.startswith("Epoch=")]


def _mean_train(line: str) -> float:
    return float(re.search(r"mean_train=([0-9.]+)", line).group(1))


def test_serial_end_to_end_and_resume(tmp_path, capsys):
    ckpt = tmp_path / "m.msgpack"
    args = ["--limit", "768", "--batch_size", "64", "--lr", "0.1",
            "--path", str(tmp_path / "nodata"), "--checkpoint", str(ckpt)]
    assert main(args + ["--n_epochs", "3"]) == 0
    out, lines = _epoch_lines(capsys)
    assert len(lines) == 3, out
    assert ckpt.exists()
    from_scratch = _mean_train(lines[0])

    # Resume: training must pick up near where it left off, not from scratch.
    assert main(args + ["--n_epochs", "1", "--resume", str(ckpt)]) == 0
    _, lines = _epoch_lines(capsys)
    resumed = _mean_train(lines[0])
    assert resumed < from_scratch * 0.5, (from_scratch, resumed)


def test_kernel_default_is_auto_and_bare_run_resolves_on_cpu(tmp_path,
                                                             capsys):
    """--kernel defaults to 'auto' (VERDICT r2 weak #5: a bare run must not
    silently train at the slowest variant on TPU); on this CPU mesh auto
    resolves to xla and a flagless run trains."""
    from pytorch_ddp_mnist_tpu.train.config import configure
    assert configure([])["trainer"]["kernel"] == "auto"
    args = ["--limit", "256", "--batch_size", "64", "--n_epochs", "1",
            "--path", str(tmp_path / "nodata"), "--checkpoint", ""]
    assert main(args) == 0
    _, lines = _epoch_lines(capsys)
    assert len(lines) == 1


def test_kernel_auto_trains_and_torch_checkpoint(tmp_path, capsys):
    """--kernel auto resolves post-wireup (xla on this CPU mesh) and a .pt
    checkpoint path round-trips through the reference's torch format."""
    pytest.importorskip("torch")
    ckpt = tmp_path / "model.pt"
    args = ["--limit", "256", "--batch_size", "64", "--kernel", "auto",
            "--path", str(tmp_path / "nodata"), "--checkpoint", str(ckpt)]
    assert main(args + ["--n_epochs", "1"]) == 0
    _, lines = _epoch_lines(capsys)
    assert len(lines) == 1 and ckpt.exists()
    assert main(args + ["--n_epochs", "1", "--resume", str(ckpt)]) == 0
    _, lines = _epoch_lines(capsys)
    assert len(lines) == 1


def test_impl_rbg_trains_deterministically(tmp_path, capsys):
    """--impl rbg (hardware-PRNG dropout stream) trains, and the same seed
    reproduces the same curve — rbg is counter-based, not stateful."""
    args = ["--limit", "256", "--batch_size", "64", "--impl", "rbg",
            "--n_epochs", "1", "--path", str(tmp_path / "nodata"),
            "--checkpoint", ""]
    def _losses(lines):
        # everything except the wall-clock figures (img/s, io= split) is
        # deterministic
        return [re.sub(r"\d+ img/s|io=[^\]]+", "", ln) for ln in lines]

    assert main(args) == 0
    _, first = _epoch_lines(capsys)
    assert main(args) == 0
    _, second = _epoch_lines(capsys)
    assert _losses(first) == _losses(second) and len(first) == 1


def test_empty_checkpoint_skips_save(tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)
    assert main(["--limit", "256", "--batch_size", "64",
                 "--path", str(tmp_path / "nodata"), "--checkpoint", ""]) == 0
    capsys.readouterr()
    assert not list(tmp_path.glob("*.msgpack"))


def test_netcdf_roundtrip_through_converter(tmp_path, capsys):
    assert convert_main(["--synthetic", "512:128",
                         "--out_dir", str(tmp_path)]) == 0
    capsys.readouterr()
    assert main(["--netcdf", "--path", str(tmp_path), "--batch_size", "64",
                 "--checkpoint", ""]) == 0
    _, lines = _epoch_lines(capsys)
    assert len(lines) == 1


def test_netcdf_cached_path(tmp_path, capsys):
    assert convert_main(["--synthetic", "512:128",
                         "--out_dir", str(tmp_path)]) == 0
    capsys.readouterr()
    assert main(["--netcdf", "--cached", "--path", str(tmp_path),
                 "--batch_size", "64", "--limit", "256",
                 "--checkpoint", ""]) == 0
    _, lines = _epoch_lines(capsys)
    assert len(lines) == 1


def test_netcdf_missing_files_error(tmp_path):
    with pytest.raises(SystemExit, match="not found"):
        main(["--netcdf", "--path", str(tmp_path), "--checkpoint", ""])


def test_pallas_cached_runs(tmp_path, capsys):
    """--kernel pallas composes with --cached: the fused kernel inside the
    epoch scan (interpreted on the CPU backend)."""
    assert main(["--kernel", "pallas", "--cached", "--limit", "256",
                 "--batch_size", "64", "--path", str(tmp_path / "nodata"),
                 "--checkpoint", ""]) == 0
    _, lines = _epoch_lines(capsys)
    assert len(lines) == 1


def test_pallas_bfloat16_trains(tmp_path, capsys):
    """--kernel pallas --dtype bfloat16 selects the kernel's bf16-matmul
    mode (bf16 MXU operands, f32 master weights) and trains end-to-end —
    interpreted on this CPU backend. Replaces the old rejection: every
    kernel now composes with bfloat16."""
    args = ["--limit", "256", "--batch_size", "64", "--n_epochs", "1",
            "--kernel", "pallas", "--dtype", "bfloat16",
            "--path", str(tmp_path / "nodata"), "--checkpoint", ""]
    assert main(args) == 0
    _, lines = _epoch_lines(capsys)
    assert len(lines) == 1


def test_package_main_dispatcher(tmp_path, capsys):
    """python -m pytorch_ddp_mnist_tpu <command> routes to the right CLI."""
    from pytorch_ddp_mnist_tpu.__main__ import main as pkg_main

    assert pkg_main([]) == 2
    assert pkg_main(["--help"]) == 0
    out = capsys.readouterr().out
    assert "train" in out and "convert" in out and "download" in out
    assert pkg_main(["bogus"]) == 2
    capsys.readouterr()
    assert pkg_main(["convert", "--synthetic", "64:16",
                     "--out_dir", str(tmp_path)]) == 0
    assert (tmp_path / "mnist_train_images.nc").exists()
    assert pkg_main(["train", "--limit", "128", "--batch_size", "64",
                     "--n_epochs", "1", "--path", str(tmp_path / "nodata"),
                     "--checkpoint", ""]) == 0
    _, lines = _epoch_lines(capsys)
    assert len(lines) == 1


def test_pallas_epoch_cli_guards(capsys):
    """pallas_epoch misuse fails with named errors before any device work:
    missing --cached and untakeable batch sizes. --parallel is now the
    EXPERIMENTAL in-kernel-ring DDP path: it must announce itself, then (on
    this CPU backend) fail at the TPU requirement, not the old --parallel
    refusal."""
    with pytest.raises(SystemExit, match="TPU"):
        main(["--kernel", "pallas_epoch", "--cached", "--parallel"])
    # the notice goes to stderr: stdout stays machine-parseable epoch lines
    assert "experimental" in capsys.readouterr().err.lower()
    with pytest.raises(SystemExit, match="cached"):
        main(["--kernel", "pallas_epoch"])
    with pytest.raises(SystemExit, match="divisible by 8"):
        main(["--kernel", "pallas_epoch", "--cached", "--batch_size", "100"])
    with pytest.raises(SystemExit, match="divisible by 8"):
        main(["--kernel", "pallas_epoch", "--cached", "--batch_size", "2048"])


def test_input_pipeline_cli_guards():
    """Input-pipeline knob hygiene (pipeline/, ISSUE 12): every
    combination some path would silently ignore is rejected by name at
    parse/validate time."""
    with pytest.raises(SystemExit, match="--input_workers must be"):
        main(["--input_workers", "-1", "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="--prefetch_depth must be"):
        main(["--prefetch_depth", "0", "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="no loader to feed"):
        main(["--input_workers", "2", "--cached", "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="supersedes"):
        main(["--input_workers", "2", "--num_workers", "2",
              "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="nothing to prefetch"):
        main(["--prefetch_depth", "2", "--cached", "--fused",
              "--n_epochs", "1"])


def test_input_pipeline_cli_end_to_end(tmp_path, capsys):
    """A piped CLI run trains and prints the reference epoch line — the
    front-door flags reach pipeline.feed."""
    rc = main(["--n_epochs", "1", "--limit", "128", "--batch_size", "32",
               "--checkpoint", "", "--path", str(tmp_path / "data"),
               "--input_workers", "2", "--prefetch_depth", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Epoch=0" in out


def test_health_cli_guards(tmp_path):
    """--health guard rails fail by name at parse/validate time: a fused
    run has no live host to watch from, and checkpoint-and-warn needs a
    checkpoint path to derive its rescue directory."""
    with pytest.raises(SystemExit, match="--fused"):
        main(["--health", "warn", "--cached", "--fused", "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="--checkpoint"):
        main(["--health", "checkpoint-and-warn", "--checkpoint", "",
              "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="--metrics_port"):
        main(["--metrics_port", "-1", "--n_epochs", "1"])


def test_profile_dispatch_cli_guards(tmp_path):
    """--profile_dispatch guard rails fail by name: a profile nobody
    records is a silent no-op (needs --telemetry), and a fused run has no
    per-step host boundary to decompose."""
    with pytest.raises(SystemExit, match="--telemetry"):
        main(["--profile_dispatch", "4", "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="--fused"):
        main(["--profile_dispatch", "4", "--cached", "--fused",
              "--telemetry", str(tmp_path / "obs"), "--n_epochs", "1"])


def test_profile_dispatch_end_to_end(tmp_path, capsys):
    """A profiled serial run emits dispatch_phase/dispatch_window points
    and the dispatch.* registry histograms (the overhead-smoke write
    side, in-process)."""
    import json as _json

    obs = tmp_path / "obs"
    main(["--n_epochs", "1", "--limit", "128", "--batch_size", "32",
          "--checkpoint", "", "--telemetry", str(obs),
          "--profile_dispatch", "2"])
    capsys.readouterr()
    recs = [_json.loads(ln) for f in sorted(obs.glob("events*.jsonl"))
            for ln in open(f).read().splitlines()]
    names = {r["name"] for r in recs}
    assert {"dispatch_phase", "dispatch_window"} <= names
    snaps = [r for r in recs if r["kind"] == "snapshot"]
    hists = {n for s in snaps
             for n in (s["attrs"].get("histograms") or {})}
    assert any(n.startswith("dispatch.") for n in hists)


def test_health_warn_end_to_end_with_injected_nan(tmp_path, capsys):
    """--health warn + --fault nan:step=K: the run finishes (rc 0), the
    epoch line shows the poisoned loss curve, and the health event landed
    in the trace."""
    import json
    obs = tmp_path / "obs"
    assert main(["--n_epochs", "1", "--limit", "256", "--batch_size", "64",
                 "--path", str(tmp_path / "nodata"), "--checkpoint", "",
                 "--health", "warn", "--fault", "nan:step=2",
                 "--telemetry", str(obs)]) == 0
    _out, lines = _epoch_lines(capsys)
    assert len(lines) == 1 and "nan" in lines[0]
    recs = [json.loads(ln) for ln in
            open(obs / "events.jsonl").read().splitlines()]
    health = [r for r in recs
              if r["kind"] == "point" and r["name"] == "health"]
    assert [h["attrs"]["detector"] for h in health] == ["nan"]
    snap = [r for r in recs if r["kind"] == "snapshot"][-1]
    assert snap["attrs"]["counters"]["health.fired.nan"] == 1


def test_ddp_comm_cli_guards_and_training(tmp_path, capsys):
    """--ddp_comm guard rails (serial and pallas_epoch rejected by name)
    and an end-to-end --parallel --ddp_comm run per non-default strategy
    on the virtual 8-device mesh — both the streaming and the cached scan
    paths train to finite numbers."""
    with pytest.raises(SystemExit, match="--parallel"):
        main(["--ddp_comm", "sharded", "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="IN-kernel"):
        main(["--ddp_comm", "bf16", "--parallel", "--cached",
              "--kernel", "pallas_epoch", "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="never casts"):
        main(["--parallel", "--ddp_comm", "sharded",
              "--bf16_rounding", "stochastic", "--n_epochs", "1"])
    main(["--parallel", "--ddp_comm", "sharded", "--n_epochs", "1",
          "--limit", "512", "--batch_size", "16", "--checkpoint", ""])
    _, lines = _epoch_lines(capsys)
    assert len(lines) == 1 and _mean_train(lines[0]) > 0
    main(["--parallel", "--cached", "--ddp_comm", "bf16", "--n_epochs", "1",
          "--limit", "512", "--batch_size", "16", "--checkpoint", ""])
    _, lines = _epoch_lines(capsys)
    assert len(lines) == 1 and _mean_train(lines[0]) > 0


def test_int8_overlap_model_cli_guards_and_training(tmp_path, capsys):
    """ISSUE 7 knob hygiene at the CLI boundary: every int8/overlap/model
    knob a configuration would silently ignore is rejected by name, and
    the new strategies train end-to-end on the virtual 8-device mesh."""
    with pytest.raises(SystemExit, match="never quantizes"):
        main(["--parallel", "--ddp_comm", "pmean", "--quant_block", "128",
              "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="no quantization error"):
        main(["--parallel", "--ddp_comm", "bf16", "--error_feedback",
              "off", "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="needs --parallel"):
        main(["--overlap", "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="IN-kernel"):
        main(["--parallel", "--cached", "--ddp_comm", "int8",
              "--kernel", "pallas_epoch", "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="need\\(s\\) --kernel xla"):
        main(["--parallel", "--cached", "--overlap",
              "--kernel", "pallas", "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="quant_block must be"):
        main(["--parallel", "--ddp_comm", "int8", "--quant_block", "4",
              "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="param_scale"):
        main(["--param_scale", "0", "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="mask stream|geometry"):
        main(["--model", "deep_mlp", "--dropout_rng", "torch",
              "--n_epochs", "1"])
    # int8 + overlap trains (streaming), int8 on the cached scan trains
    main(["--parallel", "--ddp_comm", "int8", "--overlap", "--n_epochs",
          "1", "--limit", "512", "--batch_size", "16", "--checkpoint", ""])
    _, lines = _epoch_lines(capsys)
    assert len(lines) == 1 and _mean_train(lines[0]) > 0
    main(["--parallel", "--cached", "--ddp_comm", "int8", "--n_epochs",
          "1", "--limit", "512", "--batch_size", "16", "--checkpoint", ""])
    _, lines = _epoch_lines(capsys)
    assert len(lines) == 1 and _mean_train(lines[0]) > 0


def test_int8_resume_refuses_mismatched_resid_device_count(tmp_path):
    """The int8 error-feedback residual is per-DEVICE state, so a
    checkpoint saved on a different mesh size cannot resume — refused by
    name at the CLI boundary (like every geometry mismatch) instead of
    surfacing place_comm_state's ValueError from inside fit."""
    import numpy as np
    import jax
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.train.ckpt_manager import CheckpointManager

    steps = tmp_path / "m.steps"
    CheckpointManager(str(steps)).save(
        init_mlp(jax.random.key(0)),
        np.asarray(jax.random.key_data(jax.random.key(0))),
        "threefry2x32", step=1, epoch=1, offset=0,
        # geometry stamp matching the resume run below (8-device mesh,
        # --batch_size 16 -> global batch 128) — only the residual's
        # device-row count disagrees
        meta={"global_batch": 128, "limit": 512, "sampler_rng": "pcg64",
              "model": "mlp", "param_scale": 1},
        resid=np.zeros((4, 2048), np.float32))
    with pytest.raises(SystemExit, match="residual.*4 device"):
        main(["--parallel", "--cached", "--ddp_comm", "int8",
              "--n_epochs", "2", "--limit", "512", "--batch_size", "16",
              "--path", str(tmp_path), "--checkpoint", "",
              "--resume", str(steps)])


def test_model_zoo_cli_trains_scaled_model(tmp_path, capsys):
    """--model deep_mlp --param_scale 2 trains end-to-end (serial cached
    path; the params line reflects the scaled count)."""
    assert main(["--model", "deep_mlp", "--param_scale", "2", "--cached",
                 "--n_epochs", "1", "--limit", "256", "--batch_size", "64",
                 "--path", str(tmp_path), "--checkpoint", ""]) == 0
    out, lines = _epoch_lines(capsys)
    assert len(lines) == 1 and _mean_train(lines[0]) > 0


def test_eval_shuffle_changes_only_ref_unit(tmp_path, capsys):
    """--eval_shuffle reproduces the reference's shuffled test loader
    (ddp_tutorial_multi_gpu.py:43-47): the Σ(mean/B) ref-unit val_loss gets
    a different (deterministic) batch segmentation while mean loss and
    accuracy — order-invariant — stay identical, run to run and vs the
    sequential default."""
    args = ["--limit", "512", "--batch_size", "64", "--cached",
            "--n_epochs", "1", "--path", str(tmp_path), "--checkpoint", ""]
    assert main(args) == 0
    _, [plain] = _epoch_lines(capsys)
    assert main(args + ["--eval_shuffle"]) == 0
    _, [shuf1] = _epoch_lines(capsys)
    assert main(args + ["--eval_shuffle"]) == 0
    _, [shuf2] = _epoch_lines(capsys)

    def parts(line):
        val = float(re.search(r"val_loss=([0-9.]+)", line).group(1))
        mean = float(re.search(r"mean_val=([0-9.]+)", line).group(1))
        acc = float(re.search(r"acc=([0-9.]+)", line).group(1))
        return val, mean, acc

    vp, mp, ap = parts(plain)
    v1, m1, a1 = parts(shuf1)
    v2, m2, a2 = parts(shuf2)
    assert v1 == v2 and m1 == m2 == mp and a1 == a2 == ap
    assert v1 != vp     # a different batch segmentation of the same losses


def test_eval_shuffle_perm_matches_torch_random_sampler():
    """The shuffled eval's per-epoch permutation IS torch's test-loader
    order for a seeded generator: DataLoader(shuffle=True) iterates
    RandomSampler = torch.randperm — which torch_rng reproduces bitwise."""
    torch = pytest.importorskip("torch")
    from torch.utils.data import RandomSampler

    from pytorch_ddp_mnist_tpu.parallel.torch_rng import torch_randperm

    g = torch.Generator()
    g.manual_seed(17)
    order = list(RandomSampler(range(10000), generator=g))
    assert order == torch_randperm(10000, 17).tolist()


def test_sampler_rng_torch_cli_trains_deterministically(tmp_path, capsys):
    """--sampler_rng torch (bitwise DistributedSampler shard composition)
    through the CLI: runs end-to-end, deterministic, and actually changes
    the epoch's batch composition vs the pcg64 default."""
    args = ["--limit", "512", "--batch_size", "64", "--cached",
            "--n_epochs", "1", "--path", str(tmp_path), "--checkpoint", ""]
    assert main(args + ["--sampler_rng", "torch"]) == 0
    _, [a] = _epoch_lines(capsys)
    assert main(args + ["--sampler_rng", "torch"]) == 0
    _, [b] = _epoch_lines(capsys)
    assert main(args) == 0
    _, [c] = _epoch_lines(capsys)

    def losses(line):   # every numeric field except wall-clock throughput
        return (re.search(r"train_loss=([0-9.]+)", line).group(1),
                re.search(r"val_loss=([0-9.]+)", line).group(1),
                _mean_train(line))

    assert losses(a) == losses(b)             # deterministic
    assert _mean_train(a) != _mean_train(c)   # different shard composition


def test_dropout_rng_torch_cli_trains_and_rejections(tmp_path, capsys):
    """--dropout_rng torch (torch's bitwise CPU bernoulli mask stream,
    VERDICT r4 #3) through the CLI: the serial streaming path runs
    end-to-end and is deterministic; the combinations whose mask semantics
    it cannot model are rejected by NAME (parallel per-rank streams,
    in-device cached/fused draws, in-kernel pallas draws)."""
    import pytest

    args = ["--limit", "512", "--batch_size", "64", "--n_epochs", "1",
            "--path", str(tmp_path), "--checkpoint", "",
            "--dropout_rng", "torch"]
    assert main(args) == 0
    _, [a] = _epoch_lines(capsys)
    assert main(args) == 0
    _, [b] = _epoch_lines(capsys)
    assert _mean_train(a) == _mean_train(b)   # deterministic mask stream
    # a different dropout seed changes the masks (the stream is real)
    assert main(args + ["--seed", "1"]) == 0
    _, [c] = _epoch_lines(capsys)
    assert _mean_train(a) != _mean_train(c)

    with pytest.raises(SystemExit, match="serial-only"):
        main(args + ["--parallel"])
    with pytest.raises(SystemExit, match="cached"):
        main(args + ["--cached"])
    with pytest.raises(SystemExit, match="in-kernel"):
        main(args + ["--kernel", "pallas"])
    # the in-process retry cannot re-seat the host-side mask stream
    # (already advanced through the dead epoch's partial draws) —
    # rejected by name so the bitwise contract can't silently break
    with pytest.raises(SystemExit, match="mask stream"):
        main(args + ["--outage_retries", "1"])
    # --resume without --start_epoch would silently restart the stream at
    # position 0 against mid-run weights — rejected by name
    with pytest.raises(SystemExit, match="start_epoch"):
        main(args + ["--resume", "x.msgpack"])


def test_dropout_rng_torch_resume_is_bitwise(tmp_path):
    """--dropout_rng torch composes with --resume/--start_epoch: the mask
    stream's position is a pure function of completed steps (every batch
    wrap-padded to full size), so the resumed run fast-forwards the
    stream and lands bitwise on the unbroken trajectory."""
    import jax as _jax
    import numpy as _np

    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.train.checkpoint import load_checkpoint

    base = ["--limit", "300", "--batch_size", "64", "--path", str(tmp_path),
            "--dropout_rng", "torch", "--lr", "0.1"]
    golden = tmp_path / "golden.msgpack"
    assert main(base + ["--n_epochs", "3", "--checkpoint", str(golden)]) == 0
    part = tmp_path / "part.msgpack"
    assert main(base + ["--n_epochs", "2", "--checkpoint", str(part)]) == 0
    assert main(base + ["--n_epochs", "3", "--checkpoint", str(part),
                        "--resume", str(part), "--start_epoch", "2"]) == 0
    a = load_checkpoint(str(part), init_mlp(_jax.random.key(0)))
    b = load_checkpoint(str(golden), init_mlp(_jax.random.key(0)))
    for u, v in zip(_jax.tree_util.tree_leaves(a),
                    _jax.tree_util.tree_leaves(b)):
        _np.testing.assert_array_equal(_np.asarray(u), _np.asarray(v))
