"""Elastic training (pytorch_ddp_mnist_tpu/elastic/ — docs/ROBUSTNESS.md
§Elastic training).

Unit tier: the reshape plan/offset/residual semantics both modes pin
(including the int8 error-feedback fold's sum-preservation drift bound and
per_rank's deliberate drop), the beacon membership protocol, the
world-generation and rendezvous-port rules, the coordinator's re-exec
argv/env derivation, sampler/pipeline re-sharding, the CLI's by-name knob
hygiene, and the `--elastic`-off inertness pin. The live shrink/grow cycle
(SIGKILL a rank, survivors rescue + re-wire + continue) is subprocess
territory: `scripts/elastic_smoke.py` / `make elastic-smoke`."""

import os
import types

import numpy as np
import pytest

from pytorch_ddp_mnist_tpu.elastic import (ElasticCoordinator, ReshapeError,
                                           clear_beacons, collect_membership,
                                           next_generation, plan_reshape,
                                           read_beacons, remap_offset,
                                           remap_residual, rendezvous_port,
                                           reshape_checkpoint,
                                           world_generation, write_beacon)
from pytorch_ddp_mnist_tpu.elastic.coordinator import _strip_opt


# -- reshape plans -----------------------------------------------------------

def test_plan_global_batch_shrink_preserves_global_batch():
    plan = plan_reshape(64, 4, 2, mode="global_batch")
    assert plan.new_global_batch == 64       # the mode's whole point
    assert plan.per_device_batch == 32       # re-split over survivors
    assert plan.offset_map == "preserved"
    assert plan.resid_map == "folded"
    assert plan.changed


def test_plan_global_batch_grow_and_unchanged():
    plan = plan_reshape(64, 2, 4, mode="global_batch")
    assert (plan.per_device_batch, plan.resid_map) == (16, "grown_zeros")
    plan = plan_reshape(64, 2, 2, mode="global_batch")
    assert plan.resid_map == "kept" and not plan.changed


def test_plan_global_batch_indivisible_refuses_naming_per_rank():
    """The divisibility refusal must point at the OTHER mode — the operator
    fix — not just report the arithmetic."""
    with pytest.raises(ReshapeError, match="per_rank"):
        plan_reshape(64, 4, 3, mode="global_batch")


def test_plan_per_rank_scales_global_batch_with_world():
    plan = plan_reshape(64, 4, 2, mode="per_rank", per_device_batch=16)
    assert plan.new_global_batch == 32       # 16 x 2 survivors
    assert plan.offset_map == "floor_rescaled"
    assert plan.resid_map == "dropped"
    # same resulting geometry -> nothing to re-map
    plan = plan_reshape(64, 4, 4, mode="per_rank", per_device_batch=16)
    assert plan.offset_map == "preserved" and plan.resid_map == "kept"


def test_plan_rejects_bad_shapes_by_name():
    with pytest.raises(ReshapeError, match="unknown reshape mode"):
        plan_reshape(64, 4, 2, mode="magic")
    with pytest.raises(ReshapeError, match="device counts"):
        plan_reshape(64, 0, 2, mode="global_batch")
    with pytest.raises(ReshapeError, match="--batch_size"):
        plan_reshape(64, 4, 2, mode="per_rank", per_device_batch=0)


# -- offset re-mapping -------------------------------------------------------

def test_offset_preserved_under_global_batch():
    plan = plan_reshape(64, 4, 2, mode="global_batch")
    assert remap_offset(7, plan) == 7


def test_offset_floor_rescaled_by_samples_under_per_rank():
    """7 batches x 64 samples = 448 samples consumed; at the new global
    batch of 32 that is 14 whole batches — floored, so the tail of a
    partially-consumed new batch REPLAYS rather than being skipped."""
    plan = plan_reshape(64, 4, 2, mode="per_rank", per_device_batch=16)
    assert remap_offset(7, plan) == 14
    plan = plan_reshape(48, 4, 2, mode="per_rank", per_device_batch=16)
    assert remap_offset(5, plan) == 5 * 48 // 32  # == 7, floor of 7.5
    with pytest.raises(ReshapeError, match=">= 0"):
        remap_offset(-1, plan)


# -- residual re-mapping (the satellite: fold vs drop, drift bounds) ---------

def test_residual_fold_preserves_column_sums_exactly_for_int_values():
    """Shrink under global_batch: dead row j folds into survivor j % new.
    The residual is dequantized int8 error (integer-valued f32 x a scale),
    so the fold's additions are exact — column sums match bitwise."""
    rng = np.random.default_rng(0)
    resid = rng.integers(-127, 128, size=(4, 33)).astype(np.float32)
    plan = plan_reshape(64, 4, 2, mode="global_batch")
    out, disp = remap_residual(resid, plan)
    assert disp == "folded" and out.shape == (2, 33)
    assert np.array_equal(out.sum(axis=0), resid.sum(axis=0))
    # the fold rule itself: row j lands in j % 2
    assert np.array_equal(out[0], resid[0] + resid[2])
    assert np.array_equal(out[1], resid[1] + resid[3])


def test_residual_fold_drift_bound_for_general_floats():
    """A scaled (non-integer) residual folds with only f32 reordering
    drift: column sums agree to ~1 ulp of the magnitude, NOT the one-step
    quantization error a drop would cost."""
    rng = np.random.default_rng(1)
    resid = (rng.standard_normal((8, 257)) * 1e-3).astype(np.float32)
    with pytest.raises(ReshapeError):
        plan_reshape(256, 8, 3, mode="global_batch")  # 256 % 3 != 0
    plan = plan_reshape(256, 8, 2, mode="global_batch")
    out, _ = remap_residual(resid, plan)
    drift = np.abs(out.sum(axis=0, dtype=np.float64)
                   - resid.sum(axis=0, dtype=np.float64))
    assert drift.max() <= 1e-6  # reordering noise only


def test_residual_dropped_under_per_rank_and_grown_with_zeros():
    resid = np.ones((4, 5), np.float32)
    plan = plan_reshape(64, 4, 2, mode="per_rank", per_device_batch=16)
    assert remap_residual(resid, plan) == (None, "dropped")
    plan = plan_reshape(64, 2, 4, mode="global_batch")
    out, disp = remap_residual(resid[:2], plan)
    assert disp == "grown_zeros"
    assert np.array_equal(out[:2], resid[:2]) and not out[2:].any()


def test_residual_rejects_inconsistent_state_by_name():
    plan = plan_reshape(64, 4, 2, mode="global_batch")
    with pytest.raises(ReshapeError, match="n_devices, elems"):
        remap_residual(np.ones(5, np.float32), plan)
    with pytest.raises(ReshapeError, match="inconsistent"):
        remap_residual(np.ones((3, 5), np.float32), plan)
    assert remap_residual(None, plan) == (None, "absent")


def test_reshape_checkpoint_passes_params_through():
    plan = plan_reshape(64, 4, 2, mode="global_batch")
    restored = types.SimpleNamespace(offset=3,
                                     resid=np.ones((4, 5), np.float32))
    off, resid, disp = reshape_checkpoint(restored, plan)
    assert (off, disp) == (3, "folded")
    assert np.array_equal(resid, np.full((2, 5), 2.0, np.float32))


# -- beacons / membership ----------------------------------------------------

def test_beacon_roundtrip_and_generation_scoping(tmp_path):
    d = str(tmp_path)
    write_beacon(d, 1, 0)
    write_beacon(d, 1, 2)
    write_beacon(d, 2, 1)       # another generation's round
    (tmp_path / "journal.jsonl").write_text("x")  # non-beacon noise
    assert read_beacons(d, 1) == [0, 2]
    assert read_beacons(d, 2) == [1]
    clear_beacons(d, 1)
    assert read_beacons(d, 1) == [] and read_beacons(d, 2) == [1]
    clear_beacons(d)            # all generations
    assert read_beacons(d, 2) == []
    assert read_beacons(str(tmp_path / "missing"), 0) == []


def test_collect_membership_settles_on_the_beacon_set(tmp_path):
    d = str(tmp_path)
    write_beacon(d, 3, 0)       # a peer already arrived
    got = collect_membership(d, 3, 2, settle_s=0.05, deadline_s=2.0,
                             poll_s=0.01)
    assert got == [0, 2]        # both survivors, sorted = dense re-rank order
    assert got.index(2) == 1    # this rank's new dense rank


# -- world-generation rules --------------------------------------------------

def test_generation_env_parse_and_monotonic_increment(monkeypatch):
    monkeypatch.delenv("PDMT_ELASTIC_GEN", raising=False)
    assert world_generation() == 0
    monkeypatch.setenv("PDMT_ELASTIC_GEN", "3")
    assert world_generation() == 3
    for bad in ("", "x", "-2"):
        monkeypatch.setenv("PDMT_ELASTIC_GEN", bad)
        assert world_generation() == 0
    assert next_generation(3) == 4
    assert rendezvous_port(29500, 2) == 29502


def _coord(**kw):
    base = dict(steps_dir="/tmp/s.steps", telemetry_dir="/tmp/t", rank=1,
                world=2, reshape_mode="global_batch", impl="threefry2x32",
                geometry={"global_batch": 64})
    base.update(kw)
    return ElasticCoordinator(**base)


def test_rewire_env_port_math_never_compounds(monkeypatch):
    """MASTER_PORT for generation G is base + G where base is the ORIGINAL
    launch's port: a process already at generation 2 must un-apply its own
    offset, or repeated shrinks would drift the port unboundedly."""
    monkeypatch.setenv("MASTER_ADDR", "10.0.0.7")
    monkeypatch.setenv("MASTER_PORT", "29502")  # base 29500 + gen 2
    monkeypatch.setenv("PDMT_ELASTIC_GEN", "2")
    env = _coord().rewire_env(3, 0, 1)
    assert env == {"RANK": "0", "WORLD_SIZE": "1",
                   "MASTER_ADDR": "10.0.0.7", "MASTER_PORT": "29503",
                   "PDMT_ELASTIC_GEN": "3"}


def test_reexec_argv_strips_resume_and_forces_env_wireup():
    """The re-exec'd argv must resume from the SHARED steps dir (any stale
    --resume/--start_epoch stripped, both spellings) and rendezvous from
    the rewire env — a scheduler-derived wireup method would re-read the
    dead world's variables."""
    tail = ["--parallel", "--elastic", "--resume", "/old/dir",
            "--start_epoch=3", "--wireup_method", "slurm", "--lr", "0.1"]
    argv = _coord(argv_tail=tail).reexec_argv()
    assert argv == ["--parallel", "--elastic", "--lr", "0.1",
                    "--resume", "/tmp/s.steps", "--wireup_method", "env"]
    assert _strip_opt(["--a", "--resume=/x", "--b"], "--resume", 1) == \
        ["--a", "--b"]


def test_react_reraises_non_backend_errors():
    """A program error (shape mismatch, OOM) is NOT a peer loss: react()
    must fail fast and hand it back, never beacon/rescue on it."""
    err = RuntimeError("dot_general shape mismatch")
    with pytest.raises(RuntimeError, match="shape mismatch"):
        _coord().react(err, {}, journal=None)


# -- sampler / pipeline re-sharding ------------------------------------------

def test_sampler_reshard_shard_union_covers_the_epoch():
    from pytorch_ddp_mnist_tpu.parallel.sampler import ShardedSampler
    s = ShardedSampler(1000, num_replicas=4, rank=1, seed=7)
    s.set_epoch(2)
    survivors = [s.reshard(2, r) for r in range(2)]
    assert all(t.epoch == 2 for t in survivors)
    union = np.concatenate([t.indices() for t in survivors])
    # the union re-covers the SAME epoch permutation the old world agreed
    # on (wrap-padding may duplicate, never drop)
    assert set(union.tolist()) == set(range(1000))
    assert np.array_equal(np.sort(s.global_permutation()),
                          np.sort(survivors[0].global_permutation()))


def test_reshard_source_swaps_the_sampler_in_place():
    from pytorch_ddp_mnist_tpu.parallel.sampler import ShardedSampler
    from pytorch_ddp_mnist_tpu.pipeline.reader import reshard_source

    class Source:
        def __init__(self):
            self.sampler = ShardedSampler(64, num_replicas=4, rank=3)
            self.batch_size = 8

        def read_batch(self, rows):
            return rows, rows

    src = Source()
    src.sampler.set_epoch(5)
    out = reshard_source(src, 2, 1)
    assert out is src
    assert (src.sampler.num_replicas, src.sampler.rank) == (2, 1)
    assert src.sampler.epoch == 5
    with pytest.raises(ValueError, match="not pipeline-capable"):
        reshard_source(object(), 2, 0)
    src.sampler = object()      # duck-typed sampler without reshard()
    with pytest.raises(ValueError, match="no reshard"):
        reshard_source(src, 2, 0)


# -- CLI knob hygiene --------------------------------------------------------

def test_cli_rejects_unsound_elastic_combinations_by_name(tmp_path):
    from pytorch_ddp_mnist_tpu.cli.train import main
    with pytest.raises(SystemExit, match="needs --elastic"):
        main(["--reshape", "per_rank", "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="add --parallel"):
        main(["--elastic", "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="add --telemetry"):
        main(["--elastic", "--parallel", "--n_epochs", "1"])
    with pytest.raises(SystemExit, match="--ckpt_every_steps"):
        main(["--elastic", "--parallel", "--telemetry", str(tmp_path)])
    with pytest.raises(SystemExit, match="drop --cached"):
        main(["--elastic", "--parallel", "--telemetry", str(tmp_path),
              "--checkpoint", str(tmp_path / "c.msgpack"),
              "--ckpt_every_steps", "2", "--cached"])
    # a fully-valid elastic line is still CLI-only: re-exec needs sys.argv
    with pytest.raises(SystemExit, match="only available from the CLI"):
        main(["--elastic", "--parallel", "--telemetry", str(tmp_path),
              "--checkpoint", str(tmp_path / "c.msgpack"),
              "--ckpt_every_steps", "2"])


def test_configure_defaults_keep_elastic_off():
    from pytorch_ddp_mnist_tpu.train.config import configure
    tcfg = configure([])["trainer"]
    assert tcfg["elastic"] is False
    assert tcfg["reshape"] is None   # None != "global_batch": explicitly
    #                                  set without --elastic is detectable


# -- the --elastic-off inertness pin -----------------------------------------

def test_non_elastic_run_stamps_no_elastic_meta(tmp_path):
    """`--elastic` off must stay bitwise-identical to the pre-elastic CLI.
    The on-disk half of that pin: a plain checkpointed run's manifests
    carry NO elastic stamps (devices/elastic_gen), so its resume path —
    geometry comparison included — is byte-for-byte the old behavior. (The
    in-memory half is the whole rest of the suite: the elastic branch is
    the only new code path and it is gated on the flag.)"""
    from pytorch_ddp_mnist_tpu.cli.train import main
    from pytorch_ddp_mnist_tpu.train.ckpt_manager import peek_latest_meta
    ckpt = tmp_path / "plain.msgpack"
    assert main(["--n_epochs", "1", "--limit", "128", "--batch_size", "32",
                 "--lr", "0.1", "--checkpoint", str(ckpt),
                 "--ckpt_every_steps", "2",
                 "--path", str(tmp_path / "data")]) == 0
    peek = peek_latest_meta(str(ckpt) + ".steps")
    assert peek is not None
    assert "devices" not in peek["meta"]
    assert "elastic_gen" not in peek["meta"]
