"""ShardedSampler parity vs torch DistributedSampler semantics (SURVEY.md §7
item 3): seeded global permutation, padding by repetition, round-robin split,
per-epoch reshuffle."""

import numpy as np
import pytest

from pytorch_ddp_mnist_tpu.parallel import ShardedSampler

torch = pytest.importorskip("torch")
from torch.utils.data import DistributedSampler  # noqa: E402


class _FakeDataset:
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n


@pytest.mark.parametrize("n,world", [(100, 4), (60_000, 8), (10, 3), (7, 8)])
def test_no_shuffle_bitwise_matches_torch(n, world):
    for rank in range(world):
        ours = ShardedSampler(n, num_replicas=world, rank=rank, shuffle=False)
        theirs = DistributedSampler(_FakeDataset(n), num_replicas=world,
                                    rank=rank, shuffle=False)
        np.testing.assert_array_equal(ours.indices(), np.fromiter(iter(theirs), int))
        assert len(ours) == len(theirs)


@pytest.mark.parametrize("n,world", [(100, 4), (1000, 8), (13, 4)])
def test_shards_partition_padded_permutation(n, world):
    samplers = [ShardedSampler(n, num_replicas=world, rank=r, seed=42)
                for r in range(world)]
    for s in samplers:
        s.set_epoch(3)
    shards = [s.indices() for s in samplers]
    total = samplers[0].total_size
    assert sum(len(s) for s in shards) == total
    # Concatenated shards re-interleave into the global padded permutation.
    merged = np.empty(total, dtype=int)
    for r, sh in enumerate(shards):
        merged[r::world] = sh
    np.testing.assert_array_equal(merged, samplers[0].global_permutation())
    # Every original sample appears at least once.
    assert set(np.concatenate(shards)) == set(range(n))


def test_epoch_reshuffle_and_determinism():
    s = ShardedSampler(1000, num_replicas=4, rank=1, seed=42)
    s.set_epoch(0)
    e0 = s.indices()
    s.set_epoch(1)
    e1 = s.indices()
    assert not np.array_equal(e0, e1)
    s.set_epoch(0)
    np.testing.assert_array_equal(e0, s.indices())
    # Same (seed, epoch) on another instance agrees — all ranks can shuffle
    # without communicating, like torch's set_epoch contract.
    s2 = ShardedSampler(1000, num_replicas=4, rank=1, seed=42)
    s2.set_epoch(1)
    np.testing.assert_array_equal(e1, s2.indices())


def test_padding_by_repetition_from_head():
    s = ShardedSampler(10, num_replicas=4, rank=0, shuffle=False)
    perm = s.global_permutation()
    # 10 -> total 12, pad with head of the (identity) order: [0, 1]
    np.testing.assert_array_equal(perm, np.r_[np.arange(10), [0, 1]])


def test_pad_exceeding_dataset_cycles():
    # world > n: torch cycles the index list to fill the pad.
    s = ShardedSampler(3, num_replicas=8, rank=0, shuffle=False)
    perm = s.global_permutation()
    assert perm.size == 8
    np.testing.assert_array_equal(perm, [0, 1, 2, 0, 1, 2, 0, 1])


@pytest.mark.parametrize("n,world,epoch", [
    (100, 4, 0), (1000, 8, 17), (13, 4, 2), (7, 8, 1), (60_000, 4, 3)])
def test_torch_permutation_bitwise_matches_torch_shuffled(n, world, epoch):
    """permutation='torch' reproduces DistributedSampler(shuffle=True)
    INDEX-FOR-INDEX: the MT19937 randperm stream itself (torch_rng.py), the
    padding, and the interleave — the full shard composition of
    ddp_tutorial_multi_gpu.py:26-30 at the same seed. 60_000 covers real
    MNIST epochs (and >624-word generator blocks, where a wrong twist
    recurrence would first diverge)."""
    for rank in range(world):
        ours = ShardedSampler(n, num_replicas=world, rank=rank, seed=42,
                              permutation="torch")
        ours.set_epoch(epoch)
        theirs = DistributedSampler(_FakeDataset(n), num_replicas=world,
                                    rank=rank, shuffle=True, seed=42)
        theirs.set_epoch(epoch)
        np.testing.assert_array_equal(
            ours.indices(), np.fromiter(iter(theirs), int))


def test_torch_mt19937_engine_matches_torch_randperm_stream():
    """The engine itself (not just the composed sampler): randperm at sizes
    straddling the 624-word twist block, multiple seeds."""
    from pytorch_ddp_mnist_tpu.parallel.torch_rng import torch_randperm

    for n in (0, 1, 2, 623, 624, 625, 2000):
        for seed in (0, 42, 1 << 31):
            g = torch.Generator()
            g.manual_seed(seed)
            np.testing.assert_array_equal(
                torch_randperm(n, seed),
                torch.randperm(n, generator=g).numpy())


def test_permutation_kwarg_validated():
    with pytest.raises(ValueError, match="permutation"):
        ShardedSampler(10, permutation="mt19937")


def test_torch_permutation_default_unchanged():
    """The default stays PCG64 (documented fast path, no behavior change
    for existing callers); 'torch' is the opt-in."""
    a = ShardedSampler(100, seed=42)
    b = ShardedSampler(100, seed=42, permutation="torch")
    a.set_epoch(0), b.set_epoch(0)
    assert a.permutation == "pcg64"
    assert not np.array_equal(a.indices(), b.indices())


def test_torch_randperm_fuzz_random_sizes_and_seeds():
    """Randomized sweep (fixed meta-seed) of torch_randperm vs real torch:
    sizes straddle tile/twist boundaries by chance rather than curation, so
    a draw-order or block-boundary regression can't hide behind the
    hand-picked cases."""
    from pytorch_ddp_mnist_tpu.parallel.torch_rng import torch_randperm

    meta = np.random.default_rng(2026)
    for _ in range(25):
        n = int(meta.integers(0, 5000))
        seed = int(meta.integers(0, 2**63 - 1))
        g = torch.Generator()
        g.manual_seed(seed)
        np.testing.assert_array_equal(
            torch_randperm(n, seed),
            torch.randperm(n, generator=g).numpy(), err_msg=f"{n=} {seed=}")


def test_torch_bernoulli_fuzz_vs_real_torch():
    """torch_bernoulli IS torch's CPU ``tensor.bernoulli_(p)`` stream,
    bitwise: randomized sweep (fixed meta-seed) over seeds, sizes, and
    probabilities, with sizes straddling the 624-word twist blocks by
    chance. Also pins the nn.Dropout identity the trainer relies on
    (`--dropout_rng torch`): Dropout(p) == bernoulli_(1-p)/(1-p) on the
    same generator stream (ddp_tutorial_cpu.py:47)."""
    from pytorch_ddp_mnist_tpu.parallel.torch_rng import (TorchMT19937,
                                                          torch_bernoulli)

    meta = np.random.default_rng(31337)
    for _ in range(20):
        n = int(meta.integers(1, 40000))
        seed = int(meta.integers(0, 2**31 - 1))
        p = float(meta.uniform(0.05, 0.95))
        torch.manual_seed(seed)
        obs = torch.empty(n).bernoulli_(p).numpy()
        np.testing.assert_array_equal(
            torch_bernoulli(TorchMT19937(seed), n, p), obs,
            err_msg=f"{n=} {seed=} {p=}")
    # the dropout identity, on the reference's exact rate
    torch.manual_seed(7)
    drop = torch.nn.Dropout(0.2)(torch.ones(64, 128)).numpy()
    mask = torch_bernoulli(TorchMT19937(7), 64 * 128, 0.8)
    np.testing.assert_array_equal(drop, mask.reshape(64, 128) * np.float32(1.25))
