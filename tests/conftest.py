"""Test harness: run everything on a virtual 8-device CPU mesh.

This is the TPU-native analog of the reference's cluster stand-in — it tests
multi-node DDP semantics with 4 local gloo processes (train_cpu_mp.csh:1,
forced CPU at mnist_cpu_mp.py:248-250). Here, 8 virtual XLA host devices
stand in for a v4-8 slice (SURVEY.md §4): the same SPMD code paths, shardings
and collectives compile and run, just on CPU.

The session may have a real TPU backend pre-registered at interpreter startup
(sitecustomize), so setting env vars alone is not enough: we set XLA_FLAGS
(read lazily at CPU client creation), force the platform list to cpu, and
drop any already-initialized backend set.
"""

import os

if os.environ.get("PDMT_TPU_TESTS") == "1":
    # Hardware mode: keep the session's real TPU backend so the
    # Mosaic-only tests (marked tpu_only, skipped on CPU) actually run.
    # Intended for targeted selections on a TPU-attached machine, e.g.
    #   PDMT_TPU_TESTS=1 pytest tests/test_pallas_step.py -k pallas_rng
    # NOT for the full suite: most tests assume the 8-device CPU mesh.
    pass
else:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax.extend.backend import clear_backends
        clear_backends()
    except Exception:
        pass


# Two-tier suite (VERDICT r4 #7): the subprocess-heavy end-to-end files
# dominate wall-clock (each spawns fresh interpreters that re-import jax and
# re-jit), so they carry the `integration` mark and the default selection
# excludes them (addopts in pyproject.toml). `pytest -q` stays a fast unit
# pass; `pytest -m integration -q` runs the rest; `pytest -m "" -q` runs all.
_INTEGRATION_FILES = {
    "test_multiprocess.py",   # real jax.distributed 4-process rendezvous runs
    "test_mp_comm.py",        # 4-process DDP comm-strategy parity worlds
    "test_bench.py",          # bench.py CLI end-to-end via subprocess
    "test_cli.py",            # full trainer CLI configs end-to-end
    "test_measure_scripts.py",  # measure_hw.sh / hw_window.sh shell runs
    "test_outage_resume.py",  # repeated full training runs + re-exec paths
    "test_chaos.py",          # SIGKILL/resume chaos worlds via subprocess
}


def pytest_collection_modifyitems(items):
    import pytest

    for item in items:
        if os.path.basename(str(item.fspath)) in _INTEGRATION_FILES:
            item.add_marker(pytest.mark.integration)


if os.environ.get("PDMT_TPU_TESTS") == "1":
    # Hardware-mode watchdog: the tunneled backend can HANG mid-test (a
    # device sync that never returns — see parallel/wireup.py's hang-mode
    # notes), and a blocked C call is immune to pytest/SIGALRM. Arm a
    # faulthandler watchdog per test: if one test exceeds the bound, dump
    # every thread's traceback and hard-exit, so a wrapping `timeout`/script
    # sees the failure in minutes instead of losing the whole hardware
    # window. Bound via PDMT_TPU_TEST_TIMEOUT (seconds, default 600 —
    # generous for first-compile variance).
    import faulthandler

    import pytest

    _TEST_TIMEOUT = float(os.environ.get("PDMT_TPU_TEST_TIMEOUT", "600"))

    @pytest.fixture(autouse=True)
    def _tpu_test_watchdog():
        faulthandler.dump_traceback_later(_TEST_TIMEOUT, exit=True)
        yield
        faulthandler.cancel_dump_traceback_later()
