"""Test harness: run everything on a virtual 8-device CPU mesh.

This is the TPU-native analog of the reference's cluster stand-in — it tests
multi-node DDP semantics with 4 local gloo processes (train_cpu_mp.csh:1,
forced CPU at mnist_cpu_mp.py:248-250). Here, 8 virtual XLA host devices
stand in for a v4-8 slice (SURVEY.md §4): the same SPMD code paths, shardings
and collectives compile and run, just on CPU.

The session may have a real TPU backend pre-registered at interpreter startup
(sitecustomize), so setting env vars alone is not enough: we set XLA_FLAGS
(read lazily at CPU client creation), force the platform list to cpu, and
drop any already-initialized backend set.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    from jax.extend.backend import clear_backends
    clear_backends()
except Exception:
    pass
