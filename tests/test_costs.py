"""Program forensics (telemetry/costs.py): the analytic cost model pinned
against the hand-computed 118,272-param MLP, the harvest/record machinery,
the OOM classifier + flight-dump path, the measured-vs-analytic roofline
attribution, and the compile/HBM regression gate behind
`trace report --cost`."""

import json
import os

import numpy as np
import pytest

import jax

from pytorch_ddp_mnist_tpu import telemetry
from pytorch_ddp_mnist_tpu.telemetry import analysis, costs, flight
from pytorch_ddp_mnist_tpu.telemetry.runtime import (
    compile_attribution, install_compile_listener, label_compiles)
from pytorch_ddp_mnist_tpu.cli import trace as trace_cli
from pytorch_ddp_mnist_tpu.models.mlp import MLP_DIMS, init_mlp
from pytorch_ddp_mnist_tpu.parallel import collectives

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# the analytic model: exact, hand-computed for the reference MLP
# ---------------------------------------------------------------------------

def test_analytic_model_pinned_to_hand_computed_mlp():
    # 784*128 + 128*128 + 128*10 forward MACs/image — the bench.py
    # roofline constant, recomputed from the dims
    assert costs.model_macs(MLP_DIMS) == 118_016
    import bench
    assert costs.model_macs(MLP_DIMS) == bench.MACS_FWD_PER_IMG
    # train step: 6 FLOPs/MAC (fwd 2, bwd ~4), exact per-device batch
    assert costs.analytic_step_flops(MLP_DIMS, 16) == 6 * 118_016 * 16
    # inference: 2 FLOPs/MAC, the serve ladder's floor
    assert costs.analytic_forward_flops(MLP_DIMS, 8) == 1_888_256
    # the scaled r07 geometry: dims follow the zoo's width rule
    from pytorch_ddp_mnist_tpu.models.zoo import resolve_model
    dims16 = resolve_model("mlp", 16).dims
    assert costs.model_macs(dims16) == 784 * 2048 + 2048 * 2048 + 2048 * 10


def test_cost_labels_cannot_drift_from_parallel():
    """costs.py keeps a framework-free literal twin of
    collectives.step_cost_label; this is the no-drift pin."""
    for comm in collectives.STRATEGIES:
        for overlap in (False, True):
            for form in ("step", "run"):
                assert (costs._label(comm, overlap, form)
                        == collectives.step_cost_label(comm, overlap, form))


def test_dp_step_carries_cost_label():
    from pytorch_ddp_mnist_tpu.compat import abstract_mesh
    from pytorch_ddp_mnist_tpu.parallel.ddp import make_dp_train_step
    step = make_dp_train_step(abstract_mesh((8,), ("dp",)), 0.01,
                              comm="bf16", overlap=True)
    assert step.cost_label == "ddp.step.bf16+overlap"


def test_checker_field_catalogs_cannot_drift():
    """analysis.py's literal catalog (the file-loading checker's) must
    cover exactly the numeric fields a CostRecord can carry."""
    numeric = {"flops", "transcendentals", "bytes_accessed",
               "argument_bytes", "output_bytes", "temp_bytes",
               "generated_code_bytes", "alias_bytes", "peak_bytes",
               "analytic_flops", "wire_bytes", "compile_s"}
    assert set(analysis.COST_NUMERIC_FIELDS) == numeric
    assert analysis.COST_POINT == costs.COST_POINT


# ---------------------------------------------------------------------------
# harvest
# ---------------------------------------------------------------------------

def test_harvest_program_compiled_record():
    def f(x):
        return (x * 2.0 + 1.0).sum()

    rec = costs.harvest_program(f, (np.ones((4, 8), np.float32),),
                                label="test.tiny", kind="ddp", n_devices=1,
                                analytic_flops=32)
    assert rec.compiled is True and rec.error is None
    assert rec.flops is not None and rec.flops >= 0
    assert rec.compile_s is not None and rec.compile_s >= 0
    # the peak estimate sums the resident parts minus donated aliases
    parts = sum(p or 0 for p in (rec.argument_bytes, rec.output_bytes,
                                 rec.temp_bytes, rec.generated_code_bytes))
    assert rec.peak_bytes == parts - (rec.alias_bytes or 0)
    # harvest registers into the OOM-forensics program table
    assert costs.loaded_program_table()["test.tiny"]["compiled"] is True


def test_harvest_step_matrix_deviceless_fallback():
    """Forced mesh=None (no real mesh, the builders' AbstractMesh path):
    compile is impossible, but `lowered.cost_analysis()` still prices the
    math — records degrade to compiled=False with the error named, never
    raise."""
    recs = costs.harvest_step_matrix(comms=("pmean",), overlaps=(False,),
                                     n_dev=8, batch=4, mesh=None)
    assert len(recs) == 1
    rec = recs[0]
    assert rec.program == "ddp.step.pmean" and rec.compiled is False
    assert rec.error and "compile" in rec.error
    assert rec.flops is not None and rec.flops > 0      # deviceless analysis
    assert rec.peak_bytes is None                       # needs a compile
    assert rec.wire_bytes == collectives.bytes_on_wire(
        init_mlp(jax.random.PRNGKey(0)), 8, "pmean")
    assert rec.analytic_flops == costs.analytic_step_flops(MLP_DIMS, 4)


def test_harvest_step_matrix_compiled_on_fake_mesh():
    """The acceptance geometry: on the suite's 8 fake CPU devices the
    harvest compiles the real sharded program and fills the memory
    table."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh (conftest)")
    recs = costs.harvest_step_matrix(comms=("sharded",), overlaps=(False,),
                                     n_dev=8, batch=4)
    rec = recs[0]
    assert rec.program == "ddp.step.sharded" and rec.compiled is True
    assert rec.flops and rec.flops > 0
    assert rec.peak_bytes and rec.peak_bytes > 0
    assert rec.compile_s and rec.compile_s > 0
    # per-device analytic floor under the XLA bill for the per-device
    # partition (8 local rows of the 32-row global batch)
    assert rec.analytic_flops == costs.analytic_step_flops(MLP_DIMS, 4)
    assert costs.loaded_program_table()["ddp.step.sharded"]["compiled"]


def test_harvest_run_form_prices_all_steps():
    """A run-form record covers the scan body's RUN_EPOCHS x RUN_STEPS
    train steps: its analytic/wire totals must be the per-step figures
    times the step count, not one step's."""
    recs = costs.harvest_step_matrix(comms=("pmean",), overlaps=(False,),
                                     forms=("step", "run"), n_dev=8,
                                     batch=4, mesh=None)
    by_form = {r.form: r for r in recs}
    n_steps = costs.RUN_EPOCHS * costs.RUN_STEPS
    assert by_form["run"].analytic_flops \
        == by_form["step"].analytic_flops * n_steps
    assert by_form["run"].wire_bytes \
        == by_form["step"].wire_bytes * n_steps
    assert by_form["run"].program == "ddp.run.pmean"


def test_bench_overlap_copy_rows_carry_overlap_bound():
    """The byte-identical sharded/int8 overlap rows copy the MEASUREMENT
    but must stamp the overlap-form analytic bound (max(C, M)), so the
    artifact row and `trace report --cost`'s attribution of the same row
    can never disagree."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh (conftest)")
    import bench
    rows = bench.ddp_strategy_rows(per_chip_batch=4, epochs=2, n_rows=64,
                                   strategies=("sharded",),
                                   parity_steps=1,
                                   overlap_variants=(False, True))
    by = {r["overlap"]: r for r in rows}
    assert by[True]["images_per_sec"] == by[False]["images_per_sec"]  # copy
    # recompute both bounds from the row's own fields
    t1 = 4 / ((by[False]["per_chip_images_per_sec"] / 1)
              / by[False]["scaling_efficiency_vs_1dev"])  # C = b/one_dev_rate
    m = by[False]["collective_s_p50"]
    assert by[False]["analytic_efficiency"] == pytest.approx(
        t1 / (t1 + m), abs=2e-4)
    assert by[True]["analytic_efficiency"] == pytest.approx(
        t1 / max(t1, m), abs=2e-4)


def test_harvest_engine_ladder_and_accessor():
    from pytorch_ddp_mnist_tpu.serve.engine import InferenceEngine
    eng = InferenceEngine(init_mlp(jax.random.key(0)), max_batch=8)
    assert sorted(eng.compiled_programs()) == [1, 2, 4, 8]
    recs = costs.harvest_engine(eng)
    assert [r.program for r in recs] == [
        "serve.bucket1", "serve.bucket2", "serve.bucket4", "serve.bucket8"]
    for r in recs:
        assert r.compiled and r.kind == "serve" and r.wire_bytes == 0
        assert r.analytic_flops == costs.analytic_forward_flops(
            MLP_DIMS, int(r.program.replace("serve.bucket", "")))
        # XLA's bill is at least the matmul floor
        if r.flops is not None:
            assert r.flops >= r.analytic_flops
    # engine warmup already registered the ladder (constructor path)
    assert "serve.bucket8" in costs.loaded_program_table()


def test_compile_listener_records_durations_and_labels():
    """Satellite: the monitoring listener no longer drops the durations —
    xla.compile_s fills alongside xla.compiles, and a label_compiles block
    attributes them per program."""
    if not install_compile_listener():
        pytest.skip("jax.monitoring unavailable")
    hist = telemetry.get_registry().histogram("xla.compile_s")
    before_n = hist.n
    with label_compiles("test.labeled_compile"):
        jax.jit(lambda x: x * 5 + 2)(np.ones((3, 11, 5), np.float32))
    assert hist.n > before_n
    assert hist.total > 0
    attr = compile_attribution()
    assert attr["test.labeled_compile"]["count"] >= 1
    assert attr["test.labeled_compile"]["total_s"] > 0


def test_label_compiles_nests_and_restores():
    from pytorch_ddp_mnist_tpu.telemetry.runtime import current_compile_label
    assert current_compile_label() is None
    with label_compiles("outer"):
        assert current_compile_label() == "outer"
        with label_compiles("inner"):
            assert current_compile_label() == "inner"
        assert current_compile_label() == "outer"
    assert current_compile_label() is None


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

def test_looks_like_oom_matrix():
    oom = [
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating "
                     "1073741824 bytes."),
        RuntimeError("Resource exhausted: failed to allocate request for "
                     "2.5GiB"),
        ValueError("allocation failure on device 0"),
    ]
    not_oom = [
        RuntimeError("UNAVAILABLE: socket closed"),           # backend loss
        RuntimeError("DEADLINE_EXCEEDED: collective timeout"),
        RuntimeError("Incompatible shapes for dot: (3, 4) vs (5, 6)"),
        ValueError("start_offset=9 must be >= 0"),
    ]
    for e in oom:
        assert costs.looks_like_oom(e), e
    for e in not_oom:
        assert not costs.looks_like_oom(e), e
    # disjoint from the retry classifier: an OOM must never read as a
    # retryable outage, and vice versa
    from pytorch_ddp_mnist_tpu.parallel.wireup import looks_like_backend_loss
    for e in oom:
        assert not looks_like_backend_loss(e), e


def test_record_oom_forensics_dumps_program_and_watermarks(tmp_path):
    rec = flight.get_flight_recorder()
    costs.register_program({"program": "test.oomer", "peak_bytes": 12345,
                            "temp_bytes": 100})
    before = rec.recorded
    old_dir = rec.dump_dir
    try:
        rec.dump_dir = str(tmp_path)
        e = RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating "
                         "99999 bytes")
        path = costs.record_oom_forensics(e, program="test.oomer")
        assert path is not None and os.path.exists(path)
        entries = [x for x in rec.snapshot()
                   if x["kind"] == "oom_forensics" and x["seq"] >= before]
        assert len(entries) == 1
        entry = entries[0]
        assert entry["program"] == "test.oomer"
        assert entry["programs"]["test.oomer"]["peak_bytes"] == 12345
        # host RSS watermark exists everywhere; device ones only where
        # the backend reports memory_stats (guarded probe)
        assert entry["watermarks"].get("mem.host_rss_bytes", 0) > 0
        dumped = json.load(open(path))
        assert dumped["reason"] == "oom: test.oomer"
    finally:
        rec.dump_dir = old_dir


def test_record_oom_forensics_ignores_non_oom():
    rec = flight.get_flight_recorder()
    before = rec.recorded
    assert costs.record_oom_forensics(
        RuntimeError("Incompatible shapes"), program="x") is None
    assert not [x for x in rec.snapshot()
                if x["kind"] == "oom_forensics" and x["seq"] >= before]


def test_engine_run_bucket_oom_names_program(tmp_path):
    from pytorch_ddp_mnist_tpu.serve.engine import InferenceEngine
    eng = InferenceEngine(init_mlp(jax.random.key(0)), max_batch=4)
    rec = flight.get_flight_recorder()
    old_dir, rec.dump_dir = rec.dump_dir, str(tmp_path)
    try:
        def boom(params, x):
            raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory "
                               "allocating 7 bytes")
        eng._compiled[4] = boom
        before = rec.recorded
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            eng.forward(np.zeros((3, 784), np.float32))
        entries = [x for x in rec.snapshot()
                   if x["kind"] == "oom_forensics" and x["seq"] >= before]
        assert len(entries) == 1 and entries[0]["program"] == "serve.bucket4"

        def shape_err(params, x):
            raise RuntimeError("Incompatible shapes for dot")
        eng._compiled[4] = shape_err
        before = rec.recorded
        with pytest.raises(RuntimeError, match="Incompatible"):
            eng.forward(np.zeros((3, 784), np.float32))
        assert not [x for x in rec.snapshot()
                    if x["kind"] == "oom_forensics" and x["seq"] >= before]
    finally:
        rec.dump_dir = old_dir


# ---------------------------------------------------------------------------
# attribution: the measured-vs-analytic roofline decomposition
# ---------------------------------------------------------------------------

def _artifact(rows):
    return {"n_devices": 8, "strategies": rows}


def _row(**kw):
    base = {"strategy": "pmean", "overlap": False, "n_devices": 8,
            "images_per_sec": 80.0, "scaling_efficiency_vs_1dev": 0.10,
            "collective_s_p50": 0.08}
    base.update(kw)
    return base


def test_attribution_decomposition_math():
    rows = costs.attribution_from_artifact(
        _artifact([_row()]), per_chip_batch=4)
    assert len(rows) == 1
    r = rows[0]
    t = 4 * 8 / 80.0                          # measured step seconds
    assert r["measured_step_s"] == pytest.approx(t)
    assert r["compute_s"] == pytest.approx(0.10 * t)
    assert r["comm_s"] == pytest.approx(0.08)
    assert r["bound_s"] == pytest.approx(0.10 * t + 0.08)   # serial: C + M
    sh = r["shares"]
    assert sh["compute"] + sh["comm_exposed"] + sh["overhead"] \
        == pytest.approx(1.0, abs=1e-3)
    assert sh["compute"] == pytest.approx(0.10, abs=1e-3)   # == efficiency
    assert r["analytic_efficiency"] == pytest.approx(
        r["compute_s"] / r["bound_s"], abs=1e-3)


def test_attribution_overlap_bound_is_max():
    r = costs.attribution_from_artifact(
        _artifact([_row(overlap=True)]), per_chip_batch=4)[0]
    assert r["bound_s"] == pytest.approx(max(r["compute_s"], r["comm_s"]))
    assert r["program"] == "ddp.step.pmean+overlap"


def test_attribution_prefers_row_stamp_over_default():
    r = costs.attribution_from_artifact(
        _artifact([_row(per_chip_batch=4)]))[0]
    assert r["per_chip_batch"] == 4
    # legacy row (no stamp, no override) falls back to the bench default
    r = costs.attribution_from_artifact(_artifact([_row()]))[0]
    assert r["per_chip_batch"] == costs.DEFAULT_PER_CHIP_BATCH


def test_attribution_skips_undecomposable_rows():
    rows = costs.attribution_from_artifact(_artifact([
        _row(images_per_sec=0.0),                 # dead strategy
        _row(n_devices=1),                        # nothing on the wire
        _row(collective_s_p50=None),              # legacy probe-less row
        "not a dict",
    ]))
    assert rows == []


def test_committed_r07_artifact_decomposes_all_strategies():
    """The acceptance pin: the real MULTICHIP_r07.json decomposes into
    compute/comm/overhead for all 4 strategies on the 8-fake-device
    mesh."""
    report, err = costs.load_cost_report(
        os.path.join(REPO, "MULTICHIP_r07.json"), per_chip_batch=4)
    assert err is None
    att = report["attribution"]
    assert {r["strategy"] for r in att} == set(costs.COMMS)
    assert len(att) == 8                          # x overlap variants
    for r in att:
        sh = r["shares"]
        assert sh["compute"] + sh["comm_exposed"] + sh["overhead"] \
            == pytest.approx(1.0, abs=2e-3)
        assert 0 < r["analytic_efficiency"] <= 1
        assert r["measured_efficiency"] <= r["analytic_efficiency"]


def test_committed_cost_r01_stamps_required_fields():
    d = json.load(open(os.path.join(REPO, "COST_r01.json")))
    assert d["report"] == costs.COST_REPORT_TAG
    s = d["summary"]
    assert isinstance(s["peak_hbm_bytes"], int) and s["peak_hbm_bytes"] > 0
    assert s["compile_s_total"] > 0
    assert set(s["analytic_efficiency"]) == {
        costs._label(c, o) for c in costs.COMMS for o in (False, True)}
    assert d["param_scale"] == 16 and d["n_devices"] == 8  # r07 geometry


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------

def _mini_report(compile_count=3, peak=1000, eff=0.3):
    recs = [{"program": "ddp.step.pmean", "kind": "ddp", "compiled": True,
             "peak_bytes": peak, "compile_s": 0.1}]
    return {"report": costs.COST_REPORT_TAG, "v": 1, "records": recs,
            "attribution": [], "summary": {
                "programs": 1, "compile_count": compile_count,
                "compile_s_total": 0.1, "peak_hbm_bytes": peak,
                "analytic_efficiency": {"ddp.step.pmean": eff}}}


def test_compare_cost_self_is_clean():
    r = _mini_report()
    diff = costs.compare_cost(r, r)
    assert diff["rows"] and not diff["regressions"]


def test_compare_cost_gates_compile_count_growth():
    # ANY growth regresses (structural, not noisy)
    diff = costs.compare_cost(_mini_report(compile_count=4), _mini_report())
    assert [r["metric"] for r in diff["regressions"]] == ["compile_count"]
    # shrinking is fine
    diff = costs.compare_cost(_mini_report(compile_count=2), _mini_report())
    assert not diff["regressions"]


def test_compare_cost_gates_peak_hbm():
    diff = costs.compare_cost(_mini_report(peak=2500), _mini_report())
    assert {r["metric"] for r in diff["regressions"]} == {"peak_hbm_bytes",
                                                          "peak_bytes"}
    # under threshold: no fire
    diff = costs.compare_cost(_mini_report(peak=1400), _mini_report())
    assert not diff["regressions"]


def test_compare_cost_gates_analytic_efficiency():
    diff = costs.compare_cost(_mini_report(eff=0.1), _mini_report())
    assert [r["metric"] for r in diff["regressions"]] \
        == ["analytic_efficiency"]


def test_trace_report_cost_cli_gate_exit_codes(tmp_path, capsys):
    new = tmp_path / "new.json"
    old = tmp_path / "old.json"
    new.write_text(json.dumps(_mini_report(compile_count=5)))
    old.write_text(json.dumps(_mini_report()))
    # self-baseline: clean pass
    assert trace_cli.main(["report", "--cost", str(old),
                           "--baseline", str(old)]) == 0
    # injected compile-count regression: the exit-3 acceptance
    assert trace_cli.main(["report", "--cost", str(new),
                           "--baseline", str(old)]) == 3
    capsys.readouterr()
    # peak-HBM regression alone also exits 3
    bumped = tmp_path / "peak.json"
    bumped.write_text(json.dumps(_mini_report(peak=5000)))
    assert trace_cli.main(["report", "--cost", str(bumped),
                           "--baseline", str(old)]) == 3
    # plain report (no baseline) renders and exits 0
    assert trace_cli.main(["report", "--cost", str(old)]) == 0
    # unreadable target: exit 1
    assert trace_cli.main(["report", "--cost",
                           str(tmp_path / "missing.json")]) == 1
    capsys.readouterr()


def test_trace_report_cost_rejects_flag_combos(capsys):
    with pytest.raises(SystemExit):
        trace_cli.main(["report", "--cost", "--serve", "x"])
    with pytest.raises(SystemExit):
        trace_cli.main(["report", "--cost", "--data", "x"])
    with pytest.raises(SystemExit):                 # --batch is --cost-only
        trace_cli.main(["report", "--batch", "4", "x"])
    capsys.readouterr()


def test_load_cost_report_shapes(tmp_path):
    # non-JSON
    p = tmp_path / "x.json"
    p.write_text("not json")
    rep, err = costs.load_cost_report(str(p))
    assert rep is None and "not a JSON document" in err
    # JSON but neither shape
    p.write_text(json.dumps({"hello": 1}))
    rep, err = costs.load_cost_report(str(p))
    assert rep is None and "neither" in err
    # combined --baseline shape unwraps
    p.write_text(json.dumps({"report": _mini_report(), "comparison": {}}))
    rep, err = costs.load_cost_report(str(p))
    assert err is None and rep["summary"]["compile_count"] == 3


# ---------------------------------------------------------------------------
# cost records in the JSONL trace + the checker contract
# ---------------------------------------------------------------------------

def test_cost_record_errors_matrix():
    def pt(attrs):
        return {"v": 1, "kind": "point", "name": "program_cost",
                "t_wall": 1.0, "t_mono": 1.0, "proc": 0, "_line": 7,
                "attrs": attrs}

    good = pt({"program": "ddp.step.pmean", "flops": 1.0, "peak_bytes": 5})
    assert analysis.cost_record_errors([good]) == []
    errs = analysis.cost_record_errors([pt({"program": ""})])
    assert errs and "non-empty program" in errs[0][1]
    errs = analysis.cost_record_errors(
        [pt({"program": "x", "wire_bytes": -1})])
    assert errs and "non-negative" in errs[0][1]
    errs = analysis.cost_record_errors(
        [pt({"program": "x", "flops": True})])   # bool is not a count
    assert errs
    # non-cost points are not this contract's business
    other = {"v": 1, "kind": "point", "name": "health", "t_wall": 1.0,
             "t_mono": 1.0, "proc": 0, "attrs": {"detector": ""}}
    assert analysis.cost_record_errors([other]) == []


def test_emit_records_round_trips_through_trace(tmp_path):
    tr = telemetry.EventTrace(str(tmp_path / "events.jsonl"),
                              process_index=0)
    rec = costs.CostRecord(program="test.rt", kind="ddp", n_devices=8,
                           compiled=False, flops=12.0, wire_bytes=99)
    costs.emit_records(tr, [rec])
    tr.close()
    lines = [json.loads(ln) for ln in
             open(tmp_path / "events.jsonl").read().splitlines()]
    pts = [r for r in lines if r.get("name") == "program_cost"]
    assert len(pts) == 1
    a = pts[0]["attrs"]
    assert a["program"] == "test.rt" and a["wire_bytes"] == 99
    assert "peak_bytes" not in a                  # None fields stay absent


def test_checker_names_skipped_cost_checks_when_degraded(
        tmp_path, capsys, monkeypatch):
    """A checker copied beside an analysis.py that predates
    cost_record_errors must say so, once — the serve-contract degrade
    rule, extended to the cost contract."""
    import importlib.util
    import pathlib
    spec = importlib.util.spec_from_file_location(
        "check_telemetry_for_costs",
        pathlib.Path(__file__).resolve().parents[1] / "scripts"
        / "check_telemetry.py")
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)

    class _OldAnalysis:                     # pre-cost-contract surface
        @staticmethod
        def span_structure_errors(segment):
            return []

        @staticmethod
        def serve_structure_errors(segment):
            return []

    rec = {"v": 1, "kind": "point", "name": "program_cost", "t_wall": 1.0,
           "t_mono": 1.0, "proc": 0, "attrs": {"program": ""}}
    path = tmp_path / "events.jsonl"
    path.write_text(json.dumps(rec) + "\n")
    monkeypatch.setattr(checker, "_analysis", _OldAnalysis)
    monkeypatch.setattr(checker, "_degrade_noted", set())
    assert checker.main([str(path)]) == 0   # still a pass (check skipped)...
    err = capsys.readouterr().err
    assert err.count("skipping the program_cost record contract") == 1
    assert "non-negative byte/flop" in err  # ...naming WHAT was skipped
    # with the real analysis.py beside it, the same record FAILS
    monkeypatch.setattr(checker, "_analysis", analysis)
    monkeypatch.setattr(checker, "_degrade_noted", set())
    assert checker.main([str(path)]) == 1
    capsys.readouterr()


# ---------------------------------------------------------------------------
# memory watermarks: gauges, per-epoch points, Perfetto counter track
# ---------------------------------------------------------------------------

def test_collect_memory_installs_mem_namespace():
    reg = telemetry.MetricsRegistry()
    telemetry.collect_memory(reg)
    gauges = reg.snapshot()["gauges"]
    # the watermark names are ALWAYS present (the --require mem. gate);
    # device values None off-accelerator, host RSS a number where /proc
    # exists
    for name in ("mem.device_in_use_bytes", "mem.device_peak_bytes",
                 "mem.host_rss_bytes"):
        assert name in gauges
    if telemetry.host_rss_bytes() is not None:
        assert gauges["mem.host_rss_bytes"] > 0


def test_record_memory_point_emits_under_enabled_tracer(tmp_path):
    tr = telemetry.EventTrace(str(tmp_path / "events.jsonl"),
                              process_index=0)
    telemetry.record_memory_point(tr)
    tr.close()
    recs = [json.loads(ln) for ln in
            open(tmp_path / "events.jsonl").read().splitlines()]
    pts = [r for r in recs if r.get("name") == "mem_watermark"]
    if telemetry.host_rss_bytes() is None:
        pytest.skip("no RSS source on this platform")
    assert len(pts) == 1
    assert pts[0]["attrs"]["mem.host_rss_bytes"] > 0
    # NullTracer: no-op, no record, no probe
    telemetry.record_memory_point(telemetry.NullTracer())


def test_export_renders_mem_watermark_as_counter_track(tmp_path):
    path = tmp_path / "events.jsonl"
    recs = [
        {"v": 1, "kind": "meta", "name": "trace_start", "t_wall": 1.0,
         "t_mono": 0.0, "proc": 0},
        {"v": 1, "kind": "point", "name": "mem_watermark", "t_wall": 1.5,
         "t_mono": 0.5, "proc": 0,
         "attrs": {"mem.device_in_use_bytes": 4096,
                   "mem.host_rss_bytes": 1 << 20}},
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    trace = telemetry.chrome_trace([str(path)])
    counters = [e for e in trace["traceEvents"] if e["ph"] == "C"]
    assert {e["name"] for e in counters} == {"mem.device_in_use_bytes",
                                             "mem.host_rss_bytes"}
    assert all(e["cat"] == "mem" for e in counters)
    # no instant-event duplicate of the watermark sample
    assert not [e for e in trace["traceEvents"]
                if e["ph"] == "i" and e["name"] == "mem_watermark"]


def test_registry_stamp_carries_forensics_fields(monkeypatch):
    monkeypatch.setenv("PDMT_STATICS_STAMP", "0")   # keep the stamp cheap
    import bench
    reg = telemetry.MetricsRegistry()
    stamp = bench.registry_stamp(reg)
    assert "peak_hbm_bytes" in stamp            # None off-accelerator
    assert stamp["compile_s_total"] is None     # no compile_s hist yet
    reg.histogram("xla.compile_s").record(0.25)
    reg.histogram("xla.compile_s").record(0.5)
    assert bench.registry_stamp(reg)["compile_s_total"] == 0.75
