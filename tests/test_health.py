"""Live health monitoring (telemetry/health.py + telemetry/prom.py): the
watchdog's detector matrix (spike / NaN / grad-norm / update-ratio /
throughput / straggler), the fatal-signal policies (warn,
checkpoint-and-warn rescue of the last known-good state, abort), the
zero-host-sync invariant (the NullTracer-test technique), the Prometheus
text-format exposition (golden) and its stdlib HTTP endpoint, the serve
`{"op": "health"}` SLO op, and the end-to-end nan:step=K chaos path
through both trainers."""

import json
import urllib.request

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from pytorch_ddp_mnist_tpu import telemetry
from pytorch_ddp_mnist_tpu.telemetry import MetricsRegistry
from pytorch_ddp_mnist_tpu.telemetry.health import (AUX_FIELDS, HealthConfig,
                                                    TrainingHealthError,
                                                    Watchdog,
                                                    device_health_aux,
                                                    health_summary)
from pytorch_ddp_mnist_tpu.telemetry.prom import (metric_name,
                                                  render_prometheus,
                                                  start_metrics_server)
from pytorch_ddp_mnist_tpu.utils import faultpoints


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(faultpoints.FAULT_ENV, raising=False)
    faultpoints.install()
    yield
    faultpoints.install()


def _wd(policy="warn", **cfg):
    reg = MetricsRegistry()
    return Watchdog(HealthConfig(policy=policy, **cfg), registry=reg,
                    lr=0.1, log=lambda _m: None), reg


def _warm(wd, n=4, loss=1.0, dt=1.0):
    """Feed `n` healthy observations so the ratio detectors arm."""
    for e in range(n):
        ev = wd.observe(np.full(8, loss), epoch=e, step=(e + 1) * 8,
                        dt_s=dt, imgs=8 * 64)
        assert ev == []


# ---------------------------------------------------------------------------
# the detector matrix
# ---------------------------------------------------------------------------

def test_healthy_run_fires_nothing():
    wd, reg = _wd()
    _warm(wd, n=8)
    assert wd.events == []
    assert health_summary(reg) == {"fired": {}, "worst_severity": "ok"}


def test_nan_loss_is_fatal():
    from pytorch_ddp_mnist_tpu.telemetry.flight import get_flight_recorder
    # filter by monotonic seq, not a length-based slice: the ring is
    # BOUNDED, so once 256 earlier entries exist (e.g. the serve tracing
    # tests' reject/exemplar traffic) len() stops growing and a [before:]
    # slice of a full ring is forever empty
    seq_before = get_flight_recorder().recorded
    wd, reg = _wd()
    (ev,) = wd.observe(np.array([1.0, float("nan"), 1.0]), epoch=0, step=3)
    assert (ev.detector, ev.severity) == ("nan", "fatal")
    assert reg.snapshot()["counters"]["health.fired.nan"] == 1
    assert reg.snapshot()["gauges"]["health.worst_severity_level"] == 2
    assert health_summary(reg)["worst_severity"] == "fatal"
    # acceptance: the event reaches the flight recorder too (the
    # post-mortem ring), not just the trace + registry
    tail = [e for e in get_flight_recorder().snapshot()
            if e["kind"] == "health" and e["seq"] >= seq_before]
    assert tail and tail[0]["detector"] == "nan" \
        and tail[0]["severity"] == "fatal"


def test_inf_loss_is_fatal():
    wd, _reg = _wd()
    (ev,) = wd.observe(np.array([float("inf")]), epoch=0, step=1)
    assert ev.detector == "nan" and ev.severity == "fatal"


def test_aux_finite_flag_trips_nan_detector():
    wd, _ = _wd()
    aux = np.array([[1.0, 0.0, 10.0]])     # finite flag 0: in-program trip
    (ev,) = wd.observe(np.array([1.0]), aux=aux, epoch=0, step=1)
    assert ev.detector == "nan" and "finite-check" in ev.message


def test_loss_spike_after_warmup_only():
    wd, _ = _wd()
    # during warmup a 10x loss must NOT fire (no baseline yet)
    assert wd.observe(np.full(8, 10.0), epoch=0, step=8) == []
    wd2, _ = _wd()
    _warm(wd2)
    (ev,) = wd2.observe(np.full(8, 10.0), epoch=4, step=40)
    assert (ev.detector, ev.severity) == ("loss_spike", "warn")
    assert ev.value == pytest.approx(10.0)


def test_grad_norm_explosion():
    wd, _ = _wd()
    good = np.tile([2.0, 1.0, 100.0], (8, 1))
    for e in range(4):
        assert wd.observe(np.full(8, 1.0), aux=good, epoch=e,
                          step=(e + 1) * 8) == []
    boom = np.tile([50.0, 1.0, 100.0], (8, 1))
    (ev,) = wd.observe(np.full(8, 1.0), aux=boom, epoch=4, step=40)
    assert (ev.detector, ev.severity) == ("grad_norm", "warn")


def test_update_ratio_outside_band():
    wd, _ = _wd()   # lr=0.1; band default (1e-9, 1e-1)
    # ratio = lr * g / p = 0.1 * 60 / 10 = 0.6 > 0.1
    aux = np.tile([60.0, 1.0, 10.0], (4, 1))
    events = wd.observe(np.full(4, 1.0), aux=aux, epoch=0, step=4)
    assert [e.detector for e in events] == ["update_ratio"]


def test_throughput_collapse():
    wd, _ = _wd()
    _warm(wd, n=4, dt=1.0)                          # ~512 img/s baseline
    (ev,) = wd.observe(np.full(8, 1.0), epoch=4, step=40,
                       dt_s=20.0, imgs=8 * 64)      # ~26 img/s: collapse
    assert (ev.detector, ev.severity) == ("throughput", "warn")


def test_straggler_drift_uses_shared_skew_math():
    from pytorch_ddp_mnist_tpu.telemetry.analysis import skew
    wd, _ = _wd(straggler_skew_pct=50.0)
    _warm(wd, n=4, dt=1.0)                          # warmup windows dropped
    for e in range(4, 7):                           # steady post-warmup
        assert wd.observe(np.full(8, 1.0), epoch=e, step=(e + 1) * 8,
                          dt_s=1.0, imgs=8 * 64) == []
    events = wd.observe(np.full(8, 1.0), epoch=7, step=64,
                        dt_s=3.0, imgs=8 * 64)      # one 3x-slow window
    names = [e.detector for e in events]
    assert "straggler" in names
    ev = events[names.index("straggler")]
    # the online detector reports exactly analysis.skew over its window
    # (the window opened at the last warmup observation: 4 steady values
    # of 1/8 s/step before the 3/8 slow one)
    _, expect_pct = skew([1.0 / 8] * 4 + [3.0 / 8])
    assert ev.value == pytest.approx(expect_pct)


def test_compile_heavy_first_window_not_a_straggler(caplog):
    # the first observations carry XLA compile time; the straggler window
    # must open after warmup or every run would begin with a false alarm
    wd, _ = _wd(straggler_skew_pct=50.0)
    assert wd.observe(np.full(8, 1.0), epoch=0, step=8,
                      dt_s=30.0, imgs=8 * 64) == []      # compile window
    for e in range(1, 8):
        ev = wd.observe(np.full(8, 1.0), epoch=e, step=(e + 1) * 8,
                        dt_s=1.0, imgs=8 * 64)
        assert "straggler" not in [x.detector for x in ev]


# ---------------------------------------------------------------------------
# policy: warn / checkpoint-and-warn / abort
# ---------------------------------------------------------------------------

def test_abort_raises_training_health_error():
    wd, _ = _wd(policy="abort")
    with pytest.raises(TrainingHealthError, match="nan"):
        wd.observe(np.array([float("nan")]), epoch=2, step=17)
    # the events were recorded BEFORE the raise
    assert [e.detector for e in wd.events] == ["nan"]


def test_training_health_error_is_not_a_runtime_error():
    # the outage-retry machinery triages RuntimeErrors for backend-loss
    # signatures; a diverged model must never enter that path
    assert not issubclass(TrainingHealthError, RuntimeError)


class _FakeState:
    def __init__(self, params, resid=None):
        self.params = params
        self.key = jax.random.key(0)
        self.resid = resid


def test_checkpoint_and_warn_rescues_pre_nan_state():
    saved = []
    reg = MetricsRegistry()
    wd = Watchdog(HealthConfig(policy="checkpoint-and-warn"), registry=reg,
                  on_fatal=saved.append, log=lambda _m: None)
    good = _FakeState({"w": np.full(3, 7.0)},
                      resid=np.full((2, 4), 0.5, np.float32))
    wd.seed_good(_FakeState({"w": np.zeros(3)}), epoch=0, offset=0, step=0)
    wd.observe(np.full(4, 1.0), state=good, epoch=0, step=4,
               ckpt_epoch=0, ckpt_offset=4)               # healthy: stashed
    poisoned = _FakeState({"w": np.full(3, float("nan"))})
    wd.observe(np.array([float("nan")]), state=poisoned, epoch=0, step=8)
    (stash,) = saved
    # the rescue got the LAST KNOWN-GOOD state and positions, not the
    # poisoned one observed at detection time
    assert stash["step"] == 4 and (stash["epoch"], stash["offset"]) == (0, 4)
    np.testing.assert_array_equal(stash["params"]["w"], np.full(3, 7.0))
    # the int8 error-feedback residual is resume state: it rides the
    # rescue stash alongside params/key (None when the strategy carries
    # none — the seed state above — or when it is not host-addressable)
    np.testing.assert_array_equal(stash["resid"],
                                  np.full((2, 4), 0.5, np.float32))


def test_checkpoint_and_warn_first_window_rescues_the_seed():
    saved = []
    wd = Watchdog(HealthConfig(policy="checkpoint-and-warn"),
                  registry=MetricsRegistry(), on_fatal=saved.append,
                  log=lambda _m: None)
    wd.seed_good(_FakeState({"w": np.ones(2)}), epoch=0, offset=0, step=0)
    wd.observe(np.array([float("nan")]), epoch=0, step=4)
    assert saved and saved[0]["step"] == 0


def test_rescue_hook_failure_never_raises():
    def explode(_stash):
        raise OSError("disk died")
    wd = Watchdog(HealthConfig(policy="checkpoint-and-warn"),
                  registry=MetricsRegistry(), on_fatal=explode,
                  log=lambda _m: None)
    wd.seed_good(_FakeState({"w": np.ones(2)}), epoch=0, offset=0, step=0)
    wd.observe(np.array([float("nan")]), epoch=0, step=1)   # must not raise


def test_stash_skipped_without_rescue_hook():
    # non-rank-0 watchdogs must not pay the per-observation params copy
    wd, _ = _wd(policy="checkpoint-and-warn")
    assert wd.on_fatal is None
    wd.observe(np.full(4, 1.0), state=_FakeState({"w": np.ones(2)}),
               epoch=0, step=4)
    assert wd._last_good is None


# ---------------------------------------------------------------------------
# the device-side aux fold + zero-host-sync invariant
# ---------------------------------------------------------------------------

def test_device_health_aux_values():
    loss = jnp.float32(1.0)
    grads = {"a": jnp.array([3.0, 4.0])}            # |g| = 5
    params = {"a": jnp.array([0.0, 12.0, 5.0])}     # |p| = 13
    aux = np.asarray(device_health_aux(loss, grads, params))
    assert aux.shape == (len(AUX_FIELDS),)
    assert aux[0] == pytest.approx(5.0)
    assert aux[1] == 1.0
    assert aux[2] == pytest.approx(13.0)
    bad = np.asarray(device_health_aux(
        jnp.float32(float("nan")), grads, params))
    assert bad[1] == 0.0


def test_health_step_matches_plain_step_trajectory():
    """health=True only APPENDS an output: params/key/loss bitwise match
    the plain step."""
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.train.loop import make_train_step

    x = np.random.default_rng(0).random((8, 784)).astype(np.float32)
    y = np.arange(8) % 10
    plain = make_train_step(0.1)
    health = make_train_step(0.1, health=True)
    assert not getattr(plain, "health_aux") and health.health_aux
    p1, k1, l1 = plain(init_mlp(jax.random.key(0)), jax.random.key(1), x, y)
    p2, k2, l2, aux = health(init_mlp(jax.random.key(0)),
                             jax.random.key(1), x, y)
    assert float(l1) == float(l2)
    np.testing.assert_array_equal(np.asarray(jax.random.key_data(k1)),
                                  np.asarray(jax.random.key_data(k2)))
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    aux = np.asarray(aux)
    assert aux[1] == 1.0 and aux[0] > 0 and aux[2] > 0


def test_dp_health_step_returns_aux():
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.parallel.ddp import dp_mesh, make_dp_train_step

    mesh = dp_mesh()
    step = make_dp_train_step(mesh, 0.1, health=True)
    assert step.health_aux
    n = mesh.devices.size
    x = np.random.default_rng(0).random((8 * n, 784)).astype(np.float32)
    y = np.arange(8 * n) % 10
    params, key, loss, aux = step(init_mlp(jax.random.key(0)),
                                  jax.random.key(1), x, y)
    aux = np.asarray(aux)
    assert aux.shape == (3,) and aux[1] == 1.0 and aux[0] > 0


def _tiny_fit(watchdog=None):
    from pytorch_ddp_mnist_tpu.data import (BatchLoader, normalize_images,
                                            synthetic_mnist)
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.parallel import ShardedSampler
    from pytorch_ddp_mnist_tpu.train import TrainState, fit

    train = synthetic_mnist(128, seed=0)
    test = synthetic_mnist(64, seed=1)
    sampler = ShardedSampler(128, num_replicas=1, rank=0, seed=42)
    loader = BatchLoader(normalize_images(train.images), train.labels,
                         sampler, batch_size=32)
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(1))
    return fit(state, loader, normalize_images(test.images),
               test.labels.astype(np.int32), epochs=2, batch_size=32,
               lr=0.1, log=lambda _m: None, watchdog=watchdog)


def test_watchdog_healthy_path_never_forces_block_until_ready():
    """Acceptance: an ENABLED watchdog on a healthy run — with the
    health-aux step fold active — adds zero block_until_ready-forcing
    calls, exactly like the NullTracer invariant (the detectors consume
    only already-fetched values; the aux rides the loss fetch). Pinned
    via the shared sanitizer (statics.sanitize.no_host_sync), which is
    the monkeypatch idiom this test invented, promoted."""
    from pytorch_ddp_mnist_tpu.statics import sanitize

    wd, _ = _wd()
    with sanitize.no_host_sync() as sync:     # max_block_until_ready=0
        _tiny_fit(watchdog=wd)
    assert sync.armed and sync.block_until_ready_calls == 0
    assert wd.events == [] or all(e.severity != "fatal" for e in wd.events)


def test_watchdog_fetches_stay_epoch_granular():
    """The block_until_ready pin above cannot see np.asarray-style fetches
    — so additionally count device->host conversions of jax Arrays during
    a watchdog-enabled run: they must scale with EPOCHS (one loss + one
    aux fetch per epoch, plus the eval fetch), never with STEPS. The
    counter is the shared sanitizer's fetch budget; 2 epochs x 16 steps
    would show >= 32 conversions on a per-step regression."""
    from pytorch_ddp_mnist_tpu.data import (BatchLoader, normalize_images,
                                            synthetic_mnist)
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.parallel import ShardedSampler
    from pytorch_ddp_mnist_tpu.statics import sanitize
    from pytorch_ddp_mnist_tpu.train import TrainState, fit

    train = synthetic_mnist(128, seed=0)
    test = synthetic_mnist(64, seed=1)
    sampler = ShardedSampler(128, num_replicas=1, rank=0, seed=42)
    loader = BatchLoader(normalize_images(train.images), train.labels,
                         sampler, batch_size=8)       # 16 steps/epoch
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(1))
    wd, _ = _wd()

    with sanitize.no_host_sync(max_block_until_ready=None,
                               max_fetches=2 * 6) as sync:
        fit(state, loader, normalize_images(test.images),
            test.labels.astype(np.int32), epochs=2, batch_size=8,
            lr=0.1, log=lambda _m: None, watchdog=wd)
    assert 0 < sync.fetches <= 2 * 6, sync.fetches


def test_fit_detects_injected_nan_and_emits_trace_event(tmp_path):
    faultpoints.install("nan:step=2")
    telemetry.enable(str(tmp_path))
    try:
        wd, reg = _wd()
        _tiny_fit(watchdog=wd)
    finally:
        telemetry.disable()
    nan_events = [e for e in wd.events if e.detector == "nan"]
    assert nan_events and nan_events[0].severity == "fatal"
    # detection at the fetch boundary: the window END is epoch 0's last
    # step, the poisoned step is inside it
    assert nan_events[0].epoch == 0 and nan_events[0].step == 4
    recs = [json.loads(ln) for ln in
            open(tmp_path / "events.jsonl").read().splitlines()]
    health_pts = [r for r in recs
                  if r["kind"] == "point" and r["name"] == "health"]
    assert [p["attrs"]["detector"] for p in health_pts] == ["nan"]
    assert health_pts[0]["attrs"]["severity"] == "fatal"


def test_fit_cached_chunk_rescue_saves_pre_nan_chunk_boundary():
    """The scanned trainer detects at checkpoint-chunk granularity: a NaN
    in chunk 2 rescues the chunk-1-boundary state (the acceptance
    'intact checkpoint at the pre-NaN step')."""
    from pytorch_ddp_mnist_tpu.data import synthetic_mnist
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.parallel import ShardedSampler
    from pytorch_ddp_mnist_tpu.train import TrainState
    from pytorch_ddp_mnist_tpu.train.scan import fit_cached

    faultpoints.install("nan:step=6")           # chunk 2 (steps 5..8)
    saved = []
    wd = Watchdog(HealthConfig(policy="checkpoint-and-warn"),
                  registry=MetricsRegistry(), on_fatal=saved.append,
                  log=lambda _m: None)
    train = synthetic_mnist(512, seed=0)
    test = synthetic_mnist(64, seed=1)
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(1))
    wd.seed_good(state, epoch=0, offset=0, step=0)
    sampler = ShardedSampler(512, num_replicas=1, rank=0, seed=42)
    fit_cached(state, train.images, train.labels, sampler,
               (test.images.reshape(64, -1) / 255.0).astype(np.float32),
               test.labels.astype(np.int32), epochs=1, batch_size=64,
               lr=0.1, ckpt_every_steps=4, log=lambda _m: None,
               watchdog=wd)
    (stash,) = saved
    assert stash["step"] == 4                    # the pre-NaN boundary
    assert (stash["epoch"], stash["offset"]) == (0, 4)
    assert all(np.isfinite(leaf).all()
               for leaf in jax.tree_util.tree_leaves(stash["params"]))
    nan_events = [e for e in wd.events if e.detector == "nan"]
    assert nan_events and nan_events[0].step == 8


def test_fit_cached_fused_rejects_watchdog_by_name():
    from pytorch_ddp_mnist_tpu.data import synthetic_mnist
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.parallel import ShardedSampler
    from pytorch_ddp_mnist_tpu.train import TrainState
    from pytorch_ddp_mnist_tpu.train.scan import fit_cached

    train = synthetic_mnist(128, seed=0)
    wd, _ = _wd()
    with pytest.raises(ValueError, match="fused"):
        fit_cached(TrainState(init_mlp(jax.random.key(0)),
                              jax.random.key(1)),
                   train.images, train.labels,
                   ShardedSampler(128, num_replicas=1, rank=0, seed=42),
                   np.zeros((8, 784), np.float32),
                   np.zeros(8, np.int32), epochs=1, batch_size=64,
                   lr=0.1, fused=True, watchdog=wd)


# ---------------------------------------------------------------------------
# Prometheus exposition: golden + endpoint
# ---------------------------------------------------------------------------

def test_metric_name_mapping():
    assert metric_name("serve.latency_s") == "serve_latency_s"
    assert metric_name("health.worst_severity_level") == \
        "health_worst_severity_level"
    assert metric_name("a-b c") == "a_b_c"
    assert metric_name("9lives") == "_9lives"


def test_render_prometheus_golden():
    reg = MetricsRegistry()
    reg.counter("train.steps").inc(42)
    reg.gauge("queue.depth").set(3)
    reg.gauge("dead.provider").set_fn(lambda: None)   # omitted, not lied
    h = reg.histogram("lat_s")
    h.record(0.001)
    h.record(0.001)
    text = render_prometheus(reg)
    lines = text.splitlines()
    assert lines[0] == "# TYPE train_steps counter"
    assert lines[1] == "train_steps 42"
    assert "# TYPE queue_depth gauge" in lines
    assert "queue_depth 3" in lines
    assert not any("dead_provider" in ln for ln in lines)
    i = lines.index("# TYPE lat_s summary")
    q50 = lines[i + 1]
    assert q50.startswith('lat_s{quantile="0.5"} ')
    # percentile clamps to the recorded max (the registry's contract)
    assert float(q50.split()[-1]) == pytest.approx(0.001)
    assert f"lat_s_count 2" in lines
    assert any(ln.startswith("lat_s_sum ") for ln in lines)
    assert "# TYPE lat_s_max gauge" in lines
    assert text.endswith("\n")


def test_render_covers_every_registry_metric_plus_health():
    """Acceptance: the exposition covers every registry metric plus the
    health_* gauges once a watchdog exists."""
    reg = MetricsRegistry()
    wd = Watchdog(HealthConfig(), registry=reg, log=lambda _m: None)
    reg.counter("xla.compiles").inc(5)
    reg.histogram("serve.latency_s").record(0.01)
    wd.observe(np.array([float("nan")]), epoch=0, step=1)
    text = render_prometheus(reg)
    snap = reg.snapshot()
    for name in (list(snap["counters"]) + list(snap["histograms"])
                 + [n for n, v in snap["gauges"].items() if v is not None]):
        assert metric_name(name) in text, name
    assert "health_worst_severity_level 2" in text
    assert "health_fired_nan 1" in text


def test_render_safe_under_concurrent_metric_creation():
    """The scrape thread renders while the training thread lazily creates
    metrics (health.fired.<detector> on first firing, timer histograms):
    snapshot() must list the tables under the registry lock or a scrape
    dies with 'dictionary changed size during iteration'."""
    import threading

    reg = MetricsRegistry()
    stop = threading.Event()

    def churn():
        i = 0
        while not stop.is_set() and i < 20000:
            reg.counter(f"c{i}").inc()
            reg.histogram(f"h{i}").record(0.001)
            i += 1

    t = threading.Thread(target=churn)
    t.start()
    try:
        for _ in range(60):
            render_prometheus(reg)        # raised RuntimeError pre-fix
    finally:
        stop.set()
        t.join()


def test_metrics_http_endpoint_and_healthz():
    reg = MetricsRegistry()
    wd = Watchdog(HealthConfig(), registry=reg, log=lambda _m: None)
    reg.counter("train.steps").inc(7)
    srv = start_metrics_server(0, registry=reg)
    try:
        port = srv.server_address[1]
        resp = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                      timeout=10)
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        body = resp.read().decode()
        assert "train_steps 7" in body
        assert "health_worst_severity_level 0" in body
        hz = urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz",
                                    timeout=10)
        assert json.loads(hz.read()) == {"fired": {}, "worst_severity": "ok"}
        with pytest.raises(urllib.error.HTTPError) as e404:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope",
                                   timeout=10)
        assert e404.value.code == 404
        # a fatal signal flips /healthz to 503 — the liveness-probe story
        wd.observe(np.array([float("nan")]), epoch=0, step=1)
        with pytest.raises(urllib.error.HTTPError) as e503:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz",
                                   timeout=10)
        assert e503.value.code == 503
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# serve: the rolling SLO monitor + {"op": "health"}
# ---------------------------------------------------------------------------

def test_slo_window_exact_percentile_and_rate():
    from pytorch_ddp_mnist_tpu.serve import SLOWindow
    w = SLOWindow(window=100)
    assert w.percentile(0.99) == 0.0 and w.service_rate() is None
    for i in range(100):
        w.record(0.001 * (i + 1), t_done=float(i))
    assert w.percentile(0.99) == pytest.approx(0.099)
    assert w.percentile(0.50) == pytest.approx(0.050)
    assert w.service_rate() == pytest.approx(1.0)   # 99 completions / 99 s
    # the window ROLLS: a regime change is fully visible after `window`
    for i in range(100):
        w.record(0.5, t_done=100.0 + i * 0.01)      # collapse to 100 rps...
    assert w.percentile(0.99) == pytest.approx(0.5)
    assert w.service_rate() == pytest.approx(100.0, rel=0.02)


def test_serve_health_op_answers_rolling_slo(monkeypatch):
    import asyncio
    from pytorch_ddp_mnist_tpu.cli.serve import handle_request
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.serve import InferenceEngine, ServeService

    eng = InferenceEngine(init_mlp(jax.random.key(0)), max_batch=4)
    svc = ServeService(eng, max_delay_ms=1.0)

    async def scenario():
        for _ in range(5):
            await handle_request(svc, {"pixels": [0.1] * 784})
        return await handle_request(svc, {"op": "health"})

    h = asyncio.run(scenario())
    assert h["ok"]
    health = h["health"]
    assert health["window_n"] == 5
    assert health["rolling_p99_ms"] > 0
    assert health["service_rate_rps"] is not None
    assert health["queue_depth"] == 0 and health["draining"] is False
    # the same live numbers are registry gauges (the /metrics surface)
    gauges = svc.metrics.registry.snapshot()["gauges"]
    # the op rounds to 3 decimals of a millisecond; the gauge is exact
    assert gauges["serve.rolling_p99_s"] == pytest.approx(
        health["rolling_p99_ms"] / 1e3, abs=1e-6)
    assert gauges["serve.service_rate_rps"] is not None


# ---------------------------------------------------------------------------
# health_summary + the bench stamp shape
# ---------------------------------------------------------------------------

def test_health_summary_empty_process():
    assert health_summary(MetricsRegistry()) == {"fired": {},
                                                 "worst_severity": None}


def test_registry_stamp_carries_health_summary():
    import importlib.util
    import pathlib
    spec = importlib.util.spec_from_file_location(
        "bench", pathlib.Path(__file__).resolve().parents[1] / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    reg = MetricsRegistry()
    wd = Watchdog(HealthConfig(), registry=reg, log=lambda _m: None)
    wd.observe(np.array([float("nan")]), epoch=0, step=1)
    stamp = bench.registry_stamp(reg)
    assert stamp["health_summary"] == {"fired": {"nan": 1},
                                       "worst_severity": "fatal"}
    json.dumps(stamp)                            # artifact-line JSON-able


# ---------------------------------------------------------------------------
# the checker's health-event schema
# ---------------------------------------------------------------------------

def _check(path_args):
    import importlib.util
    import pathlib
    spec = importlib.util.spec_from_file_location(
        "check_telemetry",
        pathlib.Path(__file__).resolve().parents[1] / "scripts"
        / "check_telemetry.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main(path_args)


def _trace_with(tmp_path, attrs):
    recs = [{"v": 1, "kind": "meta", "name": "trace_start", "t_wall": 1.0,
             "t_mono": 1.0, "proc": 0},
            {"v": 1, "kind": "point", "name": "health", "t_wall": 2.0,
             "t_mono": 2.0, "proc": 0, "attrs": attrs}]
    p = tmp_path / "events.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return str(p)


def test_checker_accepts_wellformed_health_event(tmp_path):
    assert _check([_trace_with(tmp_path, {"detector": "nan",
                                          "severity": "fatal",
                                          "value": 1.0})]) == 0


@pytest.mark.parametrize("attrs", [
    {"severity": "warn"},                         # detector missing
    {"detector": "nan"},                          # severity missing
    {"detector": "", "severity": "warn"},         # empty detector
    {"detector": "nan", "severity": "nuclear"},   # unknown severity
])
def test_checker_rejects_malformed_health_events(tmp_path, attrs):
    assert _check([_trace_with(tmp_path, attrs)]) == 1
