"""The driver entry contract (__graft_entry__.py): entry() must hand back a
jittable forward on the flagship model, and dryrun_multichip(n) must compile
and run the SPMD training programs on an n-device mesh. Locked here so the
contract can't rot between driver runs (conftest provides the 8-device CPU
pool the dry run needs)."""

import os
import sys

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft  # noqa: E402


def test_entry_forward_jits():
    fn, (params, x) = graft.entry()
    logits = jax.jit(fn)(params, x)
    assert logits.shape == (x.shape[0], 10)
    assert np.isfinite(np.asarray(logits)).all()


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)  # asserts internally; must not raise
