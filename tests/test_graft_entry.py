"""The driver entry contract (__graft_entry__.py): entry() must hand back a
jittable forward on the flagship model, and dryrun_multichip(n) must compile
and run the SPMD training programs on an n-device mesh. Locked here so the
contract can't rot between driver runs."""

import os
import pathlib
import subprocess
import sys

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft  # noqa: E402

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_entry_forward_jits():
    fn, (params, x) = graft.entry()
    logits = jax.jit(fn)(params, x)
    assert logits.shape == (x.shape[0], 10)
    assert np.isfinite(np.asarray(logits)).all()


def test_dryrun_multichip_8():
    # In a SUBPROCESS, exactly like the driver runs it: dryrun_multichip
    # re-provisions the host pool to mesh+1 devices (the simulator's spare
    # worker) by restarting the backend with new XLA_FLAGS — done
    # in-process, every later test in the suite would see a 9-device pool
    # (this broke test_mesh/test_scan order-dependently when the spare
    # landed).
    out = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        cwd=REPO, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
