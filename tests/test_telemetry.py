"""telemetry/: the unified metrics registry, the JSONL event trace and its
schema checker, the runtime collectors, and the acceptance invariants —
`--telemetry` emits a schema-valid trace with per-epoch phase spans and a
registry snapshot, the serve `{"op": "stats"}` op answers the same registry
shape, and DISABLED telemetry adds zero `block_until_ready`-forcing calls
to the training hot loop."""

import asyncio
import json
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from pytorch_ddp_mnist_tpu import telemetry
from pytorch_ddp_mnist_tpu.telemetry import (Counter, EventTrace, Gauge,
                                             Histogram, MetricsRegistry,
                                             NullTracer)
from pytorch_ddp_mnist_tpu.telemetry import events as events_mod
from pytorch_ddp_mnist_tpu.telemetry import runtime as runtime_mod

# the checker is a repo-root script, not a package module (the repo idiom,
# see test_bench's bench_matrix loads)
import importlib.util
import pathlib

_spec = importlib.util.spec_from_file_location(
    "check_telemetry",
    pathlib.Path(__file__).resolve().parents[1] / "scripts"
    / "check_telemetry.py")
_checker = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_checker)
check_main = _checker.main


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_get_or_create_and_snapshot():
    reg = MetricsRegistry()
    c = reg.counter("train.steps")
    c.inc()
    c.inc(4)
    assert reg.counter("train.steps") is c          # same live instance
    reg.gauge("queue.depth").set(7)
    reg.histogram("lat").record(0.010)
    snap = reg.snapshot()
    assert snap["counters"]["train.steps"] == 5
    assert snap["gauges"]["queue.depth"] == 7
    assert snap["histograms"]["lat"]["n"] == 1
    json.dumps(snap)                                # JSON-able verbatim


def test_registry_type_conflict_raises():
    reg = MetricsRegistry()
    reg.counter("x")
    with pytest.raises(ValueError, match="different type"):
        reg.gauge("x")
    with pytest.raises(ValueError, match="already registered"):
        reg.register("x", Histogram("x"))
    with pytest.raises(TypeError):
        reg.register("y", object())


def test_counter_is_monotonic():
    c = Counter("n")
    with pytest.raises(ValueError, match="decrease"):
        c.inc(-1)
    c.set_total(10)
    c.set_total(3)                                  # never moves down
    assert c.value == 10


def test_gauge_callable_reads_the_instant():
    box = {"v": 1}
    g = Gauge("depth")
    g.set_fn(lambda: box["v"])
    assert g.value == 1
    box["v"] = 9
    assert g.value == 9
    g.set_fn(lambda: 1 / 0)                         # dead provider
    assert g.value is None                          # must not kill snapshot


def test_histogram_percentiles_pessimistic_and_clamped():
    h = Histogram("lat")
    assert h.percentile(0.99) == 0.0                # empty
    for v in (0.001, 0.002, 0.005, 0.100):
        h.record(v)
    assert h.percentile(0.50) == pytest.approx(0.002, rel=0.25)
    assert h.percentile(0.99) == pytest.approx(0.100, rel=1e-6)  # clamp
    snap = h.snapshot()
    assert set(snap) == {"n", "mean", "max", "total", "p50", "p95", "p99"}
    assert snap["total"] == pytest.approx(0.108)    # exact sum, not bucketed
    assert snap["n"] == 4 and snap["max"] == 0.100


def test_serve_latency_histogram_is_registry_alias():
    """The old private serve type survives as a thin alias of the shared
    Histogram, seconds-unit spellings intact."""
    from pytorch_ddp_mnist_tpu.serve.metrics import LatencyHistogram
    h = LatencyHistogram()
    assert isinstance(h, Histogram)
    h.record(0.004)
    assert h.mean_s == h.mean and h.max_s == h.max and h.total_s == h.total


def test_serve_metrics_publish_into_registry():
    from pytorch_ddp_mnist_tpu.serve.metrics import ServeMetrics
    reg = MetricsRegistry()
    m = ServeMetrics(depth_fn=lambda: 2, registry=reg)
    m.record_arrival()
    m.record_done(0.003)
    m.record_reject()
    m.record_batch(3, 4)
    snap = reg.snapshot()
    assert snap["counters"]["serve.completed"] == 1
    assert snap["counters"]["serve.rejected"] == 1
    assert snap["counters"]["serve.bucket_rows"] == 4
    assert snap["gauges"]["serve.queue_depth"] == 2
    assert snap["histograms"]["serve.latency_s"]["n"] == 1
    # the dashboard snapshot keeps its original shape on top
    assert m.snapshot()["completed"] == 1


# ---------------------------------------------------------------------------
# events: JSONL trace
# ---------------------------------------------------------------------------

def test_event_trace_spans_nest_and_validate(tmp_path):
    trace = telemetry.enable(str(tmp_path))
    try:
        with trace.span("epoch", epoch=0) as ep:
            trace.complete_span("data_wait", 0.25, batches=3)
            with trace.span("eval") as ev:
                pass
        trace.point("checkpoint", path="m.msgpack")
        reg = MetricsRegistry()
        reg.counter("xla.compiles").inc(2)
        trace.snapshot(reg)
    finally:
        telemetry.disable()
    assert check_main([str(tmp_path)]) == 0
    recs = [json.loads(ln) for ln in
            open(tmp_path / "events.jsonl").read().splitlines()]
    by_name = {r["name"]: r for r in recs}
    assert by_name["trace_start"]["kind"] == "meta"
    # children carry the epoch span's id as parent; the epoch span itself
    # is top-level, and nesting state unwound cleanly
    assert by_name["data_wait"]["parent"] == by_name["epoch"]["span"]
    assert by_name["eval"]["parent"] == by_name["epoch"]["span"]
    assert by_name["epoch"]["parent"] is None
    assert by_name["data_wait"]["dur_s"] == 0.25
    assert by_name["checkpoint"]["kind"] == "point"
    assert by_name["registry"]["attrs"]["counters"]["xla.compiles"] == 2
    assert all(r["v"] == 1 and "proc" in r for r in recs)
    # ordering invariant the checker enforces: emission-stamped t_mono
    monos = [r["t_mono"] for r in recs]
    assert monos == sorted(monos)
    assert ep.parent_id is None and ev.parent_id == ep.span_id


def test_event_trace_span_sync_blocks_at_exit(tmp_path, monkeypatch):
    """span.sync(tree) is the Timer.sync contract: nothing blocks at the
    sync() call, the registered tree drains once at span exit."""
    calls = []
    monkeypatch.setattr(jax, "block_until_ready",
                        lambda t: calls.append(t) or t)
    trace = EventTrace(str(tmp_path / "t.jsonl"), process_index=0)
    fake_tree = {"loss": object()}
    with trace.span("epoch") as s:
        assert s.sync(fake_tree) is fake_tree
        assert calls == []                          # deferred
    assert calls == [fake_tree]                     # exactly one drain
    trace.close()


def test_span_sync_failure_still_emits_and_unwinds(tmp_path, monkeypatch):
    """A failing device drain (XlaRuntimeError at block_until_ready) must
    not corrupt the tracer: the span still pops off the parent stack and
    its record is still written, then the exception propagates."""
    def boom(_t):
        raise RuntimeError("device lost")
    monkeypatch.setattr(jax, "block_until_ready", boom)
    trace = EventTrace(str(tmp_path / "t.jsonl"), process_index=0)
    with pytest.raises(RuntimeError, match="device lost"):
        with trace.span("epoch") as s:
            s.sync({"x": 1})
    with trace.span("next"):            # stack unwound: top-level again
        pass
    trace.close()
    spans = {r["name"]: r for r in
             (json.loads(ln) for ln in open(tmp_path / "t.jsonl"))
             if r["kind"] == "span"}
    assert spans["epoch"]["dur_s"] >= 0     # failed span still recorded
    assert spans["next"]["parent"] is None  # not parented to the dead span


def test_null_tracer_is_default_and_free():
    assert isinstance(events_mod.get_tracer(), NullTracer)
    t = events_mod.get_tracer()
    with t.span("anything", epoch=1) as s:
        tree = {"a": 1}
        assert s.sync(tree) is tree                 # forwards untouched
    t.complete_span("x", 1.0)
    t.point("y")
    t.snapshot(MetricsRegistry())
    t.close()                                       # all no-ops


def test_enable_disable_swaps_process_tracer(tmp_path):
    tr = telemetry.enable(str(tmp_path), process_index=3)
    try:
        assert events_mod.get_tracer() is tr
        assert tr.path.endswith("events.rank3.jsonl")  # rank-gated file
    finally:
        telemetry.disable()
    assert isinstance(events_mod.get_tracer(), NullTracer)


# ---------------------------------------------------------------------------
# checker: reject the broken streams
# ---------------------------------------------------------------------------

def _write(tmp_path, lines, name="events.jsonl"):
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    return str(tmp_path)


def _rec(**kw):
    base = {"v": 1, "kind": "point", "name": "x", "t_wall": 1.0,
            "t_mono": 1.0, "proc": 0}
    base.update(kw)
    return json.dumps(base)


def test_checker_require_metric_prefix(tmp_path, capsys):
    """--require PREFIX (the ddp-smoke contract): pass when the registry
    snapshot carries a matching metric, fail (naming the prefix) when not,
    usage error when the prefix value is missing."""
    trace = [
        _rec(kind="meta", name="trace_start", t_mono=1.0),
        _rec(kind="snapshot", name="registry", t_mono=2.0,
             attrs={"counters": {"ddp.bytes_on_wire": 8192},
                    "gauges": {},
                    "histograms": {"ddp.collective_s": {"n": 3}}}),
    ]
    path = _write(tmp_path, trace)
    assert check_main(["--require", "ddp.", path]) == 0
    assert check_main(["--require", "ddp.", "--require", "serve.",
                       path]) == 1
    assert "serve." in capsys.readouterr().err
    assert check_main([path, "--require"]) == 2     # usage
    # a trace with NO snapshot at all fails the gate too (own dir — the
    # gate is per-target, and the first dir legitimately satisfies it)
    bare_dir = tmp_path / "bare"
    bare_dir.mkdir()
    bare = _write(bare_dir, [_rec(kind="meta", name="trace_start",
                                  t_mono=1.0)])
    assert check_main(["--require", "ddp.", bare]) == 1


def test_checker_accepts_synthetic_good_stream(tmp_path, capsys):
    good = [
        _rec(kind="meta", name="trace_start", t_mono=1.0),
        _rec(kind="span", name="child", t_mono=2.0, span=2, parent=1,
             dur_s=0.5),
        _rec(kind="span", name="parent", t_mono=3.0, span=1, parent=None,
             dur_s=1.0),
        _rec(kind="snapshot", name="registry", t_mono=4.0),
    ]
    assert check_main([_write(tmp_path, good)]) == 0
    assert "OK" in capsys.readouterr().out


@pytest.mark.parametrize("bad,why", [
    (["{not json"], "malformed"),
    ([_rec(v=99)], "schema version"),
    ([_rec(kind="mystery")], "unknown kind"),
    ([json.dumps({"v": 1, "kind": "point"})], "missing fields"),
    ([_rec(t_mono=5.0), _rec(t_mono=1.0)], "out of order"),
    ([_rec(kind="span", span=1, dur_s=-0.1)], "negative"),
    ([_rec(kind="span", span=1, dur_s="0.5")], "not a number"),
    ([_rec(kind="span", span=2, parent=77, dur_s=0.1)], "never recorded"),
    ([_rec(kind="span", dur_s=0.5)], "missing 'span'"),
])
def test_checker_rejects_broken_streams(tmp_path, capsys, bad, why):
    assert check_main([_write(tmp_path, bad)]) == 1
    assert why in capsys.readouterr().err


def test_checker_resets_scope_per_appended_segment(tmp_path):
    """The writer appends, so two runs share one file: the second segment's
    restarted t_mono clock and reused span ids must validate, while a
    cross-segment parent reference must not resolve."""
    two_runs = [
        _rec(kind="meta", name="trace_start", t_mono=100.0),
        _rec(kind="span", name="epoch", t_mono=101.0, span=1, parent=None,
             dur_s=1.0),
        # appended second run: clock restarted (reboot/new process), same ids
        _rec(kind="meta", name="trace_start", t_mono=5.0),
        _rec(kind="span", name="epoch", t_mono=6.0, span=1, parent=None,
             dur_s=1.0),
    ]
    assert check_main([_write(tmp_path, two_runs)]) == 0
    leaky = two_runs[:3] + [
        _rec(kind="span", name="child", t_mono=6.0, span=2, parent=1,
             dur_s=0.5),   # parent 1 lives in the PREVIOUS segment only
    ]
    assert check_main([_write(tmp_path, leaky)]) == 1


def test_checker_empty_and_missing_targets(tmp_path, capsys):
    assert check_main([str(tmp_path)]) == 1         # no events*.jsonl
    assert check_main([str(tmp_path / "nope")]) == 1
    (tmp_path / "events.jsonl").write_text("")
    assert check_main([str(tmp_path)]) == 1         # empty trace
    assert check_main([]) == 2                      # usage


def test_checker_names_skipped_serve_checks_when_degraded(
        tmp_path, capsys, monkeypatch):
    """A checker copied beside an OLDER analysis.py (no
    serve_structure_errors) must not degrade silently: one stderr note
    names the skipped serve span checks, once — a partial copy can't
    masquerade as a full pass. Same for a missing analysis.py."""
    class _OldAnalysis:                     # pre-serve-contract surface
        @staticmethod
        def span_structure_errors(segment):
            return []

    trace = [_rec(kind="meta", name="trace_start", t_mono=1.0),
             _rec(kind="span", name="s", t_mono=2.0, span=1, parent=None,
                  dur_s=0.1)]
    path = _write(tmp_path, trace)
    monkeypatch.setattr(_checker, "_analysis", _OldAnalysis)
    monkeypatch.setattr(_checker, "_degrade_noted", set())
    assert check_main([path]) == 0          # still a pass...
    err = capsys.readouterr().err
    assert err.count("skipping the serve span contract") == 1  # ...but said
    assert "request_id" in err              # names WHAT was skipped

    monkeypatch.setattr(_checker, "_analysis", None)
    monkeypatch.setattr(_checker, "_degrade_noted", set())
    assert check_main([path]) == 0
    err = capsys.readouterr().err
    assert "orphaned-parent" in err and "serve span contract" in err


# ---------------------------------------------------------------------------
# runtime collectors
# ---------------------------------------------------------------------------

def test_process_index_cached_resolves_once(monkeypatch):
    monkeypatch.setattr(runtime_mod, "_process_index", None)
    assert runtime_mod.process_index_cached() == 0  # single process
    # resolved value is cached: a later backend failure cannot change it
    monkeypatch.setattr(jax, "process_index",
                        lambda: (_ for _ in ()).throw(RuntimeError("down")))
    assert runtime_mod.process_index_cached() == 0


def test_process_index_failure_reads_rank0_uncached(monkeypatch):
    """Pre-`jax.distributed`-init behavior: a failing resolve reports 0
    but is NOT cached, so the first post-init call still lands the real
    rank."""
    monkeypatch.setattr(runtime_mod, "_process_index", None)
    monkeypatch.setattr(jax, "process_index",
                        lambda: (_ for _ in ()).throw(RuntimeError("not up")))
    assert runtime_mod.process_index_cached() == 0
    assert runtime_mod._process_index is None       # failure not cached
    monkeypatch.setattr(jax, "process_index", lambda: 2)
    assert runtime_mod.process_index_cached() == 2


def test_rank_zero_log_uses_cached_index(monkeypatch):
    from pytorch_ddp_mnist_tpu.utils import rank_zero_log
    lines = []
    assert rank_zero_log(lines.append)("hi") is None and lines == ["hi"]
    monkeypatch.setattr(runtime_mod, "_process_index", 3)
    silent = rank_zero_log(lines.append)
    silent("dropped")
    assert lines == ["hi"]                          # non-zero rank: no-op


def test_compile_listener_counts_fresh_compiles():
    armed = telemetry.install_compile_listener()
    counter = telemetry.get_registry().counter("xla.compiles")
    if not armed:                                   # old jax: fallback path
        pytest.skip("jax.monitoring unavailable")
    before = counter.value
    # a shape this process has never jitted: guaranteed fresh backend compile
    fn = jax.jit(lambda x: x * 3 + 1)
    fn(jnp.ones((7, 13, 3)))
    assert counter.value > before
    # cache hit (same jitted callable, same shape): no new compile counted
    mid = counter.value
    fn(jnp.ones((7, 13, 3)))
    assert counter.value == mid


def test_compile_listener_single_target_per_process():
    """One counter per process: a repeat install for the same target is a
    no-op True; a different registry gets an honest False (never a
    silently zero-reading counter) and keeps the engine-probe fallback."""
    if not telemetry.install_compile_listener():
        pytest.skip("jax.monitoring unavailable")
    assert telemetry.install_compile_listener() is True      # same target
    other = MetricsRegistry()
    assert telemetry.install_compile_listener(other) is False
    # the refusal left no zero-reading counter behind: the artifact stamp
    # reads absent (None), never a false 0
    assert "xla.compiles" not in other.snapshot()["counters"]


def test_serve_metrics_reconstruct_on_shared_registry():
    """A second ServeMetrics on the same registry (service rebuilt against
    the process-wide registry) adopts the live metrics instead of raising —
    merge semantics, same as the counters' get-or-create."""
    from pytorch_ddp_mnist_tpu.serve.metrics import ServeMetrics
    reg = MetricsRegistry()
    m1 = ServeMetrics(registry=reg)
    m1.record_arrival()
    m1.record_done(0.001)
    m2 = ServeMetrics(registry=reg)
    m2.record_arrival()
    m2.record_done(0.002)
    assert reg.snapshot()["histograms"]["serve.latency_s"]["n"] == 2
    assert m2.snapshot()["completed"] == 2
    # the adopted instance keeps the deprecated *_s compat spellings
    assert m2.latency.mean_s == m2.latency.mean
    assert m2.latency is m1.latency


def test_engine_compile_probe_fallback():
    reg = MetricsRegistry()
    telemetry.record_engine_compiles(reg, 5)
    assert reg.snapshot()["counters"]["serve.engine_compiles"] == 5


def test_memory_collectors_guarded_for_cpu():
    assert telemetry.device_memory_stats() is None or \
        isinstance(telemetry.device_memory_stats(), dict)   # CPU: None
    rss = telemetry.host_rss_bytes()
    assert rss is None or rss > 0
    reg = MetricsRegistry()
    out = telemetry.collect_memory(reg)
    if rss is not None:
        assert out["host.rss_bytes"] > 0
        assert reg.snapshot()["gauges"]["host.rss_bytes"] > 0


# ---------------------------------------------------------------------------
# serve {"op": "stats"}
# ---------------------------------------------------------------------------

def test_serve_stats_op_answers_registry_snapshot():
    from pytorch_ddp_mnist_tpu.cli.serve import handle_request
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.serve import InferenceEngine, ServeService

    eng = InferenceEngine(init_mlp(jax.random.key(0)), max_batch=4)
    reg = MetricsRegistry()
    telemetry.record_engine_compiles(reg, eng.compile_count)
    svc = ServeService(eng, max_delay_ms=1.0, registry=reg)

    async def scenario():
        pred = await handle_request(svc, {"pixels": [0.1] * 784})
        stats = await handle_request(svc, {"op": "stats"})
        legacy = await handle_request(svc, {"op": "metrics"})
        return pred, stats, legacy

    pred, stats, legacy = asyncio.run(scenario())
    assert pred["ok"] and 0 <= pred["pred"] <= 9
    # the registry snapshot shape, same as the JSONL final record's attrs
    assert set(stats["registry"]) == {"counters", "gauges", "histograms"}
    assert stats["registry"]["counters"]["serve.completed"] == 1
    assert stats["registry"]["counters"]["serve.engine_compiles"] == \
        eng.compile_count
    assert stats["registry"]["histograms"]["serve.latency_s"]["n"] == 1
    # the percentile dashboard rides along, identical to the legacy op
    assert stats["serve"]["completed"] == legacy["completed"] == 1
    json.dumps(stats)


# ---------------------------------------------------------------------------
# train loop wiring + the no-sync acceptance invariant
# ---------------------------------------------------------------------------

def _tiny_fit(tracer_dir=None, dispatch_profiler=None):
    from pytorch_ddp_mnist_tpu.data import (BatchLoader, normalize_images,
                                            synthetic_mnist)
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.parallel import ShardedSampler
    from pytorch_ddp_mnist_tpu.train import TrainState, fit

    train = synthetic_mnist(128, seed=0)
    test = synthetic_mnist(64, seed=1)
    sampler = ShardedSampler(128, num_replicas=1, rank=0, seed=42)
    loader = BatchLoader(normalize_images(train.images), train.labels,
                         sampler, batch_size=32)
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(1))
    return fit(state, loader, normalize_images(test.images),
               test.labels.astype(np.int32), epochs=2, batch_size=32,
               lr=0.1, log=lambda _m: None,
               dispatch_profiler=dispatch_profiler)


def test_hot_loop_never_forces_block_until_ready(monkeypatch):
    """Acceptance: telemetry DISABLED (the default) adds no per-step host
    sync — the streaming train loop performs ZERO block_until_ready-forcing
    calls (its one sync per epoch is the loss-curve fetch, not a drain);
    and ENABLING telemetry keeps it at zero (spans never sync unless a
    call site opts in)."""
    calls = []
    real = jax.block_until_ready
    monkeypatch.setattr(jax, "block_until_ready",
                        lambda t: calls.append(1) or real(t))
    _tiny_fit()
    assert calls == []                              # disabled: none
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        telemetry.enable(td)
        try:
            _tiny_fit()
        finally:
            telemetry.disable()
    assert calls == []                              # enabled: still none


def test_fit_emits_epoch_phase_spans(tmp_path):
    telemetry.enable(str(tmp_path))
    try:
        _tiny_fit()
    finally:
        telemetry.disable()
    assert check_main([str(tmp_path)]) == 0
    recs = [json.loads(ln) for ln in
            open(tmp_path / "events.jsonl").read().splitlines()]
    epochs = [r for r in recs if r["name"] == "epoch"]
    assert [r["attrs"]["epoch"] for r in epochs] == [0, 1]
    for ep in epochs:
        kids = {r["name"]: r for r in recs
                if r.get("parent") == ep["span"]}
        assert {"data_wait", "step_compute", "eval"} <= set(kids)
        assert kids["step_compute"]["attrs"]["steps"] == 4   # 128/32
        # the phase split can never exceed the epoch wall time
        assert (kids["data_wait"]["dur_s"] + kids["step_compute"]["dur_s"]
                <= ep["dur_s"] + 1e-6)


# ---------------------------------------------------------------------------
# dispatch forensics (telemetry/dispatch.py)
# ---------------------------------------------------------------------------

def test_null_profiler_is_the_free_default():
    # every hook a no-op, unarmed — the loop's default costs nothing
    prof = telemetry.NullProfiler()
    assert prof.armed is False
    prof.mark_prestep()
    prof.begin_dispatch(sync_tree={"p": 1})
    prof.end_dispatch(0)
    prof.note_sync_wait(0.5)
    prof.flush_epoch(0, steps=4)


def test_dispatch_profiler_off_path_is_bitwise_and_zero_sync(monkeypatch):
    """The zero-overhead contract: an ARMED profiler with sampling off
    (sample_every=0) never drains — zero block_until_ready — and the
    trained params are bitwise identical to the unprofiled run."""
    state_ref = _tiny_fit()
    calls = []
    real = jax.block_until_ready
    monkeypatch.setattr(jax, "block_until_ready",
                        lambda t: calls.append(1) or real(t))
    prof = telemetry.DispatchProfiler(sample_every=0)
    state = _tiny_fit(dispatch_profiler=prof)
    assert calls == []
    for a, b in zip(jax.tree_util.tree_leaves(state_ref.params),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dispatch_profiler_samples_through_the_module_attr(monkeypatch):
    """The 1-in-K drain goes through the jax.block_until_ready MODULE
    attribute — exactly what sanitize.no_host_sync patches — so sampled
    syncs are counted against a sanitizer budget, never smuggled."""
    calls = []
    real = jax.block_until_ready
    monkeypatch.setattr(jax, "block_until_ready",
                        lambda t: calls.append(1) or real(t))
    prof = telemetry.DispatchProfiler(sample_every=2)
    _tiny_fit(dispatch_profiler=prof)
    assert len(calls) == 4          # 8 steps over 2 epochs, every 2nd
    # sampled steps land in the flight ring for post-mortem dumps
    from pytorch_ddp_mnist_tpu.telemetry import flight
    entries = [e for e in flight.get_flight_recorder().snapshot()
               if e["kind"] == "dispatch"]
    sampled = [e for e in entries if "idle_s" in e]
    assert len(sampled) == 4 and all(e["idle_s"] >= 0 for e in sampled)


def test_dispatch_flush_emits_contract_valid_points(tmp_path):
    telemetry.enable(str(tmp_path))
    try:
        _tiny_fit(dispatch_profiler=telemetry.DispatchProfiler(
            sample_every=2))
        # the run-end registry snapshot cli/train.py emits (the --require
        # gate reads metric names off snapshot records)
        telemetry.get_tracer().snapshot(telemetry.get_registry())
    finally:
        telemetry.disable()
    # schema + the dispatch record contract + the dispatch.* metric gate
    assert check_main(["--require", "dispatch.", str(tmp_path)]) == 0
    recs = [json.loads(ln) for ln in
            open(tmp_path / "events.jsonl").read().splitlines()]
    phases = [r for r in recs if r.get("name") == "dispatch_phase"]
    windows = [r for r in recs if r.get("name") == "dispatch_window"]
    assert {p["attrs"]["phase"] for p in phases} >= {"python_prestep",
                                                     "dispatch",
                                                     "device_idle"}
    assert all(p["attrs"]["total_s"] >= 0 for p in phases)
    assert [w["attrs"]["epoch"] for w in windows] == [0, 1]
    for w in windows:
        assert w["attrs"]["steps"] == 4
        # the loop hands its OWN step-timer total as the window: the
        # profiler's attribution is checked against an independent clock
        assert 0 <= w["attrs"]["attributed_s"]
        assert 0 <= w["attrs"]["coverage"]


def test_dispatch_profiler_under_no_host_sync_budget():
    """sample_every=0 passes the zero-block budget; a sampling profiler
    under the same budget is the violation no_host_sync exists to catch."""
    from pytorch_ddp_mnist_tpu.statics import sanitize
    with sanitize.no_host_sync(max_block_until_ready=0):
        _tiny_fit(dispatch_profiler=telemetry.DispatchProfiler(
            sample_every=0))
    with pytest.raises(sanitize.HostSyncError):
        with sanitize.no_host_sync(max_block_until_ready=0):
            _tiny_fit(dispatch_profiler=telemetry.DispatchProfiler(
                sample_every=2))


def test_measure_dispatch_phases_shares_sum_to_wall():
    import time as _time

    def step_once():
        _time.sleep(0.001)
        return jnp.zeros(8) + 1

    out = telemetry.measure_dispatch_phases(step_once, steps=3)
    assert out["steps"] == 3
    total = (out["python_prestep"] + out["dispatch"] + out["sync_wait"])
    assert total == pytest.approx(out["probe_step_s"], rel=1e-6)
    assert out["device_idle"] >= 0


# ---------------------------------------------------------------------------
# CLI front door (in-process): the acceptance command's contract
# ---------------------------------------------------------------------------

def test_cli_train_telemetry_end_to_end(tmp_path, capsys):
    from pytorch_ddp_mnist_tpu.cli.train import main
    obs = tmp_path / "obs"
    assert main(["--epochs", "1", "--limit", "256", "--batch_size", "64",
                 "--path", str(tmp_path / "nodata"), "--checkpoint", "",
                 "--telemetry", str(obs)]) == 0
    out = capsys.readouterr().out
    assert "[telemetry]" in out and "xla_compiles=" in out  # rank-0 summary
    assert check_main([str(obs)]) == 0
    recs = [json.loads(ln) for ln in
            open(obs / "events.jsonl").read().splitlines()]
    names = [r["name"] for r in recs]
    assert {"epoch", "data_wait", "step_compute", "eval"} <= set(names)
    final = recs[-1]
    assert final["kind"] == "snapshot"              # last record = registry
    assert final["attrs"]["counters"]["xla.compiles"] > 0
    assert final["attrs"]["gauges"].get("host.rss_bytes", 0) > 0


def test_epochs_alias_for_n_epochs():
    from pytorch_ddp_mnist_tpu.train.config import configure
    assert configure(["--epochs", "3"])["trainer"]["n_epochs"] == 3
    assert configure(["--n_epochs", "2"])["trainer"]["n_epochs"] == 2
    assert configure([])["trainer"]["telemetry"] is None    # off by default
