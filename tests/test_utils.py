"""utils/: timing, profiler capture, rank-gated logging, progress."""

import os
import time

import jax
import jax.numpy as jnp
import pytest

from pytorch_ddp_mnist_tpu.utils import (Timer, CumulativeTimer, trace,
                                         device_sync, rank_zero_log, progress)


def test_timer_measures_wall_time():
    with Timer("t") as t:
        time.sleep(0.05)
    assert t.seconds is not None and t.seconds >= 0.05


def test_timer_sync_blocks_on_device_work():
    x = jnp.ones((256, 256))
    with Timer("matmul") as t:
        out = t.sync(jax.jit(lambda a: a @ a)(x))
    assert t.seconds is not None and t.seconds > 0
    assert out.shape == (256, 256)


def test_timer_sync_defers_blocking_to_exit(monkeypatch):
    """Timer.sync registers (and forwards) a pytree without blocking; the
    one block_until_ready happens at EXIT, on exactly that tree — the
    async-dispatch contract telemetry spans inherit. A fake pytree (never a
    device array) proves the timer itself does the draining."""
    calls = []
    monkeypatch.setattr(jax, "block_until_ready",
                        lambda t: calls.append(t) or t)
    fake_tree = {"loss": object()}
    with Timer("t") as t:
        assert t.sync(fake_tree) is fake_tree       # returned unchanged
        assert calls == []                          # no block at sync()
    assert calls == [fake_tree]                     # one drain, at exit
    assert t.seconds is not None and t.seconds >= 0


def test_timer_without_sync_never_blocks(monkeypatch):
    monkeypatch.setattr(
        jax, "block_until_ready",
        lambda t: (_ for _ in ()).throw(AssertionError("unexpected drain")))
    with Timer("t"):
        pass


def test_cumulative_timer_mean_count_arithmetic():
    """mean == total/count exactly, and the empty timer reads 0.0 (not a
    ZeroDivisionError) — the denominators telemetry's per-epoch aggregate
    spans divide by."""
    t = CumulativeTimer("x")
    assert t.count == 0 and t.total == 0.0 and t.mean == 0.0
    for _ in range(4):
        with t:
            pass
    assert t.count == 4
    assert t.mean == pytest.approx(t.total / 4, rel=0, abs=1e-15)


def test_cumulative_timer_accumulates():
    t = CumulativeTimer("io")
    for _ in range(3):
        with t:
            time.sleep(0.01)
    assert t.count == 3
    assert t.total >= 0.03
    assert abs(t.mean - t.total / 3) < 1e-12
    assert "io" in repr(t)


def test_timer_registry_bridge_publishes_histogram():
    """registry=: each completed Timer block lands in the unified
    `timer.{name}_s` histogram — the telemetry bridge that deprecates
    bespoke accumulate-then-print plumbing around .seconds."""
    from pytorch_ddp_mnist_tpu.telemetry import MetricsRegistry
    reg = MetricsRegistry()
    for _ in range(2):
        with Timer("step", registry=reg) as t:
            time.sleep(0.005)
    snap = reg.snapshot()["histograms"]["timer.step_s"]
    assert snap["n"] == 2
    assert snap["max"] >= 0.005
    assert t.seconds is not None                    # standalone path intact
    # no registry (the default): nothing registered anywhere
    with Timer("step") as t2:
        pass
    assert reg.snapshot()["histograms"]["timer.step_s"]["n"] == 2
    assert t2.seconds is not None


def test_cumulative_timer_registry_bridge_records_distribution():
    """CumulativeTimer's registry hook records each SECTION (n == count),
    giving percentiles where total/count could only ever report a mean."""
    from pytorch_ddp_mnist_tpu.telemetry import MetricsRegistry
    reg = MetricsRegistry()
    t = CumulativeTimer("io", registry=reg)
    for _ in range(3):
        with t:
            time.sleep(0.002)
    snap = reg.snapshot()["histograms"]["timer.io_s"]
    assert snap["n"] == t.count == 3
    assert snap["mean"] == pytest.approx(t.mean, rel=0.5)
    assert snap["p95"] > 0


def test_device_sync_accepts_tree_and_noarg():
    out = jax.jit(lambda a: a * 2)(jnp.ones(8))
    device_sync({"a": out})
    device_sync()  # all live arrays — must not raise


def test_trace_writes_profile(tmp_path):
    logdir = tmp_path / "prof"
    with trace(str(logdir)):
        jax.block_until_ready(jnp.ones((64, 64)) @ jnp.ones((64, 64)))
    # jax.profiler.trace writes plugins/profile/<run>/ with xplane protos
    found = [p for p, _, files in os.walk(logdir) for f in files]
    assert found, "trace produced no files"


def test_trace_none_is_noop(tmp_path):
    with trace(None):
        pass
    with trace(""):
        pass


def test_rank_zero_log_passes_through_single_process():
    lines = []
    log = rank_zero_log(lines.append)
    log("hello")
    assert lines == ["hello"]  # single-process == process 0


def test_progress_disabled_passthrough():
    assert list(progress(range(5), disable=True)) == list(range(5))


def test_progress_default_in_test_env():
    # stderr is not a tty under pytest -> plain iterator, still yields all
    assert list(progress([1, 2, 3])) == [1, 2, 3]


def test_progress_enabled_returns_live_loss_capable_bar():
    """With tqdm forced on, progress() must hand back the tqdm INSTANCE
    (set_postfix_str available — what train.loop._LiveLoss drives), not a
    bare iterator; iterating it still yields the items. Guards the
    integration the live-loss feature depends on."""
    pytest.importorskip("tqdm")
    bar = progress([1, 2, 3], desc="t", disable=False)
    assert hasattr(bar, "set_postfix_str")
    bar.set_postfix_str("loss=0.1@0")
    assert list(bar) == [1, 2, 3]
