"""Worker process for the multi-process DDP-comms parity test
(tests/test_mp_comm.py) — NOT collected by pytest (no test_ prefix).

The mp_worker.py shape (one jax.distributed process per rank, env wireup,
SPMD DP training over the cross-process mesh), parameterized by the
gradient-communication strategy: `--comm pmean|sharded|bf16|int8` selects
the parallel/collectives.py program inside make_dp_train_step (`--overlap`
adds the bucket-pipelined form; int8 threads its error-feedback residual
through the step, zero-seeded here). After
HPARAMS["steps"] steps every rank prints one JSON line (losses + checksum)
and, when `--save PATH` is given, rank 0 writes the final params to
PATH (.npz, one array per leaf in tree order) so the parent can compare
full parameter vectors across strategies — pmean-vs-pmean bitwise,
sharded-vs-pmean rtol 1e-6, bf16-vs-pmean bounded drift.
"""

import argparse
import json
import sys

# Single source of truth with the serial golden replay — same contract as
# tests/mp_worker.py (n / WORLD >= steps * local_batch).
HPARAMS = dict(n=1024, local_batch=32, steps=3, lr=0.05,
               data_seed=0, sampler_seed=42, param_seed=0, key_seed=1)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--comm", choices=("pmean", "sharded", "bf16", "int8"),
                   required=True)
    p.add_argument("--overlap", action="store_true",
                   help="bucket-pipelined collectives (overlap=True)")
    p.add_argument("--save", default=None,
                   help="rank 0: write final params here (.npz)")
    a = p.parse_args()

    import numpy as np
    import jax

    from pytorch_ddp_mnist_tpu.data import normalize_images, synthetic_mnist
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.parallel.ddp import (
        dp_mesh, global_batch_from_local, make_dp_train_step,
        replicate_state)
    from pytorch_ddp_mnist_tpu.parallel.sampler import ShardedSampler
    from pytorch_ddp_mnist_tpu.parallel.wireup import initialize_runtime

    n, local_batch, steps, lr = (HPARAMS["n"], HPARAMS["local_batch"],
                                 HPARAMS["steps"], HPARAMS["lr"])

    rt = initialize_runtime("env")
    assert jax.process_count() == rt.size, "rendezvous failed"
    mesh = dp_mesh()
    assert mesh.devices.size == rt.size

    split = synthetic_mnist(n, seed=HPARAMS["data_seed"])
    x_all = normalize_images(split.images)
    y_all = split.labels.astype(np.int32)
    sampler = ShardedSampler(n, num_replicas=rt.size, rank=rt.rank,
                             seed=HPARAMS["sampler_seed"])
    sampler.set_epoch(0)
    shard = sampler.indices()

    step = make_dp_train_step(mesh, lr=lr, comm=a.comm, overlap=a.overlap)
    params = replicate_state(mesh,
                             init_mlp(jax.random.key(HPARAMS["param_seed"])))
    key = replicate_state(mesh, jax.random.key(HPARAMS["key_seed"]))
    resid = (step.place_comm_state(None, params) if step.comm_state
             else None)

    losses = []
    for s in range(steps):
        rows = shard[s * local_batch:(s + 1) * local_batch]
        assert len(rows) == local_batch, \
            f"shard exhausted at step {s}: raise HPARAMS['n']"
        gx, gy = global_batch_from_local(mesh, (x_all[rows], y_all[rows]))
        if step.comm_state:
            params, key, loss, resid = step(params, key, gx, gy, resid)
        else:
            params, key, loss = step(params, key, gx, gy)
        losses.append(float(loss))

    # Params are replicated on every strategy's output (pmean by out_specs,
    # sharded/bf16 by the trailing all-gather/psum) — any rank can fetch.
    leaves = [np.asarray(leaf)
              for leaf in jax.tree_util.tree_leaves(params)]
    checksum = float(sum(np.abs(leaf).sum() for leaf in leaves))
    if a.save and rt.rank == 0:
        np.savez(a.save, **{f"leaf{i}": leaf
                            for i, leaf in enumerate(leaves)})
    rt.barrier()
    print(json.dumps({"rank": rt.rank, "size": rt.size, "comm": a.comm,
                      "losses": losses, "checksum": checksum}))
    sys.stdout.flush()
    rt.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
