"""CE loss parity vs torch.nn.CrossEntropyLoss (the reference's criterion,
ddp_tutorial_multi_gpu.py:76) and SGD step parity vs torch.optim.SGD."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_mnist_tpu.ops import cross_entropy, accuracy, sgd_step

torch = pytest.importorskip("torch")


def test_cross_entropy_matches_torch():
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(64, 10)).astype(np.float32) * 5
    labels = rng.integers(0, 10, size=64)
    ours = float(cross_entropy(jnp.asarray(logits), jnp.asarray(labels)))
    theirs = float(torch.nn.CrossEntropyLoss()(
        torch.tensor(logits), torch.tensor(labels)))
    assert abs(ours - theirs) < 1e-5


def test_cross_entropy_grad_matches_torch():
    rng = np.random.default_rng(1)
    logits = rng.normal(size=(8, 10)).astype(np.float32)
    labels = rng.integers(0, 10, size=8)
    g_ours = np.asarray(jax.grad(
        lambda l: cross_entropy(l, jnp.asarray(labels)))(jnp.asarray(logits)))
    t = torch.tensor(logits, requires_grad=True)
    torch.nn.CrossEntropyLoss()(t, torch.tensor(labels)).backward()
    np.testing.assert_allclose(g_ours, t.grad.numpy(), atol=1e-6)


def test_accuracy():
    logits = jnp.asarray([[1.0, 0.0], [0.0, 1.0], [3.0, 2.0], [0.1, 0.2]])
    labels = jnp.asarray([0, 1, 1, 1])
    assert float(accuracy(logits, labels)) == 0.75


def test_sgd_matches_torch():
    rng = np.random.default_rng(2)
    w = rng.normal(size=(16, 4)).astype(np.float32)
    g = rng.normal(size=(16, 4)).astype(np.float32)
    ours = np.asarray(sgd_step({"w": jnp.asarray(w)}, {"w": jnp.asarray(g)},
                               lr=0.01)["w"])
    tw = torch.tensor(w, requires_grad=True)
    opt = torch.optim.SGD([tw], lr=0.01)
    tw.grad = torch.tensor(g)
    opt.step()
    np.testing.assert_allclose(ours, tw.detach().numpy(), atol=1e-7)
