"""Fused Pallas train-step kernel vs the unfused XLA path.

Runs the kernel through the Pallas interpreter on the CPU mesh (conftest),
so every comparison here is exact-math parity with the jit'd reference
implementation — the same verification the TPU compile gets, minus Mosaic.
"""

import pytest
import numpy as np
import jax
import jax.numpy as jnp

from pytorch_ddp_mnist_tpu.models import init_mlp, mlp_apply
from pytorch_ddp_mnist_tpu.ops.loss import cross_entropy
from pytorch_ddp_mnist_tpu.ops.pallas_step import (
    fused_loss_and_grads, dropout_mask, make_pallas_train_step,
    make_pallas_dp_train_step, pad_fc3, PADDED_CLASSES)
from pytorch_ddp_mnist_tpu.train.loop import make_train_step
from pytorch_ddp_mnist_tpu.parallel.ddp import (make_dp_train_step,
                                                batch_sharding, replicated)
from pytorch_ddp_mnist_tpu.parallel.mesh import data_parallel_mesh


def _data(batch=32, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(batch, 784)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 10, batch).astype(np.int32))
    return x, y


def _tree_allclose(a, b, **kw):
    flat_a = jax.tree_util.tree_leaves(a)
    flat_b = jax.tree_util.tree_leaves(b)
    assert len(flat_a) == len(flat_b)
    for u, v in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(u), np.asarray(v), **kw)


def test_pad_fc3_shape_and_content():
    w3 = init_mlp(jax.random.key(0))["fc3"]["w"]
    p = pad_fc3(w3)
    assert p.shape == (128, PADDED_CLASSES)
    np.testing.assert_array_equal(np.asarray(p[:, :10]), np.asarray(w3))
    assert float(jnp.abs(p[:, 10:]).sum()) == 0.0


def test_fused_eval_matches_reference_loss_and_grads():
    params = init_mlp(jax.random.key(0))
    x, y = _data()
    ones = dropout_mask(jax.random.key(9), x.shape[0], train=False)

    def ref_loss(p):
        return cross_entropy(mlp_apply(p, x, train=False), y)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    loss, grads = fused_loss_and_grads(params, x, y, ones, interpret=True)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    _tree_allclose(grads, ref_g, rtol=2e-4, atol=1e-6)


def test_fused_train_matches_reference_with_same_mask():
    params = init_mlp(jax.random.key(1))
    x, y = _data(seed=3)
    sub = jax.random.key(42)
    mask = dropout_mask(sub, x.shape[0])

    def ref_loss(p):
        return cross_entropy(
            mlp_apply(p, x, train=True, dropout_key=sub), y)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    loss, grads = fused_loss_and_grads(params, x, y, mask, interpret=True)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    _tree_allclose(grads, ref_g, rtol=2e-4, atol=1e-6)


def test_pallas_step_matches_unfused_step_over_run():
    """Same key chain -> same dropout masks -> same training trajectory."""
    params_a = init_mlp(jax.random.key(0))
    params_b = jax.tree_util.tree_map(jnp.copy, params_a)
    key_a = jax.random.key(7)
    key_b = jax.random.key(7)
    step_ref = make_train_step(lr=0.01)
    step_pal = make_pallas_train_step(lr=0.01, interpret=True)
    for i in range(5):
        x, y = _data(seed=i)
        params_a, key_a, loss_a = step_ref(params_a, key_a, x, y)
        params_b, key_b, loss_b = step_pal(params_b, key_b, x, y)
        np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
    _tree_allclose(params_a, params_b, rtol=1e-4, atol=1e-6)


def test_pallas_dp_step_matches_unfused_dp_step():
    mesh = data_parallel_mesh()
    n = mesh.devices.size
    x, y = _data(batch=16 * n, seed=5)
    x = jax.device_put(x, batch_sharding(mesh))
    y = jax.device_put(y, batch_sharding(mesh))
    rep = replicated(mesh)
    params_a = jax.device_put(init_mlp(jax.random.key(2)), rep)
    params_b = jax.device_put(init_mlp(jax.random.key(2)), rep)
    key_a = jax.device_put(jax.random.key(3), rep)
    key_b = jax.device_put(jax.random.key(3), rep)
    step_ref = make_dp_train_step(mesh, lr=0.01)
    step_pal = make_pallas_dp_train_step(mesh, lr=0.01, interpret=True)
    for i in range(3):
        params_a, key_a, loss_a = step_ref(params_a, key_a, x, y)
        params_b, key_b, loss_b = step_pal(params_b, key_b, x, y)
        np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
    _tree_allclose(params_a, params_b, rtol=1e-4, atol=1e-6)


def test_fused_large_batch_grid_matches_reference():
    """B=1024 spans multiple grid blocks (MAX_BATCH_BLOCK=512): gradient
    accumulation across grid steps must match the unfused full-batch path
    (VERDICT r1 item 7)."""
    params = init_mlp(jax.random.key(0))
    x, y = _data(batch=1024, seed=6)
    sub = jax.random.key(21)
    mask = dropout_mask(sub, x.shape[0])

    def ref_loss(p):
        return cross_entropy(
            mlp_apply(p, x, train=True, dropout_key=sub), y)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    loss, grads = fused_loss_and_grads(params, x, y, mask, interpret=True)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    _tree_allclose(grads, ref_g, rtol=2e-4, atol=1e-6)


def test_fused_ragged_batch_tail_masked():
    """B=700 = 512 + 188: the padded tail rows of the second block must not
    leak into loss or grads."""
    params = init_mlp(jax.random.key(2))
    x, y = _data(batch=700, seed=7)
    ones = dropout_mask(jax.random.key(0), x.shape[0], train=False)

    def ref_loss(p):
        return cross_entropy(mlp_apply(p, x, train=False), y)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    loss, grads = fused_loss_and_grads(params, x, y, ones, interpret=True)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    _tree_allclose(grads, ref_g, rtol=2e-4, atol=1e-6)


def test_fused_tiny_batch_padded_to_sublane():
    """B=3 (under the 8-row f32 sublane) pads and masks correctly."""
    params = init_mlp(jax.random.key(5))
    x, y = _data(batch=3, seed=9)
    ones = dropout_mask(jax.random.key(0), x.shape[0], train=False)

    def ref_loss(p):
        return cross_entropy(mlp_apply(p, x, train=False), y)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    loss, grads = fused_loss_and_grads(params, x, y, ones, interpret=True)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    _tree_allclose(grads, ref_g, rtol=2e-4, atol=1e-6)


def test_fused_loss_decreases_when_training():
    params = init_mlp(jax.random.key(4))
    step = make_pallas_train_step(lr=0.05, interpret=True)
    key = jax.random.key(11)
    x, y = _data(batch=64, seed=8)
    first = last = None
    for _ in range(100):
        params, key, loss = step(params, key, x, y)
        first = float(loss) if first is None else first
        last = float(loss)
    assert last < first * 0.5, (first, last)


tpu_only = pytest.mark.skipif(
    jax.default_backend() not in ("tpu", "axon"),
    reason="pallas_rng draws bits with the TPU core PRNG (no interpreter "
           "lowering); Mosaic only")


@tpu_only
def test_pallas_rng_deterministic_per_seed():
    """In-kernel dropout: same seed -> bitwise-identical loss/grads;
    different seed -> different mask, different loss."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import fused_loss_and_grads_rng
    params = init_mlp(jax.random.key(0))
    x, y = _data(128)
    l1, g1 = fused_loss_and_grads_rng(params, x, y, 7)
    l2, g2 = fused_loss_and_grads_rng(params, x, y, 7)
    assert float(l1) == float(l2)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    l3, _ = fused_loss_and_grads_rng(params, x, y, 8)
    assert float(l3) != float(l1)


@tpu_only
def test_pallas_rng_matches_mask_kernel_in_distribution():
    """The in-kernel Bernoulli stream must be the same DISTRIBUTION as the
    mask-input kernel's bernoulli stream: mean loss over seeds within a few
    percent (the observed gap on hardware is <0.5%)."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import (
        dropout_mask, fused_loss_and_grads, fused_loss_and_grads_rng)
    params = init_mlp(jax.random.key(1))
    x, y = _data(512)
    n = 8
    mask_losses = [float(fused_loss_and_grads(
        params, x, y, dropout_mask(jax.random.key(100 + i), 512))[0])
        for i in range(n)]
    rng_losses = [float(fused_loss_and_grads_rng(params, x, y, 200 + i)[0])
                  for i in range(n)]
    m, r = np.mean(mask_losses), np.mean(rng_losses)
    assert abs(m - r) / m < 0.05, (m, r)


@tpu_only
def test_scan_pallas_rng_trains():
    """kernel='pallas_rng' through the epoch-scanned trainer: loss falls."""
    from pytorch_ddp_mnist_tpu.train.scan import make_run_fn
    from pytorch_ddp_mnist_tpu.data import synthetic_mnist, normalize_images
    split = synthetic_mnist(1024, seed=5)
    x_all = normalize_images(split.images)
    y_all = split.labels.astype(np.int32)
    idxs = np.arange(1024, dtype=np.int32).reshape(1, 8, 128)
    run = make_run_fn(lr=0.1, kernel="pallas_rng")
    params, key = init_mlp(jax.random.key(0)), jax.random.key(1)
    _, _, losses = run(params, key, jnp.asarray(x_all), jnp.asarray(y_all),
                       jnp.asarray(np.concatenate([idxs] * 4)))
    losses = np.asarray(losses).ravel()
    assert np.isfinite(losses).all() and losses[-1] < losses[0] * 0.7


def test_pallas_rng_rejected_on_interpreter():
    """Off-TPU the scan layer must reject pallas_rng with a named error."""
    from pytorch_ddp_mnist_tpu.train.scan import _loss_and_grads
    params = init_mlp(jax.random.key(0))
    x, y = _data(16)
    with pytest.raises(ValueError, match="pallas_rng"):
        _loss_and_grads(params, jnp.asarray(x), jnp.asarray(y),
                        jax.random.key(0), "pallas_rng", True)


@tpu_only
def test_epoch_kernel_trains_and_matches_per_step_kernel():
    """pallas_epoch (whole epoch, VMEM-resident weights, in-kernel SGD) must
    track the per-step pallas kernel's curve within dropout-stream noise."""
    from pytorch_ddp_mnist_tpu.train.scan import make_run_fn
    from pytorch_ddp_mnist_tpu.data import synthetic_mnist, normalize_images

    split = synthetic_mnist(4096, seed=3)
    x_all = jnp.asarray(normalize_images(split.images))
    y_all = jnp.asarray(split.labels.astype(np.int32))
    idxs = jnp.asarray(
        np.arange(4096, dtype=np.int32).reshape(1, 32, 128).repeat(3, 0))

    means = {}
    for kern in ("pallas", "pallas_epoch"):
        run = make_run_fn(lr=0.01, kernel=kern)
        _, _, losses = run(init_mlp(jax.random.key(0)), jax.random.key(1),
                           x_all, y_all, idxs)
        losses = np.asarray(losses)
        assert np.isfinite(losses).all()
        means[kern] = losses.mean(axis=1)
    a, b = means["pallas"], means["pallas_epoch"]
    assert b[-1] < b[0] * 0.7          # it actually trains
    np.testing.assert_allclose(a, b, rtol=0.15)  # same curve, other stream


@tpu_only
def test_epoch_kernel_deterministic_per_seed():
    from pytorch_ddp_mnist_tpu.ops.pallas_step import epoch_fused_sgd
    params = init_mlp(jax.random.key(0))
    x, y = _data(256)
    p1, l1 = epoch_fused_sgd(params, x, y, 5, 0.01, 128)
    p2, l2 = epoch_fused_sgd(params, x, y, 5, 0.01, 128)
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    for u, v in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_array_equal(np.asarray(u), np.asarray(v))
    assert l1.shape == (2,)  # 256 rows / batch 128 -> 2 per-step losses


def test_epoch_kernel_rejects_unaligned_batch():
    from pytorch_ddp_mnist_tpu.ops.pallas_step import epoch_fused_sgd
    params = init_mlp(jax.random.key(0))
    x, y = _data(200)
    with pytest.raises(ValueError, match="divisible by 8"):
        epoch_fused_sgd(params, x, y, 1, 0.01, 100)


def test_epoch_kernel_rejected_by_dp_and_interpreter():
    """make_dp_run_fn must refuse pallas_epoch (no per-step allreduce), and
    the serial path must refuse it off-TPU."""
    from pytorch_ddp_mnist_tpu.train.scan import make_dp_run_fn, make_run_fn
    mesh = data_parallel_mesh()
    with pytest.raises(ValueError, match="allreduce"):
        make_dp_run_fn(mesh, lr=0.01, kernel="pallas_epoch")
    with pytest.raises(ValueError, match="pallas_epoch"):
        make_run_fn(lr=0.01, kernel="pallas_epoch", interpret=True)
