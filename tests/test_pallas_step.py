"""Fused Pallas train-step kernel vs the unfused XLA path.

Runs the kernel through the Pallas interpreter on the CPU mesh (conftest),
so every comparison here is exact-math parity with the jit'd reference
implementation — the same verification the TPU compile gets, minus Mosaic.
"""

import os
import sys

import pytest
import numpy as np
import jax
import jax.numpy as jnp

from pytorch_ddp_mnist_tpu.models import init_mlp, mlp_apply
from pytorch_ddp_mnist_tpu.ops.loss import cross_entropy
from pytorch_ddp_mnist_tpu.ops.pallas_step import (
    fused_loss_and_grads, dropout_mask, make_pallas_train_step,
    make_pallas_dp_train_step, pad_fc3, PADDED_CLASSES)
from pytorch_ddp_mnist_tpu.train.loop import make_train_step
from pytorch_ddp_mnist_tpu.parallel.ddp import (make_dp_train_step,
                                                batch_sharding, replicated)
from pytorch_ddp_mnist_tpu.parallel.mesh import data_parallel_mesh


def _data(batch=32, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(batch, 784)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 10, batch).astype(np.int32))
    return x, y


def _tree_allclose(a, b, **kw):
    flat_a = jax.tree_util.tree_leaves(a)
    flat_b = jax.tree_util.tree_leaves(b)
    assert len(flat_a) == len(flat_b)
    for u, v in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(u), np.asarray(v), **kw)


def test_pad_fc3_shape_and_content():
    w3 = init_mlp(jax.random.key(0))["fc3"]["w"]
    p = pad_fc3(w3)
    assert p.shape == (128, PADDED_CLASSES)
    np.testing.assert_array_equal(np.asarray(p[:, :10]), np.asarray(w3))
    assert float(jnp.abs(p[:, 10:]).sum()) == 0.0


def test_fused_eval_matches_reference_loss_and_grads():
    params = init_mlp(jax.random.key(0))
    x, y = _data()
    ones = dropout_mask(jax.random.key(9), x.shape[0], train=False)

    def ref_loss(p):
        return cross_entropy(mlp_apply(p, x, train=False), y)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    loss, grads = fused_loss_and_grads(params, x, y, ones, interpret=True)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    _tree_allclose(grads, ref_g, rtol=2e-4, atol=1e-6)


def test_fused_train_matches_reference_with_same_mask():
    params = init_mlp(jax.random.key(1))
    x, y = _data(seed=3)
    sub = jax.random.key(42)
    mask = dropout_mask(sub, x.shape[0])

    def ref_loss(p):
        return cross_entropy(
            mlp_apply(p, x, train=True, dropout_key=sub), y)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    loss, grads = fused_loss_and_grads(params, x, y, mask, interpret=True)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    _tree_allclose(grads, ref_g, rtol=2e-4, atol=1e-6)


def test_pallas_step_matches_unfused_step_over_run():
    """Same key chain -> same dropout masks -> same training trajectory."""
    params_a = init_mlp(jax.random.key(0))
    params_b = jax.tree_util.tree_map(jnp.copy, params_a)
    key_a = jax.random.key(7)
    key_b = jax.random.key(7)
    step_ref = make_train_step(lr=0.01)
    step_pal = make_pallas_train_step(lr=0.01, interpret=True)
    for i in range(5):
        x, y = _data(seed=i)
        params_a, key_a, loss_a = step_ref(params_a, key_a, x, y)
        params_b, key_b, loss_b = step_pal(params_b, key_b, x, y)
        np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
    _tree_allclose(params_a, params_b, rtol=1e-4, atol=1e-6)


def test_pallas_dp_step_matches_unfused_dp_step():
    mesh = data_parallel_mesh()
    n = mesh.devices.size
    x, y = _data(batch=16 * n, seed=5)
    x = jax.device_put(x, batch_sharding(mesh))
    y = jax.device_put(y, batch_sharding(mesh))
    rep = replicated(mesh)
    params_a = jax.device_put(init_mlp(jax.random.key(2)), rep)
    params_b = jax.device_put(init_mlp(jax.random.key(2)), rep)
    key_a = jax.device_put(jax.random.key(3), rep)
    key_b = jax.device_put(jax.random.key(3), rep)
    step_ref = make_dp_train_step(mesh, lr=0.01)
    step_pal = make_pallas_dp_train_step(mesh, lr=0.01, interpret=True)
    for i in range(3):
        params_a, key_a, loss_a = step_ref(params_a, key_a, x, y)
        params_b, key_b, loss_b = step_pal(params_b, key_b, x, y)
        np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
    _tree_allclose(params_a, params_b, rtol=1e-4, atol=1e-6)


def test_fused_large_batch_grid_matches_reference():
    """B=1024 spans multiple grid blocks (MAX_BATCH_BLOCK=512): gradient
    accumulation across grid steps must match the unfused full-batch path
    (VERDICT r1 item 7)."""
    params = init_mlp(jax.random.key(0))
    x, y = _data(batch=1024, seed=6)
    sub = jax.random.key(21)
    mask = dropout_mask(sub, x.shape[0])

    def ref_loss(p):
        return cross_entropy(
            mlp_apply(p, x, train=True, dropout_key=sub), y)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    loss, grads = fused_loss_and_grads(params, x, y, mask, interpret=True)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    _tree_allclose(grads, ref_g, rtol=2e-4, atol=1e-6)


def test_fused_ragged_batch_tail_masked():
    """B=700 = 512 + 188: the padded tail rows of the second block must not
    leak into loss or grads."""
    params = init_mlp(jax.random.key(2))
    x, y = _data(batch=700, seed=7)
    ones = dropout_mask(jax.random.key(0), x.shape[0], train=False)

    def ref_loss(p):
        return cross_entropy(mlp_apply(p, x, train=False), y)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    loss, grads = fused_loss_and_grads(params, x, y, ones, interpret=True)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    _tree_allclose(grads, ref_g, rtol=2e-4, atol=1e-6)


def test_fused_tiny_batch_padded_to_sublane():
    """B=3 (under the 8-row f32 sublane) pads and masks correctly."""
    params = init_mlp(jax.random.key(5))
    x, y = _data(batch=3, seed=9)
    ones = dropout_mask(jax.random.key(0), x.shape[0], train=False)

    def ref_loss(p):
        return cross_entropy(mlp_apply(p, x, train=False), y)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    loss, grads = fused_loss_and_grads(params, x, y, ones, interpret=True)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    _tree_allclose(grads, ref_g, rtol=2e-4, atol=1e-6)


def test_fused_loss_decreases_when_training():
    params = init_mlp(jax.random.key(4))
    step = make_pallas_train_step(lr=0.05, interpret=True)
    key = jax.random.key(11)
    x, y = _data(batch=64, seed=8)
    first = last = None
    for _ in range(100):
        params, key, loss = step(params, key, x, y)
        first = float(loss) if first is None else first
        last = float(loss)
    assert last < first * 0.5, (first, last)


if os.environ.get("PDMT_TPU_TESTS") == "1":
    # Hardware mode: the tpu_only marker below queries the backend at
    # COLLECTION time — before the per-test watchdog (conftest) arms — and
    # a downed tunnel can HANG that first query (wireup.py's hang-mode
    # notes), silently burning the whole hardware window. Probe bounded
    # first and skip the module by name instead.
    from pytorch_ddp_mnist_tpu.parallel.wireup import (
        _honor_platform_env, _probe_devices_bounded, env_seconds)
    _honor_platform_env()   # an explicit JAX_PLATFORMS (e.g. cpu) wins
    _status, _ = _probe_devices_bounded(env_seconds("PDMT_HANG_TIMEOUT",
                                                    75.0))
    if _status != "ok":
        pytest.skip(f"PDMT_TPU_TESTS=1 but the backend probe returned "
                    f"{_status!r} (tunnel outage?) — skipping the Mosaic "
                    f"module instead of hanging collection",
                    allow_module_level=True)

tpu_only = pytest.mark.skipif(
    jax.default_backend() not in ("tpu", "axon"),
    reason="pallas_rng draws bits with the TPU core PRNG (no interpreter "
           "lowering); Mosaic only")

from jax.experimental.pallas import tpu as _pltpu_mod

# The TPU-semantics simulator (remote-DMA/semaphore modeling, core-PRNG,
# race detector) arrived after jax 0.4.x; on installs without it the
# simulator-executed tests are genuinely unrunnable — skip by name.
_HAS_TPU_SIM = hasattr(_pltpu_mod, "InterpretParams")
needs_tpu_sim = pytest.mark.skipif(
    not _HAS_TPU_SIM,
    reason="pltpu.InterpretParams (TPU-semantics simulator) not in this jax")


@tpu_only
def test_pallas_rng_deterministic_per_seed():
    """In-kernel dropout: same seed -> bitwise-identical loss/grads;
    different seed -> different mask, different loss."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import fused_loss_and_grads_rng
    params = init_mlp(jax.random.key(0))
    x, y = _data(128)
    l1, g1 = fused_loss_and_grads_rng(params, x, y, 7)
    l2, g2 = fused_loss_and_grads_rng(params, x, y, 7)
    assert float(l1) == float(l2)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    l3, _ = fused_loss_and_grads_rng(params, x, y, 8)
    assert float(l3) != float(l1)


@tpu_only
def test_pallas_rng_matches_mask_kernel_in_distribution():
    """The in-kernel Bernoulli stream must be the same DISTRIBUTION as the
    mask-input kernel's bernoulli stream: mean loss over seeds within a few
    percent (the observed gap on hardware is <0.5%)."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import (
        dropout_mask, fused_loss_and_grads, fused_loss_and_grads_rng)
    params = init_mlp(jax.random.key(1))
    x, y = _data(512)
    n = 8
    mask_losses = [float(fused_loss_and_grads(
        params, x, y, dropout_mask(jax.random.key(100 + i), 512))[0])
        for i in range(n)]
    rng_losses = [float(fused_loss_and_grads_rng(params, x, y, 200 + i)[0])
                  for i in range(n)]
    m, r = np.mean(mask_losses), np.mean(rng_losses)
    assert abs(m - r) / m < 0.05, (m, r)


@tpu_only
def test_scan_pallas_rng_trains():
    """kernel='pallas_rng' through the epoch-scanned trainer: loss falls."""
    from pytorch_ddp_mnist_tpu.train.scan import make_run_fn
    from pytorch_ddp_mnist_tpu.data import synthetic_mnist, normalize_images
    split = synthetic_mnist(1024, seed=5)
    x_all = normalize_images(split.images)
    y_all = split.labels.astype(np.int32)
    idxs = np.arange(1024, dtype=np.int32).reshape(1, 8, 128)
    run = make_run_fn(lr=0.1, kernel="pallas_rng")
    params, key = init_mlp(jax.random.key(0)), jax.random.key(1)
    _, _, losses = run(params, key, jnp.asarray(x_all), jnp.asarray(y_all),
                       jnp.asarray(np.concatenate([idxs] * 4)))
    losses = np.asarray(losses).ravel()
    assert np.isfinite(losses).all() and losses[-1] < losses[0] * 0.7


def test_pallas_rng_rejected_on_interpreter():
    """Off-TPU the scan layer must reject pallas_rng with a named error."""
    from pytorch_ddp_mnist_tpu.train.scan import _loss_and_grads
    params = init_mlp(jax.random.key(0))
    x, y = _data(16)
    with pytest.raises(ValueError, match="pallas_rng"):
        _loss_and_grads(params, jnp.asarray(x), jnp.asarray(y),
                        jax.random.key(0), "pallas_rng", True)


@tpu_only
def test_epoch_kernel_trains_and_matches_per_step_kernel():
    """pallas_epoch (whole epoch, VMEM-resident weights, in-kernel SGD) must
    track the per-step pallas kernel's curve within dropout-stream noise."""
    from pytorch_ddp_mnist_tpu.train.scan import make_run_fn
    from pytorch_ddp_mnist_tpu.data import synthetic_mnist, normalize_images

    split = synthetic_mnist(4096, seed=3)
    x_all = jnp.asarray(normalize_images(split.images))
    y_all = jnp.asarray(split.labels.astype(np.int32))
    idxs = jnp.asarray(
        np.arange(4096, dtype=np.int32).reshape(1, 32, 128).repeat(3, 0))

    means = {}
    for kern in ("pallas", "pallas_epoch"):
        run = make_run_fn(lr=0.01, kernel=kern)
        _, _, losses = run(init_mlp(jax.random.key(0)), jax.random.key(1),
                           x_all, y_all, idxs)
        losses = np.asarray(losses)
        assert np.isfinite(losses).all()
        means[kern] = losses.mean(axis=1)
    a, b = means["pallas"], means["pallas_epoch"]
    assert b[-1] < b[0] * 0.7          # it actually trains
    np.testing.assert_allclose(a, b, rtol=0.15)  # same curve, other stream


@tpu_only
def test_epoch_kernel_deterministic_per_seed():
    from pytorch_ddp_mnist_tpu.ops.pallas_step import epoch_fused_sgd
    params = init_mlp(jax.random.key(0))
    x, y = _data(256)
    p1, l1 = epoch_fused_sgd(params, x, y, 5, 0.01, 128)
    p2, l2 = epoch_fused_sgd(params, x, y, 5, 0.01, 128)
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    for u, v in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_array_equal(np.asarray(u), np.asarray(v))
    assert l1.shape == (2,)  # 256 rows / batch 128 -> 2 per-step losses


@tpu_only
def test_epoch_kernel_dp_wrapper_matches_serial_on_hardware():
    """make_dp_run_fn(kernel='pallas_epoch') on the real chip's 1-device
    mesh: Mosaic-compiles the shard_map-wrapped epoch kernel (the DP entry
    path; ring degenerate) and must equal the serial run exactly — same
    seed words, same kernel."""
    from pytorch_ddp_mnist_tpu.parallel.mesh import make_mesh
    from pytorch_ddp_mnist_tpu.train.scan import make_dp_run_fn, make_run_fn
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.integers(0, 256, (512, 784), dtype=np.uint8))
    y = jnp.asarray(rng.integers(0, 10, 512).astype(np.int32))
    idxs = jnp.asarray(np.stack([
        np.random.default_rng(e).permutation(512).reshape(4, 128)
        for e in range(3)]).astype(np.int32))
    mesh1 = make_mesh([1], ["dp"], jax.devices()[:1])

    def fresh():
        return (init_mlp(jax.random.key(0)), jax.random.key(3))

    p_dp, _, l_dp = make_dp_run_fn(mesh1, lr=0.01,
                                   kernel="pallas_epoch")(*fresh(), x, y,
                                                          idxs)
    p_s, _, l_s = make_run_fn(lr=0.01, kernel="pallas_epoch")(*fresh(), x,
                                                              y, idxs)
    np.testing.assert_array_equal(np.asarray(l_dp), np.asarray(l_s))
    _tree_allclose(p_dp, p_s, rtol=0, atol=0)


@tpu_only
def test_epoch_kernel_bf16_trains_on_hardware():
    """The bf16-matmul epoch kernel (in-kernel RNG, uint8 streaming) on the
    real chip: trains to a falling, finite curve that tracks the f32 kernel
    within bf16 noise."""
    from pytorch_ddp_mnist_tpu.train.scan import make_run_fn
    rng = np.random.default_rng(8)
    x = jnp.asarray(rng.integers(0, 256, (2048, 784), dtype=np.uint8))
    yl = jnp.asarray((rng.integers(0, 256, 2048) % 10).astype(np.int32))
    idxs = jnp.asarray(np.stack([
        np.random.default_rng(e).permutation(2048).reshape(16, 128)
        for e in range(4)]).astype(np.int32))
    curves = {}
    for dt in ("float32", "bfloat16"):
        run = make_run_fn(lr=0.05, kernel="pallas_epoch", dtype=dt)
        _, _, losses = run(init_mlp(jax.random.key(0)), jax.random.key(1),
                           x, yl, idxs)
        losses = np.asarray(losses)
        assert np.isfinite(losses).all()
        curves[dt] = losses.mean(axis=1)
    assert curves["bfloat16"][-1] < curves["bfloat16"][0]
    np.testing.assert_allclose(curves["bfloat16"], curves["float32"],
                               rtol=0.1)


@tpu_only
def test_epoch_kernel_uint8_matches_f32_on_hardware():
    """The uint8-streaming epoch kernel (in-kernel VPU normalize) must match
    the pre-normalized f32 path: same seed -> same in-kernel dropout masks,
    and the int32-widened normalize is exact for 0..255 — observed bitwise
    equal on hardware."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import epoch_fused_sgd
    from pytorch_ddp_mnist_tpu.data.mnist import MNIST_MEAN, MNIST_STD
    rng = np.random.default_rng(2)
    x_u8 = rng.integers(0, 256, (512, 784), dtype=np.uint8)
    y = jnp.asarray(rng.integers(0, 10, 512).astype(np.int32))
    params = init_mlp(jax.random.key(0))
    pu, lu = epoch_fused_sgd(params, jnp.asarray(x_u8), y, 11, 0.01, 128)
    xf = (x_u8.astype(np.float32) / np.float32(255.0)
          - np.float32(MNIST_MEAN)) / np.float32(MNIST_STD)
    pf, lf = epoch_fused_sgd(params, jnp.asarray(xf), y, 11, 0.01, 128)
    np.testing.assert_allclose(np.asarray(lu), np.asarray(lf),
                               rtol=1e-6, atol=1e-7)
    _tree_allclose(pu, pf, rtol=1e-6, atol=1e-7)


def test_epoch_kernel_rejects_unaligned_batch():
    from pytorch_ddp_mnist_tpu.ops.pallas_step import epoch_fused_sgd
    params = init_mlp(jax.random.key(0))
    x, y = _data(200)
    with pytest.raises(ValueError, match="divisible by 8"):
        epoch_fused_sgd(params, x, y, 1, 0.01, 100)


def test_epoch_kernel_batch_cap_applies_to_all_input_dtypes():
    """The one-VMEM-block batch cap binds uint8 epochs too (the normalize
    materializes the block as f32 in VMEM, so the activation budget is the
    same as the f32 path's — a larger uint8-only cap would need hardware
    validation first)."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import (
        EPOCH_KERNEL_MAX_BATCH, epoch_fused_sgd)
    params = init_mlp(jax.random.key(0))
    b = EPOCH_KERNEL_MAX_BATCH + 8
    for uint8 in (False, True):
        x, y = _epoch_data(1, b, seed=0, uint8=uint8)
        with pytest.raises(ValueError, match=str(EPOCH_KERNEL_MAX_BATCH)):
            epoch_fused_sgd(params, x, y, 1, 0.01, b)


def test_epoch_kernel_threefry_step_cap():
    """rng_impl='threefry' rides the whole per-step key table SMEM-resident;
    a step count past EPOCH_KERNEL_MAX_RNG_STEPS must fail with the named
    budget ValueError (ADVICE r5 #1), not an opaque Mosaic lowering error —
    mirroring the other resource-budget guards."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import (
        EPOCH_KERNEL_MAX_RNG_STEPS, epoch_fused_sgd)
    params = init_mlp(jax.random.key(0))
    nsteps, batch = EPOCH_KERNEL_MAX_RNG_STEPS + 8, 8
    x, y = _epoch_data(nsteps, batch, uint8=True)  # uint8: 4x lighter rows
    keys = jnp.zeros((nsteps, 2), jnp.int32)
    with pytest.raises(ValueError, match="SMEM key-table budget"):
        epoch_fused_sgd(params, x, y, keys, 0.01, batch,
                        rng_impl="threefry", interpret=True)
    # at the cap the guard stays quiet (the shape checks run next) — the
    # bound itself must not reject the budget it protects
    n_ok = EPOCH_KERNEL_MAX_RNG_STEPS
    x, y = _epoch_data(n_ok, 8, uint8=True)
    params2, losses = epoch_fused_sgd(params, x, y,
                                      jnp.zeros((n_ok, 2), jnp.int32),
                                      0.0, 8, rng_impl="threefry",
                                      interpret=True, valid_steps=1)
    assert losses.shape == (1,)


def _needs_devices(n):
    """Skip on device pools smaller than the CPU-mesh CI shape: hardware
    mode (PDMT_TPU_TESTS=1) runs this file against the real chip count
    (typically 1), where multi-device named-error/trace assertions about
    the virtual 8-device mesh cannot hold. Evaluated after the module-level
    backend probe, so the device query cannot hang."""
    import jax as _jax
    return pytest.mark.skipif(
        _jax.device_count() < n,
        reason=f"needs a {n}-device pool (CPU-mesh CI shape)")


@_needs_devices(2)
def test_epoch_kernel_dp_interpret_rejected_on_multidevice_mesh():
    """interpret=True with the multi-device ring (remote DMAs have no
    interpreter lowering) fails by name — needs a >=2-device mesh; the
    1-device degenerate legitimately interprets."""
    from pytorch_ddp_mnist_tpu.train.scan import make_dp_run_fn
    mesh = data_parallel_mesh()
    with pytest.raises(ValueError, match="interpreter"):
        make_dp_run_fn(mesh, lr=0.01, kernel="pallas_epoch", interpret=True)


def test_epoch_kernel_dp_named_errors():
    """The DP epoch kernel's constraint surface: no unroll, ring strategy
    validation, axis plumbing — all device-count-independent (the
    mesh-dependent interpret rejection has its own guarded test)."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import (
        EPOCH_KERNEL_MAX_DEVICES, epoch_fused_sgd)
    from pytorch_ddp_mnist_tpu.train.scan import make_dp_run_fn, make_run_fn
    mesh = data_parallel_mesh()
    with pytest.raises(ValueError, match="unroll"):
        make_dp_run_fn(mesh, lr=0.01, kernel="pallas_epoch", unroll=2)
    with pytest.raises(ValueError, match="unroll"):
        make_run_fn(lr=0.01, kernel="pallas_epoch", unroll=4)
    params = init_mlp(jax.random.key(0))
    x, y = _data(16)
    # past the all-gather slot budget, ring='auto' switches to the
    # reduce-scatter ring instead of raising; forcing 'allgather' there is
    # the named error
    with pytest.raises(ValueError, match="reduce_scatter"):
        epoch_fused_sgd(params, x, y, 1, 0.01, 16, axis_name="dp",
                        axis_size=EPOCH_KERNEL_MAX_DEVICES + 1,
                        ring="allgather")
    with pytest.raises(ValueError, match="ring"):
        epoch_fused_sgd(params, x, y, 1, 0.01, 16, axis_name="dp",
                        axis_size=2, ring="tree")
    with pytest.raises(ValueError, match="axis_name"):
        epoch_fused_sgd(params, x, y, 1, 0.01, 16, axis_size=2)
    # forcing a strategy on the serial (no-ring) kernel is the same silent
    # no-op hazard — rejected by name at the op level too
    with pytest.raises(ValueError, match="serial"):
        epoch_fused_sgd(params, x, y, 1, 0.01, 16, ring="reduce_scatter")
    # the API-level guard: forcing a ring strategy anywhere it would be a
    # silent no-op (wrong kernel, or a 1-device mesh whose ring degenerates
    # away) is rejected by name, not ignored
    with pytest.raises(ValueError, match="pallas_epoch"):
        make_dp_run_fn(mesh, lr=0.01, kernel="xla", ring="reduce_scatter")
    from pytorch_ddp_mnist_tpu.parallel.mesh import make_mesh
    mesh1 = make_mesh([1], ["dp"], jax.devices()[:1])
    with pytest.raises(ValueError, match="multi-device"):
        make_dp_run_fn(mesh1, lr=0.01, kernel="pallas_epoch",
                       ring="allgather")


@pytest.mark.parametrize("n", [2, 3, 4, 8])
def test_epoch_kernel_ring_slot_schedule_algebra(n):
    """Pure simulation of the DP ring's slot schedule — the exact index
    formulas the kernel uses (hop h: device me forwards slot (me-h) mod n to
    its right neighbor, same origin-slot index on the receiver). The
    multi-chip ring cannot execute in this 1-chip session, so the protocol
    algebra is pinned here instead: every device ends holding all n origin
    slots, each (device, slot) is written exactly once per step (no reuse
    hazard), and each hop forwards exactly what arrived the hop before."""
    held = {d: {d} for d in range(n)}          # slots present per device
    writes = {d: [] for d in range(n)}         # remote writes received
    for h in range(n - 1):
        sends = {}
        for me in range(n):
            send_slot = (me - h) % n
            # the kernel forwards only data it already holds: own slot at
            # hop 0, afterwards the slot received at hop h-1
            assert send_slot in held[me], (h, me, send_slot)
            if h > 0:
                assert send_slot == (me - (h - 1) - 1) % n  # prev hop's recv
            sends[(me + 1) % n] = send_slot
        for dst, slot in sends.items():
            assert slot not in held[dst], "slot delivered twice"
            writes[dst].append(slot)
            held[dst].add(slot)
    for d in range(n):
        assert held[d] == set(range(n))        # all-gather complete
        assert len(writes[d]) == len(set(writes[d])) == n - 1  # 1 write/slot


@pytest.mark.parametrize("n", [2, 3, 9, 16])
def test_epoch_kernel_rs_ring_schedule_algebra(n):
    """Pure simulation of the reduce-scatter + all-gather ring's schedule —
    the exact index formulas of _make_epoch_kernel's ring_rs branch (RS hop
    h: send partial chunk (me-h) right, fold arriving chunk (me-h-1); AG hop
    a: forward reduced chunk (me+1-a) right, into the same position). Pinned
    here because the multi-chip ring cannot execute in a 1-chip session:
    every fold matches what the left neighbor just sent, each per-hop recv
    slot and each AG position is written exactly once per step, a device
    only ever forwards a reduced chunk it already holds, and the final
    buffer is byte-identical on every device (the lockstep-weights
    invariant) and equals the mean."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import (
        EPOCH_COMM_ROWS, _rs_chunk_rows)
    C = _rs_chunk_rows(n)
    assert C % 8 == 0 and n * C >= EPOCH_COMM_ROWS
    rng = np.random.default_rng(n)
    grads = rng.normal(size=(n, n * C)).astype(np.float32)
    grads[:, EPOCH_COMM_ROWS:] = 0.0           # pack zeroes the tail
    acc = grads.copy()                          # per-device comm buffer
    # phase 1 — reduce-scatter (hop-synchronous sim; snapshot the sends so
    # simulation order can't leak a neighbor's same-hop fold)
    for h in range(n - 1):
        sent = {}
        for me in range(n):
            send_c = (me - h) % n
            if h > 0:   # forwards exactly the chunk folded the hop before
                assert send_c == (me - (h - 1) - 1) % n
            sent[(me + 1) % n] = acc[me, send_c * C:(send_c + 1) * C].copy()
        for me in range(n):
            add_c = (me - h - 1) % n
            # the arriving chunk IS the one my left neighbor just sent
            assert add_c == ((me - 1) % n - h) % n
            # kernel folds local + incoming, in that order
            acc[me, add_c * C:(add_c + 1) * C] = (
                acc[me, add_c * C:(add_c + 1) * C] + sent[me])
    # each device owns the fully reduced chunk (me+1) mod n: bitwise equal
    # to the single sequential chain starting at the chunk's origin device
    for me in range(n):
        c = (me + 1) % n
        chain = grads[c, c * C:(c + 1) * C]
        for k in range(1, n):
            chain = grads[(c + k) % n, c * C:(c + 1) * C] + chain
        np.testing.assert_array_equal(acc[me, c * C:(c + 1) * C], chain)
    # phase 2 — all-gather of reduced chunks
    final = {me: {(me + 1) % n} for me in range(n)}
    for a in range(n - 1):
        sent = {}
        for me in range(n):
            send_c = (me + 1 - a) % n
            assert send_c in final[me], "forwarded a non-final chunk"
            sent[(me + 1) % n] = (
                send_c, acc[me, send_c * C:(send_c + 1) * C].copy())
        for me in range(n):
            c, val = sent[me]
            assert c == (me - a) % n
            assert c not in final[me], "AG position written twice"
            final[me].add(c)
            acc[me, c * C:(c + 1) * C] = val
    for me in range(n):
        assert final[me] == set(range(n))       # every chunk delivered
        np.testing.assert_array_equal(acc[me], acc[0])   # lockstep bytes
    np.testing.assert_allclose(acc[0][:EPOCH_COMM_ROWS] / n,
                               grads.mean(0)[:EPOCH_COMM_ROWS],
                               rtol=1e-5, atol=1e-6)


def test_epoch_kernel_dp_16dev_rs_program_traces():
    """Past EPOCH_KERNEL_MAX_DEVICES the DP epoch program resolves to the
    reduce-scatter ring (ring='auto') and must still trace cleanly — shapes,
    shard_map specs, the chunked-ring scratch structure. 16 virtual devices
    need their own XLA client, so the trace runs in a subprocess."""
    import subprocess
    import sys
    script = (
        "import jax, jax.numpy as jnp\n"
        # honor JAX_PLATFORMS=cpu BEFORE the first backend query: the
        # session's pre-registered tunneled-TPU backend can hang a bare
        # jax.devices() when the tunnel is down (wireup.py hang-mode notes)
        "from pytorch_ddp_mnist_tpu.parallel.wireup import "
        "_honor_platform_env\n"
        "_honor_platform_env()\n"
        "from pytorch_ddp_mnist_tpu.parallel.mesh import make_mesh\n"
        "from pytorch_ddp_mnist_tpu.train.scan import make_dp_run_fn\n"
        "from pytorch_ddp_mnist_tpu.models import init_mlp\n"
        "n = 16\n"
        "mesh = make_mesh([n], ['dp'], jax.devices()[:n])\n"
        "run = make_dp_run_fn(mesh, lr=0.01, kernel='pallas_epoch')\n"
        "params = init_mlp(jax.random.key(0))\n"
        "b = 16 * n\n"
        "out = jax.eval_shape(run, params, jax.random.key(1),\n"
        "    jax.ShapeDtypeStruct((2 * b, 784), jnp.uint8),\n"
        "    jax.ShapeDtypeStruct((2 * b,), jnp.int32),\n"
        "    jax.ShapeDtypeStruct((1, 2, b), jnp.int32))\n"
        "assert out[2].shape == (1, 2), out[2].shape\n"
        "print('TRACED-OK')\n")
    env = dict(os.environ,
               XLA_FLAGS="--xla_force_host_platform_device_count=16",
               JAX_PLATFORMS="cpu")
    env.pop("PDMT_TPU_TESTS", None)
    out = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=300,
                         cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr[-2000:]
    assert "TRACED-OK" in out.stdout


@_needs_devices(8)
def test_epoch_kernel_dp_8dev_program_traces():
    """The 8-replica DP epoch program (in-kernel ring, remote DMAs,
    semaphore scratch) must TRACE cleanly — shapes, shard_map specs, scratch
    structure — even though executing the ring needs real multi-chip
    hardware. Catches structural regressions the 1-device tests can't."""
    from pytorch_ddp_mnist_tpu.train.scan import make_dp_run_fn
    # pin EXACTLY 8 devices: on a larger pool data_parallel_mesh() would
    # change the traced program (ring='auto' flips to reduce_scatter past
    # 8 replicas) and break the hard-coded 1024-row batch split
    from pytorch_ddp_mnist_tpu.parallel.mesh import make_mesh
    mesh = make_mesh([8], ["dp"], jax.devices()[:8])
    run = make_dp_run_fn(mesh, lr=0.01, kernel="pallas_epoch",
                         snapshots=True)
    params = init_mlp(jax.random.key(0))
    key = jax.random.key(1)
    x = jax.ShapeDtypeStruct((1024, 784), jnp.uint8)
    y = jax.ShapeDtypeStruct((1024,), jnp.int32)
    idxs = jax.ShapeDtypeStruct((2, 1, 1024), jnp.int32)  # 128 rows/replica
    out = jax.eval_shape(run, params, key, x, y, idxs)
    assert out[2].shape == (2, 1)                    # (epochs, steps) losses
    assert out[3][0]["fc1"]["w"].shape == (2, 784, 128)   # params snapshots
    # Forcing the reduce-scatter strategy on the same 8-device mesh (auto
    # would pick allgather here) must trace the RS scratch structure too.
    run_rs = make_dp_run_fn(mesh, lr=0.01, kernel="pallas_epoch",
                            ring="reduce_scatter")
    out = jax.eval_shape(run_rs, params, key, x, y, idxs)
    assert out[2].shape == (2, 1)


def test_epoch_kernel_dp_single_device_mesh_matches_serial_interpret():
    """kernel='pallas_epoch' through make_dp_run_fn on a 1-device mesh (the
    ring degenerates away) must reproduce the serial run_epochal bit-for-bit
    on the interpreter — pins the shard_map wrapper's gather/pmean/key
    plumbing for the DP epoch path."""
    from pytorch_ddp_mnist_tpu.parallel.mesh import make_mesh
    from pytorch_ddp_mnist_tpu.train.scan import make_dp_run_fn, make_run_fn
    nsteps, batch, epochs = 4, 16, 2
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.integers(0, 256, (nsteps * batch, 784),
                                 dtype=np.uint8))
    y = jnp.asarray(rng.integers(0, 10, nsteps * batch).astype(np.int32))
    idxs = jnp.asarray(np.stack([
        np.random.default_rng(e).permutation(nsteps * batch).reshape(
            nsteps, batch) for e in range(epochs)]).astype(np.int32))
    mesh1 = make_mesh([1], ["dp"], jax.devices()[:1])

    def fresh():
        return (init_mlp(jax.random.key(0)), jax.random.key(3))

    run_dp = make_dp_run_fn(mesh1, lr=0.05, kernel="pallas_epoch",
                            interpret=True)
    p_dp, _, l_dp = run_dp(*fresh(), x, y, idxs)
    run_s = make_run_fn(lr=0.05, kernel="pallas_epoch", interpret=True)
    p_s, _, l_s = run_s(*fresh(), x, y, idxs)
    np.testing.assert_allclose(np.asarray(l_dp), np.asarray(l_s), rtol=1e-6)
    _tree_allclose(p_dp, p_s, rtol=1e-6)


def test_epoch_in_kernel_rng_rejected_on_interpreter():
    """The in-kernel-PRNG epoch kernel (masks=None) has no interpreter
    lowering; the wrapper must say so by name."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import epoch_fused_sgd
    params = init_mlp(jax.random.key(0))
    x, y = _data(16)
    with pytest.raises(ValueError, match="masks"):
        epoch_fused_sgd(params, x, y, 1, 0.01, 16, interpret=True)


def _epoch_data(nsteps=4, batch=16, seed=0, uint8=False):
    rng = np.random.default_rng(seed)
    rows = nsteps * batch
    if uint8:
        x = jnp.asarray(rng.integers(0, 256, (rows, 784), dtype=np.uint8))
    else:
        x = jnp.asarray(rng.normal(size=(rows, 784)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 10, rows).astype(np.int32))
    return x, y


def _epoch_masks(key, nsteps, batch):
    from pytorch_ddp_mnist_tpu.ops.pallas_step import HIDDEN1
    masks = jax.vmap(lambda k: dropout_mask(k, batch))(
        jax.random.split(key, nsteps))
    return masks.reshape(nsteps * batch, HIDDEN1)


def test_per_step_kernel_bf16_matches_cast_point_oracle():
    """A bf16 batch selects the per-step kernel's bf16-matmul mode; the
    result must match step_reference_bf16 (the cast-point-exact oracle) and
    genuinely differ from the f32 kernel."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import step_reference_bf16
    params = init_mlp(jax.random.key(2))
    x, y = _data(64, seed=9)
    mask = dropout_mask(jax.random.key(4), 64)
    kl, kg = fused_loss_and_grads(params, x.astype(jnp.bfloat16), y, mask,
                                  interpret=True)
    rl, rg = step_reference_bf16(params, x, y, mask)
    np.testing.assert_allclose(float(kl), float(rl), rtol=1e-3)
    _tree_allclose(kg, rg, rtol=2e-3, atol=1e-4)
    fl, _ = fused_loss_and_grads(params, x, y, mask, interpret=True)
    assert float(kl) != float(fl)     # the mode switch did something


@pytest.mark.parametrize("bf16", [False, True])
def test_epoch_masked_kernel_bf16_matches_oracle(bf16):
    """The bf16-matmul epoch kernel variant (bf16 operands, f32 accumulation
    + f32 master weights) against the oracle restating the same cast points;
    the f32 case doubles as a no-op-cast sanity check of the shared path."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import (epoch_fused_sgd,
                                                       epoch_sgd_reference)
    nsteps, batch = 4, 16
    x, y = _epoch_data(nsteps, batch, seed=11, uint8=True)
    masks = _epoch_masks(jax.random.key(6), nsteps, batch)
    params = init_mlp(jax.random.key(0))
    pk, kl = epoch_fused_sgd(params, x, y, None, 0.05, batch,
                             masks=masks, interpret=True, compute_bf16=bf16)
    pr, rl = epoch_sgd_reference(params, x, y, masks, 0.05, batch,
                                 compute_bf16=bf16)
    tol = dict(rtol=1e-3, atol=1e-4) if bf16 else dict(rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(kl), np.asarray(rl), **tol)
    _tree_allclose(pk, pr, **tol)
    if bf16:
        # bf16 matmuls genuinely differ from f32 ones (sanity: the flag did
        # something), but train the same model to similar losses
        _, rl32 = epoch_sgd_reference(params, x, y, masks, 0.05, batch)
        assert not np.array_equal(np.asarray(rl), np.asarray(rl32))
        np.testing.assert_allclose(np.asarray(rl), np.asarray(rl32),
                                   rtol=0.05)


@pytest.mark.parametrize("uint8", [False, True])
def test_epoch_masked_kernel_matches_pure_jax_oracle(uint8):
    """CPU CI coverage of the epoch-kernel wrapper (VERDICT r2 #4): the
    interpreted masked kernel — loss detiling from the (8,128) output, block
    streaming, in-kernel normalize (uint8), weight residency/update — must
    reproduce the pure-JAX oracle of the same recurrence. Observed exact on
    CPU (same f32 ops); tolerance covers reduction-order freedom."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import (epoch_fused_sgd,
                                                       epoch_sgd_reference)
    nsteps, batch = 12, 16   # crosses an (8,128) loss-tile boundary
    x, y = _epoch_data(nsteps, batch, seed=3, uint8=uint8)
    masks = _epoch_masks(jax.random.key(5), nsteps, batch)
    params = init_mlp(jax.random.key(0))
    pk, kl = epoch_fused_sgd(params, x, y, None, 0.05, batch,
                             masks=masks, interpret=True)
    pr, rl = epoch_sgd_reference(params, x, y, masks, 0.05, batch)
    assert kl.shape == (nsteps,)
    np.testing.assert_allclose(np.asarray(kl), np.asarray(rl),
                               rtol=1e-5, atol=1e-6)
    _tree_allclose(pk, pr, rtol=1e-5, atol=1e-6)


def test_epoch_wrapper_interpret_snapshots_plumbing():
    """run_epochal's plumbing (key split chain, per-epoch gather, snapshot
    stacking) on CPU: the interpreted kernel='pallas_epoch' run must equal
    composing epoch_fused_sgd by hand with the same key chain and the same
    seeds->mask mapping."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import epoch_fused_sgd
    from pytorch_ddp_mnist_tpu.train.scan import make_run_fn
    nsteps, batch, epochs = 4, 16, 3
    x, y = _epoch_data(nsteps, batch, seed=7, uint8=True)
    idxs = jnp.asarray(np.stack([
        np.random.default_rng(e).permutation(nsteps * batch).reshape(
            nsteps, batch) for e in range(epochs)]).astype(np.int32))
    params, key = init_mlp(jax.random.key(0)), jax.random.key(9)

    run = make_run_fn(lr=0.05, kernel="pallas_epoch", interpret=True,
                      snapshots=True)
    # run() donates params/key; hand it copies so the manual loop below
    # can still use the originals
    rp, rkey, losses, (p_snaps, k_snaps) = run(
        jax.tree_util.tree_map(jnp.array, params),
        jax.random.wrap_key_data(jnp.array(jax.random.key_data(key))),
        x, y, idxs)
    assert losses.shape == (epochs, nsteps)

    # manual composition with the identical key/mask schedule
    mp, mkey = params, key
    for e in range(epochs):
        mkey, sub = jax.random.split(mkey)
        rows = idxs[e].reshape(-1)
        masks = _epoch_masks(sub, nsteps, batch)
        mp, le = epoch_fused_sgd(mp, jnp.take(x, rows, axis=0),
                                 jnp.take(y, rows, axis=0), None, 0.05,
                                 batch, masks=masks, interpret=True)
        np.testing.assert_allclose(np.asarray(losses[e]), np.asarray(le),
                                   rtol=1e-6)
        # snapshot e must be the params AFTER epoch e
        _tree_allclose(jax.tree_util.tree_map(lambda a, _e=e: a[_e], p_snaps),
                       mp, rtol=1e-6)
    _tree_allclose(rp, mp, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(jax.random.key_data(rkey)),
                                  np.asarray(jax.random.key_data(mkey)))


@pytest.mark.parametrize("K", [2, 4, 8])
def test_epoch_kernel_superstep_bitwise_matches_k1(K):
    """steps_per_iter=K (K sub-steps per grid iteration) is a pure schedule
    change: same per-step math in the same order on the same resident
    weights, so params AND losses must be BITWISE equal to K=1 — including
    a ragged tail (11 steps: K=2 pads 1 step, K=8 pads 5)."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import epoch_fused_sgd
    nsteps, batch = 11, 16
    x, y = _epoch_data(nsteps, batch, seed=7, uint8=True)
    masks = _epoch_masks(jax.random.key(9), nsteps, batch)
    params = init_mlp(jax.random.key(0))
    p1, l1 = epoch_fused_sgd(params, x, y, None, 0.01, batch,
                             masks=masks, interpret=True)
    pk, lk = epoch_fused_sgd(params, x, y, None, 0.01, batch,
                             masks=masks, interpret=True, steps_per_iter=K)
    assert lk.shape == (nsteps,)
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(lk))
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(pk)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_epoch_kernel_superstep_named_errors():
    """Invalid superstep combinations fail by name at the wrapper and scan
    layers (never a silent no-op — the unroll lesson, ADVICE r2)."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import epoch_fused_sgd
    from pytorch_ddp_mnist_tpu.train.scan import make_run_fn
    nsteps, batch = 4, 16
    x, y = _epoch_data(nsteps, batch)
    masks = _epoch_masks(jax.random.key(1), nsteps, batch)
    params = init_mlp(jax.random.key(0))

    with pytest.raises(ValueError, match="steps_per_iter must be 1, 2, 4"):
        epoch_fused_sgd(params, x, y, None, 0.01, batch, masks=masks,
                        interpret=True, steps_per_iter=3)
    with pytest.raises(ValueError, match="single-replica only"):
        epoch_fused_sgd(params, x, y, None, 0.01, batch, masks=masks,
                        axis_name="dp", axis_size=2, steps_per_iter=2)
    with pytest.raises(ValueError, match="VMEM stream budget"):
        epoch_fused_sgd(params, jnp.tile(x, (16, 1)), jnp.tile(y, 16),
                        None, 0.01, 256,
                        masks=jnp.tile(masks, (16, 1)), interpret=True,
                        steps_per_iter=8)
    with pytest.raises(ValueError, match="valid_steps=9 must be in"):
        epoch_fused_sgd(params, x, y, None, 0.01, batch, masks=masks,
                        interpret=True, valid_steps=9)
    with pytest.raises(ValueError, match="whole-epoch-kernel knob"):
        make_run_fn(lr=0.01, kernel="pallas", superstep=2)
    with pytest.raises(ValueError, match="superstep must be 1, 2, 4 or 8"):
        make_run_fn(lr=0.01, kernel="pallas_epoch", superstep=5)


@_needs_devices(2)
def test_superstep_rejected_on_multidevice_mesh():
    """superstep on a multi-device DP mesh fails by name at the scan layer
    (the DP ring's handshake is per grid iteration, not per sub-step)."""
    from pytorch_ddp_mnist_tpu.train.scan import make_dp_run_fn
    from pytorch_ddp_mnist_tpu.parallel.mesh import make_mesh
    mesh = make_mesh([2], ["dp"], jax.devices()[:2])
    with pytest.raises(ValueError, match="single-replica only"):
        make_dp_run_fn(mesh, lr=0.01, kernel="pallas_epoch", superstep=2)


def test_run_fn_superstep_matches_default():
    """The scan-level plumbing (gather, key chain, scan over epochs) is
    superstep-invariant: a 2-epoch interpreted run at superstep=8 equals
    superstep=1 bitwise."""
    from pytorch_ddp_mnist_tpu.train.scan import make_run_fn, resident_images
    from pytorch_ddp_mnist_tpu.data import synthetic_mnist

    split = synthetic_mnist(512, seed=11)
    x_all = jnp.asarray(resident_images(split.images))  # uint8-resident
    y_all = jnp.asarray(split.labels.astype(np.int32))
    idxs = jnp.asarray(np.stack([
        np.random.default_rng(e).permutation(512)[:11 * 32].reshape(11, 32)
        for e in range(2)]).astype(np.int32))

    outs = {}
    for K in (1, 8):
        run = make_run_fn(lr=0.01, kernel="pallas_epoch", interpret=True,
                          superstep=K)
        p, _, losses = run(init_mlp(jax.random.key(0)), jax.random.key(1),
                           x_all, y_all, idxs)
        outs[K] = (p, np.asarray(losses))
    np.testing.assert_array_equal(outs[1][1], outs[8][1])
    for a, b in zip(jax.tree_util.tree_leaves(outs[1][0]),
                    jax.tree_util.tree_leaves(outs[8][0])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@tpu_only
def test_epoch_kernel_superstep_matches_k1_on_hardware():
    """Mosaic path: the in-kernel-PRNG epoch kernel at superstep=8 must be
    bitwise-equal to superstep=1 (same (seed, global step) words per
    sub-step), ragged tail included."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import epoch_fused_sgd
    nsteps, batch = 11, 128
    x, y = _epoch_data(nsteps, batch, seed=13, uint8=True)
    params = init_mlp(jax.random.key(0))
    p1, l1 = epoch_fused_sgd(params, x, y, 42, 0.01, batch)
    p8, l8 = epoch_fused_sgd(params, x, y, 42, 0.01, batch,
                             steps_per_iter=8)
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l8))
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p8)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _pack_grads_like_kernel(g):
    """Per-replica grads packed exactly as the DP kernel's comm buffer:
    (EPOCH_COMM_ROWS, 128) f32, rows per _COMM_LAYOUT (gw1,gb1,gw2,gb2,gw3
    with fc3 column-padded) — the layout both ring strategies reduce over."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import (
        _COMM_LAYOUT, EPOCH_COMM_ROWS, pad_fc3)
    buf = np.zeros((EPOCH_COMM_ROWS, 128), np.float32)
    parts = (g["fc1"]["w"], g["fc1"]["b"][None, :],
             g["fc2"]["w"], g["fc2"]["b"][None, :],
             pad_fc3(g["fc3"]["w"]))
    for (off, rows), part in zip(_COMM_LAYOUT, parts):
        buf[off:off + rows] = np.asarray(part, np.float32)
    return buf


def _unpack_grads_like_kernel(buf):
    from pytorch_ddp_mnist_tpu.ops.pallas_step import _COMM_LAYOUT, NUM_CLASSES
    (o1, r1), (ob1, _), (o2, r2), (ob2, _), (o3, r3) = _COMM_LAYOUT
    return {"fc1": {"w": buf[o1:o1 + r1], "b": buf[ob1]},
            "fc2": {"w": buf[o2:o2 + r2], "b": buf[ob2]},
            "fc3": {"w": buf[o3:o3 + r3, :NUM_CLASSES]}}


def _ring_mean_grads(per_replica, ring):
    """The two in-kernel allreduce strategies' EXACT float summation trees
    (pinned against the kernel's index algebra by the two schedule tests
    above), applied numerically to packed per-replica grads:

    - allgather (_make_epoch_kernel's else-branch, fixed origin-order sum):
        tot = g0; tot = tot + g1; ...; mean = tot * f32(1/n)
    - reduce_scatter (ring_rs branch): chunk c is reduced by the sequential
        chain starting at its origin device, folding local + incoming:
        s = g_c[c]; s = g_{c+1}[c] + s; ...; mean[c] = s * f32(1/n)
    """
    from pytorch_ddp_mnist_tpu.ops.pallas_step import (
        EPOCH_COMM_ROWS, _rs_chunk_rows)
    n = len(per_replica)
    packs = [_pack_grads_like_kernel(g) for g in per_replica]
    if ring == "allgather":
        tot = packs[0]
        for d in range(1, n):
            tot = tot + packs[d]
        return _unpack_grads_like_kernel(tot * np.float32(1.0 / n))
    assert ring == "reduce_scatter"
    C = _rs_chunk_rows(n)
    padded = np.zeros((n, n * C, 128), np.float32)
    for d in range(n):
        padded[d, :EPOCH_COMM_ROWS] = packs[d]
    out = np.zeros((n * C, 128), np.float32)
    for c in range(n):
        s = padded[c, c * C:(c + 1) * C]
        for k in range(1, n):
            s = padded[(c + k) % n, c * C:(c + 1) * C] + s
        out[c * C:(c + 1) * C] = s * np.float32(1.0 / n)
    return _unpack_grads_like_kernel(out[:EPOCH_COMM_ROWS])


@pytest.mark.parametrize("ring,n", [("allgather", 8), ("reduce_scatter", 8),
                                    ("reduce_scatter", 16)])
def test_dp_epoch_kernel_math_numeric_oracle(ring, n):
    """Full NUMERIC execution of the DP epoch kernel's math at n replicas on
    CPU — the ring replaced by its simulated reduction order (same summation
    tree; see _ring_mean_grads), everything else the per-replica step math
    of epoch_sgd_reference — against the serial oracle on the equivalent
    GLOBAL batch. (1/n)·Σ_d (1/B)·Σ_rows ≡ (1/G)·Σ_rows with G = n·B, so
    the DP run must land on the serial run's final params to float-rounding
    (the summation orders differ — documented tolerance, not bitwise). With
    the schedule-algebra tests pinning the ring's index protocol, a future
    multi-chip window only has to confirm the DMAs, not the math
    (VERDICT r3 #6).

    CPU-backend only: the tolerances are calibrated for CPU f32 matmuls;
    under the hardware suite (PDMT_TPU_TESTS=1 keeps the real TPU backend)
    the jitted matmuls run at TPU default precision, where a spurious
    failure would flip the whole measurement pass's exit status."""
    import jax

    if jax.default_backend() != "cpu":
        pytest.skip("numeric oracle tolerances are CPU-calibrated; the "
                    "kernel itself has its own hardware tests")

    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.ops.loss import cross_entropy
    from pytorch_ddp_mnist_tpu.ops.pallas_step import epoch_sgd_reference
    from pytorch_ddp_mnist_tpu.ops.sgd import sgd_step

    S, B, lr = 5, 16, 0.05
    G = n * B
    rng = np.random.default_rng(7)
    x = rng.normal(size=(S, G, 784)).astype(np.float32)
    y = rng.integers(0, 10, size=(S, G)).astype(np.int32)
    # pre-scaled inverted-dropout masks, distinct per replica (fold_in model)
    m = (rng.random(size=(S, G, 128)) > 0.2).astype(np.float32) / 0.8

    def loss_fn(p, xb, yb, mb):
        # epoch_sgd_reference's step restated (f32 path)
        z1 = xb @ p["fc1"]["w"] + p["fc1"]["b"]
        d1 = jnp.maximum(z1, 0.0) * mb
        z2 = d1 @ p["fc2"]["w"] + p["fc2"]["b"]
        h2 = jnp.maximum(z2, 0.0)
        return cross_entropy(h2 @ p["fc3"]["w"], yb)

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    # --- serial oracle on the global batch ---
    params0 = init_mlp(jax.random.key(0))
    p_ref, losses_ref = epoch_sgd_reference(
        params0, jnp.asarray(x.reshape(S * G, 784)),
        jnp.asarray(y.reshape(S * G)), jnp.asarray(m.reshape(S * G, 128)),
        lr, G)

    # --- DP execution: per-replica grads + simulated-ring mean per step ---
    p = params0
    dp_losses = []
    for s in range(S):
        reps = []
        shard_means = []
        for d in range(n):
            xb = jnp.asarray(x[s, d * B:(d + 1) * B])
            yb = jnp.asarray(y[s, d * B:(d + 1) * B])
            mb = jnp.asarray(m[s, d * B:(d + 1) * B])
            loss_d, g_d = grad_fn(p, xb, yb, mb)
            reps.append(jax.tree_util.tree_map(np.asarray, g_d))
            shard_means.append(float(loss_d))
        mean_g = jax.tree_util.tree_map(
            jnp.asarray, _ring_mean_grads(reps, ring))
        p = sgd_step(p, mean_g, lr)
        dp_losses.append(np.mean(shard_means))   # the outer pmean

    for ka, kb in zip(jax.tree_util.tree_leaves(p),
                      jax.tree_util.tree_leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(ka), np.asarray(kb),
                                   rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(dp_losses),
                               np.asarray(losses_ref), rtol=1e-5, atol=1e-6)


def test_threefry_cipher_and_mask_bitwise_vs_jax():
    """The in-kernel threefry primitives ARE jax's stream: cipher outputs
    xor-combined must equal jax.random.bits, and the mask block must equal
    dropout_mask (models/mlp.py's bernoulli draw) bit-for-bit."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import (
        _threefry_mask_block, dropout_mask, threefry2x32)

    for seed in (0, 7, (1 << 31) + 3):
        key = jax.random.key(seed)          # jax default impl = threefry
        k0, k1 = (jnp.uint32(w) for w in np.asarray(
            jax.random.key_data(key), np.uint32))
        idx = jnp.arange(4096, dtype=jnp.uint32)
        o0, o1 = threefry2x32(k0, k1, jnp.zeros_like(idx), idx)
        np.testing.assert_array_equal(
            np.asarray(o0 ^ o1),
            np.asarray(jax.random.bits(key, (4096,), "uint32")))
        np.testing.assert_array_equal(
            np.asarray(jax.jit(_threefry_mask_block,
                               static_argnums=2)(k0, k1, 256)),
            np.asarray(dropout_mask(key, 256)))


def test_epoch_kernel_threefry_interpret_matches_masked_bitwise():
    """rng_impl='threefry' must reproduce the masks=vmap(dropout_mask) path
    BIT-FOR-BIT for the same per-step keys — interpreted on CPU, so the
    whole reference-RNG kernel path is CI-covered without hardware (the
    core-PRNG mode never could be). Also pins K-invariance: superstep 2
    (including the ragged zero-key tail pad at S=5) changes nothing."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import (
        dropout_mask, epoch_fused_sgd)

    S, B = 5, 32
    params = init_mlp(jax.random.key(0))
    x, y = _data(S * B, seed=3)
    subs = jax.random.split(jax.random.key(42), S)
    keys = jax.random.key_data(subs).astype(jnp.int32)
    masks = jax.vmap(lambda k: dropout_mask(k, B))(subs).reshape(S * B, -1)

    p_tf, l_tf = epoch_fused_sgd(params, x, y, keys, 0.05, B,
                                 rng_impl="threefry", interpret=True)
    p_mk, l_mk = epoch_fused_sgd(params, x, y, None, 0.05, B,
                                 masks=masks, interpret=True)
    np.testing.assert_array_equal(np.asarray(l_tf), np.asarray(l_mk))
    for a, b in zip(jax.tree_util.tree_leaves(p_tf),
                    jax.tree_util.tree_leaves(p_mk)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    p_k2, l_k2 = epoch_fused_sgd(params, x, y, keys, 0.05, B,
                                 rng_impl="threefry", interpret=True,
                                 steps_per_iter=2)
    np.testing.assert_array_equal(np.asarray(l_k2), np.asarray(l_tf))
    for a, b in zip(jax.tree_util.tree_leaves(p_k2),
                    jax.tree_util.tree_leaves(p_tf)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_run_epochal_threefry_key_chain_matches_interpret_path():
    """The scan layer routes a 2-word (threefry) train key to the in-kernel
    reference-RNG draw using the SAME per-step key chain as the interpreted
    masks path: replaying the chain by hand through the interpreted
    threefry kernel reproduces make_run_fn(interpret=True) bit-for-bit."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import epoch_fused_sgd
    from pytorch_ddp_mnist_tpu.train.scan import make_run_fn

    S, B = 3, 16
    x_all, y_all = _data(S * B, seed=11)
    idxs = jnp.arange(S * B, dtype=jnp.int32).reshape(1, S, B)
    run = make_run_fn(0.05, kernel="pallas_epoch", interpret=True)
    p_a, _, l_a = run(init_mlp(jax.random.key(0)), jax.random.key(9),
                      x_all, y_all, idxs)

    _, sub = jax.random.split(jax.random.key(9))   # the body's epoch split
    subs = jax.random.split(sub, S)
    keys = jax.random.key_data(subs).astype(jnp.int32)
    p_b, l_b = epoch_fused_sgd(init_mlp(jax.random.key(0)),
                               x_all, y_all, keys, 0.05, B,
                               rng_impl="threefry", interpret=True)
    np.testing.assert_array_equal(np.asarray(l_a[0]), np.asarray(l_b))
    for a, b in zip(jax.tree_util.tree_leaves(p_a),
                    jax.tree_util.tree_leaves(p_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_epoch_kernel_threefry_validation():
    from pytorch_ddp_mnist_tpu.ops.pallas_step import (
        dropout_mask, epoch_fused_sgd)

    params = init_mlp(jax.random.key(0))
    x, y = _data(32)
    keys = jnp.zeros((2, 2), jnp.int32)
    with pytest.raises(ValueError, match="rng_impl"):
        epoch_fused_sgd(params, x, y, keys, 0.01, 16, rng_impl="rbg")
    with pytest.raises(ValueError, match="not both"):
        epoch_fused_sgd(params, x, y, keys, 0.01, 16, rng_impl="threefry",
                        masks=dropout_mask(jax.random.key(0), 32))
    with pytest.raises(ValueError, match="key words"):
        epoch_fused_sgd(params, x, y, jnp.zeros((2,), jnp.int32), 0.01, 16,
                        rng_impl="threefry", interpret=True)
    with pytest.raises(ValueError, match="one key-word row per step"):
        epoch_fused_sgd(params, x, y, jnp.zeros((3, 2), jnp.int32), 0.01,
                        16, rng_impl="threefry", interpret=True)
    # the core-PRNG interpreter rejection now names the interpretable
    # alternative
    with pytest.raises(ValueError, match="threefry"):
        epoch_fused_sgd(params, x, y, 5, 0.01, 16, interpret=True)


@tpu_only
def test_epoch_kernel_threefry_matches_masked_kernel_on_hardware():
    """Mosaic lowering of the in-kernel threefry draw: identical kernel,
    identical mask VALUES (the cipher is bit-exact and masks are only ever
    1/keep or 0), so the Mosaic threefry run must equal the Mosaic
    masked-kernel run BITWISE — and transitively the reference RNG."""
    from pytorch_ddp_mnist_tpu.ops.pallas_step import (
        dropout_mask, epoch_fused_sgd)

    S, B = 4, 128
    params = init_mlp(jax.random.key(0))
    x, y = _data(S * B, seed=6)
    subs = jax.random.split(jax.random.key(77), S)
    keys = jax.random.key_data(subs).astype(jnp.int32)
    masks = jax.vmap(lambda k: dropout_mask(k, B))(subs).reshape(S * B, -1)
    p_tf, l_tf = epoch_fused_sgd(params, x, y, keys, 0.01, B,
                                 rng_impl="threefry")
    p_mk, l_mk = epoch_fused_sgd(params, x, y, None, 0.01, B, masks=masks)
    np.testing.assert_array_equal(np.asarray(l_tf), np.asarray(l_mk))
    for a, b in zip(jax.tree_util.tree_leaves(p_tf),
                    jax.tree_util.tree_leaves(p_mk)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@tpu_only
def test_scan_threefry_key_trains_on_hardware():
    """The flagship reference-RNG configuration end-to-end on the chip:
    make_run_fn(kernel='pallas_epoch') with a threefry train key (the CLI
    default --impl) routes to the in-kernel threefry draw and trains."""
    from pytorch_ddp_mnist_tpu.train.scan import make_run_fn

    x_all, y_all = _data(1024, seed=5)
    idxs = jnp.asarray(np.arange(1024, dtype=np.int32)
                       .reshape(1, 8, 128).repeat(4, 0))
    run = make_run_fn(lr=0.1, kernel="pallas_epoch")
    _, _, losses = run(init_mlp(jax.random.key(0)), jax.random.key(1),
                       x_all, y_all, idxs)
    losses = np.asarray(losses).ravel()
    assert np.isfinite(losses).all() and losses[-1] < losses[0] * 0.7


def test_threefry_kernel_rejects_legacy_threefry_config():
    """The in-kernel threefry replays jax's PARTITIONABLE counter layout;
    with jax_threefry_partitionable disabled, dropout_mask's stream differs
    and bitwise parity would break silently — the scan layer refuses by
    name instead. (jax.random.bits itself changes under the legacy flag, so
    no fallback could be bit-faithful to both.)"""
    import jax as _jax

    from pytorch_ddp_mnist_tpu.train.scan import make_run_fn

    x_all, y_all = _data(32, seed=0)
    idxs = jnp.arange(32, dtype=jnp.int32).reshape(1, 2, 16)
    run = make_run_fn(0.05, kernel="pallas_epoch")  # non-interpret: threefry
    prev = _jax.config.jax_threefry_partitionable
    _jax.config.update("jax_threefry_partitionable", False)
    try:
        with pytest.raises(ValueError, match="partitionable"):
            # eval_shape is enough: the guard fires at trace time, before
            # any Mosaic compile — so this tests on CPU too
            _jax.eval_shape(run, init_mlp(_jax.random.key(0)),
                            _jax.random.key(1), x_all, y_all, idxs)
    finally:
        _jax.config.update("jax_threefry_partitionable", prev)


@pytest.mark.integration
@needs_tpu_sim
def test_epoch_kernel_threefry_simulator_at_real_epoch_scale():
    """The fixed SMEM-resident threefry key table at the REAL flagship
    epoch shape — S=469 steps (ragged-padded to 472 table rows), batch
    128, uint8 input — executed by the TPU-semantics simulator and
    bitwise equal to the masked-interpreter oracle. The r05 hardware
    window failed this kernel at exactly this scale (the (K, 2) streamed
    key block was Mosaic-illegal); tiny-shape tests keep the semantics
    honest, this one keeps the full-scale SMEM-table shape honest."""
    from jax.experimental.pallas import tpu as pltpu

    from pytorch_ddp_mnist_tpu.ops.pallas_step import (dropout_mask,
                                                       epoch_fused_sgd)

    S, B = 469, 128
    params = init_mlp(jax.random.key(0))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 256, (S * B, 784), dtype=np.uint8))
    y = jnp.asarray(rng.integers(0, 10, (S * B,), dtype=np.int32))
    subs = jax.random.split(jax.random.key(4), S)
    keys = jax.random.key_data(subs).astype(jnp.int32)

    p_sim, l_sim = epoch_fused_sgd(params, x, y, keys, 0.01, B,
                                   rng_impl="threefry",
                                   interpret=pltpu.InterpretParams())
    masks = jax.vmap(lambda k: dropout_mask(k, B))(subs).reshape(S * B, -1)
    p_mk, l_mk = epoch_fused_sgd(params, x, y, None, 0.01, B, masks=masks,
                                 interpret=True)
    np.testing.assert_array_equal(np.asarray(l_sim), np.asarray(l_mk))
    for a, b in zip(jax.tree_util.tree_leaves(p_sim),
                    jax.tree_util.tree_leaves(p_mk)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.integration
@needs_tpu_sim
def test_epoch_kernel_superstep8_simulator_at_real_epoch_scale():
    """The wedge-suspect r05 configuration — superstep K=8 at the real
    flagship epoch shape (S=469 ragged-padded to 472, grid 59, batch 128,
    uint8 input, core-PRNG dropout) — EXECUTED by the TPU-semantics
    simulator and bitwise K-invariant vs the K=1 run. With export
    lowering also green (test_export_lowering), every client-side check
    clears K=8: if the next hardware window still hangs it, the fault is
    in the remote Mosaic compile or hardware-only runtime, not the
    kernel's program."""
    from jax.experimental.pallas import tpu as pltpu

    from pytorch_ddp_mnist_tpu.ops.pallas_step import epoch_fused_sgd

    S, B = 469, 128
    params = init_mlp(jax.random.key(0))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 256, (S * B, 784), dtype=np.uint8))
    y = jnp.asarray(rng.integers(0, 10, (S * B,), dtype=np.int32))
    outs = {}
    for K in (1, 8):
        outs[K] = epoch_fused_sgd(params, x, y, jnp.int32(7), 0.01, B,
                                  steps_per_iter=K,
                                  interpret=pltpu.InterpretParams())
    np.testing.assert_array_equal(np.asarray(outs[1][1]),
                                  np.asarray(outs[8][1]))
    for a, b in zip(jax.tree_util.tree_leaves(outs[1][0]),
                    jax.tree_util.tree_leaves(outs[8][0])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@needs_tpu_sim
def test_epoch_kernel_executes_under_tpu_semantics_simulator():
    """The REAL serial epoch kernel — SMEM key words, in-kernel threefry
    draw, loss tiling, resident weights — EXECUTED on CPU by the
    TPU-semantics simulator (pltpu.InterpretParams), and bitwise equal to
    the plain-interpreter masked run of the same keys. This runs the exact
    code Mosaic compiles (not the masks-abstracted CI variant), so kernel
    logic regressions surface here without a chip. (The DP ring executes
    under the simulator too, at <=4 devices — see
    test_dp_epoch_kernel_executes_under_tpu_semantics_simulator.)"""
    from jax.experimental.pallas import tpu as pltpu

    from pytorch_ddp_mnist_tpu.ops.pallas_step import (dropout_mask,
                                                       epoch_fused_sgd)

    S, B = 3, 16
    params = init_mlp(jax.random.key(0))
    x, y = _data(S * B, seed=9)
    subs = jax.random.split(jax.random.key(4), S)
    keys = jax.random.key_data(subs).astype(jnp.int32)
    masks = jax.vmap(lambda k: dropout_mask(k, B))(subs).reshape(S * B, -1)

    p_sim, l_sim = epoch_fused_sgd(params, x, y, keys, 0.05, B,
                                   rng_impl="threefry",
                                   interpret=pltpu.InterpretParams())
    p_mk, l_mk = epoch_fused_sgd(params, x, y, None, 0.05, B, masks=masks,
                                 interpret=True)
    np.testing.assert_array_equal(np.asarray(l_sim), np.asarray(l_mk))
    for a, b in zip(jax.tree_util.tree_leaves(p_sim),
                    jax.tree_util.tree_leaves(p_mk)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@needs_tpu_sim
def test_ring_protocol_executes_under_tpu_semantics_simulator():
    """The DP epoch kernel's ring protocol — entry barrier via the
    collective-id semaphore, per-grid-iteration two-neighbor handshake,
    n-1 per-hop remote DMAs forwarding origin-indexed slots, fixed-order
    sum — EXECUTED with simulated inter-device DMAs and semaphores on the
    virtual CPU mesh (pltpu.InterpretParams), as a standalone kernel using
    the kernel's exact index formulas. Every device must end with the
    identical fixed-order sum on every grid step: the lockstep-weights
    invariant, now pinned by EXECUTION rather than only algebra."""
    from functools import partial

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from pytorch_ddp_mnist_tpu.compat import tpu_compiler_params
    from jax.sharding import Mesh, PartitionSpec as P
    from pytorch_ddp_mnist_tpu.compat import shard_map

    n, S = 4, 2
    if jax.device_count() < n:
        pytest.skip(f"needs {n} devices")
    mesh = Mesh(np.array(jax.devices()[:n]), ("dp",))

    def kernel(x_ref, o_ref, comm, send_sem, recv_sem, lsem, rsem):
        pid = pl.program_id(0)
        me = jax.lax.axis_index("dp")
        left = jax.lax.rem(me + (n - 1), n)
        right = jax.lax.rem(me + 1, n)
        did = pltpu.DeviceIdType.MESH

        @pl.when(pid == 0)
        def _entry_barrier():
            bsem = pltpu.get_barrier_semaphore()
            pltpu.semaphore_signal(bsem, inc=1, device_id=(left,),
                                   device_id_type=did)
            pltpu.semaphore_signal(bsem, inc=1, device_id=(right,),
                                   device_id_type=did)
            pltpu.semaphore_wait(bsem, 2)

        pltpu.semaphore_signal(lsem, inc=1, device_id=(right,),
                               device_id_type=did)
        pltpu.semaphore_signal(rsem, inc=1, device_id=(left,),
                               device_id_type=did)
        pltpu.semaphore_wait(lsem, 1)
        pltpu.semaphore_wait(rsem, 1)

        comm[me] = x_ref[:]
        for h in range(n - 1):
            slot = jax.lax.rem(me - h + 2 * n, n)
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm.at[slot], dst_ref=comm.at[slot],
                send_sem=send_sem.at[h], recv_sem=recv_sem.at[h],
                device_id=(right,), device_id_type=did)
            rdma.start()
            rdma.wait()
        tot = comm[0]
        for d in range(1, n):
            tot = tot + comm[d]
        o_ref[:] = tot

    def shard_fn(x):
        return pl.pallas_call(
            kernel,
            grid=(S,),
            out_shape=jax.ShapeDtypeStruct((S * 8, 128), jnp.float32),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.VMEM((n, 8, 128), jnp.float32),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.DMA((n - 1,)),
                            pltpu.SemaphoreType.REGULAR,
                            pltpu.SemaphoreType.REGULAR],
            compiler_params=tpu_compiler_params(
                dimension_semantics=("arbitrary",),
                collective_id=7, has_side_effects=True),
            interpret=pltpu.InterpretParams(),
        )(x)

    x = jnp.arange(n * S * 8 * 128, dtype=jnp.float32) \
           .reshape(n * S * 8, 128)
    f = jax.jit(shard_map(shard_fn, mesh=mesh, in_specs=P("dp"),
                          out_specs=P("dp"), check_vma=False))
    out = np.asarray(f(x)).reshape(n, S, 8, 128)
    expect = np.asarray(x).reshape(n, S, 8, 128).sum(0)
    for d in range(n):
        # BITWISE cross-device equality — the lockstep invariant itself
        # (an order-swapped sum would pass a mere allclose)
        np.testing.assert_array_equal(out[d], out[0])
        for s in range(S):
            np.testing.assert_allclose(out[d, s], expect[s])


def _dp_sim_ring_check(ring, n, interpret_params=None):
    """Shared body of the DP-simulator execution tests: run the REAL
    `_make_epoch_kernel` DP branch at `n` replicas under the TPU-semantics
    simulator and pin (1) bitwise cross-replica weight lockstep and
    (2) equality with the serial global-batch oracle. Called in-process by
    the parametrized test (n<=4 on the exactly-8-device CI pool), from a
    spare-device subprocess for the full 8-replica flagship shape, and
    with a detect_races InterpretParams by the race-detector test."""
    from jax.experimental.pallas import tpu as pltpu

    if interpret_params is None:
        interpret_params = pltpu.InterpretParams()
    from jax.sharding import Mesh, PartitionSpec as P
    from pytorch_ddp_mnist_tpu.compat import shard_map

    from pytorch_ddp_mnist_tpu.ops.pallas_step import (dropout_mask,
                                                       epoch_fused_sgd,
                                                       epoch_sgd_reference)

    S, B, lr = 3, 16, 0.05
    mesh = Mesh(np.array(jax.devices()[:n]), ("dp",))
    params0 = init_mlp(jax.random.key(0))
    rng = np.random.default_rng(5)
    # replica-major layout: x_rep[d] is replica d's epoch (S*B rows)
    x_rep = rng.normal(size=(n, S * B, 784)).astype(np.float32)
    y_rep = rng.integers(0, 10, size=(n, S * B)).astype(np.int32)
    subs = jax.random.split(jax.random.key(11), n * S)   # distinct streams
    keys_rep = jax.random.key_data(subs).astype(jnp.int32).reshape(n, S, 2)

    def shard_fn(params, xs, ys, ks):
        p2, losses = epoch_fused_sgd(
            params, xs, ys, ks, lr, B, rng_impl="threefry",
            axis_name="dp", axis_size=n, ring=ring,
            interpret=interpret_params)
        # leading length-1 axis per leaf -> out_specs P('dp') stacks the
        # replicas, exposing each device's resident weights for the
        # bitwise lockstep check
        return jax.tree_util.tree_map(lambda a: a[None], p2), losses[None]

    f = jax.jit(shard_map(
        shard_fn, mesh=mesh, in_specs=(P(), P("dp"), P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp")), check_vma=False))
    p_stack, losses = f(params0, x_rep.reshape(n * S * B, 784),
                        y_rep.reshape(n * S * B),
                        jnp.asarray(keys_rep.reshape(n * S, 2)))

    # 1. bitwise lockstep across the mesh
    for leaf in jax.tree_util.tree_leaves(p_stack):
        arr = np.asarray(leaf)
        for d in range(1, n):
            np.testing.assert_array_equal(arr[d], arr[0])

    # 2. serial oracle on the global batch: step s trains on the
    # concatenation of every replica's step-s block, with each replica's
    # in-kernel threefry mask (bit-equal to dropout_mask of the same key
    # words — pinned by test_threefry_cipher_and_mask_bitwise_vs_jax)
    x_glob = np.concatenate(
        [x_rep[:, s * B:(s + 1) * B].reshape(n * B, 784) for s in range(S)])
    y_glob = np.concatenate(
        [y_rep[:, s * B:(s + 1) * B].reshape(n * B) for s in range(S)])
    m_glob = np.concatenate(
        [np.concatenate([np.asarray(dropout_mask(subs[d * S + s], B))
                         for d in range(n)]) for s in range(S)])
    p_ref, losses_ref = epoch_sgd_reference(
        params0, jnp.asarray(x_glob), jnp.asarray(y_glob),
        jnp.asarray(m_glob), lr, n * B)
    p_dev0 = jax.tree_util.tree_map(lambda a: np.asarray(a)[0], p_stack)
    for a, b in zip(jax.tree_util.tree_leaves(p_dev0),
                    jax.tree_util.tree_leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(losses).mean(0),
                               np.asarray(losses_ref), rtol=1e-5, atol=1e-6)


@pytest.mark.integration
@pytest.mark.parametrize("ring,n", [("allgather", 2), ("reduce_scatter", 2),
                                    ("allgather", 4), ("reduce_scatter", 4)])
@needs_tpu_sim
def test_dp_epoch_kernel_executes_under_tpu_semantics_simulator(ring, n):
    """The REAL `_make_epoch_kernel` DP branch — entry barrier, per-step
    two-neighbor handshake, ring remote DMAs, fixed-order mean, resident-
    weight SGD — EXECUTED end-to-end on the virtual CPU mesh by the
    TPU-semantics simulator (VERDICT r4 #4: previously only shape-traced).
    Two pins (see _dp_sim_ring_check): bitwise cross-replica weight
    lockstep on the SHIPPED kernel, and equality with the serial
    global-batch oracle. n<=4 in-process: the kernel must not occupy the
    whole 8-device pool (the starvation deadlock in the epoch_fused_sgd
    guard note); the full 8-replica shape runs in the spare-device
    subprocess test below."""
    import jax as _jax

    if _jax.device_count() < n:
        pytest.skip(f"needs {n} devices")
    if _jax.default_backend() != "cpu":
        pytest.skip("oracle tolerances are CPU-calibrated")
    _dp_sim_ring_check(ring, n)


@pytest.mark.integration
@needs_tpu_sim
def test_serial_epoch_kernel_clean_under_race_detector(capsys):
    """The SERIAL whole-epoch kernel under the simulator's race detector:
    no cross-device ring here, but the detector still checks the
    pipelined input-block DMAs against the kernel body's reads and the
    revisited loss-tile/resident-weight output blocks for unfenced
    overlap — the single-chip half of the §5.2 machine-check."""
    from jax.experimental.pallas import tpu as pltpu

    from pytorch_ddp_mnist_tpu.ops.pallas_step import (dropout_mask,
                                                       epoch_fused_sgd)

    S, B = 3, 16
    params = init_mlp(jax.random.key(0))
    x, y = _data(S * B, seed=9)
    subs = jax.random.split(jax.random.key(4), S)
    keys = jax.random.key_data(subs).astype(jnp.int32)
    p_sim, l_sim = epoch_fused_sgd(
        params, x, y, keys, 0.05, B, rng_impl="threefry",
        interpret=pltpu.InterpretParams(detect_races=True))
    # same numeric pin as the plain simulator test: bitwise equal to the
    # interpreter masked run of the same keys
    masks = jax.vmap(lambda k: dropout_mask(k, B))(subs).reshape(S * B, -1)
    p_mk, l_mk = epoch_fused_sgd(params, x, y, None, 0.05, B, masks=masks,
                                 interpret=True)
    np.testing.assert_array_equal(np.asarray(l_sim), np.asarray(l_mk))
    for a, b in zip(jax.tree_util.tree_leaves(p_sim),
                    jax.tree_util.tree_leaves(p_mk)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert "RACE DETECTED" not in capsys.readouterr().out
    from jax._src.pallas.mosaic.interpret import (
        interpret_pallas_call as _ipc)
    assert _ipc.races is not None and _ipc.races.races_found is False


@pytest.mark.integration
@pytest.mark.parametrize("ring,n", [("allgather", 2), ("allgather", 3),
                                    ("reduce_scatter", 4)])
@needs_tpu_sim
def test_dp_ring_kernel_clean_under_simulator_race_detector(ring, n, capsys):
    """Race detection on the SHIPPED ring kernel (SURVEY §5.2, upgraded
    from 'scoped absent'): the TPU-semantics simulator's vector-clock race
    detector (InterpretParams(detect_races=True)) executes the real
    `_make_epoch_kernel` DP branch and must find no data race — the
    semaphore-fencing design arguments (entry barrier, per-step
    two-neighbor handshake, per-hop DMA semaphores, AG-position
    write-once) are machine-checked by execution instead of prose. The
    detector prints 'RACE DETECTED' and raises its races_found flag on a
    violation; both must stay clean, and the numeric results must still
    pass the lockstep + oracle pins (_dp_sim_ring_check)."""
    import jax as _jax

    if _jax.device_count() < n:
        pytest.skip(f"needs {n} devices")
    if _jax.default_backend() != "cpu":
        pytest.skip("oracle tolerances are CPU-calibrated")
    from jax.experimental.pallas import tpu as pltpu

    _dp_sim_ring_check(ring, n, pltpu.InterpretParams(detect_races=True))
    # Secondary check — empty under `pytest -s`, so it must not be the
    # only enforcement.
    assert "RACE DETECTED" not in capsys.readouterr().out
    # PRIMARY check: the detector's aggregate flag. Private jax module, so
    # fail LOUDLY if the path moves on a jax upgrade (a silent skip would
    # leave the §5.2 machine-checked claim unenforced under -s) — on the
    # pinned jax the module global `races` holds the last run's state.
    from jax._src.pallas.mosaic.interpret import (
        interpret_pallas_call as _ipc)
    assert _ipc.races is not None, (
        "jax moved/renamed the race-detection state; re-pin this check")
    assert _ipc.races.races_found is False


@pytest.mark.integration
@needs_tpu_sim
def test_dp_epoch_kernel_full_eight_replica_ring_in_subprocess():
    """The FLAGSHIP multi-chip shape — the 8-replica all-gather ring —
    executed under the TPU-semantics simulator, lockstep- and
    oracle-checked (_dp_sim_ring_check). Runs in a subprocess whose host
    pool holds 8 + 1 devices: a ring occupying EVERY device of the pool
    deadlocks the simulator's worker threads (measured; guard note in
    epoch_fused_sgd), so the spare device is the enabling workaround —
    and this test is the proof the workaround holds."""
    import subprocess

    code = (
        "import os\n"
        "os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS', '') +"
        " ' --xla_force_host_platform_device_count=9')\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from jax.extend.backend import clear_backends\n"
        "clear_backends()\n"
        "assert jax.device_count() == 9\n"
        "from test_pallas_step import _dp_sim_ring_check\n"
        "_dp_sim_ring_check('allgather', 8)\n"
        "print('RING8 OK')\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the child sets its own 9-device pool
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [repo, os.path.join(repo, "tests"),
         env.get("PYTHONPATH", "")]).rstrip(os.pathsep)
    r = subprocess.run([sys.executable, "-c", code], env=env, cwd=repo,
                       capture_output=True, text=True, timeout=540)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "RING8 OK" in r.stdout


@pytest.mark.integration
@needs_tpu_sim
def test_dp_run_fn_epoch_kernel_executes_under_simulator():
    """The SCAN-layer DP wrapper (make_dp_run_fn, kernel='pallas_epoch')
    with interpret=pltpu.InterpretParams() EXECUTES the real ring kernel
    over the mesh — the full fused multi-epoch program with snapshots and
    pmean'd losses — instead of being rejected or shape-traced. Pins the
    wrapper plumbing (key fold-in, index sharding, InterpretParams
    threading) end-to-end off-hardware.

    4-device sub-mesh, not the full CI mesh: the simulator runs each
    device's kernel on a blocking thread, and the ring's entry barrier
    needs every replica's kernel LIVE at once — above ~4 concurrent
    kernels this 1-core CI host starves the pool and the run deadlocks
    (the diagnosed round-4 'hang'; see epoch_fused_sgd's guard note).
    The 8-device program stays trace-validated (dryrun_multichip)."""
    from jax.experimental.pallas import tpu as pltpu
    from jax.sharding import Mesh

    from pytorch_ddp_mnist_tpu.train.scan import make_dp_run_fn

    n = 4
    if jax.device_count() < n:
        pytest.skip(f"needs {n} devices")
    mesh = Mesh(np.array(jax.devices()[:n]), ("dp",))
    E, S, B = 2, 2, 8
    G = S * B * n
    x_all, y_all = _data(G, seed=3)
    idxs = jnp.arange(E * S * B * n, dtype=jnp.int32).reshape(
        E, S, B * n) % G
    run = make_dp_run_fn(mesh, lr=0.05, kernel="pallas_epoch",
                         interpret=pltpu.InterpretParams(), snapshots=True)
    p2, _, losses, (p_snaps, _) = run(
        init_mlp(jax.random.key(0)), jax.random.key(9), x_all, y_all, idxs)
    losses = np.asarray(losses)
    assert losses.shape == (E, S) and np.isfinite(losses).all()
    # training moved the weights, and the per-epoch snapshots end at the
    # final params
    assert not np.allclose(np.asarray(p2["fc1"]["w"]),
                           np.asarray(init_mlp(jax.random.key(0))["fc1"]["w"]))
    for leaf, snap in zip(jax.tree_util.tree_leaves(p2),
                          jax.tree_util.tree_leaves(p_snaps)):
        np.testing.assert_array_equal(np.asarray(leaf),
                                      np.asarray(snap)[-1])


@needs_tpu_sim
def test_run_epochal_executes_under_tpu_semantics_simulator():
    """The SCAN-layer wrapper path of the simulator mode: make_run_fn
    (kernel='pallas_epoch', interpret=pltpu.InterpretParams()) must route
    a threefry key to the REAL in-kernel draw under the simulator and
    reproduce the plain-interpreter masked run bit-for-bit — pinning that
    the wrapper actually threads the InterpretParams through (a dropped
    interpret= would attempt a Mosaic compile on CPU and crash)."""
    from jax.experimental.pallas import tpu as pltpu

    from pytorch_ddp_mnist_tpu.train.scan import make_run_fn

    S, B = 2, 16
    x_all, y_all = _data(S * B, seed=13)
    idxs = jnp.arange(S * B, dtype=jnp.int32).reshape(1, S, B)
    run_sim = make_run_fn(0.05, kernel="pallas_epoch",
                          interpret=pltpu.InterpretParams())
    p_sim, _, l_sim = run_sim(init_mlp(jax.random.key(0)),
                              jax.random.key(7), x_all, y_all, idxs)
    run_mk = make_run_fn(0.05, kernel="pallas_epoch", interpret=True)
    p_mk, _, l_mk = run_mk(init_mlp(jax.random.key(0)),
                           jax.random.key(7), x_all, y_all, idxs)
    np.testing.assert_array_equal(np.asarray(l_sim), np.asarray(l_mk))
    for a, b in zip(jax.tree_util.tree_leaves(p_sim),
                    jax.tree_util.tree_leaves(p_mk)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
