"""REAL multi-process parity of the DDP gradient-communication strategies —
the acceptance pin of the comms-efficient DDP PR, at the reference's own
cluster stand-in size (4 processes, one CPU device each; the
tests/test_multiprocess.py pattern).

Each run is a 4-process jax.distributed world training
mp_comm_worker.HPARAMS["steps"] steps through one strategy; rank 0 saves
the final params. The ladder:

  * pmean vs pmean     — BITWISE identical (exact DDP semantics are
    deterministic across whole re-runs of the world);
  * sharded vs pmean   — allclose at rtol 1e-6 (same mean gradient through
    a reduce-scatter tree instead of an allreduce; f32 reduction-order
    tolerance) — the acceptance criterion;
  * bf16 vs pmean      — drift bounded by the cast-error envelope
    (lr * 2^-8-relative per step — pinned well below any wrong-mean bug);
  * int8 vs pmean      — drift bounded by the block-quantization envelope
    (error feedback keeps it from compounding);
  * pmean+overlap      — allclose at rtol 1e-6 (bucket-pipelining is pure
    scheduling; the per-element math is unchanged).

Every rank must also agree with every other rank within one run (replica
lockstep — the strategies' all-gather/psum outputs are truly replicated).
"""

import json
import os
import sys

import numpy as np
import pytest

import jax

# Same capability gate as test_multiprocess.py: CPU-backend cross-process
# collectives need jax >= 0.5.
_JAX_V = tuple(int(x) for x in jax.__version__.split(".")[:2])
pytestmark = pytest.mark.skipif(
    _JAX_V < (0, 5),
    reason="this jaxlib's CPU backend does not implement multiprocess "
           "collectives (needs jax >= 0.5)")

from test_multiprocess import WORLD, _run_world  # noqa: E402


def _run_comm(comm: str, save_path, overlap: bool = False) -> tuple:
    """One 4-process world through `comm`; returns (records, leaves)."""
    outs = _run_world(
        [sys.executable, os.path.join("tests", "mp_comm_worker.py"),
         "--comm", comm, "--save", str(save_path)]
        + (["--overlap"] if overlap else []))
    recs = []
    for rank, (_, out, err) in enumerate(outs):
        line = [ln for ln in out.splitlines() if ln.startswith("{")]
        assert line, f"rank {rank} produced no JSON:\n{out}\n{err}"
        recs.append(json.loads(line[-1]))
    recs.sort(key=lambda r: r["rank"])
    assert [r["rank"] for r in recs] == list(range(WORLD))
    # replica lockstep within the run: identical curve + checksum on
    # every rank, whatever the strategy
    for r in recs[1:]:
        np.testing.assert_allclose(recs[0]["losses"], r["losses"],
                                   rtol=0, atol=0)
        assert recs[0]["checksum"] == r["checksum"]
    z = np.load(save_path)
    leaves = [z[k] for k in sorted(z.files,
                                   key=lambda s: int(s[len("leaf"):]))]
    return recs, leaves


@pytest.fixture(scope="module")
def comm_runs(tmp_path_factory):
    """All four worlds (pmean twice + sharded + bf16), run once and shared
    by the assertions below — each world is 4 fresh interpreters."""
    d = tmp_path_factory.mktemp("mp_comm")
    runs = {}
    runs["pmean"] = _run_comm("pmean", d / "pmean.npz")
    runs["pmean2"] = _run_comm("pmean", d / "pmean2.npz")
    runs["sharded"] = _run_comm("sharded", d / "sharded.npz")
    runs["bf16"] = _run_comm("bf16", d / "bf16.npz")
    runs["int8"] = _run_comm("int8", d / "int8.npz")
    runs["pmean_ov"] = _run_comm("pmean", d / "pmean_ov.npz", overlap=True)
    return runs


def test_pmean_rerun_is_bitwise(comm_runs):
    _, a = comm_runs["pmean"]
    _, b = comm_runs["pmean2"]
    for u, v in zip(a, b):
        np.testing.assert_array_equal(u, v)


def test_sharded_matches_pmean_rtol_1e6(comm_runs):
    recs_ref, ref = comm_runs["pmean"]
    recs_sh, sh = comm_runs["sharded"]
    np.testing.assert_allclose(recs_sh[0]["losses"], recs_ref[0]["losses"],
                               rtol=1e-6)
    for u, v in zip(sh, ref):
        np.testing.assert_allclose(u, v, rtol=1e-6, atol=1e-7)


def test_bf16_drift_bounded(comm_runs):
    _, ref = comm_runs["pmean"]
    _, bf = comm_runs["bf16"]
    worst = max(float(np.max(np.abs(u - v))) for u, v in zip(bf, ref))
    assert worst < 1e-4, worst


def test_int8_drift_bounded(comm_runs):
    """int8 error-feedback quantized allreduce across REAL process
    boundaries (the all_to_all/all_gather phases cross the wire): bounded
    drift vs the pmean world — same envelope as the in-process pin."""
    _, ref = comm_runs["pmean"]
    _, q = comm_runs["int8"]
    worst = max(float(np.max(np.abs(u - v))) for u, v in zip(q, ref))
    assert 0 < worst < 1e-3, worst


def test_pmean_overlap_matches_pmean(comm_runs):
    """Bucket-pipelining is pure scheduling: the overlapped pmean world
    stays within f32 reassociation tolerance of the whole-tree one."""
    _, ref = comm_runs["pmean"]
    _, ov = comm_runs["pmean_ov"]
    for u, v in zip(ov, ref):
        np.testing.assert_allclose(u, v, rtol=1e-6, atol=1e-7)
