"""NetCDF CDF-5 subset: round trips, format bytes, converter CLI, and the
reference schema (mnist_to_netcdf.ipynb: dims Y/X/idx, NC_UBYTE vars)."""

import numpy as np
import pytest

from pytorch_ddp_mnist_tpu.data.netcdf import (
    NetCDFReader, write_netcdf, write_mnist_netcdf, read_mnist_netcdf,
    NC_UBYTE)
from pytorch_ddp_mnist_tpu.data import synthetic_mnist, write_idx
from pytorch_ddp_mnist_tpu.data.convert import convert


@pytest.fixture
def mnist_nc(tmp_path):
    split = synthetic_mnist(50, seed=0)
    path = str(tmp_path / "mnist_train_images.nc")
    write_mnist_netcdf(path, split.images, split.labels)
    return path, split


def test_cdf5_magic_and_schema(mnist_nc):
    path, split = mnist_nc
    with open(path, "rb") as f:
        assert f.read(4) == b"CDF\x05"  # 64BIT_DATA, as PnetCDF writes
    r = NetCDFReader(path)
    assert r.dimensions == {"Y": 28, "X": 28, "idx": 50}
    assert r.variables["images"].shape == (50, 28, 28)
    assert r.variables["images"].nc_type == NC_UBYTE
    assert r.variables["labels"].shape == (50,)


def test_round_trip_whole(mnist_nc):
    path, split = mnist_nc
    images, labels = read_mnist_netcdf(path)
    np.testing.assert_array_equal(images, split.images)
    np.testing.assert_array_equal(labels, split.labels)


def test_row_gather_matches_independent_reads(mnist_nc):
    """The per-sample access pattern of mnist_pnetcdf_cpu_mp.py:46 (each rank
    reads only its sampler's indices)."""
    path, split = mnist_nc
    idx = [3, 47, 0, 11, 11]
    images, labels = read_mnist_netcdf(path, idx)
    np.testing.assert_array_equal(images, split.images[idx])
    np.testing.assert_array_equal(labels, split.labels[idx])
    with pytest.raises(IndexError):
        read_mnist_netcdf(path, [50])


@pytest.mark.parametrize("version", [1, 2, 5])
def test_versions_and_dtypes(tmp_path, version):
    path = str(tmp_path / f"v{version}.nc")
    rng = np.random.default_rng(0)
    f32 = rng.normal(size=(4, 6)).astype(np.float32)
    i32 = rng.integers(-5, 5, size=(6,)).astype(np.int32)
    write_netcdf(path, {"a": 4, "b": 6},
                 {"f": (("a", "b"), f32), "i": (("b",), i32)},
                 version=version)
    with open(path, "rb") as fh:
        assert fh.read(4) == b"CDF" + bytes([version])
    r = NetCDFReader(path)
    np.testing.assert_array_equal(r.read("f"), f32)
    np.testing.assert_array_equal(r.read("i"), i32)


def test_vsize_padding_odd_rows(tmp_path):
    # labels of odd length exercise the 4-byte vsize pad between variables
    path = str(tmp_path / "odd.nc")
    lab = np.arange(7, dtype=np.uint8)
    img = np.arange(7 * 3 * 3, dtype=np.uint8).reshape(7, 3, 3)
    write_netcdf(path, {"Y": 3, "X": 3, "idx": 7},
                 {"labels": (("idx",), lab),
                  "images": (("idx", "Y", "X"), img)})
    r = NetCDFReader(path)
    np.testing.assert_array_equal(r.read("labels"), lab)
    np.testing.assert_array_equal(r.read("images"), img)


def test_converter_cli_from_idx(tmp_path):
    split = synthetic_mnist(20, seed=2)
    test_split = synthetic_mnist(8, seed=3)
    write_idx(str(tmp_path / "train-images-idx3-ubyte"), split.images)
    write_idx(str(tmp_path / "train-labels-idx1-ubyte"), split.labels)
    write_idx(str(tmp_path / "t10k-images-idx3-ubyte"), test_split.images)
    write_idx(str(tmp_path / "t10k-labels-idx1-ubyte"), test_split.labels)
    out = convert(str(tmp_path), str(tmp_path / "nc"))
    images, labels = read_mnist_netcdf(out[0])
    np.testing.assert_array_equal(images, split.images)
    np.testing.assert_array_equal(labels, split.labels)
    images, labels = read_mnist_netcdf(out[1])
    np.testing.assert_array_equal(images, test_split.images)
    np.testing.assert_array_equal(labels, test_split.labels)


def test_converter_cli_synthetic(tmp_path):
    out = convert("unused", str(tmp_path), synthetic="30:10")
    r = NetCDFReader(out[0])
    assert r.dimensions["idx"] == 30
    r = NetCDFReader(out[1])
    assert r.dimensions["idx"] == 10


def test_converter_missing_idx_errors(tmp_path):
    with pytest.raises(FileNotFoundError, match="no IDX files"):
        convert(str(tmp_path), str(tmp_path))


def test_netcdf_shard_loader_matches_in_memory(tmp_path):
    """Disk-sharded batches must equal the in-memory BatchLoader's batches
    for the same sampler state (same shard, same order, same transform)."""
    from pytorch_ddp_mnist_tpu.data import BatchLoader, normalize_images
    from pytorch_ddp_mnist_tpu.data.loader import NetCDFShardLoader
    from pytorch_ddp_mnist_tpu.parallel import ShardedSampler

    split = synthetic_mnist(100, seed=7)
    path = str(tmp_path / "m.nc")
    write_mnist_netcdf(path, split.images, split.labels)

    s1 = ShardedSampler(100, num_replicas=4, rank=1, seed=42)
    s2 = ShardedSampler(100, num_replicas=4, rank=1, seed=42)
    s1.set_epoch(2)
    s2.set_epoch(2)
    mem = BatchLoader(normalize_images(split.images), split.labels, s1,
                      batch_size=8)
    disk = NetCDFShardLoader(path, s2, batch_size=8)
    assert len(mem) == len(disk)
    for (mx, my), (dx, dy) in zip(mem, disk):
        np.testing.assert_allclose(mx, dx, rtol=1e-6)
        np.testing.assert_array_equal(my, dy)
        assert dy.dtype == np.int32


def test_netcdf_shard_loader_readahead_parity(tmp_path):
    """num_workers>0 must yield bit-identical batches in identical order to
    the synchronous path, across epoch reshuffles."""
    from pytorch_ddp_mnist_tpu.data.loader import NetCDFShardLoader
    from pytorch_ddp_mnist_tpu.parallel import ShardedSampler

    split = synthetic_mnist(200, seed=3)
    path = str(tmp_path / "m.nc")
    write_mnist_netcdf(path, split.images, split.labels)
    sync = NetCDFShardLoader(path, batch_size=16, num_workers=0)
    ahead = NetCDFShardLoader(path, batch_size=16, num_workers=3)
    for ldr in (sync, ahead):
        ldr.sampler = ShardedSampler(200, num_replicas=1, rank=0, seed=42)
    for epoch in (0, 1):
        sync.sampler.set_epoch(epoch)
        ahead.sampler.set_epoch(epoch)
        pairs = list(zip(sync, ahead))
        assert len(pairs) == len(sync)
        for (sx, sy), (ax, ay) in pairs:
            np.testing.assert_array_equal(sx, ax)
            np.testing.assert_array_equal(sy, ay)


def test_netcdf_shard_loader_iter_from_skips_disk_reads(tmp_path):
    """iter_from(n) drops skipped batches BEFORE any disk gather (both the
    sync path and the readahead workers), and yields the exact tail."""
    from pytorch_ddp_mnist_tpu.data.loader import NetCDFShardLoader
    from pytorch_ddp_mnist_tpu.parallel import ShardedSampler

    split = synthetic_mnist(200, seed=3)
    path = str(tmp_path / "m.nc")
    write_mnist_netcdf(path, split.images, split.labels)
    for nw in (0, 2):
        ldr = NetCDFShardLoader(path, batch_size=16, num_workers=nw)
        ldr.sampler = ShardedSampler(200, num_replicas=1, rank=0, seed=42)
        full = list(ldr)
        loads = []
        orig = ldr._load
        ldr._load = lambda b: loads.append(len(b)) or orig(b)
        tail = list(ldr.iter_from(10))
        assert len(tail) == len(full) - 10
        assert len(loads) == len(tail)     # skipped batches never loaded
        for (fx, fy), (tx, ty) in zip(full[10:], tail):
            np.testing.assert_array_equal(fx, tx)
            np.testing.assert_array_equal(fy, ty)


def test_netcdf_shard_loader_readahead_overlaps(tmp_path):
    """With a busy consumer, readahead workers hide the load time: the
    overlapped run must beat the synchronous run (VERDICT r1 item 4
    done-condition). Sleeps release the GIL, so even a 1-CPU host overlaps."""
    import time
    from pytorch_ddp_mnist_tpu.data.loader import NetCDFShardLoader
    from pytorch_ddp_mnist_tpu.parallel import ShardedSampler

    split = synthetic_mnist(160, seed=5)
    path = str(tmp_path / "m.nc")
    write_mnist_netcdf(path, split.images, split.labels)
    delay = 0.02

    def timed_run(nw):
        ldr = NetCDFShardLoader(path, batch_size=16, num_workers=nw)
        ldr.sampler = ShardedSampler(160, num_replicas=1, rank=0, seed=42)
        ldr.sampler.set_epoch(0)
        orig = ldr._load
        ldr._load = lambda b: (time.sleep(delay), orig(b))[1]  # slow "disk"
        t0 = time.perf_counter()
        n = 0
        for x, y in ldr:
            time.sleep(delay)  # busy "train step"
            n += 1
        assert n == 10
        return time.perf_counter() - t0

    t_sync = timed_run(0)       # ~10*(delay_load + delay_step) = 0.4s
    t_overlap = timed_run(2)    # loads hidden behind steps: ~0.2s + slack
    assert t_overlap < 0.8 * t_sync, (t_sync, t_overlap)


def test_netcdf_shard_loader_worker_exception_propagates(tmp_path):
    from pytorch_ddp_mnist_tpu.data.loader import NetCDFShardLoader
    from pytorch_ddp_mnist_tpu.parallel import ShardedSampler

    split = synthetic_mnist(64, seed=9)
    path = str(tmp_path / "m.nc")
    write_mnist_netcdf(path, split.images, split.labels)
    ldr = NetCDFShardLoader(path, batch_size=16, num_workers=2)
    ldr.sampler = ShardedSampler(64, num_replicas=1, rank=0, seed=42)
    ldr.sampler.set_epoch(0)

    def boom(b):
        raise RuntimeError("disk exploded")

    ldr._load = boom
    with pytest.raises(RuntimeError, match="disk exploded"):
        list(ldr)
