"""serve/ request path on the virtual CPU mesh: bucketing/padding
correctness, batcher coalescing under a fake clock, admission backpressure
and graceful drain, metrics snapshot schema, and the two acceptance
invariants — served results bitwise-equal to a direct engine forward, and
zero compiles after warmup (structural: serving only ever calls the AOT
executables compiled at construction)."""

import asyncio

import numpy as np
import pytest
import jax

from pytorch_ddp_mnist_tpu.models import init_mlp
from pytorch_ddp_mnist_tpu.serve import (AdmissionController, InferenceEngine,
                                         MicroBatcher, Rejected, ServeMetrics,
                                         ServeService, bucket_ladder)
from pytorch_ddp_mnist_tpu.serve.loadgen import request_rows, run_loadgen


@pytest.fixture(scope="module")
def params():
    return init_mlp(jax.random.key(0))


@pytest.fixture(scope="module")
def engine(params):
    return InferenceEngine(params, max_batch=16)


# ---------------------------------------------------------------------------
# engine: bucket ladder, padding, compile accounting
# ---------------------------------------------------------------------------

def test_bucket_ladder():
    assert bucket_ladder(16) == (1, 2, 4, 8, 16)
    assert bucket_ladder(1) == (1,)
    # a non-power-of-two cap is always its own top rung
    assert bucket_ladder(12) == (1, 2, 4, 8, 12)
    # mesh-constrained ladders only hold multiples of the device count
    assert bucket_ladder(32, 8) == (8, 16, 32)
    with pytest.raises(ValueError, match="multiple"):
        bucket_ladder(12, 8)
    with pytest.raises(ValueError, match="max_batch"):
        bucket_ladder(0)


def test_bucket_for_smallest_fit(engine):
    assert engine.bucket_for(1) == 1
    assert engine.bucket_for(3) == 4
    assert engine.bucket_for(8) == 8
    assert engine.bucket_for(9) == 16
    with pytest.raises(ValueError, match="largest bucket"):
        engine.bucket_for(17)


def test_warmup_compiles_once_per_bucket_then_never(engine):
    assert engine.compile_count == len(engine.buckets) == 5
    x = request_rows(23, seed=3)          # chunks: 16 + 7 -> buckets 16, 8
    for _ in range(3):
        engine.predict(x)
        engine.forward(x[:5])
    # serving touched several shapes and sizes beyond the ladder; the
    # engine still holds exactly the warmup executables — a shape that
    # missed the ladder would have raised, not compiled
    assert engine.compile_count == len(engine.buckets)


def test_padding_is_inert(engine):
    """Rows answered identically whether padded a little (bucket 4) or
    arriving at exactly their own bucket — for the same bucket the padded
    program IS the unpadded program, bitwise."""
    x = request_rows(4, seed=1)
    whole = engine.forward(x)
    # 3 rows pad into bucket 4: the same executable, same leading rows
    np.testing.assert_array_equal(engine.forward(x[:3]), whole[:3])


def test_forward_chunks_large_batches(engine):
    x = request_rows(40, seed=2)          # > max_batch=16: 3 chunks
    out = engine.forward(x)
    assert out.shape == (40, 10)
    np.testing.assert_array_equal(out[:16], engine.forward(x[:16]))


def test_input_validation(engine):
    with pytest.raises(ValueError, match="784"):
        engine.forward(np.zeros((2, 100), np.float32))
    with pytest.raises(ValueError, match="input_dtype"):
        InferenceEngine(init_mlp(jax.random.key(0)), max_batch=1,
                        input_dtype="int64")


def test_uint8_engine_normalizes_on_device(params):
    """A uint8 engine's logits match the f32 engine fed host-normalized
    pixels (same device_normalize chain as training/eval)."""
    from pytorch_ddp_mnist_tpu.data import normalize_images
    eng8 = InferenceEngine(params, max_batch=4, input_dtype="uint8")
    engf = InferenceEngine(params, max_batch=4)
    raw = request_rows(4, dtype="uint8", seed=5)
    normed = normalize_images(raw.reshape(4, 28, 28)).astype(np.float32)
    np.testing.assert_allclose(eng8.forward(raw), engf.forward(normed),
                               rtol=1e-6, atol=1e-6)


def test_mesh_replicated_engine_matches_serial(params):
    """8-virtual-device data-parallel engine: sharded buckets, identical
    logits to the single-device engine."""
    from pytorch_ddp_mnist_tpu.parallel import data_parallel_mesh
    mesh = data_parallel_mesh()
    assert mesh.devices.size == 8     # conftest's virtual CPU mesh
    dp = InferenceEngine(params, max_batch=32, mesh=mesh)
    assert dp.buckets == (8, 16, 32)
    serial = InferenceEngine(params, max_batch=32)
    x = request_rows(20, seed=7)
    np.testing.assert_allclose(dp.forward(x), serial.forward(x),
                               rtol=1e-6, atol=1e-6)


def test_checkpoint_round_trip(tmp_path, params):
    from pytorch_ddp_mnist_tpu.train.checkpoint import save_checkpoint
    path = str(tmp_path / "m.msgpack")
    save_checkpoint(path, params)
    eng = InferenceEngine.from_checkpoint(path, max_batch=4)
    ref = InferenceEngine(params, max_batch=4)
    x = request_rows(4, seed=9)
    np.testing.assert_array_equal(eng.forward(x), ref.forward(x))


# ---------------------------------------------------------------------------
# batcher: coalescing under a fake clock
# ---------------------------------------------------------------------------

class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _RecordingEngine:
    """Engine wrapper that records every flush's real-row count."""

    def __init__(self, engine):
        self._engine = engine
        self.max_batch = engine.max_batch
        self.calls = []

    def _as_rows(self, x):
        return self._engine._as_rows(x)

    def _run_bucket(self, x):
        self.calls.append(x.shape[0])
        return self._engine._run_bucket(x)


def test_batcher_coalesces_to_one_engine_call(engine):
    clock = _FakeClock()
    rec = _RecordingEngine(engine)

    async def scenario():
        b = MicroBatcher(rec, max_batch=8, max_delay_ms=1000.0, clock=clock)
        subs = [asyncio.ensure_future(b.submit(row))
                for row in request_rows(3, seed=11)]
        await asyncio.sleep(0)            # let submits enqueue
        assert b.depth == 3 and rec.calls == []   # deadline far: no flush
        assert b.flush() == 3
        return await asyncio.gather(*subs)

    preds = asyncio.run(scenario())
    assert rec.calls == [3]               # ONE engine call for 3 requests
    assert all(isinstance(p, int) for p in preds)


def test_batcher_deadline_decision_is_pure(engine):
    clock = _FakeClock()

    async def scenario():
        b = MicroBatcher(engine, max_batch=8, max_delay_ms=5.0, clock=clock)
        assert not b.flush_due(clock())           # empty: never due
        fut = asyncio.ensure_future(b.submit(request_rows(1, seed=12)[0]))
        await asyncio.sleep(0)
        assert not b.flush_due(clock())           # fresh: not due yet
        clock.t += 0.0049
        assert not b.flush_due(clock())
        clock.t += 0.0002                         # past the 5 ms deadline
        assert b.flush_due(clock())
        b.flush()
        return await fut

    assert isinstance(asyncio.run(scenario()), int)


def test_batcher_full_batch_flushes_immediately(engine):
    rec = _RecordingEngine(engine)

    async def scenario():
        b = MicroBatcher(rec, max_batch=4, max_delay_ms=1000.0)
        subs = [asyncio.ensure_future(b.submit(row))
                for row in request_rows(4, seed=13)]
        await asyncio.sleep(0)            # 4th submit hits max_batch
        assert rec.calls == [4] and b.depth == 0
        return await asyncio.gather(*subs)

    asyncio.run(scenario())


def test_served_batch_bitwise_equals_direct_forward(engine):
    """Acceptance: predictions through the coalescing path == a direct
    engine pass on the same stacked inputs, bitwise."""
    rows = request_rows(6, seed=14)

    async def scenario():
        b = MicroBatcher(engine, max_batch=8, max_delay_ms=1000.0)
        subs = [asyncio.ensure_future(b.submit(r)) for r in rows]
        await asyncio.sleep(0)
        b.flush()                         # one coalesced bucket-8 call
        return await asyncio.gather(*subs)

    served = np.asarray(asyncio.run(scenario()), np.int32)
    direct = engine.predict(rows)         # same rows -> same bucket 8
    np.testing.assert_array_equal(served, direct)


# ---------------------------------------------------------------------------
# admission: backpressure + drain
# ---------------------------------------------------------------------------

def test_admission_rejects_past_budget_with_retry_after():
    adm = AdmissionController(2, retry_after_s=0.25)
    adm.admit()
    adm.admit()
    with pytest.raises(Rejected) as e:
        adm.admit()
    assert e.value.retry_after_s == 0.25
    assert adm.rejected == 1 and adm.depth == 2
    adm.release()
    adm.admit()                           # slot freed: admitted again
    assert adm.admitted == 3


def test_admission_graceful_drain():
    async def scenario():
        adm = AdmissionController(8)
        adm.admit()
        adm.admit()
        waiter = asyncio.ensure_future(adm.drained())
        await asyncio.sleep(0)
        assert not waiter.done()          # two in flight: still draining
        with pytest.raises(Rejected, match="draining"):
            adm.admit()                   # door closed during drain
        adm.release()
        adm.release()
        await asyncio.wait_for(waiter, 1.0)

    asyncio.run(scenario())


def test_service_backpressure_and_drain(engine):
    """Full path under overload: a tiny queue budget forces rejects while
    admitted requests all complete through the drain."""
    svc = ServeService(engine, max_delay_ms=50.0, max_depth=2)
    rows = request_rows(6, seed=15)

    async def scenario():
        results = await asyncio.gather(
            *[svc.handle(r) for r in rows], return_exceptions=True)
        await svc.shutdown()
        return results

    results = asyncio.run(scenario())
    served = [r for r in results if isinstance(r, int)]
    rejected = [r for r in results if isinstance(r, Rejected)]
    assert len(served) == 2 and len(rejected) == 4
    snap = svc.metrics.snapshot()
    assert snap["completed"] == 2 and snap["rejected"] == 4
    assert snap["reject_rate"] == pytest.approx(4 / 6, abs=1e-4)
    with pytest.raises(Rejected, match="draining"):
        asyncio.run(svc.handle(rows[0]))  # drained service stays closed


def test_malformed_row_rejected_at_submit_not_poisoning_batch(engine):
    """A ragged row raises synchronously to ITS caller; pending well-formed
    requests in the same flush window still serve, and no admission slot
    leaks (the review-found hang: np.stack of ragged rows after the pending
    swap stranded every waiter)."""
    svc = ServeService(engine, max_delay_ms=1000.0, max_depth=8)
    good = request_rows(2, seed=21)

    async def scenario():
        tasks = [asyncio.ensure_future(svc.handle(r)) for r in good]
        bad = asyncio.ensure_future(svc.handle(np.zeros(783, np.float32)))
        await asyncio.sleep(0)
        svc.batcher.flush()
        results = await asyncio.gather(*tasks, bad, return_exceptions=True)
        await svc.shutdown()            # must not deadlock on leaked slots
        return results

    r0, r1, rbad = asyncio.run(scenario())
    assert isinstance(r0, int) and isinstance(r1, int)
    assert isinstance(rbad, ValueError) and "783" in str(rbad)
    snap = svc.metrics.snapshot()
    assert snap["completed"] == 2 and snap["failed"] == 1
    assert snap["requests"] == 3        # the errored request still counted
    assert snap["queue_depth"] == 0     # its admission slot was released


# ---------------------------------------------------------------------------
# metrics: snapshot schema
# ---------------------------------------------------------------------------

def test_metrics_snapshot_schema():
    m = ServeMetrics(depth_fn=lambda: 3)
    for ms in (1.0, 2.0, 5.0, 100.0):
        m.record_arrival()
        m.record_done(ms / 1e3)
    m.record_reject()
    m.record_batch(3, 4)
    snap = m.snapshot()
    assert set(snap) == {"requests", "completed", "rejected", "failed",
                         "reject_rate", "achieved_rps", "latency_ms",
                         "batches", "batch_occupancy", "mean_batch_size",
                         "queue_depth", "slo", "attribution"}
    # the rolling SLO view rides along: exact-window percentiles + the
    # observed service rate (what SLO-aware admission will consume)
    assert set(snap["slo"]) == {"window_n", "rolling_p50_ms",
                                "rolling_p99_ms", "service_rate_rps"}
    assert snap["slo"]["window_n"] == 4
    assert snap["slo"]["rolling_p99_ms"] == pytest.approx(100.0, rel=1e-6)
    # request-scoped attribution rides along too: per-stage p50/p99 under
    # the tracing stage names + the predicted-p99 admission signal
    assert set(snap["attribution"]) == {"stages", "predicted_p99_ms"}
    assert snap["attribution"]["predicted_p99_ms"] is not None
    assert snap["requests"] == 5 and snap["completed"] == 4
    assert snap["reject_rate"] == 0.2
    assert snap["queue_depth"] == 3
    assert snap["batch_occupancy"] == 0.75
    lat = snap["latency_ms"]
    assert set(lat) == {"p50", "p95", "p99", "mean", "max"}
    assert 0 < lat["p50"] <= lat["p95"] <= lat["p99"] <= lat["max"]
    # log-bucket estimate stays within the bucket ratio of the true value
    assert lat["p50"] == pytest.approx(2.0, rel=0.25)
    assert lat["max"] == pytest.approx(100.0, rel=1e-6)
    import json
    json.dumps(snap)                      # snapshot is JSON-able verbatim


def test_histogram_percentiles_clamped_to_max():
    from pytorch_ddp_mnist_tpu.serve.metrics import LatencyHistogram
    h = LatencyHistogram()
    assert h.percentile(0.99) == 0.0      # empty
    h.record(0.010)
    # single sample: every percentile is that sample, not a bucket edge
    assert h.percentile(0.5) == h.percentile(0.99) == 0.010


# ---------------------------------------------------------------------------
# loadgen + end-to-end
# ---------------------------------------------------------------------------

def test_loadgen_deterministic_and_complete(engine):
    svc = ServeService(engine, max_delay_ms=2.0, max_depth=64)
    out = run_loadgen(svc, offered_rps=2000.0, n_requests=50, seed=42)
    assert out["n_requests"] == 50
    assert out["completed"] + out["rejected"] == 50
    assert all(p is None or 0 <= p <= 9 for p in out["predictions"])
    # engine never compiled past warmup under open-loop load
    assert engine.compile_count == len(engine.buckets)


@pytest.mark.slow
def test_loadgen_soak_overload_saturates_not_collapses():
    """Soak: offered load far past capacity must saturate into rejects
    with bounded admitted-latency, not queue without bound."""
    eng = InferenceEngine(init_mlp(jax.random.key(0)), max_batch=32)
    svc = ServeService(eng, max_delay_ms=1.0, max_depth=64,
                       retry_after_s=0.01)
    out = run_loadgen(svc, offered_rps=20000.0, n_requests=4000, seed=1)
    assert out["completed"] + out["rejected"] == 4000
    assert out["queue_depth"] == 0                 # drained clean
    assert eng.compile_count == len(eng.buckets)   # soak never compiled


def test_cli_serve_selftest_subprocess(tmp_path):
    """The `python -m pytorch_ddp_mnist_tpu serve --selftest` front door:
    full path in a fresh interpreter, one JSON metrics line on stdout."""
    import json
    import os
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    out = subprocess.run(
        [sys.executable, "-m", "pytorch_ddp_mnist_tpu", "serve",
         "--selftest", "80", "--offered_rps", "2000", "--max_batch", "16"],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1
    snap = json.loads(lines[0])
    assert snap["completed"] + snap["rejected"] == 80
    assert {"p50", "p95", "p99"} <= set(snap["latency_ms"])
    assert "compiles=5" in out.stderr     # bucket ladder 1..16 warmed once
