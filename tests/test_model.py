"""Model parity tests vs reference §2.6 (MLP 784-128-128-10)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_mnist_tpu.models import init_mlp, mlp_apply, param_count


@pytest.fixture(scope="module")
def params():
    return init_mlp(jax.random.key(0))


def test_param_count_matches_reference(params):
    # 784*128 + 128 + 128*128 + 128 + 128*10 = 118,272 (BASELINE.md)
    assert param_count(params) == 118_272


def test_output_layer_has_no_bias(params):
    assert "b" not in params["fc3"]
    assert params["fc3"]["w"].shape == (128, 10)


def test_init_bounds_match_torch_linear(params):
    # torch Linear: weight, bias ~ U(-1/sqrt(fan_in), 1/sqrt(fan_in))
    for name, fan_in in (("fc1", 784), ("fc2", 128), ("fc3", 128)):
        bound = 1.0 / np.sqrt(fan_in)
        w = np.asarray(params[name]["w"])
        assert np.abs(w).max() <= bound
        # Distribution sanity: spread should fill a good part of the range.
        assert np.abs(w).max() > 0.8 * bound


def test_forward_shape_and_determinism(params):
    x = jnp.ones((4, 784))
    out1 = mlp_apply(params, x)
    out2 = mlp_apply(params, x)
    assert out1.shape == (4, 10)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_dropout_only_in_train_mode(params):
    x = jnp.ones((8, 784))
    eval_out = mlp_apply(params, x, train=False)
    k1, k2 = jax.random.key(1), jax.random.key(2)
    t1 = mlp_apply(params, x, train=True, dropout_key=k1)
    t2 = mlp_apply(params, x, train=True, dropout_key=k2)
    # train-mode outputs vary with the dropout key; eval does not use one
    assert not np.allclose(np.asarray(t1), np.asarray(t2))
    assert np.all(np.isfinite(np.asarray(eval_out)))
    with pytest.raises(ValueError):
        mlp_apply(params, x, train=True)


def test_train_eval_expectation_consistent(params):
    # Inverted dropout: E[train output] ~= eval output. Average many keys.
    x = jax.random.normal(jax.random.key(3), (16, 784))
    eval_out = np.asarray(mlp_apply(params, x, train=False))
    outs = [np.asarray(mlp_apply(params, x, train=True,
                                 dropout_key=jax.random.key(100 + i)))
            for i in range(200)]
    mean_out = np.mean(outs, axis=0)
    np.testing.assert_allclose(mean_out, eval_out, atol=0.25)
