"""Model parity tests vs reference §2.6 (MLP 784-128-128-10)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_mnist_tpu.models import init_mlp, mlp_apply, param_count


@pytest.fixture(scope="module")
def params():
    return init_mlp(jax.random.key(0))


def test_param_count_matches_reference(params):
    # 784*128 + 128 + 128*128 + 128 + 128*10 = 118,272 (BASELINE.md)
    assert param_count(params) == 118_272


def test_output_layer_has_no_bias(params):
    assert "b" not in params["fc3"]
    assert params["fc3"]["w"].shape == (128, 10)


def test_init_bounds_match_torch_linear(params):
    # torch Linear: weight, bias ~ U(-1/sqrt(fan_in), 1/sqrt(fan_in))
    for name, fan_in in (("fc1", 784), ("fc2", 128), ("fc3", 128)):
        bound = 1.0 / np.sqrt(fan_in)
        w = np.asarray(params[name]["w"])
        assert np.abs(w).max() <= bound
        # Distribution sanity: spread should fill a good part of the range.
        assert np.abs(w).max() > 0.8 * bound


def test_forward_shape_and_determinism(params):
    x = jnp.ones((4, 784))
    out1 = mlp_apply(params, x)
    out2 = mlp_apply(params, x)
    assert out1.shape == (4, 10)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_dropout_only_in_train_mode(params):
    x = jnp.ones((8, 784))
    eval_out = mlp_apply(params, x, train=False)
    k1, k2 = jax.random.key(1), jax.random.key(2)
    t1 = mlp_apply(params, x, train=True, dropout_key=k1)
    t2 = mlp_apply(params, x, train=True, dropout_key=k2)
    # train-mode outputs vary with the dropout key; eval does not use one
    assert not np.allclose(np.asarray(t1), np.asarray(t2))
    assert np.all(np.isfinite(np.asarray(eval_out)))
    with pytest.raises(ValueError):
        mlp_apply(params, x, train=True)


def test_train_eval_expectation_consistent(params):
    # Inverted dropout: E[train output] ~= eval output. Average many keys.
    x = jax.random.normal(jax.random.key(3), (16, 784))
    eval_out = np.asarray(mlp_apply(params, x, train=False))
    outs = [np.asarray(mlp_apply(params, x, train=True,
                                 dropout_key=jax.random.key(100 + i)))
            for i in range(200)]
    mean_out = np.mean(outs, axis=0)
    np.testing.assert_allclose(mean_out, eval_out, atol=0.25)


# ---------------------------------------------------------------------------
# models/zoo.py — the workload-scaling knob (ISSUE 7): every family is the
# same (init, apply) functional pair, and the default IS the reference.
# ---------------------------------------------------------------------------


def test_resolve_model_default_is_reference_identity():
    """resolve_model('mlp', 1) returns the UNTOUCHED reference functions —
    same objects, not wrappers — so every bitwise pin built on
    init_mlp/mlp_apply keeps holding by construction."""
    from pytorch_ddp_mnist_tpu.models import resolve_model

    spec = resolve_model("mlp", 1)
    assert spec.init is init_mlp
    assert spec.apply is mlp_apply
    assert spec.dims == (784, 128, 128, 10)


def test_resolve_model_scales_quadratically():
    from pytorch_ddp_mnist_tpu.models import resolve_model

    p1 = param_count(resolve_model("mlp", 1).init(jax.random.key(0)))
    p8 = param_count(resolve_model("mlp", 8).init(jax.random.key(0)))
    assert p1 == 118_272
    # 784*1024 + 1024 + 1024*1024 + 1024 + 1024*10 = 1,863,680
    assert p8 == 1_863_680
    d4 = resolve_model("deep_mlp", 4).init(jax.random.key(0))
    # 4 hidden layers of width 512, bias-free 10-unit head
    assert set(d4) == {"h0", "h1", "h2", "h3", "out"}
    assert "b" not in d4["out"]
    assert d4["out"]["w"].shape == (512, 10)


@pytest.mark.parametrize("model,scale", [("mlp", 4), ("deep_mlp", 2)])
def test_zoo_apply_contract_matches_mlp_apply(model, scale):
    """Every family honors mlp_apply's exact contract: (n, 784) -> (n, 10),
    deterministic in eval, dropout-key-varying in train, exactly one of
    key/mask required in train mode."""
    from pytorch_ddp_mnist_tpu.models import resolve_model

    spec = resolve_model(model, scale)
    p = spec.init(jax.random.key(0))
    x = jnp.ones((4, 784))
    out = spec.apply(p, x, train=False)
    assert out.shape == (4, 10)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(spec.apply(p, x, train=False)))
    t1 = spec.apply(p, x, train=True, dropout_key=jax.random.key(1))
    t2 = spec.apply(p, x, train=True, dropout_key=jax.random.key(2))
    assert not np.allclose(np.asarray(t1), np.asarray(t2))
    with pytest.raises(ValueError, match="exactly one"):
        spec.apply(p, x, train=True)


def test_validate_model_rejects_by_name():
    from pytorch_ddp_mnist_tpu.models import validate_model

    with pytest.raises(ValueError, match="convnet"):
        validate_model("convnet", 1)
    for bad in (0, -1, "2", 1.5):
        with pytest.raises(ValueError, match="param_scale"):
            validate_model("mlp", bad)


def test_nondefault_model_rejected_on_pallas_kernels():
    from pytorch_ddp_mnist_tpu.train.scan import make_run_fn

    with pytest.raises(ValueError, match="kernel='xla'"):
        make_run_fn(0.01, kernel="pallas", param_scale=2)
