"""MNIST downloader against a local HTTP fixture server.

Capability parity with `datasets.MNIST(download=True)`
(ddp_tutorial_cpu.py:20,31): mirror failover, checksum verification,
structural (IDX magic) validation, atomic writes, warm-cache no-op, and the
get_mnist probe order disk -> download -> synthetic.
"""

import gzip
import hashlib
import http.server
import os
import threading

import numpy as np
import pytest

from pytorch_ddp_mnist_tpu.data.download import (
    DownloadError, FILES, MIRRORS, download_file, download_mnist)
from pytorch_ddp_mnist_tpu.data.idx import write_idx
from pytorch_ddp_mnist_tpu.data.mnist import get_mnist


def _gz_idx_images(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (n, 28, 28)).astype(np.uint8)


def _make_fixtures(dirpath):
    """The four MNIST artifacts, tiny (8 train / 4 test), correctly gzipped
    IDX. Returns {filename: md5}."""
    rng = np.random.default_rng(1)
    arrays = {
        "train-images-idx3-ubyte.gz": _gz_idx_images(8, 0),
        "train-labels-idx1-ubyte.gz": rng.integers(0, 10, 8).astype(np.uint8),
        "t10k-images-idx3-ubyte.gz": _gz_idx_images(4, 1),
        "t10k-labels-idx1-ubyte.gz": rng.integers(0, 10, 4).astype(np.uint8),
    }
    manifest = {}
    for name, arr in arrays.items():
        raw = os.path.join(dirpath, name[:-3])
        write_idx(raw, arr)
        with open(raw, "rb") as f:
            payload = gzip.compress(f.read(), mtime=0)
        os.unlink(raw)
        with open(os.path.join(dirpath, name), "wb") as f:
            f.write(payload)
        manifest[name] = hashlib.md5(payload).hexdigest()
    return manifest


@pytest.fixture()
def mirror(tmp_path):
    """Serve a fixture mirror over localhost HTTP; yields (url, manifest)."""
    docroot = tmp_path / "mirror"
    docroot.mkdir()
    manifest = _make_fixtures(str(docroot))
    handler = lambda *a, **kw: http.server.SimpleHTTPRequestHandler(  # noqa: E731
        *a, directory=str(docroot), **kw)
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield f"http://127.0.0.1:{srv.server_port}/", manifest
    finally:
        srv.shutdown()
        srv.server_close()


def test_download_mnist_end_to_end(mirror, tmp_path, capsys):
    url, manifest = mirror
    dest = tmp_path / "data"
    download_mnist(str(dest), mirrors=[url], files=manifest)
    for name in manifest:
        assert (dest / name).exists()
    # and the standard loader reads what was fetched
    split = get_mnist(str(dest), train=True)
    assert split.images.shape == (8, 28, 28)
    test = get_mnist(str(dest), train=False)
    assert len(test) == 4
    # no synthetic-fallback message was printed
    assert "synthetic" not in capsys.readouterr().out


def test_checksum_mismatch_rejected_then_next_mirror(mirror, tmp_path):
    url, manifest = mirror
    name = "train-images-idx3-ubyte.gz"
    bad = dict(manifest)
    bad[name] = "0" * 32
    with pytest.raises(DownloadError, match="checksum mismatch"):
        download_file(name, str(tmp_path / "d1"), mirrors=[url], md5=bad[name])
    # failover: dead mirror first, good mirror second
    out = download_file(name, str(tmp_path / "d2"),
                        mirrors=["http://127.0.0.1:9/", url],
                        md5=manifest[name])
    assert os.path.exists(out)
    # no .part litter left behind in either dir
    for d in ("d1", "d2"):
        leftovers = [p for p in os.listdir(tmp_path / d)
                     if p.endswith(".part")]
        assert leftovers == []


def test_non_idx_payload_rejected(tmp_path):
    """A mirror serving an HTML error page with HTTP 200 must be refused
    even when no checksum is pinned."""
    # a name with no pinned digest: the structural check is the only defense
    junk_name = "custom-images-idx3-ubyte.gz"
    jroot = tmp_path / "junk"
    jroot.mkdir()
    (jroot / junk_name).write_bytes(gzip.compress(b"<html>404</html>"))
    handler = lambda *a, **kw: http.server.SimpleHTTPRequestHandler(  # noqa: E731
        *a, directory=str(jroot), **kw)
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        with pytest.raises(DownloadError, match="not a gzipped IDX"):
            download_file(junk_name, str(tmp_path / "dst"),
                          mirrors=[f"http://127.0.0.1:{srv.server_port}/"],
                          md5=None)
    finally:
        srv.shutdown()
        srv.server_close()


def test_warm_cache_short_circuits(mirror, tmp_path):
    url, manifest = mirror
    dest = tmp_path / "data"
    name = "t10k-labels-idx1-ubyte.gz"
    download_file(name, str(dest), mirrors=[url], md5=manifest[name])
    mtime = os.path.getmtime(dest / name)
    # second call must not re-fetch (dead mirror list proves no network)
    out = download_file(name, str(dest), mirrors=["http://127.0.0.1:9/"],
                        md5=manifest[name])
    assert out == str(dest / name)
    assert os.path.getmtime(dest / name) == mtime


def test_get_mnist_download_probe_order(mirror, tmp_path, monkeypatch):
    """get_mnist(download=True): disk wins; else fetch; else synthetic."""
    url, manifest = mirror
    import pytorch_ddp_mnist_tpu.data.mnist as mnist_mod
    import pytorch_ddp_mnist_tpu.data.download as dl_mod
    monkeypatch.setattr(dl_mod, "MIRRORS", (url,))
    monkeypatch.setattr(dl_mod, "FILES", manifest)
    # empty dir + download=True -> fetches the fixture artifacts
    split = mnist_mod.get_mnist(str(tmp_path / "a"), train=True,
                                download=True, quiet=True)
    assert split.images.shape == (8, 28, 28)
    # all mirrors dead + download=True -> synthetic fallback, no raise
    monkeypatch.setattr(dl_mod, "MIRRORS", ("http://127.0.0.1:9/",))
    split = mnist_mod.get_mnist(str(tmp_path / "b"), train=False,
                                download=True, quiet=True, synthetic_n=16)
    assert len(split) == 16


def test_cli_train_download_end_to_end(mirror, tmp_path, monkeypatch, capsys):
    """`cli.train --download` fetches real IDX artifacts and trains on them
    (VERDICT r1 missing #1 done-condition, against the fixture mirror)."""
    url, manifest = mirror
    import pytorch_ddp_mnist_tpu.data.download as dl_mod
    monkeypatch.setattr(dl_mod, "MIRRORS", (url,))
    monkeypatch.setattr(dl_mod, "FILES", manifest)
    from pytorch_ddp_mnist_tpu.cli.train import main
    rc = main(["--download", "--path", str(tmp_path / "dl"),
               "--n_epochs", "1", "--batch_size", "4", "--checkpoint", ""])
    assert rc == 0
    out = capsys.readouterr().out
    assert "downloaded train-images-idx3-ubyte.gz" in out
    assert "synthetic" not in out
    assert "Epoch=0" in out


def test_download_module_cli(mirror, tmp_path, monkeypatch):
    """python -m pytorch_ddp_mnist_tpu.data.download --root <dir>"""
    url, manifest = mirror
    import pytorch_ddp_mnist_tpu.data.download as dl_mod
    monkeypatch.setattr(dl_mod, "MIRRORS", (url,))
    monkeypatch.setattr(dl_mod, "FILES", manifest)
    dest = tmp_path / "root"
    assert dl_mod.main(["--root", str(dest)]) == 0
    for name in manifest:
        assert (dest / name).exists()


@pytest.fixture()
def truncating_mirror(mirror, tmp_path):
    """A mirror serving TRUNCATED copies of the fixture artifacts (the
    injected fault: a connection dropped mid-body that still delivers
    HTTP 200 — half the bytes, no gzip trailer). Yields its URL."""
    url, manifest = mirror
    docroot = tmp_path / "truncated"
    docroot.mkdir()
    import urllib.request
    for name in manifest:
        with urllib.request.urlopen(url + name) as r:
            payload = r.read()
        (docroot / name).write_bytes(payload[: len(payload) // 2])
    handler = lambda *a, **kw: http.server.SimpleHTTPRequestHandler(  # noqa: E731
        *a, directory=str(docroot), **kw)
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        yield f"http://127.0.0.1:{srv.server_port}/"
    finally:
        srv.shutdown()
        srv.server_close()


def test_truncated_first_mirror_fails_over_to_intact_second(
        truncating_mirror, mirror, tmp_path):
    """Mirror-failover under an injected truncation fault: mirror 1 serves
    half the payload (checksum rejects it), mirror 2 is intact — the fetch
    must succeed with verified bytes and leave no .part litter."""
    url, manifest = mirror
    name = "train-images-idx3-ubyte.gz"
    dest = tmp_path / "dst"
    out = download_file(name, str(dest),
                        mirrors=[truncating_mirror, url],
                        md5=manifest[name])
    assert os.path.exists(out)
    with open(out, "rb") as f:
        payload = f.read()
    assert hashlib.md5(payload).hexdigest() == manifest[name]
    assert [p for p in os.listdir(dest) if p.endswith(".part")] == []


def test_all_mirrors_failing_names_every_mirror_tried(
        truncating_mirror, mirror, tmp_path):
    """Total failure must produce ONE error naming every mirror and its
    individual defect — the evidence an operator needs to tell 'my network
    is down' from 'one mirror is corrupt'."""
    url, manifest = mirror
    name = "t10k-images-idx3-ubyte.gz"
    dead = "http://127.0.0.1:9/"
    with pytest.raises(DownloadError) as ei:
        download_file(name, str(tmp_path / "dst"),
                      mirrors=[truncating_mirror, dead],
                      md5=manifest[name])
    msg = str(ei.value)
    assert truncating_mirror + name in msg
    assert dead + name in msg
    assert "checksum mismatch" in msg        # the truncated mirror's defect
    # and the whole-manifest front door surfaces the same failure
    with pytest.raises(DownloadError, match="could not download"):
        download_mnist(str(tmp_path / "dst2"),
                       mirrors=[dead], files=manifest)


def test_real_manifest_and_mirrors_shape():
    """The production manifest lists the four canonical artifacts with
    32-hex digests, and mirror URLs are well-formed."""
    assert set(FILES) == {
        "train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz",
        "t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"}
    for digest in FILES.values():
        assert len(digest) == 32 and int(digest, 16) >= 0
    for m in MIRRORS:
        assert m.startswith(("http://", "https://")) and m.endswith("/")
