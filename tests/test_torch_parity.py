"""End-to-end golden-curve parity vs an independent torch implementation of
the reference trainer.

The reference's own acceptance test is validation-loss parity against the
serial baseline curve (SURVEY.md §4 item 1). Here we go one step stronger:
an independent torch re-statement of the reference semantics — the §2.6 model
(ddp_tutorial_cpu.py:43-53), CE loss + plain SGD lr=0.01
(ddp_tutorial_multi_gpu.py:75-76) — is trained on identical data in identical
batch order from identical initial weights, and the JAX trainer must
reproduce its loss curve step-for-step and its final weights.

Dropout is held off on both sides (torch eval-mode, JAX train=False): the
masks are RNG-engine-specific, and this test pins down the deterministic
linear/CE/SGD path. Dropout semantics are covered separately
(tests/test_model.py, tests/test_ddp.py).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402
import torch.nn.functional as F  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from pytorch_ddp_mnist_tpu.data import normalize_images, synthetic_mnist  # noqa: E402
from pytorch_ddp_mnist_tpu.models import mlp_apply  # noqa: E402
from pytorch_ddp_mnist_tpu.ops import cross_entropy, sgd_step  # noqa: E402
# the ONE shared torch re-statement of the reference model (also drives
# scripts/golden_accuracy.py — a drift here would desynchronize the golden
# artifact from these unit tests, so both import the same statement)
from pytorch_ddp_mnist_tpu.utils.torch_ref import (  # noqa: E402
    build_reference_model, params_from_torch)

STEPS = 30
BATCH = 128
LR = 0.01


def _torch_model() -> nn.Sequential:
    return build_reference_model(7)


_params_from_torch = params_from_torch


def _data():
    split = synthetic_mnist(STEPS * BATCH, seed=11)
    return normalize_images(split.images), split.labels.astype(np.int64)


def test_forward_logits_match_torch():
    model = _torch_model().eval()
    params = _params_from_torch(model)
    x, _ = _data()
    xb = x[:256]
    with torch.no_grad():
        theirs = model(torch.tensor(xb)).numpy()
    ours = np.asarray(mlp_apply(params, jnp.asarray(xb), train=False))
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


def test_training_curve_and_weights_match_torch():
    x, y = _data()
    model = _torch_model().eval()  # eval = dropout off; grads still flow
    params = _params_from_torch(model)
    opt = torch.optim.SGD(model.parameters(), lr=LR)

    @jax.jit
    def step(params, xb, yb):
        def loss_fn(p):
            return cross_entropy(mlp_apply(p, xb, train=False), yb)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        return sgd_step(params, grads, LR), loss

    torch_losses, jax_losses = [], []
    for s in range(STEPS):
        xb = x[s * BATCH:(s + 1) * BATCH]
        yb = y[s * BATCH:(s + 1) * BATCH]

        opt.zero_grad()
        tl = F.cross_entropy(model(torch.tensor(xb)), torch.tensor(yb))
        tl.backward()
        opt.step()
        torch_losses.append(float(tl.detach()))

        params, jl = step(params, jnp.asarray(xb), jnp.asarray(yb.astype(np.int32)))
        jax_losses.append(float(jl))

    np.testing.assert_allclose(jax_losses, torch_losses, rtol=1e-4, atol=1e-5)
    # Curve must actually be a training curve, not a flat line.
    assert jax_losses[-1] < jax_losses[0] * 0.9

    # Weights agree to float32 accumulation noise over 30 SGD steps; absolute
    # tolerance only — many weights sit near zero where rtol is meaningless.
    sd = {k: v.detach().numpy() for k, v in model.state_dict().items()}
    np.testing.assert_allclose(np.asarray(params["fc1"]["w"]), sd["0.weight"].T,
                               rtol=0, atol=1e-4)
    np.testing.assert_allclose(np.asarray(params["fc3"]["w"]), sd["5.weight"].T,
                               rtol=0, atol=1e-4)


def test_training_with_dropout_active_matches_torch(tmp_path):
    """The LAST reference RNG stream, closed (VERDICT r4 missing #3 /
    next #3): with `--dropout_rng torch` semantics the full serial
    trajectory trains against a LIVE torch run with dropout ACTIVE —
    identical masks drawn from torch's own CPU bernoulli stream
    (ddp_tutorial_cpu.py:47), so the loss curves and final weights agree
    to f32 matmul-rounding, not just in distribution. The comparator shim:
    torch reseeds its global generator with the dropout seed after model
    init (init consumes the same generator; documented on the flag)."""
    from pytorch_ddp_mnist_tpu.train.loop import make_torch_dropout_train_step

    DSEED = 991
    x, y = _data()

    model = _torch_model()
    params = _params_from_torch(model)    # reseeds+reinits; same init bytes
    jstep = make_torch_dropout_train_step(LR, DSEED)
    jkey = jax.random.key(0)              # threaded through, never consumed

    torch.manual_seed(DSEED)              # the comparator shim
    model.train()                         # dropout ACTIVE
    opt = torch.optim.SGD(model.parameters(), lr=LR)

    torch_losses, jax_losses = [], []
    for s in range(STEPS):
        xb = x[s * BATCH:(s + 1) * BATCH]
        yb = y[s * BATCH:(s + 1) * BATCH]
        opt.zero_grad()
        tl = F.cross_entropy(model(torch.tensor(xb)), torch.tensor(yb))
        tl.backward()
        opt.step()
        torch_losses.append(float(tl.detach()))
        params, jkey, jl = jstep(params, jkey, jnp.asarray(xb),
                                 jnp.asarray(yb.astype(np.int32)))
        jax_losses.append(float(jl))

    np.testing.assert_allclose(jax_losses, torch_losses, rtol=1e-4, atol=1e-5)
    assert jax_losses[-1] < jax_losses[0] * 0.9
    sd = {k: v.detach().numpy() for k, v in model.state_dict().items()}
    for ours, theirs in ((params["fc1"]["w"], sd["0.weight"].T),
                         (params["fc1"]["b"], sd["0.bias"]),
                         (params["fc2"]["w"], sd["3.weight"].T),
                         (params["fc3"]["w"], sd["5.weight"].T)):
        np.testing.assert_allclose(np.asarray(ours), theirs, rtol=0,
                                   atol=1e-4)
