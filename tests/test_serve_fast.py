"""The serve fast path (ISSUE 14): persistent staging buffers, zero-copy
batch forming, double-buffered H2D, off-loop reply scatter.

The acceptance pins:

  * served == direct stays BITWISE with the fast path on (staging slabs +
    reply thread observe the request path, never perturb it), and the
    fast and legacy paths answer identically on the same rows;
  * staging reuse — zero np.stack/np.concatenate and zero new staging
    allocations per flush once the pool has reached its steady state
    (the slabs are the SAME objects flush after flush);
  * double-buffer teardown — `engine.close()` drains in-flight transfers
    (block_until_ready) and returns every slab to the pool;
  * the NullTracer zero-overhead contract re-verified on the fast path
    via `sanitize.no_host_sync`: zero block_until_ready, exactly two
    device->host fetches per flush — now performed on the reply thread,
    where the interception still counts them;
  * the reply thread lands in the statics thread-entry map and the
    loop-side scatter callback is audited as loop-resident
    (ASYNC001/LOCK001 coverage for the new concurrency);
  * `engine.bucket_for` (now bisect) agrees with the linear-scan oracle
    across the whole ladder, and multi-chunk forward/predict dispatch
    all chunks before fetching (overlap) while staying bitwise.
"""

import asyncio

import numpy as np
import pytest
import jax

from pytorch_ddp_mnist_tpu.models import init_mlp
from pytorch_ddp_mnist_tpu.serve import (InferenceEngine, MicroBatcher,
                                         ServeService)
from pytorch_ddp_mnist_tpu.serve.engine import STAGING_SLOTS
from pytorch_ddp_mnist_tpu.serve.loadgen import request_rows, run_loadgen
from pytorch_ddp_mnist_tpu.statics import concurrency, sanitize
from pytorch_ddp_mnist_tpu import telemetry


@pytest.fixture(scope="module")
def params():
    return init_mlp(jax.random.key(0))


@pytest.fixture(scope="module")
def engine(params):
    return InferenceEngine(params, max_batch=16)


# ---------------------------------------------------------------------------
# path selection
# ---------------------------------------------------------------------------

def test_fast_path_on_by_default_off_by_knob_and_for_wrappers(engine):
    assert ServeService(engine).batcher.fast_path
    assert not ServeService(engine, fast=False).batcher.fast_path

    class Wrapper:      # duck-typed engine without the staging surface
        max_batch = engine.max_batch

        def _as_rows(self, x):
            return engine._as_rows(x)

        def _run_bucket(self, x):
            return engine._run_bucket(x)

    assert not MicroBatcher(Wrapper()).fast_path


def test_fast_and_legacy_paths_answer_bitwise_identically(engine):
    rows = request_rows(11, seed=31)

    def serve(fast):
        svc = ServeService(engine, max_delay_ms=1000.0, max_depth=64,
                           fast=fast)

        async def scenario():
            subs = [asyncio.ensure_future(svc.handle(r)) for r in rows]
            await asyncio.sleep(0)
            svc.batcher.flush()
            preds = await asyncio.gather(*subs)
            await svc.shutdown()
            return preds

        return np.asarray(asyncio.run(scenario()), np.int32)

    fast, legacy = serve(True), serve(False)
    direct = engine.predict(rows)
    np.testing.assert_array_equal(fast, direct)
    np.testing.assert_array_equal(legacy, direct)


# ---------------------------------------------------------------------------
# staging: zero-copy forming, reuse, inert padding
# ---------------------------------------------------------------------------

def test_zero_copy_no_stack_concat_and_no_new_slabs_per_flush(engine,
                                                              monkeypatch):
    """The staging-reuse pin: across many flushes the batcher calls
    neither np.stack nor np.concatenate, the pool never grows past its
    steady state, and the slabs the engine cycles are the SAME objects
    throughout."""
    svc = ServeService(engine, max_delay_ms=1000.0, max_depth=64)
    rows = request_rows(24, seed=32)
    slab_ids = set()
    calls = {"stack": 0, "concatenate": 0}
    real_stack, real_concat = np.stack, np.concatenate

    def counting_stack(*a, **kw):
        calls["stack"] += 1
        return real_stack(*a, **kw)

    def counting_concat(*a, **kw):
        calls["concatenate"] += 1
        return real_concat(*a, **kw)

    async def scenario():
        grown_before = engine.staging_grown
        monkeypatch.setattr(np, "stack", counting_stack)
        monkeypatch.setattr(np, "concatenate", counting_concat)
        try:
            for start in range(0, 24, 3):      # 8 flushes of 3 rows each
                slab_ids.add(id(engine.staging()))
                subs = [asyncio.ensure_future(svc.handle(r))
                        for r in rows[start:start + 3]]
                await asyncio.sleep(0)
                svc.batcher.flush()
                await asyncio.gather(*subs)    # reply lands: slab returns
        finally:
            monkeypatch.undo()
        await svc.shutdown()
        return grown_before

    grown_before = asyncio.run(scenario())
    assert calls == {"stack": 0, "concatenate": 0}
    # drain-before-next-flush keeps the double buffer sufficient: no
    # growth, and the active slab only ever cycles through the pool's
    # persistent allocations
    assert engine.staging_grown == grown_before
    assert 1 <= len(slab_ids) <= STAGING_SLOTS


def test_staging_pad_tail_is_inert_across_flushes(engine):
    """A big flush leaves stale rows in the slab; a following small flush
    into the same rung family must zero its pad tail — served results
    stay bitwise equal to a direct pass on the same rows."""
    svc = ServeService(engine, max_delay_ms=1000.0, max_depth=64)
    big = request_rows(16, seed=33)
    small = request_rows(3, seed=34)

    async def scenario():
        subs = [asyncio.ensure_future(svc.handle(r)) for r in big]
        await asyncio.sleep(0)          # 16 hits max_batch: size flush
        await asyncio.gather(*subs)
        subs = [asyncio.ensure_future(svc.handle(r)) for r in small]
        await asyncio.sleep(0)
        svc.batcher.flush()
        preds = await asyncio.gather(*subs)
        await svc.shutdown()
        return preds

    served = np.asarray(asyncio.run(scenario()), np.int32)
    np.testing.assert_array_equal(served, engine.predict(small))


def test_submit_validation_never_touches_staging(engine):
    """A ragged row raises at submit BEFORE any staging write: the slab
    rows already staged for well-formed requests are untouched."""
    svc = ServeService(engine, max_delay_ms=1000.0, max_depth=8)
    good = request_rows(2, seed=35)

    async def scenario():
        tasks = [asyncio.ensure_future(svc.handle(r)) for r in good]
        bad = asyncio.ensure_future(svc.handle(np.zeros(10, np.float32)))
        await asyncio.sleep(0)
        svc.batcher.flush()
        results = await asyncio.gather(*tasks, bad, return_exceptions=True)
        await svc.shutdown()
        return results

    r0, r1, rbad = asyncio.run(scenario())
    assert isinstance(r0, int) and isinstance(r1, int)
    assert isinstance(rbad, ValueError)
    np.testing.assert_array_equal(np.asarray([r0, r1], np.int32),
                                  engine.predict(good))


# ---------------------------------------------------------------------------
# double buffer + teardown
# ---------------------------------------------------------------------------

def test_dispatch_swaps_slab_and_fetch_returns_it(params):
    eng = InferenceEngine(params, max_batch=4)
    slab0 = eng.staging()
    slab0[:2] = request_rows(2, seed=36)
    h = eng.dispatch_staged(2)
    # double buffer: the active slab changed while the flush is in flight
    assert eng.staging() is not slab0
    assert eng.inflight_count == 1
    logits, preds = eng.fetch_staged(h)
    assert logits.shape == (2, 10) and preds.shape == (2,)
    assert eng.inflight_count == 0
    # the fetched flush's slab is back in rotation: one more dispatch
    # cycle reuses it rather than allocating
    grown = eng.staging_grown
    eng.staging()[:1] = request_rows(1, seed=37)
    h2 = eng.dispatch_staged(1)
    assert eng.staging() is slab0
    eng.fetch_staged(h2)
    assert eng.staging_grown == grown


def test_engine_close_drains_inflight_transfers(params):
    """The teardown pin: close() blocks on every un-fetched dispatch
    (block_until_ready — counted by the sanitizer) and returns the slabs,
    leaving the engine quiesced but still serveable."""
    eng = InferenceEngine(params, max_batch=4)
    eng.staging()[:2] = request_rows(2, seed=38)
    eng.dispatch_staged(2)
    eng.staging()[:1] = request_rows(1, seed=39)
    eng.dispatch_staged(1)          # pool exhausted: this grew the pool
    assert eng.inflight_count == 2
    with sanitize.no_host_sync(max_block_until_ready=None) as sync:
        eng.close()
    assert sync.armed and sync.block_until_ready_calls == 2
    assert sync.fetches == 0        # a drain is not a fetch
    assert eng.inflight_count == 0
    eng.close()                     # idempotent
    # still serveable after close (close quiesces, it does not poison)
    x = request_rows(2, seed=40)
    assert eng.predict(x).shape == (2,)


def test_staging_pool_growth_is_burst_bounded_then_flat(params):
    """Replies lagging more than a flush behind grow the pool (never
    overwrite a slab the device may still read); the growth is counted
    and one release later the enlarged pool serves allocation-free."""
    eng = InferenceEngine(params, max_batch=4)
    handles = []
    for i in range(4):              # 4 un-fetched dispatches in flight
        eng.staging()[:1] = request_rows(1, seed=41 + i)
        handles.append(eng.dispatch_staged(1))
    assert eng.staging_grown == 4 - (STAGING_SLOTS - 1)
    for h in handles:
        eng.fetch_staged(h)
    grown = eng.staging_grown       # steady state: the pool is sized now
    for i in range(6):
        eng.staging()[:1] = request_rows(1, seed=50 + i)
        eng.fetch_staged(eng.dispatch_staged(1))
    assert eng.staging_grown == grown


def test_fetch_failure_still_releases_slab_and_records_forensics(params):
    """Review-found leak: a failed fetch must still return the slab to
    the pool and drop the in-flight entry — one leak per failed flush
    would bleed the pool on a long-running server."""
    eng = InferenceEngine(params, max_batch=4)
    eng.staging()[:1] = request_rows(1, seed=60)
    h = eng.dispatch_staged(1)

    class Boom:
        def __array__(self, *a, **kw):
            raise RuntimeError("RESOURCE_EXHAUSTED: injected fetch OOM")

    h.logits_d = Boom()
    with pytest.raises(RuntimeError, match="injected"):
        eng.fetch_staged(h)
    assert eng.inflight_count == 0
    grown = eng.staging_grown
    eng.staging()[:1] = request_rows(1, seed=61)
    eng.fetch_staged(eng.dispatch_staged(1))
    assert eng.staging_grown == grown   # the failed flush's slab came back


def test_second_concurrent_batcher_fails_loudly(params):
    """Review-found invariant: the staging slab is engine-global, so a
    second batcher filling the same engine concurrently must raise at
    submit — not silently overwrite the first batcher's rows."""
    eng = InferenceEngine(params, max_batch=4)
    svc1 = ServeService(eng, max_delay_ms=1000.0, max_depth=8)
    svc2 = ServeService(eng, max_delay_ms=1000.0, max_depth=8)
    rows = request_rows(2, seed=62)

    async def scenario():
        t1 = asyncio.ensure_future(svc1.handle(rows[0]))
        await asyncio.sleep(0)              # svc1 claims the slab
        t2 = asyncio.ensure_future(svc2.handle(rows[1]))
        results = await asyncio.gather(t2, return_exceptions=True)
        svc1.batcher.flush()
        r1 = await t1
        await svc1.shutdown()
        await svc2.shutdown()
        return r1, results[0]

    r1, r2 = asyncio.run(scenario())
    assert isinstance(r1, int)              # the owner kept serving
    assert isinstance(r2, RuntimeError) and "ONE batcher" in str(r2)
    # sequential sharing stays allowed: the flush released the claim
    svc3 = ServeService(eng, max_delay_ms=1000.0, max_depth=8)

    async def sequential():
        t = asyncio.ensure_future(svc3.handle(rows[1]))
        await asyncio.sleep(0)
        svc3.batcher.flush()
        pred = await t
        await svc3.shutdown()
        return pred

    assert isinstance(asyncio.run(sequential()), int)


def test_router_ewma_is_per_bucket(params):
    """Review-found stall risk: small-bucket fetch history must never
    vouch for a top-bucket flush — each bucket's inline decision rides
    its own EWMA."""
    eng = InferenceEngine(params, max_batch=4)
    b = MicroBatcher(eng, max_delay_ms=2.0)
    b._fetch_ewma[1] = 1e-4                 # bucket 1 looks cheap
    assert b._fetch_ewma.get(4) is None     # bucket 4 has no history


# ---------------------------------------------------------------------------
# off-loop reply scatter
# ---------------------------------------------------------------------------

def test_reply_thread_fetch_failure_scatters_to_futures(params):
    eng = InferenceEngine(params, max_batch=4)
    svc = ServeService(eng, max_delay_ms=1000.0, max_depth=8)
    boom = RuntimeError("injected fetch failure")
    orig = eng.fetch_staged

    def failing_fetch(handle):
        orig(handle)                # release the slab, then fail
        raise boom

    eng.fetch_staged = failing_fetch
    rows = request_rows(2, seed=42)

    async def scenario():
        tasks = [asyncio.ensure_future(svc.handle(r)) for r in rows]
        await asyncio.sleep(0)
        svc.batcher.flush()
        results = await asyncio.gather(*tasks, return_exceptions=True)
        await svc.shutdown()
        return results

    try:
        results = asyncio.run(scenario())
    finally:
        eng.fetch_staged = orig
    assert all(r is boom for r in results)
    snap = svc.metrics.snapshot()
    assert snap["failed"] == 2 and snap["queue_depth"] == 0


def test_drain_waits_for_outstanding_replies(engine):
    svc = ServeService(engine, max_delay_ms=1000.0, max_depth=64)
    rows = request_rows(5, seed=43)

    async def scenario():
        tasks = [asyncio.ensure_future(svc.handle(r)) for r in rows]
        await asyncio.sleep(0)
        await svc.shutdown()        # drain flushes AND awaits the replies
        return tasks

    tasks = asyncio.run(scenario())
    assert all(t.done() and isinstance(t.result(), int) for t in tasks)
    # the reply thread was joined by shutdown
    assert svc.batcher._reply_thread is None


def test_reply_thread_in_statics_thread_entry_map():
    """The ISSUE 14 statics contract: the reply thread is a registered
    thread entry, and the loop-side scatter callback is audited as
    loop-resident (so ASYNC001 watches what actually runs on the loop)."""
    import pytorch_ddp_mnist_tpu.serve.batcher as batcher_mod

    auditor = concurrency.ConcurrencyAuditor()
    with open(batcher_mod.__file__, encoding="utf-8") as f:
        auditor.add_source(f.read(), batcher_mod.__file__)
    assert "_reply_worker" in auditor.entries["thread"]
    assert "_scatter" in auditor.entries["loop"]
    # and the audit itself stays clean: no ASYNC/LOCK findings on the
    # fast-path concurrency
    assert [f for f in auditor.finish()
            if f.rule.startswith(("ASYNC", "LOCK"))] == []


# ---------------------------------------------------------------------------
# zero-overhead + bitwise pins on the fast path
# ---------------------------------------------------------------------------

def test_fast_path_no_host_sync_two_fetches_per_flush(engine):
    """The NullTracer zero-overhead contract on the FAST path: zero
    block_until_ready anywhere (loop or reply thread), and exactly two
    device->host fetches (logits + preds) per flush — the off-loop fetch
    is still on the sanitizer's books."""
    assert not telemetry.get_tracer().enabled
    svc = ServeService(engine, max_delay_ms=2.0, max_depth=256,
                       registry=telemetry.MetricsRegistry())
    assert svc.batcher.fast_path
    with sanitize.no_host_sync() as sync:
        out = run_loadgen(svc, offered_rps=3000.0, n_requests=40, seed=0)
    assert out["completed"] == 40
    assert sync.armed and sync.block_until_ready_calls == 0
    assert sync.fetches == 2 * svc.batcher.flushes
    assert svc.metrics.attribution()["stages"]["compute"]["n"] == 40


def test_served_equals_direct_bitwise_with_tracing_and_fast_path(
        engine, tmp_path):
    """THE bitwise pin with everything on: staging buffers + reply thread
    + span emission, against a direct engine pass on the same rows."""
    rows = request_rows(6, seed=14)
    telemetry.enable(str(tmp_path / "obs"))
    try:
        svc = ServeService(engine, max_delay_ms=1000.0, max_depth=16,
                           registry=telemetry.MetricsRegistry())
        assert svc.batcher.fast_path

        async def scenario():
            subs = [asyncio.ensure_future(svc.handle(r)) for r in rows]
            await asyncio.sleep(0)
            svc.batcher.flush()
            preds = await asyncio.gather(*subs)
            await svc.shutdown()
            return preds

        served = np.asarray(asyncio.run(scenario()), np.int32)
    finally:
        telemetry.disable()
    np.testing.assert_array_equal(served, engine.predict(rows))


def test_event_loop_never_blocks_on_inflight_compute(engine, monkeypatch):
    """The off-loop win, pinned directly: a flush whose results are NOT
    ready (forced here) goes to the reply thread, and with an
    artificially slowed fetch the loop keeps running callbacks while the
    reply is pending — under the legacy path the flush itself would have
    blocked the loop for the whole fetch."""
    from pytorch_ddp_mnist_tpu.serve.engine import InflightBatch

    svc = ServeService(engine, max_delay_ms=1000.0, max_depth=16)
    orig = engine.fetch_staged

    def slow_fetch(handle):
        import time as _t
        _t.sleep(0.15)
        return orig(handle)

    engine.fetch_staged = slow_fetch
    # never "ready": every reply must take the thread path (the
    # TPU-scale-compute shape)
    monkeypatch.setattr(InflightBatch, "ready", lambda self: False)
    ticks = []

    async def scenario():
        sub = asyncio.ensure_future(svc.handle(request_rows(1, seed=44)[0]))
        await asyncio.sleep(0)
        svc.batcher.flush()
        for _ in range(10):         # the loop must stay responsive while
            ticks.append(1)          # the 150ms fetch runs off-loop
            await asyncio.sleep(0.005)
        pred = await sub
        await svc.shutdown()
        return pred

    with sanitize.event_loop_stall(threshold_ms=100.0) as guard:
        try:
            pred = asyncio.run(scenario())
        finally:
            engine.fetch_staged = orig
    assert isinstance(pred, int)
    assert len(ticks) == 10
    assert svc.batcher.inline_replies == 0      # thread path exercised
    assert guard.stalls == []       # no single loop callback neared 100ms


def test_ready_replies_complete_inline_without_thread_handoff(engine):
    """The routing's other half: when results are device-complete by the
    time the ready queue cycles back, the reply completes INLINE on the
    loop — no cross-thread handoff (the single-core GIL tax)."""
    svc = ServeService(engine, max_delay_ms=1000.0, max_depth=16)
    rows = request_rows(3, seed=45)

    async def scenario():
        import time as _t
        subs = [asyncio.ensure_future(svc.handle(r)) for r in rows]
        await asyncio.sleep(0)
        svc.batcher.flush()
        # hold the loop (no await) while the dispatched executable
        # finishes off-GIL — the deterministic stand-in for "the loop
        # was busy": when the routing callback finally runs, the
        # results are ready and the reply completes inline
        _t.sleep(0.05)
        preds = await asyncio.gather(*subs)
        await svc.shutdown()
        return preds

    preds = np.asarray(asyncio.run(scenario()), np.int32)
    np.testing.assert_array_equal(preds, engine.predict(rows))
    assert svc.batcher.flushes == 1
    assert svc.batcher.inline_replies == 1      # no thread handoff paid


# ---------------------------------------------------------------------------
# satellites: bisect bucket_for + overlapped multi-chunk forward
# ---------------------------------------------------------------------------

def test_bucket_for_bisect_matches_linear_oracle(params):
    eng = InferenceEngine(params, max_batch=16, buckets=(2, 3, 8, 16))
    for n in range(1, 17):
        oracle = next(b for b in eng.buckets if b >= n)
        assert eng.bucket_for(n) == oracle
    with pytest.raises(ValueError, match="largest bucket"):
        eng.bucket_for(17)


def test_multichunk_forward_dispatches_all_before_fetch(engine,
                                                       monkeypatch):
    """Satellite 2: every chunk's executable is dispatched before the
    first result is fetched (the old loop fetched per chunk), and the
    overlapped result stays bitwise identical to per-chunk calls."""
    x = request_rows(40, seed=2)          # 16+16+8: three chunks
    order = []
    orig_dispatch = type(engine)._dispatch
    real_asarray = np.asarray

    def spying_dispatch(self, xx, bctx=None):
        order.append(("dispatch", xx.shape[0]))
        return orig_dispatch(self, xx, bctx)

    def spying_asarray(a, *args, **kw):
        if isinstance(a, jax.Array):
            order.append(("fetch", None))
        return real_asarray(a, *args, **kw)

    monkeypatch.setattr(type(engine), "_dispatch", spying_dispatch)
    monkeypatch.setattr(np, "asarray", spying_asarray)
    try:
        out = engine.forward(x)
    finally:
        monkeypatch.undo()
    dispatches = [i for i, (k, _) in enumerate(order) if k == "dispatch"]
    fetches = [i for i, (k, _) in enumerate(order) if k == "fetch"]
    assert len(dispatches) == 3 and len(fetches) == 3
    assert max(dispatches) < min(fetches)   # all dispatched, then fetched
    np.testing.assert_array_equal(out[:16], engine.forward(x[:16]))
    np.testing.assert_array_equal(out[32:], engine.forward(x[32:]))
