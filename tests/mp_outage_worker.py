"""Worker for the multi-process mid-run-outage resume test — NOT collected
by pytest (no test_ prefix).

Each of the WORLD ranks runs the real trainer CLI (`cli.train.main(None)`,
the CLI context the re-exec path requires) with a BOMB installed on the
cached fit: after global epoch FAIL_EPOCH completes (the stash has it), the
epoch hook raises a backend-loss-shaped RuntimeError on every rank —
exactly a collective dying mid-run. The retry path then persists each
rank's stash and re-execs `python -m pytorch_ddp_mnist_tpu.cli.train ...`,
which is the PLAIN CLI: the bomb does not exist in the resumed processes,
so the world re-rendezvouses and finishes the run. The parent test asserts
the final checkpoint is bitwise an unbroken 4-process run's.
"""

import sys

FAIL_EPOCH = 1


def main() -> int:
    from pytorch_ddp_mnist_tpu.cli.train import main as cli_main
    from pytorch_ddp_mnist_tpu.train import scan

    real = scan.fit_cached

    def flaky(*a, **kw):
        user = kw.get("epoch_hook")

        def bomb(e, st):
            if user is not None:
                user(e, st)
            if e == FAIL_EPOCH:
                raise RuntimeError("UNAVAILABLE: socket closed (simulated "
                                   "mid-run tunnel outage, parallel)")

        kw["epoch_hook"] = bomb
        return real(*a, **kw)

    scan.fit_cached = flaky
    # argv=None: the CLI context (sys.argv carries the flags) — required by
    # the persist+re-exec path, and exactly how a launcher invokes this.
    return cli_main(None)


if __name__ == "__main__":
    sys.exit(main())
