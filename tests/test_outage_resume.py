"""Mid-run backend-outage resilience (--outage_retries, VERDICT r3 #8).

The tunneled TPU this framework targets drops for multi-hour stretches MID
run, not just at startup (docs/PERF.md outage log). These tests simulate a
backend loss in the middle of a --cached fit on CPU and assert the opt-in
retry completes the run — and that the resumed trajectory is BITWISE the
unbroken one (start_epoch keeps the sampler's reshuffle sequence, the stash
carries epoch k's params AND key, so nothing about the interruption is
visible in the final checkpoint).
"""

import json

import numpy as np
import pytest

import jax

from pytorch_ddp_mnist_tpu.cli.train import main
from pytorch_ddp_mnist_tpu.models import init_mlp
from pytorch_ddp_mnist_tpu.train.checkpoint import load_checkpoint


def _params(ckpt):
    return load_checkpoint(str(ckpt), init_mlp(jax.random.key(0)))


def _args(tmp_path, ckpt, extra):
    return ["--limit", "512", "--batch_size", "64", "--lr", "0.1",
            "--cached", "--n_epochs", "3", "--path", str(tmp_path),
            "--checkpoint", str(ckpt)] + extra


def _bomb(monkeypatch, module, attr, fail_epoch=1, times=1):
    """Wrap a fit entry point so its FIRST `times` invocations raise a
    backend-style RuntimeError from the epoch hook after `fail_epoch`
    completes — the stash has recorded that epoch, exactly like a device
    loss between epochs. One helper serves both the cached (scan.fit_cached)
    and streaming (cli.train.fit) paths so the simulated-outage contract
    can never drift between them."""
    real = getattr(module, attr)
    calls = {"n": 0}

    def flaky(*a, **kw):
        calls["n"] += 1
        if calls["n"] <= times:
            user = kw.get("epoch_hook")

            def bomb(e, st):
                if user is not None:
                    user(e, st)
                if fail_epoch == "any" or e == fail_epoch:
                    raise RuntimeError(
                        "UNAVAILABLE: socket closed (simulated mid-run "
                        "tunnel outage)")

            kw["epoch_hook"] = bomb
        return real(*a, **kw)

    monkeypatch.setattr(module, attr, flaky)
    return calls


def _bomb_fit_cached(monkeypatch, fail_epoch=1, times=1):
    from pytorch_ddp_mnist_tpu.train import scan
    return _bomb(monkeypatch, scan, "fit_cached", fail_epoch, times)


def test_midrun_outage_resumes_bitwise_identical(tmp_path, monkeypatch,
                                                 capsys):
    golden = tmp_path / "golden.msgpack"
    assert main(_args(tmp_path, golden, [])) == 0
    capsys.readouterr()

    flaky_ckpt = tmp_path / "flaky.msgpack"
    calls = _bomb_fit_cached(monkeypatch, fail_epoch=1)
    assert main(_args(tmp_path, flaky_ckpt, ["--outage_retries", "1"])) == 0
    assert calls["n"] == 2          # original attempt + one resume
    out = capsys.readouterr()
    # resumed run continues at GLOBAL epoch 2 — epochs 0/1 are not re-run
    # or re-printed by the second attempt
    assert out.out.count("Epoch=2,") == 1
    assert "[outage] training interrupted" in out.err
    for a, b in zip(jax.tree_util.tree_leaves(_params(flaky_ckpt)),
                    jax.tree_util.tree_leaves(_params(golden))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_outage_before_first_epoch_resumes_from_seeded_stash(
        tmp_path, monkeypatch, capsys):
    """A loss before ANY epoch completes resumes from the starting state
    (the stash is pre-seeded with epoch start_epoch-1), still bitwise."""
    golden = tmp_path / "golden.msgpack"
    assert main(_args(tmp_path, golden, [])) == 0
    flaky_ckpt = tmp_path / "flaky.msgpack"
    # fail_epoch=0: the bomb goes off after epoch 0's hook, so the retry
    # resumes at epoch 1 with epoch 0's stashed state
    _bomb_fit_cached(monkeypatch, fail_epoch=0)
    assert main(_args(tmp_path, flaky_ckpt, ["--outage_retries", "1"])) == 0
    for a, b in zip(jax.tree_util.tree_leaves(_params(flaky_ckpt)),
                    jax.tree_util.tree_leaves(_params(golden))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_outage_retries_exhausted_reraises(tmp_path, monkeypatch):
    # every attempt dies at its first completed epoch -> budget exhausts
    _bomb_fit_cached(monkeypatch, fail_epoch="any", times=5)
    with pytest.raises(RuntimeError, match="UNAVAILABLE"):
        main(_args(tmp_path, tmp_path / "x.msgpack",
                   ["--outage_retries", "2"]))


def test_wedged_client_persists_and_reexecs_then_completes(
        tmp_path, monkeypatch, capsys):
    """The hang-mode outage: wait_for_backend reports the in-process client
    WEDGED. The retry must persist the stash (checkpoint + RNG sidecar) and
    re-exec with --resume/--start_epoch — and actually running the re-exec
    argv must finish the run bitwise equal to the unbroken one."""
    import os
    import sys

    golden = tmp_path / "golden.msgpack"
    assert main(_args(tmp_path, golden, [])) == 0

    from pytorch_ddp_mnist_tpu.parallel import wireup

    def wedged(max_wait_s):
        raise wireup.BackendWedgedError("client wedged (simulated)")

    monkeypatch.setattr(wireup, "wait_for_backend", wedged)
    execs = []
    monkeypatch.setattr(os, "execv",
                        lambda exe, argv: execs.append(argv) or (
                            _ for _ in ()).throw(SystemExit(99)))
    flaky_ckpt = tmp_path / "flaky.msgpack"
    cli_args = _args(tmp_path, flaky_ckpt, ["--outage_retries", "1"])
    _bomb_fit_cached(monkeypatch, fail_epoch=1)
    monkeypatch.delenv("PDMT_NO_REEXEC", raising=False)
    monkeypatch.setattr(sys, "argv", ["train.py"] + cli_args)
    try:
        # CLI path (argv=None): the wedged state re-execs rather than raising
        with pytest.raises(SystemExit) as ei:
            main(None)
        assert ei.value.code == 99 and len(execs) == 1
        argv = execs[0]
        assert argv[1:3] == ["-m", "pytorch_ddp_mnist_tpu.cli.train"]
        tail = argv[3:]
        i = tail.index("--resume")
        assert tail[i + 1] == str(flaky_ckpt)
        assert tail[tail.index("--start_epoch") + 1] == "2"
        assert tail[tail.index("--outage_retries", i) + 1] == "0"
        # the persisted progress: epoch-1 params + the RNG sidecar
        assert flaky_ckpt.exists()
        assert (tmp_path / "flaky.msgpack.rng.npz").exists()
        z = np.load(str(flaky_ckpt) + ".rng.npz")
        assert str(z["impl"]) == "threefry2x32"
        # run the re-exec'd command line for real (fresh, un-bombed fit):
        # it must complete epochs 2.. and land on the golden params
        monkeypatch.setattr(wireup, "wait_for_backend",
                            lambda max_wait_s: [])
        capsys.readouterr()
        assert main(tail) == 0
        # the sidecar is one-shot: consumed (and removed) by the resume, so
        # a LATER --resume of the evolving checkpoint can't pair fresh
        # params with this stale epoch-1 key
        assert not (tmp_path / "flaky.msgpack.rng.npz").exists()
    finally:
        os.environ.pop("PDMT_NO_REEXEC", None)
    for a, b in zip(jax.tree_util.tree_leaves(_params(flaky_ckpt)),
                    jax.tree_util.tree_leaves(_params(golden))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_default_stash_cleaned_up_after_successful_resume(
        tmp_path, monkeypatch, capsys):
    """With --checkpoint '' the wedged-client re-exec stashes progress to
    the DEFAULT 'outage_resume.msgpack' in the cwd — a file no final save
    ever overwrites/consumes. A completed resume must remove it and its
    RNG sidecar instead of leaving them behind forever."""
    import os
    import sys

    monkeypatch.chdir(tmp_path)
    from pytorch_ddp_mnist_tpu.parallel import wireup

    def wedged(max_wait_s):
        raise wireup.BackendWedgedError("client wedged (simulated)")

    monkeypatch.setattr(wireup, "wait_for_backend", wedged)
    execs = []
    monkeypatch.setattr(os, "execv",
                        lambda exe, argv: execs.append(argv) or (
                            _ for _ in ()).throw(SystemExit(99)))
    cli_args = ["--limit", "512", "--batch_size", "64", "--cached",
                "--n_epochs", "3", "--path", str(tmp_path),
                "--checkpoint", "", "--outage_retries", "1"]
    _bomb_fit_cached(monkeypatch, fail_epoch=1)
    monkeypatch.delenv("PDMT_NO_REEXEC", raising=False)
    monkeypatch.setattr(sys, "argv", ["train.py"] + cli_args)
    try:
        with pytest.raises(SystemExit) as ei:
            main(None)
        assert ei.value.code == 99 and len(execs) == 1
        tail = execs[0][3:]
        stash = tail[tail.index("--resume") + 1]
        assert os.path.basename(stash) == "outage_resume.msgpack"
        assert os.path.exists(stash)
        assert os.path.exists(stash + ".rng.npz")
        # run the re-exec'd command line for real: it must complete AND
        # sweep the now-consumed default stash pair from the cwd
        monkeypatch.setattr(wireup, "wait_for_backend",
                            lambda max_wait_s: [])
        assert main(tail) == 0
        assert not os.path.exists(stash)
        assert not os.path.exists(stash + ".rng.npz")
    finally:
        os.environ.pop("PDMT_NO_REEXEC", None)


def test_program_error_not_retried_on_healthy_backend(tmp_path, monkeypatch):
    """A deterministic program error (no backend-loss signature) on a
    HEALTHY backend must surface immediately instead of burning the retry
    budget re-running into it (ADVICE r4): the wrapper checks the message
    signature, then confirms backend health from a fresh interpreter."""
    from pytorch_ddp_mnist_tpu.train import scan

    calls = {"n": 0}

    def broken(*a, **kw):
        calls["n"] += 1
        raise RuntimeError("Mismatched XLA computation shapes "
                           "(simulated deterministic program bug)")

    monkeypatch.setattr(scan, "fit_cached", broken)
    with pytest.raises(RuntimeError, match="Mismatched"):
        main(_args(tmp_path, tmp_path / "x.msgpack",
                   ["--outage_retries", "3"]))
    assert calls["n"] == 1  # no silent re-runs


def test_sidecar_survives_resume_that_dies_before_first_save(
        tmp_path, monkeypatch):
    """The (checkpoint, .rng.npz) pair must stay intact when a resumed run
    dies before its first checkpoint save (ADVICE r4) — a later manual
    --resume of the same pair still restores the sidecar key chain — and
    must be consumed once the resumed run overwrites the checkpoint."""
    ckpt = tmp_path / "c.msgpack"
    base = ["--limit", "512", "--batch_size", "64", "--cached",
            "--path", str(tmp_path), "--checkpoint", str(ckpt)]
    assert main(base + ["--n_epochs", "1"]) == 0
    sidecar = tmp_path / "c.msgpack.rng.npz"
    np.savez(sidecar,
             key=np.asarray(jax.random.key_data(jax.random.key(123))),
             impl="threefry2x32")

    from pytorch_ddp_mnist_tpu.train import scan

    def dies(*a, **kw):
        raise RuntimeError("UNAVAILABLE: socket closed (simulated outage "
                           "before any epoch completes)")

    monkeypatch.setattr(scan, "fit_cached", dies)
    with pytest.raises(RuntimeError, match="UNAVAILABLE"):
        main(base + ["--n_epochs", "2", "--resume", str(ckpt),
                     "--start_epoch", "1"])
    assert sidecar.exists()  # pair intact for the next manual --resume

    monkeypatch.undo()
    assert main(base + ["--n_epochs", "2", "--resume", str(ckpt),
                        "--start_epoch", "1"]) == 0
    assert not sidecar.exists()  # consumed at the first overwrite


def test_outage_retries_rejected_by_name_with_fused(tmp_path):
    # --parallel composes since round 5 (the coordinated re-exec resume,
    # tests/test_multiprocess.py) — but only from the CLI: the resume
    # REPLACES the process, so programmatic callers fail fast at parse
    # time instead of getting a retry flag that cannot act.
    with pytest.raises(SystemExit, match="CLI"):
        main(["--parallel", "--outage_retries", "1", "--path", str(tmp_path)])
    # --fused still has no mid-run state to resume from
    with pytest.raises(SystemExit, match="fused"):
        main(["--cached", "--fused", "--outage_retries", "1",
              "--path", str(tmp_path)])
    with pytest.raises(SystemExit, match="start_epoch"):
        main(["--start_epoch", "5", "--n_epochs", "3",
              "--path", str(tmp_path)])


def test_midrun_outage_resumes_streaming_path(tmp_path, monkeypatch):
    """The retry wrapper covers the STREAMING loop too (no --cached): same
    stash/resume machinery through train.loop.fit, bitwise equal to the
    unbroken run."""
    from pytorch_ddp_mnist_tpu.cli import train as cli_mod

    args = ["--limit", "512", "--batch_size", "64", "--lr", "0.1",
            "--n_epochs", "3", "--path", str(tmp_path)]
    golden = tmp_path / "golden.msgpack"
    assert main(args + ["--checkpoint", str(golden)]) == 0

    calls = _bomb(monkeypatch, cli_mod, "fit", fail_epoch=1)
    flaky_ckpt = tmp_path / "flaky.msgpack"
    assert main(args + ["--checkpoint", str(flaky_ckpt),
                        "--outage_retries", "1"]) == 0
    assert calls["n"] == 2
    for a_, b_ in zip(jax.tree_util.tree_leaves(_params(flaky_ckpt)),
                      jax.tree_util.tree_leaves(_params(golden))):
        np.testing.assert_array_equal(np.asarray(a_), np.asarray(b_))
