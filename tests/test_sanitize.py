"""statics/sanitize.py — the runtime contract sanitizers.

Each sanitizer is exercised in both directions: a violating block raises
the named SanitizerError subclass with the evidence in the message, a
clean block passes, and in EVERY case the patched process-wide entry
points (jax.block_until_ready / np.asarray / asyncio Handle._run / the
threading lock factories) are restored afterwards — a sanitizer that
leaks its patch would corrupt every later test. The PR 9 bug shapes
(event-loop stall, lock-order cycle) are reproduced as runtime fixtures,
mirroring the lexical fixtures in test_statics.py.
"""

import asyncio
import threading
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from pytorch_ddp_mnist_tpu.statics import sanitize


# ---------------------------------------------------------------------------
# no_host_sync
# ---------------------------------------------------------------------------

def test_no_host_sync_counts_and_restores():
    orig_bur = jax.block_until_ready
    orig_asarray = np.asarray
    x = jnp.arange(8.0)
    with sanitize.no_host_sync(max_block_until_ready=None) as s:
        jax.block_until_ready(x)
        np.asarray(x)
        np.asarray([1, 2, 3])            # host data: not a fetch
        jax.device_get(x)
    assert s.armed
    assert s.block_until_ready_calls == 1
    assert s.fetches == 2                # asarray-of-Array + device_get
    assert jax.block_until_ready is orig_bur
    assert np.asarray is orig_asarray


def test_no_host_sync_zero_budget_raises():
    x = jnp.arange(4.0)
    with pytest.raises(sanitize.HostSyncError, match="zero-host-sync"):
        with sanitize.no_host_sync():
            jax.block_until_ready(x)


def test_no_host_sync_fetch_budget_raises_and_names_cadence():
    x = jnp.arange(4.0)
    with pytest.raises(sanitize.HostSyncError, match="fetch cadence"):
        with sanitize.no_host_sync(max_fetches=1):
            np.asarray(x)
            np.asarray(x)


def test_no_host_sync_never_masks_the_primary_failure():
    # a block that raises must propagate ITS error, not the budget's —
    # and still restore the patches
    orig = np.asarray
    with pytest.raises(RuntimeError, match="primary"):
        with sanitize.no_host_sync():
            jax.block_until_ready(jnp.arange(2.0))   # over budget
            raise RuntimeError("primary")
    assert np.asarray is orig


def test_no_host_sync_is_nestable():
    x = jnp.arange(2.0)
    with sanitize.no_host_sync(max_block_until_ready=None) as outer:
        with sanitize.no_host_sync(max_block_until_ready=None) as inner:
            np.asarray(x)
        np.asarray(x)
    assert inner.fetches == 1
    assert outer.fetches == 2            # inner's count forwards upward


# ---------------------------------------------------------------------------
# event_loop_stall
# ---------------------------------------------------------------------------

def test_event_loop_stall_flags_a_blocking_callback():
    async def scenario():
        loop = asyncio.get_running_loop()
        loop.call_soon(time.sleep, 0.05)         # the PR 9 bug class
        await asyncio.sleep(0.1)

    orig = asyncio.events.Handle._run
    with pytest.raises(sanitize.EventLoopStallError, match="sleep"):
        with sanitize.event_loop_stall(threshold_ms=20.0):
            asyncio.run(scenario())
    assert asyncio.events.Handle._run is orig


def test_event_loop_stall_clean_loop_passes():
    async def scenario():
        for _ in range(10):
            await asyncio.sleep(0)

    with sanitize.event_loop_stall(threshold_ms=200.0) as guard:
        asyncio.run(scenario())
    assert guard.stalls == []


def test_event_loop_stall_records_duration_evidence():
    async def scenario():
        time.sleep(0.03)                 # the coroutine step itself stalls

    with sanitize.event_loop_stall(threshold_ms=10.0, max_stalls=5) as g:
        asyncio.run(scenario())
    assert g.stalls and g.stalls[0]["dur_ms"] >= 10.0


def test_event_loop_stall_rejects_bad_threshold():
    with pytest.raises(ValueError):
        sanitize.event_loop_stall(threshold_ms=0)


# ---------------------------------------------------------------------------
# lock_trace
# ---------------------------------------------------------------------------

def test_lock_trace_observes_order_and_detects_cycles():
    with pytest.raises(sanitize.LockOrderError, match="cycle"):
        with sanitize.lock_trace():
            a = threading.Lock()
            b = threading.Lock()
            with a:
                with b:
                    pass
            with b:
                with a:                  # the reverse order
                    pass


def test_lock_trace_consistent_order_passes_and_restores():
    orig_lock, orig_rlock = threading.Lock, threading.RLock
    with sanitize.lock_trace() as trace:
        a = threading.Lock()
        b = threading.Lock()
        for _ in range(3):
            with a:
                with b:
                    pass
    assert threading.Lock is orig_lock and threading.RLock is orig_rlock
    assert trace.cycles() == []
    ((src, dst, n),) = trace.edges()
    assert n == 3 and src != dst


def test_lock_trace_rlock_reentry_adds_no_self_edge():
    with sanitize.lock_trace() as trace:
        r = threading.RLock()
        with r:
            with r:                      # re-entry, not an ordering edge
                pass
    assert trace.edges() == []


def test_lock_trace_sees_cross_thread_inconsistency():
    # thread 1 takes a->b, thread 2 takes b->a: the UNION graph has the
    # cycle even though each thread's own order is locally consistent
    with pytest.raises(sanitize.LockOrderError):
        with sanitize.lock_trace():
            a = threading.Lock()
            b = threading.Lock()

            def fwd():
                with a:
                    with b:
                        pass

            def rev():
                with b:
                    with a:
                        pass

            t1 = threading.Thread(target=fwd)
            t1.start()
            t1.join()
            rev()


def test_lock_trace_sees_locks_created_under_an_earlier_trace():
    # review-found bug: instrumented lock OBJECTS outlive their trace (a
    # service built under trace 1 holds them forever), so they must
    # report to whichever trace is armed at ACQUISITION time — a later
    # trace still sees cycles on them, and an exited trace gains nothing
    with sanitize.lock_trace() as t1:
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
    n_t1 = len(t1.edges())
    with pytest.raises(sanitize.LockOrderError):
        with sanitize.lock_trace():
            with a:
                with b:
                    pass
            with b:
                with a:
                    pass
    assert len(t1.edges()) == n_t1       # the dead trace gained nothing


def test_lock_trace_wrappers_are_passthrough_outside_any_trace():
    with sanitize.lock_trace() as t:
        lock = threading.Lock()
    # after exit: still a working lock, and nothing records anywhere
    with lock:
        assert lock.locked()
    assert t.edges() == []


def test_lock_trace_refuses_to_nest():
    with sanitize.lock_trace():
        with pytest.raises(RuntimeError, match="already armed"):
            with sanitize.lock_trace():
                pass
    # the failed arm must not have disarmed/unpatched the outer trace
    orig = threading.Lock
    with sanitize.lock_trace():
        assert threading.Lock is not orig
    assert threading.Lock is orig


def test_lock_trace_inspection_mode_reports_without_raising():
    with sanitize.lock_trace(fail_on_cycle=False) as trace:
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
    (cycle,) = trace.cycles()
    assert len(cycle) == 2


def test_traced_locks_keep_working_as_locks():
    # the wrapper must remain a real lock: exclusion across threads holds
    with sanitize.lock_trace() as trace:
        lock = threading.Lock()
        hits = []

        def worker():
            for _ in range(200):
                with lock:
                    n = len(hits)
                    hits.append(n)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not lock.locked()
    assert hits == list(range(800))      # no lost updates under the lock
    assert trace.cycles() == []


# ---------------------------------------------------------------------------
# the smoke harness (in-process: the make target's own entry point)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_sanitize_smoke_main_passes(capsys):
    # by file path: scripts/ is not a package (the repo's script idiom)
    import importlib.util
    import pathlib
    path = (pathlib.Path(__file__).resolve().parent.parent / "scripts"
            / "sanitize_smoke.py")
    spec = importlib.util.spec_from_file_location("_sanitize_smoke", path)
    smoke = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(smoke)
    assert smoke.main([]) == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    import json
    report = json.loads(out)
    assert report["ok"] is True
    assert report["serve"]["block_until_ready"] == 0
    assert report["serve"]["fetches"] == 2 * report["serve"]["flushes"]
    assert report["serve"]["stalls"] == 0
    assert report["train"]["fetches"] <= report["train"]["epochs"] * 6
    assert report["lock_cycles"] == 0
