"""statics/ — the JAX-aware lint + concurrency auditor + jaxpr program
auditor.

Four layers, mirroring the subsystem:

  * rule-by-rule fixture matrix: every rule ID in the catalog (the PR 8
    source rules AND the ASYNC/LOCK concurrency rules) is exercised with
    BOTH a triggering and a non-triggering source fixture, so a rule that
    stops firing (or starts over-firing) is caught by name;
  * concurrency machinery: the thread-entry map on real sources, the
    one-hop residency propagation, the PR 9 event-loop-sort regression
    fixture ASYNC001 must flag by ID, the lock-cycle fixture LOCK002 must
    flag, and the cross-file union lock-order graph;
  * baseline semantics: new finding fails, baselined finding passes, stale
    entry warns, --prune-baseline rewrites the file — plus `--check-docs`
    rule-catalog/doc drift detection;
  * the program auditor: the full comm x overlap x {step, run} matrix
    passes on the real step builders, a deliberately mismatched program
    fails with the NAMED contract (the acceptance pin: an int8 audit fed
    an f32-allreduce program dies on wire-dtype), and the audited wire
    bytes equal the ddp.bytes_on_wire cost model to the byte.

The lint engine itself is exercised through the public API (lint_source /
lint_paths / main) — the same entry points `python -m pytorch_ddp_mnist_tpu
lint` dispatches to.
"""

import json
import textwrap

import pytest

from pytorch_ddp_mnist_tpu.statics import concurrency, jaxpr_audit, lint
from pytorch_ddp_mnist_tpu.statics.rules import CONCURRENCY_RULES, RULES


def rules_of(src):
    return {f.rule for f in lint.lint_source(textwrap.dedent(src), "fix.py")}


# ---------------------------------------------------------------------------
# rule fixtures: (rule id, triggering source, non-triggering source)
# ---------------------------------------------------------------------------

FIXTURES = [
    ("SYNC001", """
        import jax
        import numpy as np

        def step(x):
            return np.asarray(x) + 1

        fast = jax.jit(step)
     """, """
        import numpy as np

        def host_helper(x):          # not traced: np.asarray is host work
            return np.asarray(x) + 1
     """),
    ("SYNC001", """
        import jax

        def step(x):
            return float(x.sum())

        fast = jax.jit(step)
     """, """
        import jax

        def step(x):
            return x.sum() * float("inf")   # literal: not a tracer coerce

        fast = jax.jit(step)
     """),
    ("SYNC002", """
        import jax
        import time

        def step(x):
            return x * time.time()

        fast = jax.jit(step)
     """, """
        import time

        def measure(fn):             # untraced host timing is the POINT
            t0 = time.perf_counter()
            fn()
            return time.perf_counter() - t0
     """),
    ("SYNC003", """
        import jax
        import jax.numpy as jnp

        def step(x):
            if jnp.max(x) > 0:
                return x
            return -x

        fast = jax.jit(step)
     """, """
        import jax
        import jax.numpy as jnp

        def step(x):
            if x.shape[0] > 2:       # static metadata: legal specialization
                return x
            if x.dtype == jnp.uint8:
                return x
            return -x

        fast = jax.jit(step)
     """),
    ("DT001", """
        import jax.numpy as jnp

        SCALE = jnp.float64(1.0)
     """, """
        import numpy as np

        def host_stats(losses):      # host f64 statistics are fine
            return np.asarray(losses, np.float64).mean()
     """),
    ("DT001", """
        import jax
        import jax.numpy as jnp

        def step(x):
            return x.astype(jnp.float64)

        fast = jax.jit(step)
     """, """
        import jax
        import jax.numpy as jnp

        def step(x):
            return x.astype(jnp.float32)

        fast = jax.jit(step)
     """),
    ("COLL001", """
        import jax

        def body(g):
            return jax.lax.psum(g)
     """, """
        import jax

        def body(g):
            a = jax.lax.psum(g, "dp")
            b = jax.lax.pmean(g, axis_name="dp")
            return a + b + jax.lax.axis_index("dp")
     """),
    ("EXC001", """
        def fragile():
            try:
                work()
            except Exception:
                pass
     """, """
        def careful():
            try:
                work()
            except ValueError:
                pass
            try:
                work()
            except Exception:
                cleanup()
                raise            # re-raising handlers don't swallow
     """),
    ("MUT001", """
        def collect(item, acc=[]):
            acc.append(item)
            return acc
     """, """
        def collect(item, acc=None):
            acc = [] if acc is None else acc
            acc.append(item)
            return acc
     """),
    ("MUT002", """
        _CACHE = None

        def get():
            global _CACHE
            if _CACHE is None:
                _CACHE = build()
            return _CACHE
     """, """
        import threading

        _CACHE = None
        _LOCK = threading.Lock()

        def get():
            global _CACHE
            with _LOCK:
                if _CACHE is None:
                    _CACHE = build()
            return _CACHE
     """),
    ("ASYNC001", """
        import time

        async def handler(q):
            time.sleep(0.01)          # parks every in-flight request
            return q
     """, """
        import asyncio
        import time

        async def handler(q):
            await asyncio.sleep(0.01)
            return q

        def host_bench(fn):           # untraced host code may sleep
            time.sleep(0.01)
            return fn()
     """),
    ("ASYNC002", """
        import threading

        _STATE_LOCK = threading.Lock()

        async def update(x):
            with _STATE_LOCK:
                return await compute(x)
     """, """
        import asyncio
        import threading

        _STATE_LOCK = threading.Lock()
        _LOOP_LOCK = asyncio.Lock()

        async def update(x):
            with _STATE_LOCK:         # sync lock, no await inside: fine
                stage(x)
            async with _LOOP_LOCK:    # asyncio lock across await: fine
                return await compute(x)
     """),
    ("LOCK001", """
        import threading

        class Registry:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0           # construction is exempt

            def add(self):
                with self._lock:
                    self._n += 1

            def reset(self):
                self._n = 0           # races every locked writer/reader
     """, """
        import threading

        class Registry:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def add(self):
                with self._lock:
                    self._n += 1

            def reset(self):
                with self._lock:
                    self._n = 0
     """),
    ("LOCK002", """
        import threading

        A_LOCK = threading.Lock()
        B_LOCK = threading.Lock()

        def forward():
            with A_LOCK:
                with B_LOCK:
                    work()

        def backward():
            with B_LOCK:
                with A_LOCK:          # the reverse order: deadlock bait
                    work()
     """, """
        import threading

        A_LOCK = threading.Lock()
        B_LOCK = threading.Lock()

        def forward():
            with A_LOCK:
                with B_LOCK:
                    work()

        def also_forward():
            with A_LOCK:
                with B_LOCK:          # same global order everywhere
                    work()
     """),
]


def test_every_rule_id_has_fixture_coverage():
    covered = {rule for rule, _bad, _good in FIXTURES}
    assert covered == set(RULES), (
        f"rule catalog and fixture matrix drifted: "
        f"uncovered={set(RULES) - covered} unknown={covered - set(RULES)}")


@pytest.mark.parametrize("rule,bad,good",
                         FIXTURES, ids=[f"{r}-{i}" for i, (r, _b, _g)
                                        in enumerate(FIXTURES)])
def test_rule_fires_on_bad_not_on_good(rule, bad, good):
    assert rule in rules_of(bad), f"{rule} missed its triggering fixture"
    assert rule not in rules_of(good), \
        f"{rule} fired on its non-triggering fixture"


def test_partial_hop_marks_traced():
    # step = partial(body, ...) then lax.scan(step, ...) must mark `body`
    src = """
        import jax
        from functools import partial

        def body(carry, x, lr):
            return carry + float(x), None

        def run(xs):
            step = partial(body, lr=0.1)
            return jax.lax.scan(step, 0.0, xs)
    """
    assert "SYNC001" in rules_of(src)


def test_decorated_jit_marks_traced():
    src = """
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(x):
            return x.item()
    """
    assert "SYNC001" in rules_of(src)


# ---------------------------------------------------------------------------
# the concurrency auditor's machinery
# ---------------------------------------------------------------------------

def test_async001_flags_the_pr9_event_loop_sort_bug():
    """Regression fixture: the PR 9 bug — an O(W log W) sort over the
    rolling SLO window executed on the serve event loop per offered
    request — must be flagged by ASYNC001, by ID, in both spellings."""
    src = """
        async def admit(window, q):
            lat = sorted(window)       # re-sorts the window per request
            return lat[int(q * len(lat))]

        async def admit_inplace(window):
            window.sort()
            return window[-1]
    """
    findings = [f for f in lint.lint_source(textwrap.dedent(src), "fix.py")
                if f.rule == "ASYNC001"]
    assert len(findings) == 2
    assert any("sorted(window)" in f.message for f in findings)


def test_async001_propagates_through_sync_helpers():
    # the event-loop residency fixpoint: a sync helper CALLED from a
    # coroutine is on the loop too — one hop or many
    src = """
        import time

        def deep():
            time.sleep(1)

        def helper():
            deep()

        async def handler():
            helper()
    """
    (f,) = [f for f in lint.lint_source(textwrap.dedent(src), "fix.py")
            if f.rule == "ASYNC001"]
    assert "time.sleep" in f.content and "deep" in f.message


def test_async001_covers_loop_scheduled_callbacks():
    # call_later/call_soon targets are loop-resident without being async
    # (the MicroBatcher._on_timer shape)
    src = """
        import time

        class Batcher:
            def arm(self, loop):
                loop.call_later(0.1, self._tick)

            def _tick(self):
                time.sleep(0.5)
    """
    (f,) = [f for f in lint.lint_source(textwrap.dedent(src), "fix.py")
            if f.rule == "ASYNC001"]
    assert "call_later" in f.message


def test_async001_acquire_timeout_is_exempt():
    src_bad = """
        import threading
        _LOCK = threading.Lock()
        async def grab():
            _LOCK.acquire()
    """
    src_good = """
        import threading
        _LOCK = threading.Lock()
        async def grab():
            _LOCK.acquire(timeout=0.1)
        async def try_grab():
            _LOCK.acquire(False)
    """
    assert "ASYNC001" in rules_of(src_bad)
    assert "ASYNC001" not in rules_of(src_good)


def test_lock002_unions_the_graph_across_files(tmp_path):
    # file A nests B_LOCK inside A_LOCK; file B nests the reverse: the
    # cycle only exists in the UNION graph lint_paths builds (lock ids
    # are name-qualified, not path-qualified)
    (tmp_path / "a.py").write_text(textwrap.dedent("""
        from locks import A_LOCK, B_LOCK
        def forward():
            with A_LOCK:
                with B_LOCK:
                    pass
    """))
    (tmp_path / "b.py").write_text(textwrap.dedent("""
        from locks import A_LOCK, B_LOCK
        def backward():
            with B_LOCK:
                with A_LOCK:
                    pass
    """))
    findings, n = lint.lint_paths([str(tmp_path)], root=str(tmp_path))
    cycles = [f for f in findings if f.rule == "LOCK002"]
    assert n == 2 and len(cycles) == 2          # one edge flagged per file
    assert {f.path for f in cycles} == {"a.py", "b.py"}
    # each file alone is clean: the order is only inconsistent globally
    for name in ("a.py", "b.py"):
        alone = lint.lint_source((tmp_path / name).read_text(), name)
        assert not [f for f in alone if f.rule == "LOCK002"]


def test_thread_entry_map_on_the_real_tree():
    """The auditor's thread-entry map sees the real producers: prom.py's
    daemon scrape thread, flight.py's SIGTERM handler, batcher.py's
    loop-scheduled flush timer, and the input pipeline's decode workers
    (pipeline/workers.py — the ISSUE 12 contract: every worker thread is
    registered in the statics thread-entry map)."""
    import pytorch_ddp_mnist_tpu.pipeline.workers as workers_mod
    import pytorch_ddp_mnist_tpu.serve.batcher as batcher_mod
    import pytorch_ddp_mnist_tpu.telemetry.cluster as cluster_mod
    import pytorch_ddp_mnist_tpu.telemetry.flight as flight_mod
    import pytorch_ddp_mnist_tpu.telemetry.prom as prom_mod

    auditor = concurrency.ConcurrencyAuditor()
    for mod in (prom_mod, flight_mod, batcher_mod, workers_mod,
                cluster_mod):
        with open(mod.__file__, encoding="utf-8") as f:
            auditor.add_source(f.read(), mod.__file__)
    assert "serve_forever" in auditor.entries["thread"]
    assert "_flush_and_chain" in auditor.entries["signal"]
    assert "_on_timer" in auditor.entries["loop"]
    assert "flush" in auditor.entries["loop"]   # called from _on_timer
    # the input pipeline's decode workers land in the thread map
    assert "_work" in auditor.entries["thread"]
    # the serve fast path's reply thread (ISSUE 14): the off-loop fetch
    # worker is a thread entry, and the loop-side scatter it schedules
    # via call_soon_threadsafe is audited as loop-resident
    assert "_reply_worker" in auditor.entries["thread"]
    assert "_scatter" in auditor.entries["loop"]
    # the cluster-forensics collective watchdog (ISSUE 15): the hang
    # detector's poll loop is a registered thread entry
    assert "_watch" in auditor.entries["thread"]


def test_lock001_groups_attributes_per_class():
    # two classes each writing self._n — one mixed (flagged), one
    # consistently unlocked (not flagged: no lock claims to guard it)
    src = """
        import threading

        class Mixed:
            def locked_write(self):
                with self._lock:
                    self._n = 1
            def bare_write(self):
                self._n = 2

        class Unlocked:
            def a(self):
                self._n = 1
            def b(self):
                self._n = 2
    """
    findings = [f for f in lint.lint_source(textwrap.dedent(src), "fix.py")
                if f.rule == "LOCK001"]
    assert len(findings) == 1
    assert "bare_write" in findings[0].message


def test_lock001_ignores_pure_annotations():
    # `self._n: int` with no value is a type annotation — no store happens
    # at runtime, so it must not read as an unlocked write
    src = """
        import threading

        class C:
            def locked(self):
                with self._lock:
                    self._n = 1

            def declare(self):
                self._n: int
    """
    assert "LOCK001" not in rules_of(src)


def test_check_docs_in_sync_on_the_real_repo(capsys):
    assert lint.check_docs() == []
    assert lint.main(["--check-docs"]) == 0
    assert "agree" in capsys.readouterr().out


def test_check_docs_catches_drift_both_ways(tmp_path):
    doc = tmp_path / "STATIC_ANALYSIS.md"
    rows = "\n".join(f"| `{rid}` | x | x | x | x |"
                     for rid in sorted(RULES) if rid != "ASYNC001")
    doc.write_text(f"# rules\n\n{rows}\n| `ZZZ999` | x | x | x | x |\n")
    drift = lint.check_docs(str(doc))
    assert any("ASYNC001" in d for d in drift)      # catalog id missing a row
    assert any("ZZZ999" in d for d in drift)        # doc row without a rule


def test_findings_carry_location_and_hint():
    f = lint.lint_source("def f(xs=[]):\n    return xs\n", "somefile.py")[0]
    assert (f.rule, f.path, f.line) == ("MUT001", "somefile.py", 1)
    assert f.hint == RULES["MUT001"].hint
    assert "xs=[]" in f.content
    assert "somefile.py:1" in f.render()


# ---------------------------------------------------------------------------
# the real tree: zero unbaselined findings (the acceptance gate), and every
# baseline entry carries a reason
# ---------------------------------------------------------------------------

def test_lint_runs_clean_on_the_real_package():
    findings, n_files = lint.lint_paths(lint.default_targets())
    baseline = lint.load_baseline(lint.default_baseline_path())
    new, suppressed, stale = lint.apply_baseline(findings, baseline)
    assert n_files > 40          # the package + bench.py + scripts
    assert new == [], "unbaselined lint findings:\n" + "\n".join(
        f.render() for f in new)
    assert stale == [], f"stale baseline entries: {stale}"


def test_lint_loads_by_file_path_without_framework():
    # the check_telemetry.py discipline: the lint must run on hosts without
    # jax or the package installed — loaded by file path, stdlib only
    import subprocess
    import sys
    import pytorch_ddp_mnist_tpu.statics.lint as lint_mod
    code = f"""
import importlib.util, sys
spec = importlib.util.spec_from_file_location("sl", {lint_mod.__file__!r})
mod = importlib.util.module_from_spec(spec)
sys.modules["sl"] = mod
spec.loader.exec_module(mod)
(f,) = mod.lint_source("def f(xs=[]):\\n    return xs\\n", "x.py")
assert f.rule == "MUT001", f
# the concurrency pass rides the same file-path chain (lint -> rules ->
# concurrency, all loaded as siblings)
src = "import time\\nasync def h(q):\\n    time.sleep(1)\\n"
assert {{g.rule for g in mod.lint_source(src, "y.py")}} == {{"ASYNC001"}}
assert "jax" not in sys.modules and "pytorch_ddp_mnist_tpu" not in sys.modules
print("ok")
"""
    out = subprocess.run([sys.executable, "-c", code], cwd="/tmp",
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0 and out.stdout.strip() == "ok", out.stderr


def test_baseline_entries_all_have_reasons():
    baseline = lint.load_baseline(lint.default_baseline_path())
    assert baseline["entries"], "the committed baseline should carry the " \
                                "deliberate catch-all handlers"
    for e in baseline["entries"]:
        assert e["reason"].strip(), f"reasonless baseline entry: {e}"


# ---------------------------------------------------------------------------
# baseline semantics through the CLI entry point (in-process main())
# ---------------------------------------------------------------------------

BAD_SRC = "def f(xs=[]):\n    return xs\n"


def _write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


def test_new_finding_fails(tmp_path, capsys):
    target = _write(tmp_path, "mod.py", BAD_SRC)
    empty = _write(tmp_path, "base.json",
                   '{"version": 1, "entries": []}')
    rc = lint.main([target, "--baseline", empty])
    out = capsys.readouterr()
    assert rc == 1
    assert "MUT001" in out.out and "FAIL" in out.err


def test_baselined_finding_passes(tmp_path, capsys):
    target = _write(tmp_path, "mod.py", BAD_SRC)
    findings = lint.lint_source(BAD_SRC, target)  # path must match verbatim
    entry = {"rule": findings[0].rule, "file": findings[0].path,
             "content": findings[0].content, "reason": "test fixture"}
    base = _write(tmp_path, "base.json",
                  json.dumps({"version": 1, "entries": [entry]}))
    rc = lint.main([target, "--baseline", base])
    out = capsys.readouterr()
    assert rc == 0
    assert "1 baselined" in out.out
    assert "stale" not in out.err


def test_stale_entry_warns_and_prune_rewrites(tmp_path, capsys):
    target = _write(tmp_path, "mod.py", "x = 1\n")   # clean file
    stale_entry = {"rule": "MUT001", "file": "gone.py",
                   "content": "def f(xs=[]):", "reason": "obsolete"}
    base = _write(tmp_path, "base.json",
                  json.dumps({"version": 1, "entries": [stale_entry]}))
    rc = lint.main([target, "--baseline", base])
    out = capsys.readouterr()
    assert rc == 0                      # stale-only is clean...
    assert "stale baseline entry" in out.err   # ...but warned

    rc = lint.main([target, "--baseline", base, "--prune-baseline"])
    out = capsys.readouterr()
    assert rc == 0
    assert "pruned 1 stale" in out.err
    assert json.loads((tmp_path / "base.json").read_text())["entries"] == []
    # pruned file: a re-run is quiet
    rc = lint.main([target, "--baseline", base])
    assert "stale" not in capsys.readouterr().err and rc == 0


def test_malformed_baseline_is_usage_error(tmp_path, capsys):
    target = _write(tmp_path, "mod.py", "x = 1\n")
    base = _write(tmp_path, "base.json",
                  '{"version": 1, "entries": [{"rule": "EXC001"}]}')
    rc = lint.main([target, "--baseline", base])
    assert rc == 2
    assert "missing" in capsys.readouterr().err


def test_json_report_shape(tmp_path, capsys):
    target = _write(tmp_path, "mod.py", BAD_SRC)
    empty = _write(tmp_path, "base.json", '{"version": 1, "entries": []}')
    rc = lint.main([target, "--baseline", empty, "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert report["files"] == 1 and report["suppressed"] == 0
    (finding,) = report["findings"]
    assert finding["rule"] == "MUT001" and finding["line"] == 1


def test_front_door_dispatches_lint(tmp_path, capsys):
    # `python -m pytorch_ddp_mnist_tpu lint` routes here with argv passed
    # through (and the exit code preserved)
    from pytorch_ddp_mnist_tpu.__main__ import main as front_door
    target = _write(tmp_path, "mod.py", BAD_SRC)
    empty = _write(tmp_path, "base.json", '{"version": 1, "entries": []}')
    rc = front_door(["lint", target, "--baseline", empty])
    assert rc == 1
    assert "MUT001" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# the program auditor
# ---------------------------------------------------------------------------

ALL_CONFIGS = [(c, ov) for c in jaxpr_audit.COMMS for ov in (False, True)]


@pytest.mark.parametrize("comm,overlap", ALL_CONFIGS,
                         ids=[f"{c}{'-overlap' if ov else ''}"
                              for c, ov in ALL_CONFIGS])
def test_audit_step_matrix_passes(comm, overlap):
    report = jaxpr_audit.audit_step_program(comm, overlap)
    assert report.ok
    assert report.wire_bytes_program == report.wire_bytes_model
    assert report.n_buckets == 1          # 118k-param MLP: one bucket


@pytest.mark.parametrize("comm,overlap",
                         [("pmean", False), ("sharded", False),
                          ("bf16", True), ("int8", False)])
def test_audit_run_matrix_passes(comm, overlap):
    # the fit_cached scan body: collectives audited at the innermost scan
    # depth (the per-run pmean re-replication is correctly outside)
    report = jaxpr_audit.audit_run_program(comm, overlap)
    assert report.ok and report.form == "run"
    assert report.wire_bytes_program == report.wire_bytes_model


def test_audit_multi_bucket_layout():
    # a small bucket budget splits the MLP into 5 buckets; counts and the
    # byte model must follow the layout, not the single-bucket constants
    report = jaxpr_audit.audit_step_program("int8", bucket_elems=16384)
    assert report.n_buckets == 5
    assert report.wire_bytes_program == report.wire_bytes_model
    from pytorch_ddp_mnist_tpu.parallel import collectives
    import jax
    from pytorch_ddp_mnist_tpu.models.mlp import init_mlp
    assert report.wire_bytes_model == collectives.bytes_on_wire(
        init_mlp(jax.random.PRNGKey(0)), 8, "int8", bucket_elems=16384)


def test_broken_program_fails_wire_dtype():
    # THE acceptance pin: an "int8" path that actually allreduces f32
    # gradients (here: the pmean program audited under the int8 contract)
    # must fail with the NAMED wire-dtype contract, exit-code-visible.
    prog, args = jaxpr_audit.build_step_program("pmean")
    with pytest.raises(jaxpr_audit.AuditViolation) as exc:
        jaxpr_audit.audit_program(prog, args, "int8", False, "step")
    assert exc.value.contract == "wire-dtype"
    assert "float32" in str(exc.value)


def test_broken_program_fails_collective_shape():
    # a sharded program audited as pmean: right dtypes, wrong collective
    # kinds — the shape contract catches what the dtype contract cannot
    prog, args = jaxpr_audit.build_step_program("sharded")
    with pytest.raises(jaxpr_audit.AuditViolation) as exc:
        jaxpr_audit.audit_program(prog, args, "pmean", False, "step")
    assert exc.value.contract == "collective-shape"


def test_cost_model_drift_fails_wire_bytes(monkeypatch):
    from pytorch_ddp_mnist_tpu.parallel import collectives
    real = collectives.bytes_on_wire
    monkeypatch.setattr(collectives, "bytes_on_wire",
                        lambda *a, **k: real(*a, **k) + 1)
    with pytest.raises(jaxpr_audit.AuditViolation) as exc:
        jaxpr_audit.audit_step_program("bf16")
    assert exc.value.contract == "wire-bytes"


def test_synthetic_contracts_f64_callback_axis():
    mk = lambda **kw: jaxpr_audit.CollectiveOp(  # noqa: E731
        prim=kw.get("prim", "psum"), kind=kw.get("kind", "allreduce"),
        dtype=kw.get("dtype", "float32"), in_elems=kw.get("in_elems", 100),
        out_elems=kw.get("out_elems", 100),
        axes=kw.get("axes", ("dp",)), scan_depth=0, eqn_id=1)
    with pytest.raises(jaxpr_audit.AuditViolation) as exc:
        jaxpr_audit.audit_collected([], [("add", "float64")], [],
                                    "pmean", False, "step")
    assert exc.value.contract == "no-f64"
    with pytest.raises(jaxpr_audit.AuditViolation) as exc:
        jaxpr_audit.audit_collected([], [], ["pure_callback"],
                                    "pmean", False, "step")
    assert exc.value.contract == "no-callback"
    with pytest.raises(jaxpr_audit.AuditViolation) as exc:
        jaxpr_audit.audit_collected([mk(axes=("mp",))], [], [],
                                    "pmean", False, "step")
    assert exc.value.contract == "collective-axis"


def test_audit_donation_labels_stamped():
    # the donation-aliasing contract rides the matrix entry points: the
    # donated set lands on the report (and its JSON), resid only for the
    # stateful strategy
    rep = jaxpr_audit.audit_step_program("pmean")
    assert rep.donated_labels == ["key", "params"]
    assert rep.to_json()["donated"] == ["key", "params"]
    rep = jaxpr_audit.audit_run_program("int8")
    assert rep.donated_labels == ["key", "params", "resid"]


def test_broken_program_fails_donation_aliasing():
    # the acceptance pin: re-jit the step WITHOUT donate_argnums (the
    # silently-dropped-donation failure mode) — fails by name, naming the
    # first undonated declared input
    import jax
    step, args = jaxpr_audit.build_jit_step("int8", False)
    naked = jax.jit(lambda *a: step(*a))
    naked.donates = step.donates
    with pytest.raises(jaxpr_audit.AuditViolation) as exc:
        jaxpr_audit.audit_donation(naked, args, "int8", False, "step")
    assert exc.value.contract == "donation-aliasing"
    assert "declared donated" in str(exc.value)


def test_missing_donates_declaration_fails():
    import jax
    step, args = jaxpr_audit.build_jit_step("pmean", False)
    bare = jax.jit(lambda *a: step(*a))   # no .donates at all
    with pytest.raises(jaxpr_audit.AuditViolation) as exc:
        jaxpr_audit.audit_donation(bare, args, "pmean", False, "step")
    assert exc.value.contract == "donation-aliasing"
    assert ".donates" in str(exc.value)


def test_donation_cli_exit3(capsys, monkeypatch):
    # a dropped donation surfaces through the standard audit-program CLI
    # contract: exit 3 naming [donation-aliasing]
    import jax
    real = jaxpr_audit.build_jit_step

    def dropped(comm, overlap=False, **kw):
        step, args = real(comm, overlap, **kw)
        naked = jax.jit(lambda *a: step(*a))
        naked.donates = step.donates
        return naked, args

    monkeypatch.setattr(jaxpr_audit, "build_jit_step", dropped)
    rc = jaxpr_audit.main(["--comm", "pmean", "--form", "step"])
    err = capsys.readouterr().err
    assert rc == 3 and "[donation-aliasing]" in err


def test_donation_one_device_degrade():
    # world=1 (deviceless AbstractMesh, no collectives worth donating
    # around) still audits: same donation set, no violation
    rep = jaxpr_audit.audit_step_program("pmean", n_dev=1)
    assert rep.ok and rep.donated_labels == ["key", "params"]


def test_audit_cli_exit_codes(capsys, monkeypatch):
    rc = jaxpr_audit.main(["--comm", "int8", "--form", "step"])
    out = capsys.readouterr()
    assert rc == 0 and "every contract holds" in out.out

    monkeypatch.setattr(
        jaxpr_audit, "audit_matrix",
        lambda *a, **k: (_ for _ in ()).throw(jaxpr_audit.AuditViolation(
            "wire-dtype", "comm=int8", "patched")))
    rc = jaxpr_audit.main(["--comm", "int8", "--form", "step"])
    out = capsys.readouterr()
    assert rc == 3 and "[wire-dtype]" in out.err


def test_audit_cli_json_report(capsys):
    rc = jaxpr_audit.main(["--comm", "pmean", "--form", "step", "--json"])
    reports = json.loads(capsys.readouterr().out)
    assert rc == 0 and len(reports) == 1
    (r,) = reports
    assert r["comm"] == "pmean" and r["ok"]
    assert r["wire_bytes_program"] == r["wire_bytes_model"]
    assert all(op["axes"] == ["dp"] for op in r["payload_ops"])


def test_bench_statics_stamp():
    # the artifact-line stamp: lint + concurrency counts + audit verdict,
    # process-cached
    import bench
    bench.statics_stamp.cache_clear()
    stamp = bench.statics_stamp()
    assert stamp == {"lint_findings": 0, "concurrency_findings": 0,
                     "audit_ok": True}
    assert bench.statics_stamp() is stamp       # cached second read


def test_bench_statics_stamp_never_raises(monkeypatch):
    # a broken lint surface (unparsable scratch file, malformed baseline)
    # must degrade to null fields + error, never kill a finished
    # measurement (the registry_stamp contract)
    import bench
    from pytorch_ddp_mnist_tpu.statics import lint as lint_mod
    bench.statics_stamp.cache_clear()
    monkeypatch.setattr(
        lint_mod, "load_baseline",
        lambda p: (_ for _ in ()).throw(ValueError("malformed baseline")))
    try:
        stamp = bench.statics_stamp()
    finally:
        bench.statics_stamp.cache_clear()   # don't cache the broken stamp
    assert stamp["lint_findings"] is None
    assert stamp["concurrency_findings"] is None
    assert "malformed baseline" in stamp["error"]
    assert stamp["audit_ok"] is True        # the audit half still ran


def test_lint_cli_unparsable_target_is_usage_error(tmp_path, capsys):
    # documented exit contract: unreadable/unparsable target -> 2 (usage),
    # named on stderr — never a raw traceback
    rc = lint.main([str(tmp_path / "missing.py")])
    assert rc == 2
    assert "cannot lint target" in capsys.readouterr().err
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    rc = lint.main([str(bad)])
    assert rc == 2
    assert "broken.py" in capsys.readouterr().err
