"""The measurement-queue scripts' failure accounting (ADVICE r3): a pass
that collected nothing must exit nonzero — a driver keying on the exit code
can never mistake a dead-tunnel run for a complete one. The scripts probe
the backend in subprocesses; JAX_PLATFORMS names a platform that can never
exist, so the probe deterministically fails on ANY machine (a real backend
name like rocm could succeed where its plugin is installed and send
hw_window.sh down its measure-and-git-commit path)."""

import os
import pathlib
import subprocess

REPO = pathlib.Path(__file__).resolve().parent.parent
ENV = dict(os.environ, JAX_PLATFORMS="fakeplat")


def test_measure_hw_exits_nonzero_when_backend_never_up():
    # WAIT=0: the first failed probe always satisfies the deadline check —
    # WAIT=1 could race the wall-clock second and sleep 60s before retrying
    env = dict(ENV, PDMT_WINDOW_WAIT="0")
    out = subprocess.run(["bash", str(REPO / "scripts" / "measure_hw.sh")],
                         cwd=REPO, env=env, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 1
    assert "still unavailable" in out.stderr


def test_hw_window_gives_up_after_max_probes(tmp_path):
    env = dict(ENV, PDMT_WINDOW_POLL_MAX="1")
    sentinel = tmp_path / "never_written.json"
    out = subprocess.run(["bash", str(REPO / "scripts" / "hw_window.sh"),
                          str(sentinel)],
                         cwd=REPO, env=env, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 1
    assert "giving up" in out.stdout
    assert not sentinel.exists()
