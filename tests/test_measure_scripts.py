"""The measurement-queue scripts' failure accounting (ADVICE r3): a pass
that collected nothing must exit nonzero — a driver keying on the exit code
can never mistake a dead-tunnel run for a complete one. The scripts probe
the backend in subprocesses; JAX_PLATFORMS names a platform that can never
exist, so the probe deterministically fails on ANY machine (a real backend
name like rocm could succeed where its plugin is installed and send
hw_window.sh down its measure-and-git-commit path)."""

import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
ENV = dict(os.environ, JAX_PLATFORMS="fakeplat")


def test_measure_hw_exits_nonzero_when_backend_never_up():
    # WAIT=0: the first failed probe always satisfies the deadline check —
    # WAIT=1 could race the wall-clock second and sleep 60s before retrying
    env = dict(ENV, PDMT_WINDOW_WAIT="0")
    out = subprocess.run(["bash", str(REPO / "scripts" / "measure_hw.sh")],
                         cwd=REPO, env=env, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 1
    assert "still unavailable" in out.stderr


def test_hw_window_gives_up_after_max_probes(tmp_path):
    env = dict(ENV, PDMT_WINDOW_POLL_MAX="1")
    sentinel = tmp_path / "never_written.json"
    out = subprocess.run(["bash", str(REPO / "scripts" / "hw_window.sh"),
                          str(sentinel)],
                         cwd=REPO, env=env, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 1
    assert "giving up" in out.stdout
    assert not sentinel.exists()


# ---------------------------------------------------------------------------
# The promotion gate, end-to-end on realistic matrix artifacts (VERDICT r4
# weak #4 / next #6): the first unattended hardware window must not be the
# first time promote_epoch_dtype.py parses real input shapes. These feed the
# SCRIPT (not decide()) full bench_matrix.py-shaped JSON files and pin the
# calibration it writes or refuses, plus the rc contract measure_hw.sh keys
# on (0 = promoted, 1 = the reserved "not promoted" verdict, 2 = the gate
# itself crashed — ADVICE r4).
# ---------------------------------------------------------------------------

_GATE = REPO / "scripts" / "promote_epoch_dtype.py"
# exact labels the gate keys on (pinned against bench_matrix.VARIANTS by
# tests/test_bench.py::test_promote_gate_labels_and_matrix_explicitness)
_F32 = "f32 / whole-epoch kernel, uint8 streaming (single-chip headline)"
_BF16 = "bf16-matmul / whole-epoch kernel, uint8 streaming"
_SUP8 = "f32 / whole-epoch kernel / superstep 8"
_SUP8B = "bf16-matmul / whole-epoch kernel / superstep 8"


def _row(label, value, argv=("--kernel", "pallas_epoch")):
    # the full row shape bench_matrix.py commits, not a minimal stub
    return {"label": label, "argv": list(argv), "value": value,
            "unit": "images/sec/chip",
            "vs_baseline": None if value is None else round(value / 1e6, 4),
            "tflops": None if value is None else 12.3,
            "mfu_vs_197t_bf16": None if value is None else 4.5,
            **({} if value is not None else {"error": "timeout rc=124"})}


def _matrix(tmp_path, rows, name="matrix.json"):
    path = tmp_path / name
    path.write_text(json.dumps({
        "timestamp": "2026-08-01T00:00:00+00:00", "epochs_per_window": 400,
        "backend": "tpu", "device_kind": "TPU v5e", "jax_version": "0.9.0",
        "variants": rows}, indent=1))
    return path


def _run_gate(matrix_path, out_path):
    # Pin the gate subprocess to the CPU backend: on a TPU-attached host
    # the inherited env would let on_tpu_backend() see the real chip and
    # send the bf16 branch into 10-epoch hardware accuracy runs instead of
    # the deterministic off-hardware refusal these tests assert. (cpu, not
    # the module's fakeplat: the gate QUERIES the backend, it doesn't just
    # probe for liveness — fakeplat would crash the query into rc=2.)
    return subprocess.run(
        [sys.executable, str(_GATE), "--matrix", str(matrix_path),
         "--out", str(out_path), "--epochs", "1"],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=300)


def test_promote_script_f32_baseline_wins(tmp_path):
    m = _matrix(tmp_path, [_row(_F32, 36.9e6), _row(_BF16, 30e6),
                           _row(_SUP8, 35e6), _row(_SUP8B, 33e6)])
    out = tmp_path / "cal.json"
    r = _run_gate(m, out)
    assert r.returncode == 1, r.stderr
    assert "already fastest" in r.stderr
    assert not out.exists()


def test_promote_script_superstep_wins_writes_calibration(tmp_path):
    m = _matrix(tmp_path, [_row(_F32, 36.9e6), _row(_BF16, 30e6),
                           _row(_SUP8, 41e6), _row(_SUP8B, 33e6)])
    out = tmp_path / "cal.json"
    r = _run_gate(m, out)
    assert r.returncode == 0, r.stderr
    cal = json.loads(out.read_text())
    assert cal["epoch_kernel_dtype"] == "float32"
    assert cal["epoch_kernel_superstep"] == 8
    assert cal["evidence"]["winner"] == _SUP8
    assert cal["evidence"]["matrix"] == str(m)
    assert cal["evidence"]["matrix_timestamp"] == "2026-08-01T00:00:00+00:00"


def test_promote_script_bf16_win_refused_off_hardware(tmp_path):
    # A bf16 winner needs the 10-epoch accuracy gate ON THE CHIP; off
    # hardware the script must refuse (rc=1), never promote unverified.
    m = _matrix(tmp_path, [_row(_F32, 36.9e6), _row(_BF16, 55e6),
                           _row(_SUP8, 35e6), _row(_SUP8B, 33e6)])
    out = tmp_path / "cal.json"
    r = _run_gate(m, out)
    assert r.returncode == 1, r.stderr
    assert "real TPU" in r.stderr
    assert not out.exists()


def test_promote_script_incomplete_matrix_not_promoted(tmp_path):
    # a flaky window: candidate rows failed (value null + error field)
    m = _matrix(tmp_path, [_row(_F32, 36.9e6), _row(_BF16, None),
                           _row(_SUP8, None), _row(_SUP8B, None)])
    out = tmp_path / "cal.json"
    r = _run_gate(m, out)
    assert r.returncode == 1, r.stderr
    assert "unmeasured" in r.stderr
    assert not out.exists()
    # ... and a matrix whose baseline itself never measured
    m2 = _matrix(tmp_path, [_row(_F32, None), _row(_BF16, 55e6)], "m2.json")
    r = _run_gate(m2, out)
    assert r.returncode == 1 and "baseline" in r.stderr
    assert not out.exists()


def test_promote_script_crash_is_rc2_not_a_verdict(tmp_path):
    # missing matrix file and corrupt JSON are gate CRASHES (rc=2) —
    # distinguishable from the reserved rc=1 "not promoted" verdict so
    # measure_hw.sh can track them as phase failures (ADVICE r4)
    out = tmp_path / "cal.json"
    r = _run_gate(tmp_path / "nope.json", out)
    assert r.returncode == 2, (r.returncode, r.stderr)
    corrupt = tmp_path / "corrupt.json"
    corrupt.write_text("{not json")
    r = _run_gate(corrupt, out)
    assert r.returncode == 2, (r.returncode, r.stderr)
    assert not out.exists()


def test_bench_matrix_skip_defers_rows_without_running_them(tmp_path):
    # --skip records matching rows as explicit null-valued skips (never
    # launched, never retried) so measure_hw.sh can defer the
    # wedge-suspect superstep rows to its final phase; a skip-all pattern
    # makes the run instant and backend-free. The gate must read such an
    # artifact as "candidate rows unmeasured" -> not promoted (rc=1).
    out_json = tmp_path / "m.json"
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "bench_matrix.py"),
         "--skip", "/", "--epochs", "5", "--out", str(out_json)],
        cwd=REPO, env=ENV, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    rows = json.loads(out_json.read_text())["variants"]
    assert len(rows) == 24    # 14 kernel variants + 10 DDP comms/scale rows
    assert all(row["value"] is None and
               "skipped by --skip" in row["error"][0] for row in rows)
    assert "retry pass" not in r.stderr       # skips are not failures
    assert "(skipped)" in r.stdout and "(failed)" not in r.stdout
    cal = tmp_path / "cal.json"
    g = _run_gate(out_json, cal)
    assert g.returncode == 1 and not cal.exists()


def test_promote_script_small_superstep_wins(tmp_path):
    # K=2/K=4 joined the candidates after the r05 window left K=8
    # wedge-suspect: a safe small-K win must promote even when the K=8
    # rows never measured (deferred by --skip superstep), and superstep
    # alone needs no accuracy run (bitwise-equal math).
    _SUP4 = "f32 / whole-epoch kernel / superstep 4"
    m = _matrix(tmp_path, [
        _row(_F32, 36.9e6), _row(_BF16, 36.5e6),
        _row("f32 / whole-epoch kernel / superstep 2", 38e6),
        _row(_SUP4, 39.5e6), _row(_SUP8, None), _row(_SUP8B, None)])
    out = tmp_path / "cal.json"
    r = _run_gate(m, out)
    assert r.returncode == 0, r.stderr
    cal = json.loads(out.read_text())
    assert cal["epoch_kernel_dtype"] == "float32"
    assert cal["epoch_kernel_superstep"] == 4
    assert cal["evidence"]["winner"] == _SUP4
    assert sorted(cal["evidence"]["unmeasured_candidates"]) == [_SUP8B,
                                                               _SUP8]
    assert "no accuracy gate" in r.stderr


def test_bench_matrix_base_reuses_prior_window_rows(tmp_path):
    # measure_hw phase 5: rows excluded by --only are filled from the
    # phase-1 artifact (--base) instead of skipped, marked reused_from —
    # the gate then sees one complete same-window sweep. Rows in neither
    # set stay explicit skips. --only "nothing-matches" keeps the run
    # backend-free.
    base = _matrix(tmp_path, [_row(_F32, 36.9e6), _row(_BF16, None)],
                   "base.json")
    out_json = tmp_path / "m.json"
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "bench_matrix.py"),
         "--only", "no-such-label", "--base", str(base),
         "--epochs", "5", "--out", str(out_json)],
        cwd=REPO, env=ENV, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    rows = {row["label"]: row
            for row in json.loads(out_json.read_text())["variants"]}
    assert rows[_F32]["value"] == 36.9e6
    assert rows[_F32]["reused_from"] == str(base)
    # reused rows carry the BASE run's timestamp + backend identity inline
    # (ADVICE r5 #3) so merged-matrix provenance audits from the artifact
    # alone — the top-level fields describe the phase-5 run, not this row
    assert rows[_F32]["base_timestamp"] == "2026-08-01T00:00:00+00:00"
    assert rows[_F32]["base_backend"] == "tpu"
    assert rows[_F32]["base_device_kind"] == "TPU v5e"
    assert rows[_F32]["base_jax_version"] == "0.9.0"
    assert "base_timestamp" not in rows[_SUP8]  # plain skips: no base stamp
    # base had _BF16 unmeasured (value null) -> NOT reusable, stays a skip
    assert rows[_BF16]["value"] is None
    assert "skipped by --only" in rows[_BF16]["error"][0]
    assert rows[_SUP8]["value"] is None


def test_hw_window_multipass_retries_and_commits_per_pass(tmp_path):
    # The multi-pass loop (a window closing mid-queue re-polls and reruns)
    # exercised end-to-end in an ISOLATED throwaway git repo: a stub
    # measure script fails pass 1 and succeeds pass 2; the runner must
    # write per-pass artifacts (bench.json, then _p2-suffixed), commit
    # each pass, and exit 0 after the clean pass. JAX_PLATFORMS=cpu makes
    # the backend probe succeed instantly (cpu devices always exist).
    repo = tmp_path / "fake_repo"
    (repo / "scripts").mkdir(parents=True)
    import shutil
    shutil.copy(REPO / "scripts" / "hw_window.sh",
                repo / "scripts" / "hw_window.sh")
    stub = repo / "measure_stub.sh"
    stub.write_text(
        "#!/bin/bash\n"
        "echo measured > \"$1\"\n"
        "n=$(cat passes 2>/dev/null || echo 0); n=$((n+1)); echo $n > passes\n"
        "((n >= 2)) && exit 0 || exit 1\n")
    subprocess.run(["git", "init", "-q", "."], cwd=repo, check=True)
    subprocess.run(["git", "-c", "user.email=t@t", "-c", "user.name=t",
                    "commit", "-q", "--allow-empty", "-m", "root"],
                   cwd=repo, check=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PDMT_MEASURE_CMD="measure_stub.sh",
               GIT_AUTHOR_EMAIL="t@t", GIT_AUTHOR_NAME="t",
               GIT_COMMITTER_EMAIL="t@t", GIT_COMMITTER_NAME="t")
    # without this the axon plugin registers in the probe subprocess and
    # hangs on the dead tunnel regardless of JAX_PLATFORMS (sitecustomize)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        ["bash", "scripts/hw_window.sh", "bench.json"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert (repo / "bench.json").exists()          # pass 1 artifact
    assert (repo / "bench_p2.json").exists()       # pass 2, not overwritten
    assert (repo / "bench_sweep.log").exists()
    assert (repo / "bench_p2_sweep.log").exists()
    assert "re-polling" in out.stdout and "pass 2" in out.stdout
    log = subprocess.run(["git", "log", "--oneline"], cwd=repo,
                         capture_output=True, text=True).stdout
    assert "measurement pass 1 (bench.json)" in log
    assert "measurement pass 2 (bench_p2.json)" in log
