"""SPMD data-parallel step on the 8-virtual-device CPU mesh — the analog of
the reference's 4-process gloo cluster stand-in (SURVEY.md §4 item 2).

Checks the DDP parity contract (SURVEY.md §7 item 4): grad-mean semantics
(DP result == serial result on the same global batch, up to dropout RNG),
replica-independent dropout, and replicated params staying in sync."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_ddp_mnist_tpu.models import init_mlp, mlp_apply
from pytorch_ddp_mnist_tpu.ops import cross_entropy, sgd_step
from pytorch_ddp_mnist_tpu.parallel.ddp import (
    make_dp_train_step, batch_sharding, replicated)
from pytorch_ddp_mnist_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() >= 8, "conftest should provide 8 virtual devices"
    return make_mesh([8], ["dp"], jax.devices()[:8])


def _batch(n, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 784)).astype(np.float32)
    y = rng.integers(0, 10, size=n).astype(np.int32)
    return x, y


def test_dp_step_runs_and_params_replicated(mesh):
    step = make_dp_train_step(mesh, lr=0.01)
    params = jax.device_put(init_mlp(jax.random.key(0)), replicated(mesh))
    key = jax.device_put(jax.random.key(1), replicated(mesh))
    x, y = _batch(8 * 16)
    xs = jax.device_put(x, batch_sharding(mesh))
    ys = jax.device_put(y, batch_sharding(mesh))
    params, key, loss = step(params, key, xs, ys)
    assert np.isfinite(float(loss))
    # Update must be identical on every device (DDP redundant-optimizer
    # invariant): fully-replicated output sharding guarantees it; fetch and
    # sanity check values are finite.
    w = np.asarray(params["fc1"]["w"])
    assert np.all(np.isfinite(w))


def test_dp_grad_mean_matches_serial_no_dropout(mesh):
    """With dropout removed, one DP step == one serial step on the global
    batch: gradient pmean == global batch mean. This is the allreduce
    semantics check."""
    lr = 0.05
    x, y = _batch(8 * 8, seed=3)
    params0 = init_mlp(jax.random.key(2))

    def loss_fn(p, x, y):
        return cross_entropy(mlp_apply(p, x, train=False), y)

    # Serial reference step.
    g = jax.grad(loss_fn)(params0, jnp.asarray(x), jnp.asarray(y))
    serial = sgd_step(params0, g, lr)

    # DP step via shard_map psum-mean (eval-mode forward to drop RNG noise).
    from jax.sharding import PartitionSpec as P
    from pytorch_ddp_mnist_tpu.compat import shard_map
    from pytorch_ddp_mnist_tpu.parallel.ddp import _pvary

    def shard_fn(p, x, y):
        p = _pvary(p, "dp")  # local copies: grads reduce ONLY via our pmean
        loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
        return jax.lax.pmean(grads, "dp")

    dp = jax.jit(shard_map(shard_fn, mesh=mesh,
                           in_specs=(P(), P("dp"), P("dp")),
                           out_specs=P()))
    xs = jax.device_put(x, batch_sharding(mesh))
    ys = jax.device_put(y, batch_sharding(mesh))
    grads = dp(jax.device_put(params0, replicated(mesh)), xs, ys)
    dp_params = sgd_step(params0, grads, lr)
    for a, b in zip(jax.tree_util.tree_leaves(serial),
                    jax.tree_util.tree_leaves(dp_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6)


def test_dropout_masks_differ_across_replicas(mesh):
    """Each replica must draw an independent mask (SURVEY §7 item 4). Feed the
    SAME example to all 8 replicas; train-mode outputs must differ between
    replicas (shared mask would make them identical)."""
    from jax.sharding import PartitionSpec as P
    from pytorch_ddp_mnist_tpu.compat import shard_map

    params = init_mlp(jax.random.key(0))
    x_one = np.random.default_rng(5).normal(size=(1, 784)).astype(np.float32)
    x = np.repeat(x_one, 8, axis=0)

    def shard_fn(p, key, x):
        rkey = jax.random.fold_in(key, jax.lax.axis_index("dp"))
        return mlp_apply(p, x, train=True, dropout_key=rkey)

    f = jax.jit(shard_map(shard_fn, mesh=mesh,
                          in_specs=(P(), P(), P("dp")),
                          out_specs=P("dp")))
    out = np.asarray(f(jax.device_put(params, replicated(mesh)),
                       jax.device_put(jax.random.key(9), replicated(mesh)),
                       jax.device_put(x, batch_sharding(mesh))))
    # At least some pairs of replica outputs must differ.
    diffs = [not np.allclose(out[i], out[j])
             for i in range(8) for j in range(i + 1, 8)]
    assert any(diffs)


def test_dp_training_reduces_loss(mesh):
    from pytorch_ddp_mnist_tpu.data import synthetic_mnist, normalize_images
    split = synthetic_mnist(8 * 64, seed=0)
    x = normalize_images(split.images)
    y = split.labels.astype(np.int32)
    step = make_dp_train_step(mesh, lr=0.05)
    params = jax.device_put(init_mlp(jax.random.key(0)), replicated(mesh))
    key = jax.device_put(jax.random.key(1), replicated(mesh))
    losses = []
    for epoch in range(6):
        for i in range(4):
            xb = jax.device_put(x[i * 128:(i + 1) * 128], batch_sharding(mesh))
            yb = jax.device_put(y[i * 128:(i + 1) * 128], batch_sharding(mesh))
            params, key, loss = step(params, key, xb, yb)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9


def test_bf16_compute_path(mesh):
    step = make_dp_train_step(mesh, lr=0.01, dtype="bfloat16")
    params = jax.device_put(init_mlp(jax.random.key(0)), replicated(mesh))
    key = jax.device_put(jax.random.key(1), replicated(mesh))
    x, y = _batch(8 * 8)
    params, key, loss = step(params, key,
                             jax.device_put(x, batch_sharding(mesh)),
                             jax.device_put(y, batch_sharding(mesh)))
    assert np.isfinite(float(loss))
    # master params stay float32
    assert params["fc1"]["w"].dtype == jnp.float32


def test_shard_batch_ragged_raises_named_error(mesh):
    """A batch not divisible by the mesh size used to surface as an opaque
    XLA sharding error; it must now be a ValueError naming the batch size
    and device count."""
    from pytorch_ddp_mnist_tpu.parallel.ddp import shard_batch
    x, y = _batch(30)
    with pytest.raises(ValueError) as ei:
        shard_batch(mesh, (x, y))
    assert "30" in str(ei.value) and "8" in str(ei.value)


def test_global_batch_from_local_ragged_raises_named_error(mesh):
    from pytorch_ddp_mnist_tpu.parallel.ddp import global_batch_from_local
    x, y = _batch(30)
    with pytest.raises(ValueError) as ei:
        global_batch_from_local(mesh, (x, y))
    assert "30" in str(ei.value) and "8" in str(ei.value)


def test_shard_batch_divisible_still_works(mesh):
    from pytorch_ddp_mnist_tpu.parallel.ddp import shard_batch
    x, y = _batch(32)
    xs, ys = shard_batch(mesh, (x, y))
    np.testing.assert_array_equal(np.asarray(xs), x)
    np.testing.assert_array_equal(np.asarray(ys), y)


def test_comm_strategies_run_and_losses_close(mesh):
    """Every comm strategy builds, runs, and reports (to strategy
    tolerance) the same loss on the same batch — the single-process smoke
    of the deeper parity suite in test_collectives.py."""
    from pytorch_ddp_mnist_tpu.parallel import COMM_STRATEGIES
    x, y = _batch(8 * 8, seed=11)
    losses = {}
    for comm in COMM_STRATEGIES:
        step = make_dp_train_step(mesh, lr=0.01, comm=comm)
        assert step.ddp_comm == comm and step.ddp_devices == 8
        params = jax.device_put(init_mlp(jax.random.key(0)),
                                replicated(mesh))
        key = jax.device_put(jax.random.key(1), replicated(mesh))
        args = [params, key,
                jax.device_put(x, batch_sharding(mesh)),
                jax.device_put(y, batch_sharding(mesh))]
        if step.comm_state:      # int8 threads its error-feedback state
            args.append(step.place_comm_state(None, params))
        loss = step(*args)[2]
        losses[comm] = float(loss)
    assert np.allclose(losses["sharded"], losses["pmean"], rtol=1e-6)
    assert np.allclose(losses["bf16"], losses["pmean"], rtol=1e-3)
    assert np.allclose(losses["int8"], losses["pmean"], rtol=1e-3)


def test_replicate_state_preserves_rbg_key_impl(mesh):
    """replicate_state must rewrap PRNG keys with their own engine — an rbg
    key (key_data shape (4,), not threefry's (2,)) used to crash the DP
    --impl rbg path at wrap_key_data."""
    from pytorch_ddp_mnist_tpu.parallel.ddp import replicate_state

    key = jax.random.key(7, impl="rbg")
    out = replicate_state(mesh, {"k": key})["k"]
    assert str(jax.random.key_impl(out)) == str(jax.random.key_impl(key))
    # and it must actually work as a key on the mesh
    assert np.isfinite(
        float(jax.random.uniform(jax.random.fold_in(out, 3))))
