"""The trace analysis layer: span-tree reconstruction + per-phase stats
(telemetry/analysis.py), Chrome/Perfetto export (telemetry/export.py), the
step-time regression gate (`trace report --baseline`), the flight recorder
(telemetry/flight.py) and its wireup/serve/bench wiring, and REAL 2-process
trace aggregation via the mp_worker launch pattern."""

import json
import os
import subprocess
import sys

import pytest

from pytorch_ddp_mnist_tpu import telemetry
from pytorch_ddp_mnist_tpu.telemetry import analysis, export, flight
from pytorch_ddp_mnist_tpu.cli import trace as trace_cli

# the checker script, file-loaded (repo idiom, see test_telemetry)
import importlib.util
import pathlib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "check_telemetry_for_analysis",
    pathlib.Path(__file__).resolve().parents[1] / "scripts"
    / "check_telemetry.py")
_checker = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_checker)
check_main = _checker.main


# ---------------------------------------------------------------------------
# trace fabrication helpers
# ---------------------------------------------------------------------------

def _emit_run(path, proc, step_durs, *, data_wait=0.002, eval_s=0.004):
    """Write one process's trace: one epoch span per entry of `step_durs`,
    with the train loop's aggregate children at fabricated durations."""
    tr = telemetry.EventTrace(str(path), process_index=proc)
    for epoch, dur in enumerate(step_durs):
        with tr.span("epoch", epoch=epoch):
            tr.complete_span("data_wait", data_wait, batches=2)
            tr.complete_span("step_compute", dur, steps=2)
            tr.complete_span("eval", eval_s)
    reg = telemetry.MetricsRegistry()
    reg.counter("xla.compiles").inc(3)
    reg.gauge("host.rss_bytes").set(1 << 20)
    tr.snapshot(reg)
    tr.close()
    return str(path)


def _rec(**kw):
    base = {"v": 1, "kind": "point", "name": "x", "t_wall": 1.0,
            "t_mono": 1.0, "proc": 0}
    base.update(kw)
    return json.dumps(base)


def _write(tmp_path, lines, name="events.jsonl"):
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    return str(tmp_path)


# ---------------------------------------------------------------------------
# analysis: report structure and statistics
# ---------------------------------------------------------------------------

def test_analyze_single_process_report(tmp_path):
    f = _emit_run(tmp_path / "events.jsonl", 0, [0.010, 0.012, 0.011])
    rep = analysis.analyze([f])
    assert rep["n_processes"] == 1 and rep["processes"] == [0]
    assert rep["span_errors"] == []
    assert rep["snapshots"] == 1
    ph = rep["phases"]
    assert set(ph) == {"data_wait", "step_compute", "eval"}
    assert ph["step_compute"]["n"] == 3
    assert ph["step_compute"]["p50_s"] == pytest.approx(0.011)
    assert ph["step_compute"]["max_s"] == pytest.approx(0.012)
    assert ph["step_compute"]["p95_s"] == pytest.approx(0.012)
    assert rep["epochs"]["count"] == 3
    # single process: nothing to compare across ranks
    assert rep["straggler"]["epochs_compared"] == 0
    json.dumps(rep)                                 # machine-readable


def test_analyze_epoch_trend_detects_slowdown(tmp_path):
    # epoch durations grow monotonically -> positive trend (%/epoch)
    f = _emit_run(tmp_path / "events.jsonl", 0, [0.01] * 4)
    rep = analysis.analyze([f])
    trend = analysis._linear_trend_pct([1.0, 1.1, 1.2, 1.3])
    assert trend == pytest.approx(100 * 0.1 / 1.15, rel=1e-6)
    assert analysis._linear_trend_pct([1.0]) is None
    assert rep["epochs"]["trend_pct_per_epoch"] is not None


def test_analyze_keeps_appended_segments_apart(tmp_path):
    """Append mode is a designed feature (outage resume / repeat runs):
    the second run's epochs 0..N must not last-wins-overwrite the first
    run's in the per-epoch view, and each segment gets its own wall/mono
    clock offset (perf_counter restarts across re-execs)."""
    path = tmp_path / "events.jsonl"
    _emit_run(path, 0, [0.010, 0.010])
    _emit_run(path, 0, [0.030, 0.030])   # EventTrace appends: segment 2
    rep = analysis.analyze([str(path)])
    assert rep["span_errors"] == []
    assert rep["epochs"]["count"] == 4           # 2 + 2, not max() = 2
    assert len(rep["epochs"]["durations_s"]) == 4
    # BOTH runs' step_compute aggregates pooled in the phase stats
    assert rep["phases"]["step_compute"]["n"] == 4
    assert rep["phases"]["step_compute"]["max_s"] == pytest.approx(0.030)
    # and the appended file still exports with every event at sane stamps
    doc = export.chrome_trace([str(path)])
    _validate_chrome(doc)


def test_percentile_nearest_rank():
    vals = sorted([0.1, 0.2, 0.3, 0.4])
    assert analysis._percentile(vals, 0.50) == 0.2
    assert analysis._percentile(vals, 0.95) == 0.4
    assert analysis._percentile([], 0.5) == 0.0


def test_clock_offset_is_median_of_stamp_pairs():
    recs = [{"t_wall": 100.0 + m, "t_mono": m} for m in (1.0, 2.0, 3.0)]
    recs.append({"t_wall": 999.0, "t_mono": 4.0})   # one delayed outlier
    assert analysis.clock_offset(recs) == pytest.approx(100.0)


# ---------------------------------------------------------------------------
# span-tree reconstruction: structural violations
# ---------------------------------------------------------------------------

def test_span_structure_real_trace_is_clean(tmp_path):
    f = _emit_run(tmp_path / "events.jsonl", 0, [0.01, 0.01])
    records, errors = analysis.load_trace(f)
    assert errors == []
    for seg in analysis.split_segments(records):
        assert analysis.span_structure_errors(seg) == []


def _seg(lines):
    recs = [json.loads(ln) for ln in lines]
    for i, r in enumerate(recs, 1):
        r["_line"] = i
    return recs


def test_span_structure_flags_orphan_duplicate_crossing_and_bad_exit():
    orphan = _seg([_rec(kind="span", name="c", span=2, parent=77,
                        dur_s=0.1)])
    assert any("never recorded" in msg
               for _ln, msg in analysis.span_structure_errors(orphan))

    dup = _seg([
        _rec(kind="span", name="a", span=1, parent=None, dur_s=0.1,
             t_mono=1.0),
        _rec(kind="span", name="b", span=1, parent=None, dur_s=0.1,
             t_mono=2.0),
    ])
    assert any("duplicate span id" in msg
               for _ln, msg in analysis.span_structure_errors(dup))

    # child [0.5, 1.1] pokes out of parent [0.0, 1.0]
    crossing = _seg([
        _rec(kind="span", name="child", span=2, parent=1, dur_s=0.6,
             t_mono=1.1, attrs={"t0_mono": 0.5, "t0_wall": 0.5}),
        _rec(kind="span", name="parent", span=1, parent=None, dur_s=1.0,
             t_mono=1.15, attrs={"t0_mono": 0.0, "t0_wall": 0.0}),
    ])
    assert any("crosses" in msg
               for _ln, msg in analysis.span_structure_errors(crossing))

    # exit stamp (t0 + dur = 7.0) lands after the emission stamp (6.0):
    # an exit with no matching enter
    bad_exit = _seg([
        _rec(kind="span", name="ghost", span=1, parent=None, dur_s=2.0,
             t_mono=6.0, attrs={"t0_mono": 5.0}),
    ])
    assert any("no matching enter" in msg
               for _ln, msg in analysis.span_structure_errors(bad_exit))


def test_checker_rejects_structural_violations(tmp_path, capsys):
    """The checker satellite: span-STRUCTURE violations (shared
    reconstructor) exit nonzero with named messages."""
    crossing = [
        _rec(kind="meta", name="trace_start", t_mono=0.0),
        _rec(kind="span", name="child", span=2, parent=1, dur_s=0.6,
             t_mono=1.1, attrs={"t0_mono": 0.5}),
        _rec(kind="span", name="parent", span=1, parent=None, dur_s=1.0,
             t_mono=1.15, attrs={"t0_mono": 0.0}),
    ]
    assert check_main([_write(tmp_path, crossing)]) == 1
    assert "crosses" in capsys.readouterr().err

    dup = [
        _rec(kind="span", name="a", span=1, dur_s=0.1, t_mono=1.0),
        _rec(kind="span", name="b", span=1, dur_s=0.1, t_mono=2.0),
    ]
    assert check_main([_write(tmp_path, dup)]) == 1
    assert "duplicate span id" in capsys.readouterr().err

    ghost = [_rec(kind="span", name="g", span=1, dur_s=2.0, t_mono=6.0,
                  attrs={"t0_mono": 5.0})]
    assert check_main([_write(tmp_path, ghost)]) == 1
    assert "no matching enter" in capsys.readouterr().err


def test_checker_still_accepts_real_emitted_trace(tmp_path):
    _emit_run(tmp_path / "events.jsonl", 0, [0.01, 0.01])
    assert check_main([str(tmp_path)]) == 0


# ---------------------------------------------------------------------------
# the regression gate
# ---------------------------------------------------------------------------

def test_compare_flags_2x_step_compute_regression(tmp_path):
    base = _emit_run(tmp_path / "base.jsonl", 0, [0.010] * 4)
    slow = _emit_run(tmp_path / "slow.jsonl", 0, [0.020] * 4)
    diff = analysis.compare(analysis.analyze([slow]),
                            analysis.analyze([base]), threshold=1.5)
    regressed = {(r["phase"], r["stat"]) for r in diff["regressions"]}
    assert ("step_compute", "p50_s") in regressed
    # unchanged phases pass
    assert not any(r["phase"] == "eval" for r in diff["regressions"])
    # and the inverse comparison (things got FASTER) gates nothing
    diff_fast = analysis.compare(analysis.analyze([base]),
                                 analysis.analyze([slow]), threshold=1.5)
    assert diff_fast["regressions"] == []


def test_compare_ignores_sub_millisecond_noise(tmp_path):
    base = _emit_run(tmp_path / "base.jsonl", 0, [0.010], eval_s=0.0001)
    new = _emit_run(tmp_path / "new.jsonl", 0, [0.010], eval_s=0.0003)
    diff = analysis.compare(analysis.analyze([new]),
                            analysis.analyze([base]), threshold=1.5)
    assert not any(r["phase"] == "eval" for r in diff["regressions"])


def test_trace_cli_report_baseline_gate_exit_codes(tmp_path, capsys):
    base_dir, slow_dir = tmp_path / "base", tmp_path / "slow"
    base_dir.mkdir(), slow_dir.mkdir()
    _emit_run(base_dir / "events.jsonl", 0, [0.010] * 4)
    _emit_run(slow_dir / "events.jsonl", 0, [0.020] * 4)
    # a run gated against itself passes (the trace-smoke round-trip)
    assert trace_cli.main(["report", str(base_dir),
                           "--baseline", str(base_dir)]) == 0
    capsys.readouterr()
    # the injected 2x step_compute regression exits 3
    rc = trace_cli.main(["report", str(slow_dir),
                         "--baseline", str(base_dir)])
    assert rc == 3
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "step_compute" in out and "FAIL" in out


def _ddp_artifact(effs: dict) -> dict:
    """A MULTICHIP_r0X.json-shaped artifact from {label: efficiency}
    (label 'strategy' or 'strategy+overlap')."""
    return {"n_devices": 8, "ok": True, "strategies": [
        {"strategy": lbl.split("+")[0], "overlap": lbl.endswith("+overlap"),
         "scaling_efficiency_vs_1dev": eff,
         "images_per_sec": 1000.0} for lbl, eff in effs.items()]}


def test_efficiency_report_from_ddp_artifact():
    """The MULTICHIP artifact adapter: one efficiency entry per strategy
    row, overlap rows labeled apart, device count in every label (from
    the artifact when rows don't carry it), malformed rows skipped."""
    art = _ddp_artifact({"pmean": 0.30, "sharded": 0.25,
                         "sharded+overlap": 0.33})
    art["strategies"].append({"strategy": "bf16"})       # no efficiency
    art["strategies"].append("not-a-dict")
    rep = analysis.efficiency_report(art, path="r07.json")
    assert rep["report"] == "trace_phase_stats"
    assert rep["records"] == 3
    assert rep["efficiency"] == {"pmean@8dev": 0.30, "sharded@8dev": 0.25,
                                 "sharded+overlap@8dev": 0.33}
    assert rep["phases"] == {}


def test_efficiency_labels_carry_workload():
    """Efficiency rows measured on different --model/--param_scale
    workloads or device counts must NEVER gate against each other: a
    scale-16 pmean row is not a regression of a scale-1 pmean row, and a
    per-chip efficiency measured on 8 devices is not a regression of one
    measured on 4 (it always falls with device count). Non-default
    workloads get `@model xN` labels, device counts `@Ndev`; legacy rows
    without the workload fields are the default mlp x1."""
    r06 = analysis.efficiency_report(_ddp_artifact({"pmean": 0.1991}))
    art16 = _ddp_artifact({"pmean": 0.1094})
    for row in art16["strategies"]:
        row["model"] = "mlp"
        row["param_scale"] = 16
    r07 = analysis.efficiency_report(art16)
    assert r07["efficiency"] == {"pmean@mlp x16@8dev": 0.1094}
    # zero shared labels -> zero rows -> no false exit-3 regression
    assert analysis.compare(r07, r06, threshold=1.5)["rows"] == []
    # different pool size: same strategy, same workload, no pairing
    art4 = _ddp_artifact({"pmean": 0.30})
    art4["n_devices"] = 4
    r4 = analysis.efficiency_report(art4)
    assert r4["efficiency"] == {"pmean@4dev": 0.30}
    assert analysis.compare(r06, r4, threshold=1.5)["rows"] == []
    # explicit default workload stamps collapse to the bare legacy label
    art1 = _ddp_artifact({"pmean": 0.2})
    for row in art1["strategies"]:
        row["model"] = "mlp"
        row["param_scale"] = 1
    assert analysis.efficiency_report(art1)["efficiency"] == {
        "pmean@8dev": 0.2}


def test_compare_gates_efficiency_drop():
    """ROADMAP item 2's tail: a scaling-efficiency drop past the threshold
    regresses (exit-3 material) exactly like a step-time blowup; an
    efficiency IMPROVEMENT gates nothing; strategies missing from either
    side are not compared."""
    old = analysis.efficiency_report(_ddp_artifact(
        {"pmean": 0.30, "sharded": 0.20, "int8": 0.10}))
    new = analysis.efficiency_report(_ddp_artifact(
        {"pmean": 0.13, "sharded": 0.30, "bf16": 0.05}))
    diff = analysis.compare(new, old, threshold=1.5)
    labels = {r["phase"]: r for r in diff["rows"]}
    assert set(labels) == {"pmean@8dev", "sharded@8dev"}  # int8/bf16 unpaired
    assert labels["pmean@8dev"]["regressed"]          # 0.30 -> 0.13 = 2.3x
    assert labels["pmean@8dev"]["stat"] == analysis.EFFICIENCY_STAT
    assert not labels["sharded@8dev"]["regressed"]    # it IMPROVED
    assert [r["phase"] for r in diff["regressions"]] == ["pmean@8dev"]
    # the ratio convention matches the time rows: bigger = worse
    assert labels["pmean@8dev"]["ratio"] == pytest.approx(0.30 / 0.13)


def test_compare_gates_efficiency_collapse_to_zero():
    """A total efficiency collapse (the artifact rounds to 4 decimals, so
    a dead strategy lands as exactly 0.0) is the WORST regression — it
    must gate with an infinite ratio, never be filtered as an unpaired
    row."""
    old = analysis.efficiency_report(_ddp_artifact(
        {"pmean": 0.30, "sharded": 0.20}))
    new = analysis.efficiency_report(_ddp_artifact(
        {"pmean": 0.0, "sharded": 0.21}))
    diff = analysis.compare(new, old, threshold=1.5)
    labels = {r["phase"]: r for r in diff["rows"]}
    assert labels["pmean@8dev"]["regressed"]
    assert labels["pmean@8dev"]["ratio"] == float("inf")
    assert [r["phase"] for r in diff["regressions"]] == ["pmean@8dev"]
    # baseline-side zero stays uncomparable (no signal to regress FROM)
    old0 = analysis.efficiency_report(_ddp_artifact({"pmean": 0.0}))
    new0 = analysis.efficiency_report(_ddp_artifact({"pmean": 0.1}))
    assert analysis.compare(new0, old0, threshold=1.5)["rows"] == []


def test_trace_cli_gates_multichip_artifact(tmp_path, capsys):
    """The front door: `trace report NEW.json --baseline OLD.json` over
    DDP bench artifacts exits 3 on an efficiency regression, 0 when
    efficiency held, 1 when an artifact carries no gateable rows."""
    old = tmp_path / "MULTICHIP_old.json"
    good = tmp_path / "MULTICHIP_good.json"
    bad = tmp_path / "MULTICHIP_bad.json"
    old.write_text(json.dumps(_ddp_artifact({"pmean": 0.30, "int8": 0.20})))
    good.write_text(json.dumps(_ddp_artifact({"pmean": 0.31, "int8": 0.22})))
    bad.write_text(json.dumps(_ddp_artifact({"pmean": 0.30, "int8": 0.08})))
    assert trace_cli.main(["report", str(good),
                           "--baseline", str(old)]) == 0
    capsys.readouterr()
    rc = trace_cli.main(["report", str(bad), "--baseline", str(old)])
    assert rc == 3
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "int8" in out
    # row-less artifact: a named failure, not a silent pass
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"strategies": [], "ok": False}))
    assert trace_cli.main(["report", str(empty),
                           "--baseline", str(old)]) == 1
    assert "no strategy rows" in capsys.readouterr().err


def test_trace_cli_report_accepts_saved_json_baseline(tmp_path, capsys):
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    _emit_run(run_dir / "events.jsonl", 0, [0.010] * 3)
    assert trace_cli.main(["report", str(run_dir), "--json"]) == 0
    saved = tmp_path / "report.json"
    saved.write_text(capsys.readouterr().out)
    assert trace_cli.main(["report", str(run_dir),
                           "--baseline", str(saved)]) == 0
    # the COMBINED --baseline --json document round-trips too (its nested
    # report unwraps), instead of silently gating nothing
    capsys.readouterr()
    assert trace_cli.main(["report", str(run_dir), "--baseline",
                           str(saved), "--json"]) == 0
    combined = tmp_path / "combined.json"
    combined.write_text(capsys.readouterr().out)
    assert trace_cli.main(["report", str(run_dir),
                           "--baseline", str(combined)]) == 0


def test_trace_cli_gate_refuses_to_pass_on_zero_overlap(tmp_path, capsys):
    """A baseline whose phases share nothing with the new run means the
    gate compared NOTHING — that must be a named failure (exit 1), not a
    silent PASS that lets renamed-span regressions through CI."""
    run_dir, empty_dir = tmp_path / "run", tmp_path / "empty"
    run_dir.mkdir(), empty_dir.mkdir()
    _emit_run(run_dir / "events.jsonl", 0, [0.010] * 2)
    tr = telemetry.EventTrace(str(empty_dir / "events.jsonl"),
                              process_index=0)
    tr.point("no_phases_here")
    tr.close()
    assert trace_cli.main(["report", str(run_dir),
                           "--baseline", str(empty_dir)]) == 1
    assert "gate checked nothing" in capsys.readouterr().err


def test_trace_cli_report_prints_phases_and_errors(tmp_path, capsys):
    assert trace_cli.main(["report", str(tmp_path / "nope")]) == 1
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    _emit_run(run_dir / "events.jsonl", 0, [0.01, 0.01])
    assert trace_cli.main(["report", str(run_dir)]) == 0
    out = capsys.readouterr().out
    assert "p50_s" in out and "step_compute" in out
    assert "span structure: OK" in out


# ---------------------------------------------------------------------------
# Chrome trace-event export
# ---------------------------------------------------------------------------

_VALID_PH = {"X", "i", "C", "M"}


def _validate_chrome(doc):
    """The schema the acceptance names: valid Chrome trace-event JSON."""
    assert isinstance(doc, dict) and isinstance(doc["traceEvents"], list)
    for ev in doc["traceEvents"]:
        assert ev["ph"] in _VALID_PH
        assert isinstance(ev["name"], str) and ev["name"]
        assert isinstance(ev["pid"], int)
        if ev["ph"] != "M":
            assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        if ev["ph"] == "C":
            assert isinstance(ev["args"]["value"], (int, float))
    json.loads(json.dumps(doc))                     # round-trips verbatim


def test_chrome_export_schema_and_tracks(tmp_path):
    _emit_run(tmp_path / "events.jsonl", 0, [0.01, 0.01])
    _emit_run(tmp_path / "events.rank1.jsonl", 1, [0.01, 0.01])
    doc = export.chrome_trace(analysis.trace_files(str(tmp_path)))
    _validate_chrome(doc)
    evs = doc["traceEvents"]
    assert {ev["pid"] for ev in evs} == {0, 1}      # one track per process
    x_names = {ev["name"] for ev in evs if ev["ph"] == "X"}
    assert {"epoch", "data_wait", "step_compute", "eval"} <= x_names
    # live spans and aggregates ride separate threads
    tids = {ev["name"]: ev["tid"] for ev in evs if ev["ph"] == "X"}
    assert tids["epoch"] != tids["step_compute"]
    # registry snapshot became counter tracks
    counters = {ev["name"] for ev in evs if ev["ph"] == "C"}
    assert {"xla.compiles", "host.rss_bytes"} <= counters
    # process metadata names both tracks
    meta = [ev for ev in evs if ev["ph"] == "M"
            and ev["name"] == "process_name"]
    assert len(meta) == 2


def test_write_chrome_trace_file(tmp_path):
    f = _emit_run(tmp_path / "events.jsonl", 0, [0.01])
    out = tmp_path / "trace.chrome.json"
    n = export.write_chrome_trace([f], str(out))
    assert n > 0
    _validate_chrome(json.loads(out.read_text()))


def test_trace_cli_export(tmp_path, capsys):
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    _emit_run(run_dir / "events.jsonl", 0, [0.01])
    out = tmp_path / "t.json"
    assert trace_cli.main(["export", str(run_dir), "-o", str(out)]) == 0
    assert "Perfetto" in capsys.readouterr().out
    _validate_chrome(json.loads(out.read_text()))
    assert trace_cli.main(["export", str(tmp_path / "nope"),
                           "-o", str(out)]) == 1


def test_export_empty_span_set(tmp_path):
    (tmp_path / "events.jsonl").write_text(
        _rec(kind="meta", name="trace_start") + "\n")
    doc = export.chrome_trace([str(tmp_path / "events.jsonl")])
    assert doc["traceEvents"] == []


def test_export_skips_stampless_records_instead_of_crashing(tmp_path):
    """A torn/foreign record without t_mono is SKIPPED (the lenient-loader
    contract), never a KeyError that hides every valid record."""
    lines = [
        _rec(kind="meta", name="trace_start"),
        json.dumps({"v": 1, "kind": "point", "name": "torn",
                    "t_wall": 1.0, "proc": 0}),          # no t_mono
        _rec(kind="span", name="ok", span=1, dur_s=0.5, t_mono=2.0),
    ]
    doc = export.chrome_trace([_write(tmp_path, lines)
                               + "/events.jsonl"])
    _validate_chrome(doc)
    names = {ev["name"] for ev in doc["traceEvents"] if ev["ph"] != "M"}
    assert "ok" in names and "torn" not in names


# ---------------------------------------------------------------------------
# the flight recorder
# ---------------------------------------------------------------------------

def test_flight_ring_is_bounded_with_exact_drop_count():
    rec = flight.FlightRecorder(capacity=4)
    for i in range(10):
        rec.record("probe", attempt=i)
    entries = rec.snapshot()
    assert len(entries) == 4
    assert rec.recorded == 10 and rec.dropped == 6
    assert [e["attempt"] for e in entries] == [6, 7, 8, 9]  # newest kept
    assert entries[-1]["seq"] == 9
    with pytest.raises(ValueError):
        flight.FlightRecorder(capacity=0)


def test_flight_dump_payload_and_empty_behavior(tmp_path):
    rec = flight.FlightRecorder(capacity=8)
    assert rec.dump("nothing recorded") is None     # empty ring: no file
    rec.record("backend_probe_error", error="UNAVAILABLE")
    rec.dump_dir = str(tmp_path)
    path = rec.dump("test failure")
    assert path and os.path.exists(path)
    payload = json.loads(open(path).read())
    # schema v2 since the rank stamp (telemetry/flight.py): every entry
    # carries process identity so merged multi-rank dumps attribute
    assert payload["v"] == 2 and payload["reason"] == "test failure"
    assert all(isinstance(e["rank"], int) for e in payload["entries"])
    assert payload["recorded"] == 1 and payload["dropped"] == 0
    assert payload["entries"][0]["kind"] == "backend_probe_error"
    assert payload["pid"] == os.getpid()


def test_admission_rejects_feed_flight_recorder():
    from pytorch_ddp_mnist_tpu.serve.admission import (AdmissionController,
                                                       Rejected)
    before = flight.get_flight_recorder().recorded
    ctl = AdmissionController(max_depth=1)
    ctl.admit()
    with pytest.raises(Rejected):
        ctl.admit()                                 # queue full
    ctl.begin_drain()
    ctl.release()
    with pytest.raises(Rejected):
        ctl.admit()                                 # draining
    kinds = [e for e in flight.get_flight_recorder().snapshot()
             if e["kind"] == "serve_reject" and e["seq"] >= before]
    reasons = {e["reason"] for e in kinds}
    assert {"queue_full", "draining"} <= reasons


def test_wireup_retry_loop_feeds_flight_recorder(monkeypatch):
    from pytorch_ddp_mnist_tpu.parallel import wireup
    before = flight.get_flight_recorder().recorded
    monkeypatch.setattr(
        wireup, "_probe_devices_bounded",
        lambda _t: ("error", RuntimeError("UNAVAILABLE: tunnel down")))
    with pytest.raises(wireup.BackendUnavailableError):
        wireup.wait_for_backend(max_wait_s=0.05, poll_s=0.01)
    fresh = [e for e in flight.get_flight_recorder().snapshot()
             if e["seq"] >= before]
    kinds = {e["kind"] for e in fresh}
    assert {"backend_wait_start", "backend_probe_error",
            "backend_unavailable"} <= kinds
    err = next(e for e in fresh if e["kind"] == "backend_probe_error")
    assert "UNAVAILABLE" in err["error"]


def test_bench_artifact_stamps_flight_dump(tmp_path, monkeypatch, capsys):
    """The satellite: a backend_unavailable artifact line carries the
    flight-recorder dump path, so BENCH_r0X-style failures are diagnosable
    from the JSON alone."""
    import bench
    monkeypatch.setenv("PDMT_FLIGHT_DIR", str(tmp_path))
    flight.record("backend_probe_error", attempt=1, error="UNAVAILABLE")
    bench._emit_backend_error(RuntimeError("tunnel never came up"))
    line = json.loads(capsys.readouterr().out.strip())
    assert line["error"].startswith("backend_unavailable")
    assert line["value"] is None
    dump_path = line["flight_recorder"]
    assert dump_path and os.path.exists(dump_path)
    payload = json.loads(open(dump_path).read())
    assert payload["reason"].startswith("bench backend_unavailable")
    assert any(e["kind"] == "backend_probe_error"
               for e in payload["entries"])


def test_flight_sigterm_flush_preserves_sig_ign(tmp_path, monkeypatch):
    """A run launched with SIGTERM ignored (supervisor choice) must stay
    alive after the flush — chaining means preserving the disposition,
    not converting ignore into death."""
    import signal
    prev = signal.signal(signal.SIGTERM, signal.SIG_IGN)
    monkeypatch.setattr(flight, "_sigterm_installed", False)
    try:
        assert flight.install_sigterm_flush() is True
        flight.record("probe", note="pre-ign-sigterm")
        flight.set_dump_dir(str(tmp_path))
        os.kill(os.getpid(), signal.SIGTERM)    # must NOT kill the test
        assert (tmp_path / f"flight.{os.getpid()}.json").exists()
    finally:
        flight.set_dump_dir(None)
        monkeypatch.setattr(flight, "_sigterm_installed", False)
        signal.signal(signal.SIGTERM, prev)


def test_flight_sigterm_flush_chains(tmp_path, monkeypatch):
    """install_sigterm_flush dumps the ring then chains the previous
    handler (a callable here, so the process survives the test)."""
    import signal
    hits = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: hits.append(s))
    monkeypatch.setattr(flight, "_sigterm_installed", False)
    try:
        assert flight.install_sigterm_flush() is True
        flight.record("probe", note="pre-sigterm")
        flight.set_dump_dir(str(tmp_path))
        os.kill(os.getpid(), signal.SIGTERM)
        assert hits == [signal.SIGTERM]             # chained
        dumped = json.loads(
            open(tmp_path / f"flight.{os.getpid()}.json").read())
        assert dumped["reason"] == "SIGTERM"
    finally:
        flight.set_dump_dir(None)
        monkeypatch.setattr(flight, "_sigterm_installed", False)
        signal.signal(signal.SIGTERM, prev)


# ---------------------------------------------------------------------------
# REAL multi-process aggregation (the mp_worker launch pattern)
# ---------------------------------------------------------------------------

STALL_S = 0.05
MP_EPOCHS = 3


def test_two_process_trace_aggregation(tmp_path):
    """Two real worker processes emit rank-gated traces into one dir; the
    merged report must see both processes, aligned epochs, and the injected
    rank-1 straggler in its skew fields — the acceptance scenario."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", "trace_worker.py"),
         str(tmp_path), str(rank), str(MP_EPOCHS), str(STALL_S)],
        cwd=REPO, env=env, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for rank in range(2)]
    for p in procs:
        _out, err = p.communicate(timeout=120)
        assert p.returncode == 0, err[-2000:]

    files = analysis.trace_files(str(tmp_path))
    assert len(files) == 2                          # events + rank1 sibling
    assert check_main([str(tmp_path)]) == 0         # schema + structure

    rep = analysis.analyze(files)
    assert rep["n_processes"] == 2 and rep["processes"] == [0, 1]
    assert rep["span_errors"] == []
    assert rep["epochs"]["count"] == MP_EPOCHS
    assert rep["phases"]["step_compute"]["n"] == 2 * MP_EPOCHS
    # the injected straggler: every epoch compared across both ranks, and
    # the skew is at least most of the injected stall
    st = rep["straggler"]
    assert st["epochs_compared"] == MP_EPOCHS
    assert st["max_skew_s"] >= STALL_S * 0.6
    assert st["max_skew_pct"] > 0
    assert set(st["worst_epoch"]["dur_s_by_proc"]) == {"0", "1"}
    # wall alignment: both workers started within the same few seconds
    assert st["max_start_spread_s"] < 60.0

    # the CLI front door renders the same merged view (acceptance text)
    out = subprocess.run(
        [sys.executable, "-m", "pytorch_ddp_mnist_tpu", "trace", "report",
         str(tmp_path)],
        cwd=REPO, env=env, text=True, capture_output=True, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "2 process(es)" in out.stdout
    assert "straggler skew" in out.stdout and "p50_s" in out.stdout

    # and the merged trace exports as valid Chrome trace-event JSON
    doc = export.chrome_trace(files)
    _validate_chrome(doc)
    assert {ev["pid"] for ev in doc["traceEvents"]} == {0, 1}


# ---------------------------------------------------------------------------
# front-door registration
# ---------------------------------------------------------------------------

def test_main_dispatch_knows_trace():
    from pytorch_ddp_mnist_tpu.__main__ import _COMMANDS
    assert "trace" in _COMMANDS
    assert _COMMANDS["trace"][0] == "pytorch_ddp_mnist_tpu.cli.trace"


# ---------------------------------------------------------------------------
# the data-wait attribution report + share regression gate (ISSUE 12)
# ---------------------------------------------------------------------------

def _emit_data_trace(path, shares, *, epoch_s=0.1, proc=0):
    """One process's trace with FABRICATED epoch/data_wait intervals:
    epoch e lasts `epoch_s`, its data_wait child `shares[e] * epoch_s` —
    explicit-interval spans (emit_span) so the structure validator's
    exit-before-emission and containment rules hold."""
    import time as _time

    tr = telemetry.EventTrace(str(path), process_index=proc)
    for e, share in enumerate(shares):
        t0 = _time.perf_counter() - epoch_s - 0.01
        w0 = _time.time() - epoch_s - 0.01
        pid = tr.emit_span("epoch", t0_mono=t0, t0_wall=w0, dur_s=epoch_s,
                           attrs={"epoch": e})
        tr.emit_span("data_wait", t0_mono=t0, t0_wall=w0,
                     dur_s=share * epoch_s, parent=pid,
                     attrs={"batches": 4})
    tr.close()
    return str(path)


def test_data_report_shares_and_stats(tmp_path):
    f = _emit_data_trace(tmp_path / "events.jsonl", [0.2, 0.4, 0.8])
    rep = analysis.data_report([f])
    assert rep["report"] == "trace_data_stats"
    assert rep["epochs"] == 3
    assert rep["batches"] == 12
    assert rep["share"]["p50"] == pytest.approx(0.4, rel=1e-6)
    assert rep["share"]["p95"] == pytest.approx(0.8, rel=1e-6)
    assert rep["share"]["max"] == pytest.approx(0.8, rel=1e-6)
    assert rep["data_wait"]["p95_s"] == pytest.approx(0.08, rel=1e-6)
    assert not rep["span_errors"]


def test_data_report_ignores_unparented_data_wait(tmp_path):
    # a data_wait with no epoch parent (e.g. a hand-rolled trace) cannot
    # produce a share
    tr = telemetry.EventTrace(str(tmp_path / "events.jsonl"),
                              process_index=0)
    tr.complete_span("data_wait", 0.5)
    tr.close()
    rep = analysis.data_report([str(tmp_path / "events.jsonl")])
    assert rep["epochs"] == 0


def test_compare_data_gates_share_regression():
    new = {"share": {"p50": 0.5, "p95": 0.8},
           "data_wait": {"p95_s": 0.08}}
    old = {"share": {"p50": 0.1, "p95": 0.2},
           "data_wait": {"p95_s": 0.02}}
    diff = analysis.compare_data(new, old, threshold=1.5)
    assert len(diff["rows"]) == 2
    assert len(diff["regressions"]) == 2
    # improvement never regresses
    ok = analysis.compare_data(old, new, threshold=1.5)
    assert not ok["regressions"]


def test_compare_data_sub_ms_exempt():
    # 4x share regression, but the new data_wait p95 is sub-ms: exempt
    new = {"share": {"p50": 0.4, "p95": 0.4},
           "data_wait": {"p95_s": 0.0004}}
    old = {"share": {"p50": 0.1, "p95": 0.1},
           "data_wait": {"p95_s": 0.0001}}
    diff = analysis.compare_data(new, old, threshold=1.5)
    assert diff["rows"] and all(r["sub_ms_exempt"] for r in diff["rows"])
    assert not diff["regressions"]


def _serve_stage_report(shares, p95_s=0.01):
    """A minimal serve report: stage -> pct_of_e2e (p95 defaults past the
    sub-ms exemption so shares actually gate)."""
    return {"report": "serve_trace_attribution",
            "stages": {s: {"pct_of_e2e": pct, "p95_s": p95_s}
                       for s, pct in shares.items()}}


def test_compare_serve_gates_compute_share_drop():
    """The ISSUE 14 gate: compute's share of e2e dropping past threshold
    regresses (ratio old/new, the efficiency convention); an overhead
    stage's share GROWING past threshold regresses too (ratio new/old);
    an improvement in either direction passes."""
    old = _serve_stage_report({"compute": 40.0, "reply": 20.0})
    bad = _serve_stage_report({"compute": 10.0, "reply": 70.0})
    diff = analysis.compare_serve(bad, old, threshold=1.5)
    by_stage = {r["stage"]: r for r in diff["rows"]}
    assert by_stage["compute"]["regressed"]
    assert by_stage["compute"]["ratio"] == pytest.approx(4.0)
    assert by_stage["reply"]["regressed"]
    assert by_stage["reply"]["ratio"] == pytest.approx(3.5)
    # the fast-path direction (compute share UP, overhead DOWN) passes
    ok = analysis.compare_serve(old, bad, threshold=1.5)
    assert not ok["regressions"]
    # self-comparison is always a PASS with full row coverage
    self_diff = analysis.compare_serve(old, old, threshold=1.5)
    assert self_diff["rows"] and not self_diff["regressions"]
    # compute share collapsing to zero is the worst regression, not a
    # skipped row
    dead = analysis.compare_serve(
        _serve_stage_report({"compute": 0.0}), old)
    assert [r for r in dead["regressions"] if r["stage"] == "compute"]


def test_compare_serve_sub_ms_exempt():
    old = _serve_stage_report({"batch_form": 1.0}, p95_s=0.0002)
    new = _serve_stage_report({"batch_form": 5.0}, p95_s=0.0004)
    diff = analysis.compare_serve(new, old, threshold=1.5)
    assert diff["rows"] and diff["rows"][0]["sub_ms_exempt"]
    assert not diff["regressions"]
    # ...but a stage past a millisecond gates for real
    slow = _serve_stage_report({"batch_form": 5.0}, p95_s=0.002)
    assert analysis.compare_serve(slow, old, threshold=1.5)["regressions"]


def test_trace_cli_serve_gate_round_trip(tmp_path, capsys):
    """`trace report --serve --baseline`: a saved --json report feeds the
    gate; a run never regresses against itself (exit 0), a doctored
    baseline with a far larger compute share exits 3."""
    import pathlib
    import sys as _sys

    _sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    from pytorch_ddp_mnist_tpu.cli.trace import main as trace_main
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.serve import InferenceEngine, ServeService
    from pytorch_ddp_mnist_tpu.serve.loadgen import run_loadgen
    import jax

    out_dir = tmp_path / "obs"
    telemetry.enable(str(out_dir))
    try:
        eng = InferenceEngine(init_mlp(jax.random.key(0)), max_batch=8)
        svc = ServeService(eng, max_delay_ms=2.0, max_depth=256,
                           registry=telemetry.MetricsRegistry())
        run_loadgen(svc, offered_rps=3000.0, n_requests=40, seed=0)
    finally:
        telemetry.disable()
    assert trace_main(["report", "--serve", "--json", str(out_dir)]) == 0
    saved = tmp_path / "self.json"
    saved.write_text(capsys.readouterr().out)
    # self-baseline: exit 0, the gate table prints a PASS
    rc = trace_main(["report", "--serve", str(out_dir),
                     "--baseline", str(saved)])
    assert rc == 0
    assert "regression gate: PASS" in capsys.readouterr().out
    # a doctored baseline whose compute share was far larger -> exit 3
    doctored = json.loads(saved.read_text())
    st = doctored["stages"]
    st["compute"]["pct_of_e2e"] = 100.0 * max(
        1.0, st["compute"].get("pct_of_e2e") or 1.0)
    st["compute"]["p95_s"] = 0.5   # past the sub-ms exemption both sides
    for s in st.values():
        s.setdefault("p95_s", 0.5)
    bad = tmp_path / "doctored.json"
    bad.write_text(json.dumps(doctored))
    rc = trace_main(["report", "--serve", str(out_dir),
                     "--baseline", str(bad), "--threshold", "1.5"])
    assert rc == 3
    assert "REGRESSION" in capsys.readouterr().out


def test_trace_cli_data_view_and_gate(tmp_path, capsys):
    good = tmp_path / "good"
    bad = tmp_path / "bad"
    good.mkdir()
    bad.mkdir()
    _emit_data_trace(good / "events.jsonl", [0.1, 0.1, 0.12])
    _emit_data_trace(bad / "events.jsonl", [0.6, 0.7, 0.8])

    # plain view renders
    assert trace_cli.main(["report", "--data", str(good)]) == 0
    out = capsys.readouterr().out
    assert "data_wait share of epoch" in out

    # self-baseline passes; regression exits 3
    assert trace_cli.main(["report", "--data", str(good),
                           "--baseline", str(good)]) == 0
    capsys.readouterr()
    assert trace_cli.main(["report", "--data", str(bad),
                           "--baseline", str(good)]) == 3
    out = capsys.readouterr().out
    assert "REGRESSION" in out

    # a saved --json report feeds back as baseline (the step-time gate's
    # round-trip contract, mirrored)
    assert trace_cli.main(["report", "--data", str(good), "--json"]) == 0
    saved = tmp_path / "saved.json"
    saved.write_text(capsys.readouterr().out)
    assert trace_cli.main(["report", "--data", str(bad),
                           "--baseline", str(saved)]) == 3
    capsys.readouterr()


def test_trace_cli_data_errors(tmp_path, capsys):
    # no trace at all -> 1
    empty = tmp_path / "empty"
    empty.mkdir()
    assert trace_cli.main(["report", "--data", str(empty)]) == 1
    # a trace with no data_wait attribution -> 1, named
    nodata = tmp_path / "nodata"
    nodata.mkdir()
    tr = telemetry.EventTrace(str(nodata / "events.jsonl"),
                              process_index=0)
    tr.point("hello")
    tr.close()
    assert trace_cli.main(["report", "--data", str(nodata)]) == 1
    err = capsys.readouterr().err
    assert "data_wait" in err
    # --serve and --data conflict at the parser
    with pytest.raises(SystemExit):
        trace_cli.main(["report", "--data", "--serve", str(empty)])


def test_real_streaming_run_feeds_data_report(tmp_path):
    """End to end on a REAL piped training run: the emitted trace yields
    a data report whose epoch count matches, and the checker's --require
    data. gate passes on the same directory."""
    import numpy as np
    import jax

    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.pipeline import SyntheticSource
    from pytorch_ddp_mnist_tpu.data import normalize_images, synthetic_mnist
    from pytorch_ddp_mnist_tpu.train import TrainState, fit

    out_dir = tmp_path / "obs"
    telemetry.enable(str(out_dir), process_index=0)
    try:
        test = synthetic_mnist(64, seed=1)
        src = SyntheticSource(6, 32, latency_s=0.001, seed=0)
        fit(TrainState(init_mlp(jax.random.key(0)), jax.random.key(1)),
            src, normalize_images(test.images),
            test.labels.astype(np.int32), epochs=2, batch_size=32, lr=0.1,
            log=lambda _m: None, input_workers=2, prefetch_depth=2)
        telemetry.get_tracer().snapshot(telemetry.get_registry())
    finally:
        telemetry.disable()
    rep = analysis.data_report(analysis.trace_files(str(out_dir)))
    assert rep["epochs"] == 2
    assert not rep["span_errors"]
    assert check_main(["--require", "data.", str(out_dir)]) == 0


# ---------------------------------------------------------------------------
# dispatch forensics: the overhead report, the record contract, the gate
# ---------------------------------------------------------------------------

def _emit_dispatch_trace(path, proc=0, *, dispatch_s=0.6, prestep_s=0.01,
                         sync_s=0.03, idle_s=0.55, window_s=0.7):
    tr = telemetry.EventTrace(str(path), process_index=proc)
    for phase, total in (("python_prestep", prestep_s),
                         ("dispatch", dispatch_s),
                         ("device_idle", idle_s),
                         ("sync_wait", sync_s)):
        tr.point("dispatch_phase", phase=phase, total_s=total, n=8,
                 epoch=0, step=8)
    tr.point("dispatch_window", window_s=window_s,
             attributed_s=prestep_s + dispatch_s + sync_s, coverage=1.0,
             epoch=0, steps=8)
    tr.close()
    return str(path)


def test_overhead_report_from_trace(tmp_path):
    f = _emit_dispatch_trace(tmp_path / "events.jsonl")
    rep = analysis.overhead_report([f])
    assert rep["report"] == analysis.OVERHEAD_REPORT_TAG
    (row,) = rep["rows"]
    assert row["program"] == "train"
    assert row["steps"] == 8
    # coverage = attributed / the loop's own window clock
    assert row["coverage"] == pytest.approx(0.64 / 0.7, rel=1e-6)
    # worst is a HOST phase (device_idle observes the same interval and
    # sync_wait can never win against dispatch here)
    assert row["worst_phase"] == "dispatch"
    assert row["phases"]["dispatch"]["share"] == pytest.approx(0.6 / 0.7,
                                                               rel=1e-6)


def test_dispatch_record_errors_contract():
    def rec(name, **attrs):
        return {"kind": "point", "name": name, "_line": 1, "attrs": attrs}

    good = [rec("dispatch_phase", phase="dispatch", total_s=0.5, step=8),
            rec("dispatch_window", window_s=1.0, attributed_s=0.9)]
    assert analysis.dispatch_record_errors(good) == []
    bad = [rec("dispatch_phase", phase="gpu_think", total_s=0.5, step=8),
           rec("dispatch_phase", phase="dispatch", total_s=-1, step=8),
           rec("dispatch_phase", phase="dispatch", total_s=0.5, step=1.5),
           rec("dispatch_window", window_s=-0.1, attributed_s=0.9)]
    errs = analysis.dispatch_record_errors(bad)
    assert len(errs) == 4
    assert "unknown phase 'gpu_think'" in errs[0][1]


def test_checker_rejects_bad_dispatch_records(tmp_path, capsys):
    d = _write(tmp_path, [
        _rec(name="dispatch_phase",
             attrs={"phase": "warp_drive", "total_s": 0.1, "step": 0}),
    ])
    assert check_main([d]) == 1
    assert "unknown phase" in capsys.readouterr().err


def test_overhead_from_artifact_rows_and_legacy_note():
    art = {"n_devices": 8, "strategies": [
        {"strategy": "pmean", "overlap": False,
         "overhead_share": 0.5, "overhead_coverage": 1.0,
         "overhead_worst_phase": "dispatch", "overhead_worst_share": 0.9,
         "overhead_probe_steps": 8,
         "overhead_phases": {"python_prestep": 0.001, "dispatch": 0.01,
                             "device_idle": 0.01, "sync_wait": 0.002}},
        {"strategy": "bf16", "overlap": True},     # legacy: no stamp
    ]}
    rep = analysis.overhead_from_artifact(art)
    assert [r["program"] for r in rep["rows"]] == ["pmean",
                                                   "bf16+overlap"]
    stamped, legacy = rep["rows"]
    assert stamped["coverage"] == 1.0
    assert stamped["overhead_share"] == 0.5
    # the stamped worst wins over recomputation (the probe's sync_wait is
    # device compute, not overhead)
    assert stamped["worst_phase"] == "dispatch"
    assert stamped["worst_share"] == 0.9
    assert "predates the dispatch probe" in legacy["note"]


def _overhead_rows(shares, total_s=0.1):
    return {"rows": [{"program": "train",
                      "phases": {p: {"share": s, "total_s": total_s}
                                 for p, s in shares.items()}}]}


def test_compare_overhead_gates_share_growth():
    old = _overhead_rows({"python_prestep": 0.1, "dispatch": 0.5})
    new = _overhead_rows({"python_prestep": 0.2, "dispatch": 0.5})
    diff = analysis.compare_overhead(new, old, threshold=1.5)
    (reg,) = diff["regressions"]
    assert reg["phase"] == "python_prestep"
    assert reg["ratio"] == pytest.approx(2.0)
    # a run against itself never regresses
    assert not analysis.compare_overhead(new, new)["regressions"]


def test_compare_overhead_sub_ms_exempt():
    # a 3x share ratio whose absolute new total is sub-ms: scheduler noise
    old = _overhead_rows({"dispatch": 0.01}, total_s=0.0002)
    new = _overhead_rows({"dispatch": 0.03}, total_s=0.0005)
    diff = analysis.compare_overhead(new, old, threshold=1.5)
    assert diff["rows"] and not diff["regressions"]


def test_trace_cli_overhead_round_trip(tmp_path, capsys):
    d = tmp_path / "obs"
    d.mkdir()
    _emit_dispatch_trace(d / "events.jsonl")
    assert trace_cli.main(["report", "--overhead", str(d)]) == 0
    out = capsys.readouterr().out
    assert "dispatch overhead report" in out and "worst phase" in out
    # --json round-trips through the saved-baseline path
    assert trace_cli.main(["report", "--overhead", "--json",
                           str(d)]) == 0
    saved = tmp_path / "self.json"
    saved.write_text(capsys.readouterr().out)
    assert trace_cli.main(["report", "--overhead", str(d),
                           "--baseline", str(saved)]) == 0
    capsys.readouterr()


def test_trace_cli_overhead_gate_exit3_on_regression(tmp_path, capsys):
    base_dir, slow_dir = tmp_path / "base", tmp_path / "slow"
    base_dir.mkdir(), slow_dir.mkdir()
    _emit_dispatch_trace(base_dir / "events.jsonl", prestep_s=0.01)
    # python_prestep share grows ~10x: the injected regression
    _emit_dispatch_trace(slow_dir / "events.jsonl", prestep_s=0.1)
    rc = trace_cli.main(["report", "--overhead", str(slow_dir),
                         "--baseline", str(base_dir)])
    assert rc == 3
    out = capsys.readouterr().out
    assert "python_prestep" in out and "REGRESSION" in out


def test_trace_cli_overhead_coverage_floor_exit1(tmp_path, capsys):
    d = tmp_path / "obs"
    d.mkdir()
    # phases explain only half the loop's window: unprofiled host work
    _emit_dispatch_trace(d / "events.jsonl", window_s=1.4)
    rc = trace_cli.main(["report", "--overhead", str(d)])
    assert rc == 1
    err = capsys.readouterr().err
    assert "phases explain only" in err and "floor 90%" in err


def test_trace_cli_overhead_from_artifact(tmp_path, capsys):
    art = {"n_devices": 8, "strategies": [
        {"strategy": "pmean", "overlap": False, "overhead_share": 0.5,
         "overhead_coverage": 0.97, "overhead_worst_phase": "dispatch",
         "overhead_worst_share": 0.9, "overhead_probe_steps": 8,
         "overhead_phases": {"python_prestep": 0.001, "dispatch": 0.01,
                             "device_idle": 0.01, "sync_wait": 0.002}}]}
    p = tmp_path / "MULTICHIP_rXX.json"
    p.write_text(json.dumps(art))
    assert trace_cli.main(["report", "--overhead", str(p)]) == 0
    assert "pmean" in capsys.readouterr().out


def test_committed_r08_artifact_decomposes_overhead(capsys):
    """The committed DDP artifact carries the dispatch stamps and its
    overhead report clears the 90% coverage floor (exit 0)."""
    art = pathlib.Path(__file__).resolve().parents[1] / "MULTICHIP_r08.json"
    rows = json.loads(art.read_text())["strategies"]
    assert len(rows) == 8
    for r in rows:
        assert set(r["overhead_phases"]) == set(analysis.DISPATCH_PHASES)
        assert r["overhead_coverage"] >= analysis.OVERHEAD_COVERAGE_MIN
    assert trace_cli.main(["report", "--overhead", str(art)]) == 0
    out = capsys.readouterr().out
    assert "coverage" in out and "worst phase" in out


def test_export_renders_dispatch_lanes(tmp_path):
    f = _emit_dispatch_trace(tmp_path / "events.jsonl")
    doc = export.chrome_trace([f])
    evs = doc["traceEvents"]
    slices = {ev["name"]: ev for ev in evs if ev["ph"] == "X"}
    assert {"python_prestep", "dispatch", "device_idle",
            "sync_wait"} <= set(slices)
    # host phases on the host lane, device_idle on its own lane
    assert slices["dispatch"]["tid"] == slices["python_prestep"]["tid"]
    assert slices["device_idle"]["tid"] != slices["dispatch"]["tid"]
    lanes = {ev["args"]["name"] for ev in evs
             if ev["ph"] == "M" and ev["name"] == "thread_name"}
    assert {"host dispatch", "device idle"} <= lanes
    # slices end at their emission stamp: start = emission - total_s
    d = slices["dispatch"]
    assert d["dur"] == pytest.approx(600000.0)   # 0.6s in us
    assert d["ts"] + d["dur"] <= 60.0            # ends near emission
