"""serve/fleet.py + serve/reload.py on CPU: the replica fleet's
acceptance invariants — fleet-served predictions bitwise-equal to a
direct engine, crash and wedge failover losing zero admitted requests,
hot reload swapping behind a drain so no request spans a swap, torn/NaN
checkpoints refused by name with the incumbent serving — plus the
shared-restore-preference scan (`scan_restorable`), the `claim` fault
primitive, the loadgen arrival shapes, and the fleet/reload record
validators."""

import asyncio
import glob
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from pytorch_ddp_mnist_tpu.models import init_mlp
from pytorch_ddp_mnist_tpu.serve import (FleetService, FleetUnavailable,
                                         InferenceEngine, ReloadWatcher)
from pytorch_ddp_mnist_tpu.serve.loadgen import arrival_times, request_rows
from pytorch_ddp_mnist_tpu.telemetry.registry import MetricsRegistry
from pytorch_ddp_mnist_tpu.train.ckpt_manager import CheckpointManager
from pytorch_ddp_mnist_tpu.utils import faultpoints


@pytest.fixture(scope="module")
def params():
    return init_mlp(jax.random.key(0))


@pytest.fixture(scope="module")
def params_b():
    return init_mlp(jax.random.key(1))


@pytest.fixture(scope="module")
def rows():
    return request_rows(48, "float32", seed=1)


@pytest.fixture(scope="module")
def direct(params, rows):
    eng = InferenceEngine(params, max_batch=8)
    preds = [int(eng.predict(np.stack([r]))[0]) for r in rows]
    eng.close()
    return preds


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    yield
    faultpoints.install("")


def _fleet(params, **kw):
    kw.setdefault("n_replicas", 2)
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_delay_ms", 1.0)
    kw.setdefault("registry", MetricsRegistry())
    return FleetService(lambda p: InferenceEngine(p, max_batch=8),
                        params, **kw)


def _serve_all(fleet, rows):
    async def scenario():
        got = await asyncio.gather(*[fleet.handle(r) for r in rows],
                                   return_exceptions=True)
        snap = fleet.fleet_snapshot()
        await fleet.shutdown()
        return got, snap
    return asyncio.run(scenario())


# ---------------------------------------------------------------------------
# fleet: routing + parity
# ---------------------------------------------------------------------------

def test_fleet_bitwise_parity_with_direct_engine(params, rows, direct):
    got, snap = _serve_all(_fleet(params), rows)
    assert list(got) == direct
    # both replicas actually served (least-loaded routing spreads work)
    assert snap["healthy"] == 2 and not snap["degraded"]


def test_fleet_validates_geometry(params):
    with pytest.raises(ValueError, match="n_replicas"):
        _fleet(params, n_replicas=0)
    with pytest.raises(ValueError, match="retry_budget"):
        _fleet(params, retry_budget=-1)
    with pytest.raises(ValueError, match="wedge_timeout_s"):
        _fleet(params, wedge_timeout_s=0)


def test_client_error_propagates_unretried(params, rows):
    fleet = _fleet(params)

    async def scenario():
        with pytest.raises((ValueError, TypeError)):
            await fleet.handle(np.zeros(3))     # wrong row shape
        ok = await fleet.handle(rows[0])
        snap = fleet.fleet_snapshot()
        await fleet.shutdown()
        return ok, snap

    ok, snap = asyncio.run(scenario())
    assert isinstance(ok, int)
    # a malformed payload is the CLIENT's fault: no quarantine, no retry
    assert snap["retried_requests"] == 0
    assert snap["crashes"] == 0 and snap["healthy"] == 2


# ---------------------------------------------------------------------------
# fleet: crash + wedge failover
# ---------------------------------------------------------------------------

def test_crash_failover_loses_nothing(params, rows, direct):
    faultpoints.install("engine_crash:after=1:replica=0")
    got, snap = _serve_all(_fleet(params), rows)
    assert list(got) == direct          # zero lost, zero wrong
    assert snap["crashes"] >= 1
    assert snap["retried_requests"] >= 1


def test_wedge_watchdog_fails_over(params, rows, direct):
    faultpoints.install("engine_wedge:delay_s=1.0:replica=1")
    got, snap = _serve_all(
        _fleet(params, wedge_timeout_s=0.1, retry_budget=3), rows)
    assert list(got) == direct
    assert snap["wedges"] >= 1
    assert snap["retried_requests"] >= 1


def test_retry_budget_bounds_failover(params, rows):
    # every replica's engine crashes on every serve call (times=100 so
    # the spec never exhausts before the budget does): the request must
    # surface a replica failure after retry_budget+1 attempts, never
    # spin forever
    faultpoints.install("engine_crash:times=100")
    fleet = _fleet(params, retry_budget=1, no_replica_wait_s=0.2)

    async def scenario():
        with pytest.raises(Exception) as ei:
            await fleet.handle(rows[0])
        snap = fleet.fleet_snapshot()
        await fleet.shutdown()
        return ei.value, snap

    exc, snap = asyncio.run(scenario())
    assert not isinstance(exc, (ValueError, TypeError))
    assert snap["retry_exhausted"] >= 1 or isinstance(exc, FleetUnavailable)


# ---------------------------------------------------------------------------
# hot reload: swap invariant, refusal by name
# ---------------------------------------------------------------------------

def test_reload_swaps_all_replicas_no_request_spans_swap(
        params, params_b, rows, tmp_path):
    eng_b = InferenceEngine(params_b, max_batch=8)
    direct_b = [int(eng_b.predict(np.stack([r]))[0]) for r in rows]
    eng_b.close()

    mgr = CheckpointManager(str(tmp_path))
    key = np.zeros(2, np.uint32)
    mgr.save(params_b, key, "threefry2x32", step=7, epoch=0, offset=0)
    fleet = _fleet(params, serving_step=0)
    watcher = ReloadWatcher(fleet, str(tmp_path))

    async def scenario():
        # traffic in flight while the swap happens
        burst = [asyncio.ensure_future(fleet.handle(r)) for r in rows]
        verdict = await watcher.poll_once()
        old_engines = [rep.engine for rep in fleet.replicas]
        first = await asyncio.gather(*burst)
        after = await asyncio.gather(*[fleet.handle(r) for r in rows])
        snap = fleet.fleet_snapshot()
        await watcher.stop()
        await fleet.shutdown()
        return verdict, first, after, old_engines, snap

    verdict, first, after, new_engines, snap = asyncio.run(scenario())
    assert verdict == "reloaded"
    assert fleet.serving_step == 7 and snap["generation"] == 1
    # every post-swap answer comes from the NEW params, bitwise
    assert list(after) == direct_b
    # every in-flight request completed with a real answer — none was
    # dropped or errored by the drain-and-swap
    assert all(isinstance(got, int) for got in first)
    # every replica rebuilt onto generation 1
    assert all(rep.generation == 1 for rep in fleet.replicas)


def test_reload_refuses_torn_and_nan_by_name(params, params_b, tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    key = np.zeros(2, np.uint32)
    fleet = _fleet(params, serving_step=0)
    watcher = ReloadWatcher(fleet, str(tmp_path))

    # torn: newest payload truncated after commit
    mgr.save(params_b, key, "threefry2x32", step=3, epoch=0, offset=0)
    payload = glob.glob(os.path.join(str(tmp_path), "*3*.msgpack"))[0]
    with open(payload, "r+b") as f:
        f.truncate(8)

    async def scenario():
        torn = await watcher.poll_once()
        idle = await watcher.poll_once()    # refused steps never re-poll
        # NaN: intact by CRC, non-finite values — refused where a resume
        # would fall back with a warning
        p_nan = jax.tree_util.tree_map(
            lambda a_: jnp.full_like(a_, jnp.nan), params_b)
        mgr.save(p_nan, key, "threefry2x32", step=4, epoch=0, offset=0)
        nan = await watcher.poll_once()
        still_serving = await fleet.handle(
            request_rows(1, "float32", seed=5)[0])
        await watcher.stop()
        await fleet.shutdown()
        return torn, idle, nan, still_serving

    torn, idle, nan, still_serving = asyncio.run(scenario())
    assert (torn, idle, nan) == ("refused", "idle", "refused")
    assert watcher.refused == 2 and watcher.reloads == 0
    assert fleet.serving_step == 0          # incumbent untouched
    assert isinstance(still_serving, int)   # and still serving


def test_reload_torn_faultpoint_refuses_by_name(params, params_b, tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    key = np.zeros(2, np.uint32)
    mgr.save(params_b, key, "threefry2x32", step=2, epoch=0, offset=0)
    fleet = _fleet(params, serving_step=0)
    watcher = ReloadWatcher(fleet, str(tmp_path))
    faultpoints.install("reload_torn:times=1")

    async def scenario():
        refused = await watcher.poll_once()
        await watcher.stop()
        await fleet.shutdown()
        return refused

    assert asyncio.run(scenario()) == "refused"
    assert fleet.serving_step == 0


# ---------------------------------------------------------------------------
# shared restore preference: scan_restorable
# ---------------------------------------------------------------------------

def test_scan_restorable_matches_restore_latest(params, params_b, tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    key = np.zeros(2, np.uint32)
    mgr.save(params, key, "threefry2x32", step=1, epoch=0, offset=0)
    p_nan = jax.tree_util.tree_map(
        lambda a_: jnp.full_like(a_, jnp.nan), params_b)
    mgr.save(p_nan, key, "threefry2x32", step=2, epoch=0, offset=0)

    scan = mgr.scan_restorable(params)
    # the walk prefers the newest INTACT AND FINITE step...
    assert scan.best is not None and scan.best.step == 1
    # ...while remembering the newer non-finite one (resume's fallback,
    # reload's named refusal)
    assert scan.newest_nonfinite is not None
    assert scan.newest_nonfinite.step == 2
    # and restore_latest (the --resume path) picks the same best
    assert mgr.restore_latest(params).step == 1


def test_scan_restorable_newer_than_bound(params, tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    key = np.zeros(2, np.uint32)
    for step in (1, 2):
        mgr.save(params, key, "threefry2x32", step=step, epoch=0, offset=0)
    # nothing beyond step 2: the bounded walk never touches older steps
    scan = mgr.scan_restorable(params, newer_than=2)
    assert scan.best is None and scan.tried == []
    assert mgr.scan_restorable(params, newer_than=1).best.step == 2


# ---------------------------------------------------------------------------
# faultpoints: the claim primitive
# ---------------------------------------------------------------------------

def test_claim_returns_spec_and_marks_fired():
    faultpoints.install("engine_wedge:delay_s=0.5:replica=1:times=1")
    # context mismatch: no claim, not consumed
    assert faultpoints.claim("serve_wedge", replica=0) is None
    spec = faultpoints.claim("serve_wedge", replica=1)
    assert spec is not None and spec.delay_s == 0.5
    # times=1: consumed by the claim above
    assert faultpoints.claim("serve_wedge", replica=1) is None


def test_claim_disarmed_is_free():
    faultpoints.install("")
    assert faultpoints.claim("serve_wedge", replica=0) is None


# ---------------------------------------------------------------------------
# loadgen arrival shapes
# ---------------------------------------------------------------------------

def test_poisson_shape_is_bitwise_legacy():
    rng = np.random.default_rng(9)
    legacy = np.cumsum(rng.exponential(1.0 / 250.0, size=300))
    assert np.array_equal(
        arrival_times(300, 250.0, shape="poisson", seed=9), legacy)


@pytest.mark.parametrize("shape", ["poisson", "ramp", "spike"])
def test_shapes_monotone_and_mass_balanced(shape):
    t = arrival_times(2000, 400.0, shape=shape, seed=0)
    assert t.shape == (2000,)
    assert np.all(np.diff(t) >= 0) and t[0] >= 0
    # same total load: the last arrival lands near the nominal T = n/r
    assert t[-1] == pytest.approx(5.0, rel=0.25)


def test_ramp_backloads_spike_bursts():
    r = arrival_times(4000, 400.0, shape="ramp", seed=1)   # T = 10s
    assert np.sum(r < 5.0) < 0.4 * len(r)       # analytic share: 30%
    s = arrival_times(4000, 400.0, shape="spike", seed=1)
    mid = np.sum((s >= 4.0) & (s < 6.0))
    assert mid > 0.5 * len(s)                   # analytic share: 60%


def test_unknown_shape_refused_by_name():
    with pytest.raises(ValueError, match="sawtooth"):
        arrival_times(5, 1.0, shape="sawtooth")


# ---------------------------------------------------------------------------
# fleet/reload record validators (the check_telemetry contract)
# ---------------------------------------------------------------------------

def test_fleet_record_errors_flag_contract_violations():
    from pytorch_ddp_mnist_tpu.telemetry.analysis import fleet_record_errors

    def point(name, line, **attrs):
        return {"kind": "point", "name": name, "_line": line,
                "attrs": attrs}

    good = [
        point("fleet_event", 1, event="quarantine", replica=0,
              cause="wedge"),
        point("fleet_event", 2, event="restart", replica=0, dur_s=0.1),
        point("reload_event", 3, event="swapped", replica=1,
              outstanding_at_swap=0),
        point("reload_event", 4, event="refused", step=3, reason="torn"),
    ]
    assert fleet_record_errors(good) == []

    bad = [
        point("fleet_event", 1, event="exploded", replica=0),
        point("fleet_event", 2, event="quarantine", replica=-1,
              cause="gremlins"),
        point("reload_event", 3, event="swapped", replica=1,
              outstanding_at_swap=2),
        point("reload_event", 4, event="refused", step=3, reason=""),
    ]
    msgs = dict(fleet_record_errors(bad))
    assert "unknown event 'exploded'" in msgs[1]
    assert len([ln for ln in msgs if ln == 2]) == 1
    assert "outstanding_at_swap" in msgs[3]
    assert "reason" in msgs[4]
