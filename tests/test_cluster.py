"""Cluster forensics (telemetry/cluster.py): the per-rank collective
journal's write/read round trip, the schedule-vs-cost-model parity, desync
detection, hang forensics + the collective watchdog's /healthz flip, the
Perfetto per-rank collective tracks with seq-aligned cross-rank arrows,
the journal-schedule audit contract, and THE acceptance pins — journaled
training bitwise identical to unjournaled, zero new host syncs, the
checker's comma --require form, and the flight recorder's rank stamp."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax

from pytorch_ddp_mnist_tpu.data import (BatchLoader, normalize_images,
                                        synthetic_mnist)
from pytorch_ddp_mnist_tpu.models import init_mlp
from pytorch_ddp_mnist_tpu.parallel import ShardedSampler, collectives
from pytorch_ddp_mnist_tpu.parallel.ddp import (batch_sharding,
                                                make_dp_train_step,
                                                replicated)
from pytorch_ddp_mnist_tpu.parallel.mesh import make_mesh
from pytorch_ddp_mnist_tpu.parallel.wireup import Runtime
from pytorch_ddp_mnist_tpu.statics import jaxpr_audit, sanitize
from pytorch_ddp_mnist_tpu.telemetry import MetricsRegistry, cluster, flight
from pytorch_ddp_mnist_tpu.telemetry.health import health_summary
from pytorch_ddp_mnist_tpu.train import TrainState, fit
from pytorch_ddp_mnist_tpu.utils import faultpoints

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO, "scripts", "check_telemetry.py")


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() >= 8
    return make_mesh([8], ["dp"], jax.devices()[:8])


@pytest.fixture(autouse=True)
def _null_journal():
    # every test leaves the process-wide journal AND tracer disabled (the
    # NullTracer hygiene contract) and the fault switchboard empty
    yield
    import pytorch_ddp_mnist_tpu.telemetry as telemetry
    cluster.disable_journal(clean=False)
    telemetry.disable()
    faultpoints.install(None)


def _params():
    return init_mlp(jax.random.key(0))


# ---------------------------------------------------------------------------
# the static half: collective_schedule
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("comm", collectives.STRATEGIES)
@pytest.mark.parametrize("overlap", [False, True])
def test_schedule_bytes_sum_to_cost_model(comm, overlap):
    sched = collectives.collective_schedule(_params(), 8, comm,
                                            overlap=overlap)
    assert sched, "every strategy issues payload collectives"
    assert sum(e["bytes"] for e in sched) == collectives.bytes_on_wire(
        _params(), 8, comm)
    assert all(e["axis"] == "dp" for e in sched)


def test_schedule_shapes_per_strategy():
    leaves = len(jax.tree_util.tree_leaves(_params()))
    assert len(collectives.collective_schedule(_params(), 8,
                                               "pmean")) == leaves
    assert [e["kind"] for e in collectives.collective_schedule(
        _params(), 8, "pmean", overlap=True)] == ["allreduce"]
    assert [e["kind"] for e in collectives.collective_schedule(
        _params(), 8, "sharded")] == ["reduce_scatter", "all_gather"]
    int8 = collectives.collective_schedule(_params(), 8, "int8")
    assert [e["kind"] for e in int8] == ["all_to_all", "all_to_all",
                                        "all_gather", "all_gather"]
    assert [e["dtype"] for e in int8] == ["int8", "float32",
                                         "int8", "float32"]


def test_schedule_multi_bucket_and_one_device():
    # a 40k-element bucket splits the 118k MLP into 3 buckets
    sched = collectives.collective_schedule(_params(), 8, "sharded",
                                            bucket_elems=40000)
    assert len(sched) == 6 and {e["bucket"] for e in sched} == {0, 1, 2}
    # 1-device meshes keep the schedule SHAPE with zero bytes (the ring
    # moves nothing; seq numbering must not depend on world size)
    one = collectives.collective_schedule(_params(), 1, "pmean")
    assert len(one) == len(collectives.collective_schedule(_params(), 8,
                                                           "pmean"))
    assert all(e["bytes"] == 0 for e in one)


def test_journal_schedule_audit_contract(monkeypatch):
    """The statics pin: a schedule that disagrees with the walked program
    fails the named `journal-schedule` contract (the matrix's passing
    side runs in test_statics' full audit)."""
    monkeypatch.setattr(collectives, "collective_schedule",
                        lambda *a, **k: [])
    with pytest.raises(jaxpr_audit.AuditViolation) as e:
        jaxpr_audit.audit_step_program("pmean")
    assert e.value.contract == "journal-schedule"


# ---------------------------------------------------------------------------
# journal write/read round trip
# ---------------------------------------------------------------------------

def _write_journal(out_dir, rank, *, steps=3, comm="pmean", close=True,
                   open_kind=None, kinds=None, t0=None):
    reg = MetricsRegistry()
    j = cluster.CollectiveJournal(cluster.journal_path(out_dir, rank),
                                  rank=rank, world=2, registry=reg)
    sched = (collectives.collective_schedule(_params(), 8, comm)
             if kinds is None else
             [{"kind": k, "dtype": "float32", "axis": "dp", "elems": 10,
               "bytes": b, "bucket": 0} for k, b in kinds])
    j.bind_program(comm, False, sched)
    base = time.time() if t0 is None else t0
    for i in range(steps):
        j.record_step(i, 0.0 + i, 0.001 + i, base + i)
    if open_kind is not None:
        j.enter(open_kind)
    j.close(clean=close and open_kind is None)
    return j, reg


def test_journal_round_trip(tmp_path):
    d = str(tmp_path)
    j, reg = _write_journal(d, 0, steps=3)
    loaded = cluster.load_journal(cluster.journal_path(d, 0))
    per_step = len(collectives.collective_schedule(_params(), 8, "pmean"))
    assert loaded["rank"] == 0 and loaded["world"] == 2
    assert loaded["closed"] and not loaded["open"] and not loaded["errors"]
    assert len(loaded["records"]) == 3 * per_step
    assert [r["seq"] for r in loaded["records"]] == list(
        range(3 * per_step))
    snap = reg.snapshot()
    assert snap["counters"]["cluster.collectives"] == 3 * per_step
    assert snap["counters"]["cluster.bytes_on_wire"] == 3 * \
        collectives.bytes_on_wire(_params(), 8, "pmean")
    assert snap["gauges"]["cluster.seq"] == 3 * per_step
    assert snap["gauges"]["cluster.journal_overhead_s"] >= 0


def test_enter_exit_and_open_entry(tmp_path):
    d = str(tmp_path)
    reg = MetricsRegistry()
    j = cluster.CollectiveJournal(cluster.journal_path(d, 0), rank=0,
                                  registry=reg)
    seq = j.enter("barrier")
    assert j.open_entry()["seq"] == seq
    j.exit(seq)
    assert j.open_entry() is None
    j.enter("flush", steps=4)
    j.close(clean=False)            # a crash: no trailer
    loaded = cluster.load_journal(cluster.journal_path(d, 0))
    assert not loaded["closed"]
    assert [r["k"] for r in loaded["records"]] == ["barrier"]
    assert loaded["open"][0]["kind"] == "flush"
    assert loaded["open"][0]["steps"] == 4


def test_appended_rerun_reports_newest_segment(tmp_path):
    """The append-mode contract (the outage-resume re-exec and plain
    re-runs into one --telemetry dir): seq numbering restarts per
    segment, so the reader covers each journal's NEWEST segment — a
    stale segment's open flush must not read as a hang a later clean
    run already superseded, and its seqs must not double-count."""
    d = str(tmp_path)
    # segment 1: a crashed run (open flush, no trailer) ...
    _write_journal(d, 0, steps=2, kinds=[("allreduce", 100)],
                   close=False, open_kind="flush")
    # ... then the resumed run APPENDS a clean segment to the same file
    _write_journal(d, 0, steps=3, kinds=[("allreduce", 100)])
    loaded = cluster.load_journal(cluster.journal_path(d, 0))
    assert loaded["segments"] == 2
    assert loaded["closed"] and not loaded["open"]
    assert len(loaded["records"]) == 3          # newest segment only
    rep = cluster.cluster_report(d)
    assert rep["hang"]["stuck"] is None
    assert rep["totals"]["collectives"] == 3
    assert rep["multi_segment_ranks"] == [0]
    assert "NEWEST segment" in cluster.format_cluster_report(rep)


def test_journal_files_single_file_name_rule(tmp_path):
    """A non-journal file handed to the single-file resolver must not be
    misparsed as a collective journal (the export CLI routes one target
    through both the events and journal resolvers)."""
    ev = tmp_path / "events.jsonl"
    ev.write_text("{}\n")
    assert cluster.journal_files(str(ev)) == []
    j = tmp_path / "journal.rank3.jsonl"
    j.write_text("{}\n")
    assert cluster.journal_files(str(j)) == [str(j)]
    assert cluster.journal_files(str(tmp_path / "absent.jsonl")) == []


def test_wireup_barrier_is_journal_bracketed(tmp_path):
    cluster.enable_journal(str(tmp_path), rank=0, world=1, watchdog=False,
                           registry=MetricsRegistry())
    Runtime(method="single").barrier()
    cluster.disable_journal()
    loaded = cluster.load_journal(cluster.journal_path(str(tmp_path), 0))
    assert [r["k"] for r in loaded["records"]] == ["barrier"]
    assert not loaded["open"] and loaded["closed"]


def test_injected_collective_timeout_leaves_open_entry(tmp_path):
    """The acceptance's hang half at unit scale: the collective_timeout
    faultpoint fires INSIDE the journal bracket, so the barrier's enter
    has no exit — the evidence the hang report and watchdog key on."""
    cluster.enable_journal(str(tmp_path), rank=0, world=1, watchdog=False,
                           registry=MetricsRegistry())
    faultpoints.install("collective_timeout")
    with pytest.raises(RuntimeError, match="DEADLINE_EXCEEDED"):
        Runtime(method="single").barrier()
    assert cluster.get_journal().open_entry()["kind"] == "barrier"
    cluster.disable_journal(clean=False)
    loaded = cluster.load_journal(cluster.journal_path(str(tmp_path), 0))
    assert loaded["open"][0]["kind"] == "barrier"
    assert not loaded["closed"]


# ---------------------------------------------------------------------------
# desync detection
# ---------------------------------------------------------------------------

def test_desync_same_seq_different_collective(tmp_path):
    d = str(tmp_path)
    _write_journal(d, 0, steps=1, kinds=[("allreduce", 100)])
    _write_journal(d, 1, steps=1, kinds=[("reduce_scatter", 50)])
    rep = cluster.cluster_report(d)
    assert not rep["desync"]["ok"]
    v = rep["desync"]["violations"][0]
    assert v["ranks"] == [0, 1] and v["seq"] == 0
    assert "rank 0" in v["detail"] and "rank 1" in v["detail"]
    assert "allreduce" in v["detail"] and "reduce_scatter" in v["detail"]


def test_desync_position_of_closed_journals(tmp_path):
    d = str(tmp_path)
    _write_journal(d, 0, steps=2, kinds=[("allreduce", 100)])
    _write_journal(d, 1, steps=3, kinds=[("allreduce", 100)])
    rep = cluster.cluster_report(d)
    fields = {v["field"] for v in rep["desync"]["violations"]}
    assert "position" in fields


def test_crashed_rank_is_a_hang_story_not_a_desync(tmp_path):
    # ranks run the SAME host program, so a wedged/killed rank leaves a
    # PREFIX journal (every shared seq agrees) — a hang/crash story, NOT
    # a desync verdict: rank 1 wedged in its epoch flush, rank 0 was
    # reaped before flushing (neither wrote a trailer)
    d = str(tmp_path)
    _write_journal(d, 0, steps=3, kinds=[("allreduce", 100)],
                   close=False)
    _write_journal(d, 1, steps=3, kinds=[("allreduce", 100)],
                   close=False, open_kind="flush")
    rep = cluster.cluster_report(d)
    assert rep["desync"]["ok"]
    assert rep["hang"]["stuck"]["rank"] == 1
    assert rep["hang"]["stuck"]["kind"] == "flush"
    who = {w["rank"]: w for w in rep["hang"]["who_is_where"]}
    assert not who[0]["closed"] and not who[1]["closed"]
    assert who[0]["open"] is None
    assert who[1]["open"]["kind"] == "flush"


def test_skew_names_the_worst_collective(tmp_path):
    d = str(tmp_path)
    t0 = 1000.0
    _write_journal(d, 0, steps=3, kinds=[("allreduce", 100)], t0=t0)
    # rank 1 enters every collective 50ms late, and seq 2 200ms late
    reg = MetricsRegistry()
    j = cluster.CollectiveJournal(cluster.journal_path(d, 1), rank=1,
                                  world=2, registry=reg)
    j.bind_program("pmean", False,
                   [{"kind": "allreduce", "dtype": "float32", "axis": "dp",
                     "elems": 10, "bytes": 100, "bucket": 0}])
    for i, late in enumerate((0.05, 0.05, 0.2)):
        j.record_step(i, 0.0 + i, 0.001 + i, t0 + i + late)
    j.close()
    rep = cluster.cluster_report(d)
    pair = rep["skew"]["pairs"]["0-1"]
    assert pair["n"] == 3
    assert pair["p50_s"] == pytest.approx(0.05, rel=1e-6)
    assert rep["skew"]["worst"]["seq"] == 2
    assert rep["skew"]["worst"]["spread_s"] == pytest.approx(0.2, rel=1e-6)


# ---------------------------------------------------------------------------
# the collective watchdog (live hang forensics)
# ---------------------------------------------------------------------------

def test_watchdog_fires_once_and_flips_healthz(tmp_path):
    reg = MetricsRegistry()
    j = cluster.CollectiveJournal(cluster.journal_path(str(tmp_path), 0),
                                  rank=0, world=1, registry=reg)
    before = flight.get_flight_recorder().recorded
    wd = cluster.CollectiveWatchdog(j, timeout_s=0.05, registry=reg,
                                    poll_s=0.01)
    wd.start()
    j.enter("barrier")
    deadline = time.monotonic() + 5.0
    while (reg.snapshot()["counters"].get("cluster.hangs", 0) == 0
           and time.monotonic() < deadline):
        time.sleep(0.02)
    time.sleep(0.1)   # would double-fire here if firing were not latched
    wd.stop()
    snap = reg.snapshot()
    assert snap["counters"]["cluster.hangs"] == 1
    assert snap["counters"]["health.fired.collective_hang"] == 1
    assert snap["gauges"]["health.worst_severity_level"] == 2
    # the /healthz verdict prom.py serves reads exactly this summary
    assert health_summary(reg)["worst_severity"] == "fatal"
    hangs = [e for e in flight.get_flight_recorder().snapshot()
             if e["kind"] == "collective_hang" and e["seq"] >= before]
    assert hangs and hangs[-1]["collective"] == "barrier"
    assert hangs[-1]["who_is_where"][0]["open"]["kind"] == "barrier"
    j.close(clean=False)


def test_watchdog_silent_while_collectives_exit(tmp_path):
    reg = MetricsRegistry()
    j = cluster.CollectiveJournal(cluster.journal_path(str(tmp_path), 0),
                                  rank=0, registry=reg)
    wd = cluster.CollectiveWatchdog(j, timeout_s=0.05, registry=reg,
                                    poll_s=0.01)
    wd.start()
    for _ in range(5):
        seq = j.enter("barrier")
        time.sleep(0.02)
        j.exit(seq)
    time.sleep(0.1)
    wd.stop()
    assert reg.snapshot()["counters"].get("cluster.hangs", 0) == 0
    j.close()


# ---------------------------------------------------------------------------
# trace report --cluster CLI
# ---------------------------------------------------------------------------

def _trace_cli(argv):
    from pytorch_ddp_mnist_tpu.cli import trace as trace_cli
    return trace_cli.main(argv)


def test_cluster_cli_ok_and_json(tmp_path, capsys):
    d = str(tmp_path)
    _write_journal(d, 0, steps=2)
    _write_journal(d, 1, steps=2)
    assert _trace_cli(["report", "--cluster", "--json", d]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["report"] == "cluster_forensics"
    assert rep["ranks"] == [0, 1] and rep["desync"]["ok"]


def test_cluster_cli_desync_exits_3_naming_both_ranks(tmp_path, capsys):
    d = str(tmp_path)
    _write_journal(d, 0, steps=1, kinds=[("allreduce", 100)])
    _write_journal(d, 1, steps=1, kinds=[("all_gather", 100)])
    assert _trace_cli(["report", "--cluster", d]) == 3
    err = capsys.readouterr().err
    assert "DESYNC" in err and "rank 0" in err and "rank 1" in err


def test_cluster_cli_empty_target_exits_1(tmp_path, capsys):
    assert _trace_cli(["report", "--cluster", str(tmp_path)]) == 1
    assert "no journal*.jsonl" in capsys.readouterr().err


def test_cluster_cli_rejects_baseline(tmp_path):
    with pytest.raises(SystemExit) as e:
        _trace_cli(["report", "--cluster", str(tmp_path),
                    "--baseline", "x"])
    assert e.value.code == 2


def test_cluster_cli_hang_report_names_stuck_seq(tmp_path, capsys):
    d = str(tmp_path)
    _write_journal(d, 0, steps=1, kinds=[("allreduce", 100)],
                   close=False, open_kind="barrier")
    _write_journal(d, 1, steps=1, kinds=[("allreduce", 100)])
    assert _trace_cli(["report", "--cluster", d]) == 0
    out = capsys.readouterr().out
    assert "HANG: rank 0 entered collective seq 1 (barrier)" in out
    assert "who-is-where" in out and "rank 1" in out


# ---------------------------------------------------------------------------
# Perfetto export: per-rank collective tracks + seq-aligned arrows
# ---------------------------------------------------------------------------

def test_export_collective_tracks_and_arrows(tmp_path):
    from pytorch_ddp_mnist_tpu.telemetry.export import chrome_trace
    d = str(tmp_path)
    _write_journal(d, 0, steps=2, kinds=[("allreduce", 100)], t0=1000.0)
    _write_journal(d, 1, steps=2, kinds=[("allreduce", 100)], t0=1000.3)
    trace = chrome_trace([], journal_paths=cluster.journal_files(d))
    evs = trace["traceEvents"]
    colls = [e for e in evs if e.get("cat") == "collective"]
    # per-rank tracks: both pids present, on the collectives tid, with
    # seq/bytes args riding each slice
    assert {e["pid"] for e in colls} == {0, 1}
    assert all(e["tid"] == 4 for e in colls)
    assert all("seq" in e["args"] and "bytes" in e["args"]
               for e in colls)
    names = [e for e in evs if e.get("name") == "thread_name"
             and e.get("args", {}).get("name") == "collectives"]
    assert {e["pid"] for e in names} == {0, 1}
    # seq-aligned arrows: one flow per shared seq, start and finish
    # bound to the SAME seq's slices on the two ranks
    starts = [e for e in evs if e.get("ph") == "s"
              and e.get("cat") == "collective_flow"]
    finishes = [e for e in evs if e.get("ph") == "f"
                and e.get("cat") == "collective_flow"]
    assert len(starts) == 2 and len(finishes) == 2
    slice_ts = {(e["pid"], e["args"]["seq"]): e["ts"] for e in colls}
    for s, f in zip(sorted(starts, key=lambda e: e["id"]),
                    sorted(finishes, key=lambda e: e["id"])):
        assert s["id"] == f["id"] and s["pid"] != f["pid"]
        seq = int(s["name"].split()[-1])
        assert s["ts"] == slice_ts[(s["pid"], seq)]
        assert f["ts"] == slice_ts[(f["pid"], seq)]
    # flow arrows land ON the collectives track
    assert all(e["tid"] == 4 for e in starts + finishes)


def test_export_open_entry_renders_as_open_slice(tmp_path):
    from pytorch_ddp_mnist_tpu.telemetry.export import chrome_trace
    d = str(tmp_path)
    _write_journal(d, 0, steps=1, kinds=[("allreduce", 100)],
                   close=False, open_kind="barrier")
    trace = chrome_trace([], journal_paths=cluster.journal_files(d))
    opens = [e for e in trace["traceEvents"]
             if e.get("cat") == "collective" and e["args"].get("open")]
    assert len(opens) == 1 and opens[0]["name"] == "barrier"


# ---------------------------------------------------------------------------
# the acceptance pins: journaled fit — bitwise, zero-sync, schedule-true
# ---------------------------------------------------------------------------

def _fit_once(mesh, journal=None, n=256, batch=64, epochs=1):
    split = synthetic_mnist(n, seed=0)
    test = synthetic_mnist(64, seed=1)
    sampler = ShardedSampler(n, num_replicas=1, rank=0, seed=42)
    loader = BatchLoader(normalize_images(split.images), split.labels,
                         sampler, batch_size=batch)
    step = make_dp_train_step(mesh, lr=0.1)
    state = TrainState(
        jax.device_put(init_mlp(jax.random.key(0)), replicated(mesh)),
        jax.device_put(jax.random.key(1), replicated(mesh)))
    out = fit(state, loader, normalize_images(test.images),
              test.labels.astype(np.int32), epochs=epochs,
              batch_size=batch, train_step=step,
              sharding=batch_sharding(mesh), log=lambda m: None,
              journal=journal)
    return jax.tree_util.tree_map(np.asarray, out.params)


def test_journaled_fit_bitwise_and_schedule_true(tmp_path, mesh):
    plain = _fit_once(mesh)
    reg = MetricsRegistry()
    j = cluster.CollectiveJournal(cluster.journal_path(str(tmp_path), 0),
                                  rank=0, world=1, registry=reg)
    journaled = _fit_once(mesh, journal=j)
    j.close()
    # bitwise: the journal never touches the program or the device
    for a, b in zip(jax.tree_util.tree_leaves(plain),
                    jax.tree_util.tree_leaves(journaled)):
        assert np.array_equal(a, b)
    loaded = cluster.load_journal(cluster.journal_path(str(tmp_path), 0))
    per_step = len(collectives.collective_schedule(_params(), 8, "pmean"))
    steps = 256 // 64
    colls = [r for r in loaded["records"] if r.get("k") != "flush"]
    flushes = [r for r in loaded["records"] if r.get("k") == "flush"]
    assert len(colls) == steps * per_step
    assert len(flushes) == 1 and not loaded["open"]   # epoch flush closed
    assert loaded["program"]["comm"] == "pmean"
    # the report side agrees end to end
    rep = cluster.cluster_report(str(tmp_path))
    assert rep["desync"]["ok"] and rep["hang"]["stuck"] is None
    assert rep["totals"]["collectives"] == steps * per_step + 1


def test_journaled_fit_zero_host_sync(tmp_path, mesh):
    j = cluster.CollectiveJournal(cluster.journal_path(str(tmp_path), 0),
                                  rank=0, registry=MetricsRegistry())
    with sanitize.no_host_sync(max_block_until_ready=0,
                               max_fetches=8) as stats:
        _fit_once(mesh, journal=j)
    j.close()
    assert stats.block_until_ready_calls == 0


def test_fit_rejects_scheduleless_step(tmp_path):
    j = cluster.CollectiveJournal(cluster.journal_path(str(tmp_path), 0),
                                  rank=0, registry=MetricsRegistry())
    split = synthetic_mnist(128, seed=0)
    sampler = ShardedSampler(128, num_replicas=1, rank=0, seed=42)
    loader = BatchLoader(normalize_images(split.images), split.labels,
                         sampler, batch_size=64)
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(1))
    with pytest.raises(ValueError, match="collective schedule"):
        fit(state, loader, normalize_images(split.images),
            split.labels.astype(np.int32), epochs=1, batch_size=64,
            lr=0.1, log=lambda m: None, journal=j)
    j.close(clean=False)


def test_measure_journal_overhead_is_small():
    sched = collectives.collective_schedule(_params(), 8, "int8")
    per_step = cluster.measure_journal_overhead(sched, steps=50)
    assert 0 < per_step < 0.01   # tens of microseconds, not milliseconds


# ---------------------------------------------------------------------------
# --journal CLI knob hygiene (the by-name rejection contract)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("argv,match", [
    (["--journal", "--parallel"], "--telemetry"),
    (["--journal", "--telemetry", "tdir"], "--parallel"),
    (["--journal", "--telemetry", "tdir", "--parallel", "--cached"],
     "streaming"),
    (["--journal", "--telemetry", "tdir", "--parallel", "--cached",
      "--kernel", "pallas_epoch"], "streaming|comms"),
])
def test_journal_cli_hygiene(argv, match, tmp_path, monkeypatch):
    from pytorch_ddp_mnist_tpu.cli import train as train_cli
    monkeypatch.chdir(tmp_path)   # the relative telemetry dir lands here
    with pytest.raises(SystemExit, match=match):
        train_cli.main(argv)


# ---------------------------------------------------------------------------
# flight recorder rank stamp + checker contracts
# ---------------------------------------------------------------------------

def test_flight_entries_carry_rank_stamped_at_record_time():
    rec = flight.get_flight_recorder()
    old = rec.rank
    try:
        flight.set_rank(3)
        flight.record("cluster_test_probe")
        flight.record("cluster_test_probe", rank=7)   # producer wins
        entries = [e for e in rec.snapshot()
                   if e["kind"] == "cluster_test_probe"]
        assert [e["rank"] for e in entries[-2:]] == [3, 7]
    finally:
        flight.set_rank(old)


def test_flight_dump_payload_carries_rank(tmp_path):
    rec = flight.FlightRecorder()
    rec.rank = 5
    rec.record("probe")
    path = rec.dump("test", path=str(tmp_path / "flight.1.json"))
    payload = json.loads(open(path).read())
    assert payload["v"] >= 2 and payload["rank"] == 5
    assert all(isinstance(e["rank"], int) for e in payload["entries"])


def _run_checker(args):
    return subprocess.run([sys.executable, CHECKER, *args],
                          capture_output=True, text=True, timeout=60)


def _valid_trace(tmp_path, metrics):
    p = tmp_path / "events.jsonl"
    recs = [{"v": 1, "kind": "meta", "name": "trace_start", "t_wall": 1.0,
             "t_mono": 1.0, "proc": 0},
            {"v": 1, "kind": "snapshot", "name": "registry", "t_wall": 2.0,
             "t_mono": 2.0, "proc": 0,
             "attrs": {"counters": metrics, "gauges": {},
                       "histograms": {}}}]
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return str(tmp_path)


def test_checker_comma_require_one_invocation(tmp_path):
    d = _valid_trace(tmp_path, {"cluster.collectives": 1,
                                "ddp.bytes_on_wire": 2})
    assert _run_checker(["--require", "cluster.,ddp.", d]).returncode == 0
    bad = _run_checker(["--require", "cluster.,nope.", d])
    assert bad.returncode == 1 and "nope." in bad.stderr
    # a trailing comma is a usage error, not a silently-satisfied gate
    assert _run_checker(["--require", "cluster.,", d]).returncode == 2
    # the repeatable form still composes with the comma form
    assert _run_checker(["--require", "cluster.", "--require", "ddp.",
                         d]).returncode == 0


def test_checker_validates_flight_dump_rank(tmp_path):
    d = _valid_trace(tmp_path, {"x": 1})
    dump = {"v": 2, "reason": "t", "pid": 1, "rank": 0, "recorded": 1,
            "dropped": 0,
            "entries": [{"kind": "probe", "t_wall": 1.0, "t_mono": 1.0,
                         "seq": 0}]}         # <- no rank on the entry
    (tmp_path / "flight.1.json").write_text(json.dumps(dump))
    out = _run_checker([d])
    assert out.returncode == 1 and "rank" in out.stderr
    dump["entries"][0]["rank"] = 0
    (tmp_path / "flight.1.json").write_text(json.dumps(dump))
    assert _run_checker([d]).returncode == 0
    # v1 dumps predate the field: exempt (backward compatibility)
    del dump["entries"][0]["rank"]
    dump["v"] = 1
    (tmp_path / "flight.1.json").write_text(json.dumps(dump))
    assert _run_checker([d]).returncode == 0
