"""Crash-consistent step-granular checkpointing (train/ckpt_manager.py).

Unit tier: manifest/CRC/rotation/fallback semantics, the CheckpointError
wrap on torn msgpack files, the step-position normalization shared by both
trainers, and in-process mid-epoch resume parity through fit/fit_cached
(the subprocess SIGKILL versions live in tests/test_chaos.py)."""

import json
import os
import zlib

import numpy as np
import pytest

import jax

from pytorch_ddp_mnist_tpu.models import init_mlp
from pytorch_ddp_mnist_tpu.telemetry import get_registry
from pytorch_ddp_mnist_tpu.telemetry.flight import get_flight_recorder
from pytorch_ddp_mnist_tpu.train.checkpoint import (CheckpointError,
                                                    load_checkpoint,
                                                    save_checkpoint)
from pytorch_ddp_mnist_tpu.train.ckpt_manager import CheckpointManager
from pytorch_ddp_mnist_tpu.train.loop import step_ckpt_positions


def _params(seed=0):
    return init_mlp(jax.random.key(seed))


def _key_data(seed=1):
    return np.asarray(jax.random.key_data(jax.random.key(seed)))


def _save(mgr, step, epoch=0, offset=0, seed=0):
    return mgr.save(_params(seed), _key_data(), "threefry2x32",
                    step=step, epoch=epoch, offset=offset)


def _flight_kinds():
    return [e["kind"] for e in get_flight_recorder().snapshot()]


def test_save_restore_roundtrip_carries_full_state(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "s"), keep=3)
    _save(mgr, step=7, epoch=1, offset=3, seed=5)
    got = mgr.restore_latest(_params(0))
    assert (got.step, got.epoch, got.offset) == (7, 1, 3)
    assert got.impl == "threefry2x32"
    np.testing.assert_array_equal(got.key_data, _key_data())
    for a, b in zip(jax.tree_util.tree_leaves(got.params),
                    jax.tree_util.tree_leaves(_params(5))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_payload_is_plain_save_checkpoint_format(tmp_path):
    """A manager payload is byte-identical to what save_checkpoint writes —
    load_checkpoint reads it directly (one format, two front doors)."""
    mgr = CheckpointManager(str(tmp_path / "s"), keep=3)
    _save(mgr, step=1, seed=3)
    via_plain = tmp_path / "plain.msgpack"
    save_checkpoint(str(via_plain), _params(3))
    payload = tmp_path / "s" / "step_00000001.msgpack"
    assert payload.read_bytes() == via_plain.read_bytes()
    loaded = load_checkpoint(str(payload), _params(0))
    for a, b in zip(jax.tree_util.tree_leaves(loaded),
                    jax.tree_util.tree_leaves(_params(3))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_rotation_keeps_last_n(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "s"), keep=2)
    for s in (2, 4, 6, 8):
        _save(mgr, step=s)
    assert mgr.steps() == [6, 8]
    names = sorted(os.listdir(tmp_path / "s"))
    assert names == ["step_00000006.json", "step_00000006.msgpack",
                     "step_00000008.json", "step_00000008.msgpack"]


def _nan_params(seed=0):
    p = _params(seed)
    leaves, treedef = jax.tree_util.tree_flatten(p)
    leaves = [np.asarray(a) for a in leaves]
    leaves[0] = np.full_like(leaves[0], np.nan)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def test_pinned_checkpoint_survives_rotation(tmp_path):
    """The health watchdog's rescue save (pin=True) sits outside the
    keep-last-N budget: later routine saves never rotate it away, and the
    stray-payload sweep never collects its payload."""
    mgr = CheckpointManager(str(tmp_path / "s"), keep=2)
    mgr.save(_params(9), _key_data(), "threefry2x32",
             step=4, epoch=0, offset=4, pin=True)
    for s in (8, 12, 16, 20):
        _save(mgr, step=s)
    assert mgr.steps() == [4, 16, 20]
    assert (tmp_path / "s" / "step_00000004.msgpack").exists()
    with open(tmp_path / "s" / "step_00000004.json") as f:
        assert json.load(f)["pinned"] is True
    # the pinned state is still fully restorable
    got = mgr._load_intact(4, _params(0))
    for a, b in zip(jax.tree_util.tree_leaves(got.params),
                    jax.tree_util.tree_leaves(_params(9))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_prefers_newest_finite_over_nan_checkpoints(tmp_path):
    """A diverged run commits intact-by-CRC checkpoints full of NaN;
    restore must land on the newest FINITE one (the rescue), recording
    each skipped non-finite candidate to the flight recorder."""
    mgr = CheckpointManager(str(tmp_path / "s"), keep=5)
    mgr.save(_params(7), _key_data(), "threefry2x32",
             step=4, epoch=0, offset=4, pin=True)
    for s in (8, 12):
        mgr.save(_nan_params(), _key_data(), "threefry2x32",
                 step=s, epoch=0, offset=s)
    before = len(get_flight_recorder().snapshot())
    got = mgr.restore_latest(_params(0))
    assert got.step == 4
    assert all(np.isfinite(np.asarray(a)).all()
               for a in jax.tree_util.tree_leaves(got.params))
    tail = get_flight_recorder().snapshot()[before:]
    assert [e["kind"] for e in tail] == ["checkpoint_fallback",
                                        "checkpoint_fallback",
                                        "checkpoint_restore"]
    assert "non-finite" in tail[0]["error"]


def test_restore_all_nonfinite_falls_back_to_newest_with_warning(tmp_path,
                                                                 capsys):
    """No finite candidate at all: restore returns the newest intact one
    anyway (refusing would strand pre-watchdog resumes) — loudly."""
    mgr = CheckpointManager(str(tmp_path / "s"), keep=3)
    for s in (2, 4):
        mgr.save(_nan_params(), _key_data(), "threefry2x32",
                 step=s, epoch=0, offset=s)
    got = mgr.restore_latest(_params(0))
    assert got.step == 4
    assert "non-finite" in capsys.readouterr().err


def test_truncated_newest_falls_back_and_records_flight(tmp_path):
    """THE acceptance property: newest payload truncated -> restore returns
    the previous intact checkpoint and the fallback lands in the flight
    recorder."""
    mgr = CheckpointManager(str(tmp_path / "s"), keep=3)
    _save(mgr, step=2, seed=1)
    _save(mgr, step=4, epoch=0, offset=4, seed=2)
    newest = tmp_path / "s" / "step_00000004.msgpack"
    newest.write_bytes(newest.read_bytes()[: newest.stat().st_size // 2])
    before = len(get_flight_recorder().snapshot())
    got = mgr.restore_latest(_params(0))
    assert got.step == 2
    for a, b in zip(jax.tree_util.tree_leaves(got.params),
                    jax.tree_util.tree_leaves(_params(1))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    tail = get_flight_recorder().snapshot()[before:]
    kinds = [e["kind"] for e in tail]
    assert "checkpoint_fallback" in kinds
    fb = tail[kinds.index("checkpoint_fallback")]
    assert fb["step"] == 4 and "truncated" in fb["error"]
    restore = tail[kinds.index("checkpoint_restore")]
    assert restore["step"] == 2 and restore["fallbacks"] == 1


def test_crc_mismatch_falls_back(tmp_path):
    """Same-length corruption (bit rot) passes the size check and must be
    caught by the CRC32 stamp."""
    mgr = CheckpointManager(str(tmp_path / "s"), keep=3)
    _save(mgr, step=2, seed=1)
    _save(mgr, step=4, seed=2)
    newest = tmp_path / "s" / "step_00000004.msgpack"
    blob = bytearray(newest.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    newest.write_bytes(bytes(blob))
    # ensure the corruption is not a CRC no-op
    rec = json.loads((tmp_path / "s" / "step_00000004.json").read_text())
    assert zlib.crc32(bytes(blob)) != rec["crc32"]
    assert mgr.restore_latest(_params(0)).step == 2


def test_missing_payload_and_bad_manifest_fall_back(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "s"), keep=4)
    _save(mgr, step=2, seed=1)
    _save(mgr, step=4, seed=2)
    _save(mgr, step=6, seed=3)
    os.unlink(tmp_path / "s" / "step_00000006.msgpack")   # payload gone
    (tmp_path / "s" / "step_00000004.json").write_text("{not json")
    assert mgr.restore_latest(_params(0)).step == 2


def test_manifest_missing_fields_falls_back_not_keyerror(tmp_path):
    """Valid JSON missing a required field must surface as a
    CheckpointError (so restore_latest's fallback walk absorbs it), never
    a KeyError crashing the relaunch."""
    mgr = CheckpointManager(str(tmp_path / "s"), keep=3)
    _save(mgr, step=2, seed=1)
    _save(mgr, step=4, seed=2)
    m = tmp_path / "s" / "step_00000004.json"
    rec = json.loads(m.read_text())
    del rec["bytes"]
    m.write_text(json.dumps(rec))
    assert mgr.restore_latest(_params(0)).step == 2
    with pytest.raises(CheckpointError, match="missing fields"):
        mgr._load_intact(4, _params(0))


def test_geometry_meta_roundtrips_and_cli_refuses_mismatch(tmp_path):
    """The manifest stamps run geometry; a directory resume under a
    different global batch is refused by name (a silently re-interpreted
    (epoch, offset) would walk off the bitwise trajectory)."""
    from pytorch_ddp_mnist_tpu.cli.train import main

    base = ["--limit", "512", "--lr", "0.1", "--cached", "--n_epochs", "1",
            "--path", str(tmp_path)]
    ckpt = tmp_path / "m.msgpack"
    assert main(base + ["--batch_size", "64", "--checkpoint", str(ckpt),
                        "--ckpt_every_steps", "3"]) == 0
    mgr = CheckpointManager(str(tmp_path / "m.msgpack.steps"))
    assert mgr.restore_latest(_params(0)).meta == {
        "global_batch": 64, "limit": 512, "sampler_rng": "pcg64",
        "model": "mlp", "param_scale": 1}
    with pytest.raises(SystemExit, match="global_batch"):
        main(base + ["--batch_size", "32", "--checkpoint", str(ckpt),
                     "--resume", str(tmp_path / "m.msgpack.steps")])
    # model size is geometry too: flax from_bytes restores by dict KEYS
    # (no shape check), so a mismatched --param_scale template would
    # silently accept the blob and train the wrong model
    with pytest.raises(SystemExit, match="param_scale"):
        main(base + ["--batch_size", "64", "--param_scale", "2",
                     "--checkpoint", str(ckpt),
                     "--resume", str(tmp_path / "m.msgpack.steps")])


def test_no_intact_checkpoint_raises_naming_every_tried(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "s"), keep=3)
    _save(mgr, step=2)
    _save(mgr, step=4)
    for s in (2, 4):
        (tmp_path / "s" / f"step_{s:08d}.msgpack").write_bytes(b"xx")
    with pytest.raises(CheckpointError) as ei:
        mgr.restore_latest(_params(0))
    msg = str(ei.value)
    assert "step_00000002.msgpack" in msg and "step_00000004.msgpack" in msg


def test_empty_directory_raises_named(tmp_path):
    with pytest.raises(CheckpointError, match="no committed step"):
        CheckpointManager(str(tmp_path / "nothing")).restore_latest(
            _params(0))


def test_rotation_sweeps_crash_debris(tmp_path):
    """A SIGKILL mid-save leaves .tmp strays / manifest-less payloads from
    the DEAD process; the next successful save sweeps them (each chaos
    cycle would otherwise grow the directory by one full-size orphan)."""
    mgr = CheckpointManager(str(tmp_path / "s"), keep=3)
    _save(mgr, step=2, seed=1)
    (tmp_path / "s" / "step_00000005.msgpack.tmp.99999").write_bytes(b"x")
    (tmp_path / "s" / "step_00000005.msgpack").write_bytes(b"uncommitted")
    _save(mgr, step=6, seed=2)
    assert sorted(os.listdir(tmp_path / "s")) == [
        "step_00000002.json", "step_00000002.msgpack",
        "step_00000006.json", "step_00000006.msgpack"]


def test_uncommitted_payload_is_invisible(tmp_path):
    """A payload without its manifest (crash between the two renames) is an
    uncommitted checkpoint: restore never considers it."""
    mgr = CheckpointManager(str(tmp_path / "s"), keep=3)
    _save(mgr, step=2, seed=1)
    # fake a crash: payload landed, manifest did not
    (tmp_path / "s" / "step_00000009.msgpack").write_bytes(b"partial")
    assert mgr.steps() == [2]
    assert mgr.restore_latest(_params(0)).step == 2


def test_injected_save_io_fault_fails_cleanly(tmp_path, monkeypatch):
    """PDMT_FAULT=ckpt_save_io:step=K: save K raises CheckpointError, no
    torn state is left behind, and prior checkpoints stay restorable."""
    from pytorch_ddp_mnist_tpu.utils import faultpoints
    monkeypatch.setenv("PDMT_FAULT", "ckpt_save_io:step=4")
    faultpoints.install()
    try:
        mgr = CheckpointManager(str(tmp_path / "s"), keep=3)
        _save(mgr, step=2, seed=1)
        with pytest.raises(CheckpointError, match="step 4"):
            _save(mgr, step=4, seed=2)
        assert mgr.steps() == [2]
        assert not [n for n in os.listdir(tmp_path / "s") if ".tmp" in n]
        assert mgr.restore_latest(_params(0)).step == 2
        _save(mgr, step=6, seed=3)      # the fault fired once; saves resume
        assert mgr.steps() == [2, 6]
    finally:
        monkeypatch.delenv("PDMT_FAULT")
        faultpoints.install()


def _resid(seed=2, n_dev=8, elems=2048):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n_dev, elems)).astype(np.float32)


def test_resid_payload_roundtrips(tmp_path):
    """The int8 error-feedback residual rides as a second payload with its
    own size/CRC stamp and restores exactly; saves without one restore
    resid=None (every pre-int8 manifest keeps working)."""
    mgr = CheckpointManager(str(tmp_path / "s"), keep=3)
    r = _resid()
    mgr.save(_params(5), _key_data(), "threefry2x32",
             step=4, epoch=0, offset=4, resid=r)
    got = mgr.restore_latest(_params(0))
    assert got.resid is not None and got.resid.dtype == np.float32
    np.testing.assert_array_equal(got.resid, r)
    rec = json.loads((tmp_path / "s" / "step_00000004.json").read_text())
    assert rec["resid_payload"] == "step_00000004.resid.msgpack"
    rblob = (tmp_path / "s" / "step_00000004.resid.msgpack").read_bytes()
    assert rec["resid_bytes"] == len(rblob)
    assert rec["resid_crc32"] == zlib.crc32(rblob)
    # a plain save in the same directory restores with resid=None
    _save(mgr, step=6, seed=1)
    assert mgr.restore_latest(_params(0)).resid is None


def test_torn_resid_makes_checkpoint_torn(tmp_path):
    """A truncated or bit-rotted residual payload fails the WHOLE
    checkpoint (resuming quantization-error accounting from garbage would
    silently corrupt gradients) — restore falls back to the previous
    intact one."""
    mgr = CheckpointManager(str(tmp_path / "s"), keep=3)
    mgr.save(_params(1), _key_data(), "threefry2x32",
             step=2, epoch=0, offset=2, resid=_resid(1))
    mgr.save(_params(2), _key_data(), "threefry2x32",
             step=4, epoch=0, offset=4, resid=_resid(2))
    rp = tmp_path / "s" / "step_00000004.resid.msgpack"
    rp.write_bytes(rp.read_bytes()[: rp.stat().st_size // 2])
    got = mgr.restore_latest(_params(0))
    assert got.step == 2
    np.testing.assert_array_equal(got.resid, _resid(1))
    with pytest.raises(CheckpointError, match="truncated residual"):
        mgr._load_intact(4, _params(0))
    # same-length corruption: the CRC stamp catches it
    mgr.save(_params(3), _key_data(), "threefry2x32",
             step=6, epoch=0, offset=6, resid=_resid(3))
    rp6 = tmp_path / "s" / "step_00000006.resid.msgpack"
    b6 = bytearray(rp6.read_bytes())
    b6[len(b6) // 2] ^= 0xFF
    rp6.write_bytes(bytes(b6))
    with pytest.raises(CheckpointError, match="residual CRC32"):
        mgr._load_intact(6, _params(0))


def test_rotation_and_sweep_cover_resid_payloads(tmp_path):
    """keep-last-N rotation deletes the residual payload with its
    checkpoint, and the crash-debris sweep collects manifest-less resid
    strays."""
    mgr = CheckpointManager(str(tmp_path / "s"), keep=2)
    for s in (2, 4, 6):
        mgr.save(_params(s), _key_data(), "threefry2x32",
                 step=s, epoch=0, offset=s, resid=_resid(s))
    assert mgr.steps() == [4, 6]
    names = sorted(os.listdir(tmp_path / "s"))
    assert names == [
        "step_00000004.json", "step_00000004.msgpack",
        "step_00000004.resid.msgpack",
        "step_00000006.json", "step_00000006.msgpack",
        "step_00000006.resid.msgpack"]
    # a dead writer's orphan resid payload is swept by the next save
    (tmp_path / "s" / "step_00000009.resid.msgpack").write_bytes(b"orphan")
    mgr.save(_params(8), _key_data(), "threefry2x32",
             step=8, epoch=0, offset=8, resid=_resid(8))
    assert "step_00000009.resid.msgpack" not in os.listdir(tmp_path / "s")


def test_save_publishes_registry_metrics(tmp_path):
    reg = get_registry()
    hist = reg.histogram("checkpoint.save_s")
    ctr = reg.counter("checkpoint.bytes")
    h0, c0 = hist.n, ctr.value
    _save(CheckpointManager(str(tmp_path / "s")), step=1)
    assert hist.n == h0 + 1
    assert ctr.value > c0


def test_load_checkpoint_wraps_torn_msgpack(tmp_path):
    """Satellite: a truncated/corrupt msgpack surfaces as CheckpointError
    naming the path and byte size, not a raw flax/msgpack traceback."""
    good = tmp_path / "good.msgpack"
    save_checkpoint(str(good), _params(0))
    torn = tmp_path / "torn.msgpack"
    torn.write_bytes(good.read_bytes()[:100])
    with pytest.raises(CheckpointError) as ei:
        load_checkpoint(str(torn), _params(0))
    assert "torn.msgpack" in str(ei.value) and "100 bytes" in str(ei.value)
    assert isinstance(ei.value, RuntimeError)  # old except-clauses still work


def test_step_ckpt_positions_normalizes_epoch_final_step():
    assert step_ckpt_positions(8, epoch=2, i=3) == (2, 4)
    # the state after an epoch's last step IS the next epoch's start
    assert step_ckpt_positions(8, epoch=2, i=7) == (3, 0)


@pytest.mark.parametrize("cached", [True, False], ids=["cached", "streaming"])
def test_midepoch_resume_is_bitwise_identical(tmp_path, cached):
    """In-process resume parity for BOTH trainers: restore a mid-epoch step
    checkpoint and replay the remaining steps — final params bitwise equal
    to the unbroken run. (The SIGKILL versions are tests/test_chaos.py.)"""
    from pytorch_ddp_mnist_tpu.cli.train import main
    from pytorch_ddp_mnist_tpu.train.checkpoint import load_checkpoint

    base = ["--limit", "512", "--batch_size", "64", "--lr", "0.1",
            "--n_epochs", "2", "--path", str(tmp_path)] + (
                ["--cached"] if cached else [])
    golden = tmp_path / "golden.msgpack"
    assert main(base + ["--checkpoint", str(golden)]) == 0

    work = tmp_path / "work.msgpack"
    assert main(base + ["--checkpoint", str(work),
                        "--ckpt_every_steps", "3"]) == 0
    steps_dir = tmp_path / "work.msgpack.steps"
    mgr = CheckpointManager(str(steps_dir))
    # drop back to a MID-epoch checkpoint (8 steps/epoch; keep-last-3 of
    # the 2-epoch run holds steps 11, 14, 16 — 14 is (epoch 1, offset 6))
    mid = [s for s in mgr.steps() if mgr._load_intact(s, _params(0)).offset]
    assert mid, mgr.steps()
    for s in mgr.steps():
        if s > mid[-1]:
            os.unlink(steps_dir / f"step_{s:08d}.json")
            os.unlink(steps_dir / f"step_{s:08d}.msgpack")
    resumed = tmp_path / "resumed.msgpack"
    assert main(base + ["--checkpoint", str(resumed),
                        "--ckpt_every_steps", "3",
                        "--resume", str(steps_dir)]) == 0
    for name in ("work.msgpack", "resumed.msgpack"):
        got = load_checkpoint(str(tmp_path / name), _params(0))
        want = load_checkpoint(str(golden), _params(0))
        for a, b in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(want)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cli_flag_rejections(tmp_path):
    """--ckpt_every_steps composition limits + --fault parse errors fail at
    the CLI boundary by name."""
    from pytorch_ddp_mnist_tpu.cli.train import main

    base = ["--path", str(tmp_path)]
    with pytest.raises(SystemExit, match="fused"):
        main(base + ["--cached", "--fused", "--ckpt_every_steps", "2"])
    with pytest.raises(SystemExit, match="pallas_epoch"):
        main(base + ["--cached", "--kernel", "pallas_epoch",
                     "--ckpt_every_steps", "2"])
    with pytest.raises(SystemExit, match="checkpoint"):
        main(base + ["--ckpt_every_steps", "2", "--checkpoint", ""])
    with pytest.raises(SystemExit, match="ckpt_keep"):
        main(base + ["--ckpt_every_steps", "2", "--ckpt_keep", "0"])
    with pytest.raises(SystemExit, match="unknown fault kind"):
        main(base + ["--fault", "explode:step=1"])
    with pytest.raises(SystemExit, match="start_epoch conflicts"):
        d = tmp_path / "steps"
        d.mkdir()
        main(base + ["--resume", str(d), "--start_epoch", "1",
                     "--n_epochs", "2"])


def test_geometry_mismatch_message_names_both_and_points_at_reshape():
    """The elastic satellite: a refusal must print BOTH geometries, name
    the differing keys, and point at --reshape (docs/ROBUSTNESS.md
    §Elastic training) — not just reject by key name."""
    from pytorch_ddp_mnist_tpu.train.ckpt_manager import (
        geometry_mismatch_message)
    manifest = {"global_batch": 128, "limit": 512, "model": "mlp"}
    requested = {"global_batch": 64, "limit": 512, "model": "mlp"}
    msg = geometry_mismatch_message(manifest, requested)
    assert msg is not None
    assert "checkpoint geometry:" in msg and "requested geometry:" in msg
    assert "global_batch=128" in msg and "global_batch=64" in msg
    assert "differing: global_batch" in msg
    assert "--reshape" in msg and "--elastic" in msg
    # matching geometries -> no refusal
    assert geometry_mismatch_message(requested, dict(requested)) is None
    # extra manifest-only keys (devices / elastic_gen stamps) are ignored
    stamped = dict(requested, devices=2, elastic_gen=3)
    assert geometry_mismatch_message(stamped, requested) is None


def test_peek_latest_meta_reads_newest_manifest_without_payload(tmp_path):
    from pytorch_ddp_mnist_tpu.train.ckpt_manager import peek_latest_meta
    mgr = CheckpointManager(str(tmp_path / "s"), keep=3)
    mgr.save(_params(), _key_data(), "threefry2x32", step=2, epoch=0,
             offset=1, meta={"global_batch": 64, "devices": 2})
    mgr.save(_params(1), _key_data(), "threefry2x32", step=5, epoch=1,
             offset=3, meta={"global_batch": 64, "devices": 2})
    peek = peek_latest_meta(str(tmp_path / "s"))
    assert peek == {"step": 5, "epoch": 1, "offset": 3,
                    "meta": {"global_batch": 64, "devices": 2}}
    assert peek_latest_meta(str(tmp_path / "missing")) is None
