"""Native C++ reader core vs the pure-Python parsers (which are the format
source of truth). Covers IDX and CDF-5, whole reads, sharded row gathers,
coalesced runs, and error paths."""

import numpy as np
import pytest

from pytorch_ddp_mnist_tpu.data import synthetic_mnist, write_idx
from pytorch_ddp_mnist_tpu.data.netcdf import (write_mnist_netcdf,
                                               write_netcdf, NetCDFReader)
from pytorch_ddp_mnist_tpu.data.native import (NativeReader, native_available,
                                               native_build_error)

pytestmark = pytest.mark.skipif(
    not native_available(), reason=f"no native reader: {native_build_error()}")


@pytest.fixture(scope="module")
def split():
    return synthetic_mnist(64, seed=5)


def test_netcdf_whole_read_matches_python(tmp_path, split):
    path = str(tmp_path / "m.nc")
    write_mnist_netcdf(path, split.images, split.labels)
    with NativeReader(path) as r:
        assert r.variables["images"][0] == (64, 28, 28)
        np.testing.assert_array_equal(r.read("images"), split.images)
        np.testing.assert_array_equal(r.read("labels"), split.labels)


def test_netcdf_row_gather_matches_python(tmp_path, split):
    path = str(tmp_path / "m.nc")
    write_mnist_netcdf(path, split.images, split.labels)
    py = NetCDFReader(path)
    # mixed order, duplicates, and a contiguous run to exercise coalescing
    idx = [5, 6, 7, 8, 3, 3, 63, 0, 10, 11, 12]
    with NativeReader(path) as r:
        np.testing.assert_array_equal(r.read("images", idx),
                                      py.read("images", idx))
        np.testing.assert_array_equal(r.read("labels", idx),
                                      py.read("labels", idx))


def test_idx_files(tmp_path, split):
    ipath = str(tmp_path / "imgs-idx3-ubyte")
    lpath = str(tmp_path / "lbls-idx1-ubyte")
    write_idx(ipath, split.images)
    write_idx(lpath, split.labels)
    with NativeReader(ipath) as r:
        assert list(r.variables) == ["images"]
        np.testing.assert_array_equal(r.read("images"), split.images)
        np.testing.assert_array_equal(r.read("images", [2, 2, 50]),
                                      split.images[[2, 2, 50]])
    with NativeReader(lpath) as r:
        np.testing.assert_array_equal(r.read("labels"), split.labels)


def test_multibyte_dtype_byteswap(tmp_path):
    rng = np.random.default_rng(0)
    f32 = rng.normal(size=(10, 4)).astype(np.float32)
    i64 = rng.integers(-1 << 40, 1 << 40, size=(4,)).astype(np.int64)
    path = str(tmp_path / "t.nc")
    write_netcdf(path, {"a": 10, "b": 4},
                 {"f": (("a", "b"), f32), "q": (("b",), i64)})
    with NativeReader(path) as r:
        np.testing.assert_array_equal(r.read("f"), f32)
        np.testing.assert_array_equal(r.read("f", [9, 0]), f32[[9, 0]])
        np.testing.assert_array_equal(r.read("q"), i64)


def test_large_sharded_gather_threads(tmp_path):
    # >4 MiB across many runs triggers the thread pool path.
    n, row = 4096, 2048
    data = np.arange(n * row, dtype=np.uint8).reshape(n, row) % 251
    path = str(tmp_path / "big.nc")
    write_netcdf(path, {"n": n, "r": row}, {"d": (("n", "r"), data)})
    idx = np.random.default_rng(1).permutation(n)[: n // 2 * 2]
    with NativeReader(path) as r:
        np.testing.assert_array_equal(r.read("d", idx), data[idx])


def test_randomized_schemas_cpp_matches_python(tmp_path):
    """Fuzz the C++ parser against the Python writer (the format source of
    truth): random dims/vars/dtypes across CDF-1/2/5, whole reads and
    shuffled gathers must match the Python reader bit-for-bit."""
    rng = np.random.default_rng(0xFEED)
    dtypes = [np.uint8, np.int8, np.int16, np.int32, np.float32, np.float64]
    for trial in range(12):
        version = int(rng.choice([1, 2, 5]))
        ndims = int(rng.integers(1, 4))
        dims = {f"d{i}": int(rng.integers(1, 9)) for i in range(ndims)}
        variables = {}
        for v in range(int(rng.integers(1, 4))):
            k = int(rng.integers(1, ndims + 1))
            chosen = list(rng.choice(list(dims), size=k, replace=False))
            shape = tuple(dims[c] for c in chosen)
            dt = dtypes[int(rng.integers(0, len(dtypes)))]
            if np.issubdtype(dt, np.floating):
                arr = rng.normal(size=shape).astype(dt)
            else:
                info = np.iinfo(dt)
                arr = rng.integers(info.min, info.max, size=shape,
                                   endpoint=True).astype(dt)
            variables[f"v{v}"] = (tuple(chosen), arr)
        path = str(tmp_path / f"fuzz{trial}.nc")
        write_netcdf(path, dims, variables, version=version)
        py = NetCDFReader(path)
        with NativeReader(path) as r:
            for name, (_, arr) in variables.items():
                np.testing.assert_array_equal(r.read(name), arr)
                np.testing.assert_array_equal(r.read(name), py.read(name))
                n0 = arr.shape[0]
                idx = rng.permutation(n0)[:max(1, n0 // 2)]
                np.testing.assert_array_equal(r.read(name, idx), arr[idx])


def test_concurrent_gathers_share_the_pool(tmp_path):
    """Multiple Python threads issuing pool-qualifying gathers at once (the
    readahead-worker pattern; the GIL is released inside the ctypes call).
    Pool::run is serialized across callers — results must be bit-exact."""
    import threading

    n, row = 1024, 784
    data = (np.arange(n * row, dtype=np.int64) % 251).astype(np.uint8)
    data = data.reshape(n, row)
    path = str(tmp_path / "c.nc")
    write_netcdf(path, {"n": n, "r": row}, {"d": (("n", "r"), data)})
    rng = np.random.default_rng(7)
    idxs = [rng.permutation(n)[:512] for _ in range(8)]
    results = [None] * len(idxs)
    errors = []

    with NativeReader(path) as r:
        def work(k):
            try:
                for _ in range(5):
                    results[k] = r.read("d", idxs[k])
            except Exception as e:  # pragma: no cover - failure path
                errors.append(e)

        threads = [threading.Thread(target=work, args=(k,))
                   for k in range(len(idxs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errors
    for k, out in enumerate(results):
        np.testing.assert_array_equal(out, data[idxs[k]])


def test_errors(tmp_path, split):
    path = str(tmp_path / "m.nc")
    write_mnist_netcdf(path, split.images, split.labels)
    with NativeReader(path) as r:
        with pytest.raises(KeyError):
            r.read("nope")
        with pytest.raises(IndexError):
            r.read("images", [64])
        with pytest.raises(IndexError):
            r.read("images", [-1])
    bad = str(tmp_path / "bad.bin")
    with open(bad, "wb") as f:
        f.write(b"\x12\x34\x56\x78" * 4)
    with pytest.raises(ValueError, match="magic"):
        NativeReader(bad)
    with pytest.raises(ValueError, match="open"):
        NativeReader(str(tmp_path / "missing.nc"))
