"""TPU-platform export lowering of every single-chip Pallas variant.

`jax.export` runs the full Pallas->Mosaic lowering pipeline CLIENT-SIDE
(platforms=('tpu',)) — including Mosaic's block-shape legality checks
(last two block dims divisible by (8, 128) or equal to the array dims,
memory-space rules, etc.) that the Pallas INTERPRETER never enforces. A
kernel can therefore pass every interpreter/simulator test and still be
unlaunchable on hardware: exactly what happened to the round-4 in-kernel
threefry epoch kernel, whose per-iteration (K, 2) SMEM key block was
illegal (K=1 row: neither divisible by 8 nor equal to the S-row array) and
which only surfaced in the round-5 hardware window's variant matrix.

These tests pin "lowers for TPU" for every kernel variant the bench
matrix measures, on a plain CPU host — no TPU needed, so CI catches the
whole class. The DP ring variants lower over a deviceless
jax.sharding.AbstractMesh (remote DMAs, cross-chip semaphores and the
entry barrier all go through the same client-side legality pipeline);
their hardware-SEMANTICS coverage is the TPU-semantics simulator suite
in test_pallas_step.py.

Reference workload being lowered: the flagship trainer of
/root/reference/ddp_tutorial_multi_gpu.py (118,272-param MLP, batch 128).
"""

import functools

import jax
import jax.numpy as jnp
import pytest
from jax import export

from pytorch_ddp_mnist_tpu.models.mlp import init_mlp
from pytorch_ddp_mnist_tpu.ops.pallas_step import (
    HIDDEN1,
    dropout_mask,
    epoch_fused_sgd,
    fused_loss_and_grads,
    fused_loss_and_grads_rng,
)

B = 128
S = 12  # steps: exercises loss-tile revisit (12 steps -> 2 tiles) + K tails


def _export_tpu(fn, *args):
    """Export `fn` for the TPU platform from this CPU host; any Mosaic
    lowering-legality error raises here, without hardware."""
    return export.export(jax.jit(fn), platforms=("tpu",))(*args)


@pytest.fixture(scope="module")
def epoch_args():
    params = init_mlp(jax.random.PRNGKey(0))
    xp8 = jnp.zeros((S * B, 784), jnp.uint8)
    yp = jnp.zeros((S * B,), jnp.int32)
    return params, xp8, yp


@pytest.mark.parametrize("K", [1, 2, 4, 8])
@pytest.mark.parametrize("bf16", [False, True], ids=["f32", "bf16"])
def test_epoch_kernel_core_rng_lowers(epoch_args, K, bf16):
    params, xp8, yp = epoch_args
    f = functools.partial(epoch_fused_sgd, lr=0.01, batch=B,
                          steps_per_iter=K, compute_bf16=bf16)
    _export_tpu(f, params, xp8, yp, jnp.int32(7))


@pytest.mark.parametrize("K", [1, 2, 4, 8])
def test_epoch_kernel_threefry_lowers(epoch_args, K):
    # The round-4 regression: per-step threefry key words streamed as an
    # illegal (K, 2) SMEM block failed exactly this lowering; the key
    # table is now SMEM-resident whole.
    params, xp8, yp = epoch_args
    keys = jax.random.split(jax.random.PRNGKey(1), S)
    seed = jnp.asarray(jax.vmap(jax.random.key_data)(keys), jnp.int32)
    f = functools.partial(epoch_fused_sgd, lr=0.01, batch=B,
                          rng_impl="threefry", steps_per_iter=K)
    _export_tpu(f, params, xp8, yp, seed)


def test_epoch_kernel_threefry_ragged_tail_lowers(epoch_args):
    # valid_steps < padded steps: the hot-path ragged form (scan body
    # pre-pads indices and masks the tail) must lower too.
    params, xp8, yp = epoch_args
    keys = jax.random.split(jax.random.PRNGKey(1), S)
    seed = jnp.asarray(jax.vmap(jax.random.key_data)(keys), jnp.int32)
    f = functools.partial(epoch_fused_sgd, lr=0.01, batch=B,
                          rng_impl="threefry", steps_per_iter=8,
                          valid_steps=S - 2)
    _export_tpu(f, params, xp8, yp, seed)


def test_epoch_kernel_f32_input_lowers(epoch_args):
    # Pre-normalized f32 input stream (the non-uint8 path).
    params, xp8, yp = epoch_args
    f = functools.partial(epoch_fused_sgd, lr=0.01, batch=B)
    _export_tpu(f, params, xp8.astype(jnp.float32), yp, jnp.int32(7))


def test_epoch_kernel_mask_streaming_lowers(epoch_args):
    params, xp8, yp = epoch_args
    masks = jnp.ones((S * B, HIDDEN1), jnp.float32)

    def f(params, xp, yp, masks):
        return epoch_fused_sgd(params, xp, yp, jnp.int32(0), 0.01, B,
                               masks=masks)

    _export_tpu(f, params, xp8, yp, masks)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
def test_per_step_kernel_lowers(dtype):
    params = init_mlp(jax.random.PRNGKey(0))
    x = jnp.zeros((B, 784), dtype)
    y = jnp.zeros((B,), jnp.int32)
    mask = dropout_mask(jax.random.PRNGKey(2), B)
    f = functools.partial(fused_loss_and_grads, scaled_mask=mask)
    _export_tpu(f, params, x, y)


def test_per_step_rng_kernel_lowers():
    params = init_mlp(jax.random.PRNGKey(0))
    x = jnp.zeros((B, 784), jnp.float32)
    y = jnp.zeros((B,), jnp.int32)
    _export_tpu(functools.partial(fused_loss_and_grads_rng, seed=7),
                params, x, y)


def test_per_step_kernel_ragged_batch_lowers():
    # Non-block-multiple batch: grid + zero-padded tail path.
    params = init_mlp(jax.random.PRNGKey(0))
    n = 300
    x = jnp.zeros((n, 784), jnp.float32)
    y = jnp.zeros((n,), jnp.int32)
    mask = dropout_mask(jax.random.PRNGKey(2), n)
    f = functools.partial(fused_loss_and_grads, scaled_mask=mask)
    _export_tpu(f, params, x, y)


# ---------------------------------------------------------------------------
# DP ring variants: an AbstractMesh lets the shard_map'd ring kernel —
# remote DMAs, cross-chip semaphores, entry barrier — run the same
# client-side Mosaic legality pipeline with no devices at all.
# ---------------------------------------------------------------------------

from jax.sharding import PartitionSpec as Pspec  # noqa: E402

from pytorch_ddp_mnist_tpu.compat import abstract_mesh  # noqa: E402
from pytorch_ddp_mnist_tpu.compat import shard_map  # noqa: E402


def _export_dp(n, *, ring="auto", bf16=False, rng_impl="core"):
    mesh = abstract_mesh((n,), ("dp",))
    params = init_mlp(jax.random.PRNGKey(0))
    xp = jnp.zeros((n * S * B, 784), jnp.uint8)
    yp = jnp.zeros((n * S * B,), jnp.int32)
    if rng_impl == "threefry":
        keys = jax.random.split(jax.random.PRNGKey(1), S)
        seed = jnp.asarray(jax.vmap(jax.random.key_data)(keys), jnp.int32)
    else:
        seed = jnp.int32(7)

    def f(params, xp, yp):
        def shard(params, xp, yp):
            return epoch_fused_sgd(params, xp, yp, seed, 0.01, B,
                                   axis_name="dp", axis_size=n, ring=ring,
                                   compute_bf16=bf16, rng_impl=rng_impl)
        return shard_map(shard, mesh=mesh,
                         in_specs=(Pspec(), Pspec("dp"), Pspec("dp")),
                         out_specs=(Pspec(), Pspec("dp")),
                         check_vma=False)(params, xp, yp)

    _export_tpu(f, params, xp, yp)


@pytest.mark.parametrize("n", [2, 8])
def test_dp_ring_allgather_lowers(n):
    # n=8 fills the all-gather ring's whole VMEM slot budget
    _export_dp(n, ring="allgather")


@pytest.mark.parametrize("n", [2, 16])
def test_dp_ring_reduce_scatter_lowers(n):
    # n=16 exceeds EPOCH_KERNEL_MAX_DEVICES: only the rs ring serves it
    _export_dp(n, ring="reduce_scatter")


def test_dp_ring_bf16_lowers():
    _export_dp(4, bf16=True)


def test_dp_ring_threefry_lowers():
    # the fixed SMEM-resident key table, in the DP kernel
    _export_dp(2, rng_impl="threefry")


# ---------------------------------------------------------------------------
# Gradient-communication strategies (parallel/collectives.py): every comm
# program of the DP train step — pmean, bucketized reduce-scatter +
# sharded update + all-gather, bf16-compressed allreduce, int8 quantized —
# must lower for an 8-device TPU mesh from this CPU host, AND honor the
# structural contracts (collective kinds/counts, wire dtypes, ring-model
# bytes). Both assertions run through statics/jaxpr_audit.py's SHARED
# program builders: the program these tests export-lower is byte-for-byte
# the program the auditor walks, so the tool and the tests cannot drift —
# the ad-hoc per-test checks this section used to hand-write are now the
# auditor's contract table (docs/STATIC_ANALYSIS.md).
# ---------------------------------------------------------------------------

from pytorch_ddp_mnist_tpu.statics import jaxpr_audit  # noqa: E402


@pytest.mark.parametrize("comm,overlap", [
    ("pmean", False), ("sharded", False), ("bf16", False), ("int8", False),
    ("pmean", True), ("bf16", True)])
def test_dp_comm_strategy_step_lowers_and_audits(comm, overlap):
    prog, args = jaxpr_audit.build_step_program(comm, overlap)
    _export_tpu(prog, *args)           # Mosaic/TPU client-side legality
    report = jaxpr_audit.audit_program(prog, args, comm, overlap, "step")
    assert report.ok and report.wire_bytes_program == report.wire_bytes_model


@pytest.mark.parametrize("comm,overlap", [
    ("sharded", False), ("bf16", False), ("pmean", True), ("int8", False)])
def test_dp_comm_strategy_scan_program_lowers_and_audits(comm, overlap):
    # the epoch-scanned form (make_dp_run_fn threads comm through
    # _dp_step_body) over the same 8-device abstract mesh; int8 threads
    # the dp-sharded error-feedback resid in AND out
    run, args = jaxpr_audit.build_run_program(comm, overlap)
    _export_tpu(run, *args)
    report = jaxpr_audit.audit_program(run, args, comm, overlap, "run")
    assert report.ok and report.wire_bytes_program == report.wire_bytes_model
