"""The 10-epoch accuracy-parity golden artifact (VERDICT r4 #2).

docs/golden_accuracy.json is the checked-in evidence for the north-star
acceptance — "identical 10-epoch test accuracy" vs the reference trainer
(ddp_tutorial_multi_gpu.py:100-116, :127). scripts/golden_accuracy.py
regenerates it (framework vs an independent torch re-statement, same
init/data/batch order, native dropout streams); these tests pin the
committed artifact's verdict and shape, and the integration tier re-runs
the generator end-to-end on a small workload.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "docs", "golden_accuracy.json")


@pytest.fixture(scope="module")
def artifact():
    assert os.path.exists(ARTIFACT), (
        "docs/golden_accuracy.json missing — regenerate with "
        "`python scripts/golden_accuracy.py`")
    with open(ARTIFACT) as f:
        return json.load(f)


def test_artifact_verdict_passes(artifact):
    v = artifact["verdict"]
    assert v["pass"] is True
    assert v["accuracy_gap"] <= v["accuracy_bound"]
    assert v["val_loss_ratio_gap"] <= v["val_loss_ratio_bound"]


def test_artifact_is_the_full_north_star_workload(artifact):
    # The committed artifact must be the real thing, not a smoke run.
    c = artifact["config"]
    assert c["epochs"] == 10
    assert c["train_n"] == 60000 and c["test_n"] == 10000
    assert c["batch"] == 128 and c["lr"] == 0.01
    assert len(artifact["framework_run"]["curve"]) == 10
    assert len(artifact["torch_runs"]) == 3  # comparison + 2 noise runs
    for r in artifact["torch_runs"]:
        assert len(r["curve"]) == 10


def test_artifact_curves_actually_trained(artifact):
    # Loss must fall and accuracy rise over the run on BOTH sides — parity
    # between two flat lines would be vacuous.
    for run in [artifact["framework_run"]] + artifact["torch_runs"]:
        curve = run["curve"]
        assert curve[-1]["mean_val_loss"] < curve[0]["mean_val_loss"]
        assert curve[-1]["accuracy"] > 0.9


@pytest.mark.integration
def test_regeneration_smoke(tmp_path):
    # End-to-end generator run on a small workload (the integration tier
    # exercises the script itself so artifact regeneration can't rot).
    out = tmp_path / "golden.json"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "golden_accuracy.py"),
         "--epochs", "1", "--train_n", "2048", "--test_n", "512",
         "--out", str(out)],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    art = json.loads(out.read_text())
    assert art["verdict"]["pass"] is True
