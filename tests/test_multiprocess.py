"""REAL multi-process integration tests — the TPU-native analog of the
reference's `mpiexec -n 4` localhost cluster stand-in (train_cpu_mp.csh:1,
SURVEY.md §4 item 2).

The rest of the suite tests SPMD semantics on a virtual 8-device mesh inside
one process; these tests additionally cover the true multi-controller path:
jax.distributed rendezvous via the env wireup branch (the reference fallback,
mnist_cpu_mp.py:147-185), cross-process collectives, per-process data
sharding stitched with make_array_from_process_local_data, and the Runtime
barrier/reduce_max/finalize surface.

Default shape: WORLD=4 processes, ONE local CPU device each (matching the
reference's `mpiexec -n 4`) — a 4-device global mesh; params must come back
identical on every rank, and identical to a single-process golden run of the
same math on a 4-device mesh. A dedicated test also runs 2 processes x
2 devices each — the real pod shape (multiple chips per host) where
make_array_from_process_local_data stitches per-PROCESS shards that span
multiple devices.
"""

import json
import re
import os
import socket
import subprocess
import sys

import numpy as np

import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORLD = 4  # reference cluster stand-in size (train_cpu_mp.csh:1)

# Cross-process collectives on the CPU backend (the gloo-backed path these
# tests stand on) landed after jax 0.4.x — older jaxlibs raise
# "Multiprocess computations aren't implemented on the CPU backend" at the
# first collective. A capability the install genuinely lacks is a skip by
# name, not a failure (same policy as the TPU-semantics-simulator tests).
_JAX_V = tuple(int(x) for x in jax.__version__.split(".")[:2])
pytestmark = pytest.mark.skipif(
    _JAX_V < (0, 5),
    reason="this jaxlib's CPU backend does not implement multiprocess "
           "collectives (needs jax >= 0.5)")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(rank: int, port: int, argv, extra_env=None, *, world=WORLD,
           devices_per_proc=1):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS":
            f"--xla_force_host_platform_device_count={devices_per_proc}",
        "MASTER_ADDR": "127.0.0.1",
        "MASTER_PORT": str(port),
        "WORLD_SIZE": str(world),
        "RANK": str(rank),
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    })
    env.update(extra_env or {})
    return subprocess.Popen(argv, cwd=REPO, env=env, text=True,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE)


def _run_world_once(argv, extra_env, timeout, world, devices_per_proc):
    port = _free_port()
    procs = [_spawn(r, port, argv, extra_env, world=world,
                    devices_per_proc=devices_per_proc)
             for r in range(world)]
    outs = []
    try:
        for p in procs:
            try:
                out, err = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                for q in procs:
                    if q.poll() is None:
                        q.kill()
                # Harvest what the hung/killed workers DID say — that is the
                # actual diagnostic, not the timeout itself.
                out, err = p.communicate()
                outs.append((None, out, err))
                continue
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait()
    return outs


def _run_world(argv, extra_env=None, timeout=240, attempts=3, *,
               world=WORLD, devices_per_proc=1):
    """Run `world` copies to completion, retrying on rendezvous-port races.

    _free_port() closes its probe socket before the coordinator binds the
    port, so another process can steal it in between (TOCTOU); a failed
    attempt with a fresh port is retried rather than flaking."""
    last = None
    for _ in range(attempts):
        outs = _run_world_once(argv, extra_env, timeout, world,
                               devices_per_proc)
        if all(rc == 0 for rc, _, _ in outs):
            return outs
        last = outs
        blob = "\n".join(f"{o}\n{e}" for _, o, e in outs)
        if not ("Address already in use" in blob or "Failed to bind" in blob
                or "errno: 98" in blob):
            break  # a real failure, not a port race — don't mask it
    for rank, (rc, out, err) in enumerate(last):
        assert rc == 0, (f"rank {rank} failed "
                         f"(rc={'timeout' if rc is None else rc}):\n{out}\n{err}")
    return last


def _golden_worker_run():
    """Single-process replay of mp_worker.py's training on a WORLD-device mesh.

    Device d of the golden mesh sees exactly the rows process d loaded in the
    distributed run (make_array_from_process_local_data lays process shards
    out in process order), and dropout keys fold in the same axis_index — so
    the runs must agree to float tolerance.
    """
    from mp_worker import HPARAMS
    from pytorch_ddp_mnist_tpu.data import normalize_images, synthetic_mnist
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.parallel.ddp import (
        batch_sharding, make_dp_train_step, replicated)
    from pytorch_ddp_mnist_tpu.parallel.mesh import make_mesh
    from pytorch_ddp_mnist_tpu.parallel.sampler import ShardedSampler

    n, local_batch, steps, lr = (HPARAMS["n"], HPARAMS["local_batch"],
                                 HPARAMS["steps"], HPARAMS["lr"])
    mesh = make_mesh([WORLD], ["dp"], jax.devices()[:WORLD])
    split = synthetic_mnist(n, seed=HPARAMS["data_seed"])
    x_all = normalize_images(split.images)
    y_all = split.labels.astype(np.int32)
    shards = []
    for r in range(WORLD):
        s = ShardedSampler(n, num_replicas=WORLD, rank=r,
                           seed=HPARAMS["sampler_seed"])
        s.set_epoch(0)
        shards.append(s.indices())

    step = make_dp_train_step(mesh, lr=lr)
    params = jax.device_put(init_mlp(jax.random.key(HPARAMS["param_seed"])),
                            replicated(mesh))
    key = jax.device_put(jax.random.key(HPARAMS["key_seed"]), replicated(mesh))
    losses = []
    for s in range(steps):
        rows = np.concatenate(
            [sh[s * local_batch:(s + 1) * local_batch] for sh in shards])
        gx = jax.device_put(x_all[rows], batch_sharding(mesh))
        gy = jax.device_put(y_all[rows], batch_sharding(mesh))
        params, key, loss = step(params, key, gx, gy)
        losses.append(float(loss))
    checksum = float(sum(np.abs(np.asarray(leaf)).sum()
                         for leaf in jax.tree_util.tree_leaves(params)))
    return losses, checksum


def test_four_process_training_matches_golden():
    outs = _run_world([sys.executable, os.path.join("tests", "mp_worker.py")])
    results = []
    for rank, (_, out, err) in enumerate(outs):
        line = [ln for ln in out.splitlines() if ln.startswith("{")]
        assert line, f"rank {rank} produced no JSON:\n{out}\n{err}"
        results.append(json.loads(line[-1]))
    results.sort(key=lambda r: r["rank"])

    assert [r["rank"] for r in results] == list(range(WORLD))
    assert all(r["size"] == WORLD for r in results)
    # reduce_max over ranks' own rank == WORLD-1, delivered to all.
    assert all(r["reduce_max"] == WORLD - 1 for r in results)
    # Allreduce kept replicas in lockstep: identical curve + weights on
    # EVERY rank.
    for r in results[1:]:
        np.testing.assert_allclose(results[0]["losses"], r["losses"],
                                   rtol=0, atol=0)
        assert results[0]["checksum"] == r["checksum"]
    # And the distributed run equals the single-process golden run.
    g_losses, g_checksum = _golden_worker_run()
    np.testing.assert_allclose(results[0]["losses"], g_losses,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(results[0]["checksum"], g_checksum,
                               rtol=1e-5)


def test_four_process_cli_end_to_end(tmp_path):
    """The full CLI over 4 real processes — the mnist_cpu_mp.py capability
    at the reference's own stand-in size (mpiexec -n 4, train_cpu_mp.csh:1):
    wireup, sharded loader, DDP epoch, rank-0-only checkpoint + logging."""
    ckpt = tmp_path / "model.msgpack"
    outs = _run_world(
        [sys.executable, "-m", "pytorch_ddp_mnist_tpu.cli.train",
         "--parallel", "--wireup_method", "env", "--n_epochs", "1",
         "--limit", "1024", "--batch_size", "64",
         "--checkpoint", str(ckpt)],
        )
    rank0_out = outs[0][1]
    assert "Epoch=0" in rank0_out, rank0_out
    # Rank-0-gated logging (reference prints on every rank; ours gates —
    # SURVEY.md §5.5): no other rank prints the epoch line.
    for _, out, _ in outs[1:]:
        assert "Epoch=0" not in out
    assert ckpt.exists(), "rank-0 checkpoint missing"


def test_four_process_cached_cli():
    """--parallel --cached over 4 real processes: the epoch-fused scan with
    a multi-process mesh — every process holds the dataset, the global batch
    index rows shard over all devices, one XLA program per epoch."""
    outs = _run_world(
        [sys.executable, "-m", "pytorch_ddp_mnist_tpu.cli.train",
         "--parallel", "--cached", "--wireup_method", "env",
         "--n_epochs", "2", "--limit", "1024", "--batch_size", "64",
         "--checkpoint", ""],
        )
    lines = [ln for ln in outs[0][1].splitlines() if ln.startswith("Epoch=")]
    assert len(lines) == 2, outs[0]
    for _, out, _ in outs[1:]:
        assert "Epoch=" not in out
    # The run must be numerically sane, not just alive: training loss
    # decreasing across the two epochs and a bounded accuracy.
    means = [float(re.search(r"mean_train=([0-9.]+|nan|inf)", ln).group(1))
             for ln in lines]
    assert np.isfinite(means).all() and means[1] < means[0], lines
    acc = float(re.search(r"acc=([0-9.]+)", lines[-1]).group(1))
    assert 0.0 <= acc <= 1.0, lines[-1]


def test_four_process_midrun_outage_coordinated_resume(tmp_path):
    """Coordinated multi-process mid-run outage resume (VERDICT r4 #5):
    a 4-process --parallel --cached run loses its backend after global
    epoch 1 (every rank raises a backend-loss RuntimeError — the bomb in
    tests/mp_outage_worker.py), and with --outage_retries each rank
    persists its own stash (rank 0 -> the checkpoint; ranks 1..3 ->
    rank-suffixed siblings + RNG sidecars), confirms backend health out
    of process, and re-execs into the PLAIN CLI. The fresh world
    re-rendezvouses (a clean jax.distributed.initialize on the same
    coordinator address) and finishes epochs 2.. — bitwise the unbroken
    4-process run, with the temp stash files consumed on success."""
    golden = tmp_path / "golden.msgpack"
    tail = ["--parallel", "--cached", "--wireup_method", "env",
            "--n_epochs", "3", "--limit", "1024", "--batch_size", "64",
            "--lr", "0.1", "--path", str(tmp_path)]
    _run_world([sys.executable, "-m", "pytorch_ddp_mnist_tpu.cli.train",
                *tail, "--checkpoint", str(golden)])

    flaky = tmp_path / "flaky.msgpack"
    # generous budget: the flow is two full 4-process worlds back to back
    # (original + re-exec'd), each paying fresh jax imports and jit
    # compiles, plus 4 out-of-process health probes — on a contended
    # 1-core CI host the whole dance has been observed near 7 minutes
    outs = _run_world(
        [sys.executable, os.path.join("tests", "mp_outage_worker.py"),
         *tail, "--checkpoint", str(flaky), "--outage_retries", "1"],
        timeout=600)
    # every rank saw the interruption and took the coordinated-resume path
    for rank, (_, _, err) in enumerate(outs):
        assert "[outage] training interrupted" in err, (rank, err)
        assert "coordinated parallel resume" in err, (rank, err)
    # the resumed world continued at GLOBAL epoch 2, printed once by rank 0
    # (epochs 0/1 are not re-run), and no other rank prints epoch lines
    assert outs[0][1].count("Epoch=2,") == 1, outs[0][1]
    for _, out, _ in outs[1:]:
        assert "Epoch=" not in out

    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.train.checkpoint import load_checkpoint
    a = load_checkpoint(str(flaky), init_mlp(jax.random.key(0)))
    b = load_checkpoint(str(golden), init_mlp(jax.random.key(0)))
    for u, v in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(u), np.asarray(v))

    # durable-progress cleanup: the rank-suffixed stashes and every RNG
    # sidecar were consumed by the successful resumed run
    assert not (tmp_path / "flaky.msgpack.rng.npz").exists()
    for r in range(1, WORLD):
        assert not (tmp_path / f"flaky.msgpack.rank{r}").exists()
        assert not (tmp_path / f"flaky.msgpack.rank{r}.rng.npz").exists()


def test_four_process_netcdf_cli(tmp_path):
    """DDP + NetCDF data plane over 4 real processes — the flagship
    mnist_pnetcdf_cpu_mp.py capability at its own launch shape
    (mpiexec -n 4, train_cpu_mp.csh:1): every process gathers ONLY its
    sampler shard's rows from the shared .nc file (independent-I/O analog,
    mnist_pnetcdf_cpu_mp.py:32,46)."""
    from pytorch_ddp_mnist_tpu.data.convert import main as convert_main
    assert convert_main(["--synthetic", "1024:256",
                         "--out_dir", str(tmp_path)]) == 0
    outs = _run_world(
        [sys.executable, "-m", "pytorch_ddp_mnist_tpu.cli.train",
         "--parallel", "--wireup_method", "env", "--netcdf",
         "--path", str(tmp_path), "--n_epochs", "1", "--batch_size", "64",
         "--checkpoint", ""],
        )
    line = [ln for ln in outs[0][1].splitlines() if ln.startswith("Epoch=0")]
    assert line, outs[0]
    # The run trained and evaluated real numbers through the .nc path
    # (missing files would have been a SystemExit before training).
    m = re.search(r"acc=([0-9.]+)", line[0])
    assert m and 0.0 <= float(m.group(1)) <= 1.0, line[0]
    # Rank-0-gated logging, as in the IDX-path test above.
    for _, out, _ in outs[1:]:
        assert "Epoch=0" not in out
    # Per-shard gather correctness (each rank reads only its sampler rows,
    # bit-identical to the in-memory loader) is locked at the unit level by
    # tests/test_data.py; the golden-run test above locks the DDP math.


def test_two_process_two_devices_each_stitching(tmp_path):
    """2 processes x 2 virtual devices per process — the real pod shape
    (multiple chips per host). Exercises the local_shards > 1 path: each
    process loads local_batch = batch_size * 2 rows and
    make_array_from_process_local_data stitches the per-process blocks into
    the global 4-device dp-sharded batch (cli/train.py; VERDICT r1 weak #3:
    this configuration previously had no test)."""
    ckpt = tmp_path / "model.msgpack"
    outs = _run_world(
        [sys.executable, "-m", "pytorch_ddp_mnist_tpu.cli.train",
         "--parallel", "--wireup_method", "env", "--n_epochs", "1",
         "--limit", "1024", "--batch_size", "32",
         "--checkpoint", str(ckpt)],
        world=2, devices_per_proc=2)
    rank0_out = outs[0][1]
    # global mesh = 4 devices over 2 processes; global batch = 32 * 4
    assert "devices=4 processes=2" in rank0_out, rank0_out
    assert "global_batch=128" in rank0_out, rank0_out
    line = [ln for ln in rank0_out.splitlines() if ln.startswith("Epoch=0")]
    assert line, rank0_out
    means = re.search(r"mean_train=([0-9.]+)", line[0])
    assert means and np.isfinite(float(means.group(1))), line[0]
    assert "Epoch=0" not in outs[1][1]
    assert ckpt.exists()


def test_two_process_two_devices_cached_scan(tmp_path):
    """Same 2x2 topology through the epoch-fused --cached path: the sharded
    index array spans 2 devices per process."""
    outs = _run_world(
        [sys.executable, "-m", "pytorch_ddp_mnist_tpu.cli.train",
         "--parallel", "--cached", "--wireup_method", "env",
         "--n_epochs", "2", "--limit", "1024", "--batch_size", "32",
         "--checkpoint", ""],
        world=2, devices_per_proc=2)
    rank0_out = outs[0][1]
    assert "devices=4 processes=2" in rank0_out, rank0_out
    lines = [ln for ln in rank0_out.splitlines() if ln.startswith("Epoch=")]
    assert len(lines) == 2, rank0_out
    means = [float(re.search(r"mean_train=([0-9.]+|nan|inf)", ln).group(1))
             for ln in lines]
    assert np.isfinite(means).all() and means[1] < means[0], lines


def test_two_process_two_devices_fused_run(tmp_path):
    """The 2x2 topology through --cached --fused: the WHOLE multi-epoch run
    as one device program over a multi-process mesh, with per-epoch snapshot
    replay (reporting + rank-0 checkpoint hook) after it completes."""
    ckpt = tmp_path / "model.msgpack"
    outs = _run_world(
        [sys.executable, "-m", "pytorch_ddp_mnist_tpu.cli.train",
         "--parallel", "--cached", "--fused", "--wireup_method", "env",
         "--n_epochs", "2", "--limit", "1024", "--batch_size", "32",
         "--checkpoint", str(ckpt)],
        world=2, devices_per_proc=2)
    rank0_out = outs[0][1]
    assert "devices=4 processes=2" in rank0_out, rank0_out
    lines = [ln for ln in rank0_out.splitlines() if ln.startswith("Epoch=")]
    assert len(lines) == 2, rank0_out
    means = [float(re.search(r"mean_train=([0-9.]+|nan|inf)", ln).group(1))
             for ln in lines]
    assert np.isfinite(means).all() and means[1] < means[0], lines
    assert "Epoch=" not in outs[1][1]
    assert ckpt.exists()


def test_real_mpiexec_launcher_pmi_branch():
    """The ONE launcher path never otherwise exercised end-to-end: a REAL
    `mpiexec -n 4` (the reference's launch line, train_cpu_mp.csh:1) feeding
    the PMIx/PMI env branches of wireup._derive — rendezvous, cross-process
    collectives, barrier, finalize, all under the actual launcher rather
    than hand-set env vars (VERDICT r3 #7).

    Skips when no MPI launcher is on PATH (this image ships none); on hosts
    with MPICH or Open MPI it runs for real. The hand-set-env derivation
    itself is covered launcher-less in tests/test_wireup.py.
    """
    import pytest
    import shutil

    mpiexec = shutil.which("mpiexec") or shutil.which("mpirun")
    if mpiexec is None:
        pytest.skip("no mpiexec/mpirun on PATH")

    worker = (
        "import json, os\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from pytorch_ddp_mnist_tpu.parallel.wireup import initialize_runtime\n"
        "rt = initialize_runtime('auto')\n"
        "mx = rt.reduce_max(float(rt.rank))\n"
        "rt.barrier()\n"
        "print(json.dumps({'rank': rt.rank, 'size': rt.size,\n"
        "                  'method': rt.method, 'max': mx}))\n"
        "rt.finalize()\n"
    )
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "MASTER_ADDR": "127.0.0.1",
        "MASTER_PORT": str(_free_port()),
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    })
    ver = subprocess.run([mpiexec, "--version"], capture_output=True,
                         text=True).stdout
    extra = ["--oversubscribe"] if "Open MPI" in ver else []
    out = subprocess.run(
        [mpiexec, "-n", "4", *extra, sys.executable, "-c", worker],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, (out.stdout, out.stderr)
    recs = [json.loads(ln) for ln in out.stdout.splitlines()
            if ln.startswith("{")]
    assert len(recs) == 4
    assert sorted(r["rank"] for r in recs) == [0, 1, 2, 3]
    assert all(r["size"] == 4 for r in recs)
    # a real mpiexec exports PMIx (Open MPI) or PMI (MPICH) vars — the
    # method must have been detected from the launcher, not the fallback
    assert all(r["method"] in ("openmpi", "mpich") for r in recs), recs
    assert all(r["max"] == 3.0 for r in recs)   # MPI.MAX over ranks
